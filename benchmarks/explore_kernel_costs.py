"""Isolate Mosaic construct costs for the merge kernel redesign."""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import trino_tpu.jaxcfg  # noqa: F401,E402
import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from benchmarks.devtime import devtime  # noqa: E402

N = 1 << 20
GRID = N // 1024


def run(tag, fn, *args):
    try:
        out = fn(*args)
        jax.block_until_ready(out)
        print(tag, round(devtime(fn, *args) * 1e3, 3), "ms", flush=True)
    except Exception as e:  # noqa: BLE001
        print(tag, "FAILED:", type(e).__name__, str(e)[:200], flush=True)


def main():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rng = np.random.default_rng(0)
    at = jnp.asarray(rng.integers(0, 1 << 30, (GRID * 128, 8)).astype(np.int32))
    b2 = jnp.asarray(rng.integers(0, 1 << 30, (1024, 128)).astype(np.int32))

    def call(kernel, nscratch=0):
        scr = [pltpu.SMEM((2,), jnp.int32)] + [
            pltpu.VMEM((128, 8), jnp.int32) for _ in range(nscratch)
        ]
        def f(at, b2):
            with jax.enable_x64(False):
                return pl.pallas_call(
                    kernel,
                    grid=(GRID,),
                    in_specs=[
                        pl.BlockSpec((128, 8), lambda i: (i, 0),
                                     memory_space=pltpu.VMEM),
                        pl.BlockSpec(b2.shape, lambda i: (0, 0),
                                     memory_space=pltpu.VMEM),
                    ],
                    out_specs=pl.BlockSpec((128, 8), lambda i: (i, 0),
                                           memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct(at.shape, jnp.int32),
                    scratch_shapes=scr,
                )(at, b2)
        return f

    # V0: 2 static windows, static slices, unrolled — the floor
    def k0(a_ref, b_ref, o_ref, cur):
        acc = jnp.zeros((128, 8), jnp.int32)
        for w in range(2):
            b_win = b_ref[w : w + 1, :]
            cols = []
            for c in range(8):
                a_col = a_ref[:, c : c + 1]
                cols.append(acc[:, c : c + 1] + jnp.sum(
                    (b_win < a_col).astype(jnp.int32), axis=1,
                    keepdims=True, dtype=jnp.int32))
            acc = jnp.concatenate(cols, axis=1)
        o_ref[:, :] = acc
    run("v0 2win static unrolled", call(k0), at, b2)

    # V1: 2 windows via fori_loop with STATIC bound, dynamic pl.ds
    def k1(a_ref, b_ref, o_ref, cur):
        def body(w, acc):
            b_win = b_ref[pl.ds(w, 1), :]
            cols = []
            for c in range(8):
                a_col = a_ref[:, c : c + 1]
                cols.append(acc[:, c : c + 1] + jnp.sum(
                    (b_win < a_col).astype(jnp.int32), axis=1,
                    keepdims=True, dtype=jnp.int32))
            acc = jnp.concatenate(cols, axis=1)
            return acc
        acc = jax.lax.fori_loop(0, 2, body, jnp.zeros((128, 8), jnp.int32))
        o_ref[:, :] = acc
    run("v1 2win fori dynamic-ds", call(k1), at, b2)

    # V2: 2 windows, fori with DYNAMIC bound from SMEM scalar
    def k2(a_ref, b_ref, o_ref, cur):
        @pl.when(pl.program_id(0) == 0)
        def _():
            cur[0] = jnp.int32(0)
        def body(w, acc):
            b_win = b_ref[pl.ds(w, 1), :]
            cols = []
            for c in range(8):
                a_col = a_ref[:, c : c + 1]
                cols.append(acc[:, c : c + 1] + jnp.sum(
                    (b_win < a_col).astype(jnp.int32), axis=1,
                    keepdims=True, dtype=jnp.int32))
            acc = jnp.concatenate(cols, axis=1)
            return acc
        end = cur[0] + jnp.int32(2)
        acc = jax.lax.fori_loop(cur[0], end, body,
                                jnp.zeros((128, 8), jnp.int32))
        o_ref[:, :] = acc
    run("v2 2win fori smem-bound", call(k2), at, b2)

    # V3: V0 + 2 scalar VMEM reads per window (the while-cond pattern)
    def k3(a_ref, b_ref, o_ref, cur):
        acc = jnp.zeros((128, 8), jnp.int32)
        t = jnp.int32(0)
        for w in range(2):
            t = t + b_ref[w, 0] + b_ref[w, 127]
            b_win = b_ref[w : w + 1, :]
            cols = []
            for c in range(8):
                a_col = a_ref[:, c : c + 1]
                cols.append(acc[:, c : c + 1] + jnp.sum(
                    (b_win < a_col).astype(jnp.int32), axis=1,
                    keepdims=True, dtype=jnp.int32))
            acc = jnp.concatenate(cols, axis=1)
        o_ref[:, :] = acc + t
    run("v3 2win + scalar vmem reads", call(k3), at, b2)

    # V4: bigger window: 8 static window rows (1024 B elems), unrolled
    def k4(a_ref, b_ref, o_ref, cur):
        acc = jnp.zeros((128, 8), jnp.int32)
        for w in range(8):
            b_win = b_ref[w : w + 1, :]
            cols = []
            for c in range(8):
                a_col = a_ref[:, c : c + 1]
                cols.append(acc[:, c : c + 1] + jnp.sum(
                    (b_win < a_col).astype(jnp.int32), axis=1,
                    keepdims=True, dtype=jnp.int32))
            acc = jnp.concatenate(cols, axis=1)
        o_ref[:, :] = acc
    run("v4 8win static unrolled", call(k4), at, b2)


if __name__ == "__main__":
    main()
