"""Operator microbenchmarks — the JMH-class analogue (SURVEY.md §6:
BenchmarkGroupByHash, BenchmarkHashAndStreamingAggregationOperators,
HashBuildAndJoinBenchmark, BenchmarkPageProcessor).

Each benchmark jits the kernel under test, prewarm-compiles, then
measures steady-state device wall-clock with a forced host sync, and
prints one JSON line: {"bench": ..., "rows": N, "ms": ..., "mrows_s": ...}.

Usage: python benchmarks/micro.py [--rows 4000000] [--filter groupby]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402


def _measure(fn, *args, reps: int = 20):
    """Steady-state per-call device time by slope: dispatch K calls and
    sync ONCE (the TPU stream executes them in order), so the
    host<->device round-trip latency — which dominates on a tunneled
    device and would otherwise be billed to every call — is paid once
    and cancelled out by the two-point fit.

    Robustness (the round-1 harness printed ms=0.0 when tk <= t1): take
    the MEDIAN of several slope samples, and when the spread is inside
    measurement noise, widen the rep count until the K-run batch costs
    at least ~4x the single run; if the slope still degenerates, fall
    back to the fully-synced per-call time (an upper bound that includes
    one round trip — honest, if pessimistic)."""
    import statistics

    import jax

    def force(out):
        # block_until_ready resolves optimistically over a tunneled
        # device link — only a data fetch truly waits for execution
        leaf = jax.tree_util.tree_leaves(out)[0]
        np.asarray(leaf)

    force(fn(*args))  # compile

    def timed(k):
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = fn(*args)
        force(out)
        return time.perf_counter() - t0

    k = reps
    for _ in range(4):
        slopes = []
        for _ in range(5):
            t1 = timed(1)
            tk = timed(k)
            slopes.append((tk - t1) / (k - 1))
        slope = statistics.median(slopes)
        t1_med = statistics.median(timed(1) for _ in range(3))
        # sanity: the batch must dominate the single call, else the
        # subtraction is noise-vs-noise
        if slope > 0 and slope * (k - 1) >= 3 * t1_med:
            return slope
        k *= 4
    # degenerate (kernel ~free relative to RTT jitter): report the
    # fully-synced per-call time instead of a fabricated slope
    return statistics.median(timed(1) for _ in range(5))


def _groupby_sort_bench(n: int, n_groups: int, capacity: int):
    import jax.numpy as jnp

    from trino_tpu.ops.groupby import sort_group_reduce

    rng = np.random.default_rng(0)
    keys = [jnp.asarray(rng.integers(0, n_groups, n).astype(np.int64))]
    valids = [jnp.ones(n, dtype=jnp.bool_)]
    live = jnp.ones(n, dtype=jnp.bool_)
    values = [jnp.asarray(rng.integers(0, 10**6, n).astype(np.int64))]

    def run():
        return sort_group_reduce(
            tuple(keys), tuple(valids), live, tuple(values), (None,),
            ("sum",), capacity,
        )

    return _measure(run)


def bench_groupby_sort(n: int):
    """sort_group_reduce, low cardinality (1k groups) — the single-device
    aggregation hot path (GroupByHash analogue)."""
    return _groupby_sort_bench(n, 1000, 2048)


def bench_groupby_sort_100k(n: int):
    """sort_group_reduce at high cardinality (100k groups) — the BIGINT
    group-key path (Q3/Q18 shape; MultiChannelGroupByHash.java:264).
    Capacity = bucket_capacity(100k), the engine's steady-state choice."""
    return _groupby_sort_bench(n, 100_000, 1 << 17)


def bench_groupby_mxu(n: int):
    """Pallas MXU one-hot contraction grouped sum (ops/mxu_groupby.py)."""
    import jax
    import jax.numpy as jnp

    from trino_tpu.ops.mxu_groupby import grouped_sum_mxu

    rng = np.random.default_rng(0)
    gid = jnp.asarray(rng.integers(0, 1000, n, dtype=np.int32))
    live = jnp.ones(n, dtype=jnp.bool_)
    values = (jnp.asarray(rng.integers(0, 10**6, n).astype(np.int64)),)
    interp = jax.default_backend() != "tpu"

    def run():
        return grouped_sum_mxu(gid, values, live, 1000, interpret=interp)

    return _measure(run)


def bench_join_probe(n: int):
    """Hash-join build + probe (PagesHash/LookupJoin analogue)."""
    import jax.numpy as jnp

    from trino_tpu.ops import join as J

    rng = np.random.default_rng(0)
    build_n = max(n // 8, 1024)
    bkeys = [jnp.asarray(np.arange(build_n, dtype=np.int64))]
    bvalids = [jnp.ones(build_n, dtype=jnp.bool_)]
    blive = jnp.ones(build_n, dtype=jnp.bool_)
    pkeys = [jnp.asarray(rng.integers(0, build_n * 2, n).astype(np.int64))]
    pvalids = [jnp.ones(n, dtype=jnp.bool_)]
    plive = jnp.ones(n, dtype=jnp.bool_)

    lookup = J.build_lookup(bkeys, bvalids, blive)

    def run():
        return J.probe_counts(lookup, pkeys, pvalids, plive)

    return _measure(run)


def bench_filter_project(n: int):
    """Fused filter + arithmetic projection (PageProcessor analogue)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.integers(0, 10**6, n).astype(np.int64))
    b = jnp.asarray(rng.integers(1, 100, n).astype(np.int64))

    @jax.jit
    def run(a, b):
        live = (a % 7 != 0) & (b > 10)
        x = a * (100 - b)
        y = x * (100 + b)
        return (
            jnp.sum(jnp.where(live, x, 0)),
            jnp.sum(jnp.where(live, y, 0)),
        )

    return _measure(run, a, b)


def bench_topn(n: int):
    """TopN via sort_order + slice (TopNOperator analogue)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    v = jnp.asarray(rng.integers(0, 10**9, n).astype(np.int64))

    @jax.jit
    def run(v):
        return jax.lax.top_k(v, 100)

    return _measure(run, v)


BENCHES = {
    "groupby_sort": bench_groupby_sort,
    "groupby_sort_100k": bench_groupby_sort_100k,
    "groupby_mxu": bench_groupby_mxu,
    "join_probe": bench_join_probe,
    "filter_project": bench_filter_project,
    "topn": bench_topn,
}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4_000_000)
    ap.add_argument("--filter", type=str, default="")
    args = ap.parse_args()

    import jax

    for name, fn in BENCHES.items():
        if args.filter and args.filter not in name:
            continue
        try:
            secs = fn(args.rows)
            print(
                json.dumps(
                    {
                        "bench": name,
                        "rows": args.rows,
                        "ms": round(secs * 1000, 3),
                        "mrows_s": round(args.rows / secs / 1e6, 1),
                        "backend": jax.default_backend(),
                    }
                ),
                flush=True,
            )
        except Exception as ex:
            print(
                json.dumps(
                    {"bench": name,
                     "error": f"{type(ex).__name__}: {ex}"[:160]}
                ),
                flush=True,
            )


if __name__ == "__main__":
    main()
