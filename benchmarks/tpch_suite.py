"""TPC-H suite runner — the benchto-benchmarks analogue
(testing/trino-benchto-benchmarks/.../tpch.yaml: prewarm runs + N
measured runs per query, wall-clock; SURVEY.md §6).

Usage:
    python benchmarks/tpch_suite.py [--sf 0.1] [--runs 3] [--prewarm 1]
                                    [--queries 1,6,3] [--distributed N]

Prints one JSON line per query:
    {"query": "q01", "sf": 0.1, "median_s": ..., "runs": [...],
     "rows": ..., "engine": "local"|"distributed-N"}
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tests"))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=0.1)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--prewarm", type=int, default=1)
    ap.add_argument("--queries", type=str, default="")
    ap.add_argument(
        "--distributed", type=int, default=0,
        help="run through the distributed runtime with N workers",
    )
    args = ap.parse_args()

    from tpch_queries import QUERIES  # tests/tpch_queries.py

    qids = (
        [int(q) for q in args.queries.split(",")]
        if args.queries
        else sorted(QUERIES)
    )

    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session

    # the tpch connector resolves the scale factor from the schema name
    session = Session(catalog="tpch", schema=f"sf{args.sf:g}")
    if args.distributed:
        from trino_tpu.runtime.coordinator import DistributedQueryRunner

        runner = DistributedQueryRunner(
            session=session, n_workers=args.distributed
        )
        engine = f"distributed-{args.distributed}"
    else:
        from trino_tpu.engine import LocalQueryRunner

        runner = LocalQueryRunner(session)
        engine = "local"
    runner.register_catalog("tpch", create_tpch_connector())

    for qid in qids:
        sql = QUERIES[qid]
        try:
            for _ in range(args.prewarm):
                res = runner.execute(sql)
            times = []
            for _ in range(args.runs):
                t0 = time.perf_counter()
                res = runner.execute(sql)
                times.append(time.perf_counter() - t0)
            print(
                json.dumps(
                    {
                        "query": f"q{qid:02d}",
                        "sf": args.sf,
                        "median_s": round(statistics.median(times), 4),
                        "runs": [round(t, 4) for t in times],
                        "rows": len(res.rows),
                        "engine": engine,
                    }
                ),
                flush=True,
            )
        except Exception as ex:  # keep the suite going (benchto behavior)
            print(
                json.dumps(
                    {"query": f"q{qid:02d}", "sf": args.sf,
                     "error": f"{type(ex).__name__}: {ex}"[:200]}
                ),
                flush=True,
            )

if __name__ == "__main__":
    main()
