"""Can Pallas TPU gather from a VMEM-resident table at speed?

Tests lowering + throughput of candidate in-kernel gather formulations
for the bucket-hash probe. Each variant: 1M lookups into a 128K table.
"""

from __future__ import annotations

import json
import sys
import traceback
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402

from benchmarks.micro import _measure  # noqa: E402

N = 1 << 20
B = 1 << 17
TILE = 2048  # probe rows per grid step


def report(name, secs):
    ms = secs * 1e3
    print(json.dumps({"bench": name, "ms": round(ms, 3),
                      "gb_s": round(N * 8 / secs / 1e9, 2)}), flush=True)


def try_variant(name, fn, *args):
    try:
        out = fn(*args)
        jax.block_until_ready(out)
        report(name, _measure(fn, *args))
        return np.asarray(jax.tree_util.tree_leaves(out)[0])
    except Exception:
        print(f"{name}: FAILED", flush=True)
        traceback.print_exc()
        return None


def main():
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.integers(0, 1 << 30, B).astype(np.int32))
    idx = jnp.asarray(rng.integers(0, B, N).astype(np.int32))
    table2d = table.reshape(B // 128, 128)
    idx2d = idx.reshape(N // TILE, TILE)

    # V1: flat take inside kernel, full table in VMEM
    def k1(tab_ref, idx_ref, out_ref):
        out_ref[:] = jnp.take(tab_ref[:].reshape(-1), idx_ref[:].reshape(-1),
                              axis=0).reshape(out_ref.shape)

    def v1(tab, ix):
        return pl.pallas_call(
            k1,
            grid=(N // TILE,),
            in_specs=[
                pl.BlockSpec((B // 128, 128), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, TILE), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, TILE), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((N // TILE, TILE), jnp.int32),
        )(tab, ix)

    got = try_variant("pallas_take_flat", jax.jit(v1), table2d, idx2d)
    if got is not None:
        want = np.asarray(table)[np.asarray(idx)].reshape(N // TILE, TILE)
        print("correct:", bool((got == want).all()), flush=True)

    # V2: row gather: table2d[idx_rows] via take axis=0 (128-wide rows)
    ROWT = 512
    ridx = jnp.asarray(rng.integers(0, B // 128, N).astype(np.int32))
    ridx2d = ridx.reshape(N // ROWT, ROWT)

    def k2(tab_ref, idx_ref, out_ref):
        rows = jnp.take(tab_ref[:], idx_ref[0, :], axis=0)  # (ROWT,128)
        out_ref[0, :] = jnp.sum(rows, axis=1)

    def v2(tab, ix):
        return pl.pallas_call(
            k2,
            grid=(N // ROWT,),
            in_specs=[
                pl.BlockSpec((B // 128, 128), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, ROWT), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, ROWT), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((N // ROWT, ROWT), jnp.int32),
        )(tab, ix)

    got = try_variant("pallas_take_rows128", jax.jit(v2), table2d, ridx2d)
    if got is not None:
        want = np.asarray(table2d)[np.asarray(ridx)].sum(axis=1).reshape(
            N // ROWT, ROWT)
        print("correct:", bool((got == want).all()), flush=True)

    # V3: take_along_axis within lanes: per-row gather from its own
    # 128-wide row (the two-level decomposition needs this)
    val = jnp.asarray(rng.integers(0, 128, (N // 128, 128)).astype(np.int32))
    src = jnp.asarray(rng.integers(0, 1 << 30, (N // 128, 128)).astype(np.int32))

    def k3(src_ref, sel_ref, out_ref):
        out_ref[:] = jnp.take_along_axis(src_ref[:], sel_ref[:], axis=1)

    def v3(s, sel):
        return pl.pallas_call(
            k3,
            grid=(N // 128 // 64,),
            in_specs=[
                pl.BlockSpec((64, 128), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((64, 128), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((64, 128), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((N // 128, 128), jnp.int32),
        )(s, sel)

    got = try_variant("pallas_take_along_lanes", jax.jit(v3), src, val)
    if got is not None:
        want = np.take_along_axis(np.asarray(src), np.asarray(val), axis=1)
        print("correct:", bool((got == want).all()), flush=True)


if __name__ == "__main__":
    main()
