"""Robust device-time measurement for the axon tunnel.

The tunnel has ~100-150ms host<->device RTT and ~25MB/s transfer, so
any methodology that fetches full outputs or too few reps measures the
link, not the device. `devtime` dispatches k and 4k dependent-free
calls, drains with a 1-element fetch, and fits the slope; k widens
until the 4k batch costs >= 2x the k batch.
"""

from __future__ import annotations

import time

import numpy as np
import jax


def _force_tiny(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf.ravel()[0:1])


def devtime(fn, *args, k0: int = 8, max_widen: int = 5) -> float:
    """Marginal per-call device seconds of fn(*args)."""
    _force_tiny(fn(*args))  # compile + warm

    def timed(k):
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = fn(*args)
        _force_tiny(out)
        return time.perf_counter() - t0

    k = k0
    for _ in range(max_widen):
        t1 = timed(k)
        t4 = timed(4 * k)
        if t4 >= 2.0 * t1:
            return max((t4 - t1) / (3 * k), 1e-9)
        k *= 4
    # degenerate: op so cheap the RTT dominates even at huge k
    return max((t4 - t1) / (3 * k), 1e-9)
