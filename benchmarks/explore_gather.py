"""Primitive exploration for the hash-probe redesign (round 4).

Measures candidate TPU primitives for "N random lookups into a B-row
table" — the inner op of a hash-join probe — to pick the design for
ops/join.py. Run on the real device; prints one JSON line per probe.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from benchmarks.micro import _measure  # noqa: E402


def report(name, rows, secs):
    ms = secs * 1e3
    print(json.dumps({"bench": name, "rows": rows, "ms": round(ms, 3),
                      "gb_s": round(rows * 8 / secs / 1e9, 2)}), flush=True)


def main():
    N = 1 << 20
    rng = np.random.default_rng(0)

    for B in (1 << 17, 1 << 20):
        table64 = jnp.asarray(rng.integers(0, 1 << 60, B).astype(np.int64))
        table32 = jnp.asarray(rng.integers(0, 1 << 30, B).astype(np.int32))
        idx = jnp.asarray(rng.integers(0, B, N).astype(np.int32))

        f = jax.jit(lambda t, i: jnp.take(t, i, axis=0))
        report(f"take_i64_B{B}", N, _measure(f, table64, idx))
        report(f"take_i32_B{B}", N, _measure(f, table32, idx))

        # chained dependent gathers (open-addressing simulation: 2 rounds)
        def chain(t, i):
            a = jnp.take(t, i, axis=0)
            i2 = (i + (a & 7).astype(jnp.int32)) % B
            return jnp.take(t, i2, axis=0)
        report(f"take_chain2_i32_B{B}", N, _measure(jax.jit(chain), table32, idx))

        # scatter-add (group-by accumulate analogue)
        def scat(t, i):
            return jnp.zeros(B, jnp.int32).at[i].add(t_probe32)
        t_probe32 = jnp.asarray(rng.integers(0, 100, N).astype(np.int32))
        report(f"scatter_add_B{B}", N, _measure(jax.jit(scat), table32, idx))

    # sorts for reference
    k64 = jnp.asarray(rng.integers(0, 1 << 60, N).astype(np.int64))
    k32 = jnp.asarray(rng.integers(0, 1 << 30, N).astype(np.int32))
    report("sort_i64_1M", N, _measure(jax.jit(jnp.sort), k64))
    report("sort_i32_1M", N, _measure(jax.jit(jnp.sort), k32))
    v32 = jnp.asarray(rng.integers(0, 1 << 30, N).astype(np.int32))
    f2 = jax.jit(lambda k, v: jax.lax.sort((k, v), num_keys=1))
    report("sortkv_i32_1M", N, _measure(f2, k32, v32))

    # searchsorted 1M into 128k (XLA native)
    ss_tab = jnp.sort(jnp.asarray(rng.integers(0, 1 << 60, 1 << 17).astype(np.int64)))
    q = jnp.asarray(rng.integers(0, 1 << 60, N).astype(np.int64))
    f3 = jax.jit(lambda t, x: jnp.searchsorted(t, x))
    report("searchsorted_1M_into_128k", N, _measure(f3, ss_tab, q))


if __name__ == "__main__":
    main()
