"""Blackhole connector — the null sink (plugin/trino-blackhole,
SURVEY.md §2.12). CREATE TABLE records only metadata; INSERT counts and
discards rows; SELECT returns zero rows. Used by write benchmarks and
tests that need a sink without storage."""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from trino_tpu.block import Column, RelBatch
from trino_tpu.connectors.spi import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSink,
    ConnectorPageSource,
    ConnectorSplitManager,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)


class BlackholeMetadata(ConnectorMetadata):
    def __init__(self):
        self.tables: Dict[Tuple[str, str], List[ColumnMetadata]] = {}

    def list_schemas(self) -> List[str]:
        return sorted({s for s, _ in self.tables} | {"default"})

    def list_tables(self, schema: str) -> List[str]:
        return sorted(n for s, n in self.tables if s == schema)

    def get_table_handle(self, schema: str, table: str) -> Optional[TableHandle]:
        if (schema, table) not in self.tables:
            return None
        return TableHandle("blackhole", schema, table)

    def get_table_metadata(self, handle: TableHandle) -> TableMetadata:
        return TableMetadata(
            handle.schema, handle.table, tuple(self.tables[(handle.schema, handle.table)])
        )

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        return TableStatistics(row_count=0.0)

    def create_table(self, schema: str, table: str, columns: Sequence[ColumnMetadata]) -> TableHandle:
        self.tables[(schema, table)] = list(columns)
        return TableHandle("blackhole", schema, table)

    def drop_table(self, handle: TableHandle) -> None:
        self.tables.pop((handle.schema, handle.table), None)


class BlackholeSplitManager(ConnectorSplitManager):
    def get_splits(self, handle: TableHandle, target_split_count: int) -> List[Split]:
        return [Split(handle, 0, (0, 0))]


class BlackholePageSource(ConnectorPageSource):
    def __init__(self, metadata: BlackholeMetadata):
        self.metadata = metadata

    def batches(self, split: Split, columns: Sequence[str], batch_rows: int,
                stabilizer=None) -> Iterator[RelBatch]:
        cols_meta = {
            c.name: c for c in self.metadata.tables[(split.table.schema, split.table.table)]
        }
        yield RelBatch(
            [
                Column(cols_meta[n].type, jnp.zeros(16, dtype=cols_meta[n].type.dtype))
                for n in columns
            ],
            jnp.zeros(16, dtype=jnp.bool_),
        )


class BlackholePageSink(ConnectorPageSink):
    def __init__(self):
        self.rows = 0

    def append(self, batch: RelBatch) -> None:
        self.rows += batch.row_count()

    def finish(self) -> int:
        return self.rows


class BlackholeConnector(Connector):
    def __init__(self):
        md = BlackholeMetadata()
        super().__init__(
            "blackhole", md, BlackholeSplitManager(), BlackholePageSource(md)
        )

    def page_sink(self, handle: TableHandle) -> ConnectorPageSink:
        return BlackholePageSink()


def create_blackhole_connector() -> Connector:
    return BlackholeConnector()
