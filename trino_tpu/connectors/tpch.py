"""TPC-H generator connector.

Analogue of plugin/trino-tpch (TpchConnectorFactory.java,
TpchNodePartitioningProvider.java — SURVEY.md §2.12): a deterministic,
in-memory TPC-H data generator exposed through the connector SPI, the
fixture source for correctness tests and benchmarks.

Not a port of dbgen: generation is *counter-based* — every cell is a
pure function ``f(seed, table, column, key)`` via splitmix64, so any
split of any column materializes independently, in vectorized numpy,
with no generator state. This is what makes splits retryable (FTE) and
lets column pruning skip work entirely. Schema, row counts, key
relationships (sparse order keys, the partsupp supplier spread, the
1/3-of-customers-have-no-orders rule) and value distributions follow
the TPC-H spec structure so query selectivities look right; text is
drawn from bounded pools, which keeps string dictionaries table-stable
(see spi.py) without materializing millions of distinct comments.

Schemas: tiny (sf 0.01), sf1, sf10, sf100, plus sf<float> on demand.
"""

from __future__ import annotations

import datetime
import hashlib
from functools import lru_cache
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.block import Column, Dictionary, RelBatch, bucket_capacity
from trino_tpu.connectors.spi import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSource,
    ConnectorSplitManager,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)

# ---------------------------------------------------------------------------
# counter-based uniform randomness
# ---------------------------------------------------------------------------

_U = np.uint64


@lru_cache(maxsize=4096)
def _stable_seed(*parts) -> int:
    """Process-independent seed (python's hash() is randomized per run)."""
    h = hashlib.sha256("|".join(map(str, parts)).encode()).digest()
    return int.from_bytes(h[:8], "little")


def _splitmix64(x: np.ndarray) -> np.ndarray:
    x = (x + _U(0x9E3779B97F4A7C15)).astype(np.uint64)
    x = ((x ^ (x >> _U(30))) * _U(0xBF58476D1CE4E5B9)).astype(np.uint64)
    x = ((x ^ (x >> _U(27))) * _U(0x94D049BB133111EB)).astype(np.uint64)
    return x ^ (x >> _U(31))


def _stream(table: str, column: str, keys: np.ndarray, salt: int = 0) -> np.ndarray:
    """u64 uniform stream, deterministic per (table, column, salt, key)."""
    seed = _U(_stable_seed(table, column, salt, "tpch-tpu-v1"))
    return _splitmix64(keys.astype(np.uint64) ^ seed)


def _uniform(table, column, keys, lo: int, hi: int, salt: int = 0) -> np.ndarray:
    """uniform integers in [lo, hi] inclusive (dbgen's random(lo,hi))."""
    u = _stream(table, column, keys, salt)
    span = _U(hi - lo + 1)
    return (lo + (u % span).astype(np.int64)).astype(np.int64)


# ---------------------------------------------------------------------------
# calendar constants
# ---------------------------------------------------------------------------

_EPOCH = datetime.date(1970, 1, 1)


def _d(y, m, d):
    return (datetime.date(y, m, d) - _EPOCH).days


STARTDATE = _d(1992, 1, 1)
ENDDATE = _d(1998, 12, 31)
CURRENTDATE = _d(1995, 6, 17)
ORDER_DATE_MAX = ENDDATE - 151  # 1998-08-02, per spec


# ---------------------------------------------------------------------------
# fixed vocabularies (spec lists, small dictionaries)
# ---------------------------------------------------------------------------

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

NATIONS = [  # (name, regionkey) — spec's 25 nations
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
SHIPINSTRUCT = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]

TYPE_SYLL1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_SYLL2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_SYLL3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_SYLL1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_SYLL2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]

COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "indian", "ivory", "khaki", "lace", "lavender", "lawn", "lemon",
    "light", "lime", "linen", "magenta", "maroon", "medium", "metallic", "midnight",
    "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange", "orchid",
    "pale", "papaya", "peach", "peru", "pink", "plum", "powder", "puff", "purple",
    "red", "rose", "rosy", "royal", "saddle", "salmon", "sandy", "seashell",
    "sienna", "sky", "slate", "smoke", "snow", "spring", "steel", "tan", "thistle",
    "tomato", "turquoise", "violet", "wheat", "white", "yellow",
]

_FILLER = [
    "carefully", "quickly", "furiously", "slyly", "blithely", "deposits",
    "packages", "requests", "accounts", "instructions", "foxes", "pinto",
    "beans", "ideas", "theodolites", "dependencies", "excuses", "platelets",
    "asymptotes", "courts", "dolphins", "multipliers", "sauternes", "warhorses",
    "sheaves", "pearls", "wake", "sleep", "nag", "haggle", "bold", "final",
    "ironic", "pending", "regular", "express", "unusual", "even", "silent",
    "daring", "about", "above", "according", "across", "after", "against",
]


def _make_comment_pool(name: str, size: int, inject: Optional[Tuple[str, str]],
                       inject_fraction: float) -> List[str]:
    """Bounded pool of comment strings; a fraction contain the two
    injected words in order with filler between (for LIKE '%a%b%')."""
    rng = np.random.default_rng(_stable_seed(name, "pool", "tpch-tpu-v1") % (2**32))
    pool = []
    n_inject = int(size * inject_fraction)
    for i in range(size):
        k = int(rng.integers(4, 9))
        words = [_FILLER[int(rng.integers(0, len(_FILLER)))] for _ in range(k)]
        if inject is not None and i < n_inject:
            words[1] = inject[0]
            words[k - 2] = inject[1]
        pool.append(" ".join(words))
    return pool


@lru_cache(maxsize=None)
def _comment_dict(kind: str) -> Dictionary:
    if kind == "order":  # Q13: '%special%requests%'
        return Dictionary(_make_comment_pool("order", 3000, ("special", "requests"), 0.02))
    if kind == "supplier":  # Q16: '%Customer%Complaints%'
        return Dictionary(_make_comment_pool("supplier", 1500, ("Customer", "Complaints"), 0.01))
    return Dictionary(_make_comment_pool(kind, 2000, None, 0.0))


@lru_cache(maxsize=None)
def _address_pool(kind: str, size: int = 20000) -> Dictionary:
    rng = np.random.default_rng(_stable_seed(kind, "addr", "tpch-tpu-v1") % (2**32))
    alphabet = np.array(list("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789 ,"))
    vals = []
    for _ in range(size):
        k = int(rng.integers(10, 25))
        vals.append("".join(alphabet[rng.integers(0, len(alphabet), k)]))
    return Dictionary(vals)


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

_DEC = T.decimal(12, 2)

TABLES: Dict[str, List[Tuple[str, T.DataType]]] = {
    "region": [
        ("r_regionkey", T.BIGINT), ("r_name", T.VARCHAR), ("r_comment", T.VARCHAR)],
    "nation": [
        ("n_nationkey", T.BIGINT), ("n_name", T.VARCHAR),
        ("n_regionkey", T.BIGINT), ("n_comment", T.VARCHAR)],
    "supplier": [
        ("s_suppkey", T.BIGINT), ("s_name", T.VARCHAR), ("s_address", T.VARCHAR),
        ("s_nationkey", T.BIGINT), ("s_phone", T.VARCHAR), ("s_acctbal", _DEC),
        ("s_comment", T.VARCHAR)],
    "part": [
        ("p_partkey", T.BIGINT), ("p_name", T.VARCHAR), ("p_mfgr", T.VARCHAR),
        ("p_brand", T.VARCHAR), ("p_type", T.VARCHAR), ("p_size", T.BIGINT),
        ("p_container", T.VARCHAR), ("p_retailprice", _DEC), ("p_comment", T.VARCHAR)],
    "partsupp": [
        ("ps_partkey", T.BIGINT), ("ps_suppkey", T.BIGINT),
        ("ps_availqty", T.BIGINT), ("ps_supplycost", _DEC), ("ps_comment", T.VARCHAR)],
    "customer": [
        ("c_custkey", T.BIGINT), ("c_name", T.VARCHAR), ("c_address", T.VARCHAR),
        ("c_nationkey", T.BIGINT), ("c_phone", T.VARCHAR), ("c_acctbal", _DEC),
        ("c_mktsegment", T.VARCHAR), ("c_comment", T.VARCHAR)],
    "orders": [
        ("o_orderkey", T.BIGINT), ("o_custkey", T.BIGINT), ("o_orderstatus", T.VARCHAR),
        ("o_totalprice", _DEC), ("o_orderdate", T.DATE), ("o_orderpriority", T.VARCHAR),
        ("o_clerk", T.VARCHAR), ("o_shippriority", T.BIGINT), ("o_comment", T.VARCHAR)],
    "lineitem": [
        ("l_orderkey", T.BIGINT), ("l_partkey", T.BIGINT), ("l_suppkey", T.BIGINT),
        ("l_linenumber", T.BIGINT), ("l_quantity", _DEC), ("l_extendedprice", _DEC),
        ("l_discount", _DEC), ("l_tax", _DEC), ("l_returnflag", T.VARCHAR),
        ("l_linestatus", T.VARCHAR), ("l_shipdate", T.DATE), ("l_commitdate", T.DATE),
        ("l_receiptdate", T.DATE), ("l_shipinstruct", T.VARCHAR),
        ("l_shipmode", T.VARCHAR), ("l_comment", T.VARCHAR)],
}


def _scaled(base: int, sf: float) -> int:
    return max(1, int(round(base * sf)))


def base_row_count(table: str, sf: float) -> int:
    """Rows before lineitem expansion (for lineitem: ORDER count)."""
    return {
        "region": 5,
        "nation": 25,
        "supplier": _scaled(10_000, sf),
        "part": _scaled(200_000, sf),
        "partsupp": _scaled(200_000, sf) * 4,
        "customer": _scaled(150_000, sf),
        "orders": _scaled(1_500_000, sf),
        "lineitem": _scaled(1_500_000, sf),
    }[table]


def _n_customers(sf):
    return _scaled(150_000, sf)


def _n_parts(sf):
    return _scaled(200_000, sf)


def _n_suppliers(sf):
    return _scaled(10_000, sf)


def _n_orders(sf):
    return _scaled(1_500_000, sf)


def _n_clerks(sf):
    return max(1, _scaled(1_000, sf))


# sparse order keys: 8 used keys per 32-key block (spec's mk_sparse)
def order_index_to_key(idx: np.ndarray) -> np.ndarray:
    i = idx.astype(np.int64)
    return ((i >> 3) << 5) + (i & 7) + 1


def _line_counts(order_idx: np.ndarray) -> np.ndarray:
    """lines per order, 1..7, deterministic on order index."""
    return _uniform("lineitem", "count", order_idx, 1, 7)


@lru_cache(maxsize=8)
def lineitem_row_count(sf: float) -> int:
    n = _n_orders(sf)
    total = 0
    step = 4_000_000
    for a in range(0, n, step):
        idx = np.arange(a, min(a + step, n), dtype=np.int64)
        total += int(_line_counts(idx).sum())
    return total


# ---------------------------------------------------------------------------
# per-order lineitem economics (shared by orders.o_totalprice and lineitem)
# ---------------------------------------------------------------------------


def _retail_price_cents(partkey: np.ndarray) -> np.ndarray:
    pk = partkey.astype(np.int64)
    return 90000 + ((pk // 10) % 20001) + 100 * (pk % 1000)


def _line_fields(order_idx: np.ndarray, line_no: np.ndarray, sf: float):
    """Per-(order, line) deterministic economics; keys mix both."""
    k = order_idx.astype(np.int64) * 8 + line_no.astype(np.int64)
    qty = _uniform("lineitem", "qty", k, 1, 50)
    partkey = _uniform("lineitem", "part", k, 1, _n_parts(sf))
    disc = _uniform("lineitem", "disc", k, 0, 10)  # percent
    tax = _uniform("lineitem", "tax", k, 0, 8)  # percent
    eprice = qty * _retail_price_cents(partkey)  # cents (scale 2)
    return qty, partkey, disc, tax, eprice


def _order_total_cents(order_idx: np.ndarray, sf: float) -> np.ndarray:
    """o_totalprice = sum over lines of eprice*(1+tax)*(1-disc), rounded
    per line to cents like the spec's per-line money rounding."""
    counts = _line_counts(order_idx)
    total = np.zeros(len(order_idx), dtype=np.int64)
    for ln in range(1, 8):
        mask = counts >= ln
        if not mask.any():
            continue
        qty, pk, disc, tax, ep = _line_fields(order_idx, np.full(len(order_idx), ln), sf)
        # cents * pct * pct / 10000, round half away from zero
        x = ep * (100 - disc) * (100 + tax)
        line_total = np.sign(x) * ((np.abs(x) + 5000) // 10000)
        total += np.where(mask, line_total, 0)
    return total


def _ps_suppkey(partkey: np.ndarray, j: np.ndarray, sf: float) -> np.ndarray:
    """partsupp supplier spread (spec formula): the j-th supplier of part
    p is (p + j*(S/4 + (p-1)/S)) mod S + 1 — guarantees lineitem's
    (partkey, suppkey) pairs exist in partsupp."""
    S = _n_suppliers(sf)
    pk = partkey.astype(np.int64)
    return (pk + j * (S // 4 + (pk - 1) // S)) % S + 1


# ---------------------------------------------------------------------------
# string columns: dictionaries + code computation
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _format_dict(prefix: str, n: int) -> Dictionary:
    """'Prefix#%09d' dictionaries — zero-padding makes lexical order equal
    numeric order, so code == key - 1 without a search."""
    return Dictionary([f"{prefix}#{i:09d}" for i in range(1, n + 1)])


@lru_cache(maxsize=None)
def _phone_data(kind: str, n: int) -> Tuple[Dictionary, np.ndarray]:
    """Phones 'CC-xxx-xxx-xxxx', CC = 10 + nationkey (spec format).
    Returns (dictionary, lut) with lut[key-1] = code."""
    keys = np.arange(1, n + 1, dtype=np.int64)
    nat = _uniform(kind, "nationkey", keys, 0, 24)
    a = _uniform(kind, "ph1", keys, 100, 999)
    b = _uniform(kind, "ph2", keys, 100, 999)
    c = _uniform(kind, "ph3", keys, 1000, 9999)
    vals = [f"{10 + int(nk)}-{int(x)}-{int(y)}-{int(z)}"
            for nk, x, y, z in zip(nat, a, b, c)]
    d = Dictionary(vals)
    lut = np.asarray([d.code(v) for v in vals], dtype=np.int32)
    return d, lut


@lru_cache(maxsize=None)
def _part_name_pool(size: int = 5000) -> Dictionary:
    rng = np.random.default_rng(_stable_seed("pname", "tpch-tpu-v1") % (2**32))
    vals = []
    for _ in range(size):
        idx = rng.choice(len(COLORS), size=5, replace=False)
        vals.append(" ".join(COLORS[i] for i in idx))
    return Dictionary(vals)


@lru_cache(maxsize=None)
def _small_dict(name: str) -> Dictionary:
    return {
        "regions": Dictionary(REGIONS),
        "nations": Dictionary([n for n, _ in NATIONS]),
        "segments": Dictionary(SEGMENTS),
        "priorities": Dictionary(PRIORITIES),
        "shipmodes": Dictionary(SHIPMODES),
        "shipinstruct": Dictionary(SHIPINSTRUCT),
        "types": Dictionary([f"{a} {b} {c}" for a in TYPE_SYLL1 for b in TYPE_SYLL2 for c in TYPE_SYLL3]),
        "containers": Dictionary([f"{a} {b}" for a in CONTAINER_SYLL1 for b in CONTAINER_SYLL2]),
        "brands": Dictionary([f"Brand#{m}{n}" for m in range(1, 6) for n in range(1, 6)]),
        "mfgrs": Dictionary([f"Manufacturer#{m}" for m in range(1, 6)]),
        "orderstatus": Dictionary(["F", "O", "P"]),
        "returnflag": Dictionary(["A", "N", "R"]),
        "linestatus": Dictionary(["F", "O"]),
    }[name]


def _pool_codes(d: Dictionary, stream: np.ndarray) -> np.ndarray:
    """Uniform codes over a pooled dictionary: the pool is random anyway,
    so indexing the *sorted* values uniformly is an equivalent draw."""
    return (stream % _U(len(d.values))).astype(np.int32)


# ---------------------------------------------------------------------------
# column generators: (sf, row_keys) -> (np data, Dictionary | None)
# row_keys is the 1-based primary index of the base table
# ---------------------------------------------------------------------------


def _gen_customer(col: str, keys: np.ndarray, sf: float):
    n = _n_customers(sf)
    if col == "c_custkey":
        return keys, None
    if col == "c_name":
        d = _format_dict("Customer", n)
        return (keys - 1).astype(np.int32), d
    if col == "c_address":
        d = _address_pool("customer")
        return _pool_codes(d, _stream("customer", "addr", keys)), d
    if col == "c_nationkey":
        return _uniform("customer", "nationkey", keys, 0, 24), None
    if col == "c_phone":
        d, lut = _phone_data("customer", n)
        return lut[keys - 1], d
    if col == "c_acctbal":
        return _uniform("customer", "acctbal", keys, -99999, 999999), None
    if col == "c_mktsegment":
        d = _small_dict("segments")
        idx = _uniform("customer", "segment", keys, 0, 4)
        lut = np.asarray([d.code(s) for s in SEGMENTS], dtype=np.int32)
        return lut[idx], d
    if col == "c_comment":
        d = _comment_dict("customer")
        return _pool_codes(d, _stream("customer", "comment", keys)), d
    raise KeyError(col)


def _gen_supplier(col: str, keys: np.ndarray, sf: float):
    n = _n_suppliers(sf)
    if col == "s_suppkey":
        return keys, None
    if col == "s_name":
        return (keys - 1).astype(np.int32), _format_dict("Supplier", n)
    if col == "s_address":
        d = _address_pool("supplier")
        return _pool_codes(d, _stream("supplier", "addr", keys)), d
    if col == "s_nationkey":
        return _uniform("supplier", "nationkey", keys, 0, 24), None
    if col == "s_phone":
        d, lut = _phone_data("supplier", n)
        return lut[keys - 1], d
    if col == "s_acctbal":
        return _uniform("supplier", "acctbal", keys, -99999, 999999), None
    if col == "s_comment":
        d = _comment_dict("supplier")
        return _pool_codes(d, _stream("supplier", "comment", keys)), d
    raise KeyError(col)


def _gen_part(col: str, keys: np.ndarray, sf: float):
    if col == "p_partkey":
        return keys, None
    if col == "p_name":
        d = _part_name_pool()
        return _pool_codes(d, _stream("part", "name", keys)), d
    if col == "p_mfgr":
        d = _small_dict("mfgrs")
        m = _uniform("part", "mfgr", keys, 1, 5)
        lut = np.asarray([d.code(f"Manufacturer#{i}") for i in range(1, 6)], dtype=np.int32)
        return lut[m - 1], d
    if col == "p_brand":
        d = _small_dict("brands")
        m = _uniform("part", "mfgr", keys, 1, 5)  # brand M = mfgr M (spec)
        n2 = _uniform("part", "brandn", keys, 1, 5)
        lut = np.asarray(
            [[d.code(f"Brand#{m_}{n_}") for n_ in range(1, 6)] for m_ in range(1, 6)],
            dtype=np.int32,
        )
        return lut[m - 1, n2 - 1], d
    if col == "p_type":
        d = _small_dict("types")
        idx = _uniform("part", "type", keys, 0, 149)
        vals = [f"{a} {b} {c}" for a in TYPE_SYLL1 for b in TYPE_SYLL2 for c in TYPE_SYLL3]
        lut = np.asarray([d.code(v) for v in vals], dtype=np.int32)
        return lut[idx], d
    if col == "p_size":
        return _uniform("part", "size", keys, 1, 50), None
    if col == "p_container":
        d = _small_dict("containers")
        idx = _uniform("part", "container", keys, 0, 39)
        vals = [f"{a} {b}" for a in CONTAINER_SYLL1 for b in CONTAINER_SYLL2]
        lut = np.asarray([d.code(v) for v in vals], dtype=np.int32)
        return lut[idx], d
    if col == "p_retailprice":
        return _retail_price_cents(keys), None
    if col == "p_comment":
        d = _comment_dict("part")
        return _pool_codes(d, _stream("part", "comment", keys)), d
    raise KeyError(col)


def _gen_partsupp(col: str, keys: np.ndarray, sf: float):
    # keys are 1-based partsupp row numbers; 4 suppliers per part
    i = keys - 1
    partkey = i // 4 + 1
    j = i % 4
    if col == "ps_partkey":
        return partkey, None
    if col == "ps_suppkey":
        return _ps_suppkey(partkey, j, sf), None
    if col == "ps_availqty":
        return _uniform("partsupp", "availqty", keys, 1, 9999), None
    if col == "ps_supplycost":
        return _uniform("partsupp", "supplycost", keys, 100, 100000), None
    if col == "ps_comment":
        d = _comment_dict("partsupp")
        return _pool_codes(d, _stream("partsupp", "comment", keys)), d
    raise KeyError(col)


def _custkey_for_order(order_idx: np.ndarray, sf: float) -> np.ndarray:
    """Orders reference only customers whose key is not divisible by 3
    (spec: one third of customers have no orders)."""
    n_cust = _n_customers(sf)
    n_usable = n_cust - n_cust // 3
    j = _uniform("orders", "cust", order_idx, 1, max(n_usable, 1))
    return j + (j - 1) // 2  # j-th positive integer not divisible by 3


def _order_status(order_idx: np.ndarray, sf: float) -> np.ndarray:
    """F if all lines shipped before CURRENTDATE, O if none, else P —
    derived from the same line fields lineitem generates."""
    counts = _line_counts(order_idx)
    odate = _uniform("orders", "date", order_idx, STARTDATE, ORDER_DATE_MAX)
    any_f = np.zeros(len(order_idx), dtype=bool)
    any_o = np.zeros(len(order_idx), dtype=bool)
    for ln in range(1, 8):
        mask = counts >= ln
        k = order_idx.astype(np.int64) * 8 + ln
        ship = odate + _uniform("lineitem", "shipdays", k, 1, 121)
        f = ship <= CURRENTDATE
        any_f |= mask & f
        any_o |= mask & ~f
    return np.where(any_f & any_o, 2, np.where(any_f, 0, 1))  # P, F, O codes below


def _gen_orders(col: str, keys: np.ndarray, sf: float):
    order_idx = keys - 1  # 0-based order index
    if col == "o_orderkey":
        return order_index_to_key(order_idx), None
    if col == "o_custkey":
        return _custkey_for_order(order_idx, sf), None
    if col == "o_orderstatus":
        d = _small_dict("orderstatus")
        st = _order_status(order_idx, sf)  # 0=F 1=O 2=P
        lut = np.asarray([d.code("F"), d.code("O"), d.code("P")], dtype=np.int32)
        return lut[st], d
    if col == "o_totalprice":
        return _order_total_cents(order_idx, sf), None
    if col == "o_orderdate":
        return _uniform("orders", "date", order_idx, STARTDATE, ORDER_DATE_MAX).astype(np.int32), None
    if col == "o_orderpriority":
        d = _small_dict("priorities")
        idx = _uniform("orders", "priority", order_idx, 0, 4)
        lut = np.asarray([d.code(p) for p in PRIORITIES], dtype=np.int32)
        return lut[idx], d
    if col == "o_clerk":
        d = _format_dict("Clerk", _n_clerks(sf))
        c = _uniform("orders", "clerk", order_idx, 1, _n_clerks(sf))
        return (c - 1).astype(np.int32), d
    if col == "o_shippriority":
        return np.zeros(len(keys), dtype=np.int64), None
    if col == "o_comment":
        d = _comment_dict("order")
        return _pool_codes(d, _stream("orders", "comment", order_idx)), d
    raise KeyError(col)


def _lineitem_rows(order_lo: int, order_hi: int, sf: float):
    """Expand orders [lo, hi) into (order_idx, line_no) row arrays."""
    order_idx = np.arange(order_lo, order_hi, dtype=np.int64)
    counts = _line_counts(order_idx)
    oi = np.repeat(order_idx, counts)
    ln = np.concatenate([np.arange(1, c + 1) for c in counts]) if len(counts) else np.zeros(0, np.int64)
    return oi, ln.astype(np.int64)


def _gen_lineitem(col: str, oi: np.ndarray, ln: np.ndarray, sf: float):
    k = oi * 8 + ln
    odate = _uniform("orders", "date", oi, STARTDATE, ORDER_DATE_MAX)
    if col == "l_orderkey":
        return order_index_to_key(oi), None
    if col == "l_linenumber":
        return ln, None
    if col in ("l_partkey", "l_suppkey", "l_quantity", "l_extendedprice", "l_discount", "l_tax"):
        qty, pk, disc, tax, ep = _line_fields(oi, ln, sf)
        if col == "l_partkey":
            return pk, None
        if col == "l_suppkey":
            j = _uniform("lineitem", "suppj", k, 0, 3)
            return _ps_suppkey(pk, j, sf), None
        if col == "l_quantity":
            return qty * 100, None  # decimal(12,2)
        if col == "l_extendedprice":
            return ep, None
        if col == "l_discount":
            return disc, None  # pct == scale-2 cents of 0.xx
        if col == "l_tax":
            return tax, None
    if col == "l_shipdate":
        return (odate + _uniform("lineitem", "shipdays", k, 1, 121)).astype(np.int32), None
    if col == "l_commitdate":
        return (odate + _uniform("lineitem", "commitdays", k, 30, 90)).astype(np.int32), None
    if col == "l_receiptdate":
        ship = odate + _uniform("lineitem", "shipdays", k, 1, 121)
        return (ship + _uniform("lineitem", "receiptdays", k, 1, 30)).astype(np.int32), None
    if col == "l_returnflag":
        d = _small_dict("returnflag")
        ship = odate + _uniform("lineitem", "shipdays", k, 1, 121)
        receipt = ship + _uniform("lineitem", "receiptdays", k, 1, 30)
        r = _uniform("lineitem", "rflag", k, 0, 1)
        lut_ar = np.asarray([d.code("A"), d.code("R")], dtype=np.int32)
        code_n = d.code("N")
        return np.where(receipt <= CURRENTDATE, lut_ar[r], code_n).astype(np.int32), d
    if col == "l_linestatus":
        d = _small_dict("linestatus")
        ship = odate + _uniform("lineitem", "shipdays", k, 1, 121)
        return np.where(ship > CURRENTDATE, d.code("O"), d.code("F")).astype(np.int32), d
    if col == "l_shipinstruct":
        d = _small_dict("shipinstruct")
        idx = _uniform("lineitem", "instruct", k, 0, 3)
        lut = np.asarray([d.code(s) for s in SHIPINSTRUCT], dtype=np.int32)
        return lut[idx], d
    if col == "l_shipmode":
        d = _small_dict("shipmodes")
        idx = _uniform("lineitem", "mode", k, 0, 6)
        lut = np.asarray([d.code(s) for s in SHIPMODES], dtype=np.int32)
        return lut[idx], d
    if col == "l_comment":
        d = _comment_dict("lineitem")
        return _pool_codes(d, _stream("lineitem", "comment", k)), d
    raise KeyError(col)


def _gen_small(table: str, col: str, keys: np.ndarray, sf: float):
    if table == "region":
        if col == "r_regionkey":
            return keys - 1, None
        if col == "r_name":
            d = _small_dict("regions")
            lut = np.asarray([d.code(r) for r in REGIONS], dtype=np.int32)
            return lut[keys - 1], d
        if col == "r_comment":
            d = _comment_dict("region")
            return _pool_codes(d, _stream("region", "comment", keys)), d
    if table == "nation":
        if col == "n_nationkey":
            return keys - 1, None
        if col == "n_name":
            d = _small_dict("nations")
            lut = np.asarray([d.code(n) for n, _ in NATIONS], dtype=np.int32)
            return lut[keys - 1], d
        if col == "n_regionkey":
            rk = np.asarray([r for _, r in NATIONS], dtype=np.int64)
            return rk[keys - 1], None
        if col == "n_comment":
            d = _comment_dict("nation")
            return _pool_codes(d, _stream("nation", "comment", keys)), d
    raise KeyError(f"{table}.{col}")


_GEN = {
    "customer": _gen_customer,
    "supplier": _gen_supplier,
    "part": _gen_part,
    "partsupp": _gen_partsupp,
    "orders": _gen_orders,
}


def generate_column(table: str, col: str, sf: float, lo: int, hi: int):
    """Generate rows [lo, hi) of a column (for lineitem: ORDER range).
    Returns (np_data, Dictionary | None)."""
    if table == "lineitem":
        oi, ln = _lineitem_rows(lo, hi, sf)
        return _gen_lineitem(col, oi, ln, sf)
    keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
    if table in ("region", "nation"):
        return _gen_small(table, col, keys, sf)
    return _GEN[table](col, keys, sf)


# ---------------------------------------------------------------------------
# connector SPI implementation
# ---------------------------------------------------------------------------

SCHEMAS = {"tiny": 0.01, "sf1": 1.0, "sf10": 10.0, "sf100": 100.0}


def _schema_sf(schema: str) -> Optional[float]:
    if schema in SCHEMAS:
        return SCHEMAS[schema]
    if schema.startswith("sf"):
        try:
            return float(schema[2:])
        except ValueError:
            return None
    return None


class TpchMetadata(ConnectorMetadata):
    def list_schemas(self) -> List[str]:
        return list(SCHEMAS)

    def list_tables(self, schema: str) -> List[str]:
        return list(TABLES) if _schema_sf(schema) is not None else []

    def get_table_handle(self, schema: str, table: str) -> Optional[TableHandle]:
        sf = _schema_sf(schema)
        if sf is None or table not in TABLES:
            return None
        return TableHandle("tpch", schema, table, payload=sf)

    def get_table_metadata(self, handle: TableHandle) -> TableMetadata:
        cols = tuple(ColumnMetadata(n, t) for n, t in TABLES[handle.table])
        return TableMetadata(handle.schema, handle.table, cols)

    def column_dictionary(self, handle: TableHandle, column: str) -> Optional[Dictionary]:
        typ = dict(TABLES[handle.table])[column]
        if not typ.is_string:
            return None
        # dictionaries are table-stable: probe one row
        lo_hi = (0, 1)
        _, d = generate_column(handle.table, column, handle.payload, *lo_hi)
        return d

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        sf = handle.payload
        if handle.table == "lineitem":
            rows = float(lineitem_row_count(sf))
        else:
            rows = float(base_row_count(handle.table, sf))
        return TableStatistics(
            row_count=rows, columns=_column_statistics(handle.table, sf, rows)
        )

    def apply_filter(self, handle: TableHandle, constraints):
        """Accept every numeric/temporal constraint: the page source
        generates the constrained columns alongside the requested ones
        and compacts each chunk exactly (full enforcement), so the
        engine never sees a violating row."""
        from trino_tpu.connectors.pushdown import (
            merge_handle_constraints,
            split_supported,
        )

        types = dict(TABLES[handle.table])
        accepted, residual = split_supported(constraints, types.get)
        if not accepted:
            return None
        return merge_handle_constraints(handle, accepted), tuple(residual)

    def apply_projection(self, handle: TableHandle, columns) -> TableHandle:
        # the generator already materializes only the requested columns
        # per batches() call; accepting records the narrowed scan
        return handle


def _days(y: int, m: int, d: int) -> int:
    import datetime

    return (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days


def _column_statistics(table: str, sf: float, rows: float):
    """Analytic per-column (ndv, null_fraction, low, high) for the CBO —
    the spec's value domains, like the reference's TpchMetadata statistic
    tables (plugin/trino-tpch ... StatisticsEstimator). Dates are epoch
    days; decimals raw values."""
    nc, np_, ns, no = _n_customers(sf), _n_parts(sf), _n_suppliers(sf), _n_orders(sf)
    d92, d98 = _days(1992, 1, 1), _days(1998, 12, 31)
    stats = {
        "region": {
            "r_regionkey": (5, 0.0, 0, 4),
            "r_name": (5, 0.0, None, None),
        },
        "nation": {
            "n_nationkey": (25, 0.0, 0, 24),
            "n_regionkey": (5, 0.0, 0, 4),
            "n_name": (25, 0.0, None, None),
        },
        "supplier": {
            "s_suppkey": (ns, 0.0, 1, ns),
            "s_nationkey": (25, 0.0, 0, 24),
            "s_acctbal": (ns, 0.0, -999.99, 9999.99),
        },
        "customer": {
            "c_custkey": (nc, 0.0, 1, nc),
            "c_nationkey": (25, 0.0, 0, 24),
            "c_mktsegment": (5, 0.0, None, None),
            "c_acctbal": (nc, 0.0, -999.99, 9999.99),
        },
        "part": {
            "p_partkey": (np_, 0.0, 1, np_),
            "p_size": (50, 0.0, 1, 50),
            "p_brand": (25, 0.0, None, None),
            "p_mfgr": (5, 0.0, None, None),
            "p_type": (150, 0.0, None, None),
            "p_retailprice": (np_ // 10 or 1, 0.0, 900.0, 2100.0),
        },
        "partsupp": {
            "ps_partkey": (np_, 0.0, 1, np_),
            "ps_suppkey": (ns, 0.0, 1, ns),
            "ps_availqty": (9999, 0.0, 1, 9999),
            "ps_supplycost": (1000, 0.0, 1.0, 1000.0),
        },
        "orders": {
            "o_orderkey": (no, 0.0, 1, (no >> 3 << 5) + 8),
            "o_custkey": (max(nc * 2 // 3, 1), 0.0, 1, nc),
            "o_orderdate": (2406, 0.0, d92, _days(1998, 8, 2)),
            "o_orderstatus": (3, 0.0, None, None),
            "o_orderpriority": (5, 0.0, None, None),
            "o_totalprice": (rows * 0.9, 0.0, 850.0, 560000.0),
        },
        "lineitem": {
            "l_orderkey": (no, 0.0, 1, (no >> 3 << 5) + 8),
            "l_partkey": (np_, 0.0, 1, np_),
            "l_suppkey": (ns, 0.0, 1, ns),
            "l_linenumber": (7, 0.0, 1, 7),
            "l_quantity": (50, 0.0, 1, 50),
            "l_discount": (11, 0.0, 0.0, 0.10),
            "l_tax": (9, 0.0, 0.0, 0.08),
            "l_returnflag": (3, 0.0, None, None),
            "l_linestatus": (2, 0.0, None, None),
            "l_shipdate": (2526, 0.0, d92 + 1, d98),
            "l_commitdate": (2466, 0.0, d92 + 30, d98 - 30),
            "l_receiptdate": (2554, 0.0, d92 + 2, d98 + 30),
            "l_extendedprice": (rows * 0.5, 0.0, 900.0, 105000.0),
        },
    }
    return {
        k: (float(ndv), nf, lo, hi)
        for k, (ndv, nf, lo, hi) in stats.get(table, {}).items()
    }


class TpchSplitManager(ConnectorSplitManager):
    def get_splits(self, handle: TableHandle, target_split_count: int) -> List[Split]:
        base = base_row_count(handle.table, handle.payload)
        n = max(1, min(target_split_count, base))
        per = -(-base // n)
        out = []
        for s, a in enumerate(range(0, base, per)):
            out.append(Split(handle, s, (a, min(a + per, base))))
        return out


class TpchPageSource(ConnectorPageSource):
    def batches(self, split: Split, columns: Sequence[str], batch_rows: int,
                stabilizer=None) -> Iterator[RelBatch]:
        table = split.table.table
        sf = split.table.payload
        cs = getattr(split.table, "constraints", ())
        lo, hi = split.row_range
        types = dict(TABLES[table])
        step = batch_rows
        for a in range(lo, hi, step):
            b = min(a + step, hi)
            gen = {}
            nrows = None
            for name in columns:
                data, d = generate_column(table, name, sf, a, b)
                gen[name] = (np.asarray(data), d)
                nrows = len(data)
            span = nrows  # pre-pruning chunk size (shape stabilization)
            keep = None
            if cs:
                # pushed-down predicate: generate the constrained
                # columns for this chunk too (surviving-columns-only
                # projection still holds — they are dropped after the
                # mask) and compact exactly
                from trino_tpu.connectors.pushdown import constraint_mask

                def _coldata(nm, _a=a, _b=b, _gen=gen):
                    if nm in _gen:
                        return _gen[nm][0], None
                    data, _ = generate_column(table, nm, sf, _a, _b)
                    return np.asarray(data), None

                mask = constraint_mask(cs, _coldata)
                keep = np.nonzero(mask)[0]
                if span is None:  # count(*) over a constrained scan
                    span = len(mask)
                nrows = len(keep)
            if nrows is None:  # no columns requested (count(*) scans)
                oi_count = b - a
                if table == "lineitem":
                    oi, _ = _lineitem_rows(a, b, sf)
                    oi_count = len(oi)
                nrows = oi_count
                span = nrows
            # stabilized scans pad to the capacity class of the chunk's
            # pre-pruning span, so pushdown/dynamic-filter pruning never
            # mints a data-dependent (smaller) class
            if stabilizer is not None:
                cap = stabilizer.chunk_capacity(span)
            else:
                cap = bucket_capacity(nrows)
            cols = []
            for name in columns:
                data, d = gen[name]
                if keep is not None:
                    data = data[keep]
                typ = types[name]
                arr = np.zeros(cap, dtype=typ.dtype)
                arr[:nrows] = data
                cols.append(Column(typ, jnp.asarray(arr), None, d))
            live = None
            if nrows != cap:
                lv = np.zeros(cap, dtype=bool)
                lv[:nrows] = True
                live = jnp.asarray(lv)
            yield RelBatch(cols, live)


def create_tpch_connector() -> Connector:
    return Connector(
        "tpch",
        TpchMetadata(),
        TpchSplitManager(),
        TpchPageSource(),
    )
