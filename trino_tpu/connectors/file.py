"""Local-filesystem file connector (CSV / JSON-lines).

The SPI-generality proof (SURVEY.md §2.12): unlike tpch/memory —
which are in-process — this connector reads external data through the
full SPI surface (schema discovery, type inference, splits, page
source, sink, DDL), the shape of plugin/trino-hive's
file-format path reduced to local files:

  root/
    <schema>/                directory per schema
      <table>.csv            single-file table (header row)
      <table>.jsonl          single-file table (one JSON object/line)
      <table>/part-*.csv     multi-file table (writes append parts)

TPU-first deltas match the other connectors: parsed files become
host-side SoA columns with table-stable dictionaries for strings
(spi.py contract), cached per (path, mtime) so repeated scans skip the
parse; batches pad to power-of-two capacities for stable compile
shapes.
"""

from __future__ import annotations

import csv
import dataclasses
import datetime
import json
import os
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.block import Column, Dictionary, RelBatch, bucket_capacity
from trino_tpu.connectors.pushdown import (
    constraint_mask,
    merge_handle_constraints,
    range_predicate,
    split_supported,
)
from trino_tpu.connectors.spi import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSink,
    ConnectorPageSource,
    ConnectorSplitManager,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)

_EPOCH = datetime.date(1970, 1, 1)
_SAMPLE_ROWS = 100  # rows examined for type inference


# ---------------------------------------------------------------------------
# type inference (the hive-connector column-coercion analogue, local form)
# ---------------------------------------------------------------------------


def _classify(text: str) -> str:
    if text == "":
        return "null"
    low = text.lower()
    if low in ("true", "false"):
        return "boolean"
    try:
        int(text)
        return "bigint"
    except ValueError:
        pass
    try:
        float(text)
        return "double"
    except ValueError:
        pass
    try:
        datetime.date.fromisoformat(text)
        return "date"
    except ValueError:
        pass
    return "varchar"


_WIDEN = {
    frozenset(("bigint", "double")): "double",
}


def _unify_kinds(kinds) -> str:
    kinds = {k for k in kinds if k != "null"}
    if not kinds:
        return "varchar"
    if len(kinds) == 1:
        return next(iter(kinds))
    widened = _WIDEN.get(frozenset(kinds))
    return widened or "varchar"


_KIND_TO_TYPE = {
    "boolean": T.BOOLEAN,
    "bigint": T.BIGINT,
    "double": T.DOUBLE,
    "date": T.DATE,
    "varchar": T.VARCHAR,
}


def _parquet_type(c) -> T.DataType:
    """Parquet physical+converted type -> engine type."""
    from trino_tpu.connectors import parquet_format as PQ

    if c.physical == PQ.T_BOOLEAN:
        return T.BOOLEAN
    if c.physical == PQ.T_INT32:
        if c.converted == PQ.C_DATE:
            return T.DATE
        if c.converted == PQ.C_DECIMAL:
            return T.decimal(min(c.precision or 9, 18), c.scale or 0)
        return T.INTEGER
    if c.physical == PQ.T_INT64:
        if c.converted == PQ.C_DECIMAL:
            return T.decimal(min(c.precision or 18, 18), c.scale or 0)
        if c.converted == PQ.C_TIMESTAMP_MICROS:
            return T.TIMESTAMP
        return T.BIGINT
    if c.physical == PQ.T_FLOAT:
        return T.REAL
    if c.physical == PQ.T_DOUBLE:
        return T.DOUBLE
    if c.physical == PQ.T_BYTE_ARRAY:
        if c.converted not in (None, PQ.C_UTF8):
            raise ValueError(
                f"unsupported BYTE_ARRAY converted type {c.converted}"
            )
        if c.converted is None:
            # raw VARBINARY has no engine representation yet
            raise ValueError(
                "BYTE_ARRAY without UTF8 annotation (varbinary) is not"
                " supported"
            )
        return T.VARCHAR
    raise ValueError(f"unsupported parquet physical type {c.physical}")


def _to_parquet_column(cm, data, valid, dictionary):
    """Engine host column -> ParquetColumn (write path)."""
    from trino_tpu.connectors import parquet_format as PQ

    t = cm.type
    if t.is_string:
        vals = [
            (dictionary.values[int(v)] if dictionary else "").encode("utf-8")
            for v in data
        ]
        return PQ.ParquetColumn(cm.name, PQ.T_BYTE_ARRAY, PQ.C_UTF8,
                                values=vals, valid=valid)
    if t.kind == T.TypeKind.BOOLEAN:
        return PQ.ParquetColumn(cm.name, PQ.T_BOOLEAN,
                                values=np.asarray(data, bool), valid=valid)
    if t.kind == T.TypeKind.DATE:
        return PQ.ParquetColumn(cm.name, PQ.T_INT32, PQ.C_DATE,
                                values=np.asarray(data, np.int32),
                                valid=valid)
    if t.kind == T.TypeKind.INTEGER:
        return PQ.ParquetColumn(cm.name, PQ.T_INT32,
                                values=np.asarray(data, np.int32),
                                valid=valid)
    if t.is_decimal:
        return PQ.ParquetColumn(cm.name, PQ.T_INT64, PQ.C_DECIMAL,
                                scale=t.scale, precision=t.precision,
                                values=np.asarray(data, np.int64),
                                valid=valid)
    if t.kind == T.TypeKind.TIMESTAMP:
        return PQ.ParquetColumn(cm.name, PQ.T_INT64, PQ.C_TIMESTAMP_MICROS,
                                values=np.asarray(data, np.int64),
                                valid=valid)
    if t.kind == T.TypeKind.REAL:
        return PQ.ParquetColumn(cm.name, PQ.T_FLOAT,
                                values=np.asarray(data, np.float32),
                                valid=valid)
    if t.kind == T.TypeKind.DOUBLE:
        return PQ.ParquetColumn(cm.name, PQ.T_DOUBLE,
                                values=np.asarray(data, np.float64),
                                valid=valid)
    if t.kind in (T.TypeKind.BIGINT, T.TypeKind.TINYINT,
                  T.TypeKind.SMALLINT):
        # narrow ints widen to INT64 (parquet has no INT8/16 physical);
        # they read back as BIGINT — documented widening, not drift
        return PQ.ParquetColumn(cm.name, PQ.T_INT64,
                                values=np.asarray(data, np.int64),
                                valid=valid)
    raise ValueError(f"cannot write {t} to parquet")


def _parse_cell(text: str, t: T.DataType):
    """-> (value, is_null) in the column's storage representation.
    Cells that fail to parse as the inferred/declared type become NULL
    (hive's lenient malformed-cell semantics) — inference samples only
    the head of the file, so a stray 'n/a' at row 101 must not kill
    the scan."""
    if text == "":
        return 0, True
    try:
        if t.kind == T.TypeKind.BOOLEAN:
            return text.lower() == "true", False
        if t.kind == T.TypeKind.DATE:
            return (datetime.date.fromisoformat(text) - _EPOCH).days, False
        if t.kind == T.TypeKind.DOUBLE:
            return float(text), False
        if t.is_string:
            return text, False
        # bigint: int(text) first — int(float(text)) loses precision past
        # 2^53 (9007199254740993 would read back as ...992); the float
        # path only tolerates decimal-looking text like "3.0"
        try:
            return int(text), False
        except ValueError:
            return int(float(text)), False
    except (ValueError, OverflowError):
        return 0, True


# ---------------------------------------------------------------------------
# parsed-table cache
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _ParsedTable:
    columns: List[ColumnMetadata]
    data: Dict[str, np.ndarray]
    valid: Dict[str, Optional[np.ndarray]]
    dictionaries: Dict[str, Optional[Dictionary]]
    row_count: int
    stamp: tuple  # (paths, mtimes) fingerprint


@dataclasses.dataclass
class _ParquetMeta:
    """Footer-derived table facts for all-parquet tables: schema, row
    count, and chunk-statistics aggregates — enough for metadata,
    statistics, and apply_filter without reading any data pages."""

    columns: List[ColumnMetadata]
    row_count: int
    stats: Dict[str, Optional[tuple]]  # name -> (min, max, null_count)
    stamp: tuple


class _FileStore:
    _MAX_FILTERED = 8  # bounded per-constraint-set parse cache

    def __init__(self, root: str):
        self.root = root
        self.lock = named_lock("_FileStore.lock")
        self._cache: Dict[Tuple[str, str], _ParsedTable] = {}
        # (schema, table, constraints) -> filtered _ParsedTable
        self._filtered_cache: Dict[tuple, _ParsedTable] = {}
        self._meta_cache: Dict[Tuple[str, str], _ParquetMeta] = {}

    # -- layout --
    def table_paths(self, schema: str, table: str) -> List[str]:
        base = os.path.join(self.root, schema)
        for ext in (".csv", ".jsonl", ".parquet"):
            p = os.path.join(base, table + ext)
            if os.path.isfile(p):
                return [p]
        d = os.path.join(base, table)
        if os.path.isdir(d):
            return sorted(
                os.path.join(d, f)
                for f in os.listdir(d)
                if f.endswith((".csv", ".jsonl", ".parquet"))
            )
        return []

    def _stamp(self, paths: List[str]) -> tuple:
        return tuple((p, os.path.getmtime(p)) for p in paths)

    def declared_schema(self, schema: str, table: str):
        """Declared column types from the table's sidecar schema file
        (the metastore-schema analogue: DDL-declared types win over
        data inference, exactly hive's schema-vs-file split). None for
        bare files that were never CREATEd."""
        p = os.path.join(self.root, schema, table, ".schema.json")
        if not os.path.isfile(p):
            return None
        with open(p) as f:
            decl = json.load(f)
        return [
            ColumnMetadata(
                c["name"],
                T.DataType(
                    T.TypeKind(c["kind"]), c.get("precision"), c.get("scale")
                ),
            )
            for c in decl
        ]

    def parsed(self, schema: str, table: str) -> _ParsedTable:
        paths = self.table_paths(schema, table)
        if not paths:
            raise KeyError(f"no files for table {schema}.{table}")
        stamp = self._stamp(paths)
        key = (schema, table)
        with self.lock:
            hit = self._cache.get(key)
            if hit is not None and hit.stamp == stamp:
                return hit
        pq = [p for p in paths if p.endswith(".parquet")]
        if pq and len(pq) != len(paths):
            raise ValueError(
                f"table {schema}.{table} mixes parquet and text parts"
            )
        if pq:
            parsed = self._parse_parquet(paths, stamp)
        else:
            parsed = self._parse(
                paths, stamp, self.declared_schema(schema, table)
            )
        with self.lock:
            self._cache[key] = parsed
        return parsed

    def parquet_meta(self, schema: str, table: str) -> Optional[_ParquetMeta]:
        """Footer-only schema + statistics for all-parquet tables (None
        for text tables or LIST schemas). Reads no data pages, so
        metadata and statistics queries never force a full parse — the
        scan pays for exactly the row groups it keeps."""
        paths = self.table_paths(schema, table)
        if not paths or not all(p.endswith(".parquet") for p in paths):
            return None
        stamp = self._stamp(paths)
        key = (schema, table)
        with self.lock:
            hit = self._meta_cache.get(key)
            if hit is not None and hit.stamp == stamp:
                return hit
        from trino_tpu.connectors import parquet_format as PQ

        per = [PQ.read_parquet_meta(p) for p in paths]
        first_cols = per[0][0]
        if any(c.list_lengths is not None for c in first_cols):
            return None  # LIST columns: the parse path fails loudly
        columns = [
            ColumnMetadata(c.name, _parquet_type(c)) for c in first_cols
        ]
        row_count = sum(n for _, n, _ in per)
        stats: Dict[str, Optional[tuple]] = {}
        for cm in columns:
            parts = [s.get(cm.name) for _, _, s in per]
            if any(p is None for p in parts):
                stats[cm.name] = None
                continue
            nulls = (
                None
                if any(p[2] is None for p in parts)
                else sum(p[2] for p in parts)
            )
            stats[cm.name] = (
                min(p[0] for p in parts), max(p[1] for p in parts), nulls
            )
        out = _ParquetMeta(columns, row_count, stats, stamp)
        with self.lock:
            self._meta_cache[key] = out
        return out

    def parsed_filtered(
        self, schema: str, table: str, constraints: tuple
    ) -> _ParsedTable:
        """Parsed table with ``constraints`` fully enforced (rows
        compacted). Parquet tables prune whole row groups by min/max
        stats first (read_parquet predicate), then apply the exact
        mask; text tables mask the cached full parse. Cached per
        constraint set with the same mtime stamp as the base cache."""
        if not constraints:
            return self.parsed(schema, table)
        paths = self.table_paths(schema, table)
        if not paths:
            raise KeyError(f"no files for table {schema}.{table}")
        stamp = self._stamp(paths)
        key = (schema, table, tuple(constraints))
        with self.lock:
            hit = self._filtered_cache.get(key)
            if hit is not None and hit.stamp == stamp:
                return hit
            base = self._cache.get((schema, table))
        if base is not None and base.stamp == stamp:
            pass  # already in memory — masking beats re-reading
        elif all(p.endswith(".parquet") for p in paths):
            base = self._parse_parquet(
                paths, stamp, predicate=range_predicate(constraints)
            )
        else:
            base = self.parsed(schema, table)
        mask = constraint_mask(
            constraints,
            lambda name: (base.data[name], base.valid[name]),
        )
        keep = (
            np.nonzero(mask)[0]
            if mask is not None
            else np.arange(base.row_count)
        )
        data = {n: a[keep] for n, a in base.data.items()}
        valid = {
            n: (v[keep] if v is not None else None)
            for n, v in base.valid.items()
        }
        out = _ParsedTable(
            base.columns, data, valid, base.dictionaries,
            int(len(keep)), stamp,
        )
        with self.lock:
            if len(self._filtered_cache) >= self._MAX_FILTERED:
                self._filtered_cache.pop(next(iter(self._filtered_cache)))
            self._filtered_cache[key] = out
        return out

    # -- parsing --
    def _rows_of(self, path: str) -> Tuple[List[str], List[List[str]]]:
        """-> (column names, rows of raw strings)."""
        if path.endswith(".jsonl"):
            names: List[str] = []
            rows: List[dict] = []
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    obj = json.loads(line)
                    for k in obj:
                        if k not in names:
                            names.append(k)
                    rows.append(obj)
            out = []
            for obj in rows:
                out.append([
                    "" if obj.get(k) is None else str(obj.get(k))
                    for k in names
                ])
            return names, out
        with open(path, newline="") as f:
            reader = csv.reader(f)
            try:
                names = next(reader)
            except StopIteration:
                return [], []
            return names, [row for row in reader]

    def _parse(
        self, paths: List[str], stamp: tuple, declared=None
    ) -> _ParsedTable:
        names: List[str] = []
        all_rows: List[List[str]] = []
        for p in paths:
            file_names, rows = self._rows_of(p)
            if not names:
                names = file_names
            elif file_names and file_names != names:
                raise ValueError(
                    f"schema mismatch across parts: {file_names} vs {names}"
                )
            all_rows.extend(rows)
        if declared is not None:
            if not names:
                names = [c.name for c in declared]
            columns = list(declared)
        else:
            # infer each column's type from a sample
            kinds = []
            for i in range(len(names)):
                sample = (
                    row[i] if i < len(row) else ""
                    for row in all_rows[:_SAMPLE_ROWS]
                )
                kinds.append(_unify_kinds(_classify(c) for c in sample))
            columns = [
                ColumnMetadata(n, _KIND_TO_TYPE[k])
                for n, k in zip(names, kinds)
            ]
        data: Dict[str, np.ndarray] = {}
        valid: Dict[str, Optional[np.ndarray]] = {}
        dicts: Dict[str, Optional[Dictionary]] = {}
        n = len(all_rows)
        for i, cm in enumerate(columns):
            vals = []
            nulls = np.zeros(n, dtype=bool)
            for r, row in enumerate(all_rows):
                cell = row[i] if i < len(row) else ""
                v, is_null = _parse_cell(cell, cm.type)
                nulls[r] = is_null
                vals.append(v)
            if cm.type.is_string:
                d = Dictionary(sorted({v for v in vals if isinstance(v, str)}))
                codes = np.asarray(
                    [d.code(v) if isinstance(v, str) else 0 for v in vals],
                    dtype=np.int32,
                )
                data[cm.name] = codes
                dicts[cm.name] = d
            else:
                data[cm.name] = np.asarray(vals, dtype=cm.type.dtype)
                dicts[cm.name] = None
            valid[cm.name] = ~nulls if nulls.any() else None
        return _ParsedTable(columns, data, valid, dicts, n, stamp)

    def _parse_parquet(
        self, paths: List[str], stamp: tuple, predicate=None
    ) -> _ParsedTable:
        """Typed parquet parts -> the parsed-table form (the
        lib/trino-parquet read path reduced to the engine's types).
        ``predicate`` ({col: (lo, hi)}) skips row groups whose min/max
        stats fall outside the range — the caller must still enforce
        the exact constraints on what survives."""
        from trino_tpu.connectors import parquet_format as PQ

        per_file = [PQ.read_parquet(p, predicate=predicate) for p in paths]
        first_cols, _ = per_file[0]
        columns: List[ColumnMetadata] = []
        for c in first_cols:
            if c.list_lengths is not None:
                # the parquet codec reads 3-level LISTs (r5); mapping
                # them onto engine ArrayColumns through this connector
                # is not wired yet — fail loudly, never flatten
                raise ValueError(
                    f"parquet LIST column {c.name!r} is not yet "
                    "supported by the file connector"
                )
            columns.append(ColumnMetadata(c.name, _parquet_type(c)))
        data: Dict[str, np.ndarray] = {}
        valid: Dict[str, Optional[np.ndarray]] = {}
        dicts: Dict[str, Optional[Dictionary]] = {}
        n = sum(nr for _, nr in per_file)
        # scale/precision are part of the signature: DECIMAL parts with
        # different scales would otherwise concatenate their scaled
        # int64 payloads unrescaled (ADVICE r3)
        def _sig(cols):
            return [
                (c.name, c.physical, c.converted, c.scale, c.precision)
                for c in cols
            ]

        first_sig = _sig(first_cols)
        for cols_f, _ in per_file[1:]:
            sig = _sig(cols_f)
            if sig != first_sig:
                raise ValueError(
                    f"schema mismatch across parquet parts: {sig} vs"
                    f" {first_sig}"
                )
        for i, cm in enumerate(columns):
            parts = [cols[i] for cols, _ in per_file]
            valids = [
                p.valid
                if p.valid is not None
                else np.ones(
                    len(p.values) if isinstance(p.values, list)
                    else p.values.shape[0], bool
                )
                for p in parts
            ]
            v = np.concatenate(valids) if valids else np.ones(0, bool)
            if cm.type.is_string:
                texts: List[Optional[str]] = []
                for p, pv in zip(parts, valids):
                    for b, ok in zip(p.values, pv):
                        texts.append(
                            b.decode("utf-8") if ok else None
                        )
                d = Dictionary(sorted({t for t in texts if t is not None}))
                data[cm.name] = np.asarray(
                    [d.code(t) if t is not None else 0 for t in texts],
                    dtype=np.int32,
                )
                dicts[cm.name] = d
            else:
                data[cm.name] = np.concatenate(
                    [np.asarray(p.values) for p in parts]
                ).astype(cm.type.dtype)
                dicts[cm.name] = None
            valid[cm.name] = v if not v.all() else None
        return _ParsedTable(columns, data, valid, dicts, n, stamp)


# ---------------------------------------------------------------------------
# SPI surfaces
# ---------------------------------------------------------------------------


class FileMetadata(ConnectorMetadata):
    def __init__(self, store: _FileStore, file_format: str = "csv"):
        self.store = store
        self.file_format = file_format

    def list_schemas(self) -> List[str]:
        root = self.store.root
        if not os.path.isdir(root):
            return []
        return sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
        )

    def list_tables(self, schema: str) -> List[str]:
        base = os.path.join(self.store.root, schema)
        if not os.path.isdir(base):
            return []
        out = set()
        for f in os.listdir(base):
            p = os.path.join(base, f)
            if os.path.isfile(p) and f.endswith((".csv", ".jsonl")):
                out.add(f.rsplit(".", 1)[0])
            elif os.path.isdir(p):
                out.add(f)
        return sorted(out)

    def get_table_handle(self, schema: str, table: str) -> Optional[TableHandle]:
        if not self.store.table_paths(schema, table):
            return None
        return TableHandle("file", schema, table)

    def get_table_metadata(self, handle: TableHandle) -> TableMetadata:
        pm = self.store.parquet_meta(handle.schema, handle.table)
        if pm is not None:
            return TableMetadata(
                handle.schema, handle.table, tuple(pm.columns)
            )
        parsed = self.store.parsed(handle.schema, handle.table)
        return TableMetadata(
            handle.schema, handle.table, tuple(parsed.columns)
        )

    def column_dictionary(self, handle: TableHandle, column: str):
        pm = self.store.parquet_meta(handle.schema, handle.table)
        if pm is not None:
            t = next(
                (c.type for c in pm.columns if c.name == column), None
            )
            if t is not None and not t.is_string:
                return None  # footer answers without touching pages
        # a constrained handle must hand out the FILTERED table's
        # dictionary — its batches carry that table's codes
        cs = getattr(handle, "constraints", ())
        parsed = (
            self.store.parsed_filtered(handle.schema, handle.table, cs)
            if cs
            else self.store.parsed(handle.schema, handle.table)
        )
        return parsed.dictionaries.get(column)

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        pm = self.store.parquet_meta(handle.schema, handle.table)
        if pm is not None:
            # footer chunk statistics: exact min/max/null-fraction, ndv
            # unknowable without reading pages — live row count is the
            # standard upper-bound estimate
            cols = {}
            for cm in pm.columns:
                st = pm.stats.get(cm.name)
                if cm.type.is_string or st is None or pm.row_count == 0:
                    continue
                mn, mx, nulls = st
                nf = (
                    float(nulls) / pm.row_count if nulls is not None else 0.0
                )
                cols[cm.name] = (
                    pm.row_count * (1.0 - nf), nf, float(mn), float(mx)
                )
            return TableStatistics(
                row_count=float(pm.row_count), columns=cols
            )
        parsed = self.store.parsed(handle.schema, handle.table)
        cols = {}
        for cm in parsed.columns:
            arr = parsed.data[cm.name]
            valid = parsed.valid[cm.name]
            # NULL placeholders (stored 0) must not pollute min/max/ndv
            live = arr if valid is None else arr[valid]
            nf = (
                0.0
                if valid is None or len(arr) == 0
                else 1.0 - float(valid.sum()) / len(arr)
            )
            if cm.type.is_string or len(live) == 0:
                continue
            cols[cm.name] = (
                float(len(np.unique(live))), nf,
                float(live.min()), float(live.max()),
            )
        return TableStatistics(
            row_count=float(parsed.row_count), columns=cols
        )

    def apply_filter(self, handle, constraints):
        pm = self.store.parquet_meta(handle.schema, handle.table)
        cols = (
            pm.columns
            if pm is not None
            else self.store.parsed(handle.schema, handle.table).columns
        )
        types = {c.name: c.type for c in cols}
        accepted, residual = split_supported(constraints, types.get)
        if not accepted:
            return None
        return merge_handle_constraints(handle, accepted), tuple(residual)

    def apply_projection(self, handle, columns):
        # batches() already materializes only the requested columns;
        # accepting keeps the ProjectNode narrowing in the plan
        return handle

    def create_table(
        self, schema: str, table: str, columns: Sequence[ColumnMetadata]
    ) -> TableHandle:
        d = os.path.join(self.store.root, schema, table)
        if self.store.table_paths(schema, table):
            raise ValueError(f"table '{schema}.{table}' already exists")
        os.makedirs(d, exist_ok=True)
        # a header-only/empty part records the column ORDER and (for
        # parquet) the TYPES; the sidecar schema file records declared
        # types for the text formats (metastore analogue)
        if self.file_format == "parquet":
            from trino_tpu.connectors import parquet_format as PQ

            empty = [
                _to_parquet_column(
                    c, np.zeros(0, dtype=c.type.dtype)
                    if not c.type.is_string else [], None, None
                )
                for c in columns
            ]
            PQ.write_parquet(os.path.join(d, "part-0.parquet"), empty, 0)
        else:
            with open(os.path.join(d, "part-0.csv"), "w", newline="") as f:
                csv.writer(f).writerow([c.name for c in columns])
        with open(os.path.join(d, ".schema.json"), "w") as f:
            json.dump(
                [
                    {
                        "name": c.name,
                        "kind": c.type.kind.value,
                        "precision": c.type.precision,
                        "scale": c.type.scale,
                    }
                    for c in columns
                ],
                f,
            )
        return TableHandle("file", schema, table)

    def drop_table(self, handle: TableHandle) -> None:
        import shutil

        for p in self.store.table_paths(handle.schema, handle.table):
            parent = os.path.dirname(p)
            if os.path.basename(parent) == handle.table:
                shutil.rmtree(parent, ignore_errors=True)
                break
            os.unlink(p)
        with self.store.lock:
            self.store._cache.pop((handle.schema, handle.table), None)
            self.store._meta_cache.pop((handle.schema, handle.table), None)
            for k in [
                k for k in self.store._filtered_cache
                if k[:2] == (handle.schema, handle.table)
            ]:
                self.store._filtered_cache.pop(k, None)


class FileSplitManager(ConnectorSplitManager):
    """One split per row range of the parsed table — the unit of source
    parallelism and FTE retry, like the hive connector's per-file
    splits collapsed onto the parse cache."""

    def __init__(self, store: _FileStore):
        self.store = store
        self.invalidations = 0  # observability for retry-boundary tests

    def invalidate_cache(self) -> None:
        """QUERY-retry boundary: drop every parsed/filtered/metadata
        listing so the replay re-reads the files (the mtime stamp
        already catches rewrites, but a stale-cache failure mode —
        e.g. a file deleted underneath a cached parse — needs the hard
        flush)."""
        self.invalidations += 1
        self.store._cache.clear()
        self.store._filtered_cache.clear()
        self.store._meta_cache.clear()

    def get_splits(self, handle: TableHandle, target_split_count: int) -> List[Split]:
        cs = getattr(handle, "constraints", ())
        parsed = (
            self.store.parsed_filtered(handle.schema, handle.table, cs)
            if cs
            else self.store.parsed(handle.schema, handle.table)
        )
        n = parsed.row_count
        k = max(1, min(target_split_count, max(n, 1)))
        per = -(-max(n, 1) // k)
        return [
            Split(handle, s, (a, min(a + per, n)))
            for s, a in enumerate(range(0, max(n, 1), per))
        ]


class FilePageSource(ConnectorPageSource):
    def __init__(self, store: _FileStore):
        self.store = store

    def batches(
        self, split: Split, columns: Sequence[str], batch_rows: int,
        stabilizer=None,
    ) -> Iterator[RelBatch]:
        cs = getattr(split.table, "constraints", ())
        t = (
            self.store.parsed_filtered(
                split.table.schema, split.table.table, cs
            )
            if cs
            else self.store.parsed(split.table.schema, split.table.table)
        )
        lo, hi = split.row_range
        types = {c.name: c.type for c in t.columns}
        for a in range(lo, hi, batch_rows):
            b = min(a + batch_rows, hi)
            n = b - a
            # chunks span the (pre-filtered) table contiguously, so the
            # span equals the chunk length; the stabilizer only snaps it
            # onto the session's capacity ladder
            cap = (stabilizer.chunk_capacity(n) if stabilizer is not None
                   else bucket_capacity(n))
            cols = []
            for name in columns:
                typ = types[name]
                arr = np.zeros(cap, dtype=typ.dtype)
                arr[:n] = t.data[name][a:b]
                v = None
                if t.valid[name] is not None:
                    vm = np.zeros(cap, dtype=bool)
                    vm[:n] = t.valid[name][a:b]
                    v = jnp.asarray(vm)
                cols.append(
                    Column(typ, jnp.asarray(arr), v, t.dictionaries[name])
                )
            live = None
            if n != cap:
                lv = np.zeros(cap, dtype=bool)
                lv[:n] = True
                live = jnp.asarray(lv)
            yield RelBatch(cols, live)
        if hi == lo:
            yield RelBatch(
                [
                    Column(
                        types[name],
                        jnp.zeros(16, dtype=types[name].dtype),
                        None,
                        t.dictionaries[name],
                    )
                    for name in columns
                ],
                jnp.zeros(16, dtype=jnp.bool_),
            )


class FilePageSink(ConnectorPageSink):
    """Each write lands a new part file (hive's write-then-rename
    discipline: parts are written under a dotted temp name and renamed
    into place at finish, so readers never see partial parts)."""

    def __init__(self, store: _FileStore, handle: TableHandle):
        self.store = store
        self.handle = handle
        self.rows = 0
        d = os.path.join(store.root, handle.schema, handle.table)
        if os.path.isfile(d + ".csv") or os.path.isfile(d + ".jsonl"):
            raise ValueError(
                "single-file tables are read-only; CREATE the table to "
                "get a multi-part directory"
            )
        os.makedirs(d, exist_ok=True)
        # unique part names: concurrent INSERTs must never collide on a
        # count-derived index (hive's UUID-suffixed write files)
        import uuid

        part = uuid.uuid4().hex[:12]
        self._final = os.path.join(d, f"part-{part}.csv")
        self._tmp = os.path.join(d, f".part-{part}.csv.tmp")
        self._file = open(self._tmp, "w", newline="")
        self._writer = csv.writer(self._file)
        parsed = self.store.parsed(handle.schema, handle.table)
        self._columns = parsed.columns
        self._writer.writerow([c.name for c in self._columns])

    def append(self, batch: RelBatch) -> None:
        import jax

        live = np.asarray(jax.device_get(batch.live_mask()))
        host_cols = []
        for cm, col in zip(self._columns, batch.columns):
            data = np.asarray(jax.device_get(col.data))[live]
            valid = (
                np.asarray(jax.device_get(col.valid))[live]
                if col.valid is not None
                else None
            )
            host_cols.append((cm, data, valid, col.dictionary))
        n = int(live.sum())
        for r in range(n):
            row = []
            for cm, data, valid, d in host_cols:
                if valid is not None and not valid[r]:
                    row.append("")
                elif cm.type.is_string:
                    row.append(d.values[int(data[r])] if d else "")
                elif cm.type.kind == T.TypeKind.DATE:
                    row.append(
                        (_EPOCH + datetime.timedelta(days=int(data[r])))
                        .isoformat()
                    )
                elif cm.type.kind == T.TypeKind.BOOLEAN:
                    row.append("true" if data[r] else "false")
                else:
                    row.append(data[r])
            self._writer.writerow(row)
        self.rows += n

    def finish(self) -> int:
        self._file.close()
        os.replace(self._tmp, self._final)
        return self.rows


class ParquetPageSink(ConnectorPageSink):
    """Columnar write path: batches buffer host-side and land as ONE
    parquet part at finish (write-then-rename, like the CSV sink)."""

    def __init__(self, store: _FileStore, handle: TableHandle):
        import uuid

        self.store = store
        self.handle = handle
        self.rows = 0
        d = os.path.join(store.root, handle.schema, handle.table)
        for ext in (".parquet", ".csv", ".jsonl"):
            if os.path.isfile(d + ext):
                raise ValueError(
                    "single-file tables are read-only; CREATE the table"
                    " to get a multi-part directory"
                )
        os.makedirs(d, exist_ok=True)
        part = uuid.uuid4().hex[:12]
        self._final = os.path.join(d, f"part-{part}.parquet")
        self._tmp = os.path.join(d, f".part-{part}.parquet.tmp")
        parsed = store.parsed(handle.schema, handle.table)
        self._columns = parsed.columns
        self._bufs = [([], []) for _ in self._columns]  # (data, valid)
        self._dicts = [None] * len(self._columns)

    def append(self, batch: RelBatch) -> None:
        import jax

        live = np.asarray(jax.device_get(batch.live_mask()))
        for i, (cm, col) in enumerate(zip(self._columns, batch.columns)):
            data = np.asarray(jax.device_get(col.data))[live]
            valid = (
                np.asarray(jax.device_get(col.valid))[live]
                if col.valid is not None
                else np.ones(len(data), bool)
            )
            if cm.type.is_string:
                # decode now: dictionaries differ per batch; a missing
                # dictionary (NULL-only projections, outer-join padding)
                # decodes as empty strings under an all-false mask
                d = col.dictionary
                data = [
                    d.values[int(v)] if ok and d else ""
                    for v, ok in zip(data, valid)
                ]
            self._bufs[i][0].append(data)
            self._bufs[i][1].append(valid)
        self.rows += int(live.sum())

    def finish(self) -> int:
        from trino_tpu.connectors import parquet_format as PQ

        cols = []
        for cm, (datas, valids) in zip(self._columns, self._bufs):
            if cm.type.is_string:
                flat = [v for part in datas for v in part]
                valid = np.concatenate(valids) if valids else np.zeros(0, bool)
                vals = [s.encode("utf-8") for s in flat]
                cols.append(PQ.ParquetColumn(
                    cm.name, PQ.T_BYTE_ARRAY, PQ.C_UTF8,
                    values=vals,
                    valid=None if valid.all() else valid,
                ))
                continue
            data = (
                np.concatenate(datas) if datas
                else np.zeros(0, dtype=cm.type.dtype)
            )
            valid = np.concatenate(valids) if valids else np.zeros(0, bool)
            cols.append(_to_parquet_column(
                cm, data, None if valid.all() else valid, None
            ))
        # gzip (C-speed zlib) + dictionary pages by default; SNAPPY/
        # ZSTD are read+write capable (parquet_format) but the pure-
        # python snappy encoder would tax every CTAS on this host.
        # 64k-row groups give min/max pruning real skip granularity
        PQ.write_parquet(
            self._tmp, cols, self.rows, codec="gzip",
            row_group_rows=1 << 16,
        )
        os.replace(self._tmp, self._final)
        return self.rows


class FileConnector(Connector):
    """`file_format` chooses the WRITE format for CREATE/INSERT parts
    ("csv" default, "parquet" for the columnar path); reads always
    dispatch by extension."""

    def __init__(self, root: str, file_format: str = "csv"):
        store = _FileStore(root)
        super().__init__(
            "file",
            FileMetadata(store, file_format),
            FileSplitManager(store),
            FilePageSource(store),
        )
        self.store = store
        self.file_format = file_format

    def page_sink(self, handle: TableHandle, transaction=None) -> ConnectorPageSink:
        # the TABLE's existing parts decide the write format — an INSERT
        # must never land a mismatched part next to them (which would
        # fail every subsequent read); the connector's configured format
        # only applies to freshly created tables
        paths = self.store.table_paths(handle.schema, handle.table)
        if paths:
            fmt = "parquet" if paths[0].endswith(".parquet") else "csv"
        else:
            fmt = self.file_format
        if fmt == "parquet":
            return ParquetPageSink(self.store, handle)
        return FilePageSink(self.store, handle)


def create_file_connector(root: str, file_format: str = "csv") -> Connector:
    """plugin entry point (Plugin.getConnectorFactories analogue)."""
    return FileConnector(root, file_format)
