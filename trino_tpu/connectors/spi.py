"""Connector SPI.

The plugin boundary between the engine and data sources — the analogue
of spi/connector/: ConnectorMetadata (spi/connector/ConnectorMetadata.java:64),
ConnectorSplitManager, ConnectorPageSourceProvider
(spi/connector/ConnectorPageSource.java:24), ConnectorPageSinkProvider,
and the Plugin registration surface (spi/Plugin.java:35), reduced to the
capability set the engine consumes. TPU-first deltas from the reference:

- Page sources yield ``RelBatch`` (device-ready SoA) instead of
  Page/Block, and declare *table-stable dictionaries* per string column
  so expression binding happens once per pipeline (see expr/compile.py).
- Splits carry explicit row ranges; a split is the unit of source
  parallelism (SOURCE_DISTRIBUTION — SystemPartitioningHandle.java:55)
  and of retry in FTE mode.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from trino_tpu import types as T
from trino_tpu.block import Dictionary, RelBatch


@dataclasses.dataclass(frozen=True)
class ColumnMetadata:
    name: str
    type: T.DataType


@dataclasses.dataclass(frozen=True)
class TableMetadata:
    schema: str
    name: str
    columns: Tuple[ColumnMetadata, ...]

    def column_index(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(name)


@dataclasses.dataclass(frozen=True)
class ColumnConstraint:
    """One pushed-down per-column predicate — the scalar reduction of
    spi/predicate/TupleDomain: `column op value` with op in
    {lt, le, gt, ge, eq, ne}. `value` is a python scalar in the
    column's PHYSICAL value space (epoch days for DATE, scaled ints for
    DECIMAL), matching what the connector's page source materializes."""

    column: str
    op: str
    value: Any


@dataclasses.dataclass(frozen=True)
class TableHandle:
    """Engine-side opaque reference to a connector table."""

    catalog: str
    schema: str
    table: str
    # connector-private payload (e.g. tpch scale factor)
    payload: Any = None
    # constraints the connector has ACCEPTED via apply_filter — every
    # row its page source emits for this handle satisfies all of them
    constraints: Tuple[ColumnConstraint, ...] = ()


@dataclasses.dataclass(frozen=True)
class Split:
    """A retryable unit of scan work (spi/connector/ConnectorSplit.java).
    `row_range` is [start, end) within the table for generator/memory
    connectors; `payload` is connector-private."""

    table: TableHandle
    seq: int
    row_range: Optional[Tuple[int, int]] = None
    payload: Any = None


@dataclasses.dataclass
class TableStatistics:
    """CBO inputs (spi/statistics/TableStatistics.java)."""

    row_count: Optional[float] = None
    # per-column: distinct count, null fraction, min, max
    columns: Dict[str, Tuple[Optional[float], Optional[float], Any, Any]] = dataclasses.field(
        default_factory=dict
    )


class ConnectorMetadata:
    """Per-connector metadata surface (ConnectorMetadata.java:64)."""

    def list_schemas(self) -> List[str]:
        raise NotImplementedError

    def list_tables(self, schema: str) -> List[str]:
        raise NotImplementedError

    def get_table_handle(self, schema: str, table: str) -> Optional[TableHandle]:
        raise NotImplementedError

    def get_table_metadata(self, handle: TableHandle) -> TableMetadata:
        raise NotImplementedError

    def column_dictionary(self, handle: TableHandle, column: str) -> Optional[Dictionary]:
        """Table-stable dictionary for a string column (None for
        non-string). Called at plan time so binding can be pipeline-wide."""
        return None

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        return TableStatistics()

    def apply_filter(
        self, handle: TableHandle, constraints: Sequence[ColumnConstraint]
    ) -> Optional[Tuple[TableHandle, Tuple[ColumnConstraint, ...]]]:
        """PushPredicateIntoTableScan seat (the reference's
        ConnectorMetadata.applyFilter, ConnectorMetadata.java:1290):
        offered the scan-pushable conjuncts of a filter above this
        table's scan. Return None when nothing can be pushed, or
        ``(new_handle, residual)`` where ``new_handle`` carries the
        accepted constraints (by convention in
        ``TableHandle.constraints``) and ``residual`` lists the OFFERED
        constraints this connector will not fully enforce — the engine
        keeps their conjuncts in a FilterNode above the scan.

        Enforcement contract: the page source must emit NO row that
        violates an accepted constraint (full enforcement; connectors
        that can only prune coarsely, e.g. by row group, must re-filter
        exactly or leave the constraint in ``residual``)."""
        return None

    def apply_projection(
        self, handle: TableHandle, columns: Sequence[str]
    ) -> Optional[TableHandle]:
        """PushProjectionIntoTableScan seat: asked to narrow the scan to
        `columns` (a subset of the table's columns, in scan order).
        Return a handle whose page source materializes ONLY those
        columns (sources that already honor the per-call ``columns``
        projection may return ``handle`` unchanged), or None when
        unsupported — the engine then keeps the wide scan."""
        return None

    def table_partitioning(self, handle: TableHandle) -> Optional[Tuple[str, ...]]:
        """Declared bucketing of a table: the ordered key columns whose
        engine-hash buckets the connector's splits are 1:1 with (split i
        holds exactly the rows where partition_of(hash32(keys), n)==i),
        or None when splits are arbitrary row ranges. The planner uses
        this to cancel repartition exchanges over co-bucketed tables —
        the ConnectorTablePartitioning / NodePartitioningManager.java:96
        seat (TpchNodePartitioningProvider.java:46 declares the same for
        the reference's tpch connector). A connector must only declare
        this if its split manager honors ANY requested split count with
        engine-hash buckets (ops/hashing.hash32_np is the lock-step
        host-side bucket function)."""
        return None

    # -- writes (optional capability) --
    def create_table(self, schema: str, table: str, columns: Sequence[ColumnMetadata]) -> TableHandle:
        raise NotImplementedError(f"{type(self).__name__} does not support CREATE TABLE")

    def drop_table(self, handle: TableHandle) -> None:
        raise NotImplementedError(f"{type(self).__name__} does not support DROP TABLE")

    def truncate_table(self, handle: TableHandle) -> None:
        """Remove all rows, keeping the table (DELETE/UPDATE rewrite
        support; the reference's ConnectorMetadata.executeDelete
        whole-table path)."""
        raise NotImplementedError(f"{type(self).__name__} does not support DELETE")


class ConnectorSplitManager:
    def get_splits(self, handle: TableHandle, target_split_count: int) -> List[Split]:
        raise NotImplementedError

    def invalidate_cache(self) -> None:
        """Drop any cached split listings. Called between whole-query
        retry attempts (CachingHiveMetastore flush on retry): the first
        attempt may have failed BECAUSE a cached listing went stale
        under it (files compacted/deleted), so the replay must re-list.
        Default: stateless split managers have nothing to drop."""


class ConnectorPageSource:
    """Produces batches for one split (ConnectorPageSource.java:24).
    `columns` is the pruned projection (channel names).

    `stabilizer` (compile.shapes.ShapeStabilizer, optional) is the
    session's capacity-class policy: when given, a source should pad
    each chunk to `stabilizer.chunk_capacity(span)` of its pre-pruning
    span so pushdown/dynamic-filter pruning lands on the same XLA
    lowering class as the unpruned scan. Sources that ignore the kwarg
    (older/external connectors) keep working — TableScanOperator falls
    back to the 3-argument call on TypeError."""

    def batches(self, split: Split, columns: Sequence[str], batch_rows: int,
                stabilizer=None) -> Iterator[RelBatch]:
        raise NotImplementedError


class ConnectorPageSink:
    """Accepts batches for a write (ConnectorPageSinkProvider analogue)."""

    def append(self, batch: RelBatch) -> None:
        raise NotImplementedError

    def finish(self) -> int:
        """Commit; returns row count written."""
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class TableFunction:
    """A connector-provided polymorphic table function
    (spi/ptf/ConnectorTableFunction.java analogue, reduced to the
    scalar-argument form: `fn(args) -> (columns, rows)` evaluated at
    plan time; table-valued arguments are handled engine-side for the
    built-ins, see sql/analyzer.py)."""

    name: str
    # fn(args: dict[str, value]) -> (List[ColumnMetadata], List[List])
    fn: Any
    description: str = ""


class Connector:
    """One catalog's capability bundle (spi/connector/Connector.java)."""

    def __init__(
        self,
        name: str,
        metadata: ConnectorMetadata,
        split_manager: Optional[ConnectorSplitManager] = None,
        page_source: Optional[ConnectorPageSource] = None,
        table_functions: Optional[Dict[str, "TableFunction"]] = None,
    ):
        self.name = name
        self.metadata = metadata
        self.split_manager = split_manager
        self.page_source = page_source
        self.table_functions = table_functions or {}

    def page_sink(self, handle: TableHandle, transaction=None) -> ConnectorPageSink:
        """`transaction` is this connector's ConnectorTransactionHandle
        (trino_tpu.transaction) when the write runs inside an explicit
        transaction; connectors that support transactional writes buffer
        until its commit. Autocommit (None) publishes at finish()."""
        raise NotImplementedError(f"connector {self.name} does not support writes")

    def begin_transaction(self, read_only: bool = False):
        """Optional: return a connector transaction handle
        (spi/transaction/ConnectorTransactionHandle analogue). Default
        is autocommit semantics."""
        from trino_tpu.transaction import ConnectorTransactionHandle

        return ConnectorTransactionHandle()

    def invalidate_split_caches(self) -> None:
        """Flush this catalog's split-listing caches (whole-query retry
        boundary — see ConnectorSplitManager.invalidate_cache)."""
        if self.split_manager is not None:
            self.split_manager.invalidate_cache()


class CatalogManager:
    """Engine-wide catalog registry — MetadataManager/CatalogManager
    analogue (main/metadata/MetadataManager.java)."""

    def __init__(self):
        self._catalogs: Dict[str, Connector] = {}

    def register(self, catalog: str, connector: Connector) -> None:
        self._catalogs[catalog] = connector

    def get(self, catalog: str) -> Connector:
        if catalog not in self._catalogs:
            raise KeyError(f"catalog '{catalog}' not registered")
        return self._catalogs[catalog]

    def catalogs(self) -> List[str]:
        return sorted(self._catalogs)

    def resolve_table(self, catalog: str, schema: str, table: str) -> Tuple[Connector, TableHandle]:
        conn = self.get(catalog)
        handle = conn.metadata.get_table_handle(schema, table)
        if handle is None:
            raise KeyError(f"table '{catalog}.{schema}.{table}' does not exist")
        return conn, handle

    def invalidate_split_listings(self) -> None:
        """Flush split-listing caches across every catalog. The QUERY
        retry loop calls this between attempts so a replay re-lists
        splits instead of replaying the stale listing that may have
        failed the first attempt. Connector errors are swallowed — a
        broken cache flush must not mask the original query failure."""
        for conn in self._catalogs.values():
            try:
                conn.invalidate_split_caches()
            except Exception:
                pass
