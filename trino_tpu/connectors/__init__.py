"""Connector layer: the plugin SPI plus built-in connectors.

Analogue of trino-spi's connector surface (spi/connector/ ~100
interfaces, spi/Plugin.java:35 — SURVEY.md §2.12) with the essential
built-ins: tpch (plugin/trino-tpch), memory (plugin/trino-memory),
blackhole (plugin/trino-blackhole).
"""

from trino_tpu.connectors.spi import (  # noqa: F401
    CatalogManager,
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSource,
    ConnectorSplitManager,
    Split,
    TableHandle,
    TableMetadata,
)
