"""Shared predicate-pushdown machinery for connectors.

The engine side of the apply_filter/apply_projection SPI contract
(spi.ConnectorMetadata): classification of filter conjuncts into
per-column ``ColumnConstraint``s (the TupleDomain extraction seat,
main/sql/planner/iterative/rule/PushPredicateIntoTableScan.java:141),
plus the numpy evaluation helpers every host-side connector uses to
ENFORCE accepted constraints exactly (the SPI contract requires full
enforcement — row-group pruning alone is not enough).

Constraint value space is the column's PHYSICAL representation (epoch
days for DATE, scaled int64 for short DECIMAL), which is exactly the
space the analyzer's comparison literals live in — classification
requires the literal's IR type to EQUAL the column type, so no scale
or unit conversion can hide here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.connectors.spi import ColumnConstraint, TableHandle
from trino_tpu.expr import ir

# op -> its mirror when the comparison is written literal-first
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}

_NP_OPS: Dict[str, Callable] = {
    "lt": np.less,
    "le": np.less_equal,
    "gt": np.greater,
    "ge": np.greater_equal,
    "eq": np.equal,
    "ne": np.not_equal,
}


def _pushable_type(t: T.DataType) -> bool:
    """Single-lane numeric/temporal columns only: strings compare via
    dictionaries, long decimals span two lanes, tstz packs a zone the
    raw int64 compare would include."""
    return not (
        t.is_string
        or t.is_nested
        or t.lanes != 1
        or t.kind == T.TypeKind.TIMESTAMP_TZ
    )


def _literal_value(t: T.DataType, b: ir.Literal) -> Optional[Any]:
    """Literal -> the column's RAW value space, or None when pushing it
    would round. Decimal columns store scale-multiplied int64: rescale
    exact literals, refuse anything lossy. NULL never classifies (a
    NULL comparison never matches; the filter keeps it)."""
    if b.value is None:
        return None
    if t.is_decimal:
        s = t.scale or 0
        if b.type.is_decimal and (b.type.scale or 0) <= s:
            return int(round(b.value * (10 ** s)))
        if b.type.is_integerlike and not isinstance(b.value, bool):
            return int(b.value) * (10 ** s)
        return None
    if not isinstance(b.value, (bool, int, float)):
        return None
    return b.value


def _classify_comparison(e, columns, fields) -> Optional[ColumnConstraint]:
    """``col op literal`` (either operand order) over a pushable column
    -> ColumnConstraint, else None."""
    if not isinstance(e, ir.Call) or len(e.args) != 2:
        return None
    op = _FLIP.get(e.name)
    if op is None:
        return None
    a, b = e.args
    if isinstance(a, ir.Literal) and isinstance(b, ir.InputRef):
        a, b, op = b, a, op
    else:
        op = e.name
    if not (isinstance(a, ir.InputRef) and isinstance(b, ir.Literal)):
        return None
    t = fields[a.index].type
    if not _pushable_type(t):
        return None
    value = _literal_value(t, b)
    if value is None:
        return None
    return ColumnConstraint(columns[a.index], op, value)


def _classify_in_list(e, columns, fields) -> Optional[ColumnConstraint]:
    """``col IN (lit, ...)`` -> op="in" with a sorted value tuple (the
    handle participates in plan-cache keys, so the representation must
    be canonical). Every option must rescale exactly; one lossy or NULL
    option keeps the whole predicate in the filter."""
    if not isinstance(e.value, ir.InputRef) or not e.options:
        return None
    t = fields[e.value.index].type
    if not _pushable_type(t):
        return None
    vals = []
    for opt in e.options:
        if not isinstance(opt, ir.Literal):
            return None
        v = _literal_value(t, opt)
        if v is None:
            return None
        vals.append(v)
    return ColumnConstraint(
        columns[e.value.index], "in", tuple(sorted(set(vals)))
    )


def _flatten_or(e) -> List:
    if isinstance(e, ir.Call) and e.name == "or":
        out: List = []
        for a in e.args:
            out.extend(_flatten_or(a))
        return out
    return [e]


def _classify_or(e, columns, fields) -> Optional[ColumnConstraint]:
    """OR tree whose every disjunct classifies against the SAME column
    -> op="or" with a tuple of (atomic op, value) pairs — the
    TupleDomain multi-range seat. IN-list disjuncts expand to eq pairs.
    Any disjunct touching another column (or not classifying at all)
    keeps the whole tree in the filter: pushing a weakened OR would be
    wrong under the exact-enforcement contract."""
    disjuncts: List[Tuple[str, Any]] = []
    column: Optional[str] = None
    for leaf in _flatten_or(e):
        c = (
            _classify_in_list(leaf, columns, fields)
            if isinstance(leaf, ir.InList)
            else _classify_comparison(leaf, columns, fields)
        )
        if c is None:
            return None
        if column is None:
            column = c.column
        elif c.column != column:
            return None
        if c.op == "in":
            disjuncts.extend(("eq", v) for v in c.value)
        else:
            disjuncts.append((c.op, c.value))
    if column is None or len(disjuncts) < 2:
        return None
    return ColumnConstraint(column, "or", tuple(disjuncts))


def classify_conjunct(e, columns, fields) -> Optional[ColumnConstraint]:
    """One filter conjunct -> ColumnConstraint, else None. Handles
    ``col op literal`` (either operand order), ``col IN (literals)``
    (op="in", value = sorted scalar tuple), and single-column OR trees
    (op="or", value = tuple of (op, value) atomic pairs). InputRefs
    index the SCAN's output channels, so ``columns[ref.index]`` is the
    connector column name."""
    if isinstance(e, ir.InList):
        return _classify_in_list(e, columns, fields)
    if isinstance(e, ir.Call) and e.name == "or":
        return _classify_or(e, columns, fields)
    return _classify_comparison(e, columns, fields)


def split_supported(
    constraints: Sequence[ColumnConstraint],
    type_of: Callable[[str], Optional[T.DataType]],
) -> Tuple[List[ColumnConstraint], List[ColumnConstraint]]:
    """(accepted, residual) under the shared host-side enforcement: a
    constraint is accepted iff its column exists and is pushable."""
    accepted: List[ColumnConstraint] = []
    residual: List[ColumnConstraint] = []
    for c in constraints:
        t = type_of(c.column)
        if (
            t is not None
            and _pushable_type(t)
            and (c.op in _NP_OPS or c.op in ("in", "or"))
        ):
            accepted.append(c)
        else:
            residual.append(c)
    return accepted, residual


def merge_handle_constraints(
    handle: TableHandle, accepted: Sequence[ColumnConstraint]
) -> TableHandle:
    """New handle with `accepted` folded into handle.constraints
    (deduplicated, original order preserved — the handle participates
    in plan-cache keys, so the representation must be canonical)."""
    merged = list(handle.constraints)
    for c in accepted:
        if c not in merged:
            merged.append(c)
    return dataclasses.replace(handle, constraints=tuple(merged))


def constraint_mask(
    constraints: Sequence[ColumnConstraint],
    column_data: Callable[[str], Tuple[np.ndarray, Optional[np.ndarray]]],
) -> Optional[np.ndarray]:
    """AND of all constraints over host arrays -> bool mask (None when
    no constraints). ``column_data(name)`` returns (data, valid-or-None);
    NULL rows never satisfy a comparison (SQL three-valued logic)."""
    mask: Optional[np.ndarray] = None
    for c in constraints:
        data, valid = column_data(c.column)
        arr = np.asarray(data)
        if c.op == "in":
            m = np.isin(arr, np.asarray(c.value))
        elif c.op == "or":
            m = np.zeros(arr.shape, dtype=bool)
            for op, v in c.value:
                m = m | _NP_OPS[op](arr, v)
        else:
            m = _NP_OPS[c.op](arr, c.value)
        if valid is not None:
            m = m & np.asarray(valid, dtype=bool)
        mask = m if mask is None else (mask & m)
    return mask


def range_predicate(
    constraints: Sequence[ColumnConstraint],
) -> Dict[str, Tuple[Optional[Any], Optional[Any]]]:
    """Constraints -> closed per-column [lo, hi] ranges for min/max
    pruning (parquet row-group stats). Conservative: gt/lt keep the
    bound closed (a group equal to the bound still reads and the exact
    mask drops it); ne prunes nothing. Multi-range constraints
    contribute the UNION of their disjuncts' bounds — an "or" only
    bounds a side when every disjunct bounds that side."""
    out: Dict[str, Tuple[Optional[Any], Optional[Any]]] = {}
    for c in constraints:
        bounds = _constraint_bounds(c)
        if bounds is None:
            continue
        clo, chi = bounds
        lo, hi = out.get(c.column, (None, None))
        if clo is not None:
            lo = clo if lo is None else max(lo, clo)
        if chi is not None:
            hi = chi if hi is None else min(hi, chi)
        out[c.column] = (lo, hi)
    return out


def _constraint_bounds(
    c: ColumnConstraint,
) -> Optional[Tuple[Optional[Any], Optional[Any]]]:
    """One constraint's own [lo, hi] contribution (None = no
    contribution at all, e.g. ne)."""
    if c.op in ("gt", "ge"):
        return (c.value, None)
    if c.op in ("lt", "le"):
        return (None, c.value)
    if c.op == "eq":
        return (c.value, c.value)
    if c.op == "in":
        return (min(c.value), max(c.value)) if c.value else None
    if c.op == "or":
        los, his = [], []
        for op, v in c.value:
            b = _constraint_bounds(ColumnConstraint(c.column, op, v))
            if b is None:
                return None  # a ne disjunct admits everything
            los.append(b[0])
            his.append(b[1])
        lo = min(los) if all(x is not None for x in los) else None
        hi = max(his) if all(x is not None for x in his) else None
        if lo is None and hi is None:
            return None
        return (lo, hi)
    return None
