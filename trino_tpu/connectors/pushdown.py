"""Shared predicate-pushdown machinery for connectors.

The engine side of the apply_filter/apply_projection SPI contract
(spi.ConnectorMetadata): classification of filter conjuncts into
per-column ``ColumnConstraint``s (the TupleDomain extraction seat,
main/sql/planner/iterative/rule/PushPredicateIntoTableScan.java:141),
plus the numpy evaluation helpers every host-side connector uses to
ENFORCE accepted constraints exactly (the SPI contract requires full
enforcement — row-group pruning alone is not enough).

Constraint value space is the column's PHYSICAL representation (epoch
days for DATE, scaled int64 for short DECIMAL), which is exactly the
space the analyzer's comparison literals live in — classification
requires the literal's IR type to EQUAL the column type, so no scale
or unit conversion can hide here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.connectors.spi import ColumnConstraint, TableHandle
from trino_tpu.expr import ir

# op -> its mirror when the comparison is written literal-first
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}

_NP_OPS: Dict[str, Callable] = {
    "lt": np.less,
    "le": np.less_equal,
    "gt": np.greater,
    "ge": np.greater_equal,
    "eq": np.equal,
    "ne": np.not_equal,
}


def _pushable_type(t: T.DataType) -> bool:
    """Single-lane numeric/temporal columns only: strings compare via
    dictionaries, long decimals span two lanes, tstz packs a zone the
    raw int64 compare would include."""
    return not (
        t.is_string
        or t.is_nested
        or t.lanes != 1
        or t.kind == T.TypeKind.TIMESTAMP_TZ
    )


def classify_conjunct(e, columns, fields) -> Optional[ColumnConstraint]:
    """``col op literal`` (either operand order) over a pushable column
    -> ColumnConstraint, else None. InputRefs index the SCAN's output
    channels, so ``columns[ref.index]`` is the connector column name."""
    if not isinstance(e, ir.Call) or len(e.args) != 2:
        return None
    op = _FLIP.get(e.name)
    if op is None:
        return None
    a, b = e.args
    if isinstance(a, ir.Literal) and isinstance(b, ir.InputRef):
        a, b, op = b, a, op
    else:
        op = e.name
    if not (isinstance(a, ir.InputRef) and isinstance(b, ir.Literal)):
        return None
    if b.value is None:  # NULL comparisons never match; leave to filter
        return None
    t = fields[a.index].type
    if not _pushable_type(t):
        return None
    # the constraint value must live in the column's RAW value space
    # (decimal columns store scale-multiplied int64): rescale exact
    # literals, refuse anything that would round
    if t.is_decimal:
        s = t.scale or 0
        if b.type.is_decimal and (b.type.scale or 0) <= s:
            return ColumnConstraint(
                columns[a.index], op, int(round(b.value * (10 ** s)))
            )
        if b.type.is_integerlike and not isinstance(b.value, bool):
            return ColumnConstraint(
                columns[a.index], op, int(b.value) * (10 ** s)
            )
        return None
    if not isinstance(b.value, (bool, int, float)):
        return None
    return ColumnConstraint(columns[a.index], op, b.value)


def split_supported(
    constraints: Sequence[ColumnConstraint],
    type_of: Callable[[str], Optional[T.DataType]],
) -> Tuple[List[ColumnConstraint], List[ColumnConstraint]]:
    """(accepted, residual) under the shared host-side enforcement: a
    constraint is accepted iff its column exists and is pushable."""
    accepted: List[ColumnConstraint] = []
    residual: List[ColumnConstraint] = []
    for c in constraints:
        t = type_of(c.column)
        if t is not None and _pushable_type(t) and c.op in _NP_OPS:
            accepted.append(c)
        else:
            residual.append(c)
    return accepted, residual


def merge_handle_constraints(
    handle: TableHandle, accepted: Sequence[ColumnConstraint]
) -> TableHandle:
    """New handle with `accepted` folded into handle.constraints
    (deduplicated, original order preserved — the handle participates
    in plan-cache keys, so the representation must be canonical)."""
    merged = list(handle.constraints)
    for c in accepted:
        if c not in merged:
            merged.append(c)
    return dataclasses.replace(handle, constraints=tuple(merged))


def constraint_mask(
    constraints: Sequence[ColumnConstraint],
    column_data: Callable[[str], Tuple[np.ndarray, Optional[np.ndarray]]],
) -> Optional[np.ndarray]:
    """AND of all constraints over host arrays -> bool mask (None when
    no constraints). ``column_data(name)`` returns (data, valid-or-None);
    NULL rows never satisfy a comparison (SQL three-valued logic)."""
    mask: Optional[np.ndarray] = None
    for c in constraints:
        data, valid = column_data(c.column)
        m = _NP_OPS[c.op](np.asarray(data), c.value)
        if valid is not None:
            m = m & np.asarray(valid, dtype=bool)
        mask = m if mask is None else (mask & m)
    return mask


def range_predicate(
    constraints: Sequence[ColumnConstraint],
) -> Dict[str, Tuple[Optional[Any], Optional[Any]]]:
    """Constraints -> closed per-column [lo, hi] ranges for min/max
    pruning (parquet row-group stats). Conservative: gt/lt keep the
    bound closed (a group equal to the bound still reads and the exact
    mask drops it); ne prunes nothing."""
    out: Dict[str, Tuple[Optional[Any], Optional[Any]]] = {}
    for c in constraints:
        lo, hi = out.get(c.column, (None, None))
        if c.op in ("gt", "ge", "eq"):
            lo = c.value if lo is None else max(lo, c.value)
        if c.op in ("lt", "le", "eq"):
            hi = c.value if hi is None else min(hi, c.value)
        if c.op in ("gt", "ge", "eq", "lt", "le"):
            out[c.column] = (lo, hi)
    return out
