"""Minimal Parquet reader/writer (pure python, no external deps).

Analogue of lib/trino-parquet (28.1k LoC in the reference): the subset
the engine's types need — PLAIN encoding, UNCOMPRESSED pages, data page
v1, optional fields via RLE/bit-packed definition levels, and the
Thrift Compact Protocol for the footer metadata. Physical/logical
types covered:

  BOOLEAN              <- boolean
  INT32 (+DATE)        <- integer, date
  INT64 (+DECIMAL/TIMESTAMP_MICROS) <- bigint, decimal(<=18), timestamp
  FLOAT / DOUBLE       <- real, double
  BYTE_ARRAY (+UTF8)   <- varchar

The format follows the parquet-format spec directly (file magic PAR1,
footer = thrift FileMetaData + little-endian length + PAR1; each column
chunk = one v1 data page). The reader skips unknown thrift fields, so
files written by other engines with extra metadata (statistics, CRCs,
column indexes) still read as long as pages are PLAIN + uncompressed.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"PAR1"

# thrift compact type ids
_CT_STOP = 0
_CT_TRUE = 1
_CT_FALSE = 2
_CT_BYTE = 3
_CT_I16 = 4
_CT_I32 = 5
_CT_I64 = 6
_CT_DOUBLE = 7
_CT_BINARY = 8
_CT_LIST = 9
_CT_SET = 10
_CT_MAP = 11
_CT_STRUCT = 12

# parquet physical types
T_BOOLEAN = 0
T_INT32 = 1
T_INT64 = 2
T_INT96 = 3
T_FLOAT = 4
T_DOUBLE = 5
T_BYTE_ARRAY = 6
T_FIXED = 7

# converted (logical) types
C_UTF8 = 0
C_DECIMAL = 5
C_DATE = 6
C_TIMESTAMP_MICROS = 10


# ---------------------------------------------------------------------------
# thrift compact protocol
# ---------------------------------------------------------------------------


def _uvarint(x: int) -> bytes:
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag(x: int) -> int:
    return (x << 1) ^ (x >> 63)


def _unzigzag(x: int) -> int:
    return (x >> 1) ^ -(x & 1)


class _Writer:
    def __init__(self):
        self.buf = bytearray()
        self._last_fid = [0]

    def _field(self, fid: int, ctype: int) -> None:
        delta = fid - self._last_fid[-1]
        if 0 < delta <= 15:
            self.buf.append((delta << 4) | ctype)
        else:
            self.buf.append(ctype)
            self.buf += _uvarint(_zigzag(fid))
        self._last_fid[-1] = fid

    def i32(self, fid: int, v: int) -> None:
        self._field(fid, _CT_I32)
        self.buf += _uvarint(_zigzag(v))

    def i64(self, fid: int, v: int) -> None:
        self._field(fid, _CT_I64)
        self.buf += _uvarint(_zigzag(v))

    def string(self, fid: int, s: str) -> None:
        self._field(fid, _CT_BINARY)
        b = s.encode("utf-8")
        self.buf += _uvarint(len(b))
        self.buf += b

    def list_begin(self, fid: int, etype: int, n: int) -> None:
        self._field(fid, _CT_LIST)
        if n < 15:
            self.buf.append((n << 4) | etype)
        else:
            self.buf.append(0xF0 | etype)
            self.buf += _uvarint(n)

    def list_i32_elem(self, v: int) -> None:
        self.buf += _uvarint(_zigzag(v))

    def list_string_elem(self, s: str) -> None:
        b = s.encode("utf-8")
        self.buf += _uvarint(len(b))
        self.buf += b

    def struct_begin(self, fid: int) -> None:
        self._field(fid, _CT_STRUCT)
        self._last_fid.append(0)

    def struct_end(self) -> None:
        self.buf.append(_CT_STOP)
        self._last_fid.pop()

    def root_end(self) -> None:
        self.buf.append(_CT_STOP)


class _Reader:
    def __init__(self, data: bytes, pos: int = 0):
        self.d = data
        self.pos = pos

    def _uvarint(self) -> int:
        x = 0
        shift = 0
        while True:
            b = self.d[self.pos]
            self.pos += 1
            x |= (b & 0x7F) << shift
            if not b & 0x80:
                return x
            shift += 7

    def _zig(self) -> int:
        return _unzigzag(self._uvarint())

    def read_struct(self) -> Dict[int, Any]:
        """Generic struct -> {field_id: value}; unknown fields kept
        (values are ints/bytes/lists/dicts)."""
        out: Dict[int, Any] = {}
        last = 0
        while True:
            head = self.d[self.pos]
            self.pos += 1
            if head == _CT_STOP:
                return out
            ctype = head & 0x0F
            delta = head >> 4
            if delta:
                fid = last + delta
            else:
                fid = self._zig()
            last = fid
            out[fid] = self._value(ctype)

    def _value(self, ctype: int):
        if ctype == _CT_TRUE:
            return True
        if ctype == _CT_FALSE:
            return False
        if ctype == _CT_BYTE:
            v = self.d[self.pos]
            self.pos += 1
            return v
        if ctype in (_CT_I16, _CT_I32, _CT_I64):
            return self._zig()
        if ctype == _CT_DOUBLE:
            v = struct.unpack_from("<d", self.d, self.pos)[0]
            self.pos += 8
            return v
        if ctype == _CT_BINARY:
            n = self._uvarint()
            v = self.d[self.pos:self.pos + n]
            self.pos += n
            return v
        if ctype == _CT_LIST or ctype == _CT_SET:
            head = self.d[self.pos]
            self.pos += 1
            etype = head & 0x0F
            n = head >> 4
            if n == 0xF:
                n = self._uvarint()
            return [self._value(etype) for _ in range(n)]
        if ctype == _CT_STRUCT:
            return self.read_struct()
        if ctype == _CT_MAP:
            n = self._uvarint()
            if n == 0:
                return {}
            kv = self.d[self.pos]
            self.pos += 1
            kt, vt = kv >> 4, kv & 0x0F
            return {
                self._value(kt): self._value(vt) for _ in range(n)
            }
        raise ValueError(f"thrift compact type {ctype}")


# ---------------------------------------------------------------------------
# column model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ParquetColumn:
    """One leaf column: name, physical/converted types, values +
    validity (None = all valid)."""

    name: str
    physical: int
    converted: Optional[int] = None
    scale: Optional[int] = None
    precision: Optional[int] = None
    values: Any = None          # np.ndarray, or list[bytes] for BYTE_ARRAY
    valid: Optional[np.ndarray] = None


def _bitpack_levels(valid: np.ndarray) -> bytes:
    """Definition levels (bit width 1) as one BIT_PACKED run of the
    RLE/bit-packed hybrid."""
    n = len(valid)
    groups = (n + 7) // 8
    padded = np.zeros(groups * 8, dtype=bool)
    padded[:n] = valid
    packed = np.packbits(padded, bitorder="little").tobytes()
    return _uvarint((groups << 1) | 1) + packed


def _read_levels(data: bytes, pos: int, n: int) -> Tuple[np.ndarray, int]:
    """RLE/bit-packed hybrid, bit width 1, length-prefixed (v1 pages)."""
    (total_len,) = struct.unpack_from("<I", data, pos)
    pos += 4
    end = pos + total_len
    out = np.zeros(n, dtype=np.uint8)
    i = 0
    r = _Reader(data, pos)
    while i < n and r.pos < end:
        header = r._uvarint()
        if header & 1:  # bit-packed: (groups << 1) | 1
            groups = header >> 1
            cnt = groups * 8
            raw = np.frombuffer(
                r.d[r.pos:r.pos + groups], dtype=np.uint8
            )
            r.pos += groups
            bits = np.unpackbits(raw, bitorder="little")[:cnt]
            take = min(cnt, n - i)
            out[i:i + take] = bits[:take]
            i += take
        else:  # RLE run: (count << 1); value in 1 byte (bit width 1)
            count = header >> 1
            val = r.d[r.pos]
            r.pos += 1
            take = min(count, n - i)
            out[i:i + take] = val & 1
            i += take
    return out.astype(bool), end


def _plain_encode(col: ParquetColumn) -> bytes:
    vals = col.values
    if col.physical == T_BOOLEAN:
        arr = np.asarray(vals, dtype=bool)
        return np.packbits(arr, bitorder="little").tobytes()
    if col.physical == T_INT32:
        return np.asarray(vals, dtype="<i4").tobytes()
    if col.physical == T_INT64:
        return np.asarray(vals, dtype="<i8").tobytes()
    if col.physical == T_FLOAT:
        return np.asarray(vals, dtype="<f4").tobytes()
    if col.physical == T_DOUBLE:
        return np.asarray(vals, dtype="<f8").tobytes()
    if col.physical == T_BYTE_ARRAY:
        out = bytearray()
        for b in vals:
            if isinstance(b, str):
                b = b.encode("utf-8")
            out += struct.pack("<I", len(b))
            out += b
        return bytes(out)
    raise ValueError(f"physical type {col.physical}")


def _plain_decode(physical: int, data: bytes, n: int):
    if physical == T_BOOLEAN:
        bits = np.unpackbits(
            np.frombuffer(data, dtype=np.uint8), bitorder="little"
        )[:n]
        return bits.astype(bool)
    if physical == T_INT32:
        return np.frombuffer(data, dtype="<i4", count=n).copy()
    if physical == T_INT64:
        return np.frombuffer(data, dtype="<i8", count=n).copy()
    if physical == T_FLOAT:
        return np.frombuffer(data, dtype="<f4", count=n).copy()
    if physical == T_DOUBLE:
        return np.frombuffer(data, dtype="<f8", count=n).copy()
    if physical == T_BYTE_ARRAY:
        out = []
        pos = 0
        for _ in range(n):
            (ln,) = struct.unpack_from("<I", data, pos)
            pos += 4
            out.append(data[pos:pos + ln])
            pos += ln
        return out
    raise ValueError(f"physical type {physical}")


# ---------------------------------------------------------------------------
# write
# ---------------------------------------------------------------------------


def write_parquet(path: str, columns: List[ParquetColumn], num_rows: int) -> None:
    body = bytearray(MAGIC)
    chunk_meta = []  # (col, data_page_offset, page_bytes_len, num_values)
    for col in columns:
        offset = len(body)
        # page payload: [def levels if optional] + PLAIN values (non-null)
        payload = bytearray()
        if col.valid is not None:
            levels = _bitpack_levels(np.asarray(col.valid, dtype=bool))
            payload += struct.pack("<I", len(levels))
            payload += levels
            if col.physical == T_BYTE_ARRAY:
                vals = [v for v, ok in zip(col.values, col.valid) if ok]
            else:
                vals = np.asarray(col.values)[np.asarray(col.valid, bool)]
            dense = dataclasses.replace(col, values=vals)
            payload += _plain_encode(dense)
        else:
            payload += _plain_encode(col)
        ph = _Writer()
        ph.i32(1, 0)                    # DATA_PAGE
        ph.i32(2, len(payload))         # uncompressed size
        ph.i32(3, len(payload))         # compressed size (== uncompressed)
        ph.struct_begin(5)              # data_page_header
        ph.i32(1, num_rows)             # num_values (incl. nulls)
        ph.i32(2, 0)                    # PLAIN
        ph.i32(3, 3)                    # def levels: RLE
        ph.i32(4, 3)                    # rep levels: RLE (absent, flat)
        ph.struct_end()
        ph.root_end()
        body += ph.buf
        body += payload
        chunk_meta.append((col, offset, len(ph.buf) + len(payload)))

    # footer
    w = _Writer()
    w.i32(1, 1)  # version
    # schema: root + leaves
    w.list_begin(2, _CT_STRUCT, len(columns) + 1)
    root = _Writer()
    root.string(4, "schema")
    root.i32(5, len(columns))
    root.root_end()
    w.buf += root.buf
    for col in columns:
        se = _Writer()
        se.i32(1, col.physical)
        se.i32(3, 1 if col.valid is not None else 0)  # optional/required
        se.string(4, col.name)
        if col.converted is not None:
            se.i32(6, col.converted)
        if col.scale is not None:
            se.i32(7, col.scale)
        if col.precision is not None:
            se.i32(8, col.precision)
        se.root_end()
        w.buf += se.buf
    w.i64(3, num_rows)
    w.list_begin(4, _CT_STRUCT, 1)  # one row group
    rg = _Writer()
    rg.list_begin(1, _CT_STRUCT, len(columns))
    total = 0
    for col, offset, nbytes in chunk_meta:
        cc = _Writer()
        cc.i64(2, offset)               # file_offset
        cc.struct_begin(3)              # meta_data
        cc.i32(1, col.physical)
        cc.list_begin(2, _CT_I32, 1)
        cc.list_i32_elem(0)             # PLAIN
        cc.list_begin(3, _CT_BINARY, 1)
        cc.list_string_elem(col.name)
        cc.i32(4, 0)                    # UNCOMPRESSED
        cc.i64(5, num_rows)
        cc.i64(6, nbytes)
        cc.i64(7, nbytes)
        cc.i64(9, offset)               # data_page_offset
        cc.struct_end()
        cc.root_end()
        rg.buf += cc.buf
        total += nbytes
    rg.i64(2, total)
    rg.i64(3, num_rows)
    rg.root_end()
    w.buf += rg.buf
    w.string(6, "trino-tpu")
    w.root_end()

    body += w.buf
    body += struct.pack("<I", len(w.buf))
    body += MAGIC
    with open(path, "wb") as f:
        f.write(body)


# ---------------------------------------------------------------------------
# read
# ---------------------------------------------------------------------------


def read_parquet(path: str) -> Tuple[List[ParquetColumn], int]:
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != MAGIC or data[-4:] != MAGIC:
        raise ValueError("not a parquet file")
    (meta_len,) = struct.unpack_from("<I", data, len(data) - 8)
    meta = _Reader(data, len(data) - 8 - meta_len).read_struct()
    schema = meta[2]
    num_rows = meta[3]
    row_groups = meta[4]
    # leaves (skip the root element); nested schemas unsupported
    leaves = []
    for se in schema[1:]:
        if 5 in se and se.get(5, 0) > 0 and 1 not in se:
            raise ValueError("nested parquet schemas not supported")
        leaves.append(se)
    cols: List[ParquetColumn] = [
        ParquetColumn(
            name=se[4].decode("utf-8"),
            physical=se[1],
            converted=se.get(6),
            scale=se.get(7),
            precision=se.get(8),
            valid=None if se.get(3, 0) == 0 else np.zeros(0, bool),
        )
        for se in leaves
    ]
    chunks: List[List[Tuple[np.ndarray, Any]]] = [[] for _ in cols]
    for rg in row_groups:
        for ci, cc in enumerate(rg[1]):
            md = cc[3]
            codec = md.get(4, 0)
            if codec != 0:
                raise ValueError(
                    f"unsupported parquet codec {codec} (UNCOMPRESSED only)"
                )
            pos = md.get(9, cc.get(2))
            n_remaining = md[5]
            while n_remaining > 0:
                r = _Reader(data, pos)
                ph = r.read_struct()
                page_len = ph[3]
                page_start = r.pos
                dph = ph.get(5)
                if dph is None:  # dictionary page etc.: skip
                    pos = page_start + page_len
                    continue
                n_vals = dph[1]
                if dph.get(2, 0) != 0:
                    raise ValueError("unsupported parquet encoding (PLAIN only)")
                if cols[ci].valid is not None:
                    valid, vpos = _read_levels(data, page_start, n_vals)
                    vals = _plain_decode(
                        cols[ci].physical, data[vpos:page_start + page_len],
                        int(valid.sum()),
                    )
                else:
                    valid = None
                    vals = _plain_decode(
                        cols[ci].physical,
                        data[page_start:page_start + page_len], n_vals,
                    )
                chunks[ci].append((valid, vals))
                n_remaining -= n_vals
                pos = page_start + page_len
    for ci, col in enumerate(cols):
        parts = chunks[ci]
        if col.physical == T_BYTE_ARRAY:
            dense: List[bytes] = []
            for _, v in parts:
                dense.extend(v)
        else:
            dense = (
                np.concatenate([v for _, v in parts])
                if parts
                else np.zeros(0)
            )
        if col.valid is not None:
            valid = (
                np.concatenate([v for v, _ in parts])
                if parts
                else np.zeros(0, bool)
            )
            # re-expand to row positions (nulls get placeholder zeros)
            if col.physical == T_BYTE_ARRAY:
                out: List[bytes] = []
                it = iter(dense)
                for ok in valid:
                    out.append(next(it) if ok else b"")
                col.values = out
            else:
                full = np.zeros(len(valid), dtype=dense.dtype)
                full[valid] = dense
                col.values = full
            col.valid = valid
        else:
            col.values = dense
    return cols, num_rows
