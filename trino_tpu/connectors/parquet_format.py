"""Parquet reader/writer (from-scratch, no external parquet deps).

Analogue of lib/trino-parquet (28.1k LoC in the reference), built
directly on the parquet-format spec: Thrift Compact Protocol footers,
v1 data pages with PLAIN and RLE_DICTIONARY encodings, RLE/bit-packed
definition AND repetition levels, per-chunk min/max statistics driving
row-group predicate pruning, and SNAPPY (pure-python, utils/snappy.py)
/ GZIP (RFC-1952 framing) / ZSTD page compression. Nested 3-level
LIST columns (the shape every modern writer emits) read and write with
Dremel-style record assembly; interop is cross-checked against pyarrow
in both directions (tests/test_parquet_interop.py). Physical/logical
types covered:

  BOOLEAN                           <- boolean
  INT32 (+DATE)                     <- integer, date
  INT64 (+DECIMAL/TIMESTAMP_MICROS) <- bigint, decimal(<=18), timestamp
  FLOAT / DOUBLE                    <- real, double
  BYTE_ARRAY (+UTF8)                <- varchar
  3-level LIST of any of the above  <- array(T)

The reader skips unknown thrift fields, so files with extra metadata
(CRCs, column indexes, bloom filters) still read.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from trino_tpu.runtime.metrics import METRICS

MAGIC = b"PAR1"

# thrift compact type ids
_CT_STOP = 0
_CT_TRUE = 1
_CT_FALSE = 2
_CT_BYTE = 3
_CT_I16 = 4
_CT_I32 = 5
_CT_I64 = 6
_CT_DOUBLE = 7
_CT_BINARY = 8
_CT_LIST = 9
_CT_SET = 10
_CT_MAP = 11
_CT_STRUCT = 12

# parquet physical types
T_BOOLEAN = 0
T_INT32 = 1
T_INT64 = 2
T_INT96 = 3
T_FLOAT = 4
T_DOUBLE = 5
T_BYTE_ARRAY = 6
T_FIXED = 7

# converted (logical) types
C_UTF8 = 0
C_DECIMAL = 5
C_DATE = 6
C_TIMESTAMP_MICROS = 10


# ---------------------------------------------------------------------------
# thrift compact protocol
# ---------------------------------------------------------------------------


def _uvarint(x: int) -> bytes:
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag(x: int) -> int:
    return (x << 1) ^ (x >> 63)


def _unzigzag(x: int) -> int:
    return (x >> 1) ^ -(x & 1)


class _Writer:
    def __init__(self):
        self.buf = bytearray()
        self._last_fid = [0]

    def _field(self, fid: int, ctype: int) -> None:
        delta = fid - self._last_fid[-1]
        if 0 < delta <= 15:
            self.buf.append((delta << 4) | ctype)
        else:
            self.buf.append(ctype)
            self.buf += _uvarint(_zigzag(fid))
        self._last_fid[-1] = fid

    def i32(self, fid: int, v: int) -> None:
        self._field(fid, _CT_I32)
        self.buf += _uvarint(_zigzag(v))

    def i64(self, fid: int, v: int) -> None:
        self._field(fid, _CT_I64)
        self.buf += _uvarint(_zigzag(v))

    def string(self, fid: int, s: str) -> None:
        self._field(fid, _CT_BINARY)
        b = s.encode("utf-8")
        self.buf += _uvarint(len(b))
        self.buf += b

    def list_begin(self, fid: int, etype: int, n: int) -> None:
        self._field(fid, _CT_LIST)
        if n < 15:
            self.buf.append((n << 4) | etype)
        else:
            self.buf.append(0xF0 | etype)
            self.buf += _uvarint(n)

    def list_i32_elem(self, v: int) -> None:
        self.buf += _uvarint(_zigzag(v))

    def list_string_elem(self, s: str) -> None:
        b = s.encode("utf-8")
        self.buf += _uvarint(len(b))
        self.buf += b

    def bytes_field(self, fid: int, b: bytes) -> None:
        self._field(fid, _CT_BINARY)
        self.buf += _uvarint(len(b))
        self.buf += b

    def struct_begin(self, fid: int) -> None:
        self._field(fid, _CT_STRUCT)
        self._last_fid.append(0)

    def struct_end(self) -> None:
        self.buf.append(_CT_STOP)
        self._last_fid.pop()

    def root_end(self) -> None:
        self.buf.append(_CT_STOP)


class _Reader:
    def __init__(self, data: bytes, pos: int = 0):
        self.d = data
        self.pos = pos

    def _uvarint(self) -> int:
        x = 0
        shift = 0
        while True:
            b = self.d[self.pos]
            self.pos += 1
            x |= (b & 0x7F) << shift
            if not b & 0x80:
                return x
            shift += 7

    def _zig(self) -> int:
        return _unzigzag(self._uvarint())

    def read_struct(self) -> Dict[int, Any]:
        """Generic struct -> {field_id: value}; unknown fields kept
        (values are ints/bytes/lists/dicts)."""
        out: Dict[int, Any] = {}
        last = 0
        while True:
            head = self.d[self.pos]
            self.pos += 1
            if head == _CT_STOP:
                return out
            ctype = head & 0x0F
            delta = head >> 4
            if delta:
                fid = last + delta
            else:
                fid = self._zig()
            last = fid
            out[fid] = self._value(ctype)

    def _value(self, ctype: int):
        if ctype == _CT_TRUE:
            return True
        if ctype == _CT_FALSE:
            return False
        if ctype == _CT_BYTE:
            v = self.d[self.pos]
            self.pos += 1
            return v
        if ctype in (_CT_I16, _CT_I32, _CT_I64):
            return self._zig()
        if ctype == _CT_DOUBLE:
            v = struct.unpack_from("<d", self.d, self.pos)[0]
            self.pos += 8
            return v
        if ctype == _CT_BINARY:
            n = self._uvarint()
            v = self.d[self.pos:self.pos + n]
            self.pos += n
            return v
        if ctype == _CT_LIST or ctype == _CT_SET:
            head = self.d[self.pos]
            self.pos += 1
            etype = head & 0x0F
            n = head >> 4
            if n == 0xF:
                n = self._uvarint()
            return [self._value(etype) for _ in range(n)]
        if ctype == _CT_STRUCT:
            return self.read_struct()
        if ctype == _CT_MAP:
            n = self._uvarint()
            if n == 0:
                return {}
            kv = self.d[self.pos]
            self.pos += 1
            kt, vt = kv >> 4, kv & 0x0F
            return {
                self._value(kt): self._value(vt) for _ in range(n)
            }
        raise ValueError(f"thrift compact type {ctype}")


# ---------------------------------------------------------------------------
# column model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ParquetColumn:
    """One leaf column: name, physical/converted types, values +
    validity (None = all valid)."""

    name: str
    physical: int
    converted: Optional[int] = None
    scale: Optional[int] = None
    precision: Optional[int] = None
    values: Any = None          # np.ndarray, or list[bytes] for BYTE_ARRAY
    valid: Optional[np.ndarray] = None
    # LIST columns (3-level parquet lists): per-row element counts,
    # per-FLAT-ELEMENT validity; `values` holds the flat elements and
    # `valid` the per-row validity
    list_lengths: Optional[np.ndarray] = None
    element_valid: Optional[np.ndarray] = None


def _bitpack_levels(valid: np.ndarray) -> bytes:
    """Definition levels (bit width 1) as one BIT_PACKED run of the
    RLE/bit-packed hybrid."""
    n = len(valid)
    groups = (n + 7) // 8
    padded = np.zeros(groups * 8, dtype=bool)
    padded[:n] = valid
    packed = np.packbits(padded, bitorder="little").tobytes()
    return _uvarint((groups << 1) | 1) + packed


def _read_levels(data: bytes, pos: int, n: int) -> Tuple[np.ndarray, int]:
    """RLE/bit-packed hybrid, bit width 1, length-prefixed (v1 pages)."""
    (total_len,) = struct.unpack_from("<I", data, pos)
    pos += 4
    end = pos + total_len
    out = np.zeros(n, dtype=np.uint8)
    i = 0
    r = _Reader(data, pos)
    while i < n and r.pos < end:
        header = r._uvarint()
        if header & 1:  # bit-packed: (groups << 1) | 1
            groups = header >> 1
            cnt = groups * 8
            raw = np.frombuffer(
                r.d[r.pos:r.pos + groups], dtype=np.uint8
            )
            r.pos += groups
            bits = np.unpackbits(raw, bitorder="little")[:cnt]
            take = min(cnt, n - i)
            out[i:i + take] = bits[:take]
            i += take
        else:  # RLE run: (count << 1); value in 1 byte (bit width 1)
            count = header >> 1
            val = r.d[r.pos]
            r.pos += 1
            take = min(count, n - i)
            out[i:i + take] = val & 1
            i += take
    return out.astype(bool), end


def _read_levels_n(data: bytes, pos: int, n: int, width: int
                   ) -> Tuple[np.ndarray, int]:
    """RLE/bit-packed hybrid at an arbitrary bit width (repetition and
    definition levels of nested columns), length-prefixed (v1 pages)."""
    (total_len,) = struct.unpack_from("<I", data, pos)
    pos += 4
    end = pos + total_len
    out = np.zeros(n, dtype=np.uint8)
    i = 0
    r = _Reader(data, pos)
    vbytes = (width + 7) // 8
    while i < n and r.pos < end:
        header = r._uvarint()
        if header & 1:  # bit-packed: (groups << 1) | 1
            groups = header >> 1
            cnt = groups * 8
            raw = np.frombuffer(
                r.d[r.pos:r.pos + groups * width], dtype=np.uint8
            )
            r.pos += groups * width
            bits = np.unpackbits(raw, bitorder="little")
            vals = np.zeros(cnt, dtype=np.uint8)
            for b in range(width):
                vals |= (bits[b::width][:cnt] << b).astype(np.uint8)
            take = min(cnt, n - i)
            out[i:i + take] = vals[:take]
            i += take
        else:  # RLE run: (count << 1); value in ceil(width/8) bytes
            count = header >> 1
            val = int.from_bytes(r.d[r.pos:r.pos + vbytes], "little")
            r.pos += vbytes
            take = min(count, n - i)
            out[i:i + take] = val
            i += take
    return out, end


def _bitpack_levels_n(levels: np.ndarray, width: int) -> bytes:
    """Arbitrary-width levels as ONE bit-packed run of the hybrid."""
    n = len(levels)
    groups = (n + 7) // 8
    padded = np.zeros(groups * 8, dtype=np.uint8)
    padded[:n] = np.asarray(levels, np.uint8)
    bits = np.zeros((groups * 8, width), dtype=np.uint8)
    for b in range(width):
        bits[:, b] = (padded >> b) & 1
    packed = np.packbits(bits.reshape(-1), bitorder="little").tobytes()
    header = bytes([(groups << 1) | 1]) if groups < 64 else None
    if header is None:
        out = bytearray()
        g = groups
        v = (g << 1) | 1
        while v >= 0x80:
            out.append((v & 0x7F) | 0x80)
            v >>= 7
        out.append(v)
        header = bytes(out)
    return header + packed


def _plain_encode(col: ParquetColumn) -> bytes:
    vals = col.values
    if col.physical == T_BOOLEAN:
        arr = np.asarray(vals, dtype=bool)
        return np.packbits(arr, bitorder="little").tobytes()
    if col.physical == T_INT32:
        return np.asarray(vals, dtype="<i4").tobytes()
    if col.physical == T_INT64:
        return np.asarray(vals, dtype="<i8").tobytes()
    if col.physical == T_FLOAT:
        return np.asarray(vals, dtype="<f4").tobytes()
    if col.physical == T_DOUBLE:
        return np.asarray(vals, dtype="<f8").tobytes()
    if col.physical == T_BYTE_ARRAY:
        out = bytearray()
        for b in vals:
            if isinstance(b, str):
                b = b.encode("utf-8")
            out += struct.pack("<I", len(b))
            out += b
        return bytes(out)
    raise ValueError(f"physical type {col.physical}")


def _plain_decode(physical: int, data: bytes, n: int):
    if physical == T_BOOLEAN:
        bits = np.unpackbits(
            np.frombuffer(data, dtype=np.uint8), bitorder="little"
        )[:n]
        return bits.astype(bool)
    if physical == T_INT32:
        return np.frombuffer(data, dtype="<i4", count=n).copy()
    if physical == T_INT64:
        return np.frombuffer(data, dtype="<i8", count=n).copy()
    if physical == T_FLOAT:
        return np.frombuffer(data, dtype="<f4", count=n).copy()
    if physical == T_DOUBLE:
        return np.frombuffer(data, dtype="<f8", count=n).copy()
    if physical == T_BYTE_ARRAY:
        out = []
        pos = 0
        for _ in range(n):
            (ln,) = struct.unpack_from("<I", data, pos)
            pos += 4
            out.append(data[pos:pos + ln])
            pos += ln
        return out
    raise ValueError(f"physical type {physical}")


# ---------------------------------------------------------------------------
# write
# ---------------------------------------------------------------------------

# parquet compression codecs this codec speaks: GZIP via zlib,
# SNAPPY via the pure-python codec (utils/snappy.py — the codec real
# lakes actually write), ZSTD via the baked-in zstandard module
CODEC_UNCOMPRESSED = 0
CODEC_SNAPPY = 1
CODEC_GZIP = 2
CODEC_ZSTD = 6
_CODEC_NAMES = {"none": CODEC_UNCOMPRESSED, "snappy": CODEC_SNAPPY,
                "gzip": CODEC_GZIP, "zstd": CODEC_ZSTD}


def _compress(codec: int, payload: bytes) -> bytes:
    if codec == CODEC_GZIP:
        import zlib

        # parquet GZIP is RFC-1952 gzip framing (other engines reject
        # bare zlib streams)
        co = zlib.compressobj(wbits=zlib.MAX_WBITS | 16)
        return co.compress(payload) + co.flush()
    if codec == CODEC_SNAPPY:
        from trino_tpu.utils import snappy

        return snappy.compress(payload)
    if codec == CODEC_ZSTD:
        import zstandard

        return zstandard.ZstdCompressor().compress(payload)
    return payload


def _decompress(codec: int, payload: bytes, uncompressed_size: int) -> bytes:
    if codec == CODEC_GZIP:
        import zlib

        # auto-detect gzip or legacy zlib framing (files this codec
        # wrote before r5 used bare zlib)
        return zlib.decompress(payload, wbits=zlib.MAX_WBITS | 32)
    if codec == CODEC_SNAPPY:
        from trino_tpu.utils import snappy

        return snappy.decompress(payload)
    if codec == CODEC_ZSTD:
        import zstandard

        return zstandard.ZstdDecompressor().decompress(
            payload, max_output_size=max(uncompressed_size, 1)
        )
    if codec == CODEC_UNCOMPRESSED:
        return payload
    raise ValueError(
        f"unsupported parquet codec {codec} "
        "(UNCOMPRESSED/SNAPPY/GZIP/ZSTD)"
    )


def _pack_indices(idx: np.ndarray, bit_width: int) -> bytes:
    """Dictionary indices as ONE bit-packed run of the RLE/bit-packed
    hybrid (preceded by the 1-byte bit width, per RLE_DICTIONARY)."""
    n = len(idx)
    groups = (n + 7) // 8
    padded = np.zeros(groups * 8, dtype=np.uint64)
    padded[:n] = idx.astype(np.uint64)
    # bit-pack little-endian within each 8-value group
    bits = np.zeros((groups * 8, bit_width), dtype=np.uint8)
    for b in range(bit_width):
        bits[:, b] = (padded >> np.uint64(b)) & np.uint64(1)
    packed = np.packbits(bits.reshape(-1), bitorder="little").tobytes()
    return (
        bytes([bit_width])
        + _uvarint((groups << 1) | 1)
        + packed
    )


def _unpack_indices(data: bytes, n: int) -> np.ndarray:
    """Inverse of _pack_indices (also accepts RLE runs)."""
    bit_width = data[0]
    out = np.zeros(n, dtype=np.int64)
    r = _Reader(data, 1)
    i = 0
    while i < n:
        header = r._uvarint()
        if header & 1:
            groups = header >> 1
            nbytes = groups * bit_width
            raw = np.frombuffer(r.d[r.pos:r.pos + nbytes], np.uint8)
            r.pos += nbytes
            bits = np.unpackbits(raw, bitorder="little").reshape(
                -1, bit_width
            )
            vals = np.zeros(len(bits), dtype=np.int64)
            for b in range(bit_width):
                vals |= bits[:, b].astype(np.int64) << b
            take = min(len(vals), n - i)
            out[i:i + take] = vals[:take]
            i += take
        else:
            count = header >> 1
            nbytes = (bit_width + 7) // 8
            val = int.from_bytes(r.d[r.pos:r.pos + nbytes], "little")
            r.pos += nbytes
            take = min(count, n - i)
            out[i:i + take] = val
            i += take
    return out


def _chunk_stats(col: ParquetColumn, valid_mask) -> Optional[Tuple[bytes, bytes, int]]:
    """(min_value, max_value, null_count) little-endian-encoded per the
    Statistics struct, or None when the type has no cheap ordering."""
    vals = col.values
    nulls = 0
    if valid_mask is not None:
        nulls = int((~valid_mask).sum())
        if col.physical == T_BYTE_ARRAY:
            vals = [v for v, ok in zip(vals, valid_mask) if ok]
        else:
            vals = np.asarray(vals)[valid_mask]
    if len(vals) == 0:
        return None
    if col.physical == T_BYTE_ARRAY:
        bs = [
            v.encode("utf-8") if isinstance(v, str) else v for v in vals
        ]
        return min(bs), max(bs), nulls
    arr = np.asarray(vals)
    fmt = {T_INT32: "<i4", T_INT64: "<i8", T_FLOAT: "<f4",
           T_DOUBLE: "<f8"}.get(col.physical)
    if fmt is None:
        return None
    return (
        np.asarray(arr.min(), fmt).tobytes(),
        np.asarray(arr.max(), fmt).tobytes(),
        nulls,
    )


def _write_chunk(body: bytearray, col: ParquetColumn, codec: int,
                 use_dictionary: bool):
    """One column chunk (optionally dictionary-encoded BYTE_ARRAY):
    returns (offsets + metadata dict for the footer)."""
    n = (
        len(col.values)
        if col.physical == T_BYTE_ARRAY or not hasattr(col.values, "shape")
        else int(np.asarray(col.values).shape[0])
    )
    valid = None if col.valid is None else np.asarray(col.valid, bool)
    dict_page_offset = None
    encoding = 0  # PLAIN
    first_offset = len(body)

    payload = bytearray()
    if col.list_lengths is not None:
        # LIST leaf: [rep levels][def levels][PLAIN dense values]
        lengths = np.asarray(col.list_lengths, np.int64)
        row_valid = (
            np.ones(len(lengths), bool) if valid is None else valid
        )
        ev = (
            np.ones(int(lengths.sum()), bool)
            if col.element_valid is None
            else np.asarray(col.element_valid, bool)
        )
        base = 1  # outer group written optional
        max_def = 3  # outer optional + repeated + optional element
        reps: List[int] = []
        defs: List[int] = []
        fi = 0
        for L, rv in zip(lengths, row_valid):
            if not rv:
                reps.append(0)
                defs.append(0)
                fi += int(L)
                continue
            if L == 0:
                reps.append(0)
                defs.append(base)
                continue
            for j in range(int(L)):
                reps.append(0 if j == 0 else 1)
                defs.append(max_def if ev[fi] else max_def - 1)
                fi += 1
        n = len(reps)
        rl = _bitpack_levels_n(np.asarray(reps, np.uint8), 1)
        dl = _bitpack_levels_n(np.asarray(defs, np.uint8), 2)
        payload += struct.pack("<I", len(rl)) + rl
        payload += struct.pack("<I", len(dl)) + dl
        # elements belonging to NULL rows carry no def-level entries,
        # so they must not enter the dense value stream either
        rv_per_elem = np.repeat(row_valid, lengths)
        keep = ev & rv_per_elem
        if col.physical == T_BYTE_ARRAY:
            dense_vals = [v for v, ok in zip(col.values, keep) if ok]
        else:
            dense_vals = np.asarray(col.values)[keep]
        payload += _plain_encode(
            dataclasses.replace(
                col, values=dense_vals, valid=None, list_lengths=None
            )
        )
        raw = bytes(payload)
        comp = _compress(codec, raw)
        ph = _Writer()
        ph.i32(1, 0)                # DATA_PAGE
        ph.i32(2, len(raw))
        ph.i32(3, len(comp))
        ph.struct_begin(5)
        ph.i32(1, n)
        ph.i32(2, 0)                # PLAIN
        ph.i32(3, 3)                # def levels: RLE
        ph.i32(4, 3)                # rep levels: RLE
        ph.struct_end()
        ph.root_end()
        first_offset = data_page_offset = len(body)
        body += ph.buf
        body += comp
        nbytes = len(body) - first_offset
        return None, data_page_offset, first_offset, nbytes, n, None
    if valid is not None:
        levels = _bitpack_levels(valid)
        payload += struct.pack("<I", len(levels))
        payload += levels
        if col.physical == T_BYTE_ARRAY:
            dense_vals = [v for v, ok in zip(col.values, valid) if ok]
        else:
            dense_vals = np.asarray(col.values)[valid]
    else:
        dense_vals = col.values

    if use_dictionary and col.physical == T_BYTE_ARRAY:
        bs = [
            v.encode("utf-8") if isinstance(v, str) else v
            for v in dense_vals
        ]
        uniq = sorted(set(bs))
        if len(uniq) and len(uniq) * 2 <= max(len(bs), 1):
            code = {v: i for i, v in enumerate(uniq)}
            idx = np.asarray([code[v] for v in bs], np.int64)
            bw = max(int(len(uniq) - 1).bit_length(), 1)
            # dictionary page first
            dpl = _compress(codec, _plain_encode(dataclasses.replace(
                col, values=uniq, valid=None
            )))
            raw_len = len(_plain_encode(dataclasses.replace(
                col, values=uniq, valid=None
            )))
            dh = _Writer()
            dh.i32(1, 2)            # DICTIONARY_PAGE
            dh.i32(2, raw_len)
            dh.i32(3, len(dpl))
            dh.struct_begin(7)      # dictionary_page_header
            dh.i32(1, len(uniq))
            dh.i32(2, 0)            # PLAIN
            dh.struct_end()
            dh.root_end()
            dict_page_offset = len(body)
            first_offset = dict_page_offset
            body += dh.buf
            body += dpl
            payload += _pack_indices(idx, bw)
            encoding = 8  # RLE_DICTIONARY
    if encoding == 0:
        payload += _plain_encode(
            dataclasses.replace(col, values=dense_vals, valid=None)
        )

    raw = bytes(payload)
    comp = _compress(codec, raw)
    ph = _Writer()
    ph.i32(1, 0)                    # DATA_PAGE
    ph.i32(2, len(raw))             # uncompressed size
    ph.i32(3, len(comp))            # compressed size
    ph.struct_begin(5)              # data_page_header
    ph.i32(1, n)                    # num_values (incl. nulls)
    ph.i32(2, encoding)
    ph.i32(3, 3)                    # def levels: RLE
    ph.i32(4, 3)                    # rep levels: RLE (absent, flat)
    ph.struct_end()
    ph.root_end()
    data_page_offset = len(body)
    if dict_page_offset is None:
        first_offset = data_page_offset
    body += ph.buf
    body += comp
    nbytes = len(body) - first_offset
    stats = _chunk_stats(col, valid)
    return dict_page_offset, data_page_offset, first_offset, nbytes, n, stats


def write_parquet(path: str, columns: List[ParquetColumn], num_rows: int,
                  codec: str = "none", row_group_rows: Optional[int] = None,
                  use_dictionary: bool = True) -> None:
    """`codec`: none | gzip. `row_group_rows` splits the file into
    multiple row groups whose per-chunk min/max statistics feed
    read_parquet's predicate pruning."""
    codec_id = _CODEC_NAMES[codec]
    if row_group_rows is None or row_group_rows >= num_rows:
        row_group_rows = max(num_rows, 1)
    body = bytearray(MAGIC)
    groups = []  # list of (chunk_meta list, rows_in_group)
    for g0 in range(0, max(num_rows, 1), row_group_rows):
        g1 = min(g0 + row_group_rows, num_rows)
        chunk_meta = []
        for col in columns:
            if col.list_lengths is not None:
                lens = np.asarray(col.list_lengths, np.int64)
                cum = np.concatenate([[0], np.cumsum(lens)])
                f0, f1 = int(cum[g0]), int(cum[g1])
                sl = dataclasses.replace(
                    col,
                    values=(
                        col.values[f0:f1]
                        if col.physical == T_BYTE_ARRAY
                        else np.asarray(col.values)[f0:f1]
                    ),
                    valid=None if col.valid is None
                    else np.asarray(col.valid, bool)[g0:g1],
                    list_lengths=lens[g0:g1],
                    element_valid=None if col.element_valid is None
                    else np.asarray(col.element_valid, bool)[f0:f1],
                )
                chunk_meta.append(
                    (col, _write_chunk(body, sl, codec_id, False))
                )
                continue
            sl_vals = (
                col.values[g0:g1]
                if col.physical == T_BYTE_ARRAY
                else np.asarray(col.values)[g0:g1]
            )
            sl = dataclasses.replace(
                col,
                values=sl_vals,
                valid=None if col.valid is None
                else np.asarray(col.valid, bool)[g0:g1],
            )
            chunk_meta.append(
                (col, _write_chunk(body, sl, codec_id, use_dictionary))
            )
        groups.append((chunk_meta, g1 - g0))
        if num_rows == 0:
            break

    # footer
    w = _Writer()
    w.i32(1, 1)  # version
    # schema: root + leaves
    n_schema = 1 + sum(
        3 if c.list_lengths is not None else 1 for c in columns
    )
    w.list_begin(2, _CT_STRUCT, n_schema)
    root = _Writer()
    root.string(4, "schema")
    root.i32(5, len(columns))
    root.root_end()
    w.buf += root.buf
    for col in columns:
        if col.list_lengths is not None:
            # 3-level LIST: optional group (LIST) > repeated group
            # "list" > optional leaf "element"
            outer = _Writer()
            outer.i32(3, 1)
            outer.string(4, col.name)
            outer.i32(5, 1)
            outer.i32(6, 3)  # converted LIST
            outer.root_end()
            w.buf += outer.buf
            mid = _Writer()
            mid.i32(3, 2)  # repeated
            mid.string(4, "list")
            mid.i32(5, 1)
            mid.root_end()
            w.buf += mid.buf
            se = _Writer()
            se.i32(1, col.physical)
            se.i32(3, 1)  # optional element
            se.string(4, "element")
            if col.converted is not None:
                se.i32(6, col.converted)
            if col.scale is not None:
                se.i32(7, col.scale)
            if col.precision is not None:
                se.i32(8, col.precision)
            se.root_end()
            w.buf += se.buf
            continue
        se = _Writer()
        se.i32(1, col.physical)
        se.i32(3, 1 if col.valid is not None else 0)  # optional/required
        se.string(4, col.name)
        if col.converted is not None:
            se.i32(6, col.converted)
        if col.scale is not None:
            se.i32(7, col.scale)
        if col.precision is not None:
            se.i32(8, col.precision)
        se.root_end()
        w.buf += se.buf
    w.i64(3, num_rows)
    w.list_begin(4, _CT_STRUCT, len(groups))
    for chunk_meta, g_rows in groups:
        rg = _Writer()
        rg.list_begin(1, _CT_STRUCT, len(columns))
        total = 0
        for col, (dict_off, data_off, first_off, nbytes, nvals, stats) in chunk_meta:
            cc = _Writer()
            cc.i64(2, first_off)            # file_offset
            cc.struct_begin(3)              # meta_data
            cc.i32(1, col.physical)
            cc.list_begin(2, _CT_I32, 2 if dict_off is not None else 1)
            cc.list_i32_elem(0)             # PLAIN
            if dict_off is not None:
                cc.list_i32_elem(8)         # RLE_DICTIONARY
            if col.list_lengths is not None:
                cc.list_begin(3, _CT_BINARY, 3)
                cc.list_string_elem(col.name)
                cc.list_string_elem("list")
                cc.list_string_elem("element")
            else:
                cc.list_begin(3, _CT_BINARY, 1)
                cc.list_string_elem(col.name)
            cc.i32(4, codec_id)
            cc.i64(5, nvals)
            cc.i64(6, nbytes)
            cc.i64(7, nbytes)
            cc.i64(9, data_off)             # data_page_offset
            if dict_off is not None:
                cc.i64(11, dict_off)        # dictionary_page_offset
            if stats is not None:
                mn, mx, nulls = stats
                cc.struct_begin(12)         # statistics
                cc.bytes_field(5, mx)       # max_value
                cc.bytes_field(6, mn)       # min_value
                cc.i64(3, nulls)
                cc.struct_end()
            cc.struct_end()
            cc.root_end()
            rg.buf += cc.buf
            total += nbytes
        rg.i64(2, total)
        rg.i64(3, g_rows)
        rg.root_end()
        w.buf += rg.buf
    w.string(6, "trino-tpu")
    w.root_end()

    body += w.buf
    body += struct.pack("<I", len(w.buf))
    body += MAGIC
    with open(path, "wb") as f:
        f.write(body)


# ---------------------------------------------------------------------------
# read
# ---------------------------------------------------------------------------


def _decode_stat(physical: int, raw: bytes):
    fmt = {T_INT32: "<i4", T_INT64: "<i8", T_FLOAT: "<f4",
           T_DOUBLE: "<f8"}.get(physical)
    if fmt is None:
        return raw  # BYTE_ARRAY: compare as bytes
    return np.frombuffer(raw, fmt)[0].item()


def _assemble_list_column(col: ParquetColumn, li: dict, parts) -> None:
    """(rep, def, values) page parts -> per-row lengths + flat elements
    (the record-shredding inverse, Dremel assembly)."""
    base = 1 if li["outer_opt"] else 0
    max_def = li["max_def"]
    lengths: List[int] = []
    row_valid: List[bool] = []
    elem_valid: List[bool] = []
    flats: List = []
    for (rep, deff), vals in parts:
        vi = 0
        for i in range(len(rep)):
            if rep[i] == 0:  # new row
                lengths.append(0)
                row_valid.append(deff[i] >= base)
            if deff[i] > base:  # an element entry (maybe null)
                lengths[-1] += 1
                ok = deff[i] == max_def
                elem_valid.append(bool(ok))
                if ok:
                    if col.physical == T_BYTE_ARRAY:
                        flats.append(vals[vi])
                    else:
                        flats.append(vals[vi].item()
                                     if hasattr(vals[vi], "item")
                                     else vals[vi])
                    vi += 1
                else:
                    flats.append(
                        b"" if col.physical == T_BYTE_ARRAY else 0
                    )
    col.list_lengths = np.asarray(lengths, np.int32)
    col.valid = (
        np.asarray(row_valid, bool) if li["outer_opt"] else None
    )
    col.element_valid = np.asarray(elem_valid, bool)
    if col.physical == T_BYTE_ARRAY:
        col.values = flats
    else:
        dtype = {T_INT32: np.int32, T_INT64: np.int64,
                 T_FLOAT: np.float32, T_DOUBLE: np.float64,
                 T_BOOLEAN: np.bool_}.get(col.physical, np.float64)
        col.values = np.asarray(flats, dtype=dtype)


def _read_footer(path: str) -> Tuple[bytes, Any]:
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != MAGIC or data[-4:] != MAGIC:
        raise ValueError("not a parquet file")
    (meta_len,) = struct.unpack_from("<I", data, len(data) - 8)
    meta = _Reader(data, len(data) - 8 - meta_len).read_struct()
    return data, meta


def _schema_columns(schema) -> Tuple[List[dict], List[ParquetColumn]]:
    """Schema tree walk: flat leaves plus 3-level LIST groups (the
    shape every modern writer emits for arrays — LogicalTypes.md#lists).
    Leaf order matches row-group chunk order."""
    descs: List[dict] = []
    idx = [1]

    def _walk_field():
        se = schema[idx[0]]
        idx[0] += 1
        nch = se.get(5, 0)
        if nch == 0:
            descs.append({"se": se, "list": None})
            return
        if se.get(6) == 3 and nch == 1:  # converted LIST
            outer_opt = se.get(3, 0) == 1
            mid = schema[idx[0]]
            idx[0] += 1
            if mid.get(3, 0) != 2 or mid.get(5, 0) != 1:
                raise ValueError("unsupported LIST shape")
            leaf = schema[idx[0]]
            idx[0] += 1
            if leaf.get(5, 0):
                raise ValueError(
                    "nested parquet beyond one LIST level not supported"
                )
            elem_opt = leaf.get(3, 0) == 1
            descs.append({
                "se": leaf, "list": {
                    "name": se[4].decode("utf-8"),
                    "outer_opt": outer_opt,
                    "elem_opt": elem_opt,
                    "max_def": (1 if outer_opt else 0) + 1
                    + (1 if elem_opt else 0),
                },
            })
            return
        raise ValueError("nested parquet group schemas not supported")

    n_root = schema[0].get(5, 0)
    for _ in range(n_root):
        _walk_field()

    cols: List[ParquetColumn] = []
    for d in descs:
        se = d["se"]
        if d["list"] is None:
            cols.append(ParquetColumn(
                name=se[4].decode("utf-8"),
                physical=se[1],
                converted=se.get(6),
                scale=se.get(7),
                precision=se.get(8),
                valid=None if se.get(3, 0) == 0 else np.zeros(0, bool),
            ))
        else:
            li = d["list"]
            cols.append(ParquetColumn(
                name=li["name"],
                physical=se[1],
                converted=se.get(6),
                scale=se.get(7),
                precision=se.get(8),
                valid=np.zeros(0, bool) if li["outer_opt"] else None,
                list_lengths=np.zeros(0, np.int32),
            ))
    return descs, cols


def read_parquet_meta(path: str) -> Tuple[
    List[ParquetColumn], int, Dict[str, Optional[tuple]]
]:
    """Footer-only read: (columns with empty values, num_rows,
    {column: (min, max, null_count) | None}) with min/max/null_count
    aggregated over row-group chunk statistics (None for a column when
    any chunk lacks them). No data pages are touched, so this never
    counts toward bytes_scanned — the seat metadata/statistics queries
    use instead of parsing the whole file."""
    _, meta = _read_footer(path)
    _, cols = _schema_columns(meta[2])
    stats: Dict[str, Optional[tuple]] = {c.name: None for c in cols}
    complete = {c.name: True for c in cols}
    acc: Dict[str, list] = {}
    for rg in meta[4]:
        for ci, cc in enumerate(rg[1]):
            name = cols[ci].name
            st = cc[3].get(12)
            if not st or 5 not in st or 6 not in st:
                complete[name] = False
                continue
            mn = _decode_stat(cols[ci].physical, st[6])
            mx = _decode_stat(cols[ci].physical, st[5])
            nulls = st.get(3)
            if name not in acc:
                acc[name] = [mn, mx, nulls]
                continue
            a = acc[name]
            a[0] = min(a[0], mn)
            a[1] = max(a[1], mx)
            a[2] = (
                None if a[2] is None or nulls is None else a[2] + nulls
            )
    for name, a in acc.items():
        if complete[name]:
            stats[name] = tuple(a)
    return cols, meta[3], stats


def read_parquet(path: str, predicate: Optional[Dict[str, tuple]] = None
                 ) -> Tuple[List[ParquetColumn], int]:
    """`predicate`: {column: (lo, hi)} closed ranges (None = unbounded
    side); row groups whose min/max statistics prove emptiness are
    skipped entirely (lib/trino-parquet predicate pushdown analogue)."""
    data, meta = _read_footer(path)
    num_rows = meta[3]
    row_groups = meta[4]
    descs, cols = _schema_columns(meta[2])
    chunks: List[List[Tuple[np.ndarray, Any]]] = [[] for _ in cols]
    rows_read = 0
    for rg in row_groups:
        # row-group pruning from chunk statistics (min_value/max_value)
        if predicate:
            skip = False
            for ci, cc in enumerate(rg[1]):
                name = cols[ci].name
                if name not in predicate:
                    continue
                st = cc[3].get(12)
                if not st or 5 not in st or 6 not in st:
                    continue
                lo, hi = predicate[name]
                mn = _decode_stat(cols[ci].physical, st[6])
                mx = _decode_stat(cols[ci].physical, st[5])
                if (hi is not None and mn is not None and mn > hi) or (
                    lo is not None and mx is not None and mx < lo
                ):
                    skip = True
                    break
            if skip:
                continue
        rows_read += rg.get(3, 0)
        for ci, cc in enumerate(rg[1]):
            md = cc[3]
            codec = md.get(4, 0)
            pos = md.get(11, md.get(9, cc.get(2)))
            n_remaining = md[5]
            dictionary = None
            while n_remaining > 0:
                r = _Reader(data, pos)
                ph = r.read_struct()
                raw_len = ph[2]
                page_len = ph[3]
                page_start = r.pos
                METRICS.increment("bytes_scanned", page_len)
                page = _decompress(
                    codec, data[page_start:page_start + page_len], raw_len
                )
                if ph.get(7) is not None:  # dictionary page
                    n_dict = ph[7][1]
                    dictionary = _plain_decode(
                        cols[ci].physical, page, n_dict
                    )
                    pos = page_start + page_len
                    continue
                dph = ph.get(5)
                if dph is None:  # index/other pages: skip
                    pos = page_start + page_len
                    continue
                n_vals = dph[1]
                enc = dph.get(2, 0)
                li = descs[ci]["list"]
                if li is not None:
                    # [rep levels][def levels][PLAIN values]
                    max_def = li["max_def"]
                    def_w = max(max_def.bit_length(), 1)
                    rep, p2 = _read_levels_n(page, 0, n_vals, 1)
                    deff, p3 = _read_levels_n(page, p2, n_vals, def_w)
                    if enc != 0:
                        raise ValueError(
                            "dictionary-encoded LIST pages not supported"
                        )
                    n_phys = int((deff == max_def).sum())
                    vals = _plain_decode(
                        cols[ci].physical, page[p3:], n_phys
                    )
                    chunks[ci].append(((rep, deff), vals))
                    n_remaining -= n_vals
                    pos = page_start + page_len
                    continue
                if cols[ci].valid is not None:
                    valid, vpos = _read_levels(page, 0, n_vals)
                    body_bytes = page[vpos:]
                    n_dense = int(valid.sum())
                else:
                    valid = None
                    body_bytes = page
                    n_dense = n_vals
                if enc in (2, 8):  # PLAIN_DICTIONARY / RLE_DICTIONARY
                    if dictionary is None:
                        raise ValueError("dictionary page missing")
                    idx = _unpack_indices(body_bytes, n_dense)
                    if cols[ci].physical == T_BYTE_ARRAY:
                        vals = [dictionary[int(i)] for i in idx]
                    else:
                        vals = np.asarray(dictionary)[idx]
                elif enc == 0:
                    vals = _plain_decode(
                        cols[ci].physical, body_bytes, n_dense
                    )
                else:
                    raise ValueError(
                        f"unsupported parquet encoding {enc}"
                    )
                chunks[ci].append((valid, vals))
                n_remaining -= n_vals
                pos = page_start + page_len
    for ci, col in enumerate(cols):
        parts = chunks[ci]
        li = descs[ci]["list"]
        if li is not None:
            _assemble_list_column(col, li, parts)
            continue
        if col.physical == T_BYTE_ARRAY:
            dense: List[bytes] = []
            for _, v in parts:
                dense.extend(v)
        else:
            dense = (
                np.concatenate([v for _, v in parts])
                if parts
                else np.zeros(0)
            )
        if col.valid is not None:
            valid = (
                np.concatenate([v for v, _ in parts])
                if parts
                else np.zeros(0, bool)
            )
            # re-expand to row positions (nulls get placeholder zeros)
            if col.physical == T_BYTE_ARRAY:
                out: List[bytes] = []
                it = iter(dense)
                for ok in valid:
                    out.append(next(it) if ok else b"")
                col.values = out
            else:
                full = np.zeros(len(valid), dtype=dense.dtype)
                full[valid] = dense
                col.values = full
            col.valid = valid
        else:
            col.values = dense
    # with predicate pruning the returned row count covers the ROW
    # GROUPS ACTUALLY READ, matching the data arrays
    return cols, (rows_read if predicate else num_rows)
