"""TPC-DS connector: deterministic in-memory generator.

Analogue of plugin/trino-tpcds (1.7k LoC — the second benchmark fixture
the reference ships, SURVEY.md §2.12). Covers the star-schema core that
the classic reporting queries touch (q3/q42/q52/q55 family): store_sales
fact plus date_dim/item/store/customer/promotion dimensions, generated
with the same splitmix64 column-hash discipline as the TPC-H connector
(byte-identical data for any (sf, row range) request, so the sqlite
oracle can load the very same rows)."""

from __future__ import annotations

import datetime
import hashlib
from functools import lru_cache
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.block import Column, Dictionary, RelBatch, bucket_capacity
from trino_tpu.connectors.spi import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSource,
    ConnectorSplitManager,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)

_U = np.uint64


def _stable_seed(*parts) -> int:
    h = hashlib.sha256("|".join(map(str, parts)).encode()).digest()
    return int.from_bytes(h[:8], "little")


def _splitmix64(x: np.ndarray) -> np.ndarray:
    x = (x + _U(0x9E3779B97F4A7C15)).astype(np.uint64)
    x = ((x ^ (x >> _U(30))) * _U(0xBF58476D1CE4E5B9)).astype(np.uint64)
    x = ((x ^ (x >> _U(27))) * _U(0x94D049BB133111EB)).astype(np.uint64)
    return x ^ (x >> _U(31))


def _uniform(table, column, keys, lo: int, hi: int) -> np.ndarray:
    seed = _U(_stable_seed(table, column, "tpcds-tpu-v1"))
    u = _splitmix64(keys.astype(np.uint64) ^ seed)
    return (lo + (u % _U(hi - lo + 1)).astype(np.int64)).astype(np.int64)


_EPOCH = datetime.date(1970, 1, 1)


def _d(y, m, d):
    return (datetime.date(y, m, d) - _EPOCH).days


# date_dim covers 1998-01-01 .. 2002-12-31; official Julian-style sks
DATE_START = _d(1998, 1, 1)
DATE_ROWS = _d(2002, 12, 31) - DATE_START + 1
DATE_SK0 = 2450815  # first sk

CATEGORIES = ["Books", "Electronics", "Home", "Jewelry", "Music",
              "Shoes", "Sports", "Women", "Men", "Children"]
DAY_NAMES = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
             "Saturday", "Sunday"]
STATES = ["TN", "CA", "TX", "WA", "OH", "GA", "NY", "IL"]
BRAND_PER_CAT = 50

_DEC = T.decimal(7, 2)

TABLES: Dict[str, List[Tuple[str, T.DataType]]] = {
    "date_dim": [
        ("d_date_sk", T.BIGINT), ("d_date", T.DATE), ("d_year", T.BIGINT),
        ("d_moy", T.BIGINT), ("d_dom", T.BIGINT), ("d_qoy", T.BIGINT),
        ("d_week_seq", T.BIGINT), ("d_month_seq", T.BIGINT),
        ("d_day_name", T.VARCHAR)],
    "time_dim": [
        ("t_time_sk", T.BIGINT), ("t_hour", T.BIGINT),
        ("t_minute", T.BIGINT)],
    "item": [
        ("i_item_sk", T.BIGINT), ("i_item_id", T.VARCHAR),
        ("i_item_desc", T.VARCHAR),
        ("i_brand_id", T.BIGINT), ("i_brand", T.VARCHAR),
        ("i_class", T.VARCHAR),
        ("i_category_id", T.BIGINT), ("i_category", T.VARCHAR),
        ("i_manufact_id", T.BIGINT), ("i_manager_id", T.BIGINT),
        ("i_current_price", _DEC)],
    "store": [
        ("s_store_sk", T.BIGINT), ("s_store_id", T.VARCHAR),
        ("s_store_name", T.VARCHAR), ("s_state", T.VARCHAR),
        ("s_gmt_offset", _DEC)],
    "warehouse": [
        ("w_warehouse_sk", T.BIGINT), ("w_warehouse_name", T.VARCHAR)],
    "customer": [
        ("c_customer_sk", T.BIGINT), ("c_customer_id", T.VARCHAR),
        ("c_first_name", T.VARCHAR), ("c_last_name", T.VARCHAR),
        ("c_birth_year", T.BIGINT)],
    "customer_demographics": [
        ("cd_demo_sk", T.BIGINT), ("cd_gender", T.VARCHAR),
        ("cd_marital_status", T.VARCHAR),
        ("cd_education_status", T.VARCHAR)],
    "household_demographics": [
        ("hd_demo_sk", T.BIGINT), ("hd_buy_potential", T.VARCHAR),
        ("hd_dep_count", T.BIGINT), ("hd_vehicle_count", T.BIGINT)],
    "promotion": [
        ("p_promo_sk", T.BIGINT), ("p_promo_id", T.VARCHAR),
        ("p_channel_email", T.VARCHAR), ("p_channel_event", T.VARCHAR)],
    "store_sales": [
        ("ss_sold_date_sk", T.BIGINT), ("ss_sold_time_sk", T.BIGINT),
        ("ss_item_sk", T.BIGINT),
        ("ss_customer_sk", T.BIGINT), ("ss_cdemo_sk", T.BIGINT),
        ("ss_hdemo_sk", T.BIGINT), ("ss_store_sk", T.BIGINT),
        ("ss_promo_sk", T.BIGINT), ("ss_quantity", T.BIGINT),
        ("ss_list_price", _DEC), ("ss_coupon_amt", _DEC),
        ("ss_sales_price", _DEC), ("ss_ext_sales_price", _DEC),
        ("ss_net_profit", _DEC)],
    "catalog_sales": [
        ("cs_sold_date_sk", T.BIGINT), ("cs_ship_date_sk", T.BIGINT),
        ("cs_bill_cdemo_sk", T.BIGINT), ("cs_bill_hdemo_sk", T.BIGINT),
        ("cs_item_sk", T.BIGINT), ("cs_promo_sk", T.BIGINT),
        ("cs_order_number", T.BIGINT), ("cs_quantity", T.BIGINT),
        ("cs_list_price", _DEC), ("cs_sales_price", _DEC)],
    "catalog_returns": [
        ("cr_item_sk", T.BIGINT), ("cr_order_number", T.BIGINT),
        ("cr_return_quantity", T.BIGINT)],
    "inventory": [
        ("inv_date_sk", T.BIGINT), ("inv_item_sk", T.BIGINT),
        ("inv_warehouse_sk", T.BIGINT),
        ("inv_quantity_on_hand", T.BIGINT)],
}


def _scaled(base: int, sf: float) -> int:
    return max(1, int(round(base * sf)))


# weekly inventory snapshots (the official generator's cadence)
INV_WEEKS = DATE_ROWS // 7


def _n_warehouses(sf: float) -> int:
    return max(1, int(round(5 * sf ** 0.5)))


def row_count(table: str, sf: float) -> int:
    return {
        "date_dim": DATE_ROWS,
        "time_dim": 86_400 // 60,  # one row per minute of day
        "item": _scaled(18_000, sf),
        "store": max(1, int(round(12 * sf ** 0.5))),
        "warehouse": _n_warehouses(sf),
        "customer": _scaled(100_000, sf),
        "customer_demographics": 1920,  # fixed-size cross of demographics
        "household_demographics": 720,
        "promotion": _scaled(300, sf),
        "store_sales": _scaled(2_880_000, sf),
        "catalog_sales": _scaled(1_440_000, sf),
        "catalog_returns": _scaled(144_000, sf),
        # weekly snapshot of every (item, warehouse) pair
        "inventory": _scaled(18_000, sf) * _n_warehouses(sf) * INV_WEEKS,
    }[table]


@lru_cache(maxsize=None)
def _brand_dict() -> Dictionary:
    return Dictionary(
        [f"{c}brand #{i}" for c in CATEGORIES for i in range(1, BRAND_PER_CAT + 1)]
    )


@lru_cache(maxsize=None)
def _id_dict(table: str, prefix: str, width: int, n: int) -> Dictionary:
    return Dictionary([f"{prefix}{i:0{width}d}" for i in range(n + 1)])


@lru_cache(maxsize=None)
def _name_dict(kind: str, n: int) -> Dictionary:
    rng = np.random.default_rng(_stable_seed(kind, "names") % (2**32))
    letters = np.array(list("abcdefghijklmnopqrstuvwxyz"))
    vals = []
    for _ in range(n):
        k = int(rng.integers(4, 10))
        s = "".join(letters[rng.integers(0, 26, k)])
        vals.append(s.capitalize())
    return Dictionary(vals)


def generate_column(table: str, col: str, sf: float, lo: int, hi: int):
    """Rows [lo, hi) of one column -> (np data, Dictionary|None)."""
    keys = np.arange(lo, hi, dtype=np.int64)
    n = len(keys)
    if table == "date_dim":
        days = DATE_START + keys
        if col == "d_date_sk":
            return DATE_SK0 + keys, None
        if col == "d_date":
            return days.astype(np.int32), None
        if col == "d_week_seq":
            # Monday-aligned consecutive week numbers (1970-01-05 was a
            # Monday, so days-since-epoch+3 is week-stable)
            return ((days + 3) // 7).astype(np.int64), None
        dates = [(_EPOCH + datetime.timedelta(days=int(x))) for x in days]
        if col == "d_year":
            return np.asarray([d.year for d in dates], dtype=np.int64), None
        if col == "d_moy":
            return np.asarray([d.month for d in dates], dtype=np.int64), None
        if col == "d_dom":
            return np.asarray([d.day for d in dates], dtype=np.int64), None
        if col == "d_qoy":
            return np.asarray([(d.month - 1) // 3 + 1 for d in dates], dtype=np.int64), None
        if col == "d_month_seq":
            # months since 1900-01 (the official sequence's epoch)
            return np.asarray(
                [(d.year - 1900) * 12 + d.month - 1 for d in dates],
                dtype=np.int64,
            ), None
        if col == "d_day_name":
            d = Dictionary(DAY_NAMES)
            return d.encode([DAY_NAMES[x.weekday()] for x in dates]), d
    if table == "time_dim":
        if col == "t_time_sk":
            return keys + 1, None
        if col == "t_hour":
            return keys // 60, None
        if col == "t_minute":
            return keys % 60, None
    if table == "warehouse":
        if col == "w_warehouse_sk":
            return keys + 1, None
        if col == "w_warehouse_name":
            d = _name_dict("warehouse", 32)
            return _uniform(table, col, keys, 0, len(d) - 1).astype(np.int32), d
    if table == "customer_demographics":
        if col == "cd_demo_sk":
            return keys + 1, None
        if col == "cd_gender":
            d = Dictionary(["M", "F"])
            return (keys % 2).astype(np.int32), d
        if col == "cd_marital_status":
            vals = ["M", "S", "D", "W", "U"]
            d = Dictionary(vals)
            return d.encode([vals[int(k) // 2 % 5] for k in keys]), d
        if col == "cd_education_status":
            vals = ["Primary", "Secondary", "College", "2 yr Degree",
                    "4 yr Degree", "Advanced Degree", "Unknown"]
            d = Dictionary(vals)
            return d.encode([vals[int(k) // 10 % 7] for k in keys]), d
    if table == "household_demographics":
        if col == "hd_demo_sk":
            return keys + 1, None
        if col == "hd_buy_potential":
            vals = ["0-500", "501-1000", "1001-5000", "5001-10000",
                    ">10000", "Unknown"]
            d = Dictionary(vals)
            return d.encode([vals[int(k) % 6] for k in keys]), d
        if col == "hd_dep_count":
            return (keys % 10).astype(np.int64), None
        if col == "hd_vehicle_count":
            return (keys % 5).astype(np.int64), None
    if table == "catalog_sales":
        if col == "cs_sold_date_sk":
            return DATE_SK0 + _uniform(table, col, keys, 0, DATE_ROWS - 8), None
        if col == "cs_ship_date_sk":
            sold = _uniform(table, "cs_sold_date_sk", keys, 0, DATE_ROWS - 8)
            lag = _uniform(table, "cs_ship_lag", keys, 1, 7)
            return DATE_SK0 + sold + lag, None
        if col == "cs_bill_cdemo_sk":
            return _uniform(table, col, keys, 1, row_count("customer_demographics", sf)), None
        if col == "cs_bill_hdemo_sk":
            return _uniform(table, col, keys, 1, row_count("household_demographics", sf)), None
        if col == "cs_item_sk":
            return _uniform(table, col, keys, 1, row_count("item", sf)), None
        if col == "cs_promo_sk":
            return _uniform(table, col, keys, 1, row_count("promotion", sf)), None
        if col == "cs_order_number":
            return keys // 4 + 1, None  # ~4 lines per order
        if col == "cs_quantity":
            return _uniform(table, col, keys, 1, 100), None
        if col == "cs_list_price":
            return _uniform(table, col, keys, 100, 30000), None
        if col == "cs_sales_price":
            return _uniform(table, col, keys, 10, 30000), None
    if table == "catalog_returns":
        # returns reference a deterministic subset of catalog_sales lines
        sale_rows = row_count("catalog_sales", sf)
        src = _uniform(table, "cr_source_row", keys, 0, max(sale_rows - 1, 0))
        if col == "cr_item_sk":
            return _uniform("catalog_sales", "cs_item_sk", src, 1, row_count("item", sf)), None
        if col == "cr_order_number":
            return src // 4 + 1, None
        if col == "cr_return_quantity":
            return _uniform(table, col, keys, 1, 20), None
    if table == "inventory":
        n_items = row_count("item", sf)
        n_wh = _n_warehouses(sf)
        week = keys // (n_items * n_wh)
        rem = keys % (n_items * n_wh)
        if col == "inv_date_sk":
            # Monday of week `week` within the date_dim range
            first_monday = (7 - ((DATE_START + 3) % 7)) % 7
            return DATE_SK0 + first_monday + week * 7, None
        if col == "inv_item_sk":
            return rem // n_wh + 1, None
        if col == "inv_warehouse_sk":
            return rem % n_wh + 1, None
        if col == "inv_quantity_on_hand":
            return _uniform(table, col, keys, 0, 1000), None
    if table == "item":
        if col == "i_item_sk":
            return keys + 1, None
        if col == "i_item_id":
            d = _id_dict("item", "AAAAAAAA", 8, row_count("item", sf))
            return d.encode([f"AAAAAAAA{int(k):08d}" for k in keys]), d
        cat_id = _uniform(table, "i_category_id", keys, 1, len(CATEGORIES))
        if col == "i_category_id":
            return cat_id, None
        if col == "i_category":
            d = Dictionary(CATEGORIES)
            return d.encode([CATEGORIES[int(c) - 1] for c in cat_id]), d
        brand_no = _uniform(table, "i_brand", keys, 1, BRAND_PER_CAT)
        if col == "i_brand_id":
            return cat_id * 1000 + brand_no, None
        if col == "i_brand":
            d = _brand_dict()
            return d.encode(
                [
                    f"{CATEGORIES[int(c) - 1]}brand #{int(b)}"
                    for c, b in zip(cat_id, brand_no)
                ]
            ), d
        if col == "i_manufact_id":
            return _uniform(table, col, keys, 1, 1000), None
        if col == "i_manager_id":
            return _uniform(table, col, keys, 1, 100), None
        if col == "i_item_desc":
            d = _name_dict("item_desc", 2000)
            return _uniform(table, col, keys, 0, len(d) - 1).astype(np.int32), d
        if col == "i_class":
            vals = [f"class{j:02d}" for j in range(16)]
            d = Dictionary(vals)
            return d.encode(
                [vals[int(x)] for x in _uniform(table, col, keys, 0, 15)]
            ), d
        if col == "i_current_price":
            return _uniform(table, col, keys, 99, 9999), None
    if table == "store":
        if col == "s_store_sk":
            return keys + 1, None
        if col == "s_store_id":
            d = _id_dict("store", "AAAAAAAA", 4, row_count("store", sf))
            return d.encode([f"AAAAAAAA{int(k):04d}" for k in keys]), d
        if col == "s_store_name":
            d = _name_dict("store", 64)
            return _uniform(table, col, keys, 0, len(d) - 1).astype(np.int32), d
        if col == "s_state":
            d = Dictionary(STATES)
            return d.encode(
                [STATES[int(x)] for x in _uniform(table, col, keys, 0, len(STATES) - 1)]
            ), d
        if col == "s_gmt_offset":
            return np.full(n, -500, dtype=np.int64), None  # -5.00
    if table == "customer":
        if col == "c_customer_sk":
            return keys + 1, None
        if col == "c_customer_id":
            # table-stable dictionary (plan-time binding sees the same
            # dictionary every batch)
            d = _id_dict("customer", "CUST", 10, row_count("customer", sf))
            return d.encode([f"CUST{int(k):010d}" for k in keys]), d
        if col in ("c_first_name", "c_last_name"):
            d = _name_dict(col, 1000)
            return _uniform(table, col, keys, 0, len(d) - 1).astype(np.int32), d
        if col == "c_birth_year":
            return _uniform(table, col, keys, 1930, 1995), None
    if table == "promotion":
        if col == "p_promo_sk":
            return keys + 1, None
        if col == "p_promo_id":
            d = _id_dict("promotion", "PROMO", 6, row_count("promotion", sf))
            return d.encode([f"PROMO{int(k):06d}" for k in keys]), d
        if col in ("p_channel_email", "p_channel_event"):
            d = Dictionary(["N", "Y"])
            return _uniform(table, col, keys, 0, 1).astype(np.int32), d
    if table == "store_sales":
        if col == "ss_sold_date_sk":
            return DATE_SK0 + _uniform(table, col, keys, 0, DATE_ROWS - 1), None
        if col == "ss_sold_time_sk":
            return _uniform(table, col, keys, 1, row_count("time_dim", sf)), None
        if col == "ss_item_sk":
            return _uniform(table, col, keys, 1, row_count("item", sf)), None
        if col == "ss_customer_sk":
            return _uniform(table, col, keys, 1, row_count("customer", sf)), None
        if col == "ss_cdemo_sk":
            return _uniform(table, col, keys, 1, row_count("customer_demographics", sf)), None
        if col == "ss_hdemo_sk":
            return _uniform(table, col, keys, 1, row_count("household_demographics", sf)), None
        if col == "ss_store_sk":
            return _uniform(table, col, keys, 1, row_count("store", sf)), None
        if col == "ss_promo_sk":
            return _uniform(table, col, keys, 1, row_count("promotion", sf)), None
        if col == "ss_quantity":
            return _uniform(table, col, keys, 1, 100), None
        if col == "ss_list_price":
            return _uniform(table, col, keys, 100, 30000), None
        if col == "ss_coupon_amt":
            amt = _uniform(table, col, keys, 0, 5000)
            return np.where(amt < 4000, 0, amt), None
        if col == "ss_sales_price":
            return _uniform(table, col, keys, 10, 20000), None
        if col == "ss_ext_sales_price":
            price = _uniform(table, "ss_sales_price", keys, 10, 20000)
            qty = _uniform(table, "ss_quantity", keys, 1, 100)
            return price * qty, None
        if col == "ss_net_profit":
            return _uniform(table, col, keys, -100000, 150000), None
    raise KeyError(f"{table}.{col}")


# ---------------------------------------------------------------------------
# connector SPI
# ---------------------------------------------------------------------------

SCHEMAS = {"tiny": 0.01, "sf1": 1.0, "sf10": 10.0}


def _schema_sf(schema: str) -> Optional[float]:
    if schema in SCHEMAS:
        return SCHEMAS[schema]
    if schema.startswith("sf"):
        try:
            return float(schema[2:])
        except ValueError:
            return None
    return None


class TpcdsMetadata(ConnectorMetadata):
    def list_schemas(self) -> List[str]:
        return sorted(SCHEMAS)

    def list_tables(self, schema: str) -> List[str]:
        return sorted(TABLES)

    def get_table_handle(self, schema: str, table: str) -> Optional[TableHandle]:
        sf = _schema_sf(schema)
        if sf is None or table not in TABLES:
            return None
        return TableHandle("tpcds", schema, table, payload=sf)

    def get_table_metadata(self, handle: TableHandle) -> TableMetadata:
        cols = tuple(ColumnMetadata(n, t) for n, t in TABLES[handle.table])
        return TableMetadata(handle.schema, handle.table, cols)

    def column_dictionary(self, handle: TableHandle, column: str) -> Optional[Dictionary]:
        typ = dict(TABLES[handle.table])[column]
        if not typ.is_string:
            return None
        _, d = generate_column(handle.table, column, handle.payload, 0, 1)
        return d

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        sf = handle.payload
        rows = float(row_count(handle.table, sf))
        cols = {}
        key_col = {
            "date_dim": "d_date_sk", "item": "i_item_sk", "store": "s_store_sk",
            "customer": "c_customer_sk", "promotion": "p_promo_sk",
            "warehouse": "w_warehouse_sk", "time_dim": "t_time_sk",
            "customer_demographics": "cd_demo_sk",
            "household_demographics": "hd_demo_sk",
        }.get(handle.table)
        if key_col:
            cols[key_col] = (rows, 0.0, 1.0, rows)
        if handle.table == "store_sales":
            cols = {
                "ss_sold_date_sk": (float(DATE_ROWS), 0.0, DATE_SK0, DATE_SK0 + DATE_ROWS - 1),
                "ss_item_sk": (float(row_count("item", sf)), 0.0, 1, row_count("item", sf)),
                "ss_customer_sk": (float(row_count("customer", sf)), 0.0, 1, row_count("customer", sf)),
                "ss_store_sk": (float(row_count("store", sf)), 0.0, 1, row_count("store", sf)),
                "ss_quantity": (100.0, 0.0, 1, 100),
            }
        elif handle.table == "date_dim":
            cols["d_year"] = (5.0, 0.0, 1998, 2002)
            cols["d_moy"] = (12.0, 0.0, 1, 12)
        return TableStatistics(row_count=rows, columns=cols)


class TpcdsSplitManager(ConnectorSplitManager):
    def get_splits(self, handle: TableHandle, target_split_count: int) -> List[Split]:
        base = row_count(handle.table, handle.payload)
        n = max(1, min(target_split_count, base))
        per = -(-base // n)
        return [
            Split(handle, s, (a, min(a + per, base)))
            for s, a in enumerate(range(0, base, per))
        ]


class TpcdsPageSource(ConnectorPageSource):
    def batches(self, split: Split, columns: Sequence[str], batch_rows: int,
                stabilizer=None) -> Iterator[RelBatch]:
        table = split.table.table
        sf = split.table.payload
        lo, hi = split.row_range
        types = dict(TABLES[table])
        for a in range(lo, hi, batch_rows):
            b = min(a + batch_rows, hi)
            cap = (stabilizer.chunk_capacity(b - a) if stabilizer is not None
                   else bucket_capacity(b - a))
            cols = []
            for name in columns:
                data, d = generate_column(table, name, sf, a, b)
                cols.append(
                    Column.from_numpy(types[name], data, None, d, capacity=cap)
                )
            live = None
            if (b - a) != cap:
                import jax.numpy as jnp

                lv = np.zeros(cap, dtype=bool)
                lv[: b - a] = True
                live = jnp.asarray(lv)
            yield RelBatch(cols, live)


def create_tpcds_connector() -> Connector:
    return Connector(
        name="tpcds",
        metadata=TpcdsMetadata(),
        split_manager=TpcdsSplitManager(),
        page_source=TpcdsPageSource(),
    )
