"""In-memory connector.

Analogue of plugin/trino-memory (MemoryPagesStore — SURVEY.md §2.12):
tables live as lists of host-side column arrays; supports CREATE TABLE,
INSERT (page sink), and scan. String columns keep one growing
table-wide dictionary so scans stay pipeline-bindable (see spi.py).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.block import Column, Dictionary, RelBatch, bucket_capacity
from trino_tpu.connectors.spi import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSource,
    ConnectorPageSink,
    ConnectorSplitManager,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)


@dataclasses.dataclass
class _StoredColumn:
    type: T.DataType
    data: np.ndarray  # host array, dense (no padding)
    valid: Optional[np.ndarray]
    dictionary: Optional[Dictionary]


@dataclasses.dataclass
class _StoredTable:
    schema: str
    name: str
    columns: List[ColumnMetadata]
    data: Dict[str, _StoredColumn] = dataclasses.field(default_factory=dict)
    row_count: int = 0
    version: int = 0  # bumped on writes; invalidates the device cache
    # device-resident batch cache: the Page/Block layer as persistent SoA
    # device arrays (SURVEY.md §2.5 "the layer that becomes TPU-resident")
    device_cache: Dict[tuple, list] = dataclasses.field(default_factory=dict)
    # declared bucketing: ordered key column names; splits are then 1:1
    # with engine-hash buckets (spi.ConnectorMetadata.table_partitioning)
    bucketed_by: Optional[Tuple[str, ...]] = None
    # (version, n_buckets) -> int32 bucket id per row
    bucket_cache: Dict[tuple, np.ndarray] = dataclasses.field(default_factory=dict)


class _Store:
    """The MemoryPagesStore analogue; guarded for concurrent inserts."""

    def __init__(self):
        self.tables: Dict[Tuple[str, str], _StoredTable] = {}
        self.lock = named_lock("_Store.lock")
        self._ids = itertools.count()


class MemoryMetadata(ConnectorMetadata):
    def __init__(self, store: _Store):
        self.store = store
        # (schema, table) -> (stored-table obj, version, TableStatistics)
        self._stats_cache: Dict[Tuple[str, str], tuple] = {}

    def list_schemas(self) -> List[str]:
        return sorted({s for s, _ in self.store.tables} | {"default"})

    def list_tables(self, schema: str) -> List[str]:
        return sorted(n for s, n in self.store.tables if s == schema)

    def get_table_handle(self, schema: str, table: str) -> Optional[TableHandle]:
        if (schema, table) not in self.store.tables:
            return None
        return TableHandle("memory", schema, table)

    def get_table_metadata(self, handle: TableHandle) -> TableMetadata:
        t = self.store.tables[(handle.schema, handle.table)]
        return TableMetadata(handle.schema, handle.table, tuple(t.columns))

    def column_dictionary(self, handle: TableHandle, column: str) -> Optional[Dictionary]:
        t = self.store.tables[(handle.schema, handle.table)]
        sc = t.data.get(column)
        return sc.dictionary if sc is not None else None

    def table_partitioning(self, handle: TableHandle):
        t = self.store.tables[(handle.schema, handle.table)]
        return t.bucketed_by

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        """Row count + sampled per-column (ndv, null_fraction, min, max).

        The reference's memory connector reports only row counts
        (MemoryMetadata.getTableStatistics), which starves the CBO: join
        orientation then rides on guessed NDVs, and a wrong guess builds
        the lookup on the BIG side (measured: TPC-H Q3 built on lineitem
        instead of orders x customer). We hold the actual arrays, so
        estimate honestly: stride-sample up to 256k rows, Duj1-estimate
        NDV from sample singletons, exact min/max. Cached per table
        version (writes invalidate)."""
        t = self.store.tables[(handle.schema, handle.table)]
        key = (handle.schema, handle.table)
        cached = self._stats_cache.get(key)
        if cached is not None and cached[0] is t and cached[1] == t.version:
            return cached[2]
        cols: Dict[str, tuple] = {}
        n = t.row_count
        for name, sc in t.data.items():
            if n == 0 or isinstance(sc.data, list):  # empty or ARRAY column
                continue
            arr = sc.data[:n]
            nf = 0.0
            if sc.valid is not None:
                nf = float(1.0 - np.count_nonzero(sc.valid[:n]) / n)
                # null slots hold placeholder payloads (the page sink keeps
                # whatever bytes the source batch had) — they must not leak
                # into ndv/min/max
                arr = arr[sc.valid[:n]]
                if len(arr) == 0:
                    cols[name] = (0.0, nf, None, None)
                    continue
            pop = len(arr)  # non-null population
            sample = arr[:: max(1, pop // 262144)]
            s = len(sample)
            vals, counts = np.unique(sample, return_counts=True)
            d = float(len(vals))
            f1 = float(np.count_nonzero(counts == 1))
            # Duj1: ndv = d / (1 - ((pop-s)/pop) * (f1/s)) — all-singleton
            # samples extrapolate to ~pop, saturated samples stay at d
            denom = 1.0 - ((pop - s) / pop) * (f1 / max(s, 1))
            ndv = min(d / max(denom, 1e-9), float(pop))
            lo = hi = None
            if not sc.type.is_string and arr.dtype.kind in "iuf":
                lo, hi = float(arr.min()), float(arr.max())
            cols[name] = (ndv, nf, lo, hi)
        ts = TableStatistics(row_count=float(n), columns=cols)
        self._stats_cache[key] = (t, t.version, ts)
        return ts

    def apply_filter(self, handle: TableHandle, constraints):
        """Accept constraints on flat numeric/temporal columns; the page
        source masks the stored arrays before materializing device
        batches (exact enforcement, composed with bucket splits)."""
        from trino_tpu.connectors.pushdown import (
            merge_handle_constraints,
            split_supported,
        )

        t = self.store.tables[(handle.schema, handle.table)]
        types = {c.name: c.type for c in t.columns}
        accepted, residual = split_supported(constraints, types.get)
        if not accepted:
            return None
        return merge_handle_constraints(handle, accepted), tuple(residual)

    def apply_projection(self, handle: TableHandle, columns) -> TableHandle:
        # _materialize already builds only the requested columns
        return handle

    def create_table(self, schema: str, table: str, columns: Sequence[ColumnMetadata]) -> TableHandle:
        with self.store.lock:
            if (schema, table) in self.store.tables:
                raise ValueError(f"table '{schema}.{table}' already exists")
            st = _StoredTable(schema, table, list(columns))
            for c in columns:
                if c.type.kind == T.TypeKind.ARRAY:
                    st.data[c.name] = _StoredColumn(
                        c.type, [], None,
                        Dictionary([]) if c.type.element.is_string else None,
                    )
                    continue
                if c.type.is_nested:  # MAP / ROW: python-object storage
                    st.data[c.name] = _StoredColumn(c.type, [], None, None)
                    continue
                shape = (0, 2) if c.type.lanes == 2 else (0,)
                st.data[c.name] = _StoredColumn(
                    c.type,
                    np.zeros(shape, dtype=c.type.dtype),
                    None,
                    Dictionary([]) if c.type.is_string else None,
                )
            self.store.tables[(schema, table)] = st
        return TableHandle("memory", schema, table)

    def truncate_table(self, handle: TableHandle) -> None:
        with self.store.lock:
            t = self.store.tables[(handle.schema, handle.table)]
            for sc in t.data.values():
                sc.data = sc.data[:0]
                sc.valid = None
            t.row_count = 0
            t.version += 1

    def drop_table(self, handle: TableHandle) -> None:
        with self.store.lock:
            self.store.tables.pop((handle.schema, handle.table), None)
            # the stats cache pins the stored table (host arrays + the
            # device-resident batch cache); a dropped table must free both
            self._stats_cache.pop((handle.schema, handle.table), None)


class MemorySplitManager(ConnectorSplitManager):
    def __init__(self, store: _Store):
        self.store = store

    def get_splits(self, handle: TableHandle, target_split_count: int) -> List[Split]:
        t = self.store.tables[(handle.schema, handle.table)]
        n = t.row_count
        if t.bucketed_by and target_split_count > 1:
            # bucketed table: EXACTLY the requested count, split i = the
            # rows whose engine key-hash lands in partition i of k. The
            # scheduler's task p <- splits[p::tc] rule then puts bucket i
            # on task i, which is what the planner's cancelled exchange
            # assumed (spi.ConnectorMetadata.table_partitioning). A
            # single-task request skips the hash: one full row-range
            # split IS the 1-bucket partitioning
            return [
                Split(handle, i, None, ("bucket", i, target_split_count))
                for i in range(target_split_count)
            ]
        k = max(1, min(target_split_count, max(n, 1)))
        per = -(-max(n, 1) // k)
        return [
            Split(handle, s, (a, min(a + per, n)))
            for s, a in enumerate(range(0, max(n, 1), per))
        ]


class MemoryPageSource(ConnectorPageSource):
    def __init__(self, store: _Store):
        self.store = store

    def batches(self, split: Split, columns: Sequence[str], batch_rows: int,
                stabilizer=None) -> Iterator[RelBatch]:
        t = self.store.tables[(split.table.schema, split.table.table)]
        cs = getattr(split.table, "constraints", ())
        # the stabilizer changes batch capacities, so it must key the
        # device cache (sessions with different ladders cannot share)
        stab_sig = (
            (stabilizer.ladder.base, stabilizer.ladder.min_capacity)
            if stabilizer is not None else None
        )
        if split.payload is not None and split.payload[0] == "bucket":
            _, bi, nb = split.payload
            idx = np.nonzero(self._bucket_ids(t, nb) == bi)[0]
            lo = hi = None
            cache_key = (t.version, tuple(columns), batch_rows, "bucket", bi,
                         nb, cs, stab_sig)
        else:
            lo, hi = split.row_range
            idx = None
            cache_key = (t.version, tuple(columns), batch_rows, lo, hi, cs,
                         stab_sig)
        cached = t.device_cache.get(cache_key)
        if cached is not None:
            yield from cached
            return
        if cs and t.row_count:
            # pushed-down predicate: mask the stored arrays, then route
            # the surviving row indices through the gather path (the
            # same one bucket splits use)
            from trino_tpu.connectors.pushdown import constraint_mask

            n = t.row_count
            mask = constraint_mask(
                cs,
                lambda name: (
                    np.asarray(t.data[name].data[:n]),
                    None if t.data[name].valid is None
                    else t.data[name].valid[:n],
                ),
            )
            if idx is None:
                idx = np.nonzero(mask[lo:hi])[0] + lo
                lo = hi = None
            else:
                idx = idx[mask[idx]]
        out = []
        for batch in self._materialize(t, columns, batch_rows, lo, hi, idx,
                                       stabilizer=stabilizer):
            out.append(batch)
            yield batch
        for k in [k for k in t.device_cache if k[0] != t.version]:
            # pop, not del: parallel tasks snapshot the same stale keys
            t.device_cache.pop(k, None)
        t.device_cache[cache_key] = out

    def _bucket_ids(self, t, nb: int) -> np.ndarray:
        """Row -> bucket id with the engine's own exchange hash (the
        lock-step host replica, ops/hashing.hash32_np), so a split of a
        bucketed table holds exactly the rows a runtime repartition on
        the same keys would have routed to that partition. Cached per
        (table version, bucket count)."""
        key = (t.version, nb)
        got = t.bucket_cache.get(key)
        if got is not None:
            return got
        from trino_tpu.ops.hashing import (
            dictionary_lut, hash32_np, partition_of_np,
        )

        n = t.row_count
        lanes, valids = [], []
        for name in t.bucketed_by:
            sc = t.data[name]
            lut = dictionary_lut(sc.dictionary)
            if lut is not None:
                codes = np.clip(np.asarray(sc.data[:n]), 0, len(lut) - 1)
                lanes.append(lut[codes.astype(np.int64)])
            else:
                lanes.append(np.asarray(sc.data[:n]).astype(np.int64))
            valids.append(None if sc.valid is None else sc.valid[:n])
        bids = partition_of_np(hash32_np(lanes, valids), nb)
        for k in [k for k in t.bucket_cache if k[0] != t.version]:
            # pop, not del: parallel tasks snapshot the same stale keys
            t.bucket_cache.pop(k, None)
        t.bucket_cache[key] = bids
        return bids

    def _materialize(self, t, columns: Sequence[str], batch_rows: int,
                     lo, hi, idx: Optional[np.ndarray] = None,
                     stabilizer=None) -> Iterator[RelBatch]:
        """Chunk either a contiguous [lo, hi) row range (plain splits —
        ndarray slicing, one memcpy per column) or an explicit row-index
        array (bucket splits — gathered copy)."""
        from trino_tpu.block import ArrayColumn

        if idx is None:
            total = hi - lo
            sels = (slice(a, min(a + batch_rows, hi))
                    for a in range(lo, hi, batch_rows))
        else:
            total = len(idx)
            sels = (idx[a: a + batch_rows]
                    for a in range(0, total, batch_rows))
        for sel in sels:
            ranged = isinstance(sel, slice)
            n = (sel.stop - sel.start) if ranged else len(sel)
            if stabilizer is None:
                cap = bucket_capacity(n)
            elif ranged:
                # contiguous chunks are unpruned: the slice length IS
                # the span, so main/tail classes match the census
                cap = stabilizer.chunk_capacity(n)
            else:
                # index-gathered chunks (pushdown-pruned rows, bucket
                # splits) have data-dependent sizes; pad to the table's
                # main scan class so pruning never mints a new lowering
                cap = stabilizer.chunk_capacity(min(t.row_count, batch_rows))
            cols = []
            for name in columns:
                sc = t.data[name]
                if sc.type.kind == T.TypeKind.ARRAY:
                    # array columns store python lists host-side; the
                    # batch view flattens the slice (ArrayBlock layout)
                    rows = (list(sc.data[sel]) if ranged
                            else [sc.data[j] for j in sel])
                    cols.append(ArrayColumn.from_pylists(
                        sc.type.element, rows + [None] * (cap - n),
                        capacity=cap, dictionary=sc.dictionary,
                    ))
                    continue
                if sc.type.is_nested:  # MAP / ROW
                    rows = (list(sc.data[sel]) if ranged
                            else [sc.data[j] for j in sel])
                    cols.append(Column.from_pylist(
                        sc.type, rows, capacity=cap,
                    ))
                    continue
                shape = (cap, 2) if sc.type.lanes == 2 else (cap,)
                arr = np.zeros(shape, dtype=sc.type.dtype)
                arr[:n] = sc.data[sel]
                valid = None
                if sc.valid is not None:
                    v = np.zeros(cap, dtype=bool)
                    v[:n] = sc.valid[sel]
                    valid = jnp.asarray(v)
                cols.append(Column(sc.type, jnp.asarray(arr), valid, sc.dictionary))
            live = None
            if n != cap:
                lv = np.zeros(cap, dtype=bool)
                lv[:n] = True
                live = jnp.asarray(lv)
            yield RelBatch(cols, live)
        if total == 0:  # empty split: one empty batch so schemas propagate
            cols = []
            for name in columns:
                sc = t.data[name]
                if sc.type.kind == T.TypeKind.ARRAY:
                    cols.append(ArrayColumn.from_pylists(
                        sc.type.element, [None] * 16, capacity=16,
                        dictionary=sc.dictionary,
                    ))
                    continue
                if sc.type.is_nested:  # MAP / ROW
                    cols.append(Column.from_pylist(
                        sc.type, [None] * 16, capacity=16,
                    ))
                    continue
                from trino_tpu.block import phys_zeros

                cols.append(Column(
                    sc.type, phys_zeros(sc.type, 16),
                    None, sc.dictionary,
                ))
            yield RelBatch(cols, jnp.zeros(16, dtype=jnp.bool_))


class MemoryPageSink(ConnectorPageSink):
    """Appends batches; string columns re-encode into the table's growing
    dictionary (unify) so the table dictionary stays authoritative."""

    def __init__(self, store: _Store, handle: TableHandle):
        self.store = store
        self.handle = handle
        self.rows = 0

    def append(self, batch: RelBatch) -> None:
        from trino_tpu.block import ArrayColumn

        key = (self.handle.schema, self.handle.table)
        live = np.asarray(batch.live_mask())
        with self.store.lock:
            t = self.store.tables[key]
            n = int(live.sum())
            for cm, col in zip(t.columns, batch.columns):
                sc = t.data[cm.name]
                if cm.type.kind == T.TypeKind.ARRAY:
                    if not isinstance(col, ArrayColumn):
                        raise TypeError(
                            f"column {cm.name}: expected ARRAY data"
                        )
                    # decode to the host list-of-lists store (and fold
                    # string elements into the table dictionary)
                    rows = [
                        r for r, k in zip(col.to_pylist(), live) if k
                    ]
                    if cm.type.element.is_string:
                        merged = Dictionary(
                            (sc.dictionary.values if sc.dictionary else ())
                            + tuple(
                                v for r in rows if r is not None
                                for v in r if v is not None
                            )
                        )
                        sc.dictionary = merged
                    sc.data = list(sc.data) + rows
                    continue
                data = np.asarray(col.data)[live]
                valid = np.asarray(col.valid)[live] if col.valid is not None else None
                if cm.type.is_string:
                    incoming = col.dictionary or Dictionary([])
                    merged, remap_old, remap_new = Dictionary.unify(sc.dictionary, incoming)
                    if len(remap_old):
                        sc.data = remap_old[sc.data] if len(sc.data) else sc.data
                    data = remap_new[np.clip(data, 0, max(len(incoming) - 1, 0))] if len(incoming) else data
                    sc.dictionary = merged
                    # back-patch: table dictionary object changes identity;
                    # readers pick up the new one on next scan
                sc.data = np.concatenate([sc.data, data.astype(sc.type.dtype)])
                if valid is not None or sc.valid is not None:
                    old_valid = (
                        sc.valid if sc.valid is not None
                        else np.ones(t.row_count, dtype=bool)
                    )
                    new_valid = valid if valid is not None else np.ones(n, dtype=bool)
                    sc.valid = np.concatenate([old_valid, new_valid])
            t.row_count += n
            t.version += 1
            self.rows += n

    def finish(self) -> int:
        return self.rows


class MemoryTransactionHandle:
    """Buffers writes until commit (read-committed: in-transaction
    scans do NOT see the transaction's own pending writes — a
    documented simplification; the reference's memory connector has no
    cross-statement write transactions at all)."""

    def __init__(self, store: _Store):
        self.store = store
        self._pending: List[tuple] = []  # (handle, batch)

    def stage(self, handle: TableHandle, batch: RelBatch) -> None:
        self._pending.append((handle, batch))

    def commit(self) -> None:
        for handle, batch in self._pending:
            MemoryPageSink(self.store, handle).append(batch)
        self._pending.clear()

    def rollback(self) -> None:
        self._pending.clear()


class _TransactionalMemorySink(ConnectorPageSink):
    def __init__(self, txn: MemoryTransactionHandle, handle: TableHandle):
        self.txn = txn
        self.handle = handle
        self.rows = 0

    def append(self, batch: RelBatch) -> None:
        self.txn.stage(self.handle, batch)
        import jax

        self.rows += int(jax.device_get(batch.live_mask()).sum())

    def finish(self) -> int:
        return self.rows  # publish happens at transaction commit


class MemoryConnector(Connector):
    def __init__(self):
        store = _Store()
        super().__init__(
            "memory",
            MemoryMetadata(store),
            MemorySplitManager(store),
            MemoryPageSource(store),
        )
        self.store = store

    def begin_transaction(self, read_only: bool = False):
        return MemoryTransactionHandle(self.store)

    def replace_rows(self, handle: TableHandle, batches) -> None:
        """Atomically replace the table's rows with `batches` (the
        DELETE/UPDATE rewrite commit): stage into a detached copy of
        the table, then swap under the store lock — a mid-stage failure
        leaves the original untouched."""
        key = (handle.schema, handle.table)
        with self.store.lock:
            t = self.store.tables[key]
            staging = _StoredTable(t.schema, t.name, list(t.columns))
            for cm in t.columns:
                src = t.data[cm.name]
                staging.data[cm.name] = _StoredColumn(
                    cm.type,
                    src.data[:0],
                    None,
                    src.dictionary,  # keep the table dictionary stable
                )
        staging_store = _Store()
        staging_store.tables[key] = staging
        sink = MemoryPageSink(staging_store, handle)
        for b in batches:
            sink.append(b)
        with self.store.lock:
            t = self.store.tables.get(key)
            if t is None:
                raise KeyError(f"table {key} dropped during rewrite")
            t.data = staging.data
            t.row_count = staging.row_count
            t.version += 1
            t.device_cache.clear()

    def page_sink(self, handle: TableHandle, transaction=None) -> ConnectorPageSink:
        if isinstance(transaction, MemoryTransactionHandle):
            return _TransactionalMemorySink(transaction, handle)
        return MemoryPageSink(self.store, handle)

    def load_table(
        self,
        schema: str,
        table: str,
        columns: Sequence[ColumnMetadata],
        arrays: Sequence[np.ndarray],
        valids: Sequence[Optional[np.ndarray]] = None,
        dictionaries: Sequence[Optional[Dictionary]] = None,
        bucketed_by: Optional[Sequence[str]] = None,
    ) -> None:
        """Bulk-load dense host columns (benchmark/fixture path).
        `bucketed_by` declares engine-hash bucketing on the named key
        columns (integer-family or dictionary-string types): splits then
        become hash buckets and co-bucketed joins/aggregations plan
        exchange-free (spi.ConnectorMetadata.table_partitioning)."""
        handle = self.metadata.create_table(schema, table, columns)
        t = self.store.tables[(schema, table)]
        if bucketed_by:
            by_name = {cm.name: cm for cm in columns}
            for c in bucketed_by:
                cm = by_name.get(c)
                if cm is None:
                    raise ValueError(f"bucketed_by column {c!r} not in table")
                ok = cm.type.is_string or (
                    not cm.type.is_nested
                    and cm.type.kind != T.TypeKind.ARRAY
                    and cm.type.lanes == 1
                    and np.issubdtype(np.dtype(cm.type.dtype), np.integer)
                )
                if not ok:
                    # float keys need the 3-lane f64 decomposition and
                    # long decimals the 4-lane limb split; neither has a
                    # host-side replica yet
                    raise ValueError(
                        f"bucketed_by column {c!r}: only integer-family "
                        f"and string types can declare bucketing"
                    )
            t.bucketed_by = tuple(bucketed_by)
        n = len(arrays[0]) if arrays else 0
        for i, (cm, arr) in enumerate(zip(columns, arrays)):
            if cm.type.kind == T.TypeKind.ARRAY:
                # python list-of-lists storage; strings get one
                # table-stable element dictionary
                d = None
                if cm.type.element.is_string:
                    d = Dictionary([
                        v for row in arr if row is not None
                        for v in row if v is not None
                    ])
                t.data[cm.name] = _StoredColumn(cm.type, list(arr), None, d)
                continue
            if cm.type.is_nested:  # MAP / ROW: python-object storage
                t.data[cm.name] = _StoredColumn(cm.type, list(arr), None, None)
                continue
            d = dictionaries[i] if dictionaries else None
            if cm.type.is_string and d is None:
                # convenience: raw python strings -> dictionary + codes
                vals = list(arr)
                d = Dictionary([v for v in vals if v is not None])
                arr = np.asarray(
                    [d.code(v) if v is not None else 0 for v in vals],
                    dtype=np.int32,
                )
            t.data[cm.name] = _StoredColumn(
                cm.type,
                np.asarray(arr, dtype=cm.type.dtype),
                valids[i] if valids else None,
                d if d is not None else (
                    Dictionary([]) if cm.type.is_string else None
                ),
            )
        t.row_count = n
        t.version += 1


def create_memory_connector() -> Connector:
    return MemoryConnector()
