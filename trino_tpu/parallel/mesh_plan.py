"""Mesh-resident distributed execution: ICI collectives as the SQL data plane.

The reference's distributed data plane is HTTP page streams between
worker JVMs, stitched by AddExchanges-inserted REMOTE exchanges
(optimizations/AddExchanges.java:266-276) and PartitionedOutputOperator
(output/PartitionedOutputOperator.java:46). The TPU-native form of the
same plan is ONE SPMD program over a `jax.sharding.Mesh`:

- every fragment's operator pipeline becomes a per-shard traced function
  over a fixed-capacity local RelBatch;
- a FIXED_HASH exchange between fragments becomes an on-device hash
  partition + `lax.all_to_all` over the mesh axis (ICI);
- a FIXED_BROADCAST exchange becomes `lax.all_gather`;
- the final gather boundary ships per-shard results to the host, where
  the root (single-partition) fragment runs through the ordinary local
  operator pipeline (merge-sorting RemoteSource included).

The compiler consumes the SAME SubPlan the HTTP scheduler would run
(sql/fragmenter.plan_distributed), so planning decisions — partial/final
aggregation, broadcast-vs-partitioned joins, merge exchanges, adaptive
partition counts — are shared between both data planes; only the
transport differs. Mesh execution is selected when all tasks would be
colocated on one host's device mesh (in-process workers); cross-host /
elastic / FTE execution keeps the pull+ack HTTP exchange.

Static-shape discipline: per-shard batch capacities are fixed at trace
time; group tables and join fan-out use host-chosen capacities with
device overflow flags and a double-and-retrace protocol (the tryRehash
analogue). An all_to_all send block equals the sender's batch capacity,
so exchange overflow is impossible by construction.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PSpec

from trino_tpu.analysis.witness import named_lock
from trino_tpu.jaxcfg import get_shard_map

shard_map = get_shard_map()

from trino_tpu import types as T
from trino_tpu.block import (
    Column,
    RelBatch,
    bucket_capacity,
    concat_batches,
    unify_column_dicts,
)
from trino_tpu.exec.operators import (
    _BATCH_REDUCER,
    AggSpec,
    _agg_output,
    _agg_slot_count,
    _append_long_decimal_slots,
    _expand_pairs,
    _left_unmatched,
    _lex128_reduce,
    _limb_join,
    _limb_split,
    _right_unmatched,
    _segment_any,
    _slot_merge_reducers,
    _slots_to_state,
    _slots_to_wire_column,
    agg_state_meta,
    make_filter_project_fn,
    make_residual_fn,
)
from trino_tpu.exec.serde import Page
from trino_tpu.expr.compile import ExprBinder
from trino_tpu.ops import groupby as G
from trino_tpu.ops import join as J
from trino_tpu.ops.gather import take_clip
from trino_tpu.ops.hashing import (
    canonical_hash_input,
    dictionary_lut,
    hash32,
    partition_of,
)
from trino_tpu.ops.sort import sort_order
from trino_tpu.sql import plan as P
from trino_tpu.sql.fragmenter import SubPlan

AXIS = "shard"

# Second named axis of the replicated serving plane: the full device
# set carves into a (replica x partition) grid (runtime/replicas.py)
# whose rows are identical 1-D sub-meshes over AXIS. Sub-mesh programs
# never reference REPLICA_AXIS — that is the point: the SAME
# prelude/step/flush lowerings serve any replica unchanged.
REPLICA_AXIS = "replica"

# Trace-time counters, monotonically increasing for the process life
# (capacity-overflow retraces count again). Tests must assert on
# before/after deltas, never absolute values.  `+=` on a dict slot is a
# non-atomic read-modify-write, and these fire from concurrent query
# threads — all bumps go through bump_mesh_counter.
_counters_lock = named_lock("mesh_plan._counters_lock")
MESH_COUNTERS = {"queries": 0, "all_to_all": 0, "all_gather": 0, "fallbacks": 0}  # guarded_by: _counters_lock

_METRICS_REGISTERED = False


def bump_mesh_counter(name: str, n: int = 1) -> None:
    with _counters_lock:
        MESH_COUNTERS[name] += n


def mesh_counter(name: str) -> int:
    with _counters_lock:
        return MESH_COUNTERS[name]


def mesh_counters_snapshot() -> dict:
    with _counters_lock:
        return dict(MESH_COUNTERS)


def register_mesh_metrics() -> None:
    """Expose MESH_COUNTERS as mesh_* gauges in the METRICS registry
    (and so in /v1/metrics). Idempotent; gauges read live at snapshot
    time, so the export tracks the trace-time counters for free."""
    global _METRICS_REGISTERED
    if _METRICS_REGISTERED:
        return
    from trino_tpu.runtime.metrics import METRICS

    for name in mesh_counters_snapshot():
        METRICS.register_gauge(
            f"mesh_{name}", lambda n=name: float(mesh_counter(n))
        )
    _METRICS_REGISTERED = True


class MeshUnsupported(Exception):
    """Plan shape the mesh compiler cannot run; the coordinator falls
    back to the host page-exchange data plane."""


# ---------------------------------------------------------------------------
# Eligibility
# ---------------------------------------------------------------------------


def _check_node(n: P.PlanNode) -> None:
    if isinstance(n, P.OutputNode):
        raise MeshUnsupported(type(n).__name__)
    if isinstance(n, P.WindowNode) and not n.partition_channels:
        # PARTITION BY-less windows are one global partition; the
        # fragmenter gathers them to the root, so a distributed one
        # reaching here is a plan bug — fall back loudly
        raise MeshUnsupported("window without partition keys")
    if isinstance(n, P.AggregateNode):
        for a in n.aggs:
            if a.distinct or a.kind not in _BATCH_REDUCER:
                raise MeshUnsupported(f"agg {a.kind}")
    if isinstance(n, P.JoinNode) and n.kind not in (
        "inner", "left", "full", "semi", "anti", "cross",
        "mark", "mark_exists",
    ):
        raise MeshUnsupported(f"join {n.kind}")
    for c in n.children():
        _check_node(c)


def _scan_nodes(n: P.PlanNode) -> List[P.ScanNode]:
    out = []
    if isinstance(n, P.ScanNode):
        out.append(n)
    for c in n.children():
        out.extend(_scan_nodes(c))
    return out


def _contains_scan(n: P.PlanNode) -> bool:
    return bool(_scan_nodes(n))


# ---------------------------------------------------------------------------
# In-trace exchange primitives
# ---------------------------------------------------------------------------


def _partition_ids(batch: RelBatch, channels: Sequence[int], n: int):
    """Row -> destination shard by canonicalized key hash (dictionary
    codes mapped through value-hash LUTs so co-partitioned producers
    agree — the exchange_ops._partition_ids contract). Dead rows -> -1."""
    lanes, valids = [], []
    for ch in channels:
        col = batch.columns[ch]
        lut = dictionary_lut(col.dictionary)
        if lut is not None:
            lanes.append(canonical_hash_input(col.data, jnp.asarray(lut)))
        else:
            lanes.append(canonical_hash_input(col.data))
        valids.append(col.valid_mask())
    pid = partition_of(hash32(lanes, valids), n)
    return jnp.where(batch.live_mask(), pid, -1)


def _scatter_to_blocks(arrays, live, pid, n: int, block: int):
    """Scatter local rows into (n, block) destination blocks (the
    PagePartitioner analogue, on device). pid < 0 drops the row. With
    block == batch capacity overflow is impossible."""
    tgt = jnp.where(pid < 0, n, pid).astype(jnp.int32)
    order = jnp.argsort(tgt, stable=True)
    st = take_clip(tgt, order)
    idx = jnp.arange(st.shape[0], dtype=jnp.int32)
    dest_start = jnp.searchsorted(st, jnp.arange(n, dtype=jnp.int32))
    slot = idx - take_clip(dest_start, jnp.clip(st, 0, n - 1))
    flat = jnp.where(
        st < n,
        jnp.clip(st, 0, n - 1) * block + jnp.clip(slot, 0, block - 1),
        n * block,
    )

    def scat(col):
        # trailing lanes (long-decimal (cap, 2) limb pairs) scatter
        # row-wise into (n, block, lanes) blocks
        tail = col.shape[1:]
        z = jnp.zeros((n * block + 1,) + tail, dtype=col.dtype)
        taken = take_clip(col, order, axis=0)
        return z.at[flat].set(taken, mode="drop")[:-1].reshape(
            (n, block) + tail
        )

    out = [scat(a) for a in arrays]
    live_b = scat(live)
    return out, live_b


def _exchange_with_pids(batch: RelBatch, pid, n: int) -> RelBatch:
    """Scatter + all_to_all with caller-supplied destination ids (the
    shared tail of the plain and salted hash exchanges)."""
    block = batch.capacity
    arrays = []
    for c in batch.columns:
        arrays.append(c.data)
        arrays.append(c.valid_mask())
    blocks, live_b = _scatter_to_blocks(arrays, batch.live_mask(), pid, n, block)
    bump_mesh_counter("all_to_all")
    ex = [jax.lax.all_to_all(b, AXIS, 0, 0, tiled=True) for b in blocks]
    live_ex = jax.lax.all_to_all(live_b, AXIS, 0, 0, tiled=True)
    cols = []
    for i, c in enumerate(batch.columns):
        d = ex[2 * i]
        # (n, block, lanes...) -> rows-major local layout
        d = d.reshape((-1,) + d.shape[2:])
        cols.append(Column(c.type, d, ex[2 * i + 1].reshape(-1), c.dictionary))
    return RelBatch(cols, live_ex.reshape(-1))


def _exchange_hash(batch: RelBatch, channels: Sequence[int], n: int) -> RelBatch:
    """FIXED_HASH remote exchange as partition + all_to_all over ICI."""
    return _exchange_with_pids(batch, _partition_ids(batch, channels, n), n)


# -- skew-aware salted repartition (ISSUE 16, the JSPIM playbook) ------
#
# A hash exchange serializes every row of one key onto one shard; with
# a heavy hitter that IS the wall-clock. The salted form keeps cold
# keys on the normal hash path and treats the adaptive controller's
# observed hot keys specially: hot BUILD rows are replicated to every
# shard (riding the same all_gather a FIXED_BROADCAST uses), hot PROBE
# rows are dealt round-robin across shards. Every probe row still
# appears on exactly one shard and finds ALL build rows of its key
# there, so inner/left/semi/anti verdicts and pair multiplicity are
# exact; full-outer and mark joins are excluded by the annotation gate
# (replicated build rows would be counted once per shard).


def _hot_mask(batch: RelBatch, channels: Sequence[int], hot_values) -> jnp.ndarray:
    """Live rows whose (single) key column holds a hot value. Guarded
    to plain integer columns: dictionary codes must never be compared
    against observed key VALUES, and both join sides share the key
    type, so the guard degrades both sides together (no salting, plain
    hash placement — correct, just not skew-resistant)."""
    col = batch.columns[channels[0]]
    if col.dictionary is not None or col.data.ndim != 1:
        return jnp.zeros((batch.capacity,), dtype=bool)
    hv = jnp.asarray(list(hot_values), dtype=col.data.dtype)
    eq = (col.data[:, None] == hv[None, :]).any(axis=1)
    return eq & col.valid_mask() & batch.live_mask()


def _salted_exchange_hash(
    batch: RelBatch, channels: Sequence[int], n: int, hot_values, role: str
) -> RelBatch:
    """Salted FIXED_HASH exchange for one side of a skew-annotated
    join. role="build": cold rows all_to_all as usual, hot rows
    all_gather to every shard (output capacity 2*n*cap). role="probe":
    hot rows' destination is overridden to a round-robin salt (offset
    by the shard index so shard locality doesn't re-converge on one
    destination); capacity unchanged."""
    hot = _hot_mask(batch, channels, hot_values)
    if role == "build":
        cold = batch.mask(~hot)
        out = _exchange_with_pids(
            cold, _partition_ids(cold, channels, n), n
        )
        return concat_batches((out, _replicate(batch.mask(hot))))
    pid = _partition_ids(batch, channels, n)
    me = jax.lax.axis_index(AXIS).astype(jnp.int32)
    salt = (jnp.cumsum(hot.astype(jnp.int32)) - 1 + me) % n
    return _exchange_with_pids(
        batch, jnp.where(hot, salt.astype(pid.dtype), pid), n
    )


def _salted_local_partition(
    batch: RelBatch, channels: Sequence[int], n: int, hot_values, role: str
) -> RelBatch:
    """Salted hash output of a REPLICATED producer (every shard already
    holds all rows — the spool-substituted build side lands here).
    build: keep own partition plus every hot row (a zero-collective
    broadcast of the hot set). probe: deal each hot row to exactly one
    shard by its position — the batch is identical on every shard, so
    the deal is globally consistent without any collective."""
    pid = _partition_ids(batch, channels, n)
    me = jax.lax.axis_index(AXIS).astype(pid.dtype)
    hot = _hot_mask(batch, channels, hot_values)
    if role == "build":
        return batch.mask((pid == me) | hot)
    salt = (jnp.cumsum(hot.astype(jnp.int32)) - 1) % n
    return batch.mask(
        jnp.where(hot, salt == me.astype(jnp.int32), pid == me)
    )


def _replicate(batch: RelBatch) -> RelBatch:
    """FIXED_BROADCAST exchange as all_gather (every shard gets all rows)."""
    bump_mesh_counter("all_gather")

    def ag(x):
        return jax.lax.all_gather(x, AXIS, tiled=True)

    cols = [
        Column(c.type, ag(c.data), ag(c.valid_mask()), c.dictionary)
        for c in batch.columns
    ]
    return RelBatch(cols, ag(batch.live_mask()))


def _local_partition(batch: RelBatch, channels: Sequence[int], n: int) -> RelBatch:
    """Hash output of a REPLICATED producer: every shard already holds
    all rows, so each keeps only its own partition (no collective)."""
    pid = _partition_ids(batch, channels, n)
    me = jax.lax.axis_index(AXIS).astype(pid.dtype)
    return batch.mask(pid == me)


# ---------------------------------------------------------------------------
# Fragment-body compiler (runs at trace time, inside shard_map)
# ---------------------------------------------------------------------------


class _FragVisitor:
    """Compiles one fragment's plan tree into per-shard array math over
    the local RelBatch (the LocalExecutionPlanner analogue for the mesh
    data plane)."""

    def __init__(self, executor: "MeshExecutor", frag_id: int,
                 feeds: Dict[int, RelBatch], ctx: Dict[int, RelBatch],
                 caps: Dict[str, int], flags: List[Tuple[str, jnp.ndarray]]):
        self.ex = executor
        self.frag_id = frag_id
        self.feeds = feeds  # id(ScanNode) -> local RelBatch
        self.ctx = ctx  # fragment id -> post-exchange local RelBatch
        self.caps = caps
        self.flags = flags
        self._site_counter = 0

    def _site(self, kind: str) -> str:
        self._site_counter += 1
        return f"f{self.frag_id}:{kind}{self._site_counter}"

    def visit(self, node: P.PlanNode) -> RelBatch:
        m = getattr(self, f"_visit_{type(node).__name__}", None)
        if m is None:
            raise MeshUnsupported(type(node).__name__)
        return m(node)

    # -- leaves --
    def _visit_ScanNode(self, node):
        return self.feeds[id(node)]

    def _visit_ValuesNode(self, node):
        keys = [f.name or f"_c{i}" for i, f in enumerate(node.fields)]
        if len(set(keys)) != len(keys):
            # spooled join subtrees repeat column names (k, name, k,
            # name); a name-keyed dict would silently drop channels
            keys = [f"{k}_{i}" for i, k in enumerate(keys)]
        data = {k: [] for k in keys}
        for row in node.rows:
            for k, v in zip(keys, row):
                data[k].append(v)
        schema_t = [(k, f.type) for k, f in zip(keys, node.fields)]
        return RelBatch.from_pydict(schema_t, data)

    _visit_SpooledValuesNode = _visit_ValuesNode

    def _visit_RemoteSourceNode(self, node):
        parts = [self.ctx[fid] for fid in node.fragment_ids]
        out = parts[0] if len(parts) == 1 else concat_batches(parts)
        if node.merge_keys:
            # a merge-gather consumed mid-mesh arrives as an all_gather
            # of locally-sorted runs (shard-major, globally unsorted);
            # restore the global order with a full re-sort (the mesh form
            # of the MergeOperator)
            out = self._sorted(out, node.merge_keys)
        return out

    # -- row transforms --
    def _bind(self, e, batch: RelBatch):
        types = [c.type for c in batch.columns]
        dicts = [c.dictionary for c in batch.columns]
        return ExprBinder(types, dicts).bind(e)

    def _identity(self, batch: RelBatch):
        from trino_tpu.expr.ir import InputRef

        return [
            self._bind(InputRef(i, c.type), batch)
            for i, c in enumerate(batch.columns)
        ]

    def _visit_FilterNode(self, node):
        batch = self.visit(node.child)
        flt = self._bind(node.predicate, batch)
        fn = make_filter_project_fn(flt, self._identity(batch))
        return fn(batch)

    def _visit_ProjectNode(self, node):
        child = node.child
        flt = None
        if isinstance(child, P.FilterNode):
            batch = self.visit(child.child)
            flt = self._bind(child.predicate, batch)
        else:
            batch = self.visit(child)
        bounds = [self._bind(e, batch) for e in node.exprs]
        fn = make_filter_project_fn(flt, bounds)
        return fn(batch)

    # -- aggregation --
    def _agg_specs(self, node) -> Tuple[AggSpec, ...]:
        return tuple(
            AggSpec(a.kind, a.arg_channel, a.out_type, a.distinct,
                    a.arg2_channel, a.percentile, a.separator,
                    a.arg3_channel, a.param, a.post)
            for a in node.aggs
        )

    def _initial_agg_cap(self, node, batch: RelBatch) -> int:
        """Dictionary/boolean-bounded key domains fix the capacity at
        plan time (the HashAggregationOperator static-bound rule)."""
        bound = 1
        for ch in node.group_channels:
            c = batch.columns[ch]
            if c.type.is_string and c.dictionary is not None and len(c.dictionary) > 0:
                bound *= len(c.dictionary) + 1
            elif c.type.kind == T.TypeKind.BOOLEAN:
                bound *= 3
            else:
                return 1024
        if 0 < bound <= (1 << 16):
            return max(bucket_capacity(bound), 16)
        return 1024

    def _batch_agg_inputs(self, aggs, batch: RelBatch):
        """Value slots + reducers per aggregate (long-decimal args split
        into their limb-slot layout, same as the local _agg_ingest)."""
        live = batch.live_mask()
        values, vvalids, reds = [], [], []
        for a in aggs:
            if a.arg_channel is None:
                values.append(live.astype(jnp.int64))
                vvalids.append(None)
            elif getattr(batch.columns[a.arg_channel].data, "ndim", 1) == 2:
                _append_long_decimal_slots(
                    a, batch.columns[a.arg_channel], live,
                    values, vvalids, reds,
                )
                continue
            else:
                col = batch.columns[a.arg_channel]
                values.append(col.data)
                vvalids.append(col.valid)
            reds.append(_BATCH_REDUCER[a.kind])
        return live, values, vvalids, reds

    def _visit_AggregateNode(self, node):
        batch = self.visit(node.child)
        if node.step == "final":
            return self._agg_final(node, batch)
        if not node.group_channels:
            if node.step != "partial":
                raise MeshUnsupported("global single-step agg in mesh fragment")
            return self._global_partial(node, batch)
        return self._agg_grouped(node, batch)

    def _agg_grouped(self, node, batch: RelBatch) -> RelBatch:
        """Grouped partial OR single-step aggregation (raw rows in)."""
        aggs = self._agg_specs(node)
        groups = tuple(node.group_channels)
        keys = [batch.columns[c].data for c in groups]
        valids = [batch.columns[c].valid_mask() for c in groups]
        live, values, vvalids, reds = self._batch_agg_inputs(aggs, batch)
        site = self._site("agg")
        cap = self.caps.setdefault(site, self._initial_agg_cap(node, batch))
        gk, gv, used, vals, cnts, ngroups, ovf = G.sort_group_reduce(
            tuple(keys), tuple(valids), live, tuple(values), tuple(vvalids),
            tuple(reds), cap,
        )
        self.flags.append((site, jnp.where(ovf, ngroups, 0).astype(jnp.int32)))
        cols: List[Column] = []
        for ch, kk, vv in zip(groups, gk, gv):
            c = batch.columns[ch]
            cols.append(Column(c.type, kk, vv, c.dictionary))
        schema = [(c.type, c.dictionary) for c in batch.columns]
        if node.step == "partial":
            # accumulator wire format (operators.partial_output_schema):
            # long-decimal limb slots join into ONE (n, 2) value column
            si = 0
            for a in aggs:
                arg_t = (
                    schema[a.arg_channel][0]
                    if a.arg_channel is not None else None
                )
                vt, vd = agg_state_meta(a, schema)[0]
                cnt = cnts[si]
                col, si = _slots_to_wire_column(a, arg_t, vt, vd, vals, si)
                cols.append(col)
                cols.append(
                    Column(T.BIGINT, cnt.astype(jnp.int64), None, None)
                )
            return RelBatch(cols, used)
        # single step: finalize in place (the operator finish path)
        si = 0
        for a in aggs:
            arg_t, arg_d = (
                schema[a.arg_channel] if a.arg_channel is not None else (None, None)
            )
            state, si = _slots_to_state(a, arg_t, vals, cnts, si)
            out = _agg_output(a, state, arg_t, None)
            d = arg_d if a.kind in ("min", "max", "any") else None
            cols.append(Column(a.out_type, out.data, out.valid, d))
        return RelBatch(cols, used)

    def _global_partial(self, node, batch: RelBatch) -> RelBatch:
        """GROUP-BY-less partial: one wire row of accumulator state."""
        aggs = self._agg_specs(node)
        live = batch.live_mask()
        schema = [(c.type, c.dictionary) for c in batch.columns]
        cols: List[Column] = []
        for a in aggs:
            if a.arg_channel is None:
                data, vvalid = live.astype(jnp.int64), None
            else:
                col = batch.columns[a.arg_channel]
                data, vvalid = col.data, col.valid
            w = live if vvalid is None else (live & vvalid)
            n = jnp.sum(w.astype(jnp.int64))
            red = _BATCH_REDUCER[a.kind]
            vt, vd = agg_state_meta(a, schema)[0]
            if getattr(data, "ndim", 1) == 2 and red != "count":
                # Int128 arg: one (1, 2) limb-pair state value (count
                # states stay scalar BIGINT regardless of arg type)
                if red == "sum":
                    limb_sums = [
                        jnp.sum(jnp.where(w, piece, jnp.int64(0)))
                        for piece in _limb_split(data)
                    ]
                    h, lo = _limb_join(limb_sums)
                elif red in ("min", "max"):
                    h, lo = _lex128_reduce(data[:, 0], data[:, 1], w, red)
                else:  # first
                    first = data[jnp.argmax(w)]
                    h, lo = first[0], first[1]
                val = jnp.stack([h, lo])[None, :]
                cols.append(Column(vt, val, None, vd))
                cols.append(
                    Column(T.BIGINT, n[None].astype(jnp.int64), None, None)
                )
                continue
            if red == "count":
                val = n
            elif red == "sum":
                acc_dt = (
                    jnp.float64
                    if jnp.issubdtype(data.dtype, jnp.floating)
                    else jnp.int64
                )
                val = jnp.sum(jnp.where(w, data.astype(acc_dt), 0))
            elif red in ("min", "max"):
                from trino_tpu.exec.operators import minmax_neutral

                neutral = minmax_neutral(data.dtype, red)
                masked = jnp.where(w, data, jnp.asarray(neutral, data.dtype))
                val = jnp.min(masked) if red == "min" else jnp.max(masked)
            else:  # first
                val = data[jnp.argmax(w)]
            cols.append(Column(vt, val[None].astype(vt.dtype), None, vd))
            cols.append(Column(T.BIGINT, n[None].astype(jnp.int64), None, None))
        return RelBatch(cols, jnp.ones(1, dtype=jnp.bool_))

    def _agg_final(self, node, batch: RelBatch) -> RelBatch:
        """FINAL step over partial-wire-format state rows: merge-reduce
        per group then finalize (HashAggregationOperator final mode).
        Long-decimal state values arrive as (n, 2) limb pairs and split
        into their internal slot layout for the merge."""
        k = len(node.group_channels)
        keys = [batch.columns[c].data for c in range(k)]
        valids = [batch.columns[c].valid_mask() for c in range(k)]
        live = batch.live_mask()
        values, vvalids, reds = [], [], []
        for a in node.aggs:
            val_col = batch.columns[a.arg_channel]
            cnt_col = batch.columns[a.arg_channel + 1]
            cnt = cnt_col.data
            mreds = _slot_merge_reducers(a, val_col.type)
            if getattr(val_col.data, "ndim", 1) == 2:
                pieces = (
                    _limb_split(val_col.data)
                    if a.kind in ("sum", "avg")
                    else [val_col.data[:, 0], val_col.data[:, 1]]
                )
            else:
                pieces = [val_col.data]
            for p, mred in zip(pieces, mreds):
                values.append(p)
                vvalids.append((cnt > 0) if mred == "first" else None)
                reds.append(mred)
                values.append(cnt)
                vvalids.append(None)
                reds.append("sum")
        site = self._site("aggf")
        cap = self.caps.setdefault(site, self._initial_agg_cap(node, batch))
        gk, gv, used, vals, _, ngroups, ovf = G.sort_group_reduce(
            tuple(keys), tuple(valids), live, tuple(values), tuple(vvalids),
            tuple(reds), cap,
        )
        self.flags.append((site, jnp.where(ovf, ngroups, 0).astype(jnp.int32)))
        cols: List[Column] = []
        for c_idx, kk, vv in zip(range(k), gk, gv):
            c = batch.columns[c_idx]
            cols.append(Column(c.type, kk, vv, c.dictionary))
        # de-interleave the merged (value, cnt) stream into slot lists
        vals_v = [v for v in vals[0::2]]
        vals_c = [c.astype(jnp.int64) for c in vals[1::2]]
        si = 0
        for a in node.aggs:
            arg_col = batch.columns[a.arg_channel]
            state, si = _slots_to_state(a, arg_col.type, vals_v, vals_c, si)
            out = _agg_output(a, state, arg_col.type, None)
            d = arg_col.dictionary if a.kind in ("min", "max", "any") else None
            cols.append(Column(a.out_type, out.data, out.valid, d))
        return RelBatch(cols, used)

    # -- joins --
    def _visit_JoinNode(self, node):
        build = self.visit(node.right)
        probe = self.visit(node.left)
        if node.kind == "cross":
            return self._cross_join(node, probe, build)
        rkeys = list(node.right_keys)
        lkeys = list(node.left_keys)
        b_keys, b_valids = [], []
        for c in rkeys:
            col = build.columns[c]
            v = col.valid_mask()
            if getattr(col.data, "ndim", 1) == 2:
                # long-decimal key: build/probe by its two int64 limbs
                b_keys.extend([col.data[:, 0], col.data[:, 1]])
                b_valids.extend([v, v])
            else:
                b_keys.append(col.data)
                b_valids.append(v)
        ls = J.build_lookup(b_keys, b_valids, build.live_mask())
        keys, valids = [], []
        for i, c in enumerate(lkeys):
            col = probe.columns[c]
            v = col.valid_mask()
            bd = build.columns[rkeys[i]].dictionary
            if getattr(col.data, "ndim", 1) == 2:
                keys.extend([col.data[:, 0], col.data[:, 1]])
                valids.extend([v, v])
                continue
            if (
                col.dictionary is not None
                and bd is not None
                and col.dictionary != bd
            ):
                # cross-dictionary string join: remap probe codes onto
                # the build dictionary by value (LookupJoinOperator rule)
                remap = jnp.asarray(
                    [bd.code(v) for v in col.dictionary.values], dtype=jnp.int32
                )
                keys.append(take_clip(remap, col.data))
            else:
                keys.append(col.data)
            valids.append(v)
        lo, counts, total = J.probe_counts(ls, keys, valids, probe.live_mask())
        site = self._site("join")
        out_cap = self.caps.setdefault(site, bucket_capacity(max(probe.capacity, 16)))
        self.flags.append(
            (site, jnp.where(total > out_cap, total, 0).astype(jnp.int32))
        )
        pi, bi, ok, pairs = _expand_pairs(
            ls, probe, build, keys, valids, lo, counts, out_cap
        )
        if node.residual is not None:
            rfn = make_residual_fn(self._bind_pair(node.residual, probe, build))
            ok = ok & rfn(pairs)
            pairs = RelBatch(pairs.columns, ok)
        if node.kind == "inner":
            return pairs
        matched = _segment_any(counts, pi, ok, probe.capacity)
        if node.kind == "semi":
            return probe.mask(matched)
        if node.kind == "anti":
            return probe.mask(~matched)
        if node.kind in ("mark", "mark_exists"):
            # appended BOOLEAN match column; "mark" (IN) adds the
            # three-valued lanes. Build-side emptiness/null flags are
            # GLOBAL properties — psum over the mesh axis (a shard with
            # an empty build slice must not report empty)
            valid = None
            if node.kind == "mark":
                b_live = build.live_mask()
                nonempty = jax.lax.psum(
                    jnp.any(b_live).astype(jnp.int32), AXIS
                ) > 0
                hn = jnp.zeros((), dtype=jnp.bool_)
                for c in rkeys:
                    bc = build.columns[c]
                    if bc.valid is not None:
                        hn = hn | jnp.any(b_live & ~bc.valid)
                has_null = jax.lax.psum(hn.astype(jnp.int32), AXIS) > 0
                pv = None
                for vv in valids:
                    pv = vv if pv is None else (pv & vv)
                probe_null = (
                    ~pv if pv is not None else jnp.zeros_like(matched)
                )
                unknown = (~matched) & (
                    (probe_null & nonempty) | has_null
                )
                valid = ~unknown
            col = Column(T.BOOLEAN, matched, valid, None)
            return RelBatch(
                list(probe.columns) + [col], probe.live_mask()
            )
        if node.kind == "full":
            # hash-partitioned full outer: every build row lives on
            # exactly one shard, so shard-local matched flags are
            # complete (the fragmenter never broadcasts full joins)
            matched_b = J.build_matched_flags(build.capacity, bi, ok)
            return concat_batches([
                pairs,
                _left_unmatched(probe, build, matched),
                _right_unmatched(
                    [(c.type, c.dictionary) for c in probe.columns],
                    build, matched_b,
                ),
            ])
        # left outer: matched pairs + unmatched probe rows with NULL build
        return concat_batches([pairs, _left_unmatched(probe, build, matched)])

    def _bind_pair(self, e, probe: RelBatch, build: RelBatch):
        cols = list(probe.columns) + list(build.columns)
        return ExprBinder(
            [c.type for c in cols], [c.dictionary for c in cols]
        ).bind(e)

    def _cross_join(self, node, probe: RelBatch, build: RelBatch) -> RelBatch:
        probe_c = probe.compact()
        build_c = build.compact()
        site = self._site("cross")
        nb = self.caps.setdefault(site, 16)
        n_l = jnp.sum(probe_c.live_mask().astype(jnp.int32))
        n_r = jnp.sum(build_c.live_mask().astype(jnp.int32))
        self.flags.append((site, jnp.where(n_r > nb, n_r, 0).astype(jnp.int32)))
        k = jnp.arange(probe_c.capacity * nb, dtype=jnp.int32)
        pi = k // nb
        bi = k % nb
        live = (pi < n_l) & (bi < n_r)
        cols = [c.gather(pi) for c in probe_c.columns]
        cols += [c.gather(bi) for c in build_c.columns]
        return RelBatch(cols, live)

    def _visit_UnionAllNode(self, node):
        outs = [self.visit(c) for c in node.inputs]
        # string columns must share dictionaries for the concatenated
        # column to stay bindable (same rule as the local UnionAll);
        # all-NULL/empty inputs are compatible with anything
        base = outs[0]
        for other in outs[1:]:
            for c0, c1 in zip(base.columns, other.columns):
                if not c0.type.is_string:
                    continue
                d0, d1 = c0.dictionary, c1.dictionary
                if (
                    d0 is not None and len(d0) > 0
                    and d1 is not None and len(d1) > 0
                    and d0 != d1
                ):
                    raise MeshUnsupported("union dictionary mismatch")
        return concat_batches(outs)

    def _visit_EnforceSingleRowNode(self, node):
        child = self.visit(node.child)
        full = _replicate(child)  # all shards see the full row set
        live = full.live_mask()
        n = jnp.sum(live.astype(jnp.int32))
        # >1 rows is a QUERY ERROR (not a capacity retry): err: flags
        # raise in the executor instead of resizing
        self.flags.append((
            f"err:single_row:{self._site('sr')}",
            jnp.where(n > 1, n, 0).astype(jnp.int32),
        ))
        order = jnp.argsort(jnp.where(live, 0, 1), stable=True)
        pos = order[:16]
        idx = jnp.arange(16, dtype=jnp.int32)
        cols = []
        for c in full.columns:
            g = c.gather(pos)
            valid = g.valid_mask() & (idx < n)  # 0 rows -> all-NULL row
            cols.append(g.with_data(g.data, valid))
        out_live = jnp.where(n > 0, idx < n, idx == 0)
        return RelBatch(cols, out_live)

    # -- ordering / limits --
    def _sorted(self, batch: RelBatch, keys) -> RelBatch:
        datas = [batch.columns[k.channel].data for k in keys]
        valids = [batch.columns[k.channel].valid for k in keys]
        order = sort_order(
            datas, valids, [k.descending for k in keys],
            [k.nulls_first for k in keys], batch.live_mask(),
        )
        return batch.gather(order, take_clip(batch.live_mask(), order))

    def _visit_SortNode(self, node):
        return self._sorted(self.visit(node.child), node.keys)

    def _visit_TopNNode(self, node):
        out = self._sorted(self.visit(node.child), node.keys)
        idx = jnp.arange(out.capacity, dtype=jnp.int32)
        return out.mask(idx < node.count)

    def _visit_LimitNode(self, node):
        out = self.visit(node.child).compact()
        idx = jnp.arange(out.capacity, dtype=jnp.int32)
        keep = idx >= node.offset
        if node.count is not None:
            keep = keep & (idx < node.offset + node.count)
        return out.mask(keep)

    def _visit_WindowNode(self, node):
        """Window over hash-distributed partition keys: the fragmenter
        repartitioned the child on PARTITION BY (an all_to_all on this
        plane), so every window partition is shard-local and the local
        window kernel applies per shard unchanged
        (optimizations/AddExchanges.java:140 window distribution)."""
        from trino_tpu.exec.operators import (
            _window_compute, window_fn_tuples,
        )

        batch = self.visit(node.child)
        schema = [(c.type, c.dictionary) for c in batch.columns]
        fns = window_fn_tuples(list(node.functions), schema)
        s_cols, s_live, out_cols = _window_compute(
            batch,
            tuple(node.partition_channels),
            tuple(node.order_keys),
            fns,
            node.frame,
        )
        cols = list(s_cols)
        for spec, (data, valid) in zip(node.functions, out_cols):
            d = None
            if spec.arg_channel is not None and spec.kind in (
                "lead", "lag", "first_value", "last_value", "nth_value",
                "min", "max"
            ):
                d = s_cols[spec.arg_channel].dictionary
            cols.append(Column(spec.out_type, data, valid, d))
        return RelBatch(cols, s_live)


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------


class _ListSource:
    """make_remote_source duck type over pre-materialized pages."""

    def __init__(self, pages: List[Page]):
        self._pages = list(pages)

    def poll(self) -> Optional[Page]:
        return self._pages.pop(0) if self._pages else None

    def is_finished(self) -> bool:
        return not self._pages


def _replicated_map(mesh_sps) -> Dict[int, bool]:
    """Compile-time data placement per fragment: a fragment with no
    scans whose inputs are all replicated executes replicated (every
    shard computes the full result deterministically)."""
    repl: Dict[int, bool] = {}
    for sp in mesh_sps:
        frag = sp.fragment
        if _contains_scan(frag.root):
            repl[frag.id] = False
            continue
        child_ok = True
        for c in sp.children:
            k = c.fragment.output_kind
            # hash input -> sharded; broadcast/gather input -> the
            # exchange itself replicates it
            if k == "hash":
                child_ok = False
        repl[frag.id] = child_ok
    return repl


def mesh_eligibility(subplan: SubPlan) -> Dict[str, int]:
    """Static mesh-plane eligibility check (no execution, no device
    work): raises MeshUnsupported with the fallback reason for plan
    shapes the mesh compiler cannot run, else returns a structural
    summary with the per-compiled-pass collective census. Deterministic,
    so EXPLAIN surfaces can print it under program-cache hits (when the
    trace-time counters would not move)."""
    from trino_tpu.parallel.mesh_chunk import static_collective_counts
    from trino_tpu.runtime.stages import topo_order

    if shard_map is None:
        raise MeshUnsupported("shard_map unavailable in this jax")
    order = topo_order(subplan)
    if len(order) < 2:
        raise MeshUnsupported("single-fragment plan")
    mesh_sps = order[:-1]
    root_sp = order[-1]
    for sp in mesh_sps:
        _check_node(sp.fragment.root)
    root_child_ids = {c.fragment.id for c in root_sp.children}
    repl = _replicated_map(mesh_sps)
    a2a, ag = static_collective_counts(mesh_sps, root_child_ids, repl)
    return {
        "fragments": len(mesh_sps),
        "all_to_all": a2a,
        "all_gather": ag,
    }


class MeshExecutor:
    """Runs a SubPlan with the device mesh as the exchange data plane.

    All non-root fragments execute as one shard_map/jit program; the root
    fragment consumes the gathered results through the ordinary local
    pipeline (so sort-merge gathers, final TopN/limit and output
    decoration share code with the HTTP path)."""

    def __init__(self, catalogs, session, devices=None, replica_id=None,
                 drain_check=None):
        """`devices` restricts the mesh to a sub-mesh (a replica row of
        the replica x partition grid); `replica_id` labels it for
        observability (chunk runners export it as ACTIVE_REPLICA, fault
        messages and deadline kills name it); `drain_check` is the
        replica manager's chunk-boundary lifecycle hook — it raises
        MeshReplicaDraining when the replica leaves rotation so the
        coordinator fails the run over to a sibling."""
        self.catalogs = catalogs
        self.session = session
        devs = list(devices) if devices is not None else list(jax.devices())
        self.n = len(devs)
        self.mesh = Mesh(np.array(devs), (AXIS,))
        self.replica_id = replica_id
        self.drain_check = drain_check
        self.last_run: Dict[str, object] = {}
        # preemptive multi-tenancy: the scheduler seat the chunk runner
        # consults at every boundary (runtime/scheduler.py MeshJob),
        # and the work-stealing context ("emit" on a helper replica,
        # "merge" on the failover primary) — both set per-execution by
        # the coordinator
        self.sched_job = None
        self.steal_ctx = None

    # -- public --
    def execute(self, subplan: SubPlan, preempt=None,
                query_span=None) -> List[list]:
        """Run the SubPlan over the mesh. `preempt(done, total)` is the
        coordinator's chunk-boundary hook (deadline / abandonment
        checks); `query_span` roots the mesh stage/task/operator spans.
        The chunked runner splits the plan into prelude / chunk-step /
        flush programs when mesh_chunk_rows > 0, else compiles one
        program — either way preemption checks bracket every program
        boundary."""
        from trino_tpu.parallel.mesh_chunk import ChunkedMeshRunner
        from trino_tpu.runtime.stages import topo_order

        if shard_map is None:
            raise MeshUnsupported("shard_map unavailable in this jax")
        order = topo_order(subplan)
        if len(order) < 2:
            raise MeshUnsupported("single-fragment plan")
        mesh_sps = order[:-1]
        root_sp = order[-1]
        for sp in mesh_sps:
            _check_node(sp.fragment.root)
        root_child_ids = {c.fragment.id for c in root_sp.children}
        repl = _replicated_map(mesh_sps)
        # feed_tables (aligned 1:1 with host_feeds) names each feed's
        # source table — the resident tier's generation-snapshot domain
        # for pinned prelude contexts
        self._feed_tables: List[tuple] = []
        feeds, host_feeds = self._load_scans(mesh_sps)

        runner = ChunkedMeshRunner(
            self, mesh_sps, root_child_ids, repl, feeds, host_feeds,
            feed_tables=tuple(self._feed_tables),
        )
        steal = self.steal_ctx
        if steal is not None and steal[0] == "emit":
            # work-stealing helper: compute chunks [mid, K) from zero
            # carries and publish them for the primary to merge — no
            # root fragment, no client-visible result
            runner.run_steal_helper(steal)
            return []
        sources = runner.run(preempt=preempt, query_span=query_span)
        # count only after the programs have actually produced results —
        # a failure above falls back to the page exchange, which must not
        # register as a mesh-executed query
        bump_mesh_counter("queries")
        self.last_run = dict(runner.info)
        return self._run_root(subplan, root_sp, sources)

    # -- planning helpers --
    def _load_scans(self, mesh_sps):
        """Host side of SOURCE distribution: each shard scans its slice
        of the connector splits; slices stack into one host RelBatch per
        ScanNode of global shape (n * cap,) (the
        SourcePartitionedScheduler assignment collapsed onto the mesh).
        Device placement is deferred to the chunk runner, which may
        re-pad the driver feed to a chunk-aligned capacity first."""
        from trino_tpu.exec.operators import TableScanOperator

        feeds: Dict[int, int] = {}  # id(node) -> feed position
        host_feeds: List[RelBatch] = []
        for sp in mesh_sps:
            for node in _scan_nodes(sp.fragment.root):
                if id(node) in feeds:
                    # the planner may reuse one ScanNode object in several
                    # plan positions (e.g. the NOT IN rewrite's subquery);
                    # one feed serves them all — a second append would
                    # misalign in_specs with feed_args
                    continue
                conn = self.catalogs.get(node.catalog)
                splits = conn.split_manager.get_splits(
                    node.handle, max(self.session.target_splits, self.n)
                )
                schema = [
                    (f.type, conn.metadata.column_dictionary(node.handle, c))
                    for c, f in zip(node.columns, node.fields)
                ]
                shard_batches = []
                for s in range(self.n):
                    my = splits[s:: self.n]
                    op = TableScanOperator(
                        conn.page_source, my, list(node.columns),
                        self.session.batch_rows,
                    )
                    parts = []
                    while not op.is_finished():
                        b = op.get_output()
                        if b is None:
                            break
                        parts.append(b)
                    if parts:
                        shard_batches.append(concat_batches(parts))
                    else:
                        shard_batches.append(_empty_batch(schema))
                feeds[id(node)] = len(host_feeds)
                host_feeds.append(_stack_shards(shard_batches, self.n))
                self._feed_tables.append((
                    str(node.catalog).lower(),
                    str(node.handle.schema).lower(),
                    str(node.handle.table).lower(),
                ))
        return feeds, host_feeds

    # -- host boundary --
    def _shard_pages(self, batch: RelBatch, replicated: bool) -> List[Page]:
        host = jax.device_get(batch)
        global_cap = host.columns[0].data.shape[0] if host.columns else 0
        cap = global_cap // self.n
        shards = range(1) if replicated else range(self.n)
        pages = []
        for s in shards:
            sl = slice(s * cap, (s + 1) * cap)
            live = (
                np.asarray(host.live)[sl].astype(bool)
                if host.live is not None
                else np.ones(cap, dtype=bool)
            )
            cols, valids, dicts, typs = [], [], [], []
            for c in host.columns:
                cols.append(np.asarray(c.data)[sl][live])
                valids.append(
                    np.asarray(c.valid)[sl][live] if c.valid is not None else None
                )
                dicts.append(
                    c.dictionary.values if c.dictionary is not None else None
                )
                typs.append(c.type)
            if int(live.sum()):
                pages.append(Page(typs, cols, valids, dicts, int(live.sum())))
        return pages

    def _run_root(self, subplan, root_sp, sources: Dict[int, List[Page]]):
        """Execute the root (single-partition) fragment on the host local
        pipeline, consuming the mesh results as its remote sources."""
        from trino_tpu.exec import CollectorSink, Driver, Pipeline
        from trino_tpu.runtime.stages import fragment_schema, topo_order
        from trino_tpu.sql.local_planner import LocalPlanner

        schemas: Dict[int, list] = {}
        for sp in topo_order(subplan):
            remote = {c.fragment.id: schemas[c.fragment.id] for c in sp.children}
            schemas[sp.fragment.id] = fragment_schema(
                self.catalogs, self.session, sp, remote
            )
        planner = LocalPlanner(
            self.catalogs,
            batch_rows=self.session.batch_rows,
            remote_schemas={
                c.fragment.id: schemas[c.fragment.id] for c in root_sp.children
            },
            dynamic_filtering=False,
        )
        physical = planner.plan(root_sp.fragment.root)
        ctx = {
            "make_remote_source": lambda fids: _ListSource(
                [p for fid in fids for p in sources[fid]]
            )
        }
        pipelines, chain = physical.instantiate(ctx)
        sink = CollectorSink()
        chain.append(sink)
        for p in pipelines:
            Driver(p).run()
        Driver(Pipeline(chain)).run()
        rows: List[list] = []
        for b in sink.batches:
            rows.extend(b.to_pylists())
        return rows


# ---------------------------------------------------------------------------
# Host-side batch assembly
# ---------------------------------------------------------------------------


def _empty_batch(schema) -> RelBatch:
    cols = [
        Column(
            t,
            jnp.zeros((16, 2) if t.lanes == 2 else (16,), dtype=t.dtype),
            jnp.zeros(16, dtype=jnp.bool_),
            d,
        )
        for t, d in schema
    ]
    return RelBatch(cols, jnp.zeros(16, dtype=jnp.bool_))


def _stack_shards(batches: List[RelBatch], n: int) -> RelBatch:
    """Pad per-shard batches to one capacity, unify dictionaries, and
    stack into host arrays of shape (n * cap,) ready for a sharded
    device_put (leading-dim sharding makes shard s's rows local to
    device s)."""
    assert len(batches) == n
    cap = bucket_capacity(max(b.capacity for b in batches))
    width = batches[0].width
    cols: List[Column] = []
    for i in range(width):
        parts = unify_column_dicts([b.columns[i] for b in batches])
        datas, valids = [], []
        for p in parts:
            d = np.asarray(jax.device_get(p.data))
            v = (
                np.asarray(jax.device_get(p.valid)).astype(bool)
                if p.valid is not None
                else np.ones(d.shape[0], dtype=bool)
            )
            if d.shape[0] < cap:
                pad = np.zeros((cap - d.shape[0],) + d.shape[1:], d.dtype)
                d = np.concatenate([d, pad])
                v = np.concatenate([v, np.zeros(cap - v.shape[0], bool)])
            datas.append(d)
            valids.append(v)
        cols.append(
            Column(
                parts[0].type,
                np.concatenate(datas),
                np.concatenate(valids),
                parts[0].dictionary,
            )
        )
    lives = []
    for b in batches:
        lv = np.asarray(jax.device_get(b.live_mask())).astype(bool)
        if lv.shape[0] < cap:
            lv = np.concatenate([lv, np.zeros(cap - lv.shape[0], bool)])
        lives.append(lv)
    return RelBatch(cols, np.concatenate(lives))
