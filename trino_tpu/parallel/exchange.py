"""Device-mesh exchange: hash repartition over ICI.

The remote-exchange data plane of the reference — AddExchanges inserting
FIXED_HASH_DISTRIBUTION repartitions between stages + the page-shuffle
wire (SURVEY.md §2.7/§2.8, optimizations/AddExchanges.java:266–276,
PartitionedOutputOperator.java:46) — rebuilt the TPU way: instead of
HTTP page streams between worker JVMs, a `shard_map` over a
`jax.sharding.Mesh` where every shard scatters its rows into
per-destination blocks and one `lax.all_to_all` rides the ICI.

Static-shape discipline: each shard owns R rows and sends at most B rows
to each destination (B bounded by R). Overflow cannot happen when
B == R; smaller B trades memory for a host-visible overflow flag the
caller can react to (grow + retry, like the group table).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PSpec

from trino_tpu.jaxcfg import get_shard_map

shard_map = get_shard_map()

from trino_tpu.ops import groupby as G
from trino_tpu.ops.gather import take_clip
from trino_tpu.ops.hashing import hash64


def partition_for_exchange(
    keys: Sequence[jnp.ndarray],
    valids: Sequence[jnp.ndarray],
    live: jnp.ndarray,
    payloads: Sequence[jnp.ndarray],
    n_shards: int,
    block_rows: int,
):
    """Per-shard half of the exchange: scatter local rows into
    (n_shards, block_rows) destination blocks by key hash.

    Runs INSIDE shard_map (operates on one shard's local rows). Returns
    (key_blocks, valid_blocks, live_blocks, payload_blocks, overflowed).
    The PagePartitioner analogue (output/PartitionedOutputOperator.java:191).
    """
    h = hash64(list(keys), list(valids))
    target = (h.astype(jnp.uint64) % jnp.uint64(n_shards)).astype(jnp.int32)
    target = jnp.where(live, target, n_shards)  # dead rows go nowhere
    # stable order by destination; rank within destination = slot index
    order = jnp.argsort(target, stable=True)
    sorted_target = take_clip(target, order)
    idx = jnp.arange(sorted_target.shape[0], dtype=jnp.int32)
    dest_start = jnp.searchsorted(sorted_target, jnp.arange(n_shards, dtype=jnp.int32))
    slot = idx - take_clip(dest_start, jnp.clip(sorted_target, 0, n_shards - 1))
    overflowed = jnp.any((slot >= block_rows) & (sorted_target < n_shards))
    flat = jnp.where(
        sorted_target < n_shards,
        jnp.clip(sorted_target, 0, n_shards - 1) * block_rows
        + jnp.clip(slot, 0, block_rows - 1),
        n_shards * block_rows,
    )

    def scatter(col):
        z = jnp.zeros(n_shards * block_rows + 1, dtype=col.dtype)
        return z.at[flat].set(take_clip(col, order), mode="drop")[:-1].reshape(
            n_shards, block_rows
        )

    live_blocks = (
        jnp.zeros(n_shards * block_rows + 1, dtype=jnp.bool_)
        .at[flat]
        .set(take_clip(live, order), mode="drop")[:-1]
        .reshape(n_shards, block_rows)
    )
    key_blocks = [scatter(k) for k in keys]
    valid_blocks = [scatter(v) for v in valids]
    payload_blocks = [scatter(p) for p in payloads]
    return key_blocks, valid_blocks, live_blocks, payload_blocks, overflowed


def distributed_groupby_step(
    mesh: Mesh,
    axis: str,
    table_capacity: int,
    n_aggs: int,
):
    """Build the jitted distributed aggregation step: rows sharded over
    `axis` -> local partial aggregation -> all_to_all hash repartition of
    group states -> final aggregation per shard.

    This is the partial->FIXED_HASH exchange->final pattern Trino plans
    for every GROUP BY (AddExchanges.java:276 + HashAggregationOperator
    PARTIAL/FINAL steps), expressed as one SPMD program. Returns
    step(keys, valids, live, values) -> per-shard
    (group_keys, group_valids, used, sums, counts, overflowed), sharded
    so every group lives on exactly one shard; a nonzero `overflowed`
    means some shard's table filled — the host reruns at 2x capacity.
    """
    if shard_map is None:
        raise RuntimeError(
            "shard_map is unavailable in this jax version; the collective "
            "exchange requires jax.shard_map or jax.experimental.shard_map"
        )
    n = mesh.shape[axis]

    def local(keys, valids, live, values):
        # shard_map hands us the local (rows/n,) blocks directly
        # partial aggregation into a local table
        gid, table, _ = G.assign_group_ids(keys, valids, live, table_capacity)
        sums = [
            G.seg_sum(gid, v, live, table_capacity, dtype=jnp.float32
                      if jnp.issubdtype(v.dtype, jnp.floating) else jnp.int64)
            for v in values
        ]
        counts = G.seg_count(gid, live, table_capacity)

        # exchange partial states: rows = table slots. block == capacity
        # means a destination can absorb every slot of a source shard, so
        # overflow is impossible by construction; smaller blocks would
        # need the grow-and-retry protocol, so surface the flag.
        block = table_capacity
        kb, vb, lb, pb, overflowed = partition_for_exchange(
            table.slot_keys,
            table.slot_valids,
            table.slot_used,
            sums + [counts],
            n,
            block,
        )
        # all_to_all over the mesh axis: axis index 0 of the (n, block) blocks
        kb = [jax.lax.all_to_all(k, axis, 0, 0, tiled=True) for k in kb]
        vb = [jax.lax.all_to_all(v, axis, 0, 0, tiled=True) for v in vb]
        lb = jax.lax.all_to_all(lb, axis, 0, 0, tiled=True)
        pb = [jax.lax.all_to_all(p, axis, 0, 0, tiled=True) for p in pb]

        # final aggregation of received partials
        fkeys = [k.reshape(-1) for k in kb]
        fvalids = [v.reshape(-1) for v in vb]
        flive = lb.reshape(-1)
        fsums = [p.reshape(-1) for p in pb[:-1]]
        fcounts = pb[-1].reshape(-1)
        fgid, ftable, final_overflow = G.assign_group_ids(
            fkeys, fvalids, flive, table_capacity
        )
        out_sums = [
            G.seg_sum(fgid, s, flive, table_capacity, dtype=s.dtype) for s in fsums
        ]
        out_counts = G.seg_sum(fgid, fcounts, flive, table_capacity, dtype=jnp.int64)
        any_overflow = jax.lax.pmax(
            (overflowed | final_overflow).astype(jnp.int32), axis
        )
        # local (C,) outputs concatenate over the mesh axis -> (n*C,)
        return (
            list(ftable.slot_keys),
            list(ftable.slot_valids),
            ftable.slot_used,
            out_sums,
            out_counts,
            any_overflow[None],
        )

    row_spec = PSpec(axis)
    out_spec = PSpec(axis)

    def step(keys, valids, live, values):
        f = shard_map(
            local,
            mesh=mesh,
            in_specs=(row_spec, row_spec, row_spec, row_spec),
            out_specs=out_spec,
            check_vma=False,
        )
        return f(keys, valids, live, values)

    return jax.jit(step)
