from trino_tpu.parallel.exchange import (
    distributed_groupby_step,
    partition_for_exchange,
)
