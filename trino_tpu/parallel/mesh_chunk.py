"""Chunked mesh execution: preemptible SPMD programs over the device mesh.

One monolithic shard_map program per query (the original mesh plane)
keeps the coordinator locked out for the whole device dispatch: deadline
kills, client abandonment and the stuck-task watchdog only fire once the
program returns. This module splits the mesh compiler's output at batch
granularity instead:

- **prelude** — every fragment whose subtree does not depend on the
  driver scan compiles into one program, run once (build sides of joins,
  dimension tables, uncorrelated subqueries). Its exchange outputs stay
  resident on device as sharded global arrays.
- **step** — fragments that stream over the driver scan compile into one
  chunk-step program, jit-compiled once and invoked K times with a chunk
  index. The driver feed is sliced on device per chunk
  (`lax.dynamic_slice_in_dim`); group/join state between steps lives in
  donated device carries (accumulator RelBatches with explicit
  live/valid lanes). Each fragment group — producer, its
  FIXED_HASH/FIXED_BROADCAST exchange, consumer — stays fused inside the
  step, so `lax.all_to_all`/`all_gather` rides inside a single compiled
  program per chunk rather than re-entering Python per fragment.
- **flush** — fragments that need the complete driver relation (final
  aggregations, sorts, limits) compile into one program over the
  accumulated carries, run once after the last chunk.

Between chunk boundaries the host regains control: the coordinator's
preemption hook (deadline / abandonment checks) and the per-chunk
stuck-task watchdog run there, which is what makes the mesh plane safe
to use for deadline-bearing queries.

Chunking engages only when `mesh_chunk_rows > 0` (session property);
with the default 0 the whole plan compiles into a single prelude
program — identical compile cost to the monolithic plane — while
preemption checks still bracket the program.

Static-shape discipline carries over: chunk capacities come off the
capacity ladder, carries use host-chosen capacities with device overflow
flags, and an overflow restarts the chunk loop under doubled capacities
(the tryRehash analogue, now spanning chunks). Program records —
jitted fns plus their host-side metadata — are built under
`jax.eval_shape` (no compilation) and cached in PROGRAM_CACHE keyed by
plan fingerprint, feed schemas and capacities, so a second execution of
the same query shape re-dispatches the already-compiled steps and mints
zero new XLA lowerings.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import threading as _threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as PSpec

from trino_tpu import types as T
from trino_tpu.analysis.witness import named_lock
from trino_tpu.block import Column, RelBatch, bucket_capacity
from trino_tpu.compile.cache import (
    PROGRAM_CACHE,
    expr_fingerprint,
    schema_cache_key,
)
from trino_tpu.compile.shapes import CapacityLadder
from trino_tpu.compile.warmup import WarmupEntry, note_classes_warm
from trino_tpu.sql import plan as P
from trino_tpu.parallel.mesh_plan import (
    AXIS,
    MeshUnsupported,
    _exchange_hash,
    _FragVisitor,
    _local_partition,
    _replicate,
    _salted_exchange_hash,
    _salted_local_partition,
    shard_map,
)

# Most recent chunked run, for tests and EXPLAIN surfaces: chunk shape,
# fragment classification and attempt count. Observability only, but
# written by chunk loops racing chaos/EXPLAIN readers — the two-step
# clear()+update() must not expose an empty dict mid-publish.
_run_info_lock = named_lock("mesh_chunk._run_info_lock")
LAST_RUN_INFO: Dict[str, object] = {}  # guarded_by: _run_info_lock


def last_run_info() -> Dict[str, object]:
    """Snapshot of the most recent chunked run's info dict."""
    with _run_info_lock:
        return dict(LAST_RUN_INFO)


def publish_run_info(info: Dict[str, object]) -> None:
    """Atomically replace LAST_RUN_INFO with `info`."""
    with _run_info_lock:
        LAST_RUN_INFO.clear()
        LAST_RUN_INFO.update(info)


# WarmupEntry registry for mesh programs (census analogue of the local
# operator registry): the warmup service can AOT-compile chunk steps by
# replaying recorded program thunks. Bounded; oldest entries drop.
# Written at plan time from concurrent query threads, read by warmup.
_warmup_entries_lock = named_lock("mesh_chunk._warmup_entries_lock")
MESH_WARMUP_ENTRIES: List[WarmupEntry] = []  # guarded_by: _warmup_entries_lock
_MAX_WARMUP_ENTRIES = 128


class MeshStuck(RuntimeError):
    """A chunk step exceeded the stuck-task watchdog threshold. Failure
    is treated as retryable — a program hung here may succeed on the
    page plane — so the coordinator falls back rather than failing the
    query."""


class MeshDeviceLost(RuntimeError):
    """A device backing the mesh failed mid-run (or a chaos fault
    simulated one). Retryable like MeshStuck: the checkpointed
    remainder replays on the restored mesh, or the whole query falls
    back to the page plane."""

    # an in-run resume retries on the SAME mesh; subclasses a sibling
    # sub-mesh must take over for (drain) turn this off so the fault
    # escalates straight to the coordinator's replica failover
    in_run_resumable = True


class MeshReplicaDraining(MeshDeviceLost):
    """The replica serving this run started draining mid-query: the
    chunk loop stops at the next boundary so the coordinator can fail
    the query over to a healthy sibling sub-mesh (which resumes from
    the host-portable checkpoint). Resuming in-run would land back on
    the draining replica, so it is disabled for this fault."""

    in_run_resumable = False


# Chaos seam: when set, called as hook(chunk_index, n_chunks) at every
# chunk boundary BEFORE the step dispatch. The chaos harness raises
# MeshStuck / MeshDeviceLost from here to inject deterministic
# mid-chunk faults (runtime/chaos.py).
MESH_FAULT_HOOK: Optional[Callable[[int, int], None]] = None

# Multi-host fabric seam (runtime/fabric.py): when set, called as
# hook(key) right after a checkpoint (or park snapshot) lands in the
# local store, so the fabric can enqueue the bytes for asynchronous
# push to peer coordinators. The hook only offers to a bounded queue —
# shedding never blocks the chunk loop.
CHECKPOINT_PUSH_HOOK: Optional[Callable[[tuple], None]] = None

# Which replica's sub-mesh the calling thread's chunk loop runs on
# (None outside a run, or on the single full-width mesh). THREAD-local:
# under serving load several chunk loops interleave on different
# replicas, and a replica-targeted fault hook must see the replica of
# the loop that invoked it, not whichever run() started last.
_ACTIVE_REPLICA = _threading.local()


def active_replica() -> Optional[int]:
    """Replica id of the sub-mesh the current thread's chunk loop runs
    on, or None. Replica-aware chaos hooks consult this to target one
    fault domain without changing the hook(k, K) signature."""
    return getattr(_ACTIVE_REPLICA, "replica", None)


class _Overflow(Exception):
    """Device overflow flags fired; restart the run with bumped caps."""

    def __init__(self, sites: List[Tuple[str, int]]):
        super().__init__(f"capacity overflow at {sites}")
        self.sites = sites


def register_mesh_warmup(entries: Sequence[WarmupEntry]) -> None:
    with _warmup_entries_lock:
        known = {id(e.fn) for e in MESH_WARMUP_ENTRIES}
        MESH_WARMUP_ENTRIES.extend(e for e in entries if id(e.fn) not in known)
        del MESH_WARMUP_ENTRIES[:-_MAX_WARMUP_ENTRIES]


def mesh_warmup_entries() -> List[WarmupEntry]:
    with _warmup_entries_lock:
        return list(MESH_WARMUP_ENTRIES)


# ---------------------------------------------------------------------------
# Fragment classification: prelude / stream / flush
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ChunkPlan:
    """How one SubPlan splits across the three mesh programs."""

    driver_pos: Optional[int]  # feed position of the driver scan (None = unchunked)
    driver_ids: frozenset  # id(ScanNode) values served by that feed
    chunk_cap: int  # per-shard rows per chunk (capacity-ladder rung)
    n_chunks: int
    prelude_fids: frozenset
    stream_fids: frozenset
    flush_fids: frozenset

    @property
    def chunked(self) -> bool:
        return self.driver_pos is not None


def _classify(mesh_sps, root_child_ids, driver_ids):
    """Split fragments by their relationship to the driver scan.

    dep      = subtree reads the driver scan (directly or via a dep
               fragment's exchange)
    stream   = dep AND every operator on the dep path distributes over
               chunk-wise union (safe to run per chunk and accumulate)
    flush    = dep but not stream (needs the complete driver relation)
    prelude  = not dep (driver-independent; runs once, results resident)
    """
    dep_fids: set = set()
    dep_cache: Dict[int, bool] = {}

    def node_dep(node) -> bool:
        r = dep_cache.get(id(node))
        if r is None:
            if isinstance(node, P.ScanNode):
                r = id(node) in driver_ids
            elif isinstance(node, P.RemoteSourceNode):
                r = any(fid in dep_fids for fid in node.fragment_ids)
            else:
                r = any(node_dep(c) for c in node.children())
            dep_cache[id(node)] = r
        return r

    def safe(node, is_root: bool) -> bool:
        # a driver-independent subtree recomputes identically every
        # chunk — always safe (its cost is paid K times, but prelude
        # exchanges keep the heavy driver-independent work out of here)
        if not node_dep(node):
            return True
        if isinstance(node, P.ScanNode):
            return True
        if isinstance(node, (P.FilterNode, P.ProjectNode)):
            return all(safe(c, False) for c in node.children())
        if isinstance(node, P.AggregateNode):
            # only a PARTIAL agg at the fragment root: per-chunk partials
            # are more (but valid) partial rows under the partial/final
            # contract — the final step's merge reducers are associative.
            # Grouped single-step or FINAL aggs need the full input.
            return (
                is_root
                and node.step == "partial"
                and safe(node.child, False)
            )
        if isinstance(node, P.JoinNode):
            ld, rd = node_dep(node.left), node_dep(node.right)
            if ld and rd:
                return False  # chunk x chunk misses cross-chunk pairs
            if node.kind == "cross":
                return safe(node.left if ld else node.right, False)
            if rd:
                # chunked BUILD side: only inner joins distribute over a
                # partition of the build relation (outer/semi/anti/mark
                # verdicts need the whole build side at once)
                return node.kind == "inner" and safe(node.right, False)
            # chunked PROBE side: per-probe-row verdicts against the
            # complete build side are exact for every kind except FULL
            # (whose right-unmatched rows need the whole probe relation)
            return node.kind != "full" and safe(node.left, False)
        if isinstance(node, P.RemoteSourceNode):
            if node.merge_keys:
                return False  # chunk concat breaks merge-sorted runs
            deps = [fid in dep_fids for fid in node.fragment_ids]
            if any(deps) and not all(deps):
                # a union of dep + non-dep sources would replay the
                # non-dep source once per chunk (duplication)
                return False
            return True
        # Sort/TopN/Limit/Window/EnforceSingleRow/UnionAll/Values...:
        # order- or cardinality-global — conservative flush
        return False

    for sp in mesh_sps:
        if node_dep(sp.fragment.root):
            dep_fids.add(sp.fragment.id)

    stream: set = set()
    for sp in mesh_sps:
        fid = sp.fragment.id
        if fid not in dep_fids:
            continue
        if sp.fragment.output_merge_keys:
            # chunk-major accumulation is not merge-sorted; consumers
            # expecting sorted runs must see the full relation
            continue
        if any(
            c.fragment.id in dep_fids and c.fragment.id not in stream
            for c in sp.children
        ):
            continue
        if safe(sp.fragment.root, True):
            stream.add(fid)

    all_fids = {sp.fragment.id for sp in mesh_sps}
    prelude = all_fids - dep_fids
    flush = dep_fids - stream
    return frozenset(prelude), frozenset(stream), frozenset(flush)


def build_chunk_plan(mesh_sps, root_child_ids, feeds, shard_caps, session):
    """Pick a driver scan and classify fragments. Chunking engages only
    when the session asks for it (mesh_chunk_rows > 0) and some feed
    admits a non-empty stream set; otherwise every fragment lands in the
    prelude (single-program execution, preemption checks around it)."""
    all_fids = frozenset(sp.fragment.id for sp in mesh_sps)
    chunk_rows = int(getattr(session, "mesh_chunk_rows", 0) or 0)
    if chunk_rows > 0 and feeds:
        ladder = CapacityLadder(
            base=int(getattr(session, "capacity_ladder_base", 2) or 2)
        )
        by_pos: Dict[int, List[int]] = {}
        for key, pos in feeds.items():
            by_pos.setdefault(pos, []).append(key)
        # largest scan first: chunking the biggest relation buys the
        # most preemption granularity per compiled program
        for pos in sorted(by_pos, key=lambda p: -shard_caps[p]):
            driver_ids = frozenset(by_pos[pos])
            prelude, stream, flush = _classify(
                mesh_sps, root_child_ids, driver_ids
            )
            if not stream:
                continue
            chunk_cap = ladder.rung(min(chunk_rows, shard_caps[pos]))
            n_chunks = max(
                1, math.ceil(shard_caps[pos] / chunk_cap)
            )
            return ChunkPlan(
                pos, driver_ids, chunk_cap, n_chunks,
                prelude, stream, flush,
            )
    return ChunkPlan(
        None, frozenset(), 0, 1, all_fids, frozenset(), frozenset()
    )


# Join kinds whose per-probe-row verdict stays exact when hot build
# rows are replicated to every shard and hot probe rows are salted off
# their canonical shard. FULL and MARK need globally consistent
# build-side placement, so they never salt.
_SALTED_JOIN_KINDS = ("inner", "left", "semi", "anti")


def _skew_exchange_map(mesh_sps, root_child_ids):
    """{producer fid: ("build"|"probe", hot_values)} for every exchange
    edge that should trace the salted repartition variant.

    A JoinNode annotated with `skew_hot_keys` (adaptive controller,
    heavy-hitter classification at the build barrier) qualifies only
    when the plan shape guarantees salting changes nothing but row
    routing:

    - kind inner/left/semi/anti with a single integer-like equi key on
      both sides (the classifier only emits plain-int hot values, and
      `_hot_mask` must see a raw 1-D integer lane on BOTH sides or on
      neither — one-sided degradation would reroute probes whose build
      rows were never replicated);
    - both join inputs are RemoteSourceNode leaves (an inline side has
      no exchange to salt) and every producer fragment behind either
      side emits a single-channel FIXED_HASH exchange (a broadcast
      build is already fully replicated — nothing to fix);
    - each producer fragment feeds exactly this consumer edge: another
      consumer of the same exchange output would observe salted
      placement while assuming canonical hash placement;
    - above the join inside the consumer fragment only Filter/Project
      and PARTIAL aggregations appear. Anything partition-reliant (a
      single/final-step grouped aggregate riding the join key's
      partitioning, another join) keeps canonical placement.

    Probe-side salting is only correct when the hot build rows are
    replicated, so the map is all-or-nothing per join: both sides
    resolve, or neither is salted.
    """
    frag_by_id = {sp.fragment.id: sp.fragment for sp in mesh_sps}
    ref_count: Dict[int, int] = {}

    def count_refs(node):
        if isinstance(node, P.RemoteSourceNode):
            for fid in node.fragment_ids:
                ref_count[fid] = ref_count.get(fid, 0) + 1
        for c in node.children():
            count_refs(c)

    for sp in mesh_sps:
        count_refs(sp.fragment.root)

    out: Dict[int, Tuple[str, tuple]] = {}

    def consider(join):
        if join.kind not in _SALTED_JOIN_KINDS:
            return
        if len(join.left_keys) != 1 or len(join.right_keys) != 1:
            return
        for node, ch in ((join.left, join.left_keys[0]),
                         (join.right, join.right_keys[0])):
            t = node.fields[ch].type
            if t.is_nested or t.lanes != 1 or not t.is_integerlike:
                return
        if not (
            isinstance(join.left, P.RemoteSourceNode)
            and isinstance(join.right, P.RemoteSourceNode)
        ):
            return
        probe_fids = tuple(join.left.fragment_ids)
        build_fids = tuple(join.right.fragment_ids)
        for fid in probe_fids + build_fids:
            frag = frag_by_id.get(fid)
            if (
                frag is None
                or ref_count.get(fid, 0) != 1
                or fid in root_child_ids
                or fid in out
                or frag.output_kind != "hash"
                or len(frag.output_channels) != 1
            ):
                return
        hot = tuple(join.skew_hot_keys)
        for fid in build_fids:
            out[fid] = ("build", hot)
        for fid in probe_fids:
            out[fid] = ("probe", hot)

    def walk(node, clean):
        if (
            isinstance(node, P.JoinNode)
            and getattr(node, "skew_hot_keys", ())
            and clean
        ):
            consider(node)
        kid_clean = clean and (
            isinstance(node, (P.FilterNode, P.ProjectNode))
            or (
                isinstance(node, P.AggregateNode)
                and node.step == "partial"
            )
        )
        for c in node.children():
            walk(c, kid_clean)

    for sp in mesh_sps:
        walk(sp.fragment.root, True)
    return out


def static_collective_counts(mesh_sps, root_child_ids, repl) -> Tuple[int, int]:
    """Structural collective census for one compiled pass over the plan:
    each non-replicated hash edge traces one all_to_all, each
    non-replicated broadcast/gather edge one all_gather, plus one
    all_gather per EnforceSingleRow occurrence and one per salted
    non-replicated BUILD edge (hot build rows ride an all_gather on top
    of the cold rows' all_to_all). Static (no execution), so EXPLAIN
    surfaces stay deterministic under program-cache hits."""

    def count_sr(node) -> int:
        own = 1 if isinstance(node, P.EnforceSingleRowNode) else 0
        return own + sum(count_sr(c) for c in node.children())

    skew = _skew_exchange_map(mesh_sps, root_child_ids)
    a2a = ag = 0
    for sp in mesh_sps:
        frag = sp.fragment
        ag += count_sr(frag.root)
        if frag.id in root_child_ids:
            continue
        if repl.get(frag.id):
            continue  # replicated producers exchange without collectives
        if frag.output_kind == "hash":
            a2a += 1
            if skew.get(frag.id, ("", ()))[0] == "build":
                ag += 1
        else:
            ag += 1
    return a2a, ag


# ---------------------------------------------------------------------------
# On-device chunk primitives
# ---------------------------------------------------------------------------


def _slice_chunk(batch: RelBatch, k, cap: int) -> RelBatch:
    """Chunk k of the (padded) driver feed, sliced on device."""
    start = (k * cap).astype(jnp.int32) if hasattr(k, "astype") else k * cap

    def sl(a):
        return jax.lax.dynamic_slice_in_dim(a, start, cap, axis=0)

    cols = [
        Column(
            c.type, sl(c.data),
            None if c.valid is None else sl(c.valid),
            c.dictionary,
        )
        for c in batch.columns
    ]
    live = None if batch.live is None else sl(batch.live)
    return RelBatch(cols, live)


def _accumulate(carry: RelBatch, contrib: RelBatch):
    """Append contrib's live rows to the carry accumulator (per shard).

    The carry keeps live rows densely packed at the front, so appended
    chunks preserve scan order (chunk-major = scan-major after compact).
    Returns (new_carry, overflow_flag): flag carries the exact needed
    capacity when the carry would overflow, 0 otherwise — same protocol
    as the agg/join sites, so the executor's restart ladder handles it.
    """
    cap_c = carry.capacity
    comp = contrib.compact()
    live_in = comp.live_mask()
    count = jnp.sum(carry.live_mask().astype(jnp.int32))
    idx = jnp.arange(comp.capacity, dtype=jnp.int32)
    # dead rows and overflow both scatter out of range -> mode="drop"
    tgt = jnp.where(live_in, count + idx, cap_c)
    cols = []
    for cc, sc in zip(carry.columns, comp.columns):
        data = cc.data.at[tgt].set(sc.data, mode="drop")
        valid = cc.valid.at[tgt].set(sc.valid_mask(), mode="drop")
        cols.append(Column(cc.type, data, valid, cc.dictionary))
    live = carry.live.at[tgt].set(live_in, mode="drop")
    n_new = jnp.sum(live_in.astype(jnp.int32))
    needed = count + n_new
    flag = jnp.where(needed > cap_c, needed, 0).astype(jnp.int32)
    return RelBatch(cols, live), flag


def _carry_template(contrib_sds: RelBatch, cap: int, n: int) -> RelBatch:
    """Global-shape ShapeDtypeStruct pytree for one carry accumulator.
    live and valid lanes are always explicit arrays: a None lane would
    change the pytree structure between the template and _accumulate's
    output, breaking the carry fixed point."""
    cols = []
    for c in contrib_sds.columns:
        if type(c) is not Column:
            raise MeshUnsupported("nested column in mesh carry")
        tail = tuple(c.data.shape[1:])
        cols.append(
            Column(
                c.type,
                jax.ShapeDtypeStruct((n * cap,) + tail, c.data.dtype),
                jax.ShapeDtypeStruct((n * cap,), jnp.bool_),
                c.dictionary,
            )
        )
    return RelBatch(cols, jax.ShapeDtypeStruct((n * cap,), jnp.bool_))


def _pad_shards(batch: RelBatch, n: int, old_cap: int, new_cap: int) -> RelBatch:
    """Re-pad a host-stacked (n * old_cap,) feed to (n * new_cap,) so the
    per-shard extent divides evenly into chunk_cap slices. Padding rows
    are dead (live=False)."""
    if new_cap == old_cap:
        return batch
    pad = new_cap - old_cap
    cols = []
    for c in batch.columns:
        d = np.asarray(c.data)
        d = d.reshape((n, old_cap) + d.shape[1:])
        d = np.pad(d, [(0, 0), (0, pad)] + [(0, 0)] * (d.ndim - 2))
        v = (
            np.asarray(c.valid).astype(bool).reshape(n, old_cap)
            if c.valid is not None
            else np.ones((n, old_cap), dtype=bool)
        )
        v = np.pad(v, [(0, 0), (0, pad)])
        cols.append(
            Column(
                c.type,
                d.reshape((n * new_cap,) + d.shape[2:]),
                v.reshape(-1),
                c.dictionary,
            )
        )
    lv = (
        np.asarray(batch.live).astype(bool).reshape(n, old_cap)
        if batch.live is not None
        else np.ones((n, old_cap), dtype=bool)
    )
    lv = np.pad(lv, [(0, 0), (0, pad)])
    return RelBatch(cols, lv.reshape(-1))


def _merge_out_carry(mine: RelBatch, theirs: RelBatch,
                     n: int) -> Optional[RelBatch]:
    """Append `theirs`'s packed live rows after `mine`'s per-shard live
    count (drain-failover work stealing: `mine` holds chunks [k0, mid),
    `theirs` holds [mid, K) computed from zero carries on a sibling).
    `_accumulate` packs live rows densely at the shard front in chunk
    order, so this concatenation is byte-identical to the sequential
    layout. Returns None when the combined rows overflow the shard
    capacity (a sequential run would have taken the overflow-restart
    ladder, which a merge cannot replay) or the packing precondition
    fails."""
    try:
        if mine.width != theirs.width or mine.capacity != theirs.capacity:
            return None
        cap = mine.capacity // n
        m_live = np.asarray(mine.live_mask()).astype(bool).reshape(n, cap)
        t_live = np.asarray(theirs.live_mask()).astype(bool).reshape(n, cap)
        datas, valids = [], []
        for c in mine.columns:
            d = np.asarray(c.data)
            datas.append(d.reshape((n, cap) + d.shape[1:]).copy())
            valids.append(
                None
                if c.valid is None
                else np.asarray(c.valid).astype(bool).reshape(n, cap).copy()
            )
        new_live = m_live.copy()
        for s in range(n):
            cm = int(m_live[s].sum())
            idx_t = np.nonzero(t_live[s])[0]
            ct = len(idx_t)
            if cm + ct > cap:
                return None
            if (cm and not m_live[s][:cm].all()) or (
                ct and int(idx_t[-1]) != ct - 1
            ):
                return None  # rows not packed at the front
            if ct == 0:
                continue
            for j, c in enumerate(theirs.columns):
                td = np.asarray(c.data)
                td = td.reshape((n, cap) + td.shape[1:])
                datas[j][s, cm:cm + ct] = td[s, idx_t]
                if valids[j] is not None:
                    tv = (
                        np.ones(cap, dtype=bool)
                        if c.valid is None
                        else np.asarray(c.valid).astype(bool).reshape(
                            n, cap
                        )[s]
                    )
                    valids[j][s, cm:cm + ct] = tv[idx_t]
            new_live[s, cm:cm + ct] = True
        cols = [
            Column(
                c.type,
                datas[j].reshape((n * cap,) + datas[j].shape[2:]),
                None if valids[j] is None else valids[j].reshape(-1),
                c.dictionary,
            )
            for j, c in enumerate(mine.columns)
        ]
        return RelBatch(cols, new_live.reshape(-1))
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Program record: jitted prelude/step/flush + host metadata, cacheable
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MeshProgramRecord:
    n_chunks: int
    chunk_cap: int
    resolved_caps: Dict[str, int]
    pctx_fids: Tuple[int, ...]
    carry_meta: Tuple[Tuple[str, int], ...]  # ("out"|"ctx", fid)
    carry_sds: tuple  # global ShapeDtypeStruct RelBatch per carry
    prelude_fn: Optional[Callable]
    prelude_sites: List[str]
    prelude_out_meta: List[Tuple[int, bool]]
    step_fn: Optional[Callable]
    step_sites: List[str]
    flush_fn: Optional[Callable]
    flush_sites: List[str]
    flush_out_meta: List[Tuple[int, bool]]
    warmup_entries: List[WarmupEntry]
    class_keys: set


class _ProgramWarmer:
    """WarmupEntry thunk for one mesh program: rebuilds zero-filled
    arguments with the program's exact mesh shardings (jit specializes
    on input shardings — replaying with default placement would warm
    the wrong executable) and dispatches the recorded jitted fn."""

    def __init__(self, fn, mesh, args_sds, scalar_mask):
        self.fn = fn
        self.mesh = mesh
        self.args_sds = args_sds
        self.scalar_mask = scalar_mask

    def __call__(self, _zeros_batch=None):
        sh = NamedSharding(self.mesh, PSpec(AXIS))
        args = []
        for sds, scalar in zip(self.args_sds, self.scalar_mask):
            if scalar:
                args.append(jnp.zeros((), dtype=jnp.int32))
            else:
                args.append(
                    jax.tree_util.tree_map(
                        lambda s: jax.device_put(
                            jnp.zeros(s.shape, s.dtype), sh
                        ),
                        sds,
                    )
                )
        out = self.fn(*args)
        jax.block_until_ready(out)
        return out


def _sds_of(tree):
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), tree
    )


def _record_key(ex, mesh_sps, root_child_ids, repl, feed_sigs, cplan, caps):
    """Cache key for a program record. Fragment trees enter by repr
    fingerprint so a structurally identical fresh plan (fresh node
    objects) reuses the record — its bodies address feeds positionally,
    and structural twins trace identically. Falls back to uncached
    builds when any repr leaks object identity."""
    frag_parts = []
    for sp in mesh_sps:
        f = sp.fragment
        frag_parts.append((
            f.id, f.partitioning, f.output_kind,
            tuple(f.output_channels), tuple(f.output_merge_keys),
            f.root, tuple(c.fragment.id for c in sp.children),
        ))
    fp = expr_fingerprint(tuple(frag_parts))
    if fp is None or any(sig is None for sig, _cap in feed_sigs):
        return None
    return (
        "mesh-chunk",
        ex.n,
        tuple(str(d) for d in ex.mesh.devices.flat),
        fp,
        tuple(sorted(root_child_ids)),
        tuple(sorted(repl.items())),
        tuple(feed_sigs),
        cplan.driver_pos,
        cplan.chunk_cap,
        cplan.n_chunks,
        tuple(sorted(caps.items())),
    )


def _build_record(ex, mesh_sps, root_child_ids, repl, feeds, feed_sds,
                  cplan, caps_in) -> MeshProgramRecord:
    """Trace the three programs under jax.eval_shape (populating flag
    sites, output metadata and carry shapes without compiling) and wrap
    them in jit. Compilation happens lazily at the first real dispatch;
    the record keeps everything the executor needs to replay."""
    n = ex.n
    mesh = ex.mesh
    caps = dict(caps_in)

    prelude_sps = [sp for sp in mesh_sps if sp.fragment.id in cplan.prelude_fids]
    stream_sps = [sp for sp in mesh_sps if sp.fragment.id in cplan.stream_fids]
    flush_sps = [sp for sp in mesh_sps if sp.fragment.id in cplan.flush_fids]

    consumer: Dict[int, int] = {}
    for sp in mesh_sps:
        for c in sp.children:
            consumer[c.fragment.id] = sp.fragment.id
    # prelude exchange outputs consumed by later programs stay resident
    pctx_fids = tuple(sorted({
        c.fragment.id
        for sp in stream_sps + flush_sps
        for c in sp.children
        if c.fragment.id in cplan.prelude_fids
    }))
    carry_meta: List[Tuple[str, int]] = []
    for sp in stream_sps:
        fid = sp.fragment.id
        if fid in root_child_ids:
            carry_meta.append(("out", fid))
        elif consumer.get(fid) in cplan.flush_fids:
            carry_meta.append(("ctx", fid))
    carry_meta = tuple(carry_meta)
    carry_index = {fid: i for i, (_k, fid) in enumerate(carry_meta)}

    skew_map = _skew_exchange_map(mesh_sps, root_child_ids)

    def emit_exchange(frag, batch, ctx):
        if frag.output_kind == "hash":
            sk = skew_map.get(frag.id)
            if sk is not None:
                role, hot = sk
                ctx[frag.id] = (
                    _salted_local_partition(
                        batch, frag.output_channels, n, hot, role
                    )
                    if repl[frag.id]
                    else _salted_exchange_hash(
                        batch, frag.output_channels, n, hot, role
                    )
                )
            else:
                ctx[frag.id] = (
                    _local_partition(batch, frag.output_channels, n)
                    if repl[frag.id]
                    else _exchange_hash(batch, frag.output_channels, n)
                )
        else:  # broadcast, or gather consumed by another mesh fragment
            ctx[frag.id] = batch if repl[frag.id] else _replicate(batch)

    def run_frags(sps, local_feeds, ctx, flags, outputs, out_meta):
        for sp in sps:
            frag = sp.fragment
            vis = _FragVisitor(ex, frag.id, local_feeds, ctx, caps, flags)
            batch = vis.visit(frag.root)
            if frag.id in root_child_ids:
                outputs.append(batch)
                out_meta.append((frag.id, repl[frag.id]))
                continue
            emit_exchange(frag, batch, ctx)

    def flag_array(flags):
        if flags:
            return jnp.stack([f for _s, f in flags])
        return jnp.zeros(1, dtype=jnp.int32)

    # -- prelude -----------------------------------------------------
    prelude_sites: List[str] = []
    prelude_out_meta: List[Tuple[int, bool]] = []

    def prelude_body(feed_batches):
        # host-visible side lists are cleared at trace entry so a
        # re-trace cannot double-append and misalign with the outputs
        prelude_sites.clear()
        prelude_out_meta.clear()
        local_feeds = {key: feed_batches[pos] for key, pos in feeds.items()}
        ctx: Dict[int, RelBatch] = {}
        flags: List[Tuple[str, jnp.ndarray]] = []
        outputs: List[RelBatch] = []
        run_frags(
            prelude_sps, local_feeds, ctx, flags, outputs, prelude_out_meta
        )
        prelude_sites.extend(s for s, _f in flags)
        return (
            tuple(outputs),
            tuple(ctx[fid] for fid in pctx_fids),
            flag_array(flags),
        )

    # -- chunk step --------------------------------------------------
    step_sites: List[str] = []

    def step_core(k, feed_batches, pctx_batches, carry_batches, probing):
        local_feeds = {}
        for key, pos in feeds.items():
            b = feed_batches[pos]
            if pos == cplan.driver_pos:
                b = _slice_chunk(b, k, cplan.chunk_cap)
            local_feeds[key] = b
        ctx: Dict[int, RelBatch] = dict(zip(pctx_fids, pctx_batches))
        flags: List[Tuple[str, jnp.ndarray]] = []
        contribs: List[RelBatch] = []
        new_carries = list(carry_batches) if carry_batches is not None else None
        for sp in stream_sps:
            frag = sp.fragment
            vis = _FragVisitor(ex, frag.id, local_feeds, ctx, caps, flags)
            batch = vis.visit(frag.root)
            if frag.id not in root_child_ids:
                emit_exchange(frag, batch, ctx)
            i = carry_index.get(frag.id)
            if i is None:
                continue  # stream->stream link: flows in-trace
            contrib = batch if carry_meta[i][0] == "out" else ctx[frag.id]
            if probing:
                contribs.append(contrib)
            else:
                new_carries[i], fl = _accumulate(carry_batches[i], contrib)
                flags.append((f"carry:f{frag.id}", fl))
        return flags, contribs, new_carries

    def probe_body(k, feed_batches, pctx_batches):
        # shape probe: what would each carry receive per chunk?
        _flags, contribs, _nc = step_core(
            k, feed_batches, pctx_batches, None, True
        )
        return tuple(contribs)

    def step_body(k, feed_batches, pctx_batches, carry_batches):
        step_sites.clear()
        flags, _contribs, new_carries = step_core(
            k, feed_batches, pctx_batches, carry_batches, False
        )
        step_sites.extend(s for s, _f in flags)
        return tuple(new_carries), flag_array(flags)

    # -- flush -------------------------------------------------------
    flush_sites: List[str] = []
    flush_out_meta: List[Tuple[int, bool]] = []

    def flush_body(feed_batches, pctx_batches, carry_batches):
        flush_sites.clear()
        flush_out_meta.clear()
        local_feeds = {key: feed_batches[pos] for key, pos in feeds.items()}
        ctx: Dict[int, RelBatch] = dict(zip(pctx_fids, pctx_batches))
        for (kind, fid), cb in zip(carry_meta, carry_batches):
            if kind == "ctx":
                ctx[fid] = cb
        flags: List[Tuple[str, jnp.ndarray]] = []
        outputs: List[RelBatch] = []
        run_frags(
            flush_sps, local_feeds, ctx, flags, outputs, flush_out_meta
        )
        flush_sites.extend(s for s, _f in flags)
        return tuple(outputs), flag_array(flags)

    def smap(body, in_specs):
        return shard_map(
            body, mesh=mesh, in_specs=in_specs,
            out_specs=PSpec(AXIS), check_vma=False,
        )

    cpu_mesh = mesh.devices.flat[0].platform == "cpu"
    feed_tuple_sds = tuple(feed_sds)
    k_sds = jax.ShapeDtypeStruct((), jnp.int32)

    prelude_fn = None
    pctx_sds: tuple = ()
    if prelude_sps:
        pf = smap(prelude_body, (PSpec(AXIS),))
        _p_outs, pctx_sds, _p_flags = jax.eval_shape(pf, feed_tuple_sds)
        prelude_fn = jax.jit(pf)

    step_fn = None
    carry_sds: tuple = ()
    if stream_sps:
        probe = smap(probe_body, (PSpec(), PSpec(AXIS), PSpec(AXIS)))
        contrib_sds = jax.eval_shape(probe, k_sds, feed_tuple_sds, pctx_sds)
        templates = []
        for (kind, fid), csds in zip(carry_meta, contrib_sds):
            contrib_cap = max(
                1, (csds.columns[0].data.shape[0] if csds.columns
                    else csds.live.shape[0]) // n
            )
            # start near the expected total contribution, capped so a
            # huge K doesn't pre-allocate the world; the overflow ladder
            # jumps straight to the flagged exact size on a miss
            initial = bucket_capacity(max(
                16,
                min(cplan.n_chunks * contrib_cap, max(contrib_cap, 8192)),
            ))
            cap = caps.setdefault(f"carry:f{fid}", initial)
            templates.append(_carry_template(csds, cap, n))
        carry_sds = tuple(templates)
        sf = smap(
            step_body, (PSpec(), PSpec(AXIS), PSpec(AXIS), PSpec(AXIS))
        )
        jax.eval_shape(sf, k_sds, feed_tuple_sds, pctx_sds, carry_sds)
        step_fn = jax.jit(
            sf, donate_argnums=() if cpu_mesh else (3,)
        )

    flush_fn = None
    if flush_sps:
        ff = smap(flush_body, (PSpec(AXIS), PSpec(AXIS), PSpec(AXIS)))
        jax.eval_shape(ff, feed_tuple_sds, pctx_sds, carry_sds)
        flush_fn = jax.jit(ff)

    # -- warmup entries ----------------------------------------------
    sig = (f"frags{len(mesh_sps)}", f"k{cplan.n_chunks}", f"n{n}")
    warm_cap = cplan.chunk_cap or 16
    entries: List[WarmupEntry] = []

    def entry(operator, fn, args_sds, scalar_mask):
        return WarmupEntry(
            operator=operator,
            fn=_ProgramWarmer(fn, mesh, args_sds, scalar_mask),
            in_schema=[(T.BIGINT, None)],
            out_dtypes=sig,
            capacities=(warm_cap,),
        )

    if prelude_fn is not None:
        entries.append(entry(
            "MeshPrelude", prelude_fn, (feed_tuple_sds,), (False,)
        ))
    if step_fn is not None:
        entries.append(entry(
            "MeshChunkStep", step_fn,
            (k_sds, feed_tuple_sds, pctx_sds, carry_sds),
            (True, False, False, False),
        ))
    if flush_fn is not None:
        entries.append(entry(
            "MeshFlush", flush_fn,
            (feed_tuple_sds, pctx_sds, carry_sds),
            (False, False, False),
        ))

    return MeshProgramRecord(
        n_chunks=cplan.n_chunks,
        chunk_cap=cplan.chunk_cap,
        resolved_caps=dict(caps),
        pctx_fids=pctx_fids,
        carry_meta=carry_meta,
        carry_sds=carry_sds,
        prelude_fn=prelude_fn,
        prelude_sites=prelude_sites,
        prelude_out_meta=prelude_out_meta,
        step_fn=step_fn,
        step_sites=step_sites,
        flush_fn=flush_fn,
        flush_sites=flush_sites,
        flush_out_meta=flush_out_meta,
        warmup_entries=entries,
        class_keys=set().union(*(e.keys() for e in entries)) if entries else set(),
    )


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


class ChunkedMeshRunner:
    """Drives one query's mesh programs: prelude once, K chunk steps
    with host preemption/watchdog checks at every boundary, flush once;
    restarts the whole loop under bumped capacities on device overflow
    (deterministic ladder — a second execution replays the same
    capacity sequence and hits every cached program)."""

    def __init__(self, ex, mesh_sps, root_child_ids, repl, feeds, host_feeds,
                 feed_tables=()):
        self.ex = ex
        self.session = ex.session
        # source table per feed (resident-tier generation domain)
        self.feed_tables = tuple(feed_tables)
        self.mesh_sps = mesh_sps
        self.root_child_ids = root_child_ids
        self.repl = repl
        self.feeds = feeds
        self.sharding = NamedSharding(ex.mesh, PSpec(AXIS))
        self.skew_map = _skew_exchange_map(mesh_sps, root_child_ids)
        n = ex.n
        shard_caps = [b.capacity // n for b in host_feeds]
        self.cplan = build_chunk_plan(
            mesh_sps, root_child_ids, feeds, shard_caps, self.session
        )
        host_feeds = list(host_feeds)
        if self.cplan.chunked:
            pos = self.cplan.driver_pos
            host_feeds[pos] = _pad_shards(
                host_feeds[pos], n, shard_caps[pos],
                self.cplan.n_chunks * self.cplan.chunk_cap,
            )
        self.feed_sigs = tuple(
            (
                schema_cache_key([(c.type, c.dictionary) for c in b.columns]),
                b.capacity,
            )
            for b in host_feeds
        )
        self.feed_sds = tuple(_sds_of(b) for b in host_feeds)
        self.feed_args = tuple(
            jax.device_put(b, self.sharding) for b in host_feeds
        )
        self.info: Dict[str, object] = {}
        self._last_record_key = None
        # recovery bookkeeping for the current run (chaos harness and
        # EXPLAIN ANALYZE read these back through self.info)
        self._run_stats: Dict[str, object] = {
            "executed_chunk_steps": 0,
            "checkpoints": 0,
            "resumes": 0,
            "resumed_from_chunk": None,
            "parks": 0,
            "unparks": 0,
            "steals": 0,
        }

    # -- program record ----------------------------------------------
    def _record(self, caps) -> MeshProgramRecord:
        def build():
            return _build_record(
                self.ex, self.mesh_sps, self.root_child_ids, self.repl,
                self.feeds, self.feed_sds, self.cplan, caps,
            )

        key = _record_key(
            self.ex, self.mesh_sps, self.root_child_ids, self.repl,
            self.feed_sigs, self.cplan, caps,
        )
        self._last_record_key = key
        if key is None:
            return build()
        record = PROGRAM_CACHE.get_or_create(key, build)
        if not isinstance(record, MeshProgramRecord):
            return build()  # foreign entry under a colliding key
        return record

    def _ckpt_key(self) -> Optional[tuple]:
        """Checkpoint-store key: the program identity minus the caps
        element (the record key's last component), so a resume after an
        overflow cap bump still finds its checkpoint, and minus the
        DEVICE identity (record-key element 2), so the checkpoint is
        host-portable — a sibling sub-mesh of the same width n (carry
        shapes are (n*cap,)) restores it after a replica failover. The
        shard count n stays in the key: carries from a different-width
        mesh could never be re-placed shape-exactly. None when the
        program itself is uncacheable (repr identity leak) — such plans
        never checkpoint."""
        if self._last_record_key is None:
            return None
        key = self._last_record_key
        return ("mesh-ckpt", key[1]) + tuple(key[3:-1])

    # -- execution ---------------------------------------------------
    def run(self, preempt=None, query_span=None) -> Dict[int, list]:
        from trino_tpu.runtime.tracing import KIND_STAGE, KIND_TASK

        stage_span = task_span = None
        if query_span is not None:
            stage_span = query_span.child(
                "stage mesh", KIND_STAGE,
                data_plane="mesh", fragments=len(self.mesh_sps),
            )
            task_span = stage_span.child(
                "task mesh.0", KIND_TASK,
                chunks=self.cplan.n_chunks, chunk_rows=self.cplan.chunk_cap,
            )
        prev_replica = active_replica()
        _ACTIVE_REPLICA.replica = getattr(self.ex, "replica_id", None)
        try:
            sched_job = getattr(self.ex, "sched_job", None)
            if sched_job is not None:
                # the seat guards DEVICE phases only (prelude, chunk
                # steps, flush): planning and host feed builds already
                # ran before this point, outside the seat, so a fast
                # arrival never queues behind another query's host
                # prep. Typed kills and drain checks fire out of the
                # wait as they do at any boundary.
                sched_job.scheduler.acquire(sched_job)
            caps: Dict[str, int] = {}
            self._run_stats = {
                "executed_chunk_steps": 0,
                "checkpoints": 0,
                "resumes": 0,
                "resumed_from_chunk": None,
                "parks": 0,
                "unparks": 0,
                "steals": 0,
            }
            resume_budget = int(
                getattr(self.session, "mesh_resume_attempts", 2) or 0
            )
            overflows = 0
            attempt = 0
            while True:
                record = self._record(caps)
                try:
                    sources = self._execute(
                        record, preempt, task_span, attempt
                    )
                    break
                except _Overflow as ov:
                    for site, _needed in ov.sites:
                        if site.startswith("err:single_row"):
                            raise RuntimeError(
                                "Scalar sub-query has returned multiple rows"
                            ) from None
                    overflows += 1
                    if overflows >= 12:
                        raise RuntimeError(
                            "mesh capacity retry limit exceeded"
                        )
                    # restart from the record's fully resolved caps so
                    # the ladder is deterministic across executions
                    caps = dict(record.resolved_caps)
                    for site, needed in ov.sites:
                        caps[site] = max(
                            caps.get(site, 16) * 2,
                            bucket_capacity(max(needed, 16)),
                        )
                    attempt += 1
                except (MeshStuck, MeshDeviceLost) as e:
                    # in-run resume: only when a live checkpoint exists
                    # and budget remains; otherwise the fault keeps its
                    # type and the coordinator's fallback dispatch (page
                    # plane / QUERY retry) takes over. Typed deadline /
                    # abandonment errors never land here — they
                    # propagate from preempt() uncaught.
                    key = self._ckpt_key()
                    ckpt = None
                    if (
                        key is not None
                        and resume_budget > 0
                        and getattr(e, "in_run_resumable", True)
                    ):
                        from trino_tpu.recovery.checkpoint import (
                            CHECKPOINTS,
                        )

                        ckpt = CHECKPOINTS.get(key)
                    if ckpt is None:
                        # annotate for the coordinator's failover path:
                        # with a live checkpoint under this key, the
                        # unstarted chunk range can be split across two
                        # sibling replicas (work stealing) — but only
                        # when every carry is an append accumulator
                        # (group carries hold cross-chunk state that
                        # cannot merge byte-identically)
                        e.ckpt_key = key
                        e.steal_ok = bool(record.carry_meta) and all(
                            kind == "out"
                            for kind, _fid in record.carry_meta
                        )
                        raise
                    resume_budget -= 1
                    if task_span is not None:
                        task_span.event(
                            "mesh_fault",
                            error=type(e).__name__,
                            resume_from=ckpt.next_chunk,
                        )
                    attempt += 1
            if record.warmup_entries:
                register_mesh_warmup(record.warmup_entries)
                note_classes_warm(record.class_keys)
            if self.skew_map:
                from trino_tpu.runtime.metrics import METRICS

                METRICS.increment(
                    "skew.salted_exchanges", len(self.skew_map)
                )
            stats = self._run_stats
            self.info = {
                "chunked": self.cplan.chunked,
                "chunks": record.n_chunks,
                "chunk_cap": record.chunk_cap,
                "driver_pos": self.cplan.driver_pos,
                "prelude_fragments": sorted(self.cplan.prelude_fids),
                "stream_fragments": sorted(self.cplan.stream_fids),
                "flush_fragments": sorted(self.cplan.flush_fids),
                "attempts": attempt + 1,
                "salted_exchanges": len(self.skew_map),
                "executed_chunk_steps": stats["executed_chunk_steps"],
                "checkpoints": stats["checkpoints"],
                "resumes": stats["resumes"],
                "resumed_from_chunk": stats["resumed_from_chunk"],
                "parks": stats["parks"],
                "unparks": stats["unparks"],
                "steals": stats["steals"],
            }
            key = self._ckpt_key()
            if key is not None:
                # a completed run's checkpoint is spent — a later
                # identical query must start fresh, not resume
                from trino_tpu.recovery.checkpoint import CHECKPOINTS

                CHECKPOINTS.discard(key)
            publish_run_info(self.info)
            self._record_divergences(sources, query_span)
            return sources
        finally:
            _ACTIVE_REPLICA.replica = prev_replica
            if task_span is not None:
                task_span.end()
                stage_span.end()

    def _execute(self, record: MeshProgramRecord, preempt, task_span,
                 attempt: int) -> Dict[int, list]:
        from trino_tpu.runtime.tracing import KIND_OPERATOR

        def op_span(name, **attrs):
            if task_span is None:
                return contextlib.nullcontext()
            return task_span.child(name, KIND_OPERATOR, **attrs)

        n = self.ex.n
        K = record.n_chunks
        watchdog_s = float(
            getattr(self.session, "stuck_task_interrupt_s", 0.0) or 0.0
        )
        outs: Dict[int, Tuple[object, bool]] = {}

        if preempt is not None:
            preempt(0, K)
        pctx: tuple = ()
        if record.prelude_fn is not None:
            p_outs, pctx = self._run_prelude(
                record, task_span, op_span, attempt, n
            )
            for (fid, rep), b in zip(record.prelude_out_meta, p_outs):
                outs[fid] = (b, rep)

        interval = int(
            getattr(self.session, "mesh_checkpoint_interval_chunks", 0)
            or 0
        )
        # park_key: program identity for scheduler parks (and for the
        # resume-on-entry lookup — a parked query failed over by a
        # drain resumes here on the sibling even with periodic
        # checkpointing off); ckpt_key additionally gates the
        # every-N-chunks fault snapshots
        park_key = self._ckpt_key() if self.cplan.chunked else None
        ckpt_key = park_key if interval > 0 else None

        carries: tuple = ()
        if record.step_fn is not None:
            k0 = 0
            carries = None
            if park_key is not None:
                from trino_tpu.recovery.checkpoint import CHECKPOINTS

                ck = CHECKPOINTS.get(park_key)
                if ck is not None and ck.n_chunks == K and 0 < ck.next_chunk <= K:
                    carries = self._restore_carries(ck, record)
                    if carries is not None:
                        k0 = ck.next_chunk
                        CHECKPOINTS.note_resume()
                        self._run_stats["resumes"] = (
                            int(self._run_stats["resumes"]) + 1
                        )
                        self._run_stats["resumed_from_chunk"] = k0
                        # deadline kills during the resumed stretch name
                        # the resume point — and, after a replica
                        # failover, which replica picked the run up
                        # (query_tracker embeds both in the typed
                        # [EXCEEDED_TIME_LIMIT] message)
                        try:
                            preempt.resumed_from = k0
                            preempt.resumed_on = active_replica()
                        except AttributeError:
                            pass  # bare-callable hooks (tests) are fine
                        if task_span is not None:
                            task_span.event("resume", from_chunk=k0, of=K)
            if carries is None:
                carries = tuple(
                    jax.tree_util.tree_map(
                        lambda s: jax.device_put(
                            jnp.zeros(s.shape, s.dtype), self.sharding
                        ),
                        t,
                    )
                    for t in record.carry_sds
                )
            drain_check = getattr(self.ex, "drain_check", None)
            # preemptive scheduler seat (runtime/scheduler.py): consult
            # at every completed boundary whether to keep the mesh,
            # yield in place, or park to the checkpoint store
            sched_job = getattr(self.ex, "sched_job", None)
            # drain-failover work stealing, primary side: at boundary
            # `mid` adopt the helper replica's [mid, K) carries instead
            # of executing those chunks ("merge", mid, key, done_event,
            # caps, timeout_s)
            steal = getattr(self.ex, "steal_ctx", None)
            if steal is not None and steal[0] != "merge":
                steal = None
            from trino_tpu.runtime.metrics import METRICS

            with op_span("MeshChunkStep", attempt=attempt, chunks=K):
                for k in range(k0, K):
                    if preempt is not None:
                        preempt(k, K)
                    if drain_check is not None:
                        # replica lifecycle: a drain requested on this
                        # sub-mesh raises MeshReplicaDraining here so
                        # the coordinator fails the run over to a
                        # sibling at this boundary
                        drain_check()
                    if MESH_FAULT_HOOK is not None:
                        MESH_FAULT_HOOK(k, K)
                    t0 = time.monotonic()
                    carries, flags = record.step_fn(
                        jnp.asarray(k, dtype=jnp.int32),
                        self.feed_args, pctx, carries,
                    )
                    # flag readback is the natural device sync point
                    self._check_flags(record.step_sites, flags, n)
                    dt = time.monotonic() - t0
                    self._run_stats["executed_chunk_steps"] = (
                        int(self._run_stats["executed_chunk_steps"]) + 1
                    )
                    # process-wide ledger: a failover spans TWO runners
                    # (the faulted one and the sibling's), so per-run
                    # stats alone cannot say how much work the whole
                    # query re-executed — bench's failover gate diffs
                    # this counter instead
                    METRICS.increment("mesh.chunk_steps")
                    # a completed boundary is a safe snapshot point:
                    # the flag readback synced the device, and the
                    # carries are only donated when passed into the
                    # NEXT step dispatch
                    if (
                        ckpt_key is not None
                        and (k + 1) % interval == 0
                        and (k + 1) < K
                    ):
                        self._checkpoint(
                            ckpt_key, record, carries, k + 1, K,
                            task_span,
                        )
                    if task_span is not None:
                        task_span.event(
                            "chunk", index=k, of=K, wall_s=round(dt, 6)
                        )
                    # chunk 0 pays the cold compile; boundary progress
                    # is only meaningful from the second chunk on
                    if watchdog_s and k >= 1 and dt > watchdog_s:
                        raise MeshStuck(
                            f"mesh chunk {k} made no boundary progress for "
                            f"{dt:.3f}s (stuck_task_interrupt_s="
                            f"{watchdog_s}); retryable on the page plane"
                        )
                    if (
                        steal is not None
                        and (k + 1) == steal[1]
                        and (k + 1) < K
                    ):
                        merged = self._steal_merge(record, carries, steal)
                        if merged is not None:
                            carries = merged
                            self._run_stats["steals"] = (
                                int(self._run_stats["steals"]) + 1
                            )
                            METRICS.increment("scheduler.steals")
                            if task_span is not None:
                                task_span.event(
                                    "steal_merge", at_chunk=k + 1, of=K
                                )
                            break  # helper computed [mid, K)
                        # helper failed: fall through and run the
                        # remainder sequentially (stealing is
                        # opportunistic, never correctness-bearing)
                        steal = None
                    if sched_job is not None and (k + 1) < K:
                        decision = sched_job.boundary(
                            k + 1, K, dt,
                            parkable=park_key is not None,
                        )
                        if decision == "park":
                            carries = self._park(
                                park_key, record, carries, k + 1, K,
                                task_span, sched_job,
                            )

        if preempt is not None:
            preempt(K, K)
        if record.flush_fn is not None:
            with op_span("MeshFlush", attempt=attempt):
                f_outs, flags = record.flush_fn(
                    self.feed_args, pctx, carries
                )
                self._check_flags(record.flush_sites, flags, n)
            for (fid, rep), b in zip(record.flush_out_meta, f_outs):
                outs[fid] = (b, rep)

        for (kind, fid), c in zip(record.carry_meta, carries):
            if kind == "out":
                outs[fid] = (c, self.repl[fid])

        return {
            fid: self.ex._shard_pages(batch, rep)
            for fid, (batch, rep) in outs.items()
        }

    def _record_divergences(self, sources, query_span) -> None:
        """Adaptive-tier observability at the mesh barrier: diff each
        mesh fragment's exported row count (prelude exports + finished
        chunk-stream outputs) against the optimizer's estimate. Instant
        events + adaptive.divergences counters only — the mesh plane
        never re-plans mid-flight; a divergent query's NEXT execution
        re-plans through the controller."""
        try:
            from trino_tpu.adaptive.observer import record_observation
            from trino_tpu.sql.stats import StatsCalculator

            threshold = float(
                getattr(self.session, "adaptive_replan_threshold", 4.0)
                or 4.0
            )
            from trino_tpu.sql.stats import PlanStats

            frag_rows: Dict[int, float] = {}

            class _FragmentStats(StatsCalculator):
                # producer fragments' estimates feed consumer leaves,
                # same stitching the coordinator's stage diff uses
                def _RemoteSourceNode(self, node):
                    rows = sum(
                        frag_rows.get(fid, 1.0)
                        for fid in node.fragment_ids
                    )
                    return PlanStats(max(rows, 1.0))

            calc = _FragmentStats(self.ex.catalogs)

            def estimate(sp) -> float:
                for c in sp.children:
                    estimate(c)
                fid = sp.fragment.id
                if fid not in frag_rows:
                    frag_rows[fid] = calc.stats(
                        sp.fragment.root
                    ).row_count
                return frag_rows[fid]

            for sp in self.mesh_sps:
                estimate(sp)
            for sp in self.mesh_sps:
                fid = sp.fragment.id
                pages = sources.get(fid)
                if pages is None:
                    continue
                observed = sum(int(p.row_count) for p in pages)
                record_observation(
                    f"mesh-fragment:{fid}", frag_rows.get(fid, 1.0),
                    observed, threshold, span=query_span,
                )
        except Exception:
            pass  # observability must never fail the run

    def _run_prelude(self, record: MeshProgramRecord, task_span, op_span,
                     attempt: int, n: int):
        """Prelude with a resident-tier consult: a warm hit reuses the
        pinned (p_outs, pctx) and skips the dispatch entirely (neither
        is ever donated — step donates only carries — so reuse is
        safe); a miss runs the prelude and pins the exported ctx under
        the feed tables' generation snapshot. Keyed off the program
        record key, so uncacheable plans (repr-identity leaks) never
        pin."""
        rkey = None
        budget_mb = int(
            getattr(self.session, "resident_pin_budget_mb", 64) or 0
        )
        if self._last_record_key is not None and budget_mb > 0:
            from trino_tpu.resident import GENERATIONS, RESIDENT

            rkey = (
                "resident-mesh",
                self._last_record_key,
                GENERATIONS.snapshot(self.feed_tables),
            )
            cached = RESIDENT.lookup(rkey)
            if cached is not None:
                if task_span is not None:
                    task_span.event("resident_hit", tier="mesh-prelude")
                self.info["prelude_resident"] = True
                return cached
            # a live entry under a stale generation is unreachable by
            # key; reclaim its device memory eagerly
            for stale in RESIDENT.entries_for_prefix(
                ("resident-mesh", self._last_record_key)
            ):
                if stale != rkey and RESIDENT.evict(stale):
                    if task_span is not None:
                        task_span.event(
                            "resident_evict", tier="mesh-prelude"
                        )
        with op_span("MeshPrelude", attempt=attempt):
            p_outs, pctx, flags = record.prelude_fn(self.feed_args)
            self._check_flags(record.prelude_sites, flags, n)
        if rkey is not None:
            import jax.tree_util as jtu

            from trino_tpu.resident import RESIDENT

            bytes_ = sum(
                int(getattr(x, "nbytes", 0))
                for x in jtu.tree_leaves((p_outs, pctx))
            )
            RESIDENT.configure(budget_mb << 20)
            RESIDENT.pin(
                rkey, (tuple(p_outs), pctx), bytes_,
                set(self.feed_tables),
            )
        return tuple(p_outs), pctx

    def _checkpoint(self, key, record, carries, next_chunk, K,
                    task_span) -> None:
        """Snapshot the device carries to the host checkpoint store as
        of having completed chunks [0, next_chunk). Best-effort: a
        snapshot failure must never fail the run it exists to protect."""
        try:
            from trino_tpu.recovery.checkpoint import (
                CHECKPOINTS,
                MeshCheckpoint,
            )
            from trino_tpu.resident import GENERATIONS

            host = tuple(
                jax.tree_util.tree_map(
                    lambda x: np.asarray(jax.device_get(x)), c
                )
                for c in carries
            )
            CHECKPOINTS.put(key, MeshCheckpoint(
                next_chunk=next_chunk,
                n_chunks=K,
                chunk_cap=record.chunk_cap,
                resolved_caps=dict(record.resolved_caps),
                carries_host=host,
                tables=self.feed_tables,
                generations=GENERATIONS.snapshot(self.feed_tables),
            ))
            self._run_stats["checkpoints"] = (
                int(self._run_stats["checkpoints"]) + 1
            )
            if task_span is not None:
                task_span.event("checkpoint", chunk=next_chunk, of=K)
            if CHECKPOINT_PUSH_HOOK is not None:
                CHECKPOINT_PUSH_HOOK(key)
        except Exception:
            pass

    def _park(self, key, record, carries, next_chunk, K, task_span,
              job) -> tuple:
        """Park this run: snapshot the device carries to the host
        checkpoint store (accounted against park_max_bytes), release
        the device memory, and block in the scheduler until regranted —
        then re-place the same snapshot and continue from `next_chunk`.

        Budget refusal returns the original carries untouched: the
        query keeps the mesh and runs to completion (degradation is
        never query failure). Typed kills (deadline / abandonment)
        raise out of the parked wait with the snapshot discarded — a
        dead query never resumes; mesh faults (drain surfacing while
        parked) keep the snapshot so a sibling replica can restore it
        through the host-portable path."""
        from trino_tpu.recovery.checkpoint import (
            CHECKPOINTS,
            MeshCheckpoint,
        )
        from trino_tpu.resident import GENERATIONS

        host = tuple(
            jax.tree_util.tree_map(
                lambda x: np.asarray(jax.device_get(x)), c
            )
            for c in carries
        )
        ckpt = MeshCheckpoint(
            next_chunk=next_chunk,
            n_chunks=K,
            chunk_cap=record.chunk_cap,
            resolved_caps=dict(record.resolved_caps),
            carries_host=host,
            tables=self.feed_tables,
            generations=GENERATIONS.snapshot(self.feed_tables),
        )
        budget = int(
            getattr(self.session, "park_max_bytes", 256 << 20)
        )
        group = None
        # admission-weighted park pool: mesh_park_max_bytes apportioned
        # across resource groups by scheduler weight — a group past its
        # share gets refused (in-place yield), never failed
        pool = int(getattr(self.session, "mesh_park_max_bytes", 0) or 0)
        if pool > 0:
            budget = job.scheduler.park_budget_for(job, pool)
            group = job.group
        if not CHECKPOINTS.park(key, ckpt, budget, group=group):
            job.park_refused()
            if task_span is not None:
                task_span.event("park_refused", chunk=next_chunk, of=K)
            return carries
        carries = None  # the snapshot is now the only copy
        self._run_stats["parks"] = int(self._run_stats["parks"]) + 1
        if task_span is not None:
            task_span.event("park", chunk=next_chunk, of=K)
        if CHECKPOINT_PUSH_HOOK is not None:
            try:
                CHECKPOINT_PUSH_HOOK(key)
            except Exception:
                pass  # push is best-effort; the park itself succeeded
        try:
            job.park_wait(next_chunk, K)
        except (MeshStuck, MeshDeviceLost):
            # mesh lifecycle fault while parked (drain): keep the
            # snapshot — the coordinator's failover restores it on a
            # sibling via the portable-bytes path
            CHECKPOINTS.unpark(key, keep=True)
            raise
        except BaseException:
            # typed kill (deadline / abandonment) while parked: the
            # query is dead and must never resume
            CHECKPOINTS.unpark(key, keep=False)
            raise
        # regranted: restore from the LOCAL snapshot object (immune to
        # DML generation invalidation — this run's feeds are an
        # immutable device snapshot, so its carries stay exact even if
        # the source tables moved on)
        restored = self._restore_carries(ckpt, record)
        CHECKPOINTS.unpark(key, keep=True)
        if restored is None:
            # cannot happen under an unchanged record (same caps, same
            # shapes) — but if it does, the kept store entry feeds the
            # in-run resume path rather than losing progress
            raise MeshDeviceLost(
                f"parked carries failed to restore at chunk {next_chunk}"
            )
        self._run_stats["unparks"] = (
            int(self._run_stats["unparks"]) + 1
        )
        if task_span is not None:
            task_span.event("unpark", chunk=next_chunk, of=K)
        return restored

    # -- drain-failover work stealing --------------------------------
    def run_steal_helper(self, steal) -> None:
        """Helper side: run chunks [mid, K) of a stolen query on this
        sub-mesh from ZERO carries and publish the resulting carries as
        a checkpoint under the steal key. No store resume on entry, no
        periodic checkpointing (the primary's own key is this program's
        identity — a helper snapshot would collide), no flush, no
        output emission: the primary merges these carries at its `mid`
        boundary and owns the rest of the run. Any failure simply skips
        the publish — the primary times out and continues sequentially."""
        _mode, mid, steal_key, done, caps = steal[:5]
        prev_replica = active_replica()
        _ACTIVE_REPLICA.replica = getattr(self.ex, "replica_id", None)
        try:
            from trino_tpu.recovery.checkpoint import (
                CHECKPOINTS,
                MeshCheckpoint,
            )
            from trino_tpu.resident import GENERATIONS
            from trino_tpu.runtime.metrics import METRICS

            record = self._record(dict(caps))
            n = self.ex.n
            K = record.n_chunks
            if not (0 < mid < K) or record.step_fn is None:
                return
            pctx: tuple = ()
            if record.prelude_fn is not None:
                p_outs, pctx = self._run_prelude(
                    record, None,
                    lambda name, **attrs: contextlib.nullcontext(),
                    0, n,
                )
            carries = tuple(
                jax.tree_util.tree_map(
                    lambda s: jax.device_put(
                        jnp.zeros(s.shape, s.dtype), self.sharding
                    ),
                    t,
                )
                for t in record.carry_sds
            )
            drain_check = getattr(self.ex, "drain_check", None)
            for k in range(mid, K):
                if drain_check is not None:
                    drain_check()
                carries, flags = record.step_fn(
                    jnp.asarray(k, dtype=jnp.int32),
                    self.feed_args, pctx, carries,
                )
                self._check_flags(record.step_sites, flags, n)
                METRICS.increment("mesh.chunk_steps")
            host = tuple(
                jax.tree_util.tree_map(
                    lambda x: np.asarray(jax.device_get(x)), c
                )
                for c in carries
            )
            CHECKPOINTS.put(steal_key, MeshCheckpoint(
                next_chunk=K,
                n_chunks=K,
                chunk_cap=record.chunk_cap,
                resolved_caps=dict(record.resolved_caps),
                carries_host=host,
                tables=self.feed_tables,
                generations=GENERATIONS.snapshot(self.feed_tables),
            ))
        except Exception:
            pass  # opportunistic: the primary covers [mid, K) itself
        finally:
            _ACTIVE_REPLICA.replica = prev_replica
            done.set()

    def _steal_merge(self, record, carries, steal) -> Optional[tuple]:
        """Primary side: adopt the helper's [mid, K) carries. Byte
        identity holds because `_accumulate` packs live rows densely at
        the front of each shard in chunk execution order — appending
        the helper's packed rows after the primary's per-shard live
        count reproduces exactly the layout a sequential run of chunks
        [mid, K) would have written, and both sides ran the same record
        at the same resolved caps so shard shapes agree. Returns None
        on any disagreement (timeout, caps drift, non-append carry,
        combined overflow): the primary continues sequentially."""
        _mode, mid, steal_key, done, caps, timeout_s = steal
        try:
            from trino_tpu.recovery.checkpoint import CHECKPOINTS

            if not done.wait(timeout_s):
                return None
            ck = CHECKPOINTS.get(steal_key)
            CHECKPOINTS.discard(steal_key)
            if (
                ck is None
                or ck.n_chunks != record.n_chunks
                or ck.resolved_caps != dict(record.resolved_caps)
                or len(ck.carries_host) != len(record.carry_sds)
            ):
                return None
            n = self.ex.n
            merged = []
            for (kind, _fid), mine_dev, theirs in zip(
                record.carry_meta, carries, ck.carries_host
            ):
                if kind != "out":
                    return None
                mine = jax.tree_util.tree_map(
                    lambda x: np.asarray(jax.device_get(x)), mine_dev
                )
                m = _merge_out_carry(mine, theirs, n)
                if m is None:
                    return None
                merged.append(m)
            return tuple(
                jax.device_put(b, self.sharding) for b in merged
            )
        except Exception:
            return None

    def _restore_carries(self, ck, record) -> Optional[tuple]:
        """Re-place a checkpoint's host carries onto the mesh, re-padding
        each accumulator whose capacity rung grew since the snapshot
        (overflow restarts bump caps; live rows stay densely packed at
        the front, so tail padding with dead rows is exact). Returns
        None — start fresh — on any shape disagreement."""
        n = self.ex.n
        try:
            if len(ck.carries_host) != len(record.carry_sds):
                return None
            host = []
            for (_kind, fid), batch in zip(
                record.carry_meta, ck.carries_host
            ):
                site = f"carry:f{fid}"
                old_cap = int(ck.resolved_caps.get(site, 0))
                new_cap = int(record.resolved_caps.get(site, old_cap))
                if old_cap and new_cap != old_cap:
                    if new_cap < old_cap:
                        return None  # shrunk rung: rows may not fit
                    batch = _pad_shards(batch, n, old_cap, new_cap)
                host.append(batch)
            for b, t in zip(host, record.carry_sds):
                bl = jax.tree_util.tree_leaves(b)
                tl = jax.tree_util.tree_leaves(t)
                if len(bl) != len(tl) or any(
                    np.shape(x) != s.shape
                    or np.asarray(x).dtype != s.dtype
                    for x, s in zip(bl, tl)
                ):
                    return None
            return tuple(
                jax.tree_util.tree_map(
                    lambda x: jax.device_put(
                        np.asarray(x), self.sharding
                    ),
                    b,
                )
                for b in host
            )
        except Exception:
            return None

    def _check_flags(self, sites, flag_arr, n):
        vals = np.asarray(jax.device_get(flag_arr))
        if not sites:
            return
        over = vals.reshape(n, -1).max(axis=0)
        sites_over = [
            (site, int(v)) for site, v in zip(sites, over) if v
        ]
        if sites_over:
            raise _Overflow(sites_over)
