"""Engine transaction manager.

Analogue of main/transaction/ + spi/transaction/: the engine-level
TransactionManager hands out transaction ids, connectors join lazily on
first touch, and commit/rollback fans out to every joined connector
handle. Connectors opt in by overriding Connector.begin_transaction;
the default handle is a no-op autocommit (matching the reference, where
most connectors are not transactional across statements).

Scope limits (documented, fail-open like most engines): DML (INSERT)
is transactional for connectors that buffer (memory connector); DDL
(CREATE/DROP TABLE, CTAS table creation) applies immediately and is
NOT rolled back — a ROLLBACK after CTAS leaves an empty table behind.
"""

from __future__ import annotations

import dataclasses
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import uuid
from typing import Dict


class TransactionError(Exception):
    pass


class ConnectorTransactionHandle:
    """spi/transaction/ConnectorTransactionHandle analogue. The default
    is autocommit: commit/rollback are no-ops."""

    def commit(self) -> None:
        pass

    def rollback(self) -> None:
        pass


@dataclasses.dataclass
class TransactionInfo:
    transaction_id: str
    read_only: bool
    # catalog -> joined connector handle
    handles: Dict[str, ConnectorTransactionHandle] = dataclasses.field(
        default_factory=dict
    )
    completed: bool = False


class TransactionManager:
    """main/transaction/InMemoryTransactionManager analogue."""

    def __init__(self, catalogs):
        self.catalogs = catalogs
        self._transactions: Dict[str, TransactionInfo] = {}
        self._lock = named_lock("TransactionManager._lock")

    def begin(self, read_only: bool = False) -> str:
        tx = TransactionInfo(uuid.uuid4().hex[:16], read_only)
        with self._lock:
            self._transactions[tx.transaction_id] = tx
        return tx.transaction_id

    def _get(self, transaction_id: str) -> TransactionInfo:
        tx = self._transactions.get(transaction_id)
        if tx is None or tx.completed:
            raise TransactionError(f"unknown or completed transaction {transaction_id}")
        return tx

    def join(
        self, transaction_id: str, catalog: str, connector=None
    ) -> ConnectorTransactionHandle:
        """Connector joins on first touch (lazy, like the reference's
        per-catalog transaction start)."""
        tx = self._get(transaction_id)
        with self._lock:
            if catalog not in tx.handles:
                if connector is None:
                    connector = self.catalogs.get(catalog)
                begin = getattr(connector, "begin_transaction", None)
                tx.handles[catalog] = (
                    begin(tx.read_only) if begin else ConnectorTransactionHandle()
                )
            return tx.handles[catalog]

    def commit(self, transaction_id: str) -> None:
        tx = self._get(transaction_id)
        tx.completed = True
        errors = []
        for catalog, handle in tx.handles.items():
            try:
                handle.commit()
            except Exception as ex:  # noqa: BLE001 - aggregate and rethrow
                errors.append(f"{catalog}: {ex}")
        self._prune(transaction_id)
        if errors:
            raise TransactionError("commit failed: " + "; ".join(errors))

    def rollback(self, transaction_id: str) -> None:
        tx = self._get(transaction_id)
        tx.completed = True
        for handle in tx.handles.values():
            handle.rollback()
        self._prune(transaction_id)

    def _prune(self, transaction_id: str) -> None:
        """Completed transactions leave the registry immediately — a
        long-lived coordinator must not accumulate them."""
        with self._lock:
            self._transactions.pop(transaction_id, None)

    def is_active(self, transaction_id: str) -> bool:
        tx = self._transactions.get(transaction_id)
        return tx is not None and not tx.completed

    def is_read_only(self, transaction_id: str) -> bool:
        return self._get(transaction_id).read_only
