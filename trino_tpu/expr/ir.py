"""Typed expression IR.

The post-analysis relational expression tree — Trino's RowExpression
(main/sql/relational/RowExpression.java:18, CallExpression.java:26).
Nodes are immutable and carry their result DataType; the analyzer has
already resolved names to channel indices and inserted coercions, so
lowering (compile.py) is purely mechanical.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

from trino_tpu import types as T


class Expr:
    """Base class. Every node has .type (DataType)."""

    type: T.DataType

    def children(self) -> Sequence["Expr"]:
        return ()


@dataclasses.dataclass(frozen=True)
class InputRef(Expr):
    """Reference to input channel `index` — RowExpression's InputReferenceExpression."""

    index: int
    type: T.DataType

    def __repr__(self):
        return f"$[{self.index}:{self.type}]"


@dataclasses.dataclass(frozen=True)
class Literal(Expr):
    """Constant. `value` is a python scalar (str for VARCHAR — lowered
    against the batch dictionary at bind time), or None for NULL."""

    value: Any
    type: T.DataType

    def __repr__(self):
        return f"lit({self.value!r}:{self.type})"


@dataclasses.dataclass(frozen=True)
class LambdaVar(Expr):
    """Parameter slot inside a lambda body (LambdaArgumentDeclaration
    analogue): index 0..n-1 within the enclosing LambdaExpr."""

    index: int
    type: T.DataType

    def __repr__(self):
        return f"$lam{self.index}:{self.type}"


@dataclasses.dataclass(frozen=True)
class LambdaExpr(Expr):
    """A lambda passed to a higher-order function: `body` is an Expr
    over LambdaVar leaves only (captures of outer columns are rejected
    at analysis — documented deviation from the reference's
    LambdaExpression capture support)."""

    body: Expr
    n_params: int
    type: T.DataType  # the body's result type

    def children(self):
        return (self.body,)

    def __repr__(self):
        return f"(lambda/{self.n_params} -> {self.body!r})"


@dataclasses.dataclass(frozen=True)
class Call(Expr):
    """Function/operator application — CallExpression. `name` indexes the
    scalar function registry (functions.py)."""

    name: str
    args: Tuple[Expr, ...]
    type: T.DataType

    def children(self):
        return self.args

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.args))})"


@dataclasses.dataclass(frozen=True)
class Cast(Expr):
    arg: Expr
    type: T.DataType

    def children(self):
        return (self.arg,)

    def __repr__(self):
        return f"cast({self.arg!r} as {self.type})"


@dataclasses.dataclass(frozen=True)
class Case(Expr):
    """Searched CASE: WHEN conds[i] THEN results[i] ... ELSE default.
    default may be None (NULL)."""

    conds: Tuple[Expr, ...]
    results: Tuple[Expr, ...]
    default: Optional[Expr]
    type: T.DataType

    def children(self):
        out = list(self.conds) + list(self.results)
        if self.default is not None:
            out.append(self.default)
        return out


@dataclasses.dataclass(frozen=True)
class InList(Expr):
    """`value IN (literal, ...)` — constant list only (dynamic IN becomes
    a semi-join in the planner, like Trino)."""

    value: Expr
    options: Tuple[Literal, ...]
    type: T.DataType = T.BOOLEAN

    def children(self):
        return (self.value,)


# ---------------------------------------------------------------------------
# Convenience constructors used by analyzer/planner.
# ---------------------------------------------------------------------------


def call(name: str, type_: T.DataType, *args: Expr) -> Call:
    return Call(name, tuple(args), type_)


def and_(*args: Expr) -> Expr:
    args = tuple(a for a in args if a is not None)
    if not args:
        return Literal(True, T.BOOLEAN)
    if len(args) == 1:
        return args[0]
    return Call("and", args, T.BOOLEAN)


def or_(*args: Expr) -> Expr:
    args = tuple(a for a in args if a is not None)
    if not args:
        return Literal(False, T.BOOLEAN)
    if len(args) == 1:
        return args[0]
    return Call("or", tuple(args), T.BOOLEAN)


def not_(a: Expr) -> Expr:
    return Call("not", (a,), T.BOOLEAN)


def comparison(op: str, left: Expr, right: Expr) -> Call:
    return Call(op, (left, right), T.BOOLEAN)


def is_null(a: Expr) -> Call:
    return Call("is_null", (a,), T.BOOLEAN)
