"""Host-side (dictionary-wise) scalar helpers.

Pure-python implementations backing the string/binary breadth functions
in expr/compile.py. These run once per DICTIONARY VALUE at bind time
(the DictionaryAwarePageProjection discipline), never per row, so plain
python is the right tool. Digest algorithms follow the reference's
operator/scalar/VarbinaryFunctions.java; the pattern translators cover
the documented token subset of DateTimeFunctions.java:961
(parse_datetime, Joda) and the Teradata to_date family.
"""

from __future__ import annotations

_M64 = (1 << 64) - 1


def xxhash64(data: bytes, seed: int = 0) -> int:
    """XXH64 (the reference's xxhash64(); io.airlift.slice.XxHash64)."""
    P1, P2, P3, P4, P5 = (
        0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F, 0x165667B19E3779F9,
        0x85EBCA77C2B2AE63, 0x27D4EB2F165667C5,
    )

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & _M64

    n = len(data)
    if n >= 32:
        v1 = (seed + P1 + P2) & _M64
        v2 = (seed + P2) & _M64
        v3 = seed & _M64
        v4 = (seed - P1) & _M64
        i = 0
        while i <= n - 32:
            for j, v in enumerate((v1, v2, v3, v4)):
                lane = int.from_bytes(data[i + 8 * j:i + 8 * j + 8], "little")
                v = rotl((v + lane * P2) & _M64, 31) * P1 & _M64
                if j == 0:
                    v1 = v
                elif j == 1:
                    v2 = v
                elif j == 2:
                    v3 = v
                else:
                    v4 = v
            i += 32
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & _M64
        for v in (v1, v2, v3, v4):
            h = ((h ^ (rotl((v * P2) & _M64, 31) * P1 & _M64)) * P1 + P4) & _M64
    else:
        h = (seed + P5) & _M64
        i = 0
    h = (h + n) & _M64
    while i <= n - 8:
        k = rotl((int.from_bytes(data[i:i + 8], "little") * P2) & _M64, 31)
        h = ((rotl(h ^ (k * P1 & _M64), 27) * P1) + P4) & _M64
        i += 8
    if i <= n - 4:
        h = ((rotl(h ^ (int.from_bytes(data[i:i + 4], "little") * P1 & _M64),
                   23) * P2) + P3) & _M64
        i += 4
    while i < n:
        h = (rotl(h ^ (data[i] * P5 & _M64), 11) * P1) & _M64
        i += 1
    h ^= h >> 33
    h = (h * P2) & _M64
    h ^= h >> 29
    h = (h * P3) & _M64
    h ^= h >> 32
    return h


def murmur3_x64_128(data: bytes, seed: int = 0) -> bytes:
    """MurmurHash3 x64_128 (the reference's murmur3())."""
    C1, C2 = 0x87C37B91114253D5, 0x4CF5AD432745937F

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & _M64

    def fmix(k):
        k ^= k >> 33
        k = (k * 0xFF51AFD7ED558CCD) & _M64
        k ^= k >> 33
        k = (k * 0xC4CEB9FE1A85EC53) & _M64
        k ^= k >> 33
        return k

    h1 = h2 = seed & _M64
    n = len(data)
    nblocks = n // 16
    for b in range(nblocks):
        k1 = int.from_bytes(data[16 * b:16 * b + 8], "little")
        k2 = int.from_bytes(data[16 * b + 8:16 * b + 16], "little")
        h1 ^= (rotl((k1 * C1) & _M64, 31) * C2) & _M64
        h1 = ((rotl(h1, 27) + h2) * 5 + 0x52DCE729) & _M64
        h2 ^= (rotl((k2 * C2) & _M64, 33) * C1) & _M64
        h2 = ((rotl(h2, 31) + h1) * 5 + 0x38495AB5) & _M64
    tail = data[16 * nblocks:]
    k1 = k2 = 0
    for i in range(len(tail) - 1, 7, -1):
        k2 = (k2 << 8) | tail[i]
    for i in range(min(len(tail), 8) - 1, -1, -1):
        k1 = (k1 << 8) | tail[i]
    if len(tail) > 8:
        h2 ^= (rotl((k2 * C2) & _M64, 33) * C1) & _M64
    if len(tail) > 0:
        h1 ^= (rotl((k1 * C1) & _M64, 31) * C2) & _M64
    h1 = (h1 ^ n) & _M64
    h2 = (h2 ^ n) & _M64
    h1 = (h1 + h2) & _M64
    h2 = (h2 + h1) & _M64
    h1 = fmix(h1)
    h2 = fmix(h2)
    h1 = (h1 + h2) & _M64
    h2 = (h2 + h1) & _M64
    return h1.to_bytes(8, "little") + h2.to_bytes(8, "little")


def porter_stem(word: str) -> str:
    """Porter (1980) stemmer — the algorithm behind the reference's
    word_stem() (Lucene EnglishStemmer for 'en')."""
    w = word.lower()
    if len(w) <= 2:
        return w
    vowels = "aeiou"

    def is_cons(s, i):
        c = s[i]
        if c in vowels:
            return False
        if c == "y":
            return i == 0 or not is_cons(s, i - 1)
        return True

    def measure(s):
        m, i, n = 0, 0, len(s)
        while i < n and is_cons(s, i):
            i += 1
        while True:
            while i < n and not is_cons(s, i):
                i += 1
            if i >= n:
                return m
            m += 1
            while i < n and is_cons(s, i):
                i += 1

    def has_vowel(s):
        return any(not is_cons(s, i) for i in range(len(s)))

    def ends_cvc(s):
        if len(s) < 3:
            return False
        if not (is_cons(s, -3 + len(s)) and not is_cons(s, len(s) - 2)
                and is_cons(s, len(s) - 1)):
            return False
        return s[-1] not in "wxy"

    def double_cons(s):
        return (len(s) >= 2 and s[-1] == s[-2] and is_cons(s, len(s) - 1))

    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("ss"):
        pass
    elif w.endswith("s"):
        w = w[:-1]
    # step 1b
    flag = False
    if w.endswith("eed"):
        if measure(w[:-3]) > 0:
            w = w[:-1]
    elif w.endswith("ed") and has_vowel(w[:-2]):
        w, flag = w[:-2], True
    elif w.endswith("ing") and has_vowel(w[:-3]):
        w, flag = w[:-3], True
    if flag:
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif double_cons(w) and not w.endswith(("l", "s", "z")):
            w = w[:-1]
        elif measure(w) == 1 and ends_cvc(w):
            w += "e"
    # step 1c
    if w.endswith("y") and has_vowel(w[:-1]):
        w = w[:-1] + "i"
    # steps 2-4: suffix tables (condition: measure of the stem)
    for suffixes, m_min in (
        ((("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
          ("anci", "ance"), ("izer", "ize"), ("abli", "able"),
          ("alli", "al"), ("entli", "ent"), ("eli", "e"), ("ousli", "ous"),
          ("ization", "ize"), ("ation", "ate"), ("ator", "ate"),
          ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
          ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"),
          ("biliti", "ble")), 0),
        ((("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
          ("ical", "ic"), ("ful", ""), ("ness", "")), 0),
        ((("al", ""), ("ance", ""), ("ence", ""), ("er", ""), ("ic", ""),
          ("able", ""), ("ible", ""), ("ant", ""), ("ement", ""),
          ("ment", ""), ("ent", ""), ("ou", ""), ("ism", ""), ("ate", ""),
          ("iti", ""), ("ous", ""), ("ive", ""), ("ize", "")), 1),
    ):
        for suf, rep in suffixes:
            if w.endswith(suf):
                stem = w[: len(w) - len(suf)]
                if measure(stem) > m_min:
                    # step 4 "ion" needs s/t before (handled via ou/ion)
                    w = stem + rep
                break
    # step 5a
    if w.endswith("e"):
        m = measure(w[:-1])
        if m > 1 or (m == 1 and not ends_cvc(w[:-1])):
            w = w[:-1]
    # step 5b
    if measure(w) > 1 and double_cons(w) and w.endswith("l"):
        w = w[:-1]
    return w


# longest token first WITHIN each letter family — a shorter prefix
# listed earlier would shadow the longer token ('MM' before 'MMM' turned
# month names into '%m%m')
_JODA = [
    # longest-first within a letter family (startswith scan)
    ("yyyy", "%Y"), ("yyy", "%Y"), ("yy", "%y"), ("y", "%Y"),
    ("YYYY", "%Y"), ("Y", "%Y"),
    ("MMMM", "%B"), ("MMM", "%b"), ("MM", "%m"), ("M", "%m"),
    ("DDD", "%j"), ("DD", "%j"), ("D", "%j"),
    ("dd", "%d"), ("d", "%d"),
    ("HH", "%H"), ("H", "%H"), ("hh", "%I"), ("h", "%I"),
    ("mm", "%M"), ("m", "%M"),
    ("SSS", "%f"), ("ss", "%S"), ("s", "%S"),
    ("a", "%p"),
    ("EEEE", "%A"), ("EEE", "%a"), ("EE", "%a"), ("E", "%a"),
    ("e", "%u"),
    ("ww", "%V"), ("w", "%V"),  # week of ISO week-year
    ("ZZ", "%z"), ("Z", "%z"), ("zzzz", "%Z"), ("z", "%Z"),
]

_ORACLE = [
    ("yyyy", "%Y"), ("yy", "%y"), ("mm", "%m"), ("dd", "%d"),
    ("hh24", "%H"), ("hh", "%I"), ("mi", "%M"), ("ss", "%S"),
]


def _translate(fmt: str, table, casefold: bool) -> str:
    out, i = [], 0
    while i < len(fmt):
        if fmt[i] == "'":  # Joda literal quoting
            j = fmt.find("'", i + 1)
            if j < 0:
                out.append(fmt[i + 1:])
                break
            out.append(fmt[i + 1:j].replace("%", "%%"))
            i = j + 1
            continue
        for tok, rep in table:
            # Joda tokens are case-sensitive (MM = month, mm = minute);
            # the Oracle/Teradata table is case-insensitive
            hit = fmt.startswith(tok, i) or (
                casefold and fmt.lower().startswith(tok, i)
            )
            if hit:
                out.append(rep)
                i += len(tok)
                break
        else:
            out.append(fmt[i].replace("%", "%%"))
            i += 1
    return "".join(out)


def joda_to_strptime(fmt: str) -> str:
    return _translate(fmt, _JODA, casefold=False)


def iso_to_micros(s: str, trim_nanos: bool = False):
    """ISO-8601 text -> UTC epoch microseconds, None if unparseable.
    The ONE conversion shared by timestamp literals, varchar->timestamp
    casts, and the from_iso8601 functions (exact integer arithmetic —
    total_seconds() would lose microseconds past ~year 2255)."""
    import datetime as _dt

    v = s.strip().replace("Z", "+00:00").replace("z", "+00:00")
    if trim_nanos and "." in v:
        head, _, frac = v.partition(".")
        tz = ""
        for sep in ("+", "-"):
            p = frac.find(sep)
            if p > 0:
                frac, tz = frac[:p], frac[p:]
        v = f"{head}.{frac[:6]}{tz}"
    try:
        dt = _dt.datetime.fromisoformat(v)
    except ValueError:
        return None
    return dt_to_micros(dt)


def dt_to_micros(dt) -> int:
    """tz-aware or naive datetime -> UTC epoch microseconds, exactly."""
    import datetime as _dt

    if dt.tzinfo is not None:
        dt = dt.astimezone(_dt.timezone.utc).replace(tzinfo=None)
    return (dt - _dt.datetime(1970, 1, 1)) // _dt.timedelta(microseconds=1)


def oracle_to_strptime(fmt: str) -> str:
    return _translate(fmt, _ORACLE, casefold=True)


# ---------------------------------------------------------------------------
# Sketch digests (HyperLogLog / T-Digest) on the varchar carrier
# ---------------------------------------------------------------------------
# The reference gives HyperLogLog and TDigest first-class SPI types
# (spi/type/HyperLogLogType, TDigestType) with varbinary wire formats;
# this engine carries serialized digests as dictionary varchar: "hll:"
# + base64 registers, "td:" + base64 centroid list. approx_set /
# tdigest_agg build them on the aggregation collect path, merge() unions
# them, and the scalar accessors below parse them per dictionary value.

_HLL_P = 12  # 4096 registers, ~1.6% standard error (reference default 11-16)


def hll_from_values(values) -> str:
    import base64

    m = 1 << _HLL_P
    regs = bytearray(m)
    for v in values:
        h = xxhash64(repr(v).encode())
        idx = h & (m - 1)
        w = h >> _HLL_P
        rank = (64 - _HLL_P) - w.bit_length() + 1 if w else (64 - _HLL_P) + 1
        if rank > regs[idx]:
            regs[idx] = rank
    return "hll:" + base64.b64encode(bytes(regs)).decode()


def hll_merge(digests) -> str:
    import base64

    m = 1 << _HLL_P
    regs = bytearray(m)
    for d in digests:
        if not d or not d.startswith("hll:"):
            continue
        other = base64.b64decode(d[4:])
        for i in range(m):
            if other[i] > regs[i]:
                regs[i] = other[i]
    return "hll:" + base64.b64encode(bytes(regs)).decode()


def hll_cardinality(digest: str):
    import math

    import base64

    if not digest or not digest.startswith("hll:"):
        return None
    regs = base64.b64decode(digest[4:])
    m = len(regs)
    inv = sum(2.0 ** -r for r in regs)
    zeros = regs.count(0)
    alpha = 0.7213 / (1 + 1.079 / m)
    e = alpha * m * m / inv
    if e <= 2.5 * m and zeros:
        e = m * math.log(m / zeros)  # linear counting for small n
    return int(round(e))


_TD_MAX = 128  # centroid cap (reference TDigest default compression 100)


def tdigest_from_values(values) -> str:
    pts = sorted((float(v), 1.0) for v in values)
    merged: list = []
    for v, c in pts:
        if merged and merged[-1][0] == v:
            merged[-1][1] += c
        else:
            merged.append([v, c])
    return _td_compress(merged)


def _td_compress(cents) -> str:
    """One-pass merging digest (Dunning's MergingDigest, the algorithm
    behind the reference's TDigest): sweep sorted centroids, folding
    neighbors while the running weight stays under the k1 q-scale
    allowance ~ q(1-q) — capacity shrinks toward the tails, so extreme
    quantiles stay sharp."""
    import base64
    import json

    cents = sorted(cents)
    total = sum(c for _, c in cents)
    if len(cents) > _TD_MAX and total > 0:
        out = []
        cur_v, cur_c = cents[0][0], cents[0][1]
        q = 0.0  # weight fully to the left of the current centroid
        for v, c in cents[1:]:
            qm = (q + (cur_c + c) / 2.0) / total
            allow = 4.0 * total * max(qm * (1 - qm), 1e-9) / _TD_MAX
            if cur_c + c <= allow:
                cur_v = (cur_v * cur_c + v * c) / (cur_c + c)
                cur_c += c
            else:
                out.append([cur_v, cur_c])
                q += cur_c
                cur_v, cur_c = v, c
        out.append([cur_v, cur_c])
        cents = out
    payload = json.dumps([[v, c] for v, c in cents])
    return "td:" + base64.b64encode(payload.encode()).decode()


def _td_parse(digest: str):
    import base64
    import json

    if not digest or not digest.startswith("td:"):
        return None
    return json.loads(base64.b64decode(digest[3:]))


def tdigest_merge(digests) -> str:
    cents: list = []
    for d in digests:
        p = _td_parse(d)
        if p:
            cents.extend(p)
    return _td_compress(cents)


def tdigest_value_at_quantile(digest: str, q: float):
    cents = _td_parse(digest)
    if not cents:
        return None
    total = sum(c for _, c in cents)
    target = q * total
    run = 0.0
    for v, c in cents:
        if run + c >= target:
            return v
        run += c
    return cents[-1][0]


def tdigest_quantile_at_value(digest: str, x: float):
    cents = _td_parse(digest)
    if not cents:
        return None
    total = sum(c for _, c in cents)
    run = 0.0
    for v, c in cents:
        if v > x:
            break
        run += c
    return run / total if total else None


def sketch_merge(digests) -> str:
    """merge() dispatches on the wire prefix (the reference overloads
    merge() per sketch type; one carrier, one name here)."""
    ds = [d for d in digests if d]
    if any(d.startswith("td:") for d in ds):
        return tdigest_merge(ds)
    return hll_merge(ds)
