"""Vectorized scalar helpers used by the expression compiler.

The device-side bodies of the scalar function library (Trino's
main/operator/scalar/, ~140 files — SURVEY.md §2.10). Only functions
whose semantics need real code live here; trivial jnp mappings are
declared inline in compile.py's registry.
"""

from __future__ import annotations

import datetime
import re

import jax.numpy as jnp
import numpy as np

EPOCH = datetime.date(1970, 1, 1)


def date_to_days(d: datetime.date) -> int:
    return (d - EPOCH).days


def days_to_date(days: int) -> datetime.date:
    return EPOCH + datetime.timedelta(days=int(days))


# -- civil-calendar decomposition, vectorized (Howard Hinnant's algorithm) --
# Pure int32 arithmetic: runs on the TPU VPU without host round-trips, the
# replacement for Trino's Joda-based DateTimeFunctions (extract YEAR/...).


def civil_from_days(days: jnp.ndarray):
    """days since 1970-01-01 -> (year, month, day), vectorized.

    Hinnant's civil_from_days restated for floor division: the original
    compensates C truncating division with a (z - 146096) shift; jnp's
    // already floors, so the era is simply z // 146097."""
    z = days.astype(jnp.int32) + 719468
    era = z // 146097
    doe = z - era * 146097  # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365  # [0, 399]
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)  # [0, 365]
    mp = (5 * doy + 2) // 153  # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1  # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)  # [1, 12]
    year = jnp.where(m <= 2, y + 1, y)
    return year, m, d


def extract_year(days):
    return civil_from_days(days)[0]


def extract_month(days):
    return civil_from_days(days)[1]


def extract_day(days):
    return civil_from_days(days)[2]


def add_months_scalar(d: datetime.date, months: int) -> datetime.date:
    """Host-side date + INTERVAL YEAR/MONTH (constant folding path)."""
    y = d.year + (d.month - 1 + months) // 12
    m = (d.month - 1 + months) % 12 + 1
    # clamp day like SQL (e.g. Jan 31 + 1 month = Feb 28/29)
    last = (
        datetime.date(y + (m == 12), m % 12 + 1, 1) - datetime.timedelta(days=1)
    ).day
    return datetime.date(y, m, min(d.day, last))


# -- decimal arithmetic on scaled int64 --


def round_half_away(x: jnp.ndarray) -> jnp.ndarray:
    """Float rounding half away from zero — Trino's MathFunctions.round /
    cast-to-integer convention (NOT banker's rounding like jnp.round)."""
    return jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)


def div_trunc(num: jnp.ndarray, den: jnp.ndarray) -> jnp.ndarray:
    """Integer division truncating toward zero (SQL), not floor."""
    den_safe = jnp.where(den == 0, jnp.ones((), den.dtype), den)
    sign = jnp.where((num < 0) ^ (den_safe < 0), -1, 1).astype(num.dtype)
    return sign * (jnp.abs(num) // jnp.abs(den_safe))


def div_round_half_away(num: jnp.ndarray, den: jnp.ndarray) -> jnp.ndarray:
    """Integer divide rounding half away from zero — Trino's decimal
    division rounding (lib ... Decimals). Division by zero yields 0; the
    caller turns it into NULL."""
    den_safe = jnp.where(den == 0, jnp.ones((), den.dtype), den)
    sign = jnp.where((num < 0) ^ (den_safe < 0), -1, 1).astype(num.dtype)
    q = (jnp.abs(num) + jnp.abs(den_safe) // 2) // jnp.abs(den_safe)
    return sign * q


def like_to_regex(pattern: str, escape: str | None = None) -> "re.Pattern":
    """SQL LIKE pattern -> anchored python regex (host side: evaluated
    over dictionary values only, never per row)."""
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


def dictionary_like_table(dictionary, pattern: str, escape=None) -> np.ndarray:
    rx = like_to_regex(pattern, escape)
    return np.asarray([rx.match(v) is not None for v in dictionary.values], dtype=bool)


# -- civil-calendar composition + date arithmetic (DateTimeFunctions
# analogues: date_trunc/date_add/date_diff/week/quarter/... — all pure
# int32 VPU arithmetic, no host round-trips) --


def days_from_civil(y: jnp.ndarray, m: jnp.ndarray, d: jnp.ndarray):
    """(year, month, day) -> days since 1970-01-01 (Hinnant's
    days_from_civil with floor division)."""
    y = y.astype(jnp.int32) - (m <= 2)
    era = y // 400
    yoe = y - era * 400  # [0, 399]
    mp = jnp.where(m > 2, m - 3, m + 9)  # [0, 11]
    doy = (153 * mp + 2) // 5 + d - 1  # [0, 365]
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy  # [0, 146096]
    return era * 146097 + doe - 719468


def days_in_month(y: jnp.ndarray, m: jnp.ndarray):
    """Length of month m in year y, vectorized."""
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    lengths = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                          dtype=jnp.int32)
    base = jnp.take(lengths, jnp.clip(m - 1, 0, 11))
    return jnp.where((m == 2) & leap, 29, base)


def day_of_week(days: jnp.ndarray):
    """ISO day-of-week: Monday=1..Sunday=7 (1970-01-01 was a Thursday)."""
    return (days.astype(jnp.int32) + 3) % 7 + 1


def day_of_year(days: jnp.ndarray):
    y, _, _ = civil_from_days(days)
    jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return days.astype(jnp.int32) - jan1 + 1


def week_of_year(days: jnp.ndarray):
    """ISO-8601 week number: the week containing this date's Thursday."""
    days = days.astype(jnp.int32)
    thursday = days - (day_of_week(days) - 4)
    y, _, _ = civil_from_days(thursday)
    jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return (thursday - jan1) // 7 + 1


def year_of_week(days: jnp.ndarray):
    """ISO week-numbering year: the calendar year of this date's
    Thursday (DateTimeFunctions.yearOfWeekFromDate)."""
    days = days.astype(jnp.int32)
    thursday = days - (day_of_week(days) - 4)
    return civil_from_days(thursday)[0]


def date_trunc_days(unit: str, days: jnp.ndarray):
    """date_trunc on epoch-day values (DATE resolution units)."""
    days = days.astype(jnp.int32)
    if unit == "day":
        return days
    if unit == "week":  # ISO week start: Monday
        return days - (day_of_week(days) - 1)
    y, m, d = civil_from_days(days)
    one = jnp.ones_like(y)
    if unit == "month":
        return days_from_civil(y, m, one)
    if unit == "quarter":
        return days_from_civil(y, ((m - 1) // 3) * 3 + 1, one)
    if unit == "year":
        return days_from_civil(y, one, one)
    raise ValueError(f"unsupported date_trunc unit {unit!r}")


def add_months_vec(days: jnp.ndarray, n: jnp.ndarray):
    """date + n months with SQL end-of-month clamping, vectorized."""
    y, m, d = civil_from_days(days)
    total = y * 12 + (m - 1) + n.astype(jnp.int32)
    ny = total // 12
    nm = total % 12 + 1
    nd = jnp.minimum(d, days_in_month(ny, nm))
    return days_from_civil(ny, nm, nd)


def date_add_days(unit: str, n: jnp.ndarray, days: jnp.ndarray):
    n = n.astype(jnp.int32)
    days = days.astype(jnp.int32)
    if unit == "day":
        return days + n
    if unit == "week":
        return days + 7 * n
    if unit == "month":
        return add_months_vec(days, n)
    if unit == "quarter":
        return add_months_vec(days, 3 * n)
    if unit == "year":
        return add_months_vec(days, 12 * n)
    raise ValueError(f"unsupported date_add unit {unit!r}")


def date_diff_days(unit: str, a: jnp.ndarray, b: jnp.ndarray):
    """date_diff(unit, a, b) = signed count of unit boundaries from a to
    b (Trino: b - a). Month/year counts are full months elapsed."""
    a = a.astype(jnp.int32)
    b = b.astype(jnp.int32)
    if unit == "day":
        return b - a
    if unit == "week":
        return div_trunc(b - a, jnp.full_like(b, 7))
    if unit in ("month", "quarter", "year"):
        ya, ma, da = civil_from_days(a)
        yb, mb, db = civil_from_days(b)
        months = (yb * 12 + mb) - (ya * 12 + ma)
        # back off one month if the day-of-month hasn't been reached
        months = months - jnp.where(
            (months > 0) & (db < da), 1, 0
        ) + jnp.where((months < 0) & (db > da), 1, 0)
        if unit == "month":
            return months
        if unit == "quarter":
            return div_trunc(months, jnp.full_like(months, 3))
        return div_trunc(months, jnp.full_like(months, 12))
    raise ValueError(f"unsupported date_diff unit {unit!r}")


def last_day_of_month_days(days: jnp.ndarray):
    y, m, _ = civil_from_days(days)
    return days_from_civil(y, m, days_in_month(y, m))


def sqrt_exact(x: jnp.ndarray) -> jnp.ndarray:
    """sqrt with integer-root snapping: TPU's software-emulated f64
    sqrt can come out 1 ulp low (sqrt(49) = 7 - 2.8e-14), which breaks
    floor/truncate-of-sqrt idioms; snap to the nearest integer when it
    is the exact root (MathFunctions.sqrt contract on the JVM)."""
    y = jnp.sqrt(x)
    yr = round_half_away(y)
    return jnp.where(yr * yr == x, yr, y)
