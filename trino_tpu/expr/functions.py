"""Vectorized scalar helpers used by the expression compiler.

The device-side bodies of the scalar function library (Trino's
main/operator/scalar/, ~140 files — SURVEY.md §2.10). Only functions
whose semantics need real code live here; trivial jnp mappings are
declared inline in compile.py's registry.
"""

from __future__ import annotations

import datetime
import re

import jax.numpy as jnp
import numpy as np

EPOCH = datetime.date(1970, 1, 1)


def date_to_days(d: datetime.date) -> int:
    return (d - EPOCH).days


def days_to_date(days: int) -> datetime.date:
    return EPOCH + datetime.timedelta(days=int(days))


# -- civil-calendar decomposition, vectorized (Howard Hinnant's algorithm) --
# Pure int32 arithmetic: runs on the TPU VPU without host round-trips, the
# replacement for Trino's Joda-based DateTimeFunctions (extract YEAR/...).


def civil_from_days(days: jnp.ndarray):
    """days since 1970-01-01 -> (year, month, day), vectorized.

    Hinnant's civil_from_days restated for floor division: the original
    compensates C truncating division with a (z - 146096) shift; jnp's
    // already floors, so the era is simply z // 146097."""
    z = days.astype(jnp.int32) + 719468
    era = z // 146097
    doe = z - era * 146097  # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365  # [0, 399]
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)  # [0, 365]
    mp = (5 * doy + 2) // 153  # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1  # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)  # [1, 12]
    year = jnp.where(m <= 2, y + 1, y)
    return year, m, d


def extract_year(days):
    return civil_from_days(days)[0]


def extract_month(days):
    return civil_from_days(days)[1]


def extract_day(days):
    return civil_from_days(days)[2]


def add_months_scalar(d: datetime.date, months: int) -> datetime.date:
    """Host-side date + INTERVAL YEAR/MONTH (constant folding path)."""
    y = d.year + (d.month - 1 + months) // 12
    m = (d.month - 1 + months) % 12 + 1
    # clamp day like SQL (e.g. Jan 31 + 1 month = Feb 28/29)
    last = (
        datetime.date(y + (m == 12), m % 12 + 1, 1) - datetime.timedelta(days=1)
    ).day
    return datetime.date(y, m, min(d.day, last))


# -- decimal arithmetic on scaled int64 --


def round_half_away(x: jnp.ndarray) -> jnp.ndarray:
    """Float rounding half away from zero — Trino's MathFunctions.round /
    cast-to-integer convention (NOT banker's rounding like jnp.round)."""
    return jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)


def div_trunc(num: jnp.ndarray, den: jnp.ndarray) -> jnp.ndarray:
    """Integer division truncating toward zero (SQL), not floor."""
    den_safe = jnp.where(den == 0, jnp.ones((), den.dtype), den)
    sign = jnp.where((num < 0) ^ (den_safe < 0), -1, 1).astype(num.dtype)
    return sign * (jnp.abs(num) // jnp.abs(den_safe))


def div_round_half_away(num: jnp.ndarray, den: jnp.ndarray) -> jnp.ndarray:
    """Integer divide rounding half away from zero — Trino's decimal
    division rounding (lib ... Decimals). Division by zero yields 0; the
    caller turns it into NULL."""
    den_safe = jnp.where(den == 0, jnp.ones((), den.dtype), den)
    sign = jnp.where((num < 0) ^ (den_safe < 0), -1, 1).astype(num.dtype)
    q = (jnp.abs(num) + jnp.abs(den_safe) // 2) // jnp.abs(den_safe)
    return sign * q


def like_to_regex(pattern: str, escape: str | None = None) -> "re.Pattern":
    """SQL LIKE pattern -> anchored python regex (host side: evaluated
    over dictionary values only, never per row)."""
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


def dictionary_like_table(dictionary, pattern: str, escape=None) -> np.ndarray:
    rx = like_to_regex(pattern, escape)
    return np.asarray([rx.match(v) is not None for v in dictionary.values], dtype=bool)
