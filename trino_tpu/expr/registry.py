"""Function registry: the catalog of callable functions.

Analogue of the reference's function-resolution layer
(main/metadata/SystemFunctionBundle.java:351 registering ~1,400
functions, FunctionResolver + Signature matching — SURVEY.md §2.10).
Each entry declares name, aliases, arity bounds, category, a one-line
description (surfaced by SHOW FUNCTIONS), and a return-type rule.

Resolution order in the analyzer: special forms first (CASE-like `if`,
constant folds such as `pi()`/`chr()`, aggregate/window detection), then
this registry. Entries whose `type_rule` is None are typed by the
analyzer's special-case code and exist here for the catalog surface;
entries WITH a rule are fully resolved from the registry — every newly
added scalar goes that way, so breadth grows declaratively.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from trino_tpu import types as T


@dataclasses.dataclass(frozen=True)
class FunctionMetadata:
    name: str
    category: str            # scalar | aggregate | window
    min_arity: int
    max_arity: Optional[int]  # None = variadic
    returns: str             # signature text for SHOW FUNCTIONS
    description: str
    aliases: Tuple[str, ...] = ()
    # arg types -> result DataType; None = analyzer special-cases typing
    type_rule: Optional[Callable[[Sequence[T.DataType]], T.DataType]] = None
    canonical: Optional[str] = None  # IR name when != `name`
    # argument positions that must be literal constants — checked at
    # ANALYSIS time so a column argument fails with AnalysisError, not
    # a binder assertion mid-execution
    const_args: Tuple[int, ...] = ()
    # concrete per-type signatures this engine genuinely accepts.
    # SHOW FUNCTIONS lists one row per overload, the reference's unit
    # (SystemFunctionBundle registers abs seven times — one per numeric
    # type; GlobalFunctionCatalog rows are per-signature). Empty = one
    # row with `returns` as the signature.
    overloads: Tuple[str, ...] = ()


class FunctionRegistry:
    def __init__(self):
        self._by_name: Dict[str, FunctionMetadata] = {}

    def register(self, meta: FunctionMetadata) -> None:
        for n in (meta.name, *meta.aliases):
            self._by_name[n] = meta

    def get(self, name: str) -> Optional[FunctionMetadata]:
        return self._by_name.get(name.lower())

    def resolve(self, name: str, arg_types: Sequence[T.DataType]):
        """(canonical_name, out_type) or None if the registry doesn't
        own this name's typing (analyzer special case or unknown)."""
        meta = self.get(name)
        if meta is None or meta.type_rule is None:
            return None
        n = len(arg_types)
        if n < meta.min_arity or (
            meta.max_arity is not None and n > meta.max_arity
        ):
            want = (
                str(meta.min_arity)
                if meta.max_arity == meta.min_arity
                else f"{meta.min_arity}..{meta.max_arity or 'N'}"
            )
            raise ValueError(
                f"{meta.name}() expects {want} arguments, got {n}"
            )
        return meta.canonical or meta.name, meta.type_rule(arg_types)

    def all(self) -> List[FunctionMetadata]:
        seen = {}
        for meta in self._by_name.values():
            seen[meta.name] = meta
        return sorted(seen.values(), key=lambda m: (m.category, m.name))


REGISTRY = FunctionRegistry()

_VARCHAR = lambda a: T.VARCHAR  # noqa: E731
_BIGINT = lambda a: T.BIGINT  # noqa: E731
_DOUBLE = lambda a: T.DOUBLE  # noqa: E731
_BOOLEAN = lambda a: T.BOOLEAN  # noqa: E731
_SAME = lambda a: a[0]  # noqa: E731


def _reg(name, category, lo, hi, returns, desc, aliases=(),
         rule=None, canonical=None, const_args=()):
    REGISTRY.register(FunctionMetadata(
        name, category, lo, hi, returns, desc, tuple(aliases), rule,
        canonical, tuple(const_args),
    ))


# --- scalars typed by the analyzer's special cases (catalog entries) ---
for name, lo, hi, ret, desc, aliases in [
    ("abs", 1, 1, "same", "absolute value", ()),
    ("round", 1, 2, "same", "round to scale digits, half away from zero", ()),
    ("floor", 1, 1, "bigint|double", "largest integer <= x", ()),
    ("ceil", 1, 1, "bigint|double", "smallest integer >= x", ("ceiling",)),
    ("sqrt", 1, 1, "double", "square root", ()),
    ("ln", 1, 1, "double", "natural logarithm", ()),
    ("exp", 1, 1, "double", "Euler's number raised to x", ()),
    ("power", 2, 2, "double", "x raised to y", ("pow",)),
    ("log2", 1, 1, "double", "base-2 logarithm", ()),
    ("log10", 1, 1, "double", "base-10 logarithm", ()),
    ("log", 2, 2, "double", "logarithm of x in base b", ()),
    ("mod", 2, 2, "same", "remainder truncated toward zero", ()),
    ("sign", 1, 1, "bigint|double", "signum", ()),
    ("truncate", 1, 2, "same", "truncate toward zero", ()),
    ("sin", 1, 1, "double", "sine", ()),
    ("cos", 1, 1, "double", "cosine", ()),
    ("tan", 1, 1, "double", "tangent", ()),
    ("asin", 1, 1, "double", "arc sine", ()),
    ("acos", 1, 1, "double", "arc cosine", ()),
    ("atan", 1, 1, "double", "arc tangent", ()),
    ("atan2", 2, 2, "double", "two-argument arc tangent", ()),
    ("sinh", 1, 1, "double", "hyperbolic sine", ()),
    ("cosh", 1, 1, "double", "hyperbolic cosine", ()),
    ("tanh", 1, 1, "double", "hyperbolic tangent", ()),
    ("cbrt", 1, 1, "double", "cube root", ()),
    ("degrees", 1, 1, "double", "radians to degrees", ()),
    ("radians", 1, 1, "double", "degrees to radians", ()),
    ("pi", 0, 0, "double", "the constant pi", ()),
    ("e", 0, 0, "double", "Euler's number", ()),
    ("nan", 0, 0, "double", "NaN", ()),
    ("infinity", 0, 0, "double", "positive infinity", ()),
    ("is_nan", 1, 1, "boolean", "true if x is NaN", ()),
    ("is_infinite", 1, 1, "boolean", "true if x is infinite", ()),
    ("is_finite", 1, 1, "boolean", "true if x is finite", ()),
    ("bitwise_and", 2, 2, "bigint", "bitwise AND", ()),
    ("bitwise_or", 2, 2, "bigint", "bitwise OR", ()),
    ("bitwise_xor", 2, 2, "bigint", "bitwise XOR", ()),
    ("bitwise_not", 1, 1, "bigint", "bitwise NOT", ()),
    ("bitwise_left_shift", 2, 2, "bigint", "shift left", ()),
    ("bitwise_right_shift", 2, 2, "bigint", "logical shift right", ()),
    ("greatest", 1, None, "same", "largest of the arguments", ()),
    ("least", 1, None, "same", "smallest of the arguments", ()),
    ("coalesce", 1, None, "same", "first non-null argument", ()),
    ("nullif", 2, 2, "same", "NULL if equal, else first argument", ()),
    ("if", 2, 3, "same", "conditional value", ()),
    ("typeof", 1, 1, "varchar", "type of the argument", ()),
    ("substr", 2, 3, "varchar", "substring from position", ("substring",)),
    ("upper", 1, 1, "varchar", "uppercase", ()),
    ("lower", 1, 1, "varchar", "lowercase", ()),
    ("length", 1, 1, "bigint", "string length in characters", ()),
    ("trim", 1, 1, "varchar", "strip leading+trailing whitespace", ()),
    ("ltrim", 1, 1, "varchar", "strip leading whitespace", ()),
    ("rtrim", 1, 1, "varchar", "strip trailing whitespace", ()),
    ("reverse", 1, 1, "varchar", "reverse the characters", ()),
    ("replace", 2, 3, "varchar", "replace occurrences", ()),
    ("concat", 2, None, "varchar", "concatenate strings", ()),
    ("starts_with", 2, 2, "boolean", "prefix test", ()),
    ("ends_with", 2, 2, "boolean", "suffix test", ()),
    ("strpos", 2, 2, "bigint", "1-based position of substring (0 = absent)", ()),
    ("codepoint", 1, 1, "bigint", "code point of the single character", ()),
    ("chr", 1, 1, "varchar", "character for a code point", ()),
    ("split_part", 3, 3, "varchar", "field at index after splitting", ()),
    ("lpad", 3, 3, "varchar", "pad on the left", ()),
    ("rpad", 3, 3, "varchar", "pad on the right", ()),
    ("translate", 3, 3, "varchar", "per-character mapping", ()),
    ("regexp_like", 2, 2, "boolean", "regex match test", ()),
    ("regexp_extract", 2, 3, "varchar", "first regex match or group", ()),
    ("regexp_replace", 2, 3, "varchar", "replace regex matches", ()),
    ("regexp_count", 2, 2, "bigint", "count regex matches", ()),
    ("year", 1, 1, "bigint", "year of a date", ()),
    ("month", 1, 1, "bigint", "month of a date", ()),
    ("day", 1, 1, "bigint", "day of month", ("day_of_month",)),
    ("quarter", 1, 1, "bigint", "quarter of the year", ()),
    ("week", 1, 1, "bigint", "ISO week of the year", ("week_of_year",)),
    ("day_of_week", 1, 1, "bigint", "ISO day of week (Mon=1)", ("dow",)),
    ("day_of_year", 1, 1, "bigint", "day of the year", ("doy",)),
    ("date_trunc", 2, 2, "date", "truncate to unit", ()),
    ("date_add", 3, 3, "date", "add n units", ()),
    ("date_diff", 3, 3, "bigint", "signed unit boundaries between dates", ()),
    ("last_day_of_month", 1, 1, "date", "last day of the month", ()),
    ("cardinality", 1, 1, "bigint", "array length", ()),
    ("sequence", 2, 3, "array", "integer sequence array", ()),
    ("contains", 2, 2, "boolean", "array containment", ()),
    ("element_at", 2, 2, "element", "array element at index", ()),
    ("array_min", 1, 1, "element", "smallest array element", ()),
    ("array_max", 1, 1, "element", "largest array element", ()),
    ("array_position", 2, 2, "bigint", "1-based index of value", ()),
    ("array_distinct", 1, 1, "array", "distinct elements", ()),
    ("array_sort", 1, 1, "array", "sorted elements", ()),
    ("array_join", 2, 3, "varchar", "join elements with separator", ()),
]:
    _reg(name, "scalar", lo, hi, ret, desc, aliases)

# --- registry-typed scalars (added breadth; typing resolved HERE).
# Each entry: (name, lo, hi, rule, ret, desc, aliases[, const_args]) ---
for entry in [
    # hashing / encoding (operator/scalar/VarbinaryFunctions analogues;
    # digests render as lowercase hex varchar — the engine's varbinary
    # carrier is dictionary-encoded varchar)
    ("md5", 1, 1, _VARCHAR, "varchar", "MD5 digest as lowercase hex", ()),
    ("sha1", 1, 1, _VARCHAR, "varchar", "SHA-1 digest as lowercase hex", ()),
    ("sha256", 1, 1, _VARCHAR, "varchar", "SHA-256 digest as lowercase hex", ()),
    ("crc32", 1, 1, _BIGINT, "bigint", "CRC-32 checksum", ()),
    ("to_hex", 1, 1, _VARCHAR, "varchar", "bytes to uppercase hex", ()),
    ("from_hex", 1, 1, _VARCHAR, "varchar", "hex to bytes (as varchar)", ()),
    ("to_base64", 1, 1, _VARCHAR, "varchar", "bytes to base64", ()),
    ("from_base64", 1, 1, _VARCHAR, "varchar", "base64 to bytes (as varchar)", ()),
    # string breadth (NOTE: no `repeat` — the reference's repeat(e, n)
    # returns ARRAY, which this engine only has as constants; occupying
    # the name with string semantics would silently diverge)
    ("levenshtein_distance", 2, 2, _BIGINT, "bigint",
     "edit distance to a constant", (), (1,)),
    ("hamming_distance", 2, 2, _BIGINT, "bigint",
     "differing positions vs a constant of equal length", (), (1,)),
    # URL functions (operator/scalar/UrlFunctions)
    ("url_extract_protocol", 1, 1, _VARCHAR, "varchar", "scheme of a URL", ()),
    ("url_extract_host", 1, 1, _VARCHAR, "varchar", "host of a URL", ()),
    ("url_extract_port", 1, 1, _BIGINT, "bigint", "port of a URL", ()),
    ("url_extract_path", 1, 1, _VARCHAR, "varchar", "path of a URL", ()),
    ("url_extract_query", 1, 1, _VARCHAR, "varchar", "query of a URL", ()),
    ("url_extract_fragment", 1, 1, _VARCHAR, "varchar", "fragment of a URL", ()),
    ("url_extract_parameter", 2, 2, _VARCHAR, "varchar",
     "value of a query parameter", (), (1,)),
    ("url_encode", 1, 1, _VARCHAR, "varchar", "percent-encode", ()),
    ("url_decode", 1, 1, _VARCHAR, "varchar", "percent-decode", ()),
    # JSON (operator/scalar/JsonFunctions; path subset $.a.b[0])
    ("json_extract_scalar", 2, 2, _VARCHAR, "varchar",
     "scalar at a JSONPath ($.a.b[0] subset)", (), (1,)),
    ("json_array_length", 1, 1, _BIGINT, "bigint",
     "length of a JSON array", ()),
    ("json_size", 2, 2, _BIGINT, "bigint",
     "size of the value at a JSONPath", (), (1,)),
    # date breadth
    ("year_of_week", 1, 1, _BIGINT, "bigint",
     "ISO week-numbering year", ("yow",)),
    ("from_iso8601_date", 1, 1, lambda a: T.DATE, "date",
     "parse YYYY-MM-DD", ()),
    # r3 breadth: JSON family (JsonFunctions.java)
    ("json_extract", 2, 2, _VARCHAR, "varchar",
     "JSON text of the value at a JSONPath", (), (1,)),
    ("json_format", 1, 1, _VARCHAR, "varchar",
     "canonical JSON text", ()),
    ("json_parse", 1, 1, _VARCHAR, "varchar",
     "parse and canonicalize JSON text", ()),
    ("is_json_scalar", 1, 1, _BOOLEAN, "boolean",
     "TRUE if the JSON document is a scalar", ()),
    ("json_array_contains", 2, 2, _BOOLEAN, "boolean",
     "TRUE if the JSON array contains the value", (), (1,)),
    ("json_array_get", 2, 2, _VARCHAR, "varchar",
     "JSON text of the array element at index", (), (1,)),
    # r3 breadth: bitwise (BitwiseFunctions.java)
    ("bitwise_and", 2, 2, _BIGINT, "bigint", "bitwise AND", ()),
    ("bitwise_or", 2, 2, _BIGINT, "bigint", "bitwise OR", ()),
    ("bitwise_xor", 2, 2, _BIGINT, "bigint", "bitwise XOR", ()),
    ("bitwise_left_shift", 2, 2, _BIGINT, "bigint",
     "shift left on the 64-bit pattern", ()),
    ("bitwise_right_shift", 2, 2, _BIGINT, "bigint",
     "logical (zero-filling) right shift", ()),
    ("bitwise_right_shift_arithmetic", 2, 2, _BIGINT, "bigint",
     "arithmetic (sign-extending) right shift", ()),
    ("bit_count", 1, 2, _BIGINT, "bigint",
     "number of set bits in the 64-bit pattern", ()),
    # r3 breadth: math remainder (MathFunctions.java)
    ("e", 0, 0, _DOUBLE, "double", "Euler's number", ()),
    ("pi", 0, 0, _DOUBLE, "double", "pi", ()),
    ("nan", 0, 0, _DOUBLE, "double", "IEEE NaN", ()),
    ("infinity", 0, 0, _DOUBLE, "double", "IEEE +Infinity", ()),
    ("cot", 1, 1, _DOUBLE, "double", "cotangent", ()),
    ("normal_cdf", 3, 3, _DOUBLE, "double",
     "normal CDF at x for (mean, sd)", ()),
    ("inverse_normal_cdf", 3, 3, _DOUBLE, "double",
     "normal quantile at p for (mean, sd)", ()),
    ("width_bucket", 4, 4, _BIGINT, "bigint",
     "equi-width histogram bucket of x over [lo, hi)", ()),
    # r3 breadth: datetime (DateTimeFunctions.java)
    ("hour", 1, 1, _BIGINT, "bigint", "hour of day [0,23]", ()),
    ("minute", 1, 1, _BIGINT, "bigint", "minute of hour [0,59]", ()),
    ("second", 1, 1, _BIGINT, "bigint", "second of minute [0,59]", ()),
    ("millisecond", 1, 1, _BIGINT, "bigint",
     "millisecond of second [0,999]", ()),
    ("from_unixtime", 1, 1, lambda a: T.TIMESTAMP, "timestamp",
     "epoch seconds -> timestamp", ()),
    ("to_unixtime", 1, 1, _DOUBLE, "double",
     "timestamp -> epoch seconds", ()),
    ("date_parse", 2, 2, lambda a: T.TIMESTAMP, "timestamp",
     "parse with MySQL-style format tokens", (), (1,)),
    # r3 breadth: string remainder (StringFunctions.java)
    ("soundex", 1, 1, _VARCHAR, "varchar", "American Soundex code", ()),
    ("normalize", 1, 1, _VARCHAR, "varchar",
     "Unicode NFC normalization", ()),
    ("regexp_position", 2, 2, _BIGINT, "bigint",
     "1-based position of the first regexp match (-1 = none)", (), (1,)),
    ("asinh", 1, 1, _DOUBLE, "double", "inverse hyperbolic sine", ()),
    ("acosh", 1, 1, _DOUBLE, "double", "inverse hyperbolic cosine", ()),
    ("atanh", 1, 1, _DOUBLE, "double", "inverse hyperbolic tangent", ()),
    ("expm1", 1, 1, _DOUBLE, "double", "exp(x) - 1, accurate near 0", ()),
    ("log1p", 1, 1, _DOUBLE, "double", "ln(1 + x), accurate near 0", ()),
    # r4 breadth: binary/digest family (VarbinaryFunctions.java,
    # HmacFunctions.java — digests render as lowercase hex varchar on the
    # engine's dictionary-varchar varbinary carrier)
    ("sha512", 1, 1, _VARCHAR, "varchar", "SHA-512 digest as lowercase hex", ()),
    ("xxhash64", 1, 1, _VARCHAR, "varchar",
     "XXHash64 of the UTF-8 bytes as 16 hex digits", ()),
    ("murmur3", 1, 1, _VARCHAR, "varchar",
     "Murmur3 x64_128 of the UTF-8 bytes as 32 hex digits", ()),
    ("hmac_md5", 2, 2, _VARCHAR, "varchar",
     "HMAC-MD5 with a constant key, as lowercase hex", (), (1,)),
    ("hmac_sha1", 2, 2, _VARCHAR, "varchar",
     "HMAC-SHA1 with a constant key, as lowercase hex", (), (1,)),
    ("hmac_sha256", 2, 2, _VARCHAR, "varchar",
     "HMAC-SHA256 with a constant key, as lowercase hex", (), (1,)),
    ("hmac_sha512", 2, 2, _VARCHAR, "varchar",
     "HMAC-SHA512 with a constant key, as lowercase hex", (), (1,)),
    ("to_base32", 1, 1, _VARCHAR, "varchar", "bytes to RFC 4648 base32", ()),
    ("from_base32", 1, 1, _VARCHAR, "varchar",
     "base32 to bytes (as varchar)", ()),
    ("to_base64url", 1, 1, _VARCHAR, "varchar",
     "bytes to URL-safe base64", ()),
    ("from_base64url", 1, 1, _VARCHAR, "varchar",
     "URL-safe base64 to bytes (as varchar)", ()),
    ("from_big_endian_32", 1, 1, _BIGINT, "bigint",
     "big-endian 4-byte value to integer (NULL if not 4 bytes)", ()),
    ("from_big_endian_64", 1, 1, _BIGINT, "bigint",
     "big-endian 8-byte value to bigint (NULL if not 8 bytes)", ()),
    ("from_ieee754_32", 1, 1, _DOUBLE, "double",
     "IEEE 754 big-endian 4-byte value to real (NULL if not 4 bytes)", ()),
    ("from_ieee754_64", 1, 1, _DOUBLE, "double",
     "IEEE 754 big-endian 8-byte value to double (NULL if not 8 bytes)", ()),
    # r4 breadth: string remainder
    ("luhn_check", 1, 1, _BOOLEAN, "boolean",
     "Luhn checksum validity of a digit string", ()),
    ("strrpos", 2, 2, _BIGINT, "bigint",
     "1-based position of the LAST occurrence of a constant (0 = absent)",
     (), (1,)),
    ("to_utf8", 1, 1, _VARCHAR, "varchar",
     "varchar to its UTF-8 bytes (identity on the varchar carrier)", ()),
    ("from_utf8", 1, 1, _VARCHAR, "varchar",
     "UTF-8 bytes to varchar, invalid sequences replaced", ()),
    ("word_stem", 1, 1, _VARCHAR, "varchar",
     "Porter stem of an English word", ()),
    ("char2hexint", 1, 1, _VARCHAR, "varchar",
     "Teradata: hex of the UTF-16BE code units", ()),
    ("index", 2, 2, _BIGINT, "bigint",
     "Teradata alias of strpos (constant substring)", (), (1,)),
    # r4 breadth: datetime parse family (DateTimeFunctions.java:961
    # parse_datetime and the from_iso8601 group)
    ("from_iso8601_timestamp", 1, 1, lambda a: T.TIMESTAMP, "timestamp",
     "parse an ISO-8601 timestamp (offsets applied, stored UTC)", ()),
    ("from_iso8601_timestamp_nanos", 1, 1, lambda a: T.TIMESTAMP,
     "timestamp",
     "parse an ISO-8601 timestamp with nanoseconds (micros kept)", ()),
    ("parse_datetime", 2, 2, lambda a: T.TIMESTAMP, "timestamp",
     "parse with a constant Joda-style pattern (yyyy/MM/dd/HH/mm/ss)",
     (), (1,)),
    ("to_date", 2, 2, lambda a: T.DATE, "date",
     "Teradata: parse with an Oracle-style pattern (yyyy-mm-dd)", (), (1,)),
    ("to_timestamp", 2, 2, lambda a: T.TIMESTAMP, "timestamp",
     "Teradata: parse with an Oracle-style pattern", (), (1,)),
    ("from_unixtime_nanos", 1, 1, lambda a: T.TIMESTAMP, "timestamp",
     "epoch nanoseconds to timestamp (truncated to micros)", ()),
    ("timezone_hour", 1, 1, _BIGINT, "bigint",
     "hour offset of the session zone (engine timestamps are UTC: 0)", ()),
    ("timezone_minute", 1, 1, _BIGINT, "bigint",
     "minute offset of the session zone (engine timestamps are UTC: 0)", ()),
    # r4 breadth: math remainder
    ("from_base", 2, 2, _BIGINT, "bigint",
     "parse as an integer in a constant radix 2..36", (), (1,)),
    ("inverse_beta_cdf", 3, 3, _DOUBLE, "double",
     "beta quantile at p for (a, b)", ()),
]:
    name, lo, hi, rule, ret, desc, aliases = entry[:7]
    const_args = entry[7] if len(entry) > 7 else ()
    _reg(name, "scalar", lo, hi, ret, desc, aliases, rule,
         const_args=const_args)

# --- aggregates (typed/validated in the analyzer; catalog surface) ---
for name, lo, hi, ret, desc in [
    ("count", 0, 1, "bigint", "row or non-null count"),
    ("sum", 1, 1, "same", "sum"),
    ("avg", 1, 1, "double|decimal", "arithmetic mean"),
    ("min", 1, 1, "same", "minimum"),
    ("max", 1, 1, "same", "maximum"),
    ("count_if", 1, 1, "bigint", "count of TRUE"),
    ("bool_and", 1, 1, "boolean", "TRUE if every value is TRUE"),
    ("bool_or", 1, 1, "boolean", "TRUE if any value is TRUE"),
    ("every", 1, 1, "boolean", "alias of bool_and"),
    ("arbitrary", 1, 1, "same", "any value"),
    ("any_value", 1, 1, "same", "any value"),
    ("variance", 1, 1, "double", "sample variance"),
    ("var_samp", 1, 1, "double", "sample variance"),
    ("var_pop", 1, 1, "double", "population variance"),
    ("stddev", 1, 1, "double", "sample standard deviation"),
    ("stddev_samp", 1, 1, "double", "sample standard deviation"),
    ("stddev_pop", 1, 1, "double", "population standard deviation"),
    ("skewness", 1, 1, "double", "skewness"),
    ("kurtosis", 1, 1, "double", "excess kurtosis"),
    ("covar_samp", 2, 2, "double", "sample covariance"),
    ("covar_pop", 2, 2, "double", "population covariance"),
    ("corr", 2, 2, "double", "correlation coefficient"),
    ("regr_slope", 2, 2, "double", "linear regression slope"),
    ("regr_intercept", 2, 2, "double", "linear regression intercept"),
    ("approx_distinct", 1, 1, "bigint", "approximate distinct count"),
    ("approx_percentile", 2, 2, "same", "approximate percentile"),
    ("min_by", 2, 2, "same", "value at the minimum of the second argument"),
    ("max_by", 2, 2, "same", "value at the maximum of the second argument"),
    ("listagg", 1, 2, "varchar", "concatenated values"),
    ("string_agg", 1, 2, "varchar", "concatenated values"),
    # r4 breadth: collect-path aggregates (host-assembled containers)
    ("array_agg", 1, 1, "array(E)", "all values, NULLs included"),
    ("map_agg", 2, 2, "map(K,V)", "map of key/value pairs"),
    ("multimap_agg", 2, 2, "map(K,array(V))",
     "map of keys to all their values"),
    ("map_union", 1, 1, "map(K,V)", "union of the input maps"),
    ("histogram", 1, 1, "map(E,bigint)", "value counts"),
    ("numeric_histogram", 2, 3, "map(double,double)",
     "approximate b-bucket histogram (Ben-Haim/Tom-Tov)"),
    ("approx_most_frequent", 2, 3, "map(E,bigint)",
     "top-b values by frequency"),
    ("bitwise_and_agg", 1, 1, "bigint", "bitwise AND of all values"),
    ("bitwise_or_agg", 1, 1, "bigint", "bitwise OR of all values"),
    ("bitwise_xor_agg", 1, 1, "bigint", "bitwise XOR of all values"),
    # r4 breadth: moment-sum composites
    ("checksum", 1, 1, "bigint",
     "order-insensitive 64-bit checksum (rendered as bigint)"),
    ("entropy", 1, 1, "double", "log-2 entropy of count weights"),
    ("geometric_mean", 1, 1, "double", "geometric mean"),
    ("regr_avgx", 2, 2, "double", "mean of x over non-null pairs"),
    ("regr_avgy", 2, 2, "double", "mean of y over non-null pairs"),
    ("regr_count", 2, 2, "bigint", "count of non-null pairs"),
    ("regr_r2", 2, 2, "double", "coefficient of determination"),
    ("regr_sxx", 2, 2, "double", "sum of squares of x"),
    ("regr_sxy", 2, 2, "double", "sum of products x*y"),
    ("regr_syy", 2, 2, "double", "sum of squares of y"),
    # r4 breadth: sketches (HyperLogLog/TDigest on the varchar carrier)
    ("approx_set", 1, 2, "HyperLogLog",
     "HyperLogLog sketch of the values (varchar-serialized)"),
    ("merge", 1, 1, "HyperLogLog|tdigest",
     "union of serialized sketches"),
    ("qdigest_agg", 1, 3, "qdigest(T)",
     "mergeable quantile digest of the values "
     "(value[, weight[, accuracy]]; weight/accuracy accepted)"),
    ("tdigest_agg", 1, 1, "tdigest",
     "t-digest sketch of the values (varchar-serialized)"),
]:
    _reg(name, "aggregate", lo, hi, ret, desc)

_reg("empty_approx_set", "scalar", 0, 0, "HyperLogLog",
     "empty HyperLogLog sketch")
_reg("value_at_quantile", "scalar", 2, 2, "double",
     "t-digest value at a constant quantile", rule=_DOUBLE,
     const_args=(1,))
_reg("quantile_at_value", "scalar", 2, 2, "double",
     "t-digest quantile of a constant value", rule=_DOUBLE,
     const_args=(1,))
_reg("values_at_quantiles", "scalar", 2, 2, "array(double)",
     "digest values at each constant quantile",
     rule=lambda a: T.array_of(T.DOUBLE), const_args=(1,))
_reg("split_to_map", "scalar", 3, 3, "map(varchar,varchar)",
     "split into a map on entry and key/value delimiters",
     rule=lambda a: T.map_of(T.VARCHAR, T.VARCHAR), const_args=(1, 2))

# --- window functions ---
for name, lo, hi, ret, desc in [
    ("row_number", 0, 0, "bigint", "sequential row number"),
    ("rank", 0, 0, "bigint", "rank with gaps"),
    ("dense_rank", 0, 0, "bigint", "rank without gaps"),
    ("percent_rank", 0, 0, "double", "relative rank in [0,1]"),
    ("cume_dist", 0, 0, "double", "cumulative distribution"),
    ("ntile", 1, 1, "bigint", "bucket number of n roughly-equal buckets"),
    ("lead", 1, 3, "same", "value at a following row"),
    ("lag", 1, 3, "same", "value at a preceding row"),
    ("first_value", 1, 1, "same", "first value of the frame"),
    ("last_value", 1, 1, "same", "last value of the frame"),
    ("nth_value", 2, 2, "same", "value at offset n within the frame"),
]:
    _reg(name, "window", lo, hi, ret, desc)

# --- r4 breadth: probability/statistics, bitwise, datetime, array/map,
# lambdas (implementations in expr/compile.py) ---
for name, lo, hi, desc in [
    ("cauchy_cdf", 3, 3, "Cauchy cdf at x for (median, scale)"),
    ("chi_squared_cdf", 2, 2, "chi-squared cdf at x for df"),
    ("gamma_cdf", 3, 3, "gamma cdf at x for (shape, scale)"),
    ("poisson_cdf", 2, 2, "Poisson cdf at k for lambda"),
    ("beta_cdf", 3, 3, "beta cdf at x for (a, b)"),
    ("f_cdf", 3, 3, "F cdf at x for (df1, df2)"),
    ("binomial_cdf", 3, 3, "binomial cdf at k for (trials, p)"),
    ("laplace_cdf", 3, 3, "Laplace cdf at x for (mean, scale)"),
    ("logistic_cdf", 3, 3, "logistic cdf at x for (a, b)"),
    ("weibull_cdf", 3, 3, "Weibull cdf at x for (a, b)"),
    ("wilson_interval_lower", 3, 3, "Wilson score interval lower bound"),
    ("wilson_interval_upper", 3, 3, "Wilson score interval upper bound"),
]:
    _reg(name, "scalar", lo, hi, "double", desc, rule=_DOUBLE)

_reg("year_of_week", "scalar", 1, 1, "bigint",
     "ISO week-numbering year", aliases=("yow",), rule=_BIGINT)

# --- r4 breadth: analyzer-special-cased additions (typing/desugaring in
# sql/analyzer.py; constant folding where the value is session-fixed) ---
for name, lo, hi, ret, desc, aliases in [
    ("now", 0, 0, "timestamp", "query start timestamp", ()),
    ("current_timezone", 0, 0, "varchar", "session time zone name", ()),
    ("current_timestamp", 0, 0, "timestamp(3) with time zone",
     "statement start instant at the session zone", ()),
    ("current_date", 0, 0, "date", "current date in the session zone", ()),
    ("localtimestamp", 0, 0, "timestamp",
     "current wall-clock timestamp in the session zone", ()),
    ("current_catalog", 0, 0, "varchar", "session catalog name", ()),
    ("current_schema", 0, 0, "varchar", "session schema name", ()),
    ("current_user", 0, 0, "varchar", "session user", ()),
    ("format_datetime", 2, 2, "varchar",
     "format a datetime with a Joda pattern (constants)", ()),
    ("at_timezone", 2, 2, "timestamp(3) with time zone",
     "same instant displayed in the given zone", ()),
    ("with_timezone", 2, 2, "timestamp(3) with time zone",
     "wall-clock timestamp reinterpreted in the given zone", ()),
    ("date", 1, 1, "date", "cast to date", ()),
    ("rand", 0, 2, "double|bigint",
     "uniform random: () in [0,1), (n) in [0,n), (lo,hi) in [lo,hi)",
     ("random",)),
    ("concat_ws", 2, None, "varchar",
     "concatenate with a constant separator, skipping NULLs", ()),
    ("position", 2, 2, "bigint",
     "1-based position of a constant substring (0 = absent)", ()),
    ("uuid", 0, 0, "varchar", "random UUID (one per query)", ()),
    ("version", 0, 0, "varchar", "engine version", ()),
    ("human_readable_seconds", 1, 1, "varchar",
     "seconds as weeks/days/hours/minutes/seconds text (constant)", ()),
    ("parse_duration", 1, 1, "interval day to second",
     "parse a duration literal like '3.5d' (constant)", ()),
    ("parse_data_size", 1, 1, "decimal(38,0)",
     "parse a size literal like '2.3MB' to bytes (constant)", ()),
    ("to_milliseconds", 1, 1, "bigint",
     "day-to-second interval to milliseconds", ()),
    ("to_iso8601", 1, 1, "varchar",
     "date/timestamp as ISO-8601 text (constant argument)", ()),
    ("to_base", 2, 2, "varchar",
     "integer rendered in radix 2..36 (constant arguments)", ()),
    ("to_big_endian_32", 1, 1, "varbinary",
     "integer to big-endian 4 bytes (constant argument)", ()),
    ("to_big_endian_64", 1, 1, "varbinary",
     "bigint to big-endian 8 bytes (constant argument)", ()),
    ("to_ieee754_32", 1, 1, "varbinary",
     "real to IEEE 754 big-endian 4 bytes (constant argument)", ()),
    ("to_ieee754_64", 1, 1, "varbinary",
     "double to IEEE 754 big-endian 8 bytes (constant argument)", ()),
    ("format_number", 1, 1, "varchar",
     "number with a unit suffix like 1.23K (constant argument)", ()),
    ("bar", 2, 4, "varchar",
     "ANSI render of x in [0,1] as a width-n bar (constant arguments)", ()),
    ("date_format", 2, 2, "varchar",
     "format with MySQL tokens (constant arguments)", ()),
    ("to_char", 2, 2, "varchar",
     "Teradata: format with an Oracle-style pattern (constants)", ()),
    ("rgb", 3, 3, "color", "color from RGB components (constants)", ()),
    ("color", 1, 1, "color", "color from a name or #hex (constant)", ()),
    ("render", 2, 2, "varchar",
     "value wrapped in an ANSI color (constant arguments)", ()),
]:
    _reg(name, "scalar", lo, hi, ret, desc, aliases)

_ARRAY0 = lambda a: a[0]  # noqa: E731
for name, lo, hi, ret, desc, rule in [
    ("slice", 3, 3, "array(E)", "subarray from position for length", _ARRAY0),
    ("trim_array", 2, 2, "array(E)", "array minus its last n elements", _ARRAY0),
    ("array_sort", 1, 1, "array(E)", "ascending sort of the elements", _ARRAY0),
    ("array_distinct", 1, 1, "array(E)", "distinct elements (sorted)", _ARRAY0),
    ("array_remove", 2, 2, "array(E)", "elements not equal to the value", _ARRAY0),
    ("array_position", 2, 2, "bigint", "1-based position of the value (0 = absent)", _BIGINT),
]:
    _reg(name, "scalar", lo, hi, ret, desc, rule=rule)
_reg("repeat", "scalar", 2, 2, "array(E)", "value repeated n times",
     rule=lambda a: T.array_of(a[0]), const_args=(1,))
_reg("split", "scalar", 2, 2, "array(varchar)", "split on a delimiter",
     rule=lambda a: T.array_of(T.VARCHAR), const_args=(1,))
_reg("regexp_split", "scalar", 2, 2, "array(varchar)",
     "split on a constant regexp",
     rule=lambda a: T.array_of(T.VARCHAR), const_args=(1,))
_reg("regexp_extract_all", "scalar", 2, 3, "array(varchar)",
     "all regexp matches (or a capture group)",
     rule=lambda a: T.array_of(T.VARCHAR), const_args=(1, 2))
_reg("map_keys", "scalar", 1, 1, "array(K)", "the map's keys")
_reg("map_values", "scalar", 1, 1, "array(V)", "the map's values")
_reg("format", "scalar", 2, None, "varchar",
     "printf-style formatting (constant arguments)")
_reg("map_contains_key", "scalar", 2, 2, "boolean",
     "whether the map has the key", rule=_BOOLEAN)

for name, lo, hi, ret, desc in [
    ("transform", 2, 2, "array(U)", "apply a lambda to every element"),
    ("filter", 2, 2, "array(E)", "elements where the lambda is true"),
    ("any_match", 2, 2, "boolean", "lambda true for any element"),
    ("all_match", 2, 2, "boolean", "lambda true for every element"),
    ("none_match", 2, 2, "boolean", "lambda true for no element"),
    ("transform_values", 2, 2, "map(K,V2)", "apply a lambda to map values"),
    ("transform_keys", 2, 2, "map(K2,V)", "apply a lambda to map keys"),
    ("map_filter", 2, 2, "map(K,V)", "entries where the lambda is true"),
]:
    _reg(name, "scalar", lo, hi, ret, desc)

for name, lo, hi, ret, desc in [
    ("arrays_overlap", 2, 2, "boolean", "whether the arrays share an element"),
    ("array_intersect", 2, 2, "array(E)", "elements in both arrays"),
    ("array_union", 2, 2, "array(E)", "union of the arrays' elements"),
    ("array_except", 2, 2, "array(E)", "elements only in the first array"),
    ("flatten", 1, 1, "array(E)", "concatenate an array of arrays"),
    ("contains_sequence", 2, 2, "boolean",
     "whether the array contains the sequence contiguously (constants)"),
    ("shuffle", 1, 1, "array(E)",
     "random permutation of a constant array"),
]:
    _reg(name, "scalar", lo, hi, ret, desc)


# --- per-type overloads (SHOW FUNCTIONS rows, the reference's unit) ---
# Only signatures this engine GENUINELY accepts are listed: the numeric
# tower tinyint..double + decimal flows through common_super_type and
# the decimal-aware binders; datetime extractors run on date AND
# timestamp via _to_days; the varbinary carrier is varchar, so the
# string/binary pairs share one implementation (both listed, as the
# reference lists both). Kept adjacent to the catalog so a new overload
# lands here in the same commit that implements it.
_INT_T = ("tinyint", "smallint", "integer", "bigint")
_NUM_T = _INT_T + ("real", "double", "decimal(p,s)")
_OVERLOADS: Dict[str, Tuple[str, ...]] = {
    "abs": tuple(f"{t} -> {t}" for t in _NUM_T),
    "sign": tuple(f"{t} -> {t}" for t in _NUM_T),
    "round": tuple(f"{t}[, n] -> {t}" for t in _NUM_T),
    "truncate": ("real -> real", "double -> double",
                 "decimal(p,s)[, n] -> decimal(p,s)"),
    "floor": ("bigint -> bigint", "real -> real", "double -> double",
              "decimal(p,s) -> decimal(p,0)"),
    "ceil": ("bigint -> bigint", "real -> real", "double -> double",
             "decimal(p,s) -> decimal(p,0)"),
    "mod": ("bigint, bigint -> bigint", "real, real -> real",
            "double, double -> double",
            "decimal(p,s), decimal(p,s) -> decimal(p,s)"),
    "sum": ("bigint -> bigint", "real -> real", "double -> double",
            "decimal(p,s) -> decimal(38,s)"),
    "avg": ("bigint -> double", "real -> real", "double -> double",
            "decimal(p,s) -> decimal(p,s)"),
    "greatest": tuple(f"{t}... -> {t}" for t in
                      ("bigint", "double", "decimal(p,s)", "varchar",
                       "date", "timestamp")),
    "least": tuple(f"{t}... -> {t}" for t in
                   ("bigint", "double", "decimal(p,s)", "varchar",
                    "date", "timestamp")),
    "approx_percentile": ("bigint, double -> bigint",
                          "real, double -> real",
                          "double, double -> double"),
    "cardinality": ("array(E) -> bigint", "map(K,V) -> bigint",
                    "HyperLogLog -> bigint"),
    "element_at": ("array(E), bigint -> E", "map(K,V), K -> V"),
    # datetime extractors: date and timestamp forms (both live paths)
    **{
        name: (f"date -> bigint", f"timestamp -> bigint")
        for name in ("year", "quarter", "month", "week", "day",
                     "day_of_week", "day_of_year", "year_of_week")
    },
    "date_trunc": ("unit, date -> date", "unit, timestamp -> timestamp"),
    "date_add": ("unit, bigint, date -> date",
                 "unit, bigint, timestamp -> timestamp"),
    "date_diff": ("unit, date, date -> bigint",
                  "unit, timestamp, timestamp -> bigint"),
    "last_day_of_month": ("date -> date", "timestamp -> date"),
    # string/varbinary pairs (one carrier, two SQL types — the
    # reference registers both signatures)
    **{
        name: ("varchar -> varchar", "varbinary -> varbinary")
        for name in ("to_hex", "to_base64", "to_base64url",
                     "to_base32", "lpad", "rpad")
    },
    "reverse": ("varchar -> varchar", "varbinary -> varbinary",
                "array(E) -> array(E)"),
    **{
        name: ("varchar -> varbinary-hex", "varbinary -> varbinary-hex")
        for name in ("md5", "sha1", "sha256", "sha512", "xxhash64",
                     "murmur3")
    },
    "length": ("varchar -> bigint", "varbinary -> bigint"),
    "substr": ("varchar, start[, length] -> varchar",
               "varbinary, start[, length] -> varbinary"),
    "concat": ("varchar... -> varchar", "varbinary... -> varbinary",
               "array(E)... -> array(E)"),
    "crc32": ("varchar -> bigint", "varbinary -> bigint"),
    "from_unixtime": ("bigint -> timestamp", "double -> timestamp",
                      "decimal(p,s) -> timestamp"),
    "width_bucket": ("double, double, double, bigint -> bigint",),
    "count": ("* -> bigint", "T -> bigint"),
}

# every type the engine's generic (type-agnostic) aggregates and value
# windows genuinely accept — one row per type, the reference's
# registration unit (SystemFunctionBundle registers min/max/min_by/
# lead/lag once per orderable type)
_GENERIC_T = (
    "boolean", "tinyint", "smallint", "integer", "bigint", "real",
    "double", "decimal(p,s)", "varchar", "date", "timestamp",
    "timestamp with time zone", "interval day to second",
)
_OVERLOADS.update({
    "min": tuple(f"{t} -> {t}" for t in _GENERIC_T),
    "max": tuple(f"{t} -> {t}" for t in _GENERIC_T),
    "min_by": tuple(f"V, {t} -> V" for t in _GENERIC_T),
    "max_by": tuple(f"V, {t} -> V" for t in _GENERIC_T),
    "any_value": tuple(f"{t} -> {t}" for t in _GENERIC_T),
    "arbitrary": tuple(f"{t} -> {t}" for t in _GENERIC_T),
    "array_agg": tuple(f"{t} -> array({t})" for t in _GENERIC_T),
    "checksum": tuple(f"{t} -> varbinary" for t in _GENERIC_T),
    "approx_distinct": tuple(f"{t} -> bigint" for t in _GENERIC_T),
    "histogram": tuple(f"{t} -> map({t},bigint)" for t in _GENERIC_T),
    "map_agg": tuple(f"{t}, V -> map({t},V)" for t in _GENERIC_T),
    "multimap_agg": tuple(
        f"{t}, V -> map({t},array(V))" for t in _GENERIC_T
    ),
    "lead": tuple(f"{t}[, offset[, default]] -> {t}" for t in _GENERIC_T),
    "lag": tuple(f"{t}[, offset[, default]] -> {t}" for t in _GENERIC_T),
    "first_value": tuple(f"{t} -> {t}" for t in _GENERIC_T),
    "last_value": tuple(f"{t} -> {t}" for t in _GENERIC_T),
    "nth_value": tuple(f"{t}, n -> {t}" for t in _GENERIC_T),
})

# datetime family rows over timestamp with time zone (r5: civil fields
# read the value's own zone; DateTimes.java)
_TSTZ = "timestamp with time zone"
for _name in ("year", "quarter", "month", "week", "day", "day_of_week",
              "day_of_year", "year_of_week", "hour", "minute", "second",
              "millisecond"):
    _m = REGISTRY.get(_name)
    if _m is not None:
        base = _m.overloads or (f"timestamp -> bigint",)
        _OVERLOADS[_name] = tuple(base) + (f"{_TSTZ} -> bigint",)
_OVERLOADS["date_trunc"] = (
    "unit, date -> date", "unit, timestamp -> timestamp",
    f"unit, {_TSTZ} -> {_TSTZ}",
)
_OVERLOADS["date_add"] = (
    "unit, bigint, date -> date", "unit, bigint, timestamp -> timestamp",
    f"unit, bigint, {_TSTZ} -> {_TSTZ}",
)
_OVERLOADS["date_diff"] = (
    "unit, date, date -> bigint", "unit, timestamp, timestamp -> bigint",
    f"unit, {_TSTZ}, {_TSTZ} -> bigint",
)
_OVERLOADS["to_unixtime"] = (
    "timestamp -> double", f"{_TSTZ} -> double",
)
_OVERLOADS["greatest"] = tuple(
    f"{t}... -> {t}" for t in ("bigint", "double", "decimal(p,s)",
                               "varchar", "date", "timestamp", _TSTZ)
)
_OVERLOADS["least"] = _OVERLOADS["greatest"]
_OVERLOADS["qdigest_agg"] = tuple(
    f"{t}[, weight[, accuracy]] -> qdigest({t})"
    for t in ("bigint", "real", "double")
)
_OVERLOADS["value_at_quantile"] = (
    "qdigest(T), double -> double", "tdigest, double -> double",
)
_OVERLOADS["values_at_quantiles"] = (
    "qdigest(T), array(double) -> array(double)",
    "tdigest, array(double) -> array(double)",
)
for _n, _sigs in _OVERLOADS.items():
    _m = REGISTRY.get(_n)
    if _m is not None:
        REGISTRY.register(dataclasses.replace(_m, overloads=_sigs))
