"""Expression binder/lowerer: typed IR -> pure-jnp closures.

The PageFunctionCompiler analogue (main/sql/gen/PageFunctionCompiler.java:103,
ExpressionCompiler.java:57). Binding happens once per pipeline against the
input schema (types + per-column string dictionaries, which are stable for
a whole table scan — the TPU answer to VariableWidthBlock); the result is
a closure of jax.numpy ops that the enclosing operator jits. All string
logic (LIKE, ordering, substr) is resolved on the host against dictionary
*values* (|dict| items), never per row; devices only see int32 code ops.

Value model: every expression evaluates to ``(data, valid)`` where
``valid=None`` means all-valid — mirroring Block's mayHaveNull fast path.
SQL three-valued logic is implemented in the and/or/not lowerings.

Known deviation from Trino: CONSTANT zero divisors error at bind time
(DIVISION_BY_ZERO); a data-dependent zero divisor yields NULL instead of
raising USER_ERROR (data-dependent errors can't abort an XLA program;
an error-flag sideband is the planned extension).
"""

from __future__ import annotations

import dataclasses
import math
import re as _re
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.block import Column, Dictionary, RelBatch
from trino_tpu.expr import functions as F
from trino_tpu.ops import int128 as I128
from trino_tpu.ops.gather import take_clip
from trino_tpu.expr.ir import Call, Case, Cast, Expr, InList, InputRef, Literal

Value = Tuple[jnp.ndarray, Optional[jnp.ndarray]]
EvalFn = Callable[[List[jnp.ndarray], List[Optional[jnp.ndarray]]], Value]

# double -> double elementwise library (MathFunctions.java analogues);
# each entry fuses into the enclosing jitted pipeline
_UNARY_DOUBLE_FNS = {
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "asinh": jnp.arcsinh, "acosh": jnp.arccosh, "atanh": jnp.arctanh,
    "cbrt": jnp.cbrt, "degrees": jnp.degrees, "radians": jnp.radians,
    "expm1": jnp.expm1, "log1p": jnp.log1p,
}

_MICROS_PER_DAY = 86400 * 1000 * 1000
# sub-day date_trunc/date_add/date_diff units (TIMESTAMP is epoch micros)
_MICROS_PER_UNIT = {
    "hour": 3600 * 1000 * 1000,
    "minute": 60 * 1000 * 1000,
    "second": 1000 * 1000,
    "millisecond": 1000,
}


@dataclasses.dataclass
class Bound:
    """A bound (lowered) expression: jnp closure + static result metadata.

    ``const_value`` is set only for bound literals — a column that happens
    to have one distinct dictionary value is NOT a constant (it can still
    hold NULLs and its mask must survive)."""

    type: T.DataType
    fn: EvalFn
    dictionary: Optional[Dictionary] = None
    const_value: object = None
    is_const: bool = False
    # set for pure input references: runtime-dictionary passthrough
    # (aggregates like listagg create dictionaries at execution time
    # that plan-time binding cannot know)
    input_ref: Optional[int] = None

    def eval_batch(self, batch: RelBatch) -> Column:
        data, valid = self.fn(
            [c.data for c in batch.columns], [c.valid for c in batch.columns]
        )
        return Column(self.type, data, valid, self.dictionary)


def scale_decimal_value(v, t: T.DataType) -> int:
    """Python value -> scaled int payload, rounding half away from zero
    (matches the device-side cast path; python round() is banker's).
    Integer inputs scale exactly — float round-tripping would corrupt
    >53-bit (long-decimal) magnitudes."""
    sf = T.decimal_scale_factor(t)
    if isinstance(v, int) and not isinstance(v, bool):
        return v * sf
    import decimal as _d

    if isinstance(v, _d.Decimal):
        return int(
            (v * sf).to_integral_value(rounding=_d.ROUND_HALF_UP)
        )
    x = v * sf
    return int(math.floor(abs(x) + 0.5)) * (1 if x >= 0 else -1)


def merge_valid(*valids: Optional[jnp.ndarray]) -> Optional[jnp.ndarray]:
    out = None
    for v in valids:
        if v is None:
            continue
        out = v if out is None else (out & v)
    return out


def _format_cast_text(v, src_type: T.DataType):
    """SQL text form for cast-to-varchar constant folding."""
    if v is None:
        return None
    if src_type.kind == T.TypeKind.BOOLEAN:
        return "true" if v else "false"
    if src_type.is_decimal:
        s = src_type.scale or 0
        return f"{v:.{s}f}" if s else str(int(v))
    if src_type.kind == T.TypeKind.TIMESTAMP_TZ:
        from trino_tpu.ops.tz import format_tstz

        return format_tstz(int(v))
    return str(v)


def _try_decode(fn):
    """bytes-producing thunk -> utf-8 varchar carrier, NULL on error."""
    try:
        return fn().decode("utf-8", "replace")
    except Exception:
        return None


def _py_soundex(s: str) -> str:
    """American Soundex (StringFunctions.soundex)."""
    codes = {
        **dict.fromkeys("BFPV", "1"), **dict.fromkeys("CGJKQSXZ", "2"),
        **dict.fromkeys("DT", "3"), "L": "4",
        **dict.fromkeys("MN", "5"), "R": "6",
    }
    u = [c for c in s.upper() if c.isalpha()]
    if not u:
        return ""
    out = [u[0]]
    prev = codes.get(u[0], "")
    for c in u[1:]:
        code = codes.get(c, "")
        if code and code != prev:
            out.append(code)
        if c not in "HW":
            prev = code
    return ("".join(out) + "000")[:4]


def _dict_code_const(probe: "Bound", dictionary, elem_type=None):
    """Constant probe -> comparable device value: dictionary code for
    string elements (absent value = sentinel that matches nothing);
    decimal probes scale into the element's scaled-int physical form.
    Column-valued probes need per-row flat broadcasting the vectorized
    paths do not do yet — fail loudly instead of silently mismatching."""
    if not probe.is_const or probe.const_value is None:
        raise NotImplementedError(
            "array/map search functions take a constant search value"
        )
    if dictionary is not None:
        code = dictionary.code(probe.const_value)
        return jnp.int32(code if code is not None and code >= 0 else -2)
    if elem_type is not None and elem_type.is_decimal:
        if elem_type.is_long_decimal:
            raise NotImplementedError(
                "array/map search over decimal(>18) elements"
            )
        return jnp.int64(scale_decimal_value(probe.const_value, elem_type))
    return jnp.asarray(probe.const_value)


def minmax_like(dtype, is_min: bool):
    import numpy as _np

    if _np.issubdtype(_np.dtype(dtype), _np.floating):
        return _np.inf if is_min else -_np.inf
    info = _np.iinfo(_np.dtype(dtype))
    return info.max if is_min else info.min


# Probability/statistics scalar family (MathFunctions *_cdf /
# WilsonInterval): plain float64 formulas over jax.scipy.special.
def _betaincinv(jsp):
    """Beta quantile via fixed 64-step bisection on betainc (this jax
    has no betaincinv; bisection is branch-free and jit-stable — the
    reference inverts with Apache commons' ContinuedFraction)."""
    if hasattr(jsp, "betaincinv"):
        return lambda a, b, p: jsp.betaincinv(a, b, p)

    def inv(a, b, p):
        lo = jnp.zeros_like(p)
        hi = jnp.ones_like(p)
        for _ in range(64):
            mid = 0.5 * (lo + hi)
            below = jsp.betainc(a, b, mid) < p
            lo = jnp.where(below, mid, lo)
            hi = jnp.where(below, hi, mid)
        x = 0.5 * (lo + hi)
        bad = (p < 0) | (p > 1) | (a <= 0) | (b <= 0)
        return jnp.where(bad, jnp.nan, x)

    return inv


def _make_prob_fns():
    import jax.scipy.special as jsp

    def binomial_cdf(n, p, k):
        kf = jnp.floor(k)
        return jnp.where(
            kf >= n, 1.0,
            jnp.where(kf < 0, 0.0, jsp.betainc(n - kf, kf + 1.0, 1.0 - p)),
        )

    def wilson(s, n, z, sign):
        p = s / n
        z2 = z * z
        center = p + z2 / (2 * n)
        half = z * jnp.sqrt(p * (1 - p) / n + z2 / (4 * n * n))
        return (center + sign * half) / (1 + z2 / n)

    return {
        "cauchy_cdf": (3, lambda m, s, x: 0.5 + jnp.arctan2(x - m, s) / jnp.pi),
        "chi_squared_cdf": (2, lambda df, x: jsp.gammainc(df / 2.0, x / 2.0)),
        "gamma_cdf": (3, lambda sh, sc, x: jsp.gammainc(sh, x / sc)),
        "poisson_cdf": (2, lambda lam, k: jsp.gammaincc(jnp.floor(k) + 1.0, lam)),
        "beta_cdf": (3, jsp.betainc),
        "f_cdf": (3, lambda d1, d2, x: jsp.betainc(
            d1 / 2.0, d2 / 2.0, d1 * x / (d1 * x + d2))),
        "binomial_cdf": (3, binomial_cdf),
        "laplace_cdf": (3, lambda m, b, x: jnp.where(
            x < m, 0.5 * jnp.exp((x - m) / b),
            1.0 - 0.5 * jnp.exp(-(x - m) / b))),
        "logistic_cdf": (3, lambda a, b, x: 1.0 / (1.0 + jnp.exp(-(x - a) / b))),
        "weibull_cdf": (3, lambda a, b, x: jnp.where(
            x <= 0, 0.0, 1.0 - jnp.exp(-((x / b) ** a)))),
        "inverse_beta_cdf": (3, _betaincinv(jsp)),
        "wilson_interval_lower": (3, lambda s, n, z: wilson(s, n, z, -1.0)),
        "wilson_interval_upper": (3, lambda s, n, z: wilson(s, n, z, 1.0)),
    }


_PROB_FNS = {k: v for k, v in _make_prob_fns().items() if v is not None}


def _const(shape_src, value, dtype) -> jnp.ndarray:
    # shape reference may be a nested Column object (nested columns flow
    # through the cols list whole — their data array carries the shape)
    if hasattr(shape_src, "data") and not hasattr(shape_src, "shape"):
        shape_src = shape_src.data
    n = shape_src.shape[0]
    return jnp.full((n,), value, dtype=dtype)


# -- Int128 lane plumbing (decimal(19..38): (n, 2) limb arrays) -------------


def _rows_of(shape_src) -> int:
    if hasattr(shape_src, "data") and not hasattr(shape_src, "shape"):
        shape_src = shape_src.data
    return shape_src.shape[0]


def _phys_const(shape_src, t: T.DataType, value128=(0, 0)):
    """Zero/constant array in t's physical shape."""
    n = _rows_of(shape_src)
    if t.lanes == 2:
        return jnp.broadcast_to(
            jnp.asarray(value128, jnp.int64), (n, 2)
        )
    return jnp.full((n,), value128[1], dtype=t.dtype)


def _split2(d):
    return d[:, 0], d[:, 1]


def _join2(h, lo):
    return jnp.stack([h, lo], axis=-1)


def _lift128(d, t: T.DataType):
    """Physical numeric data (scaled int64 decimal or integer) ->
    (hi, lo) limbs at the same scale."""
    if t.is_long_decimal:
        return _split2(d)
    return I128.from_i64(d.astype(jnp.int64))


def _f64_of_decimal(d, t: T.DataType):
    """Decimal physical -> float64 value (lossy beyond 2^53, like any
    decimal->double cast)."""
    sf = T.decimal_scale_factor(t)
    if t.is_long_decimal:
        h, lo = _split2(d)
        u = jnp.where(
            lo < 0, lo.astype(jnp.float64) + 2.0 ** 64,
            lo.astype(jnp.float64),
        )
        return (h.astype(jnp.float64) * 2.0 ** 64 + u) / sf
    return d.astype(jnp.float64) / sf


def _where_lanes(cond, a, b):
    """jnp.where that broadcasts the condition over 2-lane decimals."""
    if getattr(a, "ndim", 1) == 2 or getattr(b, "ndim", 1) == 2:
        cond = cond[:, None]
    return jnp.where(cond, a, b)


class ExprBinder:
    """Binds IR against an input schema. One instance per pipeline."""

    def __init__(self, input_types: Sequence[T.DataType], input_dicts: Sequence[Optional[Dictionary]]):
        self.input_types = list(input_types)
        self.input_dicts = list(input_dicts)

    @classmethod
    def for_batch(cls, batch: RelBatch) -> "ExprBinder":
        return cls([c.type for c in batch.columns], [c.dictionary for c in batch.columns])

    # ---- dispatch ----
    def bind(self, e: Expr) -> Bound:
        if isinstance(e, InputRef):
            return self._bind_input(e)
        if isinstance(e, Literal):
            return self._bind_literal(e)
        if isinstance(e, Cast):
            return self._bind_cast(e)
        if isinstance(e, Case):
            return self._bind_case(e)
        if isinstance(e, InList):
            return self._bind_in(e)
        if isinstance(e, Call):
            return self._bind_call(e)
        raise NotImplementedError(f"cannot bind {e!r}")

    # ---- leaves ----
    def _bind_input(self, e: InputRef) -> Bound:
        i = e.index
        return Bound(
            self.input_types[i],
            lambda cols, valids, i=i: (cols[i], valids[i]),
            self.input_dicts[i],
            input_ref=i,
        )

    def _bind_literal(self, e: Literal) -> Bound:
        t = e.type
        if e.value is None:
            def fn(cols, valids):
                ref = cols[0] if cols else jnp.zeros(1)
                return (
                    _phys_const(ref, t),
                    _const(ref, False, jnp.bool_),
                )
            return Bound(t, fn)
        if t.is_array and isinstance(e.value, tuple):
            # constant ARRAY[...] literal: CANONICAL layout — one flat
            # slice PER ROW (tiled). Shared-slice views would break
            # every repacking consumer (filter/array_distinct/...,
            # which assume non-overlapping [start, start+len) extents).
            from trino_tpu.block import ArrayColumn, Column as BCol

            proto = BCol.from_pylist(
                t.element, list(e.value) or [None],
                capacity=max(len(e.value), 1),
            )
            k = len(e.value)
            def afn(cols, valids, proto=proto, k=k, t=t):
                ref = cols[0] if cols else jnp.zeros(1)
                n = _rows_of(ref)
                reps = max(k, 1)
                flat = Column(
                    t.element,
                    jnp.tile(proto.data[:reps], (n,) + (1,) * (proto.data.ndim - 1)),
                    None if proto.valid is None else jnp.tile(proto.valid[:reps], n),
                    proto.dictionary,
                )
                return (
                    ArrayColumn(
                        t, jnp.full(n, k, jnp.int32), None, None,
                        jnp.arange(n, dtype=jnp.int32) * reps, flat,
                    ),
                    None,
                )
            return Bound(t, afn, const_value=e.value, is_const=True)
        if t.is_string:
            d = Dictionary([e.value])
            def sfn(cols, valids, d=d):
                ref = cols[0] if cols else jnp.zeros(1)
                return _const(ref, 0, jnp.int32), None
            return Bound(t, sfn, d, const_value=e.value, is_const=True)
        v = e.value
        if t.is_decimal:
            v = scale_decimal_value(v, t)
            if t.is_long_decimal:
                pair = I128.from_python(v)
                def lfn(cols, valids, pair=pair, t=t):
                    ref = cols[0] if cols else jnp.zeros(1)
                    return _phys_const(ref, t, pair), None
                return Bound(t, lfn, const_value=e.value, is_const=True)
        def vfn(cols, valids, v=v, t=t):
            ref = cols[0] if cols else jnp.zeros(1)
            return _const(ref, v, t.dtype), None
        return Bound(t, vfn, const_value=e.value, is_const=True)

    # ---- cast ----
    # int->varchar enumerates this value domain as a static dictionary;
    # values outside it become NULL (documented deviation)
    _SMALL_INT_CAST_RANGE = (0, 4096)

    def _bind_tstz(self, name: str, e: Call, args) -> Bound:
        """TIMESTAMP WITH TIME ZONE kernels over the packed int64
        encoding (instant_millis << 12 | zone_id — ops/tz.py;
        spi/type/DateTimeEncoding.java). Zone rules are static sorted
        transition tables baked into the trace; per-row-zone reads use
        the registry transition matrix (one take + searchsorted)."""
        from trino_tpu.ops import tz as TZ

        SHIFT = jnp.int64(TZ.MILLIS_SHIFT)
        MASK = jnp.int64(TZ.ZONE_MASK)

        def const_int(b: Bound, what: str) -> int:
            if not b.is_const or b.const_value is None:
                raise NotImplementedError(f"{name}() {what} must be constant")
            return int(b.const_value)

        if name == "at_timezone_id":
            a, z = args
            zid = const_int(z, "zone")
            def atfn(cols, valids):
                d, v = a.fn(cols, valids)
                return (d & ~MASK) | jnp.int64(zid), v
            return Bound(e.type, atfn)
        if name == "tstz_shift":
            a, ms = args
            def shfn(cols, valids):
                d, v = a.fn(cols, valids)
                mdata, mv = ms.fn(cols, valids)
                out = d + (mdata.astype(jnp.int64) << SHIFT)
                if mv is not None:
                    v = mv if v is None else (v & mv)
                return out, v
            return Bound(e.type, shfn)
        if name == "tstz_to_instant_ts":
            (a,) = args
            def instfn(cols, valids):
                d, v = a.fn(cols, valids)
                return (d >> SHIFT) * 1000, v
            return Bound(e.type, instfn)
        if name == "tstz_rewall":
            wall, orig = args
            def rwfn(cols, valids):
                w, wv = wall.fn(cols, valids)
                o, ov = orig.fn(cols, valids)
                zids = (o & MASK).astype(jnp.int32)
                wall_ms = jnp.floor_divide(w.astype(jnp.int64), 1000)
                inst = TZ.wall_to_instant_rowwise(wall_ms, zids)
                v = wv if ov is None else (ov if wv is None else (wv & ov))
                return (inst << SHIFT) | zids.astype(jnp.int64), v
            return Bound(e.type, rwfn)
        if name == "ts_to_tstz":
            a, z = args
            zid = const_int(z, "zone")
            def ttfn(cols, valids):
                d, v = a.fn(cols, valids)
                wall_ms = jnp.floor_divide(d.astype(jnp.int64), 1000)
                inst = TZ.wall_to_instant_millis(wall_ms, zid)
                return (inst << SHIFT) | jnp.int64(zid), v
            return Bound(e.type, ttfn)
        if name == "tstz_to_ts":
            (a,) = args
            def ftfn(cols, valids):
                d, v = a.fn(cols, valids)
                ms = d >> SHIFT
                zids = (d & MASK).astype(jnp.int32)
                off = TZ.offset_millis_rowwise(ms, zids)
                return (ms + off) * 1000, v
            return Bound(e.type, ftfn)
        if name == "parse_tstz":
            a, z = args
            zone = TZ.zone_name(const_int(z, "zone"))
            return self._bind_dict_table_nullable(
                a, e.type, lambda s: TZ.parse_tstz(s, zone), jnp.int64
            )
        # timezone_hour / timezone_minute: signed offset components
        (a,) = args
        def tzfn(cols, valids):
            d, v = a.fn(cols, valids)
            ms = d >> SHIFT
            zids = (d & MASK).astype(jnp.int32)
            off = TZ.offset_millis_rowwise(ms, zids)
            sgn = jnp.sign(off)
            mag = jnp.abs(off)
            if name == "tstz_timezone_hour":
                out = sgn * (mag // 3_600_000)
            else:
                out = sgn * ((mag % 3_600_000) // 60_000)
            return out.astype(jnp.int64), v
        return Bound(T.BIGINT, tzfn)

    def _bind_cast(self, e: Cast) -> Bound:
        a = self.bind(e.arg)
        out = self._bind_cast_from(e, a)
        # a cast of a constant is still a constant (same logical value);
        # needed e.g. for round(x, CAST(1 AS BIGINT)) scale arguments
        if a.is_const:
            out.is_const = True
            out.const_value = a.const_value
        return out

    def _bind_cast_from(self, e: Cast, a: Bound) -> Bound:
        src, dst = a.type, e.type
        if src == dst or (src.is_string and dst.is_string):
            return Bound(dst, a.fn, a.dictionary)
        if src.kind == T.TypeKind.UNKNOWN:  # NULL literal cast
            def nfn(cols, valids, afn=a.fn, dst=dst):
                d, _ = afn(cols, valids)
                return _phys_const(d, dst), _const(d, False, jnp.bool_)
            return Bound(dst, nfn)
        if src.is_decimal and dst.is_decimal:
            return self._rescaled(a, src.scale or 0, dst.scale or 0, dst)
        if src.is_decimal and dst.is_integerlike:
            if src.is_long_decimal:
                k = src.scale or 0
                def dlifn(cols, valids, afn=a.fn, k=k):
                    d, v = afn(cols, valids)
                    h, lo = I128.rescale_down_round(*_split2(d), k)
                    x, ok = I128.to_i64(h, lo)
                    v2 = ok if v is None else (v & ok)
                    return x.astype(dst.dtype), v2
                return Bound(dst, dlifn)
            sf = T.decimal_scale_factor(src)
            def difn(cols, valids, afn=a.fn):
                d, v = afn(cols, valids)
                q = F.div_round_half_away(d, _const(d, sf, d.dtype))
                return q.astype(dst.dtype), v
            return Bound(dst, difn)
        if src.is_decimal and dst.is_floating:
            def dffn(cols, valids, afn=a.fn, src=src):
                d, v = afn(cols, valids)
                return _f64_of_decimal(d, src).astype(dst.dtype), v
            return Bound(dst, dffn)
        if src.is_integerlike and dst.is_decimal:
            if dst.is_long_decimal:
                k = dst.scale or 0
                def ilfn(cols, valids, afn=a.fn, k=k):
                    d, v = afn(cols, valids)
                    h, lo = I128.rescale_up(*I128.from_i64(d.astype(jnp.int64)), k)
                    return _join2(h, lo), v
                return Bound(dst, ilfn)
            sf = T.decimal_scale_factor(dst)
            def idfn(cols, valids, afn=a.fn):
                d, v = afn(cols, valids)
                return d.astype(dst.dtype) * sf, v
            return Bound(dst, idfn)
        if src.is_floating and dst.is_decimal:
            sf = T.decimal_scale_factor(dst)
            if dst.is_long_decimal:
                def flfn(cols, valids, afn=a.fn):
                    d, v = afn(cols, valids)
                    x = F.round_half_away(d.astype(jnp.float64) * sf)
                    # split the (lossy beyond 2^53 anyway) float into limbs
                    h = jnp.floor(x / 2.0 ** 64)
                    lo_f = x - h * 2.0 ** 64
                    lo = jnp.where(
                        lo_f >= 2.0 ** 63, lo_f - 2.0 ** 64, lo_f
                    ).astype(jnp.int64)
                    return _join2(h.astype(jnp.int64), lo), v
                return Bound(dst, flfn)
            def fdfn(cols, valids, afn=a.fn):
                d, v = afn(cols, valids)
                return F.round_half_away(d * sf).astype(dst.dtype), v
            return Bound(dst, fdfn)
        if (
            src.kind == T.TypeKind.TIMESTAMP
            and dst.kind == T.TypeKind.DATE
        ):
            def tdfn(cols, valids, afn=a.fn):
                d, v = afn(cols, valids)
                days = jnp.floor_divide(
                    d.astype(jnp.int64), _MICROS_PER_DAY
                )
                return days.astype(jnp.int32), v
            return Bound(dst, tdfn)
        if (
            src.kind == T.TypeKind.DATE
            and dst.kind == T.TypeKind.TIMESTAMP
        ):
            def dtfn(cols, valids, afn=a.fn):
                d, v = afn(cols, valids)
                return d.astype(jnp.int64) * _MICROS_PER_DAY, v
            return Bound(dst, dtfn)
        if (src.is_integerlike or src.kind == T.TypeKind.BOOLEAN) and (
            dst.is_integerlike or dst.is_floating
        ):
            def iifn(cols, valids, afn=a.fn):
                d, v = afn(cols, valids)
                return d.astype(dst.dtype), v
            return Bound(dst, iifn)
        if src.is_floating and (dst.is_integerlike or dst.is_floating):
            def fifn(cols, valids, afn=a.fn):
                d, v = afn(cols, valids)
                if dst.is_integerlike:
                    d = F.round_half_away(d)
                return d.astype(dst.dtype), v
            return Bound(dst, fifn)
        if dst.is_string:
            # cast-to-varchar: constants fold; small integer domains get
            # an enumerated dictionary. Arbitrary numeric columns would
            # need a runtime-built dictionary (the static-dictionary
            # model's known limit; SURVEY.md §7 hard parts).
            if a.is_const:
                text = _format_cast_text(a.const_value, src)
                d = Dictionary([text] if text is not None else [])
                def cfn(cols, valids, d=d, text=text):
                    ref = cols[0] if cols else jnp.zeros(1)
                    if text is None:
                        return _const(ref, 0, jnp.int32), _const(ref, False, jnp.bool_)
                    return _const(ref, 0, jnp.int32), None
                return Bound(dst, cfn, d, const_value=text, is_const=True)
            if src.is_integerlike:
                lo, hi = self._SMALL_INT_CAST_RANGE
                values = [str(i) for i in range(lo, hi)]
                d = Dictionary(values)
                codes = jnp.asarray(
                    [d.code(str(i)) for i in range(lo, hi)], dtype=jnp.int32
                )
                def sfn(cols, valids, afn=a.fn):
                    data, v = afn(cols, valids)
                    in_range = (data >= lo) & (data < hi)
                    idx = jnp.clip(data - lo, 0, hi - lo - 1).astype(jnp.int32)
                    out = take_clip(codes, idx)
                    vv = in_range if v is None else (v & in_range)
                    return out, vv
                return Bound(dst, sfn, d)
        if src.is_string and dst.kind == T.TypeKind.DATE:
            import datetime as _dt

            def d_of(s):
                try:
                    return (_dt.date.fromisoformat(s.strip())
                            - _dt.date(1970, 1, 1)).days
                except ValueError:
                    return None  # the reference raises; NULL divergence

            return self._bind_dict_table_nullable(a, dst, d_of, dst.dtype)
        if src.is_string and dst.kind == T.TypeKind.TIMESTAMP:
            from trino_tpu.expr.pyfns import iso_to_micros

            return self._bind_dict_table_nullable(
                a, dst, iso_to_micros, jnp.int64
            )
        if src.is_string and dst.is_decimal:
            from decimal import Decimal, InvalidOperation

            def parse(txt):
                from decimal import ROUND_HALF_UP

                try:
                    v = Decimal(txt) * (10 ** (dst.scale or 0))
                    return int(v.to_integral_value(rounding=ROUND_HALF_UP))
                except (InvalidOperation, ValueError):
                    return None

            if a.is_const:
                sv = parse(str(a.const_value))
                if sv is None:
                    def nullfn(cols, valids):
                        ref = cols[0] if cols else jnp.zeros(1)
                        return _phys_const(ref, dst), _const(ref, False, jnp.bool_)
                    return Bound(dst, nullfn)
                if dst.is_long_decimal:
                    pair = I128.from_python(sv)
                    def lcfn(cols, valids, pair=pair):
                        ref = cols[0] if cols else jnp.zeros(1)
                        return _phys_const(ref, dst, pair), None
                    return Bound(dst, lcfn, const_value=a.const_value, is_const=True)
                def scfn(cols, valids, sv=sv):
                    ref = cols[0] if cols else jnp.zeros(1)
                    return _const(ref, sv, dst.dtype), None
                return Bound(dst, scfn, const_value=a.const_value, is_const=True)
            if a.dictionary is not None:
                parsed = [parse(v) for v in a.dictionary.values]
                ok_tab = jnp.asarray(
                    [p is not None for p in parsed] or [False], jnp.bool_
                )
                if dst.is_long_decimal:
                    pairs = [
                        I128.from_python(p if p is not None else 0)
                        for p in parsed
                    ] or [(0, 0)]
                    tab = jnp.asarray(pairs, jnp.int64)
                else:
                    tab = jnp.asarray(
                        [p if p is not None else 0 for p in parsed] or [0],
                        jnp.int64,
                    )
                def dfn(cols, valids, afn=a.fn):
                    d, v = afn(cols, valids)
                    idx = jnp.clip(d, 0, tab.shape[0] - 1)
                    out = jnp.take(tab, idx, axis=0)
                    okv = jnp.take(ok_tab, idx)
                    return out, okv if v is None else (v & okv)
                return Bound(dst, dfn)
        raise NotImplementedError(f"cast {src} -> {dst}")

    def _rescaled(self, a: Bound, sfrom: int, sto: int, out_type: T.DataType) -> Bound:
        in_long = a.type.is_long_decimal
        out_long = out_type.is_long_decimal
        if in_long or out_long:
            atype = a.type
            def lfn(cols, valids, afn=a.fn):
                d, v = afn(cols, valids)
                h, lo = _lift128(d, atype)
                if sto > sfrom:
                    # scale-up can wrap mod 2^128: overflowing rows go
                    # NULL (the Int128 module's overflow contract)
                    lim_h, lim_l = (
                        jnp.int64(x) for x in I128.from_python(
                            (2 ** 127 - 1) // 10 ** (sto - sfrom)
                        )
                    )
                    ah_, al_ = I128.abs_(h, lo)
                    ok = I128.lt(ah_, al_, lim_h, lim_l)
                    v = ok if v is None else (v & ok)
                    h, lo = I128.rescale_up(h, lo, sto - sfrom)
                elif sfrom > sto:
                    h, lo = I128.rescale_down_round(h, lo, sfrom - sto)
                if out_long:
                    return _join2(h, lo), v
                x, ok = I128.to_i64(h, lo)
                return x, ok if v is None else (v & ok)
            return Bound(out_type, lfn)
        if sfrom == sto:
            return Bound(out_type, a.fn)
        if sto > sfrom:
            m = 10 ** (sto - sfrom)
            def up(cols, valids, afn=a.fn):
                d, v = afn(cols, valids)
                return d * m, v
            return Bound(out_type, up)
        m = 10 ** (sfrom - sto)
        def down(cols, valids, afn=a.fn):
            d, v = afn(cols, valids)
            return F.div_round_half_away(d, _const(d, m, d.dtype)), v
        return Bound(out_type, down)

    # ---- CASE ----
    def _bind_case(self, e: Case) -> Bound:
        conds = [self.bind(c) for c in e.conds]
        # branches may never be selected: bind-time constant errors
        # (division by zero) must not fail the whole query for a branch
        # a FALSE condition guards (Trino defers constant-folding errors
        # to branch evaluation) — inside branches the constant-zero
        # check degrades to the runtime NULL behavior
        self._in_branch = getattr(self, "_in_branch", 0) + 1
        try:
            results = [self.bind(r) for r in e.results]
            default = self.bind(e.default) if e.default is not None else None
        finally:
            self._in_branch -= 1
        # unify string results onto one dictionary
        out_dict = None
        if e.type.is_string:
            merged = None
            for r in results + ([default] if default is not None else []):
                if r.dictionary is not None:
                    merged = (
                        r.dictionary
                        if merged is None
                        else Dictionary.unify(merged, r.dictionary)[0]
                    )
            out_dict = merged
            results = [self._remap_to(r, out_dict) for r in results]
            if default is not None:
                default = self._remap_to(default, out_dict)
        elif e.type.is_decimal:
            # numeric branches coerce through the REAL cast path (a
            # dtype view is not enough once scales differ or the output
            # is an Int128 (n, 2) decimal)
            results = [self._coerce_bound(r, e.type) for r in results]
            if default is not None:
                default = self._coerce_bound(default, e.type)
        out_t = e.type

        def fn(cols, valids):
            # else branch (or NULL)
            if default is not None:
                data, valid = default.fn(cols, valids)
                data = data.astype(out_t.dtype)
            else:
                ref, _ = conds[0].fn(cols, valids)
                data = _phys_const(ref, out_t)
                valid = _const(ref, False, jnp.bool_)
            # fold WHENs back-to-front so the first true wins
            for cb, rb in reversed(list(zip(conds, results))):
                cd, cv = cb.fn(cols, valids)
                take = cd if cv is None else (cd & cv)  # NULL cond = false
                rd, rv = rb.fn(cols, valids)
                data = _where_lanes(take, rd.astype(out_t.dtype), data)
                rvv = rv if rv is not None else _const(rd, True, jnp.bool_)
                vv = valid if valid is not None else _const(rd, True, jnp.bool_)
                valid = jnp.where(take, rvv, vv)
            return data, valid

        return Bound(out_t, fn, out_dict)

    # ---- higher-order (lambda) functions over nested columns ----
    @staticmethod
    def _lambda_body_ir(body):
        """Rewrite LambdaVar leaves into InputRefs so the body binds as
        an ordinary expression over the flat element column(s)."""
        from trino_tpu.expr import ir as _ir

        def sub(x):
            if isinstance(x, _ir.LambdaVar):
                return _ir.InputRef(x.index, x.type)
            if isinstance(x, _ir.Call):
                return _ir.Call(x.name, tuple(sub(a) for a in x.args), x.type)
            if isinstance(x, _ir.Cast):
                return _ir.Cast(sub(x.arg), x.type)
            if isinstance(x, _ir.Case):
                return _ir.Case(
                    tuple(sub(c) for c in x.conds),
                    tuple(sub(r) for r in x.results),
                    None if x.default is None else sub(x.default),
                    x.type,
                )
            if isinstance(x, _ir.InList):
                return _ir.InList(sub(x.value), x.options)
            return x  # Literal / InputRef

        return sub(body)

    @staticmethod
    def _seg_counts(flags, starts, lengths):
        """Per-row count of true flags inside [start, start+len)."""
        f32 = flags.astype(jnp.int32)
        ce = jnp.cumsum(f32)
        exc = ce - f32
        n = flags.shape[0]
        ends = jnp.clip(starts + lengths - 1, 0, max(n - 1, 0))
        s = jnp.clip(starts, 0, max(n - 1, 0))
        cnt = take_clip(ce, ends) - take_clip(exc, s)
        return jnp.where(lengths > 0, cnt, 0)

    def _bind_lambda_fn(self, e: Call) -> Bound:
        from trino_tpu.block import ArrayColumn, MapColumn
        from trino_tpu.expr import ir as _ir

        name = e.name
        coll_b = self.bind(e.args[0])
        lam: _ir.LambdaExpr = e.args[1]
        body_ir = self._lambda_body_ir(lam.body)
        out_t = e.type

        def body_over(flat_cols):
            """Bind + evaluate the body over flat element Columns."""
            binder = ExprBinder(
                [c.type for c in flat_cols],
                [c.dictionary for c in flat_cols],
            )
            b = binder.bind(body_ir)
            cols = [
                c if c.type.is_nested else c.data for c in flat_cols
            ]
            vals = [c.valid for c in flat_cols]
            d, v = b.fn(cols, vals)
            return d, v, b.dictionary

        def fn(cols, valids):
            c, cv = coll_b.fn(cols, valids)
            if name in ("transform", "filter", "any_match", "all_match",
                        "none_match"):
                flat = [c.flat]
            else:
                flat = [c.flat_keys, c.flat_values]
            d, v, bdict = body_over(flat)
            lengths = c.data
            starts = c.starts
            if name == "transform":
                out_flat = Column(out_t.element, d, v, bdict)
                return (
                    ArrayColumn(out_t, lengths, c.valid, None, starts,
                                out_flat),
                    cv,
                )
            if name in ("any_match", "all_match", "none_match"):
                keep = d if v is None else (d & v)
                cnt = self._seg_counts(keep, starts, lengths)
                if name == "any_match":
                    return cnt > 0, cv
                if name == "none_match":
                    return cnt == 0, cv
                return cnt == lengths, cv
            # filter / map_filter / transform_values / transform_keys
            if name in ("filter", "map_filter"):
                keep = d if v is None else (d & v)
                cnt = self._seg_counts(keep, starts, lengths)
                order = jnp.argsort(~keep, stable=True)
                new_starts = jnp.cumsum(cnt) - cnt
                if name == "filter":
                    return (
                        ArrayColumn(out_t, cnt, c.valid, None,
                                    new_starts.astype(jnp.int32),
                                    c.flat.gather(order)),
                        cv,
                    )
                return (
                    MapColumn(out_t, cnt, c.valid, None,
                              new_starts.astype(jnp.int32),
                              c.flat_keys.gather(order),
                              c.flat_values.gather(order)),
                    cv,
                )
            if name == "transform_values":
                return (
                    MapColumn(out_t, lengths, c.valid, None, starts,
                              c.flat_keys, Column(out_t.element, d, v, bdict)),
                    cv,
                )
            # transform_keys
            return (
                MapColumn(out_t, lengths, c.valid, None, starts,
                          Column(out_t.key, d, v, bdict), c.flat_values),
                cv,
            )

        return Bound(out_t, fn)

    # ---- array/map column functions (ArrayFunctions analogues) ----
    _ARRAY_FNS = (
        "slice", "trim_array", "repeat", "array_sort", "array_distinct",
        "array_position", "array_remove", "array_contains",
        "array_min_col", "array_max_col", "map_contains_key", "split",
        "regexp_split", "regexp_extract_all",
    )

    @staticmethod
    def _flat_rowids(starts, lengths, n_flat):
        """Row index of every flat element (canonical non-overlapping
        slices — constant arrays fold before reaching here)."""
        iota = jnp.arange(n_flat, dtype=jnp.int32)
        return (
            jnp.searchsorted(starts, iota, side="right").astype(jnp.int32)
            - 1
        )

    def _bind_array_fn(self, e: Call, args) -> Bound:
        from trino_tpu.block import ArrayColumn, MapColumn

        name = e.name
        out_t = e.type
        a = args[0]

        def compact(c, keep, out_t):
            cnt = self._seg_counts(keep, c.starts, c.data)
            order = jnp.argsort(~keep, stable=True)
            new_starts = (jnp.cumsum(cnt) - cnt).astype(jnp.int32)
            return ArrayColumn(
                out_t, cnt, c.valid, None, new_starts,
                c.flat.gather(order),
            )

        def fn(cols, valids):
            c, cv = a.fn(cols, valids)
            if name == "repeat":
                # repeat(x, n): each row's value tiled n times; x rides
                # as a plain scalar column
                n_rep = int(args[1].const_value)
                x = c  # scalar data array
                rows = _rows_of(x)
                flat = Column(
                    out_t.element, jnp.repeat(x, n_rep, axis=0),
                    None if cv is None else jnp.repeat(cv, n_rep),
                    a.dictionary,
                )
                return (
                    ArrayColumn(
                        out_t,
                        jnp.full(rows, n_rep, jnp.int32),
                        None,
                        None,
                        (jnp.arange(rows, dtype=jnp.int32) * n_rep),
                        flat,
                    ),
                    None,
                )
            lengths, starts = c.data, c.starts
            if name == "slice":
                start = args[1]
                ln = args[2]
                sd, sv = start.fn(cols, valids)
                ld, lv = ln.fn(cols, valids)
                sd = sd.astype(jnp.int32)
                ld = jnp.maximum(ld.astype(jnp.int32), 0)
                off = jnp.where(sd > 0, sd - 1, lengths + sd)
                off = jnp.clip(off, 0, lengths)
                new_len = jnp.clip(ld, 0, lengths - off)
                return (
                    ArrayColumn(out_t, new_len, c.valid, None,
                                starts + off, c.flat),
                    merge_valid(cv, sv, lv),
                )
            if name == "trim_array":
                nd, nv = args[1].fn(cols, valids)
                new_len = jnp.clip(
                    lengths - nd.astype(jnp.int32), 0, lengths
                )
                return (
                    ArrayColumn(out_t, new_len, c.valid, None, starts,
                                c.flat),
                    merge_valid(cv, nv),
                )
            if name == "map_contains_key":
                probe = args[1]
                kflat = c.flat_keys
                pd = _dict_code_const(probe, kflat.dictionary, kflat.type)
                match = kflat.data == pd
                if kflat.valid is not None:
                    match = match & kflat.valid
                cnt = self._seg_counts(match, starts, lengths)
                return cnt > 0, cv
            flat = c.flat
            n_flat = flat.data.shape[0]
            if name == "array_contains":
                probe = args[1]
                pd = _dict_code_const(probe, flat.dictionary, flat.type)
                match = flat.data == pd
                if flat.valid is not None:
                    match = match & flat.valid
                cnt = self._seg_counts(match, starts, lengths)
                return cnt > 0, cv
            rowid = self._flat_rowids(starts, lengths, n_flat)
            cap = lengths.shape[0]
            if name in ("array_min_col", "array_max_col"):
                vals = flat.data
                big = minmax_like(vals.dtype, name.endswith("min_col"))
                w = (
                    jnp.ones(n_flat, jnp.bool_)
                    if flat.valid is None else flat.valid
                )
                contrib = jnp.where(w, vals, jnp.asarray(big, vals.dtype))
                red = (
                    jax.ops.segment_min
                    if name.endswith("min_col")
                    else jax.ops.segment_max
                )
                out = red(contrib, rowid, num_segments=cap)
                has = self._seg_counts(w, starts, lengths) > 0
                valid = has if cv is None else (has & cv)
                return out, valid
            if name == "array_position":
                probe = args[1]
                pd = _dict_code_const(probe, flat.dictionary, flat.type)
                match = flat.data == pd
                if flat.valid is not None:
                    match = match & flat.valid
                pos_in_row = (
                    jnp.arange(n_flat, dtype=jnp.int32)
                    - take_clip(starts, rowid)
                )
                score = jnp.where(match, pos_in_row, jnp.int32(1 << 30))
                first = jax.ops.segment_min(
                    score, rowid, num_segments=cap
                )
                out = jnp.where(
                    first < (1 << 30), first.astype(jnp.int64) + 1,
                    jnp.int64(0),
                )
                return out, cv
            if name == "array_remove":
                probe = args[1]
                pd = _dict_code_const(probe, flat.dictionary, flat.type)
                keep = flat.data != pd
                if flat.valid is not None:
                    keep = keep | ~flat.valid  # NULL elements stay
                return compact(c, keep, out_t), cv
            # array_sort / array_distinct: stable in-segment value sort
            from trino_tpu.ops.sort import _order_value

            ov = _order_value(
                flat.data
                if getattr(flat.data, "ndim", 1) == 1
                else flat.data[:, 0],
                False,
            )
            iota = jnp.arange(n_flat, dtype=jnp.int32)
            _, sval, perm = jax.lax.sort(
                (rowid, ov, iota), num_keys=2
            )
            sorted_flat = flat.gather(perm)
            if name == "array_sort":
                return (
                    ArrayColumn(out_t, lengths, c.valid, None, starts,
                                sorted_flat),
                    cv,
                )
            # array_distinct (sorted order; Trino keeps first
            # occurrence — documented ordering deviation)
            srow = jax.lax.sort((rowid, ov, iota), num_keys=2)[0]
            first_elem = jnp.concatenate([
                jnp.ones(1, jnp.bool_),
                (sval[1:] != sval[:-1]) | (srow[1:] != srow[:-1]),
            ]) if n_flat else jnp.ones(0, jnp.bool_)
            c_sorted = ArrayColumn(
                out_t, lengths, c.valid, None, starts, sorted_flat
            )
            # keep flags are in SORTED flat order; recompute per-row
            # counts against the sorted layout's segments: rows keep
            # their [start, start+len) extents after an in-segment sort
            return compact(c_sorted, first_elem, out_t), cv

        return Bound(out_t, fn)

    def _bind_split(self, e: Call, args) -> Bound:
        """split(string, delimiter) and the regexp splitters: a
        per-dictionary-value string -> list-of-strings function. The
        output is CANONICAL — each row owns a W-wide flat slot (W = max
        part count over the dictionary) with its true length, so
        repacking consumers (filter/array_distinct/...) stay correct."""
        from trino_tpu.block import ArrayColumn

        a, delim = args[0], args[1]
        assert delim.is_const, f"{e.name}() pattern must be constant"
        sep = str(delim.const_value)
        values = a.dictionary.values if a.dictionary else []
        if e.name == "regexp_split":
            import re as _re

            rx = _re.compile(sep)
            parts_per_code = [rx.split(v) for v in values]
        elif e.name == "regexp_extract_all":
            import re as _re

            rx = _re.compile(sep)
            group = 0
            if len(args) > 2:
                assert args[2].is_const, (
                    "regexp_extract_all() group must be constant"
                )
                group = int(args[2].const_value)

            def matches(v):
                return [m.group(group) or "" for m in rx.finditer(v)]

            parts_per_code = [matches(v) for v in values]
        else:
            parts_per_code = [v.split(sep) if sep else [v] for v in values]
        W = max((len(p) for p in parts_per_code), default=1)
        out_dict = Dictionary(
            sorted({p for parts in parts_per_code for p in parts}) or [""]
        )
        # (codes, W) table: row c = the parts of dictionary value c,
        # padded with 0 (dead tail, masked by the true length)
        table = np.zeros((max(len(values), 1), W), dtype=np.int32)
        lens = np.zeros(max(len(values), 1), dtype=np.int32)
        for c, parts in enumerate(parts_per_code):
            lens[c] = len(parts)
            for j, pv in enumerate(parts):
                table[c, j] = out_dict.code(pv)
        table_j = jnp.asarray(table)
        lens_j = jnp.asarray(lens)
        out_t = e.type

        def fn(cols, valids):
            d, v = a.fn(cols, valids)
            code = jnp.clip(d, 0, max(len(values) - 1, 0))
            rows = code.shape[0]
            flat_codes = jnp.take(table_j, code, axis=0).reshape(-1)
            flat = Column(T.VARCHAR, flat_codes, None, out_dict)
            return (
                ArrayColumn(
                    out_t, take_clip(lens_j, code), v, None,
                    jnp.arange(rows, dtype=jnp.int32) * W, flat,
                ),
                v,
            )

        return Bound(out_t, fn)

    def _coerce_bound(self, b: Bound, out_t: T.DataType) -> Bound:
        """Coerce an already-bound expression to a target type via the
        cast machinery (branch unification for CASE/COALESCE)."""
        if b.type == out_t:
            return b
        import types as _pytypes

        shim = _pytypes.SimpleNamespace(type=out_t)
        return self._bind_cast_from(shim, b)

    def _remap_to(self, b: Bound, target: Dictionary) -> Bound:
        if b.dictionary is None or b.dictionary == target:
            return Bound(b.type, b.fn, target)
        remap = jnp.asarray(
            [target.code(v) for v in b.dictionary.values], dtype=jnp.int32
        )
        def fn(cols, valids, bfn=b.fn, remap=remap):
            d, v = bfn(cols, valids)
            return take_clip(remap, d), v
        return Bound(b.type, fn, target)

    # ---- IN list ----
    def _bind_in(self, e: InList) -> Bound:
        v = self.bind(e.value)
        has_null_option = any(o.value is None for o in e.options)
        if v.type.is_string:
            codes = [v.dictionary.code(o.value) for o in e.options if o.value is not None]
            opts = np.asarray([c for c in codes if c >= 0], dtype=np.int32)
        else:
            sf = T.decimal_scale_factor(v.type) if v.type.is_decimal else 1
            opts = np.asarray(
                [scale_decimal_value(o.value, v.type) if v.type.is_decimal else o.value
                 for o in e.options if o.value is not None],
                dtype=v.type.dtype,
            )
        opts_j = jnp.asarray(opts)
        def fn(cols, valids):
            d, val = v.fn(cols, valids)
            if opts_j.shape[0] == 0:
                hit = _const(d, False, jnp.bool_)
            else:
                hit = (d[:, None] == opts_j[None, :]).any(axis=1)
            # SQL 3VL: `x IN (a, NULL)` is NULL (not FALSE) when no a matches
            if has_null_option:
                val = hit if val is None else (val & hit)
            return hit, val
        return Bound(T.BOOLEAN, fn)

    # ---- calls ----
    _LAMBDA_FNS = (
        "transform", "filter", "any_match", "all_match", "none_match",
        "transform_values", "transform_keys", "map_filter",
    )

    def _bind_call(self, e: Call) -> Bound:
        name = e.name
        if name in self._LAMBDA_FNS:
            return self._bind_lambda_fn(e)
        if name in self._ARRAY_FNS:
            args = [self.bind(a) for a in e.args]
            if name in ("split", "regexp_split", "regexp_extract_all"):
                return self._bind_split(e, args)
            return self._bind_array_fn(e, args)
        if name in ("and", "or"):
            return self._bind_logical(e)
        args = [self.bind(a) for a in e.args]
        if name == "not":
            (a,) = args
            def notfn(cols, valids):
                d, v = a.fn(cols, valids)
                return ~d, v
            return Bound(T.BOOLEAN, notfn)
        if name == "is_null":
            (a,) = args
            def infn(cols, valids):
                d, v = a.fn(cols, valids)
                if v is None:
                    return _const(d, False, jnp.bool_), None
                return ~v, None
            return Bound(T.BOOLEAN, infn)
        if name == "coalesce":
            return self._bind_coalesce(e, args)
        if name in ("eq", "ne", "lt", "le", "gt", "ge"):
            return self._bind_comparison(name, args)
        if name in ("add", "sub", "mul", "div", "mod"):
            return self._bind_arith(name, e.type, args)
        if name == "negate":
            (a,) = args
            def negfn(cols, valids):
                d, v = a.fn(cols, valids)
                return -d, v
            return Bound(e.type, negfn, a.dictionary)
        if name in (
            "at_timezone_id", "ts_to_tstz", "tstz_to_ts", "parse_tstz",
            "tstz_shift", "tstz_timezone_hour", "tstz_timezone_minute",
            "tstz_to_instant_ts", "tstz_rewall",
        ):
            return self._bind_tstz(name, e, args)
        if name in ("extract_year", "extract_month", "extract_day"):
            (a,) = args
            part = {"extract_year": F.extract_year, "extract_month": F.extract_month,
                    "extract_day": F.extract_day}[name]
            def exfn(cols, valids):
                d, v = a.fn(cols, valids)
                days = d
                if a.type.kind == T.TypeKind.TIMESTAMP:
                    days = d // (86400 * 1000 * 1000)
                return part(days).astype(jnp.int64), v
            return Bound(T.BIGINT, exfn)
        if name == "like":
            return self._bind_like(e, args)
        if name in ("substr", "substring"):
            return self._bind_dict_transform(
                args[0],
                e,
                lambda s: self._py_substr(s, e.args[1], e.args[2] if len(e.args) > 2 else None),
            )
        if name in ("upper", "lower"):
            return self._bind_dict_transform(
                args[0], e, (str.upper if name == "upper" else str.lower)
            )
        if name == "length":
            a = args[0]
            if a.dictionary is None or len(a.dictionary) == 0:
                return self._null_of(a, T.BIGINT)
            table = jnp.asarray([len(v) for v in a.dictionary.values], dtype=jnp.int64)
            def lenfn(cols, valids):
                d, v = a.fn(cols, valids)
                return take_clip(table, d), v
            return Bound(T.BIGINT, lenfn)
        if name == "abs":
            (a,) = args
            def absfn(cols, valids):
                d, v = a.fn(cols, valids)
                return jnp.abs(d), v
            return Bound(e.type, absfn)
        if name == "round":
            a = args[0]
            if len(args) > 1:
                assert args[1].is_const, "round() scale must be constant"
                ndig = int(args[1].const_value)
            else:
                ndig = 0
            if a.type.is_decimal:
                s = a.type.scale or 0
                if ndig >= s:
                    return Bound(e.type, a.fn)
                m = 10 ** (s - ndig)
                def rdfn(cols, valids, afn=a.fn, m=m):
                    d, v = afn(cols, valids)
                    return F.div_round_half_away(d, _const(d, m, d.dtype)) * m, v
                return Bound(e.type, rdfn)
            def rfn(cols, valids, afn=a.fn, ndig=ndig):
                d, v = afn(cols, valids)
                sf = 10.0 ** ndig
                out = F.round_half_away(d.astype(jnp.float64) * sf) / sf
                if e.type.is_integerlike:
                    out = out.astype(e.type.dtype)
                return out, v
            return Bound(e.type, rfn)
        if name in ("trim", "ltrim", "rtrim", "reverse"):
            pyf = {"trim": str.strip, "ltrim": str.lstrip,
                   "rtrim": str.rstrip, "reverse": lambda s: s[::-1]}[name]
            return self._bind_dict_transform(args[0], e, pyf)
        if name == "replace":
            frm, to = e.args[1], e.args[2] if len(e.args) > 2 else Literal("", T.VARCHAR)
            assert isinstance(frm, Literal) and isinstance(to, Literal), (
                "replace() search/replacement must be constants"
            )
            return self._bind_dict_transform(
                args[0], e, lambda s: s.replace(frm.value, to.value)
            )
        if name == "starts_with":
            a, prefix = args[0], e.args[1]
            assert isinstance(prefix, Literal), "starts_with() prefix must be constant"
            if a.dictionary is None or len(a.dictionary) == 0:
                return self._null_of(a, T.BOOLEAN)
            table = jnp.asarray(
                [v.startswith(prefix.value) for v in a.dictionary.values],
                dtype=jnp.bool_,
            )
            def swfn(cols, valids, afn=a.fn):
                d, v = afn(cols, valids)
                return take_clip(table, d), v
            return Bound(T.BOOLEAN, swfn)
        if name == "concat":
            return self._bind_concat(e, args)
        if name == "nullif":
            a, b = args
            # route equality through the comparison binder so dictionary
            # unification and decimal rescaling apply (TypeOperators'
            # equality contract), then keep a's representation
            eqb = self._bind_comparison("eq", [a, b])
            def nifn(cols, valids):
                da, va = a.fn(cols, valids)
                de, ve = eqb.fn(cols, valids)
                eq = de if ve is None else (de & ve)
                v = va if va is not None else _const(da, True, jnp.bool_)
                return da, v & ~eq
            return Bound(e.type, nifn, a.dictionary)
        if name in ("greatest", "least"):
            jf = jnp.maximum if name == "greatest" else jnp.minimum
            out_dict = None
            if e.type.is_string:
                # unified dictionaries are sorted, so code order ==
                # lexical order and max/min on codes is correct
                merged = None
                for a in args:
                    if a.dictionary is not None:
                        merged = (
                            a.dictionary
                            if merged is None
                            else Dictionary.unify(merged, a.dictionary)[0]
                        )
                if merged is None:
                    return self._null_of(args[0], e.type)
                args = [self._remap_to(a, merged) for a in args]
                out_dict = merged
            def glfn(cols, valids):
                data, valid = args[0].fn(cols, valids)
                data = data.astype(e.type.dtype)
                for a in args[1:]:
                    d, v = a.fn(cols, valids)
                    data = jf(data, d.astype(e.type.dtype))
                    if v is not None:  # NULL poisons (Trino semantics)
                        valid = v if valid is None else (valid & v)
                return data, valid
            return Bound(e.type, glfn, out_dict)
        if name == "power":
            a, b = args
            dsa = T.decimal_scale_factor(a.type) if a.type.is_decimal else 1
            dsb = T.decimal_scale_factor(b.type) if b.type.is_decimal else 1
            def pwfn(cols, valids):
                da, va = a.fn(cols, valids)
                db, vb = b.fn(cols, valids)
                out = jnp.power(da.astype(jnp.float64) / dsa,
                                db.astype(jnp.float64) / dsb)
                v = va
                if vb is not None:
                    v = vb if v is None else (v & vb)
                return out, v
            return Bound(T.DOUBLE, pwfn)
        if name in ("log2", "log10"):
            (a,) = args[:1]
            base = 2.0 if name == "log2" else 10.0
            ds = T.decimal_scale_factor(a.type) if a.type.is_decimal else 1
            def lgfn(cols, valids):
                d, v = a.fn(cols, valids)
                return jnp.log(d.astype(jnp.float64) / ds) / np.log(base), v
            return Bound(T.DOUBLE, lgfn)
        if name == "sign":
            (a,) = args
            def sgfn(cols, valids):
                d, v = a.fn(cols, valids)
                return jnp.sign(d).astype(e.type.dtype), v
            return Bound(e.type, sgfn)
        if name in ("hll_bucket", "hll_rho", "hll_weight"):
            # HyperLogLog primitives for the approx_distinct plan rewrite
            # (sql/optimizer.RewriteApproxDistinct): bucket = low 11 bits
            # of a value-stable 62-bit hash; rho = leading-zero rank of
            # the remaining 51 bits + 1; weight = 2^-rho. String columns
            # hash the dictionary VALUE (stable across workers whose
            # batches carry different dictionaries) via the same per-code
            # value hashes the exchange partitioner uses.
            from trino_tpu.ops.hashing import dictionary_code_hashes, hash64

            a = args[0]
            a_dict = a.dictionary

            def hllfn(cols, valids, a=a, a_dict=a_dict, name=name):
                d, v = a.fn(cols, valids)
                if isinstance(d, Column):
                    if d.dictionary is not None:
                        a_dict2 = d.dictionary
                    else:
                        a_dict2 = a_dict
                    v = d.valid if v is None else v
                    d = d.data
                else:
                    a_dict2 = a_dict
                if a_dict2 is not None and len(a_dict2) > 0:
                    vh = jnp.asarray(
                        dictionary_code_hashes(a_dict2.values).astype("int64")
                    )
                    basis = take_clip(vh, jnp.clip(d, 0, len(a_dict2) - 1))
                else:
                    basis = d
                h = hash64([basis], [v])
                if name == "hll_bucket":
                    return (h & jnp.int64(2047)).astype(jnp.int64), v
                w51 = (h >> jnp.int64(11)).astype(jnp.float64)
                # rho = leading zeros within the 51-bit window + 1
                rho = jnp.where(
                    w51 > 0,
                    jnp.int64(51) - jnp.floor(jnp.log2(
                        jnp.maximum(w51, 1.0)
                    )).astype(jnp.int64),
                    jnp.int64(52),
                )
                if name == "hll_rho":
                    return rho, v
                return jnp.exp2(-rho.astype(jnp.float64)), v

            return Bound(e.type, hllfn)
        if name in ("bitwise_and", "bitwise_or", "bitwise_xor",
                    "bitwise_left_shift", "bitwise_right_shift",
                    "bitwise_right_shift_arithmetic"):
            a, b = args
            jf = {
                "bitwise_and": jnp.bitwise_and,
                "bitwise_or": jnp.bitwise_or,
                "bitwise_xor": jnp.bitwise_xor,
                # Trino's left/right shift operate on the 64-bit pattern;
                # plain right shift is LOGICAL (zero-filling)
                "bitwise_left_shift": lambda x, s: x << s,
                "bitwise_right_shift": lambda x, s: (
                    jax.lax.shift_right_logical(x, s)
                ),
                "bitwise_right_shift_arithmetic": lambda x, s: x >> s,
            }[name]

            def bwfn(cols, valids, a=a, b=b, jf=jf):
                x, xv = a.fn(cols, valids)
                s, sv = b.fn(cols, valids)
                out = jf(x.astype(jnp.int64), s.astype(jnp.int64))
                v = xv if sv is None else (sv if xv is None else (xv & sv))
                return out, v

            return Bound(T.BIGINT, bwfn)
        if name == "bit_count":
            a = args[0]
            bits = 64
            if len(e.args) > 1:
                blit = e.args[1]
                if isinstance(blit, Literal):
                    bits = int(blit.value)

            def bcfn(cols, valids, a=a, bits=bits):
                d, v = a.fn(cols, valids)
                x = jax.lax.bitcast_convert_type(
                    d.astype(jnp.int64), jnp.uint64
                )
                if bits < 64:  # count within the low `bits` only
                    x = x & jnp.uint64((1 << bits) - 1)
                return jax.lax.population_count(x).astype(jnp.int64), v

            return Bound(T.BIGINT, bcfn)
        if name == "rand":
            # pseudorandom per bind: a fresh PRNG key is drawn host-side
            # when the expression binds (per query/batch-shape), rows get
            # independent draws from it. The reference's rand() is
            # likewise non-deterministic per evaluation (MathFunctions).
            import os as _os

            seed = int.from_bytes(_os.urandom(4), "little")
            bounds = [a for a in args]

            def rndfn(cols, valids, seed=seed, bounds=bounds):
                ref = cols[0] if cols else jnp.zeros(1)
                if hasattr(ref, "data") and not hasattr(ref, "shape"):
                    ref = ref.data
                n = ref.shape[0]
                # fold the batch CONTENT into the key: a bind-time seed
                # alone would replay the identical "random" vector for
                # every batch of a multi-batch scan (biased sampling).
                # astype truncation, not bitcast — f64 bitcasts don't
                # compile on this TPU backend
                x = ref.reshape(-1)[:1024]
                if jnp.issubdtype(x.dtype, jnp.floating):
                    x = jnp.rint(x * 4096.0)
                entropy = jnp.sum(x.astype(jnp.int64)).astype(jnp.uint32)
                key = jax.random.fold_in(jax.random.PRNGKey(seed), entropy)
                u = jax.random.uniform(key, (n,), dtype=jnp.float64)
                if not bounds:
                    return u, None
                vs = [b.fn(cols, valids) for b in bounds]
                v = merge_valid(*[x[1] for x in vs])
                if len(vs) == 1:
                    hi = vs[0][0].astype(jnp.float64)
                    return jnp.floor(u * hi).astype(jnp.int64), v
                lo = vs[0][0].astype(jnp.float64)
                hi = vs[1][0].astype(jnp.float64)
                return (
                    (lo + jnp.floor(u * (hi - lo))).astype(jnp.int64),
                    v,
                )

            out_t = T.DOUBLE if not args else T.BIGINT
            return Bound(out_t, rndfn)
        if name in ("e", "pi", "nan", "infinity"):
            val = {"e": math.e, "pi": math.pi, "nan": float("nan"),
                   "infinity": float("inf")}[name]

            def cfn(cols, valids, val=val):
                ref = cols[0] if cols else jnp.zeros(1)
                return _const(ref, val, jnp.float64), None

            return Bound(T.DOUBLE, cfn, const_value=val, is_const=True)
        if name == "cot":
            a = args[0]
            sf_a = T.decimal_scale_factor(a.type) if a.type.is_decimal else 1

            def cotfn(cols, valids, a=a, sf_a=sf_a):
                d, v = a.fn(cols, valids)
                return 1.0 / jnp.tan(d.astype(jnp.float64) / sf_a), v

            return Bound(T.DOUBLE, cotfn)
        if name in _PROB_FNS:
            arity, pf = _PROB_FNS[name]
            def probfn(cols, valids, args=args, pf=pf):
                outs = [a.fn(cols, valids) for a in args]
                v = merge_valid(*[o[1] for o in outs])
                ds = [
                    _f64_of_decimal(o[0], a.type)
                    if a.type.is_decimal
                    else o[0].astype(jnp.float64)
                    for a, o in zip(args, outs)
                ]
                return pf(*ds), v
            return Bound(T.DOUBLE, probfn)
        if name == "year_of_week":
            a = args[0]
            def yowfn(cols, valids, a=a):
                d, v = a.fn(cols, valids)
                days = self._to_days(a, d)
                # ISO week-year = calendar year of that week's Thursday
                thu = days - (F.day_of_week(days) - 1) + 3
                return F.extract_year(thu).astype(jnp.int64), v
            return Bound(T.BIGINT, yowfn)
        if name in ("normal_cdf", "inverse_normal_cdf", "width_bucket"):
            # numeric args arrive in their PHYSICAL form (decimal =
            # scaled int64): descale to doubles before the math
            sfs = [
                T.decimal_scale_factor(a.type) if a.type.is_decimal else 1
                for a in args
            ]

            def _doubles(cols, valids):
                outs, v = [], None
                for a, sf in zip(args, sfs):
                    d, dv = a.fn(cols, valids)
                    outs.append(d.astype(jnp.float64) / sf)
                    if dv is not None:
                        v = dv if v is None else (v & dv)
                return outs, v

            if name == "width_bucket":
                # constant bound validation at bind time (Trino raises
                # INVALID_FUNCTION_ARGUMENT for these at runtime)
                lits = [
                    a.const_value if a.is_const else None for a in args
                ]
                if (lits[1] is not None and lits[2] is not None
                        and float(lits[1]) == float(lits[2])):
                    raise ValueError(
                        "width_bucket bounds cannot equal each other"
                    )
                if lits[3] is not None and int(lits[3]) <= 0:
                    raise ValueError(
                        "width_bucket bucketCount must be greater than 0"
                    )

                def wbfn(cols, valids):
                    (x, lo, hi, nb), v = _doubles(cols, valids)
                    # frac-based clamps work for BOTH bound orientations
                    # (Trino supports reversed bounds = descending
                    # buckets): frac < 0 is out-of-range low, >= 1 high
                    frac = (x - lo) / (hi - lo)
                    b = jnp.floor(frac * nb) + 1
                    b = jnp.where(frac < 0, 0.0, b)
                    b = jnp.where(frac >= 1, nb + 1, b)
                    return b.astype(jnp.int64), v

                return Bound(T.BIGINT, wbfn)

            def ncfn(cols, valids, name=name):
                from jax.scipy.special import erf, erfinv

                (m, s, x), v = _doubles(cols, valids)
                if name == "normal_cdf":
                    out = 0.5 * (1.0 + erf((x - m) / (s * jnp.sqrt(2.0))))
                else:
                    out = m + s * jnp.sqrt(2.0) * erfinv(2.0 * x - 1.0)
                return out, v

            return Bound(T.DOUBLE, ncfn)
        if name in ("hour", "minute", "second", "millisecond"):
            a = args[0]

            def tmfn(cols, valids, a=a, name=name):
                d, v = a.fn(cols, valids)
                if a.type.kind == T.TypeKind.TIMESTAMP:
                    us = d.astype(jnp.int64) % (86400 * 1000 * 1000)
                    us = jnp.where(us < 0, us + 86400 * 1000 * 1000, us)
                else:  # DATE has no time component
                    us = jnp.zeros_like(d.astype(jnp.int64))
                out = {
                    "hour": us // (3600 * 1000 * 1000),
                    "minute": (us // (60 * 1000 * 1000)) % 60,
                    "second": (us // (1000 * 1000)) % 60,
                    "millisecond": (us // 1000) % 1000,
                }[name]
                return out.astype(jnp.int64), v

            return Bound(T.BIGINT, tmfn)
        if name == "from_unixtime_nanos":
            a = args[0]

            def funfn(cols, valids, a=a):
                d, v = a.fn(cols, valids)
                # floor division, not truncation: -1ns is microsecond -1
                # (the reference truncates toward negative infinity)
                return (
                    jnp.floor_divide(d.astype(jnp.int64), jnp.int64(1000)),
                    v,
                )

            return Bound(T.TIMESTAMP, funfn)
        if name in ("timezone_hour", "timezone_minute"):
            # engine timestamps are UTC instants (no with-time-zone
            # physical type yet): the session offset is 0
            a = args[0]

            def tzfn(cols, valids, a=a):
                d, v = a.fn(cols, valids)
                return jnp.zeros(d.shape[:1], dtype=jnp.int64), v

            return Bound(T.BIGINT, tzfn)
        if name == "from_unixtime":
            a = args[0]
            sf_a = T.decimal_scale_factor(a.type) if a.type.is_decimal else 1

            def fufn(cols, valids, a=a, sf_a=sf_a):
                d, v = a.fn(cols, valids)
                secs = d.astype(jnp.float64) / sf_a
                # rint, not truncation: negative fractional epochs
                # (pre-1970) must round to the nearest microsecond
                # (ADVICE r3: -0.5s is -500000us, not 0)
                return jnp.rint(secs * 1e6).astype(jnp.int64), v

            return Bound(T.TIMESTAMP, fufn)
        if name == "to_unixtime":
            a = args[0]

            def tufn(cols, valids, a=a):
                d, v = a.fn(cols, valids)
                us = d.astype(jnp.float64)
                if a.type.kind == T.TypeKind.DATE:
                    return us * 86400.0, v
                return us / 1e6, v

            return Bound(T.DOUBLE, tufn)
        if name == "date_parse":
            fmt = e.args[1].value if len(e.args) > 1 else "%Y-%m-%d"
            import datetime as _dt

            def dpfn(s, fmt=fmt):
                # MySQL-style tokens -> strptime (the subset that maps 1:1)
                py = (fmt.replace("%i", "%M").replace("%s", "%S"))
                try:
                    dt = _dt.datetime.strptime(s, py)
                except ValueError:
                    return None
                epoch = _dt.datetime(1970, 1, 1)
                return int((dt - epoch).total_seconds() * 1e6)

            return self._bind_dict_table_nullable(
                args[0], T.TIMESTAMP, dpfn, jnp.int64
            )
        if name in ("json_extract", "json_format", "json_parse",
                    "is_json_scalar", "json_array_contains",
                    "json_array_get"):
            return self._bind_json_breadth(name, e, args)
        if name in ("soundex", "normalize"):
            pyf = {
                "soundex": _py_soundex,
                "normalize": lambda s: __import__(
                    "unicodedata"
                ).normalize("NFC", s),
            }[name]
            return self._bind_dict_transform(args[0], e, pyf)
        if name == "regexp_position":
            pat = _re.compile(e.args[1].value)

            def rpfn(s, pat=pat):
                m = pat.search(s)
                return m.start() + 1 if m else -1

            return self._bind_dict_table(args[0], T.BIGINT, rpfn, jnp.int64)
        if name == "pctl_bucket":
            # quantile-sketch bucket for the mergeable approx_percentile:
            # order-preserving f32 bit encoding truncated to
            # sign+exponent+9 mantissa bits (2^-9 = 0.2% within-bucket
            # relative width; exact whenever a bucket holds one distinct
            # value). f32 bitcasts compile on TPU; f64 ones do not
            # (ops/floatbits).
            from trino_tpu.ops.floatbits import f32_bits_ordered

            a = args[0]

            def pbfn(cols, valids, a=a):
                d, v = a.fn(cols, valids)
                enc = f32_bits_ordered(
                    d.astype(jnp.float64).astype(jnp.float32)
                )
                return (enc >> jnp.uint32(14)).astype(jnp.int64), v

            return Bound(T.BIGINT, pbfn)
        if name == "hll_weight_rho":
            # (merged max-rho, bucket) -> register weight 2^-rho; the
            # NULL-bucket group (all-NULL inputs) weighs 0 so it neither
            # contributes a register nor drops its key group
            r, b = args

            def hwfn(cols, valids, r=r, b=b):
                rd, rv = r.fn(cols, valids)
                _, bv = b.fn(cols, valids)
                ok = jnp.ones_like(rd, jnp.bool_)
                if rv is not None:
                    ok = ok & rv
                if bv is not None:
                    ok = ok & bv
                w = jnp.where(
                    ok, jnp.exp2(-rd.astype(jnp.float64)), 0.0
                )
                return w, None

            return Bound(T.DOUBLE, hwfn)
        if name == "hll_estimate":
            # finalize: raw = alpha_m * m^2 / (sum_w + zero_registers),
            # linear-counting correction for the small range
            # (ApproximateCountDistinctAggregations / airlift HLL)
            m = 2048.0
            alpha = 0.7213 / (1.0 + 1.079 / m)
            sw, cnt = args

            def hefn(cols, valids, sw=sw, cnt=cnt):
                s, sv = sw.fn(cols, valids)
                c, cv = cnt.fn(cols, valids)
                c = c.astype(jnp.float64)
                zeros = m - c
                raw = alpha * m * m / (s.astype(jnp.float64) + zeros)
                small = (raw <= 2.5 * m) & (zeros > 0)
                est = jnp.where(
                    small, m * jnp.log(m / jnp.maximum(zeros, 1.0)), raw
                )
                out = jnp.round(est).astype(jnp.int64)
                # NULL states (empty input / all-NULL group) estimate 0
                ok_s = jnp.ones_like(out, jnp.bool_) if sv is None else sv
                ok_c = jnp.ones_like(out, jnp.bool_) if cv is None else cv
                out = jnp.where(ok_s & ok_c, out, 0)
                return out, None

            return Bound(e.type, hefn)
        if name in ("sqrt", "ln", "exp", "floor", "ceil"):
            (a,) = args[:1]
            jf = {"sqrt": F.sqrt_exact, "ln": jnp.log, "exp": jnp.exp,
                  "floor": jnp.floor, "ceil": jnp.ceil}[name]
            descale = T.decimal_scale_factor(a.type) if a.type.is_decimal else 1
            out_scale = T.decimal_scale_factor(e.type) if e.type.is_decimal else None
            def mfn(cols, valids):
                d, v = a.fn(cols, valids)
                out = jf(d.astype(jnp.float64) / descale)
                if out_scale is not None:
                    out = F.round_half_away(out * out_scale).astype(e.type.dtype)
                elif e.type.is_integerlike:
                    out = out.astype(e.type.dtype)
                return out, v
            return Bound(e.type, mfn)
        if name in _UNARY_DOUBLE_FNS:
            (a,) = args[:1]
            jf = _UNARY_DOUBLE_FNS[name]
            ds = T.decimal_scale_factor(a.type) if a.type.is_decimal else 1
            def udfn(cols, valids):
                d, v = a.fn(cols, valids)
                return jf(d.astype(jnp.float64) / ds), v
            return Bound(T.DOUBLE, udfn)
        if name in ("is_nan", "is_infinite", "is_finite"):
            (a,) = args
            jf = {"is_nan": jnp.isnan, "is_infinite": jnp.isinf,
                  "is_finite": jnp.isfinite}[name]
            def ckfn(cols, valids):
                d, v = a.fn(cols, valids)
                return jf(d.astype(jnp.float64)), v
            return Bound(T.BOOLEAN, ckfn)
        if name in ("atan2", "log"):
            a, b = args
            def bifn(cols, valids):
                da, va = a.fn(cols, valids)
                db, vb = b.fn(cols, valids)
                x = da.astype(jnp.float64)
                y = db.astype(jnp.float64)
                if name == "atan2":
                    out = jnp.arctan2(x, y)
                else:  # log(base, x)
                    out = jnp.log(y) / jnp.log(x)
                return out, merge_valid(va, vb)
            return Bound(T.DOUBLE, bifn)
        if name == "truncate":
            a = args[0]
            ndig = 0
            if len(args) > 1:
                assert args[1].is_const, "truncate() scale must be constant"
                ndig = int(args[1].const_value)
            if a.type.is_decimal:
                s = a.type.scale or 0
                if ndig >= s:
                    return Bound(e.type, a.fn)
                m = 10 ** (s - ndig)
                def tdfn(cols, valids, afn=a.fn, m=m):
                    d, v = afn(cols, valids)
                    return F.div_trunc(d, _const(d, m, d.dtype)) * m, v
                return Bound(e.type, tdfn)
            def trfn(cols, valids, afn=a.fn, ndig=ndig):
                d, v = afn(cols, valids)
                sf = 10.0 ** ndig
                x = d.astype(jnp.float64) * sf
                return jnp.sign(x) * jnp.floor(jnp.abs(x)) / sf, v
            return Bound(T.DOUBLE, trfn)
        if name in ("bitwise_and", "bitwise_or", "bitwise_xor",
                    "bitwise_left_shift", "bitwise_right_shift"):
            a, b = args
            def logical_rshift(x, n):
                # Trino's bitwise_right_shift is a LOGICAL zero-fill
                # shift (the arithmetic variant is a separate function)
                return jnp.right_shift(
                    x.astype(jnp.uint64), n.astype(jnp.uint64)
                ).astype(jnp.int64)
            jf = {"bitwise_and": jnp.bitwise_and,
                  "bitwise_or": jnp.bitwise_or,
                  "bitwise_xor": jnp.bitwise_xor,
                  "bitwise_left_shift": jnp.left_shift,
                  "bitwise_right_shift": logical_rshift}[name]
            def bwfn(cols, valids):
                da, va = a.fn(cols, valids)
                db, vb = b.fn(cols, valids)
                return (
                    jf(da.astype(jnp.int64), db.astype(jnp.int64)),
                    merge_valid(va, vb),
                )
            return Bound(T.BIGINT, bwfn)
        if name == "bitwise_not":
            (a,) = args
            def bnfn(cols, valids):
                d, v = a.fn(cols, valids)
                return jnp.bitwise_not(d.astype(jnp.int64)), v
            return Bound(T.BIGINT, bnfn)
        # -- string functions over dictionary values (host-side transform,
        # device-side code remap — never per row) --
        if name == "strpos":
            sub = e.args[1]
            assert isinstance(sub, Literal), "strpos() substring must be constant"
            return self._bind_dict_table(
                args[0], T.BIGINT,
                lambda s: s.find(sub.value) + 1, jnp.int64,
            )
        if name == "ends_with":
            suffix = e.args[1]
            assert isinstance(suffix, Literal), "ends_with() suffix must be constant"
            return self._bind_dict_table(
                args[0], T.BOOLEAN,
                lambda s: s.endswith(suffix.value), jnp.bool_,
            )
        if name == "codepoint":
            # empty string has no code point: NULL (Trino raises; a
            # data-dependent error can't abort an XLA program — the
            # module-docstring deviation applies)
            a = args[0]
            if a.dictionary is None or len(a.dictionary) == 0:
                return self._null_of(a, T.BIGINT)
            table = jnp.asarray(
                [ord(v[0]) if v else 0 for v in a.dictionary.values],
                dtype=jnp.int64,
            )
            ok_t = jnp.asarray(
                [len(v) > 0 for v in a.dictionary.values], dtype=jnp.bool_
            )
            def cpfn(cols, valids, afn=a.fn):
                d, v = afn(cols, valids)
                idx = jnp.clip(d, 0, table.shape[0] - 1)
                ok = take_clip(ok_t, idx)
                return take_clip(table, idx), ok if v is None else (v & ok)
            return Bound(T.BIGINT, cpfn)
        if name == "split_part":
            delim, idx = e.args[1], e.args[2]
            assert isinstance(delim, Literal) and isinstance(idx, Literal), (
                "split_part() delimiter/index must be constants"
            )
            n = int(idx.value)
            assert n >= 1, "split_part() index is 1-based"
            def sp(s: str) -> str:
                parts = s.split(delim.value)
                return parts[n - 1] if n <= len(parts) else ""
            return self._bind_dict_transform(args[0], e, sp)
        if name in ("lpad", "rpad"):
            size = e.args[1]
            pad = e.args[2] if len(e.args) > 2 else Literal(" ", T.VARCHAR)
            assert isinstance(size, Literal) and isinstance(pad, Literal), (
                f"{name}() size/padstring must be constants"
            )
            width, fill = int(size.value), pad.value or " "
            def padfn(s: str) -> str:
                if len(s) >= width:
                    return s[:width]
                need = width - len(s)
                padding = (fill * need)[:need]
                return padding + s if name == "lpad" else s + padding
            return self._bind_dict_transform(args[0], e, padfn)
        if name == "translate":
            frm, to = e.args[1], e.args[2]
            assert isinstance(frm, Literal) and isinstance(to, Literal), (
                "translate() from/to must be constants"
            )
            table = {}
            for i, c in enumerate(frm.value):
                if c not in table:
                    table[c] = to.value[i] if i < len(to.value) else None
            def trl(s: str) -> str:
                return "".join(
                    table.get(c, c) for c in s if table.get(c, c) is not None
                )
            return self._bind_dict_transform(args[0], e, trl)
        if name in ("regexp_like", "regexp_count"):
            pat = e.args[1]
            assert isinstance(pat, Literal), "regexp pattern must be constant"
            rx = _re.compile(pat.value)
            if name == "regexp_like":
                return self._bind_dict_table(
                    args[0], T.BOOLEAN,
                    lambda s: rx.search(s) is not None, jnp.bool_,
                )
            return self._bind_dict_table(
                args[0], T.BIGINT,
                lambda s: sum(1 for _ in rx.finditer(s)), jnp.int64,
            )
        if name == "regexp_extract":
            pat = e.args[1]
            assert isinstance(pat, Literal), "regexp pattern must be constant"
            group = 0
            if len(e.args) > 2:
                g = e.args[2]
                assert isinstance(g, Literal), "regexp group must be constant"
                group = int(g.value)
            rx = _re.compile(pat.value)
            # NULL result for non-matches: transform to a sentinel and
            # mask it out (dictionary transforms are total functions)
            a = args[0]
            if a.dictionary is None or len(a.dictionary) == 0:
                return self._null_of(a, T.VARCHAR)
            hits, matched = [], []
            for v in a.dictionary.values:
                m = rx.search(v)
                ok = m is not None and (group == 0 or m.group(group) is not None)
                matched.append(ok)
                hits.append(m.group(group) if ok else "")
            new_dict = Dictionary(hits)
            remap = jnp.asarray(
                [new_dict.code(h) for h in hits], dtype=jnp.int32
            )
            ok_t = jnp.asarray(matched, dtype=jnp.bool_)
            def refn(cols, valids, afn=a.fn):
                d, v = afn(cols, valids)
                idx = jnp.clip(d, 0, remap.shape[0] - 1)
                ok = take_clip(ok_t, idx)
                return take_clip(remap, idx), ok if v is None else (v & ok)
            return Bound(T.VARCHAR, refn, new_dict)
        if name == "regexp_replace":
            pat = e.args[1]
            rep = e.args[2] if len(e.args) > 2 else Literal("", T.VARCHAR)
            assert isinstance(pat, Literal) and isinstance(rep, Literal), (
                "regexp_replace() pattern/replacement must be constants"
            )
            rx = _re.compile(pat.value)
            # Trino replacement template: $N = group ref, \$ = literal
            # dollar, \\ = literal backslash. Parse once into segments
            # and substitute with a callable (avoids python \-escape
            # reinterpretation of the template).
            segs: List[object] = []  # str literal | int group number
            buf: List[str] = []
            t = rep.value
            i = 0
            while i < len(t):
                c = t[i]
                if c == "\\" and i + 1 < len(t):
                    buf.append(t[i + 1])
                    i += 2
                elif c == "$" and i + 1 < len(t) and t[i + 1].isdigit():
                    j = i + 1
                    while j < len(t) and t[j].isdigit():
                        j += 1
                    if buf:
                        segs.append("".join(buf))
                        buf = []
                    segs.append(int(t[i + 1:j]))
                    i = j
                else:
                    buf.append(c)
                    i += 1
            if buf:
                segs.append("".join(buf))
            def rrepl(m):
                return "".join(
                    s if isinstance(s, str) else (m.group(s) or "")
                    for s in segs
                )
            return self._bind_dict_transform(
                args[0], e, lambda s: rx.sub(rrepl, s)
            )
        # -- date arithmetic (vectorized civil calendar, functions.py) --
        if name in ("quarter", "week", "day_of_week", "day_of_year"):
            a = args[0]
            part = {"quarter": lambda d: (F.extract_month(d) - 1) // 3 + 1,
                    "week": F.week_of_year,
                    "day_of_week": F.day_of_week,
                    "day_of_year": F.day_of_year}[name]
            def dpfn(cols, valids):
                d, v = a.fn(cols, valids)
                return part(self._to_days(a, d)).astype(jnp.int64), v
            return Bound(T.BIGINT, dpfn)
        if name == "date_trunc":
            unit = e.args[0]
            assert isinstance(unit, Literal), "date_trunc unit must be constant"
            a = args[1]
            u = unit.value.lower()
            if a.type.kind == T.TypeKind.TIMESTAMP:
                def ttfn(cols, valids):
                    d, v = a.fn(cols, valids)
                    if u in _MICROS_PER_UNIT:
                        q = _MICROS_PER_UNIT[u]
                        return (d // q) * q, v
                    days = F.date_trunc_days(u, d // _MICROS_PER_DAY)
                    return days.astype(jnp.int64) * _MICROS_PER_DAY, v
                return Bound(T.TIMESTAMP, ttfn)
            def tdfn2(cols, valids):
                d, v = a.fn(cols, valids)
                return F.date_trunc_days(u, d).astype(a.type.dtype), v
            return Bound(a.type, tdfn2)
        if name == "date_add":
            unit = e.args[0]
            assert isinstance(unit, Literal), "date_add unit must be constant"
            u = unit.value.lower()
            nb, a = args[1], args[2]
            if a.type.kind == T.TypeKind.TIMESTAMP:
                def tafn(cols, valids):
                    d, v = a.fn(cols, valids)
                    n, vn = nb.fn(cols, valids)
                    if u in _MICROS_PER_UNIT:
                        out = d + n.astype(jnp.int64) * _MICROS_PER_UNIT[u]
                    else:
                        rem = d % _MICROS_PER_DAY
                        days = F.date_add_days(u, n, d // _MICROS_PER_DAY)
                        out = days.astype(jnp.int64) * _MICROS_PER_DAY + rem
                    return out, merge_valid(v, vn)
                return Bound(T.TIMESTAMP, tafn)
            def dafn(cols, valids):
                d, v = a.fn(cols, valids)
                n, vn = nb.fn(cols, valids)
                out = F.date_add_days(u, n, d).astype(a.type.dtype)
                return out, merge_valid(v, vn)
            return Bound(a.type, dafn)
        if name == "date_diff":
            unit = e.args[0]
            assert isinstance(unit, Literal), "date_diff unit must be constant"
            u = unit.value.lower()
            a, b = args[1], args[2]
            def ddfn(cols, valids):
                da, va = a.fn(cols, valids)
                db, vb = b.fn(cols, valids)
                xa = self._to_days(a, da)
                xb = self._to_days(b, db)
                if u in _MICROS_PER_UNIT:
                    assert a.type.kind == T.TypeKind.TIMESTAMP
                    out = F.div_trunc(
                        db - da, _const(da, _MICROS_PER_UNIT[u], jnp.int64)
                    )
                else:
                    out = F.date_diff_days(u, xa, xb)
                return out.astype(jnp.int64), merge_valid(va, vb)
            return Bound(T.BIGINT, ddfn)
        if name == "last_day_of_month":
            a = args[0]
            def ldfn(cols, valids):
                d, v = a.fn(cols, valids)
                days = F.last_day_of_month_days(self._to_days(a, d))
                return days.astype(T.DATE.dtype), v
            return Bound(T.DATE, ldfn)
        if name == "array_length":
            # ArrayColumn/MapColumn.data IS the per-row lengths array
            a = args[0]
            def alfn(cols, valids):
                d, v = a.fn(cols, valids)
                if isinstance(d, Column):
                    d = d.data
                return d.astype(jnp.int64), v
            return Bound(T.BIGINT, alfn)
        if name in ("map_subscript", "array_subscript", "map_keys",
                    "map_values", "row_field", "row_pack"):
            return self._bind_nested_op(name, e, args)
        if name == "year_of_week":
            a = args[0]
            def yowfn(cols, valids):
                d, v = a.fn(cols, valids)
                return (
                    F.year_of_week(self._to_days(a, d)).astype(jnp.int64),
                    v,
                )
            return Bound(T.BIGINT, yowfn)
        bound = self._bind_registry_scalar(name, e, args)
        if bound is not None:
            return bound
        raise NotImplementedError(f"scalar function {name}")

    # -- registry-resolved breadth (expr/registry.py): hashing/encoding,
    # URL, JSON, string distances. All dictionary-wise: the python body
    # runs over |dict| values on host, codes remap on device (the
    # DictionaryAwarePageProjection discipline — per-row host work never
    # happens) --
    def _bind_registry_scalar(self, name, e, args):
        import base64 as _b64
        import hashlib as _hashlib
        import zlib as _zlib

        if name in ("md5", "sha1", "sha256", "sha512"):
            return self._bind_dict_transform(
                args[0], e,
                lambda s, algo=name: _hashlib.new(algo, s.encode()).hexdigest(),
            )
        if name in ("hmac_md5", "hmac_sha1", "hmac_sha256", "hmac_sha512"):
            import hmac as _hmac

            key = e.args[1]
            assert isinstance(key, Literal), f"{name}() key must be constant"
            if key.value is None:
                return self._null_of(args[0], e.type)
            algo = name[5:]
            return self._bind_dict_transform(
                args[0], e,
                lambda s, k=key.value, a=algo: _hmac.new(
                    k.encode(), s.encode(), a
                ).hexdigest(),
            )
        if name == "xxhash64":
            from trino_tpu.expr.pyfns import xxhash64 as _xx

            return self._bind_dict_transform(
                args[0], e, lambda s: format(_xx(s.encode()), "016x")
            )
        if name == "murmur3":
            from trino_tpu.expr.pyfns import murmur3_x64_128 as _mm

            return self._bind_dict_transform(
                args[0], e, lambda s: _mm(s.encode()).hex()
            )
        if name == "to_base32":
            return self._bind_dict_transform(
                args[0], e, lambda s: _b64.b32encode(s.encode()).decode()
            )
        if name == "from_base32":
            return self._bind_dict_transform_nullable(
                args[0], e, lambda s: _try_decode(
                    lambda: _b64.b32decode(s.encode())
                )
            )
        if name == "to_base64url":
            return self._bind_dict_transform(
                args[0], e,
                lambda s: _b64.urlsafe_b64encode(s.encode()).decode(),
            )
        if name == "from_base64url":
            return self._bind_dict_transform_nullable(
                args[0], e, lambda s: _try_decode(
                    lambda: _b64.urlsafe_b64decode(s.encode())
                )
            )
        if name in ("from_big_endian_32", "from_big_endian_64"):
            want = 4 if name.endswith("32") else 8

            def befn(s, want=want):
                b = s.encode()
                if len(b) != want:
                    return None  # the reference raises; NULL divergence
                v = int.from_bytes(b, "big", signed=True)
                return v

            return self._bind_dict_table_nullable(
                args[0], T.BIGINT, befn, jnp.int64
            )
        if name in ("from_ieee754_32", "from_ieee754_64"):
            import struct as _struct

            want, code = (4, ">f") if name.endswith("32") else (8, ">d")

            def ieeefn(s, want=want, code=code):
                b = s.encode()
                if len(b) != want:
                    return None
                return _struct.unpack(code, b)[0]

            return self._bind_dict_table_nullable(
                args[0], T.DOUBLE, ieeefn, jnp.float64
            )
        if name == "hll_cardinality":
            from trino_tpu.expr.pyfns import hll_cardinality

            return self._bind_dict_table_nullable(
                args[0], T.BIGINT, hll_cardinality, jnp.int64
            )
        if name in ("value_at_quantile", "quantile_at_value"):
            from trino_tpu.expr.pyfns import (
                tdigest_quantile_at_value, tdigest_value_at_quantile,
            )

            q = e.args[1]
            assert isinstance(q, Literal), f"{name}() argument must be constant"
            if q.value is None:
                return self._null_of(args[0], T.DOUBLE)
            # IR literals carry SQL values (scale_decimal_value is only
            # applied when materializing physical constants)
            qv = float(q.value)
            fn = (tdigest_value_at_quantile if name == "value_at_quantile"
                  else tdigest_quantile_at_value)
            return self._bind_dict_table_nullable(
                args[0], T.DOUBLE, lambda s, qv=qv, fn=fn: fn(s, qv),
                jnp.float64,
            )
        if name == "split_to_map":
            from trino_tpu.block import MapColumn

            a = args[0]
            for i in (1, 2):
                assert args[i].is_const, (
                    "split_to_map() delimiters must be constants"
                )
            ed, kd = str(args[1].const_value), str(args[2].const_value)
            values = a.dictionary.values if a.dictionary else []
            per_code = []
            for v in values:
                pairs = []
                for entry in (v.split(ed) if v else []):
                    if not entry:
                        continue
                    k, _, val = entry.partition(kd)
                    pairs.append((k, val))
                if len({k for k, _ in pairs}) != len(pairs):
                    # the reference RAISES when the offending row is
                    # evaluated; a bind-time raise would fail rows the
                    # query never touches, so malformed rows degrade to
                    # NULL instead (same class as the subscript
                    # deviation documented in the analyzer)
                    pairs = None
                per_code.append(pairs)
            W = max((len(p) for p in per_code if p is not None), default=1)
            key_dict = Dictionary(
                sorted({
                    k for ps in per_code if ps for k, _ in ps
                }) or [""]
            )
            val_dict = Dictionary(
                sorted({
                    v for ps in per_code if ps for _, v in ps
                }) or [""]
            )
            kt = np.zeros((max(len(values), 1), W), dtype=np.int32)
            vt = np.zeros((max(len(values), 1), W), dtype=np.int32)
            lens = np.zeros(max(len(values), 1), dtype=np.int32)
            okc = np.ones(max(len(values), 1), dtype=bool)
            for c, ps in enumerate(per_code):
                if ps is None:
                    okc[c] = False
                    continue
                lens[c] = len(ps)
                for j, (k, v) in enumerate(ps):
                    kt[c, j] = key_dict.code(k)
                    vt[c, j] = val_dict.code(v)
            kt_j, vt_j, lens_j, ok_j = map(
                jnp.asarray, (kt, vt, lens, okc)
            )
            out_t = e.type

            def smfn(cols, valids):
                d, v = a.fn(cols, valids)
                code = jnp.clip(d, 0, max(len(values) - 1, 0))
                rows = code.shape[0]
                row_ok = take_clip(ok_j, code)
                vv = row_ok if v is None else (v & row_ok)
                return (
                    MapColumn(
                        out_t, take_clip(lens_j, code), vv, None,
                        jnp.arange(rows, dtype=jnp.int32) * W,
                        Column(
                            T.VARCHAR,
                            jnp.take(kt_j, code, axis=0).reshape(-1),
                            None, key_dict,
                        ),
                        Column(
                            T.VARCHAR,
                            jnp.take(vt_j, code, axis=0).reshape(-1),
                            None, val_dict,
                        ),
                    ),
                    vv,
                )

            return Bound(out_t, smfn)
        if name == "values_at_quantiles":
            from trino_tpu.block import ArrayColumn
            from trino_tpu.expr.pyfns import tdigest_value_at_quantile

            a = args[0]
            qs = e.args[1]
            assert isinstance(qs, Literal), (
                "values_at_quantiles() fractions must be a constant array"
            )
            fracs = [float(x) for x in (qs.value or ())]
            values = a.dictionary.values if a.dictionary else []
            W = max(len(fracs), 1)
            table = np.zeros((max(len(values), 1), W), dtype=np.float64)
            okm = np.zeros((max(len(values), 1), W), dtype=bool)
            for c, dv in enumerate(values):
                for j, q in enumerate(fracs):
                    rv = tdigest_value_at_quantile(dv, q)
                    if rv is not None:
                        table[c, j] = rv
                        okm[c, j] = True
            table_j = jnp.asarray(table)
            ok_j = jnp.asarray(okm)
            out_t = e.type

            def vqfn(cols, valids):
                d, v = a.fn(cols, valids)
                code = jnp.clip(d, 0, max(len(values) - 1, 0))
                rows = code.shape[0]
                flat = Column(
                    T.DOUBLE,
                    jnp.take(table_j, code, axis=0).reshape(-1),
                    jnp.take(ok_j, code, axis=0).reshape(-1),
                    None,
                )
                return (
                    ArrayColumn(
                        out_t,
                        jnp.full(rows, len(fracs), dtype=jnp.int32),
                        v, None,
                        jnp.arange(rows, dtype=jnp.int32) * W, flat,
                    ),
                    v,
                )

            return Bound(out_t, vqfn)
        if name == "checksum_hash":
            # internal: per-row 62-bit value hash for checksum() — NULL
            # hashes to a constant lane (never NULL itself) so the
            # summing primitive includes every row, like the reference's
            # ChecksumAggregationFunction hashing null positions
            from trino_tpu.ops import hashing as H

            a = args[0]
            lut = H.dictionary_lut(getattr(a, "dictionary", None))

            def ckfn(cols, valids, a=a, lut=lut):
                d, v = a.fn(cols, valids)
                if lut is not None:
                    d = H.canonical_hash_input(d, jnp.asarray(lut))
                return H.hash64([d], [v]), None

            return Bound(T.BIGINT, ckfn)
        if name == "luhn_check":
            def luhn(s):
                if not s or not s.isdigit():
                    return False
                total = 0
                for i, ch in enumerate(reversed(s)):
                    d = ord(ch) - 48
                    if i % 2 == 1:
                        d *= 2
                        if d > 9:
                            d -= 9
                    total += d
                return total % 10 == 0

            return self._bind_dict_table(
                args[0], T.BOOLEAN, luhn, jnp.bool_
            )
        if name in ("strrpos", "index"):
            sub = e.args[1]
            assert isinstance(sub, Literal), (
                f"{name}() substring must be constant"
            )
            if sub.value is None:
                return self._null_of(args[0], T.BIGINT)
            finder = (
                (lambda s, t=sub.value: s.rfind(t) + 1)
                if name == "strrpos"
                else (lambda s, t=sub.value: s.find(t) + 1)
            )
            return self._bind_dict_table(
                args[0], T.BIGINT, finder, jnp.int64
            )
        if name in ("to_utf8", "from_utf8"):
            # the engine's varbinary carrier IS utf-8-decoded varchar, so
            # both directions normalize through encode/decode (invalid
            # sequences cannot occur on the carrier; from_utf8's
            # replacement contract is preserved by construction)
            return self._bind_dict_transform(
                args[0], e,
                lambda s: s.encode("utf-8").decode("utf-8", "replace"),
            )
        if name == "word_stem":
            from trino_tpu.expr.pyfns import porter_stem

            return self._bind_dict_transform(args[0], e, porter_stem)
        if name == "char2hexint":
            return self._bind_dict_transform(
                args[0], e,
                lambda s: s.encode("utf-16-be").hex().upper(),
            )
        if name == "from_base":
            radix = e.args[1]
            assert isinstance(radix, Literal), "from_base() radix must be constant"
            if radix.value is None:
                return self._null_of(args[0], T.BIGINT)
            r = int(radix.value)
            if not 2 <= r <= 36:
                raise ValueError("from_base() radix must be in [2, 36]")

            def fb(s, r=r):
                try:
                    return int(s, r)
                except ValueError:
                    return None  # the reference raises; NULL divergence

            return self._bind_dict_table_nullable(
                args[0], T.BIGINT, fb, jnp.int64
            )
        if name in ("from_iso8601_timestamp", "from_iso8601_timestamp_nanos"):
            from trino_tpu.expr.pyfns import iso_to_micros

            trim = name.endswith("nanos")
            return self._bind_dict_table_nullable(
                args[0], T.TIMESTAMP,
                lambda s, trim=trim: iso_to_micros(s, trim_nanos=trim),
                jnp.int64,
            )
        if name in ("parse_datetime", "to_timestamp", "to_date"):
            import datetime as _dt

            from trino_tpu.expr.pyfns import (
                dt_to_micros, joda_to_strptime, oracle_to_strptime,
            )

            fmt = e.args[1]
            assert isinstance(fmt, Literal), f"{name}() format must be constant"
            if fmt.value is None:
                return self._null_of(
                    args[0], T.DATE if name == "to_date" else T.TIMESTAMP
                )
            py = (joda_to_strptime(fmt.value) if name == "parse_datetime"
                  else oracle_to_strptime(fmt.value))

            def pdfn(s, py=py):
                try:
                    dt = _dt.datetime.strptime(s, py)
                except ValueError:
                    return None  # the reference raises; NULL divergence
                if name == "to_date":
                    return (dt.date() - _dt.date(1970, 1, 1)).days
                return dt_to_micros(dt)

            if name == "to_date":
                return self._bind_dict_table_nullable(
                    args[0], T.DATE, pdfn, T.DATE.dtype
                )
            return self._bind_dict_table_nullable(
                args[0], T.TIMESTAMP, pdfn, jnp.int64
            )
        if name == "crc32":
            return self._bind_dict_table(
                args[0], T.BIGINT,
                lambda s: _zlib.crc32(s.encode()), jnp.int64,
            )
        if name == "to_hex":
            return self._bind_dict_transform(
                args[0], e, lambda s: s.encode().hex().upper()
            )
        if name == "from_hex":
            return self._bind_dict_transform(
                args[0], e,
                lambda s: bytes.fromhex(s).decode("utf-8", "replace"),
            )
        if name == "to_base64":
            return self._bind_dict_transform(
                args[0], e, lambda s: _b64.b64encode(s.encode()).decode()
            )
        if name == "from_base64":
            return self._bind_dict_transform(
                args[0], e,
                lambda s: _b64.b64decode(s.encode()).decode("utf-8", "replace"),
            )
        if name in ("levenshtein_distance", "hamming_distance"):
            other = e.args[1]
            assert isinstance(other, Literal), (
                f"{name}() second argument must be a constant"
            )
            t = other.value

            def _lev(s, t=t):
                if len(s) < len(t):
                    s, t = t, s
                prev = list(range(len(t) + 1))
                for i, cs in enumerate(s):
                    cur = [i + 1]
                    for j, ct in enumerate(t):
                        cur.append(min(
                            prev[j + 1] + 1, cur[j] + 1,
                            prev[j] + (cs != ct),
                        ))
                    prev = cur
                return prev[-1]

            def _ham(s, t=t):
                # dict-table evaluation covers every table-stable
                # dictionary value, including ones the query never
                # selects — a mismatched length must not fail the whole
                # bind (the reference raises per-ROW). NULL for those
                # entries; rows that actually select them get NULL
                # instead of Trino's error (documented divergence).
                if len(s) != len(t):
                    return None
                return sum(a != b for a, b in zip(s, t))

            if name == "hamming_distance":
                return self._bind_dict_table_nullable(
                    args[0], T.BIGINT, _ham, jnp.int64
                )
            return self._bind_dict_table(args[0], T.BIGINT, _lev, jnp.int64)
        if name.startswith("url_"):
            return self._bind_url_fn(name, e, args)
        if name in ("json_extract_scalar", "json_array_length", "json_size"):
            return self._bind_json_fn(name, e, args)
        if name == "from_iso8601_date":
            import datetime as _dt

            return self._bind_dict_table(
                args[0], T.DATE,
                lambda s: (_dt.date.fromisoformat(s)
                           - _dt.date(1970, 1, 1)).days,
                T.DATE.dtype,
            )
        return None

    def _bind_url_fn(self, name, e, args):
        from urllib.parse import quote, unquote, urlsplit

        if name == "url_encode":
            return self._bind_dict_transform(
                args[0], e, lambda s: quote(s, safe="")
            )
        if name == "url_decode":
            return self._bind_dict_transform(args[0], e, unquote)

        def part(s, name=name):
            try:
                u = urlsplit(s)
                if name == "url_extract_port":
                    return u.port  # raises ValueError on ':abc' ports
            except ValueError:
                return None
            if name == "url_extract_protocol":
                return u.scheme or None
            if name == "url_extract_host":
                return u.hostname
            if name == "url_extract_path":
                return u.path
            if name == "url_extract_query":
                return u.query if "?" in s else None
            if name == "url_extract_fragment":
                return u.fragment if "#" in s else None
            return None

        if name == "url_extract_port":
            return self._bind_dict_table_nullable(
                args[0], T.BIGINT, part, jnp.int64
            )
        if name == "url_extract_parameter":
            from urllib.parse import parse_qs

            plit = e.args[1]
            assert isinstance(plit, Literal), (
                "url_extract_parameter() name must be a constant"
            )

            def param(s, p=plit.value):
                try:
                    vals = parse_qs(
                        urlsplit(s).query, keep_blank_values=True
                    ).get(p)
                except ValueError:
                    return None
                return vals[0] if vals else None

            return self._bind_dict_transform_nullable(args[0], e, param)
        return self._bind_dict_transform_nullable(args[0], e, part)

    def _bind_json_fn(self, name, e, args):
        import json as _json

        def nav(s, path, keep_tokens=False):
            """$.a.b[0] JSONPath subset over parsed JSON; None on any
            miss (JsonFunctions' lenient semantics). keep_tokens parses
            numbers as their literal text so 7.0 renders '7.0' exactly
            as the document wrote it (Trino emits the parser token)."""
            try:
                if keep_tokens:
                    v = _json.loads(s, parse_float=str, parse_int=str)
                else:
                    v = _json.loads(s)
            except (ValueError, TypeError):
                return _MISS
            if not path.startswith("$"):
                return _MISS
            i = 1
            while i < len(path):
                if path[i] == ".":
                    j = i + 1
                    while j < len(path) and path[j] not in ".[":
                        j += 1
                    key = path[i + 1:j]
                    if not isinstance(v, dict) or key not in v:
                        return _MISS
                    v = v[key]
                    i = j
                elif path[i] == "[":
                    j = path.index("]", i)
                    try:
                        idx = int(path[i + 1:j])
                    except ValueError:
                        return _MISS
                    if not isinstance(v, list) or not (
                        -len(v) <= idx < len(v)
                    ):
                        return _MISS
                    v = v[idx]
                    i = j + 1
                else:
                    return _MISS
            return v

        _MISS = object()
        if name == "json_array_length":
            def jal(s):
                try:
                    v = _json.loads(s)
                except (ValueError, TypeError):
                    return None
                return len(v) if isinstance(v, list) else None

            return self._bind_dict_table_nullable(
                args[0], T.BIGINT, jal, jnp.int64
            )
        plit = e.args[1]
        assert isinstance(plit, Literal), (
            f"{name}() path must be a constant"
        )
        path = plit.value
        if name == "json_size":
            def jsz(s, path=path):
                v = nav(s, path)
                if v is _MISS:
                    return None
                return len(v) if isinstance(v, (dict, list)) else 0

            return self._bind_dict_table_nullable(
                args[0], T.BIGINT, jsz, jnp.int64
            )

        def jes(s, path=path):
            v = nav(s, path, keep_tokens=True)
            if v is _MISS or v is None or isinstance(v, (dict, list)):
                return None
            if isinstance(v, bool):
                return "true" if v else "false"
            return str(v)  # numbers are their literal tokens (parse hooks)

        return self._bind_dict_transform_nullable(args[0], e, jes)

    def _bind_json_breadth(self, name, e, args):
        """The wider JSON family (JsonFunctions.java): json_extract
        (JSON text out), json_format/json_parse, is_json_scalar,
        json_array_contains/json_array_get."""
        import json as _json

        if name == "is_json_scalar":
            def ijs(s):
                try:
                    v = _json.loads(s)
                except (ValueError, TypeError):
                    return None
                return not isinstance(v, (dict, list))

            return self._bind_dict_table_nullable(
                args[0], T.BOOLEAN, ijs, jnp.bool_
            )
        if name in ("json_format", "json_parse"):
            # both canonicalize the document text (we carry JSON as its
            # text form; invalid input -> NULL for parse, error-free)
            def jfmt(s):
                try:
                    return _json.dumps(
                        _json.loads(s), separators=(",", ":")
                    )
                except (ValueError, TypeError):
                    return None

            return self._bind_dict_transform_nullable(args[0], e, jfmt)
        if name == "json_array_contains":
            val = e.args[1]
            assert isinstance(val, Literal), (
                "json_array_contains() value must be a constant"
            )
            want = val.value

            def jac(s, want=want):
                try:
                    v = _json.loads(s)
                except (ValueError, TypeError):
                    return None
                if not isinstance(v, list):
                    return None
                return any(
                    type(x) is type(want) and x == want
                    if isinstance(want, bool)
                    else (not isinstance(x, bool) and x == want)
                    for x in v
                )

            return self._bind_dict_table_nullable(
                args[0], T.BOOLEAN, jac, jnp.bool_
            )
        if name == "json_array_get":
            idx = e.args[1]
            assert isinstance(idx, Literal), (
                "json_array_get() index must be a constant"
            )
            i = int(idx.value)

            def jag(s, i=i):
                try:
                    v = _json.loads(s)
                except (ValueError, TypeError):
                    return None
                if not isinstance(v, list) or not (-len(v) <= i < len(v)):
                    return None
                out = v[i]
                return _json.dumps(out, separators=(",", ":"))

            return self._bind_dict_transform_nullable(args[0], e, jag)
        # json_extract: path navigation returning the JSON TEXT of the
        # matched node (json_extract_scalar returns only scalars)
        plit = e.args[1]
        assert isinstance(plit, Literal), "json_extract() path must be constant"
        path = plit.value

        def jex(s, path=path):
            try:
                v = _json.loads(s)
            except (ValueError, TypeError):
                return None
            if not path.startswith("$"):
                return None
            i = 1
            while i < len(path):
                if path[i] == ".":
                    j = i + 1
                    while j < len(path) and path[j] not in ".[":
                        j += 1
                    key = path[i + 1:j]
                    if not isinstance(v, dict) or key not in v:
                        return None
                    v = v[key]
                    i = j
                elif path[i] == "[":
                    j = path.index("]", i)
                    try:
                        idx2 = int(path[i + 1:j])
                    except ValueError:
                        return None
                    if not isinstance(v, list) or not (
                        -len(v) <= idx2 < len(v)
                    ):
                        return None
                    v = v[idx2]
                    i = j + 1
                else:
                    return None
            return _json.dumps(v, separators=(",", ":"))

        return self._bind_dict_transform_nullable(args[0], e, jex)

    def _bind_dict_transform_nullable(self, a: Bound, e, pyfn) -> Bound:
        """Like _bind_dict_transform but pyfn may return None -> NULL:
        validity is a second per-code table ANDed into the mask."""
        from trino_tpu.block import Dictionary

        if a.dictionary is None or len(a.dictionary) == 0:
            return self._null_of(a, e.type)
        transformed = [pyfn(v) for v in a.dictionary.values]
        new_dict = Dictionary([t if t is not None else "" for t in transformed])
        remap = jnp.asarray(
            [new_dict.code(t if t is not None else "") for t in transformed],
            dtype=jnp.int32,
        )
        valid_tbl = jnp.asarray(
            [t is not None for t in transformed], dtype=jnp.bool_
        )

        def fn(cols, valids):
            d, v = a.fn(cols, valids)
            ok = take_clip(valid_tbl, d)
            return take_clip(remap, d), ok if v is None else (v & ok)

        return Bound(e.type, fn, new_dict)

    def _bind_nested_op(self, name: str, e, args) -> Bound:
        """MAP/ROW/ARRAY navigation (MethodHandle operators on
        MapType/RowType/ArrayType in the reference — MapSubscript,
        RowFieldReference, spi/block accessors). Inputs arrive as whole
        Column objects through the cols list; results are either plain
        (data, valid) pairs (subscript, row_field) or full Columns
        (map_keys/map_values/row_pack — nested outputs)."""
        from trino_tpu.block import ArrayColumn, MapColumn, RowColumn

        out_t = e.type

        if name == "row_pack":
            kids = list(args)

            def packfn(cols, valids, kids=kids, out_t=out_t):
                built = []
                for b in kids:
                    d, v = b.fn(cols, valids)
                    if isinstance(d, Column):
                        built.append(d)
                    else:
                        built.append(Column(b.type, d, v, b.dictionary))
                ref = built[0].data if built else jnp.zeros(1)
                presence = jnp.ones(ref.shape[0], jnp.int8)
                return RowColumn(out_t, presence, None, None, built), None

            return Bound(out_t, packfn)

        a = args[0]

        if name == "row_field":
            fi = int(args[1].const_value)
            # string/nested fields return the child COLUMN whole (its
            # runtime dictionary / starts+flat are batch data that a
            # bare (data, valid) pair would drop); plain scalars return
            # the pair so arithmetic composes
            as_column = out_t.is_string or out_t.is_nested

            def rffn(cols, valids, a=a, fi=fi, as_column=as_column):
                d, v = a.fn(cols, valids)
                child = d.children[fi]
                cv = child.valid
                pv = d.valid if v is None else v
                merged = (
                    pv if cv is None else (cv if pv is None else (cv & pv))
                )
                if as_column:
                    return child.with_data(child.data, merged), None
                return child.data, merged

            return Bound(out_t, rffn)

        if name in ("map_keys", "map_values"):
            def mkfn(cols, valids, a=a, name=name, out_t=out_t):
                d, v = a.fn(cols, valids)
                flat = d.flat_keys if name == "map_keys" else d.flat_values
                valid = d.valid if v is None else v
                return (
                    ArrayColumn(out_t, d.data, valid, None, d.starts, flat),
                    None,
                )
            return Bound(out_t, mkfn)

        # subscripts: array access is one gather; map access is a
        # bounded vectorized scan over each row's entry slice
        # (lax.while_loop with a device-dependent trip count =
        # max entry count — compile-safe; see the groupby scan NOTE)
        k = args[1]

        if name == "array_subscript":
            def asfn(cols, valids, a=a, k=k, out_t=out_t):
                d, v = a.fn(cols, valids)
                kd, kv = k.fn(cols, valids)
                lengths = d.data
                starts = d.starts
                flat = d.flat
                F = flat.data.shape[0]
                idx = kd.astype(jnp.int64)
                # 1-based; negative counts from the end (element_at)
                eff = jnp.where(idx > 0, idx - 1, lengths.astype(jnp.int64) + idx)
                ok = (eff >= 0) & (eff < lengths.astype(jnp.int64))
                pos = jnp.clip(
                    starts.astype(jnp.int64) + jnp.where(ok, eff, 0), 0,
                    max(F - 1, 0),
                )
                valid = ok
                if d.valid is not None:
                    valid = valid & d.valid
                if v is not None:
                    valid = valid & v
                if kv is not None:
                    valid = valid & kv
                # gather through the CHILD column: preserves nested
                # layouts (array(array(...)) elements) and dictionaries
                out_col = flat.gather(pos.astype(jnp.int32), valid)
                if out_t.is_nested or out_t.is_string:
                    return out_col, None
                return out_col.data, out_col.valid

            return Bound(out_t, asfn)

        assert name == "map_subscript"

        def msfn(cols, valids, a=a, k=k, out_t=out_t):
            d, v = a.fn(cols, valids)
            lengths = d.data.astype(jnp.int32)
            starts = d.starts
            fk, fv = d.flat_keys, d.flat_values
            F = fk.data.shape[0]
            kd, kv = k.fn(cols, valids)
            kdict = k.dictionary
            if isinstance(kd, Column):
                # whole-Column key (e.g. a row_field string): its
                # RUNTIME dictionary is static pytree aux at trace time
                if kd.dictionary is not None:
                    kdict = kd.dictionary
                if kv is None:
                    kv = kd.valid
                kd = kd.data
            if fk.dictionary is not None and k.is_const:
                # constant string key: encode through the flat-key
                # dictionary (static pytree aux — folds at trace time)
                code = fk.dictionary._index.get(k.const_value, -1)
                target = jnp.full(lengths.shape, code, jnp.int32)
            elif fk.dictionary is not None and kdict is not None:
                # vectorized string key: remap key codes into the
                # flat-key dictionary (both static at trace time)
                remap = jnp.asarray(
                    [
                        fk.dictionary._index.get(val, -1)
                        for val in kdict.values
                    ],
                    jnp.int32,
                )
                target = jnp.take(remap, jnp.clip(kd, 0, len(kdict) - 1))
            elif fk.dictionary is not None:
                # a string key whose dictionary is unknown at trace time
                # would compare codes across DIFFERENT dictionaries —
                # silently wrong matches; fail loudly instead
                raise NotImplementedError(
                    "map subscript with a computed string key (no"
                    " plan-time dictionary) is not supported; use a"
                    " constant key or a string column"
                )
            else:
                target = kd.astype(fk.data.dtype)

            # the loop tracks the matching entry POSITION; the value is
            # gathered through the child column afterwards, which keeps
            # nested value types (map(k, array(...))) structurally whole
            def cond(state):
                i, found, pos = state
                return i < jnp.max(lengths)

            def body(state):
                i, found, pos = state
                active = i < lengths
                slot = jnp.clip(starts + i, 0, max(F - 1, 0))
                key_here = jnp.take(fk.data, slot)
                kok = (
                    jnp.take(fk.valid, slot)
                    if fk.valid is not None
                    else jnp.ones_like(active)
                )
                hit = active & kok & (key_here == target) & ~found
                return (
                    i + 1,
                    found | hit,
                    jnp.where(hit, slot, pos),
                )

            n = lengths.shape[0]
            init = (
                jnp.int32(0),
                jnp.zeros(n, jnp.bool_),
                jnp.zeros(n, jnp.int32),
            )
            _, found, pos = jax.lax.while_loop(cond, body, init)
            valid = found
            if d.valid is not None:
                valid = valid & d.valid
            if v is not None:
                valid = valid & v
            if kv is not None:
                valid = valid & kv
            out_col = fv.gather(pos, valid)
            if out_t.is_nested or out_t.is_string:
                return out_col, None
            return out_col.data, out_col.valid

        return Bound(out_t, msfn)

    def _bind_dict_table_nullable(self, a: Bound, out_type, pyfn, dtype) -> Bound:
        """Like _bind_dict_table but pyfn may return None -> NULL."""
        if a.dictionary is None or len(a.dictionary) == 0:
            return self._null_of(a, out_type)
        results = [pyfn(v) for v in a.dictionary.values]
        table = jnp.asarray(
            [r if r is not None else 0 for r in results], dtype=dtype
        )
        valid_tbl = jnp.asarray(
            [r is not None for r in results], dtype=jnp.bool_
        )

        def fn(cols, valids):
            d, v = a.fn(cols, valids)
            ok = take_clip(valid_tbl, d)
            return take_clip(table, d), ok if v is None else (v & ok)

        return Bound(out_type, fn)

    @staticmethod
    def _to_days(a: Bound, data: jnp.ndarray) -> jnp.ndarray:
        """DATE (epoch days) or TIMESTAMP (epoch micros) -> epoch days."""
        if a.type.kind == T.TypeKind.TIMESTAMP:
            return data // (86400 * 1000 * 1000)
        return data

    def _bind_dict_table(self, a: Bound, out_type: T.DataType, pyfn, dtype) -> Bound:
        """Non-string-valued function of a dictionary column: evaluate
        over |dict| values on host, take() the result table on device."""
        if a.dictionary is None or len(a.dictionary) == 0:
            return self._null_of(a, out_type)
        table = jnp.asarray(
            [pyfn(v) for v in a.dictionary.values], dtype=dtype
        )
        def fn(cols, valids):
            d, v = a.fn(cols, valids)
            return take_clip(table, d), v
        return Bound(out_type, fn)

    @staticmethod
    def _py_substr(s: str, start_lit: Expr, len_lit: Optional[Expr]) -> str:
        """Trino substr: 1-based; negative start counts from the end;
        start of 0 yields empty (StringFunctions.substr)."""
        start = int(start_lit.value)
        n = int(len_lit.value) if len_lit is not None else None
        if start == 0:
            return ""
        begin = start - 1 if start > 0 else max(len(s) + start, 0)
        if start < 0 and len(s) + start < 0:
            return ""
        end = len(s) if n is None else begin + max(n, 0)
        return s[begin:end]

    def _null_of(self, ref: Bound, out_type: T.DataType) -> Bound:
        from trino_tpu.block import RuntimeDictionary

        if isinstance(ref.dictionary, RuntimeDictionary):
            raise NotImplementedError(
                "expressions over runtime-dictionary strings (listagg"
                " output) are not supported yet — materialize the"
                " aggregate first (e.g. CTAS) and operate on the table"
            )
        def fn(cols, valids, rfn=ref.fn):
            d, _ = rfn(cols, valids)
            return _const(d, 0, out_type.dtype), _const(d, False, jnp.bool_)
        return Bound(out_type, fn, Dictionary([]) if out_type.is_string else None)

    def _bind_dict_transform(self, a: Bound, e: Call, pyfn) -> Bound:
        """String function on a dictionary column: transform |dict| values
        on host, remap codes on device (DictionaryAwarePageProjection
        analogue — main/operator/project/DictionaryAwarePageProjection.java)."""
        if a.dictionary is None or len(a.dictionary) == 0:  # NULL-only input
            return self._null_of(a, e.type)
        src = a.dictionary
        transformed = [pyfn(v) for v in src.values]
        new_dict = Dictionary(transformed)
        remap = jnp.asarray([new_dict.code(t) for t in transformed], dtype=jnp.int32)
        def fn(cols, valids):
            d, v = a.fn(cols, valids)
            return take_clip(remap, d), v
        return Bound(e.type, fn, new_dict)

    def _bind_concat(self, e: Call, args) -> Bound:
        """String concatenation on dictionary columns. Constant operands
        fold into a dictionary transform; two dictionary operands build
        the pairwise dictionary (bounded) with codes ca*|B|+cb."""
        if len(args) > 2:
            # left-fold longer chains into pairwise concats
            acc = args[0]
            for i in range(1, len(args)):
                pair = Call("concat", (e.args[0], e.args[i]), T.VARCHAR)
                acc = self._bind_concat(pair, [acc, args[i]])
            return acc
        a, b = args
        if b.is_const:
            suffix = b.const_value or ""
            return self._bind_dict_transform(a, e, lambda s: s + suffix)
        if a.is_const:
            prefix = a.const_value or ""
            return self._bind_dict_transform(b, e, lambda s: prefix + s)
        if a.dictionary is None or b.dictionary is None:
            return self._null_of(a, T.VARCHAR)
        da, db = a.dictionary, b.dictionary
        if len(da) * len(db) > 1 << 18:
            raise NotImplementedError(
                "concat of two high-cardinality string columns"
            )
        pairs = [x + y for x in da.values for y in db.values]
        new_dict = Dictionary(pairs)
        remap = jnp.asarray(
            [new_dict.code(p) for p in pairs], dtype=jnp.int32
        ).reshape(len(da), len(db))
        def fn(cols, valids):
            dca, va = a.fn(cols, valids)
            dcb, vb = b.fn(cols, valids)
            ca = jnp.clip(dca, 0, len(da) - 1)
            cb = jnp.clip(dcb, 0, len(db) - 1)
            out = remap[ca, cb]
            v = va
            if vb is not None:
                v = vb if v is None else (v & vb)
            return out, v
        return Bound(T.VARCHAR, fn, new_dict)

    def _bind_like(self, e: Call, args) -> Bound:
        a = args[0]
        if a.dictionary is None or len(a.dictionary) == 0:
            return self._null_of(a, T.BOOLEAN)
        pattern = e.args[1]
        assert isinstance(pattern, Literal), "LIKE pattern must be constant"
        escape = e.args[2].value if len(e.args) > 2 else None
        table = jnp.asarray(F.dictionary_like_table(a.dictionary, pattern.value, escape))
        def fn(cols, valids):
            d, v = a.fn(cols, valids)
            return take_clip(table, d), v
        return Bound(T.BOOLEAN, fn)

    def _bind_coalesce(self, e: Call, args) -> Bound:
        out_dict = None
        if e.type.is_string:
            merged = None
            for a in args:
                if a.dictionary is not None:
                    merged = a.dictionary if merged is None else Dictionary.unify(merged, a.dictionary)[0]
            out_dict = merged
            args = [self._remap_to(a, out_dict) for a in args]
        def fn(cols, valids):
            data, valid = args[-1].fn(cols, valids)
            data = data.astype(e.type.dtype)
            # fold right-to-left: an earlier argument overrides wherever
            # it is valid, so the first valid argument wins per row
            for a in reversed(args[:-1]):
                d, v = a.fn(cols, valids)
                if v is None:  # all-valid argument shadows everything after it
                    data, valid = d.astype(e.type.dtype), None
                    continue
                data = jnp.where(v, d.astype(e.type.dtype), data)
                vv = valid if valid is not None else _const(d, True, jnp.bool_)
                valid = v | vv
            return data, valid
        return Bound(e.type, fn, out_dict)

    # ---- 3VL and/or ----
    def _bind_logical(self, e: Call) -> Bound:
        args = [self.bind(a) for a in e.args]
        is_and = e.name == "and"
        def fn(cols, valids):
            datas, vals = [], []
            for a in args:
                d, v = a.fn(cols, valids)
                datas.append(d)
                vals.append(v)
            if is_and:
                # value: false dominates; nulls treated true for the value lane
                data = None
                for d, v in zip(datas, vals):
                    lane = d if v is None else (d | ~v)
                    data = lane if data is None else (data & lane)
                # valid: all valid, or some valid-false forces definite false
                valid = merge_valid(*vals)
                if valid is not None:
                    for d, v in zip(datas, vals):
                        definite_false = (~d) if v is None else (v & ~d)
                        valid = valid | definite_false
            else:
                data = None
                for d, v in zip(datas, vals):
                    lane = d if v is None else (d & v)  # null -> false lane
                    data = lane if data is None else (data | lane)
                valid = merge_valid(*vals)
                if valid is not None:
                    for d, v in zip(datas, vals):
                        definite_true = d if v is None else (v & d)
                        valid = valid | definite_true
            return data, valid
        return Bound(T.BOOLEAN, fn)

    def _bind_decimal128_comparison(self, op: str, a: Bound, b: Bound) -> Bound:
        """Compare with at least one Int128-carried decimal: lift both
        sides to limb pairs at the common scale and compare
        lexicographically."""
        sa = a.type.scale or 0 if a.type.is_decimal else 0
        sb = b.type.scale or 0 if b.type.is_decimal else 0
        sc = max(sa, sb)
        at, bt = a.type, b.type

        def fn(cols, valids):
            ad, av = a.fn(cols, valids)
            bd, bv = b.fn(cols, valids)
            ah, al = _lift128(ad, at)
            bh, bl = _lift128(bd, bt)
            # scale unification can wrap mod 2^128 at extreme
            # value x scale-gap combinations; those rows fall back to
            # an approximate float64 comparison (documented corner)
            wrap = jnp.zeros(ah.shape, jnp.bool_)

            def lim(k):
                return tuple(
                    jnp.int64(x)
                    for x in I128.from_python((2 ** 127 - 1) // 10 ** k)
                )

            if sa < sc:
                lh, ll = lim(sc - sa)
                xh, xl = I128.abs_(ah, al)
                wrap = wrap | ~I128.lt(xh, xl, lh, ll)
                ah, al = I128.rescale_up(ah, al, sc - sa)
            if sb < sc:
                lh, ll = lim(sc - sb)
                xh, xl = I128.abs_(bh, bl)
                wrap = wrap | ~I128.lt(xh, xl, lh, ll)
                bh, bl = I128.rescale_up(bh, bl, sc - sb)
            eqv = I128.eq(ah, al, bh, bl)
            ltv = I128.lt(ah, al, bh, bl)
            fa = _f64_of_decimal(ad, at) if at.is_decimal else ad.astype(jnp.float64)
            fb = _f64_of_decimal(bd, bt) if bt.is_decimal else bd.astype(jnp.float64)
            eqv = jnp.where(wrap, fa == fb, eqv)
            ltv = jnp.where(wrap, fa < fb, ltv)
            out = {
                "eq": eqv, "ne": ~eqv, "lt": ltv, "le": ltv | eqv,
                "gt": ~(ltv | eqv), "ge": ~ltv,
            }[op]
            return out, merge_valid(av, bv)

        return Bound(T.BOOLEAN, fn)

    # ---- comparisons ----
    def _bind_comparison(self, op: str, args) -> Bound:
        a, b = args
        if a.type.is_string or b.type.is_string:
            return self._bind_string_comparison(op, a, b)
        if a.type.kind == T.TypeKind.TIMESTAMP_TZ and a.type == b.type:
            # tstz compares by INSTANT only — two values naming one
            # instant in different zones are equal (DateTimes.java;
            # the packed zone bits must not tie-break equality)
            def strip(x: Bound) -> Bound:
                def sfn(cols, valids, xfn=x.fn):
                    d, v = xfn(cols, valids)
                    return d >> jnp.int64(12), v
                return Bound(T.BIGINT, sfn)
            a, b = strip(a), strip(b)
        # decimal: rescale BOTH sides (incl. a bare-integer side) to the
        # common scale so scaled int64 compares against scaled int64;
        # a long-decimal side switches the whole compare to Int128 limbs
        if (a.type.is_long_decimal or b.type.is_long_decimal) and not (
            a.type.is_floating or b.type.is_floating
        ):
            return self._bind_decimal128_comparison(op, a, b)
        if a.type.is_decimal or b.type.is_decimal:
            if a.type.is_floating or b.type.is_floating:
                # mixed decimal/double: bring the decimal side (short
                # or Int128) to double BEFORE any rescale — a detour
                # through decimal(18) would overflow large values
                def to_double(x: Bound) -> Bound:
                    if not x.type.is_decimal:
                        return x
                    xt = x.type
                    def dn(cols, valids, xfn=x.fn, xt=xt):
                        d, v = xfn(cols, valids)
                        return _f64_of_decimal(d, xt), v
                    return Bound(T.DOUBLE, dn)
                a, b = to_double(a), to_double(b)
            else:
                sc = max(a.type.scale or 0 if a.type.is_decimal else 0,
                         b.type.scale or 0 if b.type.is_decimal else 0)
                def to_scale(x: Bound) -> Bound:
                    if x.type.is_decimal:
                        return self._rescaled(x, x.type.scale or 0, sc, T.decimal(18, sc))
                    if x.type.is_integerlike:
                        m = 10 ** sc
                        def up(cols, valids, xfn=x.fn):
                            d, v = xfn(cols, valids)
                            return d.astype(jnp.int64) * m, v
                        return Bound(T.decimal(18, sc), up)
                    return x
                a, b = to_scale(a), to_scale(b)
        jf = {
            "eq": lambda x, y: x == y, "ne": lambda x, y: x != y,
            "lt": lambda x, y: x < y, "le": lambda x, y: x <= y,
            "gt": lambda x, y: x > y, "ge": lambda x, y: x >= y,
        }[op]
        def fn(cols, valids):
            ad, av = a.fn(cols, valids)
            bd, bv = b.fn(cols, valids)
            if ad.dtype != bd.dtype:
                ct = jnp.promote_types(ad.dtype, bd.dtype)
                ad, bd = ad.astype(ct), bd.astype(ct)
            return jf(ad, bd), merge_valid(av, bv)
        return Bound(T.BOOLEAN, fn)

    def _bind_string_comparison(self, op: str, a: Bound, b: Bound) -> Bound:
        """String comparison on dictionary codes. Because dictionaries are
        sorted, code order == lexical order within one dictionary; a
        constant compares via its bisect position even when absent."""
        from trino_tpu.block import RuntimeDictionary

        if isinstance(a.dictionary, RuntimeDictionary) or isinstance(
            b.dictionary, RuntimeDictionary
        ):
            # plan-time string ops cannot know an execution-time
            # dictionary (listagg output) — fail loudly HERE rather than
            # rely on _null_of's internal guard; falling through would
            # compare raw codes across dictionaries and return wrong rows
            raise NotImplementedError(
                "string comparison over an execution-time dictionary "
                "(listagg output) is not supported"
            )
        jf = {
            "eq": lambda x, y: x == y, "ne": lambda x, y: x != y,
            "lt": lambda x, y: x < y, "le": lambda x, y: x <= y,
            "gt": lambda x, y: x > y, "ge": lambda x, y: x >= y,
        }
        flip = {"lt": "gt", "gt": "lt", "le": "ge", "ge": "le", "eq": "eq", "ne": "ne"}

        for lit, col, effective in ((b, a, op), (a, b, flip[op])):
            if not lit.is_const or col.is_const or col.dictionary is None:
                continue
            v = lit.const_value
            d = col.dictionary
            code = d.code(v)
            if code >= 0:  # present: direct code comparison
                cmpfn = jf[effective]
                def pfn(cols, valids, colb=col, code=code, cmpfn=cmpfn):
                    cd, cv = colb.fn(cols, valids)
                    return cmpfn(cd, code), cv
                return Bound(T.BOOLEAN, pfn)
            if effective == "eq":
                return self._const_bool(col, False)
            if effective == "ne":
                return self._const_bool(col, True)
            lb = d.code_lower_bound(v)
            # value absent at bisect position lb: col >/>= v ⇔ code >= lb;
            # col </<= v ⇔ code < lb
            ge_side = effective in ("gt", "ge")
            def bfn(cols, valids, colb=col, lb=lb, ge_side=ge_side):
                cd, cv = colb.fn(cols, valids)
                return (cd >= lb) if ge_side else (cd < lb), cv
            return Bound(T.BOOLEAN, bfn)

        # column vs column (or equal-dictionary cases): unify then compare
        da, db = a.dictionary, b.dictionary
        if da is not None and db is not None and da != db:
            merged, _, _ = Dictionary.unify(da, db)
            a = self._remap_to(a, merged)
            b = self._remap_to(b, merged)
        cmpfn = jf[op]
        def fn(cols, valids):
            ad, av = a.fn(cols, valids)
            bd, bv = b.fn(cols, valids)
            return cmpfn(ad, bd), merge_valid(av, bv)
        return Bound(T.BOOLEAN, fn)

    @staticmethod
    def _const_bool(ref: Bound, value: bool) -> Bound:
        def fn(cols, valids, ref=ref):
            d, v = ref.fn(cols, valids)
            return _const(d, value, jnp.bool_), v
        return Bound(T.BOOLEAN, fn)

    # ---- arithmetic ----
    def _bind_arith(self, op: str, out_type: T.DataType, args) -> Bound:
        a, b = args
        if out_type.is_decimal or a.type.is_decimal or b.type.is_decimal:
            return self._bind_decimal_arith(op, out_type, a, b)
        jf = {
            "add": lambda x, y: x + y,
            "sub": lambda x, y: x - y,
            "mul": lambda x, y: x * y,
        }.get(op)
        if op in ("div", "mod") and b.is_const and b.const_value == 0 \
                and not out_type.is_floating \
                and not getattr(self, "_in_branch", 0):
            raise ValueError("Division by zero")

        def fn(cols, valids):
            ad, av = a.fn(cols, valids)
            bd, bv = b.fn(cols, valids)
            valid = merge_valid(av, bv)
            ad = ad.astype(out_type.dtype)
            bd = bd.astype(out_type.dtype)
            if op == "div":
                if out_type.is_floating:
                    # IEEE semantics like Trino: x/0 = ±Inf, 0/0 = NaN
                    return ad / bd, valid
                zero = bd == 0
                d = F.div_trunc(ad, bd)  # SQL truncates toward zero
                nv = valid if valid is not None else _const(ad, True, jnp.bool_)
                return d, jnp.where(zero, False, nv)
            if op == "mod":
                zero = bd == 0
                safe = jnp.where(zero, 1, bd)
                # SQL mod takes the dividend's sign (C semantics), unlike
                # python's floor mod
                if out_type.is_floating:
                    d = jnp.fmod(ad, safe)
                else:
                    d = jnp.sign(ad) * (jnp.abs(ad) % jnp.abs(safe))
                nv = valid if valid is not None else _const(ad, True, jnp.bool_)
                return d, jnp.where(zero, False, nv)
            return jf(ad, bd), valid
        return Bound(out_type, fn)

    def _bind_decimal_arith(self, op: str, out_type: T.DataType, a: Bound, b: Bound) -> Bound:
        if op in ("div", "mod") and b.is_const and b.const_value == 0 \
                and not getattr(self, "_in_branch", 0):
            raise ValueError("Division by zero")
        sa = a.type.scale or 0 if a.type.is_decimal else 0
        sb = b.type.scale or 0 if b.type.is_decimal else 0
        so = out_type.scale or 0

        def to_scaled(x: Bound, s: int):
            if x.type.is_decimal:
                return x, x.type.scale or 0
            if x.type.is_integerlike:
                def fi(cols, valids, xfn=x.fn):
                    d, v = xfn(cols, valids)
                    return d.astype(jnp.int64), v
                return Bound(T.decimal(18, 0), fi), 0
            raise NotImplementedError(f"decimal arith with {x.type}")

        if (a.type.is_floating or b.type.is_floating) or out_type.is_floating:
            # promote to double
            def ffn(cols, valids):
                ad, av = a.fn(cols, valids)
                bd, bv = b.fn(cols, valids)
                if a.type.is_decimal:
                    ad = ad.astype(jnp.float64) / T.decimal_scale_factor(a.type)
                if b.type.is_decimal:
                    bd = bd.astype(jnp.float64) / T.decimal_scale_factor(b.type)
                valid = merge_valid(av, bv)
                jf = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply}.get(op)
                if op == "div":
                    return ad / bd, valid  # IEEE Inf/NaN, like Trino doubles
                return jf(ad, bd), valid
            return Bound(out_type, ffn)

        if (
            a.type.is_long_decimal
            or b.type.is_long_decimal
            or out_type.is_long_decimal
        ):
            return self._bind_decimal128_arith(
                op, out_type, a, b, sa, sb, so
            )

        a, sa = to_scaled(a, sa)
        b, sb = to_scaled(b, sb)
        def fn(cols, valids):
            ad, av = a.fn(cols, valids)
            bd, bv = b.fn(cols, valids)
            ad = ad.astype(jnp.int64)
            bd = bd.astype(jnp.int64)
            valid = merge_valid(av, bv)
            if op in ("add", "sub"):
                cs = max(sa, sb)
                if sa < cs:
                    ad = ad * (10 ** (cs - sa))
                if sb < cs:
                    bd = bd * (10 ** (cs - sb))
                d = ad + bd if op == "add" else ad - bd
                if cs != so:
                    d = d * (10 ** (so - cs)) if so > cs else F.div_round_half_away(
                        d, _const(d, 10 ** (cs - so), jnp.int64))
                return d, valid
            if op == "mul":
                d = ad * bd  # scale sa+sb
                cs = sa + sb
                if cs != so:
                    d = d * (10 ** (so - cs)) if so > cs else F.div_round_half_away(
                        d, _const(d, 10 ** (cs - so), jnp.int64))
                return d, valid
            if op == "div":
                # result scale so: d = round(a * 10^(sb + so - sa) / b)
                shift = sb + so - sa
                num = ad * (10 ** shift) if shift >= 0 else F.div_round_half_away(
                    ad, _const(ad, 10 ** (-shift), jnp.int64))
                zero = bd == 0
                d = F.div_round_half_away(num, jnp.where(zero, 1, bd))
                nv = valid if valid is not None else _const(ad, True, jnp.bool_)
                return jnp.where(zero, 0, d), jnp.where(zero, False, nv)
            if op == "mod":
                cs = max(sa, sb)
                if sa < cs:
                    ad = ad * (10 ** (cs - sa))
                if sb < cs:
                    bd = bd * (10 ** (cs - sb))
                zero = bd == 0
                safe = jnp.where(zero, 1, bd)
                d = jnp.sign(ad) * (jnp.abs(ad) % jnp.abs(safe))
                nv = valid if valid is not None else _const(ad, True, jnp.bool_)
                return d, jnp.where(zero, False, nv)
            raise NotImplementedError(op)
        return Bound(out_type, fn)

    def _bind_decimal128_arith(
        self, op: str, out_type: T.DataType, a: Bound, b: Bound,
        sa: int, sb: int, so: int,
    ) -> Bound:
        """Int128-carried decimal arithmetic (DecimalOperators long
        paths, spi/type/Int128Math.java). Result overflow past 38
        digits and a divisor beyond int64 yield NULL (Trino raises
        Decimal overflow — same deviation class as the engine's
        division-by-zero NULL, see analyzer deviation notes)."""
        at, bt = a.type, b.type

        def out128(h, lo, valid):
            ovf = I128.overflows_38(h, lo)
            valid = (
                ~ovf if valid is None else (valid & ~ovf)
            )
            if out_type.is_long_decimal:
                return _join2(h, lo), valid
            x, ok = I128.to_i64(h, lo)
            return x, valid & ok

        def fn(cols, valids):
            ad, av = a.fn(cols, valids)
            bd, bv = b.fn(cols, valids)
            valid = merge_valid(av, bv)
            ah, al = _lift128(ad, at)
            bh, bl = _lift128(bd, bt)
            if op in ("add", "sub"):
                cs = max(sa, sb)
                if sa < cs:
                    ah, al = I128.rescale_up(ah, al, cs - sa)
                if sb < cs:
                    bh, bl = I128.rescale_up(bh, bl, cs - sb)
                h, lo = (
                    I128.add(ah, al, bh, bl)
                    if op == "add"
                    else I128.sub(ah, al, bh, bl)
                )
                if so > cs:
                    h, lo = I128.rescale_up(h, lo, so - cs)
                elif cs > so:
                    h, lo = I128.rescale_down_round(h, lo, cs - so)
                return out128(h, lo, valid)
            if op == "mul":
                h, lo = I128.mul_128(ah, al, bh, bl)
                cs = sa + sb
                if so > cs:
                    h, lo = I128.rescale_up(h, lo, so - cs)
                elif cs > so:
                    h, lo = I128.rescale_down_round(h, lo, cs - so)
                return out128(h, lo, valid)
            if op in ("div", "mod"):
                zero = (bh == 0) & (bl == 0)
                bad = zero
                long_divisor = getattr(bt, "is_long_decimal", False)
                if op == "div":
                    # result scale so: round(a * 10^(sb + so - sa) / b).
                    # The rescale wraps mod 2^128 for |a| beyond
                    # ~1.7e38/10^rf — those rows go NULL (the module's
                    # overflow contract) instead of wrapping silently.
                    rf = sb + so - sa
                    lim_h, lim_l = (
                        jnp.int64(x)
                        for x in I128.from_python((2 ** 127 - 1) // 10 ** rf)
                    )
                    aah, aal = I128.abs_(ah, al)
                    bad = bad | ~I128.lt(aah, aal, lim_h, lim_l)
                    nh, nl = I128.rescale_up(ah, al, rf)
                    if long_divisor:
                        # full 128/128 (Int128Math.divideRoundUp); the
                        # bit-serial kernel handles any nonzero divisor
                        sdh = jnp.where(bad, jnp.int64(0), bh)
                        sdl = jnp.where(bad, jnp.int64(1), bl)
                        h, lo = I128.div_round_128(nh, nl, sdh, sdl)
                    else:
                        # short divisor always fits int64: digitwise
                        # schoolbook fast path
                        d64, _ = I128.to_i64(bh, bl)
                        safe = jnp.where(bad, jnp.int64(1), d64)
                        h, lo = I128.div_round_i64(nh, nl, safe)
                else:
                    cs = max(sa, sb)
                    if sa < cs:
                        ah, al = I128.rescale_up(ah, al, cs - sa)
                    if long_divisor or (bt.precision or 18) + (cs - sb) > 18:
                        # divisor rescaled to cs in 128-bit limbs; guard
                        # the 128-bit wrap like the dividend rescale
                        lim_h, lim_l = (
                            jnp.int64(x)
                            for x in I128.from_python(
                                (2 ** 127 - 1) // 10 ** (cs - sb)
                            )
                        )
                        bah, bal = I128.abs_(bh, bl)
                        bad = bad | ~I128.lt(bah, bal, lim_h, lim_l)
                        sdh = jnp.where(bad, jnp.int64(0), bh)
                        sdl = jnp.where(bad, jnp.int64(1), bl)
                        sdh, sdl = I128.rescale_up(sdh, sdl, cs - sb)
                        h, lo = I128.mod_128(ah, al, sdh, sdl)
                    else:
                        d64, _ = I128.to_i64(bh, bl)
                        safe = jnp.where(bad, jnp.int64(1), d64)
                        safe = safe * jnp.int64(10 ** (cs - sb))
                        pa_h, pa_l = I128.abs_(ah, al)
                        _, _, r = I128.divmod_u128_u64(
                            pa_h, pa_l, jnp.abs(safe)
                        )
                        sgn = I128.sign(ah, al)
                        h, lo = I128.mul_128_64(jnp.int64(0) * r, r, sgn)
                d, valid2 = out128(h, lo, valid)
                nv = (
                    valid2
                    if valid2 is not None
                    else _const(ad, True, jnp.bool_)
                )
                return d, jnp.where(bad, False, nv)
            raise NotImplementedError(op)

        return Bound(out_type, fn)


def bind_expr(expr: Expr, batch_or_types, dicts=None) -> Bound:
    """Bind against a RelBatch (tests) or explicit (types, dicts)."""
    if isinstance(batch_or_types, RelBatch):
        return ExprBinder.for_batch(batch_or_types).bind(expr)
    return ExprBinder(batch_or_types, dicts or [None] * len(batch_or_types)).bind(expr)
