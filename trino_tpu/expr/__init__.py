"""Expression layer: typed IR + trace-to-XLA compiler.

Analogue of Trino's RowExpression IR (main/sql/relational/RowExpression.java:18)
and the runtime bytecode compilers ExpressionCompiler / PageFunctionCompiler
(main/sql/gen/ExpressionCompiler.java:57, PageFunctionCompiler.java:103 —
SURVEY.md §2.9). Where Trino emits JVM bytecode per expression at query
setup, we lower the IR to jax.numpy ops at trace time; `jax.jit` around the
enclosing operator plays the role of the generated PageProcessor
(main/operator/project/PageProcessor.java:53), with XLA doing the loop
fusion that Trino hand-rolls per-position.
"""

from trino_tpu.expr.ir import (  # noqa: F401
    Call,
    Case,
    Cast,
    Expr,
    InList,
    InputRef,
    Literal,
)
from trino_tpu.expr.compile import bind_expr, ExprBinder  # noqa: F401
