"""trino_tpu — a TPU-native distributed SQL query engine.

A ground-up JAX/XLA/Pallas re-design of the capabilities of Trino
(reference: linzebing/trino, surveyed in SURVEY.md): a coordinator
parses/plans/schedules SQL; workers execute columnar operator pipelines
compiled to XLA, sharded over a `jax.sharding.Mesh`.

Layer map (mirrors SURVEY.md §1, re-imagined TPU-first):

- ``trino_tpu.types`` / ``trino_tpu.block``  — columnar data model: the
  analogue of trino-spi's Page/Block/Type (spi/Page.java:31,
  spi/block/Block.java:25) as device-resident structure-of-arrays with
  validity masks and dictionary-encoded strings.
- ``trino_tpu.ops``      — XLA/Pallas kernels: group-by hash, join
  build/probe, sort/topN — the analogue of Trino's JIT bytecode layer
  (main/sql/gen/, SURVEY §2.9).
- ``trino_tpu.expr``     — typed expression IR + trace-to-XLA compiler
  (RowExpression / PageProcessor analogue).
- ``trino_tpu.sql``      — lexer/parser/analyzer (trino-parser analogue).
- ``trino_tpu.planner``  — logical plan, optimizer rules, fragmenter.
- ``trino_tpu.exec``     — operators, driver loop, task runtime,
  schedulers (pipelined + fault-tolerant).
- ``trino_tpu.parallel`` — mesh, sharded exchanges (all_to_all over ICI),
  serialized-page host exchange.
- ``trino_tpu.connectors`` — connector SPI + tpch/memory/blackhole.
- ``trino_tpu.runtime``  — session, config, memory pools, stats, tracing.
"""

from trino_tpu import jaxcfg as _jaxcfg  # noqa: F401  (side effect: x64)

__version__ = "0.1.0"
