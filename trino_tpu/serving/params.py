"""Typed EXECUTE ... USING parameter binding.

The parser tolerates `?` placeholders anywhere an expression goes and
`substitute_parameters` splices the bound values in positionally before
analysis — so an arity or type mismatch used to surface only as an
analyzer error deep inside the substituted statement (or worse, as a
silently-wrong comparison). This module checks the binding UP FRONT
against the prepared statement:

- arity: the number of bound values must equal the number of distinct
  placeholder positions (`?` count);
- dtypes: where a placeholder's expected type can be inferred from its
  use site (`col = ?`, `? < col`, `col IN (?, ...)`, `col BETWEEN ?
  AND ?` against a resolvable base table), the bound literal must
  coerce to it under the analyzer's own lattice (common_super_type) —
  the check can never be stricter or looser than analysis itself.

Uninferable positions (parameters inside function calls, derived
tables, expressions) stay unchecked: None in the dtype vector means
"analysis will judge". Failures raise ParameterBindingError naming the
1-based position, the expected type, and the got type.
"""

from __future__ import annotations

from typing import List, Optional

from trino_tpu import types as T
from trino_tpu.sql import ast

# comparison ops whose two sides must share a common super type
_COMPARISONS = {
    "eq", "ne", "lt", "le", "gt", "ge", "is_distinct",
    "=", "<>", "<", "<=", ">", ">=",
}


class ParameterBindingError(ValueError):
    """EXECUTE ... USING arity or dtype mismatch, raised before any
    planning work. `position` is 1-based (the protocol's convention)."""

    def __init__(self, message: str, position: Optional[int] = None,
                 expected=None, got=None):
        super().__init__(message)
        self.position = position
        self.expected = expected
        self.got = got


def literal_dtype(expr) -> Optional[T.DataType]:
    """Static type of a bound literal expression; None when it is not
    a plain literal (analysis will type it)."""
    if isinstance(expr, ast.NumberLiteral):
        text = expr.text.lower()
        if "." in text or "e" in text:
            return T.DOUBLE
        return T.BIGINT
    if isinstance(expr, ast.StringLiteral):
        return T.VARCHAR
    if isinstance(expr, ast.BooleanLiteral):
        return T.BOOLEAN
    if isinstance(expr, ast.NullLiteral):
        return T.UNKNOWN
    if isinstance(expr, ast.DateLiteral):
        return T.DATE
    if isinstance(expr, ast.TimestampLiteral):
        return T.TIMESTAMP
    if isinstance(expr, ast.UnaryOp) and expr.op in ("-", "+"):
        return literal_dtype(expr.operand)
    if isinstance(expr, ast.Cast):
        try:
            from trino_tpu.sql.analyzer import resolve_type

            return resolve_type(expr.type)
        except Exception:
            return None
    return None


def count_parameters(node) -> int:
    """Number of placeholder positions in a statement (max index + 1 —
    the parser numbers them left to right)."""
    import dataclasses as _dc

    top = -1

    def walk(x):
        nonlocal top
        if isinstance(x, ast.Parameter):
            top = max(top, x.index)
        elif _dc.is_dataclass(x) and isinstance(x, ast.Node):
            for f in _dc.fields(x):
                walk(getattr(x, f.name))
        elif isinstance(x, tuple):
            for e in x:
                walk(e)

    walk(node)
    return top + 1


def _column_types(node, catalogs, catalog: str, schema: str) -> dict:
    """column name -> DataType over every base table referenced by the
    statement. Names colliding across tables with DIFFERENT types map
    to None (ambiguous — leave those positions unchecked)."""
    import dataclasses as _dc

    out: dict = {}

    def add_table(parts) -> None:
        cat, sch = catalog, schema
        table = parts[-1]
        if len(parts) == 2:
            sch = parts[0]
        elif len(parts) == 3:
            cat, sch = parts[0], parts[1]
        try:
            conn = catalogs.get(cat)
            handle = conn.metadata.get_table_handle(sch, table)
            if handle is None:
                return
            meta = conn.metadata.get_table_metadata(handle)
        except Exception:
            return
        for col in meta.columns:
            if col.name in out:
                if out[col.name] is not None and out[col.name] != col.type:
                    out[col.name] = None  # ambiguous across tables
            else:
                out[col.name] = col.type

    def walk(x):
        if isinstance(x, ast.TableRef):
            add_table(x.name)
        elif _dc.is_dataclass(x) and isinstance(x, ast.Node):
            for f in _dc.fields(x):
                walk(getattr(x, f.name))
        elif isinstance(x, tuple):
            for e in x:
                walk(e)

    walk(node)
    return out


def infer_parameter_types(
    stmt, catalogs=None, catalog: str = "", schema: str = "",
) -> List[Optional[T.DataType]]:
    """Expected dtype per placeholder position, None where the use site
    does not pin a type. Resolution covers the serving hot paths —
    `col op ?`, `? op col`, `col IN (?, ...)`, `col BETWEEN ? AND ?` —
    against any base table the statement references."""
    import dataclasses as _dc

    n = count_parameters(stmt)
    expected: List[Optional[T.DataType]] = [None] * n
    if n == 0 or catalogs is None:
        return expected
    cols = _column_types(stmt, catalogs, catalog, schema)

    def col_type(e) -> Optional[T.DataType]:
        if isinstance(e, ast.Identifier):
            return cols.get(e.parts[-1])
        return None

    def note(param, ty) -> None:
        if ty is not None and isinstance(param, ast.Parameter):
            if expected[param.index] is None:
                expected[param.index] = ty

    def walk(x):
        if isinstance(x, ast.BinaryOp) and x.op in _COMPARISONS:
            note(x.right, col_type(x.left))
            note(x.left, col_type(x.right))
        elif isinstance(x, ast.InList):
            ty = col_type(x.value)
            for opt in x.options:
                note(opt, ty)
        elif isinstance(x, ast.Between):
            ty = col_type(x.value)
            note(x.low, ty)
            note(x.high, ty)
        if _dc.is_dataclass(x) and isinstance(x, ast.Node):
            for f in _dc.fields(x):
                walk(getattr(x, f.name))
        elif isinstance(x, tuple):
            for e in x:
                walk(e)

    walk(stmt)
    return expected


def bound_dtypes(parameters) -> List[Optional[T.DataType]]:
    """Dtype vector of the bound values (the plan-cache key component)."""
    return [literal_dtype(p) for p in parameters]


def check_parameters(
    stmt, parameters, catalogs=None, catalog: str = "", schema: str = "",
) -> List[Optional[T.DataType]]:
    """Arity + dtype check of `parameters` against the prepared
    statement; returns the bound dtype vector for plan-cache keying.
    Raises ParameterBindingError on mismatch."""
    n = count_parameters(stmt)
    if len(parameters) != n:
        raise ParameterBindingError(
            f"prepared statement expects {n} parameter"
            f"{'s' if n != 1 else ''}, got {len(parameters)}"
        )
    got = bound_dtypes(parameters)
    expected = infer_parameter_types(stmt, catalogs, catalog, schema)
    for i, (exp, g) in enumerate(zip(expected, got)):
        if exp is None or g is None or g.kind == T.TypeKind.UNKNOWN:
            continue
        if T.common_super_type(exp, g) is None:
            raise ParameterBindingError(
                f"parameter {i + 1}: expected {exp}, got {g}",
                position=i + 1, expected=exp, got=g,
            )
    return got
