"""Inter-query micro-batching: one device step for many point lookups.

Concurrent point lookups against the same table and key column are
individually tiny — each one pays a full scheduling round and its own
device dispatch for a handful of rows. When several arrive within a
short window they share an XLA shape class anyway (same operator tree,
same capacity rung, same dtypes), so the batcher coalesces them into
ONE rewritten query

    SELECT <key>, <cols> FROM t WHERE <key> IN (v1, ..., vN)

executes it once, and demultiplexes the result rows back to each
caller by key value. The IN list is padded to a power-of-two length by
repeating the last value (duplicates are harmless under demux-by-
equality), so N concurrent clients produce O(log N) distinct canonical
texts instead of N — repeat traffic re-lands on both the plan cache
and the compiled-program cache.

Leader/follower protocol: the first arrival in a (user, table, key
column, select list, key dtype) group becomes the leader, sleeps the
batch window (or until the group hits max_batch), then executes the
combined query and distributes per-member results. Followers block on
their member event. Classification is STRICT — single TableRef, WHERE
exactly `key = literal`, plain-identifier select list, integer or
string key (float equality is never a point lookup) — and anything
surprising returns None so the caller falls through to the normal
execute path.
"""

from __future__ import annotations

import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import Dict, List, Optional, Tuple

# follower safety net: if the leader thread dies without settling the
# group (executor torn down mid-batch), members unblock and re-raise
# rather than hang the server thread forever
_MEMBER_WAIT_S = 60.0


class _Member:
    __slots__ = ("value", "value_sql", "event", "result", "error")

    def __init__(self, value, value_sql):
        self.value = value
        self.value_sql = value_sql
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class _Group:
    __slots__ = ("ctx", "members", "closed", "full")

    def __init__(self, ctx):
        self.ctx = ctx  # _Lookup of the FIRST member (shared shape)
        self.members: List[_Member] = []
        self.closed = False
        self.full = threading.Event()


class _Lookup:
    """A classified point lookup: everything needed to key the group
    and to rebuild the combined query."""

    __slots__ = ("group_key", "table_sql", "key_col", "select_sql", "value",
                 "value_sql")

    def __init__(self, group_key, table_sql, key_col, select_sql, value,
                 value_sql):
        self.group_key = group_key
        self.table_sql = table_sql
        self.key_col = key_col
        self.select_sql = select_sql
        self.value = value
        self.value_sql = value_sql


def classify(sql: str, runner=None, prepared=None) -> Optional[_Lookup]:
    """Strict point-lookup classification; None = not batchable."""
    try:
        from trino_tpu.sql import ast
        from trino_tpu.sql.parser import parse

        stmt = parse(sql)
        if isinstance(stmt, ast.ExecuteStmt):
            text = (prepared or {}).get(stmt.name)
            if text is None and runner is not None:
                store = getattr(runner, "_prepared", None)
                if store is None and hasattr(runner, "_embedded_runner"):
                    store = runner._embedded_runner()._prepared
                hit = (store or {}).get(stmt.name)
                text = hit[1] if hit else None
            if text is None:
                return None
            stmt = ast.substitute_parameters(parse(text), stmt.parameters)
        if not isinstance(stmt, ast.Query):
            return None
        if stmt.with_ or stmt.order_by or stmt.limit is not None or stmt.offset:
            return None
        spec = stmt.body
        if not isinstance(spec, ast.QuerySpec):
            return None
        if (spec.distinct or spec.group_by or spec.having is not None
                or spec.group_by_sets is not None):
            return None
        if not isinstance(spec.from_, ast.TableRef) or spec.from_.alias:
            return None
        # WHERE must be exactly `key = literal` (either side order)
        w = spec.where
        if not isinstance(w, ast.BinaryOp) or w.op not in ("eq", "="):
            return None
        ident, lit = w.left, w.right
        if not isinstance(ident, ast.Identifier):
            ident, lit = w.right, w.left
        if not isinstance(ident, ast.Identifier) or len(ident.parts) != 1:
            return None
        if isinstance(lit, ast.NumberLiteral):
            text = lit.text.lower()
            if "." in text or "e" in text:
                return None  # float equality is never a point lookup
            value = int(lit.text)
            dkind = "i"
        elif isinstance(lit, ast.StringLiteral):
            value = lit.value
            dkind = "s"
        else:
            return None
        # select list: plain unaliased single-part identifiers only
        cols = []
        for item in spec.select:
            if item.alias is not None:
                return None
            e = item.expr
            if not isinstance(e, ast.Identifier) or len(e.parts) != 1:
                return None
            cols.append(e.parts[0])
        if not cols:
            return None
        from trino_tpu.sql.formatter import format_expression

        table_sql = ".".join(spec.from_.name)
        key_col = ident.parts[0]
        select_sql = ", ".join(cols)
        group_key = (table_sql, key_col, select_sql, dkind)
        return _Lookup(
            group_key, table_sql, key_col, select_sql, value,
            format_expression(lit),
        )
    except Exception:
        return None


class MicroBatcher:
    """submit() either returns a demultiplexed MaterializedResult (the
    query was coalesced) or None (not batchable — caller executes it
    normally). Exceptions from the shared execution propagate to every
    member of the batch."""

    def __init__(self, runner, window_s: float = 0.002, max_batch: int = 16):
        self.runner = runner
        self.window_s = window_s
        self.max_batch = max(1, int(max_batch))
        self._lock = named_lock("MicroBatcher._lock")
        self._groups: Dict[Tuple, _Group] = {}
        self.batches = 0
        self.batched_queries = 0

    def submit(self, sql: str, identity=None, prepared=None):
        look = classify(sql, runner=self.runner, prepared=prepared)
        if look is None:
            return None
        # the combined query executes under ONE identity: never coalesce
        # across users, or the leader's permissions would leak to all
        gkey = look.group_key + (getattr(identity, "user", None),)
        look.group_key = gkey
        member = _Member(look.value, look.value_sql)
        with self._lock:
            group = self._groups.get(look.group_key)
            if group is None or group.closed:
                group = _Group(look)
                self._groups[look.group_key] = group
                leader = True
            else:
                leader = False
            group.members.append(member)
            if len(group.members) >= self.max_batch:
                group.closed = True
                group.full.set()
        if leader:
            group.full.wait(self.window_s)
            with self._lock:
                group.closed = True
                if self._groups.get(look.group_key) is group:
                    del self._groups[look.group_key]
                members = list(group.members)
            self._run_group(group.ctx, members, identity)
        else:
            if not member.event.wait(_MEMBER_WAIT_S):
                raise RuntimeError(
                    "micro-batch leader never settled the group "
                    f"(waited {_MEMBER_WAIT_S:g}s)"
                )
        if member.error is not None:
            raise member.error
        return member.result

    def _run_group(self, ctx: _Lookup, members: List[_Member], identity):
        from trino_tpu.runtime.metrics import METRICS

        try:
            # dedupe + sort the key values, then pad to the next power
            # of two by repeating the last value: the combined canonical
            # text is a function of the VALUE SET, not of arrival order
            # or multiplicity, so a hot key pool produces a small, fast-
            # warming family of texts that re-land on cached plans and
            # warm lowerings
            values_sql = sorted({m.value_sql for m in members})
            n = 1
            while n < len(values_sql):
                n *= 2
            values_sql = values_sql + [values_sql[-1]] * (n - len(values_sql))
            combined = (
                f"SELECT {ctx.key_col}, {ctx.select_sql} "
                f"FROM {ctx.table_sql} "
                f"WHERE {ctx.key_col} IN ({', '.join(values_sql)})"
            )
            kwargs = {}
            if identity is not None:
                kwargs["identity"] = identity
            result = self.runner.execute(combined, **kwargs)
            from trino_tpu.engine import MaterializedResult

            names = list(result.column_names[1:])
            types = list(result.column_types[1:])
            self.batches += 1
            self.batched_queries += len(members)
            METRICS.increment("batcher.batches")
            METRICS.increment("batcher.batched_queries", len(members))
            METRICS.observe("batcher.batch_size", float(len(members)))
            for m in members:
                rows = [list(r[1:]) for r in result.rows if r[0] == m.value]
                m.result = MaterializedResult(rows, names, types)
                m.event.set()
        except BaseException as e:
            for m in members:
                if not m.event.is_set():
                    m.error = e
                    m.event.set()
            # the leader's own submit() re-raises via member.error

    def stats(self) -> dict:
        with self._lock:
            return {
                "batches": self.batches,
                "batched_queries": self.batched_queries,
                "open_groups": len(self._groups),
            }
