"""Open-loop load harness for the serving tier (`bench.py --serve`).

Open-loop means arrivals follow a PRECOMPUTED schedule that does not
slow down when the server does — the honest model of a client
population that keeps clicking while you degrade. Latency is measured
from each query's SCHEDULED arrival, so queue wait under overload
counts against the server (closed-loop harnesses hide it: a stalled
client stops generating load, flattening the tail it should expose).

The run has three phases:

1. **oracle/warm-up** — every distinct statement in the mix executes
   once directly on the runner: results become the per-statement
   oracle, plans land in the plan cache, and every XLA lowering the mix
   needs is compiled. The measured phase must add ZERO new lowerings.
2. **measured open-loop phase** — N client threads drain the arrival
   schedule through the HTTP statement protocol; each completion is
   checked against the oracle. Percentiles are computed EXACTLY from
   the raw samples (the metrics registry's geometric-bucket
   distributions carry ~2x quantile error — useless for a p99/p50
   gate).
3. **batched burst phase** (optional) — a second server with micro-
   batching enabled takes a closed-loop burst of point lookups from
   every client at once, asserting coalescing happened AND every
   demultiplexed result still matches the oracle.
"""

from __future__ import annotations

import random
import threading
from trino_tpu.analysis import threadreg
import time
from typing import Dict, List, Optional, Tuple

DEFAULT_POINT_TEMPLATE = (
    "select o_custkey, o_totalprice from orders where o_orderkey = {key}"
)
DEFAULT_POINT_KEYS = (1, 2, 3, 4, 5, 6, 7)


def exact_percentile(samples: List[float], q: float) -> float:
    """Nearest-rank percentile over the raw sample list."""
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = max(0, min(len(s) - 1, int(round(q * (len(s) - 1)))))
    return s[idx]


def build_tiny_runner(**session_kw):
    """The harness's default target: a LocalQueryRunner over TPC-H tiny
    (the CI-sized serving fixture)."""
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import LocalQueryRunner, Session
    from trino_tpu.runtime.metrics import install_xla_compile_listener

    install_xla_compile_listener()
    r = LocalQueryRunner(
        Session(catalog="tpch", schema="tiny", **session_kw)
    )
    r.register_catalog("tpch", create_tpch_connector())
    return r


def _mesh_fast_submitted(runner) -> int:
    """Sum of fast-lane submissions across the runner's mesh
    schedulers (the single-mesh run queue plus any replica run
    queues) — how the batched phase proves its combined point lookups
    actually rode the MeshScheduler fast lane rather than the page
    plane or a bare lock."""
    total = 0
    sched = getattr(runner, "_mesh_scheduler", None)
    if sched is not None:
        total += int(getattr(sched, "fast_submitted", 0))
    rm = getattr(runner, "_replicas", None)
    if rm is not None:
        total += sum(
            int(getattr(r.scheduler, "fast_submitted", 0))
            for r in rm.replicas
        )
    return total


def _weighted_schedule(
    rng: random.Random,
    names: List[str],
    weights: List[float],
    rate_qps: float,
    duration_s: float,
) -> List[Tuple[float, str]]:
    """Poisson arrivals at rate_qps over duration_s, each tagged with a
    weighted statement pick. Times are offsets from the phase start."""
    out: List[Tuple[float, str]] = []
    t = 0.0
    while True:
        t += rng.expovariate(rate_qps)
        if t >= duration_s:
            return out
        out.append((t, rng.choices(names, weights=weights)[0]))


def run_serve_load(
    queries: Optional[Dict[str, str]] = None,
    weights: Optional[Dict[str, float]] = None,
    point_template: str = DEFAULT_POINT_TEMPLATE,
    point_keys: Tuple[int, ...] = DEFAULT_POINT_KEYS,
    point_weight: float = 0.3,
    n_clients: int = 8,
    duration_s: float = 6.0,
    rate_qps: Optional[float] = None,
    utilization: float = 0.5,
    batch_phase_s: float = 1.5,
    micro_batch_window_ms: float = 3.0,
    seed: int = 7,
    runner=None,
    warmup_rounds: int = 1,
) -> dict:
    """Drive the statement protocol with an open-loop mixed workload;
    returns a report dict (see bench.py --serve for the JSON shape).
    `rate_qps=None` sizes the arrival rate from the warm-up latencies so
    the offered load lands at `utilization` of measured capacity.
    `warmup_rounds` repeats the cold pass — a runner with N replicated
    sub-meshes needs N rounds so every replica compiles its programs
    before the measured phase (placements round-robin, so sequential
    rounds land on distinct replicas)."""
    from trino_tpu.client import Client
    from trino_tpu.runtime.chaos import rows_equal
    from trino_tpu.runtime.metrics import METRICS
    from trino_tpu.runtime.server import CoordinatorServer

    rng = random.Random(seed)
    if runner is None:
        runner = build_tiny_runner()
    statements: Dict[str, str] = dict(queries or {})
    analytic_names = list(statements)
    for k in point_keys:
        statements[f"point_{k}"] = point_template.format(key=k)
    point_names = [f"point_{k}" for k in point_keys]

    # -- phase 1: oracle + warm-up (plans, lowerings, service times) --
    oracle: Dict[str, list] = {}
    warm_s: Dict[str, float] = {}
    for name, sql in statements.items():
        for _ in range(max(1, warmup_rounds)):
            runner.execute(sql)  # cold pass: compiles don't skew timing
        t0 = time.perf_counter()
        oracle[name] = runner.execute(sql).rows
        warm_s[name] = time.perf_counter() - t0

    names = list(statements)
    if weights is None:
        # default mix: points share `point_weight`, analytics split the
        # rest evenly — the shape of a serving tier fronting dashboards
        w = {
            n: (1.0 - point_weight) / max(1, len(analytic_names))
            for n in analytic_names
        }
        w.update({n: point_weight / len(point_names) for n in point_names})
        weights = w
    wlist = [weights.get(n, 0.0) for n in names]
    mean_service = sum(
        warm_s[n] * weights.get(n, 0.0) for n in names
    ) / max(sum(wlist), 1e-9)
    if rate_qps is None:
        rate_qps = max(1.0, utilization / max(mean_service, 1e-4))

    schedule = _weighted_schedule(rng, names, wlist, rate_qps, duration_s)

    # -- phase 2: measured open-loop phase (batching OFF: the gated
    # metrics isolate plan-cache + admission behavior) --
    cache = runner._plan_cache
    hits0, misses0 = cache.hits, cache.misses
    compiles0 = METRICS.counter("xla_compiles")
    server = CoordinatorServer(runner, max_concurrent=n_clients)
    samples: List[Tuple[str, float]] = []  # (name, open-loop latency s)
    mismatches: List[str] = []
    sheds = [0]
    errors: List[str] = []
    lock = threading.Lock()
    idx = [0]
    t_start = time.perf_counter()

    def client_loop():
        import urllib.error

        c = Client(server.uri, timeout=60.0, poll_interval=0.002)
        while True:
            with lock:
                if idx[0] >= len(schedule):
                    return
                at, name = schedule[idx[0]]
                idx[0] += 1
            delay = (t_start + at) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                res = c.execute(statements[name])
                lat = time.perf_counter() - (t_start + at)
                ok = rows_equal(res.rows, oracle[name])
                with lock:
                    samples.append((name, lat))
                    if not ok:
                        mismatches.append(name)
            except urllib.error.HTTPError as e:
                with lock:
                    if e.code == 429:
                        sheds[0] += 1
                    else:
                        errors.append(f"{name}: HTTP {e.code}")
            except Exception as e:
                with lock:
                    errors.append(f"{name}: {e!r}")

    threads = [
        threadreg.spawn(f"serving-client-{i}", client_loop,
                        owner="serving-harness", start=False)
        for i in range(n_clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    server.stop()

    lats = [lat for _, lat in samples]
    hits1, misses1 = cache.hits, cache.misses
    compiles1 = METRICS.counter("xla_compiles")
    d_hits, d_misses = hits1 - hits0, misses1 - misses0
    hit_rate = d_hits / max(1, d_hits + d_misses)
    p50 = exact_percentile(lats, 0.50)
    report = {
        "clients": n_clients,
        "rate_qps": round(rate_qps, 2),
        "offered": len(schedule),
        "completed": len(samples),
        "shed": sheds[0],
        "errors": errors[:5],
        "error_count": len(errors),
        "mismatches": len(mismatches),
        "wall_s": round(wall, 2),
        "qps": round(len(samples) / max(wall, 1e-9), 2),
        "p50_ms": round(p50 * 1e3, 1),
        "p95_ms": round(exact_percentile(lats, 0.95) * 1e3, 1),
        "p99_ms": round(exact_percentile(lats, 0.99) * 1e3, 1),
        "p99_over_p50": round(
            exact_percentile(lats, 0.99) / max(p50, 1e-9), 2
        ),
        "plan_cache_hit_rate": round(hit_rate, 4),
        "plan_cache": cache.stats(),
        "xla_compiles_after_warmup": int(compiles1 - compiles0),
        "per_query_p50_ms": {
            n: round(
                exact_percentile(
                    [l for nm, l in samples if nm == n], 0.50
                ) * 1e3, 1,
            )
            for n in names
            if any(nm == n for nm, _ in samples)
        },
    }

    # -- phase 3: batched burst (micro-batching ON; closed-loop so every
    # client fires simultaneously and the window has peers to coalesce)
    if batch_phase_s > 0:
        from trino_tpu.serving.batcher import MicroBatcher

        batcher = MicroBatcher(
            runner, window_s=micro_batch_window_ms / 1e3, max_batch=16
        )
        bserver = CoordinatorServer(
            runner, max_concurrent=n_clients, batcher=batcher
        )
        b_mismatch = [0]
        b_done = [0]
        b_errors: List[str] = []
        fast0 = _mesh_fast_submitted(runner)
        stop_at = time.perf_counter() + batch_phase_s

        def burst_loop(i: int):
            r = random.Random(seed * 1000 + i)
            c = Client(bserver.uri, timeout=60.0, poll_interval=0.002)
            while time.perf_counter() < stop_at:
                k = r.choice(point_keys)
                name = f"point_{k}"
                try:
                    res = c.execute(statements[name])
                    with lock:
                        b_done[0] += 1
                        if not rows_equal(res.rows, oracle[name]):
                            b_mismatch[0] += 1
                except Exception as e:
                    with lock:
                        b_errors.append(f"{name}: {e!r}")

        bts = [
            threadreg.spawn(f"serving-burst-{i}", burst_loop, args=(i,),
                            owner="serving-harness", start=False)
            for i in range(n_clients)
        ]
        for t in bts:
            t.start()
        for t in bts:
            t.join()
        bserver.stop()
        report["batch_phase"] = {
            "queries": b_done[0],
            "mismatches": b_mismatch[0],
            "errors": b_errors[:5],
            "error_count": len(b_errors),
            # combined IN-list lookups classify as fast lane
            # (serving/admission.py is_point_lookup handles InList), so
            # on a mesh-scheduled runner every batch leader's execute
            # lands as a fast submission on some sub-mesh's run queue
            "mesh_fast_lane": _mesh_fast_submitted(runner) - fast0,
            **batcher.stats(),
        }
    return report


def serve_smoke(
    queries: Dict[str, str],
    n_clients: int = 8,
    duration_s: float = 6.0,
    seed: int = 7,
) -> Tuple[dict, List[str]]:
    """The CI gate behind bench.py --serve-smoke. Returns (report,
    violations); empty violations = pass. Gates (ISSUE 8 acceptance):
    every query oracle-equal, plan-cache hit rate >= 90%, zero new XLA
    lowerings after warm-up, p99 <= 5x p50, and the batched phase must
    actually coalesce while staying oracle-equal."""
    report = run_serve_load(
        queries=queries, n_clients=n_clients, duration_s=duration_s,
        seed=seed,
    )
    v: List[str] = []
    if report["completed"] < max(10, report["offered"] // 2):
        v.append(
            f"only {report['completed']}/{report['offered']} completed"
        )
    if report["mismatches"]:
        v.append(f"{report['mismatches']} results diverged from oracle")
    if report["error_count"]:
        v.append(
            f"{report['error_count']} client errors "
            f"(first: {report['errors'][:1]})"
        )
    if report["shed"]:
        v.append(
            f"{report['shed']} sheds at nominal load (lanes undersized)"
        )
    if report["plan_cache_hit_rate"] < 0.90:
        v.append(
            f"plan-cache hit rate {report['plan_cache_hit_rate']:.2%} < 90%"
        )
    if report["xla_compiles_after_warmup"] != 0:
        v.append(
            f"{report['xla_compiles_after_warmup']} new XLA lowerings "
            "after warm-up"
        )
    if report["p99_over_p50"] > 5.0:
        v.append(
            f"p99/p50 = {report['p99_over_p50']:.2f} > 5.0 "
            f"(p50={report['p50_ms']}ms p99={report['p99_ms']}ms)"
        )
    bp = report.get("batch_phase", {})
    if bp:
        if bp["mismatches"] or bp["error_count"]:
            v.append(
                f"batch phase: {bp['mismatches']} mismatches, "
                f"{bp['error_count']} errors"
            )
        if bp["batches"] == 0 or bp["batched_queries"] <= bp["batches"]:
            v.append(
                "batch phase never coalesced "
                f"(batches={bp['batches']}, "
                f"batched_queries={bp['batched_queries']})"
            )
    return report, v
