"""Serving tier: the high-QPS front of the engine.

Everything below turns the one-query-at-a-time engine into a server:

- `plan_cache`   — prepared-statement plan cache (PREPARE/EXECUTE skips
                   parse→analyze→optimize→fragment on a hit)
- `params`       — typed EXECUTE ... USING parameter binding
- `admission`    — lane-based admission in front of the resource
                   groups, with overload shedding (429 + Retry-After)
- `batcher`      — inter-query micro-batching of point lookups onto
                   one shared device step
- `harness`      — open-loop load harness behind `bench.py --serve`

The split mirrors the reference's dispatcher layer (DispatchManager +
QueryPreparer + resource-group submit path in front of the execution
engine), which is above all a serving system: the client protocol is
built for thousands of concurrent pollers, not one REPL.
"""

from trino_tpu.serving.admission import (  # noqa: F401
    AdmissionPipeline,
    OverloadSheddedError,
)
from trino_tpu.serving.batcher import MicroBatcher  # noqa: F401
from trino_tpu.serving.params import ParameterBindingError  # noqa: F401
from trino_tpu.serving.plan_cache import PlanCache  # noqa: F401
