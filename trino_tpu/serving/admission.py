"""Admission pipeline: lane-based overload control in front of the
resource groups.

Every submission is classified into one of two lanes before it touches
the executor:

- **fast** — point lookups whose plan is already in the plan cache.
  These cost microseconds of planning and one small device step; they
  ride a short dedicated lane so a burst of heavy analytics cannot
  queue them behind itself (the reference's per-group concurrency
  carve-outs, made automatic).
- **general** — everything else.

Each lane has a bounded depth (submissions admitted-or-waiting). A
submission arriving at a full lane is SHED synchronously — the HTTP
front answers 429 with Retry-After — instead of joining an unbounded
queue: under sustained overload an open-loop client population grows
the queue without bound and every queued query eventually misses its
deadline anyway (goodput collapse). Shedding keeps the served fraction
fast and makes the overload observable (`admission.<lane>.shed`).

Inside its lane a submission still goes through the EXISTING resource
groups (weighted fairness, per-group caps) — the pipeline passes the
lane as the selector `source`, so operators can route lanes to
dedicated groups; with no selector configured both lanes share the
root group and the lane depth is the only new bound.
"""

from __future__ import annotations

import dataclasses
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import Any, Optional

LANES = ("fast", "general")


class OverloadSheddedError(RuntimeError):
    """Submission rejected at admission: the lane (or the resource-group
    queue behind it) is full. Maps to HTTP 429 + Retry-After."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 lane: str = "general"):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.lane = lane


@dataclasses.dataclass
class AdmissionReservation:
    """One submission's place in its lane, held from the synchronous
    admission check until the query releases (finish/fail/abandon)."""

    lane: str
    lease: Any = None  # resource-group lease, once wait() returns
    released: bool = False


class AdmissionPipeline:
    """reserve() is the synchronous shed point (runs on the HTTP
    thread); wait() blocks for a resource-group slot (runs on the
    executor); release() returns both."""

    def __init__(
        self,
        resource_groups=None,
        fast_depth: int = 64,
        general_depth: int = 256,
        retry_after_s: float = 1.0,
    ):
        from trino_tpu.runtime.metrics import METRICS

        self.resource_groups = resource_groups
        self.retry_after_s = retry_after_s
        self._max = {"fast": fast_depth, "general": general_depth}
        self._depth = {lane: 0 for lane in LANES}
        self.sheds = {lane: 0 for lane in LANES}
        self.admitted = {lane: 0 for lane in LANES}
        self._lock = named_lock("AdmissionPipeline._lock")
        # replica plane supplier (runtime/replicas.ReplicaManager or
        # None): admitted lanes drain onto whichever healthy sub-mesh
        # the coordinator places them on; stats() surfaces that balance
        # next to the lane depths so one endpoint shows the whole
        # admission -> placement funnel
        self._replica_supplier = None
        for lane in LANES:
            METRICS.register_gauge(
                f"admission.{lane}.queue_depth",
                lambda lane=lane: float(self._depth[lane]),
            )

    def attach_replicas(self, supplier) -> None:
        """`supplier()` returns the live ReplicaManager (or None) at
        stats time — a callable because the coordinator carves the
        replica plane lazily, after this pipeline is built."""
        self._replica_supplier = supplier

    def reserve(self, fast: bool = False) -> AdmissionReservation:
        from trino_tpu.runtime.metrics import METRICS

        lane = "fast" if fast else "general"
        with self._lock:
            if self._depth[lane] >= self._max[lane]:
                self.sheds[lane] += 1
                METRICS.increment(f"admission.{lane}.shed")
                raise OverloadSheddedError(
                    f"admission lane '{lane}' is full "
                    f"({self._max[lane]} in flight); retry after "
                    f"{self.retry_after_s:g}s",
                    retry_after_s=self.retry_after_s,
                    lane=lane,
                )
            self._depth[lane] += 1
            self.admitted[lane] += 1
            METRICS.increment(f"admission.{lane}.admitted")
        return AdmissionReservation(lane)

    def wait(self, reservation: AdmissionReservation, user: str = "user",
             cancelled=None, timeout: float = 60.0) -> None:
        """Acquire the resource-group slot for a reserved submission.
        Raises whatever the group manager raises (queue-full, killed
        while queued); the caller still must release() — release is
        idempotent on the lease being absent."""
        if self.resource_groups is None:
            return
        reservation.lease = self.resource_groups.acquire(
            user=user, source=reservation.lane,
            timeout=timeout, cancelled=cancelled,
        )

    def release(self, reservation: Optional[AdmissionReservation]) -> None:
        if reservation is None or reservation.released:
            return
        reservation.released = True
        with self._lock:
            self._depth[reservation.lane] -= 1
        if reservation.lease is not None and self.resource_groups is not None:
            self.resource_groups.release(reservation.lease)
            reservation.lease = None

    def stats(self) -> dict:
        with self._lock:
            out = {
                lane: {
                    "depth": self._depth[lane],
                    "max_depth": self._max[lane],
                    "shed": self.sheds[lane],
                    "admitted": self.admitted[lane],
                }
                for lane in LANES
            }
        supplier = self._replica_supplier
        if supplier is not None:
            try:
                rm = supplier()
            except Exception:
                rm = None
            if rm is not None:
                out["replicas"] = rm.stats()
        return out


# -- fast-path classification -------------------------------------------------

def is_point_lookup(stmt) -> bool:
    """Loose point-lookup shape test for lane routing: one base table,
    a WHERE with at least one equality/IN against a column, no joins.
    (The micro-batcher applies its own, stricter, test.)"""
    from trino_tpu.sql import ast

    if not isinstance(stmt, ast.Query):
        return False
    spec = stmt.body
    if not isinstance(spec, ast.QuerySpec):
        return False
    if not isinstance(spec.from_, ast.TableRef):
        return False
    if spec.where is None:
        return False

    def has_key_predicate(e) -> bool:
        if isinstance(e, ast.BinaryOp):
            if e.op in ("and", "AND"):
                return has_key_predicate(e.left) or has_key_predicate(e.right)
            if e.op in ("eq", "="):
                return isinstance(e.left, ast.Identifier) or isinstance(
                    e.right, ast.Identifier
                )
            return False
        if isinstance(e, ast.InList):
            return isinstance(e.value, ast.Identifier)
        return False

    return has_key_predicate(spec.where)


def is_fast_lane(stmt) -> bool:
    """Mesh-scheduler fast-lane shape test: a point lookup, possibly
    decorated with one dimension join (key-predicated base table joined
    to a plain table ref). The scheduler's fast lane preempts the
    running analytic at chunk boundaries, so eligibility must stay
    cheap — a few chunk-steps of work, never a streaming driver —
    which a single decorated lookup satisfies but a multi-join tree
    does not. Never raises: surprises classify as NOT fast."""
    try:
        from trino_tpu.sql import ast

        if is_point_lookup(stmt):
            return True
        if not isinstance(stmt, ast.Query):
            return False
        spec = stmt.body
        if not isinstance(spec, ast.QuerySpec):
            return False
        j = spec.from_
        if not isinstance(j, ast.Join):
            return False
        if not (
            isinstance(j.left, ast.TableRef)
            and isinstance(j.right, ast.TableRef)
        ):
            return False
        if spec.where is None:
            return False
        # reuse the point-lookup key test over the decorated shape
        probe = ast.Query(
            body=ast.QuerySpec(
                select=spec.select, from_=j.left, where=spec.where,
            )
        )
        return is_point_lookup(probe)
    except Exception:
        return False


def fast_path_probe(runner, sql: str, prepared=None) -> bool:
    """True iff `sql` is a point lookup whose plan the runner already
    holds — the submission can skip the general lane. Never raises:
    any surprise (unparsable text, missing prepared statement, arity
    error) classifies as NOT fast and the real dispatch reports it."""
    from trino_tpu.serving.plan_cache import PlanCache

    cache = getattr(runner, "_plan_cache", None)
    session = getattr(runner, "session", None)
    if not isinstance(cache, PlanCache) or session is None:
        return False
    try:
        from trino_tpu.serving.params import bound_dtypes
        from trino_tpu.sql import ast
        from trino_tpu.sql.formatter import format_statement
        from trino_tpu.sql.parser import parse

        stmt = parse(sql)
        dtypes = ()
        if isinstance(stmt, ast.ExecuteStmt):
            text = (prepared or {}).get(stmt.name)
            if text is None:
                store = getattr(runner, "_prepared", None)
                if store is None and hasattr(runner, "_embedded_runner"):
                    store = runner._embedded_runner()._prepared
                hit = (store or {}).get(stmt.name)
                text = hit[1] if hit else None
            if text is None:
                return False
            body = ast.substitute_parameters(parse(text), stmt.parameters)
            dtypes = tuple(bound_dtypes(stmt.parameters))
            stmt = body
        if not is_point_lookup(stmt):
            return False
        key = cache.key(format_statement(stmt), session, dtypes)
        return cache.contains(key)
    except Exception:
        return False
