"""Prepared-statement plan cache.

A hit skips the whole parse→analyze→optimize→(fragment) pipeline: the
runner re-uses the cached (logical output, physical/fragment plan)
pair and goes straight to execution. The key has three parts:

1. the formatter's CANONICAL sql text — the PR 5 formatter-fixpoint
   checker (format(parse(format(x))) == format(x)) is what makes a
   text key safe: two spellings of one statement canonicalize to one
   entry. EXECUTE keys canonicalize the BOUND statement (parameters
   substituted), so distinct bindings plan separately — values are
   folded into pushdown constraints at analysis time, and a
   value-blind key would serve wrong splits;
2. the plan-affecting session properties (a property flipped via SET
   SESSION must miss, not serve a stale shape);
3. the bound-parameter dtype vector (an EXECUTE binding 1 and one
   binding 1.5 compile different kernels even for equal canonical
   prefixes).

Entries are LRU-bounded and never store volatile plans (now(), uuid()
fold at analysis time). Invalidation is table-granular when the write
can name its target (`invalidate_tables` — DML drops only plans that
read the written table, the resident-tier protocol) and wholesale
otherwise (`invalidate` — COMMIT, catalog registration; cached
physical plans capture split listings, i.e. data snapshots). Counters
surface in /v1/metrics as
plan_cache.{hits,misses,evictions,invalidations}.
"""

from __future__ import annotations

import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from collections import OrderedDict
from typing import Any, Optional, Tuple

# Session properties that shape the plan (resolution, optimizer
# decisions, physical layout, fragmenting). Anything listed here that
# changes between two executions of the same text yields a different
# key — SET SESSION never needs to invalidate.
PLAN_AFFECTING_PROPERTIES = (
    "catalog",
    "schema",
    "timezone",
    "batch_rows",
    "target_splits",
    "enable_dynamic_filtering",
    "enable_pushdown",
    "enable_optimizer",
    "join_reordering_strategy",
    "broadcast_join_threshold",
    "shape_stabilization",
    "capacity_ladder_base",
    "plan_validation",
    "adaptive_execution",
    "adaptive_replan_threshold",
    "shared_subtree_materialization",
)


def plan_properties(session) -> Tuple:
    """The plan-shaping slice of a Session, as a hashable tuple."""
    return tuple(
        getattr(session, name, None) for name in PLAN_AFFECTING_PROPERTIES
    )


def plan_tables(root) -> frozenset:
    """Lowercased (catalog, schema, table) triples of every ScanNode
    under a plan root — the `tables=` tag for `store`, aligned with the
    resident tier's `table_key` convention."""
    out = set()
    stack = [root]
    while stack:
        node = stack.pop()
        handle = getattr(node, "handle", None)
        if handle is not None and hasattr(handle, "table"):
            catalog = getattr(node, "catalog", None) or getattr(
                handle, "catalog", ""
            )
            out.add((
                str(catalog).lower(),
                str(handle.schema).lower(),
                str(handle.table).lower(),
            ))
        stack.extend(getattr(node, "children", lambda: ())())
    return frozenset(out)


class PlanCache:
    """Thread-safe bounded-LRU plan cache with metric counters.

    Values are opaque to the cache: the local runner stores
    (OutputNode, PhysicalPlan), the distributed runner stores
    (OutputNode, SubPlan)."""

    def __init__(self, max_entries: int = 256, metrics_prefix: str = "plan_cache"):
        self.max_entries = max(1, int(max_entries))
        self._prefix = metrics_prefix
        self._lock = named_lock("PlanCache._lock")
        self._entries: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._tables: dict = {}  # key -> frozenset of source tables
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        # bumped on every invalidate: a long-running planner that began
        # before a DDL must not store its now-stale plan after it
        self.generation = 0

    # -- keying --
    def key(self, canonical_sql: str, session, param_dtypes=()) -> Tuple:
        return (
            canonical_sql,
            plan_properties(session),
            tuple(str(d) for d in param_dtypes),
        )

    # -- cache ops --
    def lookup(self, key: Tuple) -> Optional[Any]:
        from trino_tpu.runtime.metrics import METRICS

        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                METRICS.increment(f"{self._prefix}.misses")
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            METRICS.increment(f"{self._prefix}.hits")
            return entry

    def contains(self, key: Tuple) -> bool:
        """Presence probe that does NOT touch LRU order or counters
        (the admission fast-path classifier must not inflate the hit
        rate or refresh entries it will not use)."""
        with self._lock:
            return key in self._entries

    def store(self, key: Tuple, value: Any, generation: Optional[int] = None,
              tables=()) -> None:
        """`tables` is the plan's source-table set (lowercased
        (catalog, schema, table) triples); entries tagged with it are
        droppable table-granularly by `invalidate_tables`. Untagged
        entries only fall to wholesale `invalidate`."""
        from trino_tpu.runtime.metrics import METRICS

        with self._lock:
            if generation is not None and generation != self.generation:
                return  # invalidated while planning: the plan is stale
            self._entries[key] = value
            self._entries.move_to_end(key)
            self._tables[key] = frozenset(tables)
            while len(self._entries) > self.max_entries:
                old, _ = self._entries.popitem(last=False)
                self._tables.pop(old, None)
                self.evictions += 1
                METRICS.increment(f"{self._prefix}.evictions")

    def invalidate(self) -> None:
        """Catalog/schema changed (DDL, DML, commit): every cached plan
        captured split listings that may no longer describe the data."""
        from trino_tpu.runtime.metrics import METRICS

        with self._lock:
            self._entries.clear()
            self._tables.clear()
            self.generation += 1
            self.invalidations += 1
            METRICS.increment(f"{self._prefix}.invalidations")

    def invalidate_tables(self, tables) -> int:
        """Table-granular invalidation: drop plans that read any of
        `tables`, plus untagged plans (their source set is unknown, so
        they must be assumed dirty). Plans over other tables survive —
        the resident-tier protocol (DML names its target). The
        generation still bumps: a concurrent planner racing the write
        may be planning against the written table, and a refused store
        on an unaffected plan only costs one replan."""
        from trino_tpu.runtime.metrics import METRICS

        tset = {tuple(str(p).lower() for p in t) for t in tables}
        with self._lock:
            victims = [
                k
                for k in self._entries
                if not self._tables.get(k) or self._tables[k] & tset
            ]
            for k in victims:
                del self._entries[k]
                self._tables.pop(k, None)
            self.generation += 1
            self.invalidations += 1
            METRICS.increment(f"{self._prefix}.invalidations")
            return len(victims)

    # dict-compat shims: callers predating the serving tier used a raw
    # dict here (engine._plan_cache), and tests poke it directly
    def clear(self) -> None:
        self.invalidate()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
            }
