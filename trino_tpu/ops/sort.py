"""Sort / TopN kernels.

Analogue of Trino's OrderByOperator + OrderingCompiler + TopNOperator
(main/operator/OrderByOperator.java:44, main/sql/gen/OrderingCompiler.java,
TopNOperator.java:35). Trino JIT-compiles row comparators over a
PagesIndex; the TPU-native form is an LSD-radix chain of single-key
stable argsorts over order-mapped key columns (floats to total-order
bits, descending via bit inversion, NULL rank as its own pass) — see
sort_order's docstring for why a fused multi-key lax.sort loses
(XLA:TPU sort compile time explodes with key count x length). Strings
sort by dictionary code (sorted dictionaries: code order == lexical).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax.numpy as jnp

from trino_tpu.ops.gather import take_clip



@dataclasses.dataclass(frozen=True)
class SortKey:
    """channel + ordering; mirrors Trino's SortOrder
    (spi/connector/SortOrder.java: ASC/DESC x NULLS FIRST/LAST)."""

    channel: int
    descending: bool = False
    nulls_first: bool = False


def _order_value(data: jnp.ndarray, descending: bool) -> jnp.ndarray:
    """Single sortable key with Trino ordering semantics. Floats stay
    FLOATS: XLA's sort/argsort is a total order with NaN LAST, which is
    exactly Trino's ascending order (Double.compare, NaN > +Inf) — and
    64-bit float bitcasts do not compile on this TPU backend, so the
    old int-bits mapping is off the table for f64. Descending floats
    negate the value; NaN (still last after negation, but Trino wants
    it FIRST when descending) is fixed by the caller's nan pass
    (sort_order) — the pre-ordering callers are ascending-only."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        data = jnp.where(data == 0, jnp.zeros((), data.dtype), data)
        return -data if descending else data
    if not descending:
        return data
    if data.dtype == jnp.bool_:
        return ~data
    # signed ints: flip order without overflow on INT_MIN
    return jnp.invert(data)


def sort_order(
    key_data: List[jnp.ndarray],
    key_valids: List[Optional[jnp.ndarray]],
    descending: List[bool],
    nulls_first: List[bool],
    live: Optional[jnp.ndarray],
) -> jnp.ndarray:
    """Permutation putting live rows in ORDER BY order, dead rows last.

    LSD-radix chain of single-key stable argsorts (least-significant
    key first). A single fused multi-key lax.sort would be fewer
    passes, but XLA:TPU's sort compile time explodes with key/operand
    count times array length (measured: 3 keys + iota at 64k rows =
    113s to compile; 5 keys = 287s) — single-key sorts compile in
    seconds and run at ~10ms/M rows, so the chain wins end to end."""
    n = key_data[0].shape[0]
    order = jnp.arange(n, dtype=jnp.int32)
    for data, valid, desc, nf in reversed(
        list(zip(key_data, key_valids, descending, nulls_first))
    ):
        if getattr(data, "ndim", 1) == 2:
            # long-decimal limb pairs: stable LSD chain — low limb in
            # UNSIGNED order first, then the signed high limb
            lo_u = data[:, 1] ^ jnp.int64(-0x8000000000000000)
            v = _order_value(take_clip(lo_u, order), desc)
            order = take_clip(order, jnp.argsort(v, stable=True))
            v = _order_value(take_clip(data[:, 0], order), desc)
            order = take_clip(order, jnp.argsort(v, stable=True))
        else:
            v = _order_value(take_clip(data, order), desc)
            order = take_clip(order, jnp.argsort(v, stable=True))
        if desc and jnp.issubdtype(data.dtype, jnp.floating):
            # descending floats: NaN must come FIRST (it is the largest
            # value — Double.compare), but negation leaves it last
            nanrank = jnp.where(jnp.isnan(take_clip(data, order)), 0, 1)
            order = take_clip(order, jnp.argsort(nanrank, stable=True))
        if valid is not None:
            nv = take_clip(valid, order)
            null_rank = jnp.where(nv, 1, 0) if nf else jnp.where(nv, 0, 1)
            order = take_clip(order, jnp.argsort(null_rank, stable=True))
    if live is not None:
        dead = ~take_clip(live, order)
        order = take_clip(order, jnp.argsort(dead, stable=True))
    return order
