"""Row hashing kernels.

Analogue of Trino's per-type compiled hash operators
(spi/type/TypeOperators.java:64) and precomputed hash channels
(HashGenerationOptimizer). We use a murmur3-style 32-bit finalizer over
int32 lanes — native VPU width on TPU — and combine columns with a
boost-style mix. 64-bit variants are built from two independent 32-bit
streams (avoids emulated-int64 multiplies on TPU).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp


def _fmix32(x: jnp.ndarray) -> jnp.ndarray:
    """murmur3 finalizer on uint32."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _to_lanes(data: jnp.ndarray) -> tuple:
    """View a column as one or two uint32 lanes (hi lane only for 64-bit).
    Floats normalize -0.0 to +0.0 first: SQL equality treats them equal,
    so their hash lanes must match (group-by, join probe, and exchange
    routing all flow through here)."""
    dt = data.dtype
    if getattr(data, "ndim", 1) == 2:
        # long-decimal limb pairs (n, 2) int64: four u32 lanes
        hi_l = _to_lanes(data[:, 0])
        lo_l = _to_lanes(data[:, 1])
        return (*lo_l, *hi_l)
    if jnp.issubdtype(dt, jnp.floating):
        data = jnp.where(data == 0, jnp.zeros((), dt), data)
    if dt == jnp.float64:
        # f64 bitcasts do not compile on this TPU backend; the 3-lane
        # decomposition is injective + NaN/-0 canonical (ops/floatbits)
        from trino_tpu.ops.floatbits import f64_lanes

        return f64_lanes(data)
    if dt in (jnp.int64, jnp.uint64):
        bits = data.astype(jnp.int64).view(jnp.uint64)
        lo = (bits & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (bits >> jnp.uint64(32)).astype(jnp.uint32)
        return lo, hi
    if dt == jnp.float32:
        return (data.view(jnp.uint32),)
    if dt == jnp.bool_:
        return (data.astype(jnp.uint32),)
    return (data.astype(jnp.int32).view(jnp.uint32),)


def hash32(
    columns: Sequence[jnp.ndarray],
    valids: Optional[Sequence[Optional[jnp.ndarray]]] = None,
    seed: int = 0,
) -> jnp.ndarray:
    """Combined 32-bit hash over key columns; NULL hashes distinctly."""
    # rows only: a leading (n, 2) limb-pair column must not make h 2-D
    h = jnp.full(
        columns[0].shape[:1], jnp.uint32(0x9E3779B9 + seed), dtype=jnp.uint32
    )
    for i, col in enumerate(columns):
        for lane in _to_lanes(col):
            v = lane
            if valids is not None and valids[i] is not None:
                v = jnp.where(valids[i], v, jnp.uint32(0xA5A5A5A5))
            h = h ^ (_fmix32(v + jnp.uint32(i + 1)) + jnp.uint32(0x9E3779B9) + (h << 6) + (h >> 2))
    return _fmix32(h)


def hash64(
    columns: Sequence[jnp.ndarray],
    valids: Optional[Sequence[Optional[jnp.ndarray]]] = None,
    seed: int = 0,
) -> jnp.ndarray:
    """64-bit hash from two independently-seeded 32-bit streams. `seed`
    reseeds both streams (the group-by sort path reseeds on retry so a
    62-bit hash collision cannot recur)."""
    lo = hash32(columns, valids, seed=seed)
    hi = hash32(columns, valids, seed=0x243F6A88 + seed)
    # 62-bit mask: leaves headroom above the hash range for sentinel
    # values (ops/groupby._DEAD_ROW_HASH sorts dead rows last; the join
    # moved to a 32-bit domain with its own u32 sentinels in r4)
    return (hi.astype(jnp.uint64) << jnp.uint64(32) | lo.astype(jnp.uint64)).astype(
        jnp.int64
    ) & jnp.int64(0x3FFFFFFFFFFFFFFF)


def partition_of(h: jnp.ndarray, num_partitions: int) -> jnp.ndarray:
    """Map a 32-bit hash to a partition id (for hash exchanges)."""
    if num_partitions & (num_partitions - 1) == 0:
        return (h & jnp.uint32(num_partitions - 1)).astype(jnp.int32)
    return (h % jnp.uint32(num_partitions)).astype(jnp.int32)


def dictionary_code_hashes(values: Sequence[str]) -> "np.ndarray":
    """Per-code value hash for a dictionary column: hashing the string
    VALUE (crc32), not the code, so two sides of an exchange with
    different dictionaries partition equal strings identically — the
    cross-fragment analogue of TypeOperators' per-type hash contract."""
    import numpy as np
    import zlib

    return np.asarray(
        [zlib.crc32(v.encode("utf-8")) for v in values], dtype=np.uint32
    )


def dictionary_lut(dictionary) -> "Optional[np.ndarray]":
    """The single routing rule both data planes share: dictionary codes
    hash through a per-value LUT when the dictionary is NON-EMPTY; an
    absent or empty dictionary (all-NULL column) hashes codes directly
    (indexing an empty LUT would be invalid). Used by the page-exchange
    PartitionedOutputOperator AND the mesh exchange's _partition_ids —
    co-partitioned producers on either plane must route identically."""
    if dictionary is None or len(dictionary) == 0:
        return None
    return dictionary_code_hashes(dictionary.values)


def _fmix32_np(x):
    """Host-side replica of `_fmix32` (numpy, bit-for-bit)."""
    import numpy as np

    x = x.astype(np.uint32)
    x = x ^ (x >> 16)
    x = (x * np.uint32(0x85EBCA6B)).astype(np.uint32)
    x = x ^ (x >> 13)
    x = (x * np.uint32(0xC2B2AE35)).astype(np.uint32)
    x = x ^ (x >> 16)
    return x


def hash32_np(columns, valids=None, seed: int = 0):
    """Host-side replica of `hash32` for connector bucketing (the
    ConnectorBucketNodeMap / TpchNodePartitioningProvider.java:70 bucket
    function seat): a connector that pre-buckets rows with this routes
    them EXACTLY like the runtime exchanges route them, so a declared
    table partitioning can cancel a repartition exchange. Accepts the
    canonical lane dtypes only — int64 (integer-family keys) or uint32
    (dictionary value hashes from `dictionary_code_hashes`). MUST stay
    in bit-for-bit lock-step with `hash32`/`_to_lanes`
    (tests/test_bucketed.py asserts parity)."""
    import numpy as np

    def lanes_of(col):
        if getattr(col, "ndim", 1) == 2:
            # long-decimal limb pairs: lo-limb lanes then hi-limb lanes
            # (the _to_lanes order)
            return (*lanes_of(col[:, 1]), *lanes_of(col[:, 0]))
        if col.dtype == np.uint32:
            return (col,)
        bits = col.astype(np.int64).view(np.uint64)
        lo = (bits & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        hi = (bits >> np.uint64(32)).astype(np.uint32)
        return lo, hi

    h = np.full(len(columns[0]), np.uint32((0x9E3779B9 + seed) & 0xFFFFFFFF), dtype=np.uint32)
    for i, col in enumerate(columns):
        for lane in lanes_of(np.asarray(col)):
            v = lane
            if valids is not None and valids[i] is not None:
                v = np.where(valids[i], v, np.uint32(0xA5A5A5A5))
            h = h ^ (
                (_fmix32_np((v + np.uint32(i + 1)).astype(np.uint32))
                 + np.uint32(0x9E3779B9)
                 + (h << 6).astype(np.uint32)
                 + (h >> 2)).astype(np.uint32)
            )
    return _fmix32_np(h)


def partition_of_np(h, num_partitions: int):
    """Host-side replica of `partition_of`."""
    import numpy as np

    if num_partitions & (num_partitions - 1) == 0:
        return (h & np.uint32(num_partitions - 1)).astype(np.int32)
    return (h % np.uint32(num_partitions)).astype(np.int32)


def canonical_hash_input(data: jnp.ndarray, code_hashes=None) -> jnp.ndarray:
    """Normalize a key column for cross-fragment hash partitioning:
    integer-like -> int64, floating -> float64, dictionary codes -> the
    per-value hash (via `code_hashes`). Equal SQL values must produce
    equal lanes regardless of physical dtype or dictionary identity."""
    if code_hashes is not None:
        idx = jnp.clip(data, 0, code_hashes.shape[0] - 1).astype(jnp.int32)
        return jnp.take(jnp.asarray(code_hashes), idx).astype(jnp.uint32)
    if jnp.issubdtype(data.dtype, jnp.floating):
        return data.astype(jnp.float64)
    if data.dtype == jnp.bool_:
        return data.astype(jnp.int64)
    return data.astype(jnp.int64)
