"""Gather with explicit clip mode.

jnp.take's default out-of-bounds mode ('fill') lowers to a guarded
gather that is catastrophically slower on TPU (measured on v5e: 20.7ms
vs 0.09ms for a 1M-row gather from a 128k table — 230x). Every gather
in this engine indexes with values that are in range by construction
(argsort permutations, pre-clipped positions, cumsum offsets), so clip
mode is semantics-preserving and is the engine-wide default.
"""

from __future__ import annotations

import jax.numpy as jnp


def take_clip(arr, indices, *args, **kwargs):
    kwargs.setdefault("mode", "clip")
    return jnp.take(arr, indices, *args, **kwargs)
