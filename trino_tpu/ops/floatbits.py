"""TPU-safe float64 key decomposition.

This TPU backend's x64-demotion pass cannot compile
`bitcast_convert_type` involving 64-bit FLOATS (measured:
f64->u64 and f64->u32 both fail; f32->u32, i64<->u64, and u64
arithmetic all work, and `jnp.frexp` on f64 fails too because it
lowers through the same bitcast). Everything that needs "the bits of a
double" — hashing, order keys, the window min/max encodings, quantile
buckets — therefore goes through `f64_lanes`, which decomposes a
float64 into FOUR uint32 lanes using only f32 bitcasts and exact
power-of-two float arithmetic:

  lane1 = order-flipped bits of f32(x)        (coarse, order-preserving)
  lane2 = sign-adjusted range bucket k         (which 2^216 window)
  lane3 = order-flipped bits of f32(x*2^-216k) (fine, within-window)
  lane4 = exact residual of that rescale in 2^-30 ulp(f32) quanta

Properties: lexicographic (lane1..lane4) is a TOTAL ORDER of
float64 matching SQL semantics (-0.0 == +0.0, NaN canonical and
largest) and INJECTIVE over every normal double (subnormals are
flushed to zero by this backend — DAZ — so treating them as zero
matches what the engine's own comparisons do).

The residual math is exact, not approximate: x - f64(f32(x)) is a
multiple of ulp64(x) = 2^(e-52), bounded by ulp32(x)/2 = 2^(e-24), so
dividing by ulp32 (an exact power of two obtained from f32 nextafter)
yields a multiple of 2^-29 in [-1/2, 1/2] — scaling by 2^29 gives an
exact integer in [-2^28, 2^28].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# numpy on purpose: this module is imported lazily from inside traced
# code (ops/hashing), and a jnp constant created mid-trace would be a
# tracer pinned to that trace — poisoning every later retrace
_SIGN32 = np.uint32(0x80000000)


def _flip32(bits: jnp.ndarray) -> jnp.ndarray:
    """IEEE-754 bits -> unsigned order-preserving key (standard flip)."""
    neg = (bits & _SIGN32) != 0
    return jnp.where(neg, ~bits, bits | _SIGN32)


def _f32_lane(x32: jnp.ndarray) -> jnp.ndarray:
    return _flip32(jax.lax.bitcast_convert_type(x32, jnp.uint32))


def _resid_lane(x: jnp.ndarray, a32: jnp.ndarray) -> jnp.ndarray:
    """Exact within-f32-tie refinement for normal-range x: residual in
    2^-30 quanta (the extra bit covers binade-boundary rounding, where
    the residual is a multiple of HALF the regular quantum), offset to
    unsigned."""
    au = jnp.abs(a32)
    ulp = (jnp.nextafter(au, jnp.float32(jnp.inf)) - au).astype(jnp.float64)
    q = (x - a32.astype(jnp.float64)) / jnp.maximum(ulp, 1e-300)
    return ((q * float(1 << 30)).astype(jnp.int32)
            + jnp.int32(1 << 29)).astype(jnp.uint32)


def f64_lanes(x: jnp.ndarray):
    """float64 -> (lane1..lane4) uint32 tuple; see module doc.

    Range handling picks a per-element EXACT power-of-two rescale
    2^(-216k), k in [-5, 5], by direct threshold comparisons (windows of
    width 2^216 on a 2^216 step — no gaps, no iteration), bringing every
    nonzero normal double into [2^-90, 2^126), where f32(xs) cannot
    saturate AND ulp32(xs) is itself normal (DAZ-safe residuals). k rides as its own
    order lane (sign-adjusted: for negatives a larger magnitude is a
    SMALLER value). Subnormal doubles are zero on this backend (DAZ —
    its arithmetic and comparisons already treat them as 0), so the
    zero pin is consistent with engine semantics."""
    x = jnp.where(x == 0, jnp.float64(0.0), x)  # -0.0 == +0.0
    nan = jnp.isnan(x)
    zero = x == 0
    inf = jnp.isinf(x)

    # Window step 2^216 with windows [2^(216k-90), 2^(216(k+1)-90)):
    # every rescaled xs = m * 2^(-216k) lands in [2^-90, 2^126). Both
    # window edges matter (ADVICE r3, property-tested in
    # tests/test_floatbits.py):
    #  - top < f32_max: f32(xs) never saturates to inf, so distinct
    #    doubles above f32_max keep distinct refinement lanes;
    #  - bottom >= 2^-90: ulp32(xs) >= 2^-113 stays NORMAL — near the
    #    f32 min-normal, ulp32 is itself subnormal and this backend's
    #    DAZ flushes it to 0, zeroing the residual lane.
    # The rescale applies as TWO exact half-step power-of-two
    # multiplies (2^(216*5) overflows f64 as a single constant).
    m = jnp.abs(x)
    k = jnp.zeros(x.shape, jnp.int32)
    for j in range(1, 6):
        k = k + (m >= jnp.float64(2.0) ** (216 * j - 90)).astype(jnp.int32)
        k = k - (m < jnp.float64(2.0) ** (-216 * (j - 1) - 90)).astype(
            jnp.int32
        )
    half_scales = jnp.asarray(
        [jnp.float64(2.0) ** (-108 * kk) for kk in range(-5, 6)],
        dtype=jnp.float64,
    )
    s = jnp.take(half_scales, jnp.clip(k + 5, 0, 10))
    xs = (x * s) * s
    a = xs.astype(jnp.float32)

    lane1 = _f32_lane(x.astype(jnp.float32))
    # sign-adjusted range bucket: ascending in VALUE
    sb = jnp.where(x > 0, 8 + k, 8 - k).astype(jnp.uint32)
    lane2 = sb
    lane3 = _f32_lane(a)
    lane4 = _resid_lane(xs, a)

    for special in (zero, nan):
        lane2 = jnp.where(special, jnp.uint32(0), lane2)
        lane3 = jnp.where(special, jnp.uint32(0), lane3)
        lane4 = jnp.where(special, jnp.uint32(0), lane4)
    # +inf is the LARGEST member of its saturated-f32 class, -inf the
    # SMALLEST of its class — pin refinement lanes to the extremes
    hi = jnp.uint32(0xFFFFFFFF)
    pos_inf = inf & (x > 0)
    neg_inf = inf & (x < 0)
    lane2 = jnp.where(pos_inf, hi, jnp.where(neg_inf, jnp.uint32(0), lane2))
    lane3 = jnp.where(pos_inf, hi, jnp.where(neg_inf, jnp.uint32(0), lane3))
    lane4 = jnp.where(pos_inf, hi, jnp.where(neg_inf, jnp.uint32(0), lane4))
    return lane1, lane2, lane3, lane4


def f32_bits_ordered(x: jnp.ndarray) -> jnp.ndarray:
    """float32 -> order-preserving uint32 (f32 bitcasts are TPU-safe)."""
    return _f32_lane(x)
