"""Group-by hash kernels.

Analogue of Trino's GroupByHash (main/operator/GroupByHash.java:30;
MultiChannelGroupByHash.putIfAbsent:264 open-addressing linear probe) —
re-designed as a *vectorized, fixed-capacity* linear-probe table:

- Capacity is a power of two chosen by the host (bucketed), replacing
  tryRehash (MultiChannelGroupByHash.java:350) with
  rebuild-at-larger-capacity on overflow — static shapes for XLA.
- Insertion is data-parallel over all rows at once: each round, every
  unresolved row inspects its probe slot; empty slots are claimed by a
  min-row-id scatter race (one winner per slot per round, losers retry),
  occupied slots compare keys. Rounds loop via lax.while_loop. This is
  the standard way to express a concurrent hash-table insert as a
  sequence of dense vector ops — the whole batch makes progress each
  round instead of Trino's per-row scalar loop.
- SQL GROUP BY semantics: NULL is its own group, so validity bits are
  part of the key.

Aggregation itself is masked segment scatter-add/min/max into (C,)
accumulators — XLA turns these into efficient sorted-scatter updates.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from trino_tpu.ops.gather import take_clip
from trino_tpu.ops.hashing import hash32, hash64


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class GroupTable:
    """Fixed-capacity group table: slot i holds the keys of group id i."""

    slot_keys: List[jnp.ndarray]  # each (C,)
    slot_valids: List[jnp.ndarray]  # each (C,) bool
    slot_used: jnp.ndarray  # (C,) bool

    def tree_flatten(self):
        return (self.slot_keys, self.slot_valids, self.slot_used), (len(self.slot_keys),)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(list(children[0]), list(children[1]), children[2])

    @property
    def capacity(self) -> int:
        return int(self.slot_used.shape[0])

    def num_groups(self) -> jnp.ndarray:
        return jnp.sum(self.slot_used)


def _keys_equal(a_keys, a_valids, b_keys, b_valids):
    """GROUP-BY equality: NULL == NULL (IS NOT DISTINCT FROM)."""
    eq = None
    for ak, av, bk, bv in zip(a_keys, a_valids, b_keys, b_valids):
        e = ((ak == bk) & av & bv) | (~av & ~bv)
        eq = e if eq is None else (eq & e)
    return eq


def new_group_table(key_dtypes: Sequence, capacity: int) -> GroupTable:
    """Fresh empty table (host helper for streaming aggregation)."""
    assert capacity & (capacity - 1) == 0
    return GroupTable(
        [jnp.zeros(capacity, dtype=dt) for dt in key_dtypes],
        [jnp.zeros(capacity, dtype=jnp.bool_) for _ in key_dtypes],
        jnp.zeros(capacity, dtype=jnp.bool_),
    )


@jax.jit
def insert_group_ids(
    table: GroupTable,
    keys: Sequence[jnp.ndarray],
    valids: Sequence[jnp.ndarray],
    mask: jnp.ndarray,
):
    """Map each live row to a group id in [0, C), inserting new groups
    into `table` (streaming multi-batch form of assign_group_ids — the
    putIfAbsent analogue, MultiChannelGroupByHash.java:264).

    Returns (group_ids, table', overflowed). Dead rows get id = C
    (callers scatter with mode='drop'). `overflowed` is True if the
    table filled up — host rebuilds at 2x capacity (rehash analogue).
    """
    C = table.capacity
    n = keys[0].shape[0]
    keys = [k for k in keys]
    valids = [v for v in valids]

    h = (hash32(keys, valids) & jnp.uint32(C - 1)).astype(jnp.int32)

    slot_keys = list(table.slot_keys)
    slot_valids = list(table.slot_valids)
    slot_used = table.slot_used
    gid = jnp.where(mask, -1, C).astype(jnp.int32)
    probe = jnp.zeros(n, dtype=jnp.int32)
    row_id = jnp.arange(n, dtype=jnp.int32)

    def cond(state):
        gid, probe, slot_keys, slot_valids, slot_used, it = state
        return jnp.any(gid < 0) & (it < C + 2)

    def body(state):
        gid, probe, slot_keys, slot_valids, slot_used, it = state
        active = gid < 0
        pos = (h + probe) & (C - 1)
        occ = take_clip(slot_used, pos)
        slot_k = [take_clip(sk, pos) for sk in slot_keys]
        slot_v = [take_clip(sv, pos) for sv in slot_valids]
        match = occ & _keys_equal(slot_k, slot_v, keys, valids)
        gid = jnp.where(active & match, pos, gid)
        # claim race for empty slots: min row id wins the slot this round
        want = active & ~occ & ~match
        claim = jnp.full(C, n, dtype=jnp.int32)
        claim = claim.at[jnp.where(want, pos, C)].min(row_id, mode="drop")
        winner = want & (take_clip(claim, pos) == row_id)
        wpos = jnp.where(winner, pos, C)
        for i in range(len(keys)):
            slot_keys[i] = slot_keys[i].at[wpos].set(keys[i], mode="drop")
            slot_valids[i] = slot_valids[i].at[wpos].set(valids[i], mode="drop")
        slot_used = slot_used.at[wpos].set(True, mode="drop")
        gid = jnp.where(winner, pos, gid)
        # occupied-with-different-key rows advance; claim losers retry same slot
        advance = active & occ & ~match
        probe = jnp.where(advance, probe + 1, probe)
        return gid, probe, slot_keys, slot_valids, slot_used, it + 1

    gid, probe, slot_keys, slot_valids, slot_used, it = jax.lax.while_loop(
        cond, body, (gid, probe, slot_keys, slot_valids, slot_used, jnp.int32(0))
    )
    overflowed = jnp.any(gid < 0)
    gid = jnp.where(gid < 0, C, gid)
    return gid, GroupTable(slot_keys, slot_valids, slot_used), overflowed


@partial(jax.jit, static_argnames=("capacity",))
def assign_group_ids(
    keys: Sequence[jnp.ndarray],
    valids: Sequence[jnp.ndarray],
    mask: jnp.ndarray,
    capacity: int,
):
    """One-shot form: insert a single batch into a fresh table."""
    table = new_group_table([k.dtype for k in keys], capacity)
    return insert_group_ids(table, keys, valids, mask)


def grow_table(table: GroupTable, new_capacity: int):
    """Rebuild at a larger capacity — the tryRehash analogue
    (MultiChannelGroupByHash.java:350). Returns (new_table, remap) where
    remap[old_slot] = new group id (or new_capacity for unused slots) so
    callers migrate accumulator state with a scatter."""
    remap, table2, overflowed = insert_group_ids(
        new_group_table([k.dtype for k in table.slot_keys], new_capacity),
        table.slot_keys,
        table.slot_valids,
        table.slot_used,
    )
    assert not bool(overflowed)
    return table2, remap


# ---------------------------------------------------------------------------
# Masked segment accumulators — the Accumulator/GroupedAccumulator analogue
# (main/operator/aggregation/GroupedAccumulator.java:21). Each returns the
# new accumulator state array(s) of shape (C,).
# ---------------------------------------------------------------------------


def seg_sum(gid, values, weight_mask, capacity, dtype=None):
    dtype = dtype or values.dtype
    z = jnp.zeros(capacity + 1, dtype=dtype)
    contrib = jnp.where(weight_mask, values.astype(dtype), jnp.zeros((), dtype))
    return z.at[gid].add(contrib)[:capacity]


def seg_count(gid, weight_mask, capacity):
    z = jnp.zeros(capacity + 1, dtype=jnp.int64)
    return z.at[gid].add(weight_mask.astype(jnp.int64))[:capacity]


def seg_min(gid, values, weight_mask, capacity):
    info = jnp.iinfo(values.dtype) if jnp.issubdtype(values.dtype, jnp.integer) else None
    big = info.max if info else jnp.inf
    z = jnp.full(capacity + 1, big, dtype=values.dtype)
    contrib = jnp.where(weight_mask, values, jnp.asarray(big, dtype=values.dtype))
    return z.at[gid].min(contrib)[:capacity]


def seg_max(gid, values, weight_mask, capacity):
    info = jnp.iinfo(values.dtype) if jnp.issubdtype(values.dtype, jnp.integer) else None
    small = info.min if info else -jnp.inf
    z = jnp.full(capacity + 1, small, dtype=values.dtype)
    contrib = jnp.where(weight_mask, values, jnp.asarray(small, dtype=values.dtype))
    return z.at[gid].max(contrib)[:capacity]


def seg_any(gid, flags, weight_mask, capacity):
    z = jnp.zeros(capacity + 1, dtype=jnp.bool_)
    return z.at[gid].max(flags & weight_mask)[:capacity]


# ---------------------------------------------------------------------------
# Sort-based group-reduce — the TPU-native fast path.
#
# XLA lowers scatters to (near-)serial loops on TPU, so the linear-probe
# table above is only used where its streaming API is required (the
# mesh-exchange partial tables). The single-device aggregation hot path
# instead sorts rows by key (TPU sorts are fast), finds segment
# boundaries, and reduces segments with cumsum+gather (sums/counts) and
# segmented associative scans (min/max/first) — zero scatters end to end.
# Group ids come out dense [0, n_groups), which also makes the output
# batch compact for free.
# ---------------------------------------------------------------------------


def _order_seed(out_capacity: int) -> int:
    """Hash seed tied to the retry capacity: every overflow-doubling
    ALSO reseeds, so a detected 62-bit hash collision (p ~ 1e-7 per
    batch) cannot recur on the rerun."""
    return out_capacity.bit_length() * 0x9E37


_DEAD_ROW_HASH = jnp.iinfo(jnp.int64).max  # above every 62-bit hash


def _group_hash(keys, valids, mask, seed: int):
    """62-bit key-tuple hash (validity folded in: NULL == NULL groups),
    dead rows forced last."""
    if keys:
        h = hash64(list(keys), list(valids), seed=seed)
    else:
        h = jnp.zeros(mask.shape[0], dtype=jnp.int64)
    return jnp.where(mask, h, _DEAD_ROW_HASH)


def split_limb_keys(keys, valids):
    """Expand long-decimal (n, 2) limb-pair key columns into two int64
    key lanes (lax.sort operands must share one shape). EVERY grouping
    kernel normalizes through this before sorting/segmenting — pair
    equality == value equality, so grouping semantics are unchanged
    (Int128ArrayBlock keys, spi/block/Int128ArrayBlock.java)."""
    if not any(getattr(k, "ndim", 1) == 2 for k in keys):
        return tuple(keys), tuple(valids)
    nk, nv = [], []
    for k, v in zip(keys, valids):
        if getattr(k, "ndim", 1) == 2:
            nk.extend([k[:, 0], k[:, 1]])
            nv.extend([v, v])
        else:
            nk.append(k)
            nv.append(v)
    return tuple(nk), tuple(nv)


def _key_order(keys, valids, mask, order=None, seed: int = 0):
    """Stable permutation grouping equal key tuples (NULL == NULL),
    live rows first. MUST order groups exactly like sort_group_reduce
    so order-statistic kernels' slots align with its group slots:
    a single key sorts exactly by (liveness class, order-mapped key);
    several keys sort by the 62-bit tuple hash (collision probability
    ~1e-7 per 1M-row batch; sort_group_reduce DETECTS collisions via an
    independent stream and the reseeding retry re-runs the whole
    family). An incoming `order` acts as the least-significant
    pre-ordering (within-group value order for order statistics —
    stability preserves it)."""
    from trino_tpu.ops.sort import _order_value

    keys, valids = split_limb_keys(keys, valids)
    n = mask.shape[0]
    if order is None:
        order = jnp.arange(n, dtype=jnp.int32)
    if len(keys) == 1:
        k, v = keys[0], valids[0]
        if jnp.issubdtype(k.dtype, jnp.floating):
            kb = _order_value(
                jnp.where(k == 0, jnp.zeros((), k.dtype), k), False
            )
        else:
            kb = k
        kb = jnp.where(v & mask, kb, jnp.zeros((), kb.dtype))
        cls = jnp.where(mask, jnp.where(v, 0, 1), 2).astype(jnp.int8)
        order = take_clip(
            order, jnp.argsort(take_clip(kb, order), stable=True)
        )
        return take_clip(
            order, jnp.argsort(take_clip(cls, order), stable=True)
        )
    hm = _group_hash(keys, valids, mask, seed)
    return take_clip(
        order, jnp.argsort(take_clip(hm, order), stable=True)
    )


# (collision detection lives inline in sort_group_reduce: an
# independent 32-bit stream rides the sort and any in-run variation
# flags the overflow/reseed retry)



def _eq_vals(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Value equality for grouping: SQL groups NaNs together, but float
    == is false for NaN — make NaN equal NaN (floats only; cheap no-op
    for ints). Long-decimal limb pairs (n, 2) compare per row."""
    eq = a == b
    if jnp.issubdtype(a.dtype, jnp.floating):
        eq = eq | (jnp.isnan(a) & jnp.isnan(b))
    if getattr(eq, "ndim", 1) == 2:
        eq = eq.all(axis=-1)
    return eq

def _segment_bounds(sk, sv, sm, n, out_capacity):
    """Per-group segment geometry over key-sorted rows: boundary flags,
    compacted (starts, safe_starts, ends, used), n_groups, overflowed.
    Group ordering is the sorted key order — deterministic, so two
    passes over identically-sorted rows align slot for slot."""
    same = None
    for k, v in zip(sk, sv):
        prev_k = jnp.roll(k, 1)
        prev_v = jnp.roll(v, 1)
        eq = (_eq_vals(k, prev_k) & v & prev_v) | (~v & ~prev_v)
        same = eq if same is None else (same & eq)
    if same is None:  # no keys: single segment
        same = jnp.ones(n, dtype=jnp.bool_)
    first_row = jnp.arange(n) == 0
    prev_live = jnp.roll(sm, 1) & ~first_row
    boundary = sm & (first_row | ~same | ~prev_live)
    n_groups = jnp.sum(boundary.astype(jnp.int32)) if n else jnp.int32(0)
    overflowed = n_groups > out_capacity
    sidx = jnp.where(boundary, jnp.arange(n, dtype=jnp.int32), jnp.int32(n))
    starts = jnp.sort(sidx)[:out_capacity]
    if starts.shape[0] < out_capacity:
        # fewer rows than group slots: pad so every caller's group
        # arrays come out (out_capacity,) — an unpadded short array
        # misaligns against sort_group_reduce's padded key columns
        starts = jnp.pad(
            starts, (0, out_capacity - starts.shape[0]), constant_values=n
        )
    used = starts < n
    safe_starts = jnp.clip(starts, 0, max(n - 1, 0))
    next_starts = jnp.concatenate(
        [starts[1:], jnp.full((1,), n, dtype=starts.dtype)]
    )
    ends = jnp.clip(jnp.where(used, next_starts, 1) - 1, 0, max(n - 1, 0))
    return boundary, starts, safe_starts, ends, used, n_groups, overflowed


# NOTE on scans: multi-operand lax.associative_scan compiles
# pathologically on XLA:TPU at multi-million-element shapes (measured
# HANGING >400s where a full sort of the same array compiles in ~60s).
# Everything here therefore uses cumsum / scatter / gather / segment
# reduces, which compile flat regardless of length.


def _seg_id(boundary: jnp.ndarray) -> jnp.ndarray:
    """Per row: its segment ordinal (rows before the first boundary get
    -1; callers clip or mask)."""
    return jnp.cumsum(boundary.astype(jnp.int32)) - 1


def _seg_first(boundary: jnp.ndarray, vals: jnp.ndarray) -> jnp.ndarray:
    """Per row: vals at its segment's FIRST position (keep-first
    broadcast). Rows before the first boundary read segment 0's value."""
    n = boundary.shape[0]
    g = _seg_id(boundary)
    S = jnp.zeros(n, jnp.int32).at[
        jnp.where(boundary, g, n)
    ].set(jnp.arange(n, dtype=jnp.int32), mode="drop")
    return take_clip(vals, take_clip(S, g))


def _seg_reduce(red, contrib, boundary, num_segments: int):
    """Per-SEGMENT min/max reduction (not a running scan — the grouped
    consumers only read each segment's total). Returns an array indexed
    by segment ordinal, aligned with _segment_bounds' group slots. bool
    participates via an int32 round-trip (segment_min lacks bool)."""
    g = _seg_id(boundary)
    as_bool = contrib.dtype == jnp.bool_
    if as_bool:
        contrib = contrib.astype(jnp.int32)
    fn = jax.ops.segment_min if red == "min" else jax.ops.segment_max
    out = fn(contrib, g, num_segments=num_segments)
    return out.astype(jnp.bool_) if as_bool else out


def _dense_gid(keys, valids, mask, dims, radices):
    """Mixed-radix dense group id for plan-time-bounded key domains;
    NULL takes the extra digit d. Returns (gid, out_of_domain) where
    out_of_domain flags a live valid code outside [0, d) — the runtime
    dictionary outgrew the plan-time bound (fail-loud, same contract as
    sort_group_reduce's overflow flag)."""
    n = mask.shape[0]
    gid = jnp.zeros(n, dtype=jnp.int32)
    out_of_domain = jnp.asarray(False)
    for k, v, d, r in zip(keys, valids, dims, radices):
        raw = k.astype(jnp.int32)
        out_of_domain = out_of_domain | jnp.any(
            mask & v & ((raw < 0) | (raw >= d))
        )
        code = jnp.clip(raw, 0, d - 1)
        code = jnp.where(v, code, d)
        gid = gid * r + code
    return gid, out_of_domain


@partial(jax.jit, static_argnames=("dims", "reducers", "out_capacity"))
def mxu_group_reduce(
    keys: Sequence[jnp.ndarray],
    valids: Sequence[jnp.ndarray],
    mask: jnp.ndarray,
    values: Sequence[jnp.ndarray],
    value_valids: Sequence[Optional[jnp.ndarray]],
    reducers: tuple,
    dims: tuple,
    out_capacity: int,
):
    """dense_group_reduce contract, executed by the Pallas MXU one-hot
    contraction kernel (ops/mxu_groupby.py) — for bounded key domains in
    the band where the unrolled dense path explodes (one masked
    whole-column reduction per slot) but the domain still fits VMEM.
    Restrictions (caller gates): reducers in {sum, count}; integer-kind
    value dtypes (BIGINT/decimal-scaled/bool)."""
    from trino_tpu.ops.mxu_groupby import MAX_ROWS, grouped_sum_mxu

    assert all(r in ("sum", "count") for r in reducers), reducers
    if mask.shape[0] > MAX_ROWS:
        # per-tile int32 limb accumulators overflow past MAX_ROWS; the
        # sort path has no row bound
        return sort_group_reduce(
            tuple(keys), tuple(valids), mask, tuple(values),
            tuple(value_valids), reducers, out_capacity,
        )
    radices = tuple(d + 1 for d in dims)
    total = 1
    for r in radices:
        total *= r
    assert total <= out_capacity
    gid, out_of_domain = _dense_gid(keys, valids, mask, dims, radices)

    # per aggregate: a zero-masked value column plus ONE shared
    # valid-count column (for count reducers the count IS the value)
    cols = []
    col_of_value = []  # per aggregate: index of its value column
    col_of_count = []  # per aggregate: index of its count column
    for v, vv, red in zip(values, value_valids, reducers):
        w = mask if vv is None else (mask & vv)
        cnt_idx = len(cols)
        cols.append(w.astype(jnp.int64))
        col_of_count.append(cnt_idx)
        if red == "sum":
            col_of_value.append(len(cols))
            cols.append(jnp.where(w, v.astype(jnp.int64), 0))
        else:  # count: reuse the indicator column
            col_of_value.append(cnt_idx)
    interpret = jax.default_backend() != "tpu"
    sums = grouped_sum_mxu(gid, tuple(cols), mask, total, interpret=interpret)
    row_count = sums[-1]  # appended live-row count per slot

    def pad(x, fill=0):
        return jnp.pad(x, (0, out_capacity - total), constant_values=fill)

    # decode slot -> key codes/valids (mixed radix, last key fastest)
    slots = jnp.arange(total, dtype=jnp.int32)
    digits = []
    rem = slots
    for r in reversed(radices):
        digits.append(rem % r)
        rem = rem // r
    digits.reverse()
    group_keys = []
    group_valids = []
    for (k, d), digit in zip(zip(keys, dims), digits):
        group_keys.append(pad(jnp.clip(digit, 0, d - 1).astype(k.dtype)))
        group_valids.append(pad(digit < d, False))

    results = [pad(sums[i]) for i in col_of_value]
    counts = [pad(sums[i]) for i in col_of_count]
    used = pad(row_count > 0, False)
    n_groups = jnp.sum(used.astype(jnp.int32))
    return (
        group_keys,
        group_valids,
        used,
        results,
        counts,
        n_groups,
        out_of_domain,
    )


@partial(jax.jit, static_argnames=("dims", "reducers", "out_capacity"))
def dense_group_reduce(
    keys: Sequence[jnp.ndarray],
    valids: Sequence[jnp.ndarray],
    mask: jnp.ndarray,
    values: Sequence[jnp.ndarray],
    value_valids: Sequence[Optional[jnp.ndarray]],
    reducers: tuple,
    dims: tuple,  # per key: dictionary size (codes in [0, d)); NULL -> d
    out_capacity: int,
):
    """Group-reduce for PLAN-TIME-BOUNDED key domains (dictionary/bool
    codes): the group id is the dense mixed-radix composition of the
    codes — no sort, no hash table, no scatter. Each group reduces with
    a masked whole-column reduction; the per-group loop unrolls into one
    fused XLA program (total domain is capped small by the caller).
    Same output contract as sort_group_reduce; group ids are slot
    positions rather than dense-from-zero, which every consumer already
    handles via `used`."""
    n = mask.shape[0]
    radices = tuple(d + 1 for d in dims)  # one extra slot per key: NULL
    total = 1
    for r in radices:
        total *= r
    assert total <= out_capacity
    gid, out_of_domain = _dense_gid(keys, valids, mask, dims, radices)

    def pad(x, fill=0):
        return jnp.pad(x, (0, out_capacity - total), constant_values=fill)

    # decode slot -> key codes/valids (mixed radix, last key fastest)
    slots = jnp.arange(total, dtype=jnp.int32)
    digits = []
    rem = slots
    for r in reversed(radices):
        digits.append(rem % r)
        rem = rem // r
    digits.reverse()
    group_keys = []
    group_valids = []
    for (k, d), digit in zip(zip(keys, dims), digits):
        group_keys.append(pad(jnp.clip(digit, 0, d - 1).astype(k.dtype)))
        group_valids.append(pad(digit < d, False))

    results = []
    counts = []
    for v, vv, red in zip(values, value_valids, reducers):
        w = mask if vv is None else (mask & vv)
        outs = []
        cnts = []
        for g in range(total):
            sel = w & (gid == g)
            cnts.append(jnp.sum(sel.astype(jnp.int64)))
            if red in ("sum", "count"):
                acc_dt = (
                    jnp.float64
                    if jnp.issubdtype(v.dtype, jnp.floating)
                    else jnp.int64
                )
                contrib = (
                    sel.astype(jnp.int64)
                    if red == "count"
                    else jnp.where(sel, v.astype(acc_dt), jnp.zeros((), acc_dt))
                )
                outs.append(jnp.sum(contrib))
            elif red in ("min", "max"):
                if jnp.issubdtype(v.dtype, jnp.floating):
                    neutral = jnp.inf if red == "min" else -jnp.inf
                elif v.dtype == jnp.bool_:
                    neutral = red == "min"
                else:
                    info = jnp.iinfo(v.dtype)
                    neutral = info.max if red == "min" else info.min
                contrib = jnp.where(sel, v, jnp.asarray(neutral, v.dtype))
                outs.append(
                    jnp.min(contrib) if red == "min" else jnp.max(contrib)
                )
            else:
                raise ValueError(red)
        results.append(pad(jnp.stack(outs)))
        counts.append(pad(jnp.stack(cnts)))
    # used: any live row landed in the slot
    row_cnt = jnp.stack(
        [jnp.sum((mask & (gid == g)).astype(jnp.int32)) for g in range(total)]
    )
    used = pad(row_cnt > 0, False)
    n_groups = jnp.sum(used.astype(jnp.int32))
    return (
        group_keys,
        group_valids,
        used,
        results,
        counts,
        n_groups,
        out_of_domain,
    )


def _segment_geometry(boundary, n: int, out_capacity: int):
    """starts/safe_starts/ends/used/n_groups/overflow from boundary
    flags. Compaction of boundary positions uses top_k when the capacity
    is small relative to n (the common case — far cheaper than a second
    full sort), else a full sort."""
    n_groups = jnp.sum(boundary.astype(jnp.int32)) if n else jnp.int32(0)
    overflowed = n_groups > out_capacity
    sidx = jnp.where(boundary, jnp.arange(n, dtype=jnp.int32), jnp.int32(n))
    if out_capacity * 4 <= n:
        starts = -jax.lax.top_k(-sidx, out_capacity)[0]
    else:
        starts = jnp.sort(sidx)[:out_capacity]
        if starts.shape[0] < out_capacity:
            starts = jnp.pad(
                starts, (0, out_capacity - starts.shape[0]),
                constant_values=n,
            )
    used = starts < n
    safe_starts = jnp.clip(starts, 0, max(n - 1, 0))
    next_starts = jnp.concatenate(
        [starts[1:], jnp.full((1,), n, dtype=starts.dtype)]
    )
    ends = jnp.clip(jnp.where(used, next_starts, 1) - 1, 0, max(n - 1, 0))
    return starts, safe_starts, ends, used, n_groups, overflowed


# sorts with more operands than this gather their remaining payloads
# post-sort instead (XLA:TPU sort compile time grows ~linearly with
# operand count, ~7s each at 1M rows)
_MAX_SORT_OPERANDS = 10


def _fast_cumsum(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive scan via a (tiles, 256) two-level decomposition: the
    1-D lowering runs log2(n) full passes; the 2-D form does one short
    lane scan per tile plus a tiny inter-tile scan."""
    n = x.shape[0]
    tile = 256
    if n % tile:
        return jnp.cumsum(x)
    x2 = x.reshape(n // tile, tile)
    intra = jnp.cumsum(x2, axis=1)
    totals = intra[:, -1]
    offs = jnp.cumsum(totals) - totals
    return (intra + offs[:, None]).reshape(-1)


def _segment_sums_at(c: jnp.ndarray, ends, used):
    """Per-segment totals from an inclusive scan: segments tile the live
    prefix contiguously, so sum(g) = c[end_g] - c[end_{g-1}] — ONE
    capacity-sized gather + a shifted diff, instead of two gathers."""
    at_ends = jnp.where(used, take_clip(c, ends), jnp.zeros((), c.dtype))
    prev = jnp.concatenate([jnp.zeros(1, c.dtype), at_ends[:-1]])
    return jnp.where(used, at_ends - prev, jnp.zeros((), c.dtype))


@partial(jax.jit, static_argnames=("reducers", "out_capacity"))
def sort_group_reduce(
    keys: Sequence[jnp.ndarray],
    valids: Sequence[jnp.ndarray],
    mask: jnp.ndarray,
    values: Sequence[jnp.ndarray],
    value_valids: Sequence[Optional[jnp.ndarray]],
    reducers: tuple,  # per value: 'sum' | 'count' | 'min' | 'max' | 'first'
    out_capacity: int,
):
    """Group by `keys` and reduce each value column in one pass.

    Returns (group_keys, group_valids, used, results, counts, n_groups,
    overflowed): group arrays of shape (out_capacity,) dense from 0;
    `results[i]` is reducer i's per-group result; `counts[i]` the number
    of non-null contributions (for SQL empty-group NULL semantics).

    Engine hot path (GroupByHash analogue). ONE multi-operand lax.sort
    does all the data movement: the grouping key (exact (class, key)
    for a single key column; the 62-bit tuple hash for several) sorts
    value columns riding as payload operands, so per-column random
    gathers — ~10ms per 1M rows on TPU, the old design's dominant cost —
    disappear. Segment boundaries come from the sorted key itself, and
    boundary compaction uses top_k instead of a second full sort.
    """
    from trino_tpu.ops.sort import _order_value

    n = mask.shape[0]
    seed = _order_seed(out_capacity)
    iota = jnp.arange(n, dtype=jnp.int32)

    # long-decimal (n, 2) limb-pair keys split into two int64 key lanes
    # here (lax.sort operands must share one shape) and restack on
    # output, so every caller passes columns as-is (Int128ArrayBlock
    # keys group like any other type, spi/block/Int128ArrayBlock.java)
    key_lanes = [2 if getattr(k, "ndim", 1) == 2 else 1 for k in keys]
    keys, valids = split_limb_keys(keys, valids)

    single_key = len(keys) == 1
    if single_key:
        # exact: class (0 valid / 1 NULL / 2 dead) + order-mapped key
        # (-0.0 normalized to +0.0 first: SQL groups them together)
        k, v = keys[0], valids[0]
        if jnp.issubdtype(k.dtype, jnp.floating):
            kb = _order_value(
                jnp.where(k == 0, jnp.zeros((), k.dtype), k), False
            )
        else:
            kb = k
        kb = jnp.where(v & mask, kb, jnp.zeros((), kb.dtype))
        cls = jnp.where(mask, jnp.where(v, 0, 1), 2).astype(jnp.int8)
        sort_keys = (cls, kb)
        num_keys = 2
        extra = []
    else:
        # tuple hash; collisions detected via an independent 32-bit
        # stream riding as payload, resolved by the reseeding retry
        hm = _group_hash(keys, valids, mask, seed)
        sort_keys = (hm,)
        num_keys = 1
        extra = (
            [hash32(list(keys), list(valids), seed=seed + 0x7F4A)]
            if keys
            else []
        )

    # payload assembly: row ids, collision stream, then value columns
    # (+ their validity) until the operand budget forces gathers
    payloads: List[jnp.ndarray] = [iota] + extra
    carried: List[Optional[int]] = []  # per value: payload idx or None
    carried_vv: List[Optional[int]] = []
    for val, vv, red in zip(values, value_valids, reducers):
        vi = None
        if red != "count" and len(sort_keys) + len(payloads) < _MAX_SORT_OPERANDS:
            vi = len(payloads)
            payloads.append(val)
        carried.append(vi)
        wi = None
        if vv is not None and len(sort_keys) + len(payloads) < _MAX_SORT_OPERANDS:
            wi = len(payloads)
            payloads.append(vv)
        carried_vv.append(wi)
    # multi-key: group key columns ride too when budget allows, so the
    # output extraction reads sorted data at `starts` (one cap-sized
    # gather) instead of chaining through the row permutation (two)
    carried_keys: List[Optional[int]] = []
    carried_kv: List[Optional[int]] = []
    if not single_key:
        for k, v in zip(keys, valids):
            ki = None
            if len(sort_keys) + len(payloads) < _MAX_SORT_OPERANDS:
                ki = len(payloads)
                payloads.append(k)
            carried_keys.append(ki)
            kvi = None
            if len(sort_keys) + len(payloads) < _MAX_SORT_OPERANDS:
                kvi = len(payloads)
                payloads.append(v)
            carried_kv.append(kvi)

    sorted_ops = jax.lax.sort(
        sort_keys + tuple(payloads), num_keys=num_keys, is_stable=False
    )
    order = sorted_ops[num_keys]

    first = iota == 0
    if single_key:
        s_cls, s_kb = sorted_ops[0], sorted_ops[1]
        sm = s_cls < 2
        changed = (s_cls != jnp.roll(s_cls, 1)) | ~_eq_vals(
            s_kb, jnp.roll(s_kb, 1)
        )
        boundary = sm & (first | changed)
        collision = jnp.asarray(False)
    else:
        hs = sorted_ops[0]
        sm = hs != _DEAD_ROW_HASH
        boundary = sm & (first | (hs != jnp.roll(hs, 1)))
        if extra:
            # rows of one segment are adjacent after the sort, so "some
            # row's independent stream differs from its segment's" ⟺
            # "some adjacent pair inside a segment differs" — a dense
            # roll+compare instead of _seg_first's scatter+gather
            # (scatters cost ~117ms/M on this TPU)
            h2s = sorted_ops[num_keys + 1]
            collision = jnp.any(
                sm & ~boundary & (h2s != jnp.roll(h2s, 1))
            )
        else:
            collision = jnp.asarray(False)

    def sorted_payload(idx, col):
        if idx is not None:
            return sorted_ops[num_keys + idx]
        return take_clip(col, order)

    # -- boundary compaction + per-group extraction ------------------
    # Large group counts (cap*4 > n) use CARRIED compaction: the
    # boundary-position sort carries every per-group output value as a
    # payload operand, so the cap-sized gathers of the gather path
    # (~16.5ms per 1M gathered elements on this TPU — they dominated
    # Q18's 1.5M-group aggregation) disappear. Segment sums ride as
    # exclusive prefix sums whose shifted diff is the per-group total;
    # non-boundary filler entries carry the grand total so the last
    # group's diff closes correctly. Small caps keep the top_k + tiny
    # gather path (a full multi-operand n-sort would cost more).
    big_cap = out_capacity * 4 > n > 0
    iota32 = jnp.arange(n, dtype=jnp.int32)
    sidx = jnp.where(boundary, iota32, jnp.int32(n))

    carry_cols: List[jnp.ndarray] = []
    carry_totals: dict = {}

    def carry(arr):
        if arr.dtype == jnp.bool_:
            arr = arr.astype(jnp.int8)
        carry_cols.append(arr)
        return len(carry_cols) - 1

    def excl_carry(contrib):
        """Exclusive cumsum at boundaries, grand total elsewhere."""
        c = _fast_cumsum(contrib)
        total = c[-1] if n else jnp.zeros((), contrib.dtype)
        slot = carry(jnp.where(boundary, c - contrib, total))
        carry_totals[slot] = total
        return slot

    plan: dict = {}
    if big_cap:
        if single_key:
            plan["kb"] = carry(sorted_ops[1])
            plan["cls"] = carry(sorted_ops[0].astype(jnp.int32))
        else:
            plan["mk"] = []
            for i, (k, v) in enumerate(zip(keys, valids)):
                plan["mk"].append((
                    carry(sorted_payload(carried_keys[i], k)),
                    carry(sorted_payload(carried_kv[i], v)),
                ))
        plan["rows"] = excl_carry(sm.astype(jnp.int64))
        plan["vals"] = []
        for i, (val, vv, red) in enumerate(
            zip(values, value_valids, reducers)
        ):
            svv = None if vv is None else sorted_payload(carried_vv[i], vv)
            w = sm if svv is None else (sm & svv)
            cnt_slot = None if svv is None else excl_carry(w.astype(jnp.int64))
            sum_slot = None
            if red == "sum":
                sv_ = sorted_payload(carried[i], val)
                acc_dt = (
                    jnp.float64
                    if jnp.issubdtype(sv_.dtype, jnp.floating)
                    else jnp.int64
                )
                contrib = jnp.where(
                    w, sv_.astype(acc_dt), jnp.zeros((), acc_dt)
                )
                sum_slot = excl_carry(contrib)
            plan["vals"].append((cnt_slot, sum_slot))
        # compaction sorts share the boundary-position key; payloads
        # chunk under the operand budget (compile time grows with
        # operand count)
        comp: List[jnp.ndarray] = []
        starts_full = None
        budget = _MAX_SORT_OPERANDS - 1
        for c0 in range(0, len(carry_cols), budget):
            chunk = carry_cols[c0 : c0 + budget]
            out = jax.lax.sort(tuple([sidx] + chunk), num_keys=1)
            starts_full = out[0]
            comp.extend(out[1:])
        starts = starts_full[:out_capacity]
        if starts.shape[0] < out_capacity:
            starts = jnp.pad(
                starts, (0, out_capacity - starts.shape[0]),
                constant_values=n,
            )
        comp = [c[:out_capacity] for c in comp]
        used = starts < n
        safe_starts = jnp.clip(starts, 0, max(n - 1, 0))
        next_starts = jnp.concatenate(
            [starts[1:], jnp.full((1,), n, dtype=starts.dtype)]
        )
        ends = jnp.clip(jnp.where(used, next_starts, 1) - 1, 0, max(n - 1, 0))
        n_groups = jnp.sum(boundary.astype(jnp.int32)) if n else jnp.int32(0)
        overflowed = (n_groups > out_capacity) | collision

        def pad_slot(slot, fill=0):
            c = comp[slot]
            if c.shape[0] < out_capacity:
                c = jnp.pad(c, (0, out_capacity - c.shape[0]))
                c = jnp.where(
                    jnp.arange(out_capacity) < comp[slot].shape[0],
                    c, jnp.asarray(fill, c.dtype),
                )
            return c

        def seg_total(slot):
            total = carry_totals[slot]
            e = pad_slot(slot, fill=total)
            nxt = jnp.concatenate([e[1:], total[None]])
            # unused slots carry the grand total (the filler), so the
            # last used group's diff reads total - its prefix
            return jnp.where(used, nxt - e, jnp.zeros((), e.dtype))

        if single_key:
            kvals = pad_slot(plan["kb"])
            if jnp.issubdtype(keys[0].dtype, jnp.floating):
                # the carried operand holds order-mapped BITS; recover
                # through the row permutation (cap-sized, rare path)
                kvals = take_clip(keys[0], take_clip(order, safe_starts))
            group_keys = [
                jnp.where(
                    used, kvals.astype(keys[0].dtype),
                    jnp.zeros((), keys[0].dtype),
                )
            ]
            group_valids = [(pad_slot(plan["cls"], fill=2) == 0) & used]
        else:
            group_keys = []
            group_valids = []
            for i, (k, v) in enumerate(zip(keys, valids)):
                ks, vs_ = plan["mk"][i]
                group_keys.append(
                    jnp.where(
                        used, pad_slot(ks).astype(k.dtype),
                        jnp.zeros((), k.dtype),
                    )
                )
                group_valids.append((pad_slot(vs_) != 0) & used)
        seg_rows = seg_total(plan["rows"])
    else:
        starts, safe_starts, ends, used, n_groups, overflowed = (
            _segment_geometry(boundary, n, out_capacity)
        )
        overflowed = overflowed | collision

        # group key columns: read the SORTED key at each segment start —
        # one capacity-sized gather per column, no permutation chase
        if single_key:
            if jnp.issubdtype(keys[0].dtype, jnp.floating):
                # the sorted operand holds order-mapped BITS; recover the
                # float through the row permutation instead
                kvals = take_clip(keys[0], take_clip(order, safe_starts))
            else:
                kvals = take_clip(sorted_ops[1], safe_starts)
            group_keys = [
                jnp.where(used, kvals, jnp.zeros((), keys[0].dtype))
            ]
            group_valids = [
                (take_clip(sorted_ops[0], safe_starts) == 0) & used
            ]
        else:
            group_keys = []
            group_valids = []
            for i, (k, v) in enumerate(zip(keys, valids)):
                sk_full = sorted_payload(carried_keys[i], k)
                sv_full = sorted_payload(carried_kv[i], v)
                group_keys.append(
                    jnp.where(
                        used, take_clip(sk_full, safe_starts),
                        jnp.zeros((), k.dtype),
                    )
                )
                group_valids.append(take_clip(sv_full, safe_starts) & used)

        # per-segment live-row count straight from the geometry (no
        # scan); the LAST segment's `ends` extends to n-1 past the dead
        # tail, so clamp to the final live row
        n_live = jnp.sum(sm.astype(jnp.int32))
        seg_rows = jnp.where(
            used,
            (jnp.minimum(ends, n_live - 1) - safe_starts + 1).astype(jnp.int64),
            0,
        )

    results = []
    counts = []
    for i, (val, vv, red) in enumerate(zip(values, value_valids, reducers)):
        svv = None if vv is None else sorted_payload(carried_vv[i], vv)
        sv_ = (
            sorted_payload(carried[i], val)
            if red != "count"
            else jnp.zeros(n, dtype=jnp.int64)
        )
        w = sm if svv is None else (sm & svv)
        if svv is None:
            cnt = seg_rows
        elif big_cap:
            cnt = seg_total(plan["vals"][i][0])
        else:
            cnt = _segment_sums_at(
                _fast_cumsum(w.astype(jnp.int64)), ends, used
            )
        counts.append(cnt)
        if red in ("sum", "count"):
            if red == "count":
                out = cnt
                results.append(out)
                continue
            if big_cap:
                out = seg_total(plan["vals"][i][1])
                results.append(out)
                continue
            acc_dt = (
                jnp.float64
                if jnp.issubdtype(sv_.dtype, jnp.floating)
                else jnp.int64
            )
            contrib = jnp.where(w, sv_.astype(acc_dt), jnp.zeros((), acc_dt))
            out = _segment_sums_at(_fast_cumsum(contrib), ends, used)
        elif red in ("min", "max"):
            if jnp.issubdtype(sv_.dtype, jnp.floating):
                neutral = jnp.inf if red == "min" else -jnp.inf
            elif sv_.dtype == jnp.bool_:
                neutral = red == "min"
            else:
                info = jnp.iinfo(sv_.dtype)
                neutral = info.max if red == "min" else info.min
            contrib = jnp.where(w, sv_, jnp.asarray(neutral, dtype=sv_.dtype))
            out = _seg_reduce(
                "min" if red == "min" else "max",
                contrib, boundary, ends.shape[0],
            )
        elif red in ("min128h", "max128h"):
            # Int128 extreme, high limb: plain signed min/max. The LOW
            # limb rides the NEXT slot with the matching *128l reducer.
            base = red[:3]
            info = jnp.iinfo(jnp.int64)
            neutral = info.max if base == "min" else info.min
            contrib = jnp.where(w, sv_, jnp.asarray(neutral, jnp.int64))
            out = _seg_reduce(base, contrib, boundary, ends.shape[0])
        elif red in ("min128l", "max128l"):
            # Int128 extreme, low limb: unsigned min/max among rows
            # whose high limb equals the group's extreme (lexicographic
            # (hi, lo-as-u64) = Int128 order; Int128Math.compare). The
            # matching *128h slot precedes this one, though not always
            # adjacently (state merges interleave count slots).
            base = red[:3]
            hi_idx = max(
                j for j in range(i) if reducers[j] == f"{base}128h"
            )
            s_hi = sorted_payload(carried[hi_idx], values[hi_idx])
            hi_grp = results[hi_idx]
            g = jnp.clip(_seg_id(boundary), 0, ends.shape[0] - 1)
            hi_row = take_clip(hi_grp, g)
            sgn = jnp.int64(-0x8000000000000000)
            info = jnp.iinfo(jnp.int64)
            neutral = info.max if base == "min" else info.min
            sel = w & (s_hi == hi_row)
            contrib = jnp.where(sel, sv_ ^ sgn, jnp.asarray(neutral, jnp.int64))
            out = _seg_reduce(base, contrib, boundary, ends.shape[0]) ^ sgn
        elif red == "first":
            # first non-null value per segment: the smallest row index
            # whose value is non-null, then one gather
            pos = jax.ops.segment_min(
                jnp.where(w, jnp.arange(n, dtype=jnp.int32), jnp.int32(n)),
                _seg_id(boundary),
                num_segments=ends.shape[0],
            )
            out = take_clip(sv_, pos)
        else:
            raise ValueError(red)
        results.append(out)
    if any(l == 2 for l in key_lanes):
        gk2, gv2 = [], []
        i = 0
        for l in key_lanes:
            if l == 2:
                gk2.append(
                    jnp.stack([group_keys[i], group_keys[i + 1]], axis=-1)
                )
                gv2.append(group_valids[i])
            else:
                gk2.append(group_keys[i])
                gv2.append(group_valids[i])
            i += l
        group_keys, group_valids = gk2, gv2
    return group_keys, group_valids, used, results, counts, n_groups, overflowed


# ---------------------------------------------------------------------------
# Holistic (order-statistic) grouped aggregates — min_by/max_by and
# approx_percentile need the raw rows, not mergeable accumulators
# (Trino's MinMaxByNStateFactory / qdigest aggregations). The planner
# runs them single-step after a gather; these kernels share the key
# sort + segment geometry with sort_group_reduce, so their per-slot
# outputs align with its group ordering exactly.
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("out_capacity",))
def key_order(keys, valids, mask, out_capacity: int = 0):
    """Jitted public form of the grouping sort permutation, so callers
    computing several order statistics over the same keys sort ONCE and
    pass the permutation into each kernel. `out_capacity` must match the
    capacity passed to the kernels sharing this order (it seeds the
    group hash, and slot alignment requires one ordering). Long-decimal
    (n, 2) keys split into limb lanes like sort_group_reduce."""
    return _key_order(keys, valids, mask, seed=_order_seed(out_capacity))


@partial(jax.jit, static_argnames=("kind", "out_capacity"))
def grouped_argbest(
    keys, valids, mask, by, by_valid, x, x_valid, kind: str,
    out_capacity: int, order=None,
):
    """min_by/max_by: x at the row with the smallest/largest `by` per
    group (rows with NULL `by` are ignored; ties keep the first row in
    sort order — Trino returns an arbitrary one). Returns
    (x_data, x_valid) aligned with sort_group_reduce's group slots.
    Long-decimal (n, 2) group keys, `by`, and `x` columns all
    supported (keys split into limb lanes; Int128 `by` reduces
    lexicographically; `x` gathers row-wise)."""
    n = mask.shape[0]
    keys, valids = split_limb_keys(keys, valids)
    if order is None:
        order = _key_order(
            keys, valids, mask, seed=_order_seed(out_capacity)
        )
    sm = take_clip(mask, order)
    sk = [take_clip(k, order) for k in keys]
    sv = [take_clip(v, order) for v in valids]
    boundary, starts, safe_starts, ends, used, _, _ = _segment_bounds(
        sk, sv, sm, n, out_capacity
    )
    w = sm if by_valid is None else (sm & take_clip(by_valid, order))
    s_x = take_clip(x, order, axis=0)
    s_xv = (
        jnp.ones(n, dtype=jnp.bool_)
        if x_valid is None
        else take_clip(x_valid, order)
    )
    # two segment reduces + gathers instead of a 5-operand associative
    # scan (see the scan NOTE above): (1) the best `by` per segment,
    # (2) the FIRST row attaining it (ties keep first in sort order).
    # NaN `by` values diverge from the old scan (NaN poisons the
    # reduce -> NULL result, where the scan kept the first valid row);
    # SQL comparison keys are NaN-free in practice.
    cap = ends.shape[0]
    g = _seg_id(boundary)
    red = "min" if kind == "min_by" else "max"
    if getattr(by, "ndim", 1) == 2:
        # Int128 `by`: lexicographic (signed hi, unsigned lo) best
        s_bh = take_clip(by[:, 0], order)
        s_bl = take_clip(by[:, 1], order)
        sgn = jnp.int64(-0x8000000000000000)
        info = jnp.iinfo(jnp.int64)
        neutral = info.max if kind == "min_by" else info.min
        nbh = jnp.where(w, s_bh, jnp.asarray(neutral, jnp.int64))
        best_h = _seg_reduce(red, nbh, boundary, cap)
        at_h = w & (s_bh == take_clip(best_h, g))
        lo_u = s_bl ^ sgn
        nbl = jnp.where(at_h, lo_u, jnp.asarray(neutral, jnp.int64))
        best_l = _seg_reduce(red, nbl, boundary, cap)
        is_best = at_h & (lo_u == take_clip(best_l, g))
    else:
        s_by = take_clip(by, order)
        if jnp.issubdtype(s_by.dtype, jnp.floating):
            neutral = jnp.inf if kind == "min_by" else -jnp.inf
        elif s_by.dtype == jnp.bool_:
            neutral = kind == "min_by"
        else:
            info = jnp.iinfo(s_by.dtype)
            neutral = info.max if kind == "min_by" else info.min
        nb = jnp.where(w, s_by, jnp.asarray(neutral, s_by.dtype))
        best = _seg_reduce(red, nb, boundary, cap)
        is_best = w & (nb == take_clip(best, g))
    pos = jax.ops.segment_min(
        jnp.where(is_best, jnp.arange(n, dtype=jnp.int32), jnp.int32(n)),
        g, num_segments=cap,
    )
    has = pos < n
    out_x = take_clip(s_x, pos, axis=0)
    out_valid = has & take_clip(s_xv, pos) & used
    used_b = used[:, None] if getattr(out_x, "ndim", 1) == 2 else used
    return jnp.where(used_b, out_x, jnp.zeros((), out_x.dtype)), out_valid


@partial(jax.jit, static_argnames=("fraction", "out_capacity"))
def grouped_weighted_percentile(
    keys, valids, mask, mn, mn_valid, cnt, mx,
    fraction: float, out_capacity: int,
):
    """Percentile over per-BUCKET summaries (count, min, max) — the
    merge half of the mergeable approx_percentile
    (sql/optimizer.RewriteApproxPercentile): rows are quantile-bucket
    summaries, weights are exact element counts, and the estimate
    interpolates between the chosen bucket's min and max. Exact when
    the bucket holds one distinct value. Returns (data, valid) aligned
    with sort_group_reduce's group slots."""
    from trino_tpu.ops.sort import _order_value

    n = mask.shape[0]
    mv = jnp.ones(n, jnp.bool_) if mn_valid is None else mn_valid
    # pre-order: bucket min ascending (bucket ids are order-preserving,
    # so min-order == bucket order); invalid rows last
    pre = jnp.argsort(_order_value(mn, False), stable=True).astype(jnp.int32)
    pre = take_clip(pre, jnp.argsort(take_clip(~mv, pre), stable=True))
    keys, valids = split_limb_keys(keys, valids)
    order = _key_order(
        keys, valids, mask, order=pre, seed=_order_seed(out_capacity)
    )
    sm = take_clip(mask, order)
    sk = [take_clip(k, order) for k in keys]
    sv = [take_clip(v, order) for v in valids]
    boundary, starts, safe_starts, ends, used, _, _ = _segment_bounds(
        sk, sv, sm, n, out_capacity
    )
    w = sm & take_clip(mv, order)
    s_mn = take_clip(mn, order)
    s_mx = take_clip(mx, order)
    s_c = jnp.where(w, take_clip(cnt, order).astype(jnp.int64), 0)
    cum = jnp.cumsum(s_c)
    cum_ex = cum - s_c
    # per segment: total weight N, target rank R = floor(f*(N-1)+0.5)
    N = take_clip(cum, ends) - take_clip(cum_ex, safe_starts)
    R = jnp.clip(
        jnp.floor(fraction * (N - 1).astype(jnp.float64) + 0.5)
        .astype(jnp.int64),
        0, jnp.maximum(N - 1, 0),
    )
    g = _seg_id(boundary)
    base = take_clip(cum_ex, safe_starts)  # per-slot segment weight offset
    cum_in = cum - take_clip(base, g)  # within-segment inclusive weight
    R_row = take_clip(R, g)
    hit = w & (cum_in > R_row)
    pos = jax.ops.segment_min(
        jnp.where(hit, jnp.arange(n, dtype=jnp.int32), jnp.int32(n)),
        g, num_segments=ends.shape[0],
    )
    safe_pos = jnp.clip(pos, 0, max(n - 1, 0))
    c_at = jnp.maximum(take_clip(s_c, safe_pos), 1)
    p_in = R - (take_clip(cum_in, safe_pos) - c_at)
    lo_v = take_clip(s_mn, safe_pos)
    hi_v = take_clip(s_mx, safe_pos)
    frac_in = jnp.where(
        c_at > 1,
        p_in.astype(jnp.float64) / (c_at - 1).astype(jnp.float64),
        0.0,
    )
    est = lo_v.astype(jnp.float64) + (
        hi_v.astype(jnp.float64) - lo_v.astype(jnp.float64)
    ) * frac_in
    if jnp.issubdtype(mn.dtype, jnp.floating):
        out = est.astype(mn.dtype)
    else:
        out = (jnp.sign(est) * jnp.floor(jnp.abs(est) + 0.5)).astype(mn.dtype)
    valid = used & (N > 0) & (pos < n)
    return jnp.where(valid, out, jnp.zeros((), out.dtype)), valid


@partial(jax.jit, static_argnames=("fraction", "out_capacity"))
def grouped_percentile(
    keys, valids, mask, x, x_valid, fraction: float, out_capacity: int,
):
    """approx_percentile(x, fraction) per group, computed EXACTLY by
    nearest-rank over the sorted segment (exact answers satisfy the
    approximate contract; the reference's qdigest sketch trades
    accuracy for mergeability we don't need single-step). NULL x rows
    are excluded. Returns (data, valid) aligned with
    sort_group_reduce's group slots."""
    from trino_tpu.ops.sort import _order_value

    n = mask.shape[0]
    xv = (
        jnp.ones(n, dtype=jnp.bool_) if x_valid is None else x_valid
    )
    # pre-order: x ascending, NULL x last within each group
    pre = jnp.argsort(_order_value(x, False), stable=True).astype(jnp.int32)
    pre = take_clip(pre, jnp.argsort(take_clip(~xv, pre), stable=True))
    keys, valids = split_limb_keys(keys, valids)
    order = _key_order(
        keys, valids, mask, order=pre, seed=_order_seed(out_capacity)
    )
    sm = take_clip(mask, order)
    sk = [take_clip(k, order) for k in keys]
    sv = [take_clip(v, order) for v in valids]
    boundary, starts, safe_starts, ends, used, _, _ = _segment_bounds(
        sk, sv, sm, n, out_capacity
    )
    w = sm & take_clip(xv, order)
    s_x = take_clip(x, order)
    cnt_c = jnp.cumsum(w.astype(jnp.int64))
    cnt_ex = cnt_c - w.astype(jnp.int64)
    cnt = take_clip(cnt_c, ends) - take_clip(cnt_ex, safe_starts)
    # nearest rank: index floor(fraction * (cnt-1) + 0.5) into the
    # valid prefix of the segment (invalid rows sorted to its tail)
    rank = jnp.floor(
        fraction * (cnt - 1).astype(jnp.float64) + 0.5
    ).astype(jnp.int64)
    rank = jnp.clip(rank, 0, jnp.maximum(cnt - 1, 0))
    idx = jnp.clip(
        safe_starts.astype(jnp.int64) + rank, 0, max(n - 1, 0)
    ).astype(jnp.int32)
    out = take_clip(s_x, idx)
    valid = used & (cnt > 0)
    return jnp.where(valid, out, jnp.zeros((), out.dtype)), valid


@partial(jax.jit, static_argnames=("out_capacity",))
def grouped_count_distinct(keys, valids, mask, x, x_valid, out_capacity):
    """Distinct non-NULL x per group (approx_distinct's contract with
    error 0 — exact answers satisfy the approximate bound; the
    mergeable HLL sketch is planned work). Rows pre-order by (valid x
    first, x ascending) so equal values sit adjacent within each group;
    a distinct value = a valid row at a group boundary or where x
    changes. Slots align with sort_group_reduce's group ordering."""
    from trino_tpu.ops.sort import _order_value

    n = mask.shape[0]
    xv = jnp.ones(n, dtype=jnp.bool_) if x_valid is None else x_valid
    xb = (
        _order_value(x, False)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x
    )
    pre = jnp.argsort(xb, stable=True).astype(jnp.int32)
    pre = take_clip(pre, jnp.argsort(take_clip(~xv, pre), stable=True))
    keys, valids = split_limb_keys(keys, valids)
    order = _key_order(
        keys, valids, mask, order=pre, seed=_order_seed(out_capacity)
    )
    sm = take_clip(mask, order)
    sk = [take_clip(k, order) for k in keys]
    sv = [take_clip(v, order) for v in valids]
    boundary, starts, safe_starts, ends, used, _, _ = _segment_bounds(
        sk, sv, sm, n, out_capacity
    )
    sx = take_clip(xb, order)
    sxv = take_clip(xv, order) & sm
    first = jnp.arange(n) == 0
    flag = sxv & (boundary | first | ~_eq_vals(sx, jnp.roll(sx, 1)))
    c = jnp.cumsum(flag.astype(jnp.int64))
    cnt = take_clip(c, ends) - take_clip(c - flag.astype(jnp.int64), safe_starts)
    return jnp.where(used, cnt, 0)


@partial(jax.jit, static_argnames=("out_capacity",))
@partial(jax.jit, static_argnames=("out_capacity",))
def grouped_rows_order(keys, valids, mask, x, x_valid, out_capacity):
    """Rows grouped and value-ordered for HOST-side assembly, returned
    as a row ORDER so the assembler (array_agg, map_agg, histogram —
    the collect-path aggregates) can gather ANY number of argument
    columns into the same group-contiguous, value-ordered layout.
    Returns (dense_gid_per_sorted_row, group_live, order, n_groups,
    overflowed); dense gids index sort_group_reduce's compacted slots
    1:1 (same sort chain, same segment ordering)."""
    n = mask.shape[0]
    xv = jnp.ones(n, dtype=jnp.bool_) if x_valid is None else x_valid
    from trino_tpu.ops.sort import _order_value

    pre = jnp.argsort(_order_value(x, False), stable=True).astype(jnp.int32)
    pre = take_clip(pre, jnp.argsort(take_clip(~xv, pre), stable=True))
    seed = _order_seed(out_capacity)
    keys, valids = split_limb_keys(keys, valids)
    order = _key_order(keys, valids, mask, order=pre, seed=seed)
    sm = take_clip(mask, order)
    sk = [take_clip(k, order) for k in keys]
    sv = [take_clip(v, order) for v in valids]
    # no collision overlay here: the caller (_finish_holistic) settles
    # capacity/seed through sort_group_reduce's detector over the SAME
    # keys and seed first, which flags exactly the collisions this
    # ordering could have
    boundary, starts, safe_starts, ends, used, n_groups, overflowed = (
        _segment_bounds(sk, sv, sm, n, out_capacity)
    )
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    return gid, sm, order, n_groups, overflowed


@partial(jax.jit, static_argnames=("out_capacity",))
def grouped_rows_sorted(keys, valids, mask, x, x_valid, out_capacity):
    """grouped_rows_order with the value column pre-gathered (listagg:
    building new strings is host work by nature — Trino's
    ListaggAggregationFunction builds its VARCHAR on the heap too).
    Returns (dense_gid_per_sorted_row, weight, sorted_x, n_groups,
    overflowed)."""
    gid, sm, order, n_groups, overflowed = grouped_rows_order(
        keys, valids, mask, x, x_valid, out_capacity
    )
    n = mask.shape[0]
    xv = jnp.ones(n, dtype=jnp.bool_) if x_valid is None else x_valid
    w = sm & take_clip(xv, order)
    return gid, w, take_clip(x, order), n_groups, overflowed
