"""Time-zone database + device kernels for TIMESTAMP WITH TIME ZONE.

Reference surface: spi/type/TimeZoneKey.java (zone-name registry keyed
by a small integer), io/trino/spi/type/DateTimeEncoding.java (the short
timestamp-with-time-zone packing: instant millis << 12 | zoneKey, 12
bits of zone id), and main/type/DateTimes.java (zone-offset math).

TPU-first layout: ONE int64 column per tstz value — the SAME packing
as the reference's short encoding, chosen because the instant occupies
the HIGH bits, so plain int64 ordering orders by instant first (sorts,
group-bys, joins and range filters run the ordinary integer kernels
with zero unpacking). Zone rules become per-zone sorted transition
tables; the offset at an instant is one `searchsorted` + `take` on
device — no per-row host callbacks, no data-dependent control flow.

COMPARISONS (=, <, BETWEEN, IN, IS DISTINCT) strip the zone bits and
compare instants only — Trino semantics. The KEY paths agree: the
planner's canonicalize_tstz_keys pass (sql/optimizer.py) rewrites
GROUP BY / JOIN / DISTINCT over tstz to key on a zone-masked copy
(an any() aggregate preserves one original packed value per group as
the rendered representative), and exchange hash partitioning masks the
zone bits before hashing (exec/exchange_ops.py) — so equal instants in
different zones group, join, and co-partition together, matching the
reference's keying on getEpochMillis().

The zone registry is deterministic: UTC = 0; fixed offsets ±14:00 map
minutes -840..840 onto ids 1..1681; IANA names (sorted) start at 1800.
Rules parse from the host's TZif files (zoneinfo.TZPATH — binary
parse, no zoneinfo-internals dependency) and are cached per zone.
"""

from __future__ import annotations

import functools
import os
import struct
from typing import Optional, Tuple

import numpy as np

MILLIS_SHIFT = 12
ZONE_MASK = (1 << MILLIS_SHIFT) - 1

_FIXED_BASE = 841  # id = _FIXED_BASE + offset_minutes (-840..840 -> 1..1681)
_NAMED_BASE = 1800

UTC_ID = 0


@functools.lru_cache(maxsize=1)
def _named_zones() -> Tuple[str, ...]:
    import zoneinfo

    return tuple(sorted(zoneinfo.available_timezones()))


@functools.lru_cache(maxsize=1)
def _named_index() -> dict:
    return {n: i for i, n in enumerate(_named_zones())}


def zone_id(name: str) -> int:
    """Zone name -> 12-bit key (TimeZoneKey.getTimeZoneKey analogue).
    Raises ValueError for unknown zones."""
    s = name.strip()
    if s.upper() in ("UTC", "Z", "GMT", "UT", "+00:00", "-00:00"):
        return UTC_ID
    if s and s[0] in "+-":
        sign = -1 if s[0] == "-" else 1
        body = s[1:]
        if ":" in body:
            hh, mm = body.split(":", 1)
        elif len(body) == 4:
            hh, mm = body[:2], body[2:]
        else:
            hh, mm = body, "0"
        minutes = sign * (int(hh) * 60 + int(mm))
        if not -840 <= minutes <= 840:
            raise ValueError(f"zone offset out of range: {name!r}")
        return _FIXED_BASE + minutes
    idx = _named_index().get(s)
    if idx is None:
        raise ValueError(f"unknown time zone: {name!r}")
    return _NAMED_BASE + idx


def zone_name(zid: int) -> str:
    if zid == UTC_ID:
        return "UTC"
    if _FIXED_BASE - 840 <= zid <= _FIXED_BASE + 840:
        minutes = zid - _FIXED_BASE
        sign = "-" if minutes < 0 else "+"
        m = abs(minutes)
        return f"{sign}{m // 60:02d}:{m % 60:02d}"
    names = _named_zones()
    idx = zid - _NAMED_BASE
    if 0 <= idx < len(names):
        return names[idx]
    raise ValueError(f"unknown zone id: {zid}")


# ---------------------------------------------------------------------------
# TZif parsing (RFC 8536) — transitions in UTC seconds + utoff per type
# ---------------------------------------------------------------------------


def _tzif_path(name: str) -> Optional[str]:
    import zoneinfo

    for root in zoneinfo.TZPATH:
        p = os.path.join(root, name)
        if os.path.isfile(p):
            return p
    try:  # pip tzdata package fallback
        import importlib.resources as res

        pkg = "tzdata.zoneinfo." + ".".join(name.split("/")[:-1])
        fname = name.split("/")[-1]
        with res.as_file(res.files(pkg) / fname) as p:
            return str(p)
    except Exception:
        return None


def _parse_tzif(data: bytes):
    """-> (transitions_s int64[T], offsets_s int64[T+1]): offsets_s[i]
    applies before transitions_s[i]; offsets_s[-1] after the last."""

    def header(off):
        magic, ver = data[off: off + 4], data[off + 4: off + 5]
        if magic != b"TZif":
            raise ValueError("not a TZif file")
        counts = struct.unpack(">6I", data[off + 20: off + 44])
        return ver, counts  # isutcnt isstdcnt leapcnt timecnt typecnt charcnt

    ver, counts = header(0)
    isut, isstd, leap, timecnt, typecnt, charcnt = counts
    size = lambda tc, ty, ch, lp, istd, iut, w: (  # noqa: E731
        tc * w + tc + ty * 6 + ch + lp * (w + 4) + istd + iut
    )
    off = 44
    width = 4
    if ver in (b"2", b"3", b"4"):
        # skip the v1 body, parse the 64-bit v2 body
        off += size(timecnt, typecnt, charcnt, leap, isstd, isut, 4)
        ver2, counts = header(off)
        isut, isstd, leap, timecnt, typecnt, charcnt = counts
        off += 44
        width = 8
    fmt = ">%d%s" % (timecnt, "q" if width == 8 else "l")
    trans = np.array(
        struct.unpack(fmt, data[off: off + timecnt * width]), dtype=np.int64
    )
    off += timecnt * width
    idx = np.frombuffer(data[off: off + timecnt], dtype=np.uint8)
    off += timecnt
    utoffs = np.empty(typecnt, dtype=np.int64)
    for t in range(typecnt):
        utoff = struct.unpack(">l", data[off + t * 6: off + t * 6 + 4])[0]
        utoffs[t] = utoff
    # offset BEFORE the first transition: first non-DST type by
    # convention (RFC 8536 §3.2), falling back to type 0
    first = 0
    for t in range(typecnt):
        isdst = data[off + t * 6 + 4]
        if not isdst:
            first = t
            break
    offsets = np.concatenate(
        [[utoffs[first]], utoffs[idx]] if timecnt else [[utoffs[first]]]
    ).astype(np.int64)
    return trans, offsets


@functools.lru_cache(maxsize=None)
def zone_rules(zid: int) -> Tuple[np.ndarray, np.ndarray]:
    """(transitions_s, offsets_s) for a zone id; fixed-offset zones have
    zero transitions."""
    if zid == UTC_ID:
        return np.empty(0, dtype=np.int64), np.zeros(1, dtype=np.int64)
    if _FIXED_BASE - 840 <= zid <= _FIXED_BASE + 840:
        minutes = zid - _FIXED_BASE
        return (
            np.empty(0, dtype=np.int64),
            np.array([minutes * 60], dtype=np.int64),
        )
    name = zone_name(zid)
    path = _tzif_path(name)
    if path is None:
        raise ValueError(f"no TZif data for zone {name!r}")
    with open(path, "rb") as f:
        return _parse_tzif(f.read())


# ---------------------------------------------------------------------------
# Packing + device kernels
# ---------------------------------------------------------------------------


def pack(millis, zid):
    """(instant millis, zone id) -> packed int64 (DateTimeEncoding)."""
    import jax.numpy as jnp

    return (jnp.asarray(millis, jnp.int64) << MILLIS_SHIFT) | jnp.int64(zid)


def unpack_millis(packed):
    import jax.numpy as jnp

    return jnp.asarray(packed) >> MILLIS_SHIFT


def unpack_zone(packed):
    import jax.numpy as jnp

    return (jnp.asarray(packed) & jnp.int64(ZONE_MASK)).astype(jnp.int32)


def pack_py(millis: int, zid: int) -> int:
    return (int(millis) << MILLIS_SHIFT) | int(zid)


def offset_millis_at(instant_ms, zid: int):
    """Device: UTC offset (ms) of static zone `zid` at each instant.
    One searchsorted over the zone's transition table."""
    import jax.numpy as jnp

    trans, offs = zone_rules(zid)
    if len(trans) == 0:
        return jnp.full_like(
            jnp.asarray(instant_ms, jnp.int64), int(offs[0]) * 1000
        )
    t = jnp.asarray(trans * 1000)  # ms
    o = jnp.asarray(offs * 1000)
    pos = jnp.searchsorted(t, jnp.asarray(instant_ms, jnp.int64), side="right")
    return jnp.take(o, pos, mode="clip")


def offset_millis_rowwise(instant_ms, zids):
    """Device: UTC offset (ms) with PER-ROW zone ids. Builds a dense
    (n_zones_used is unknown at trace time) -> uses the full registry's
    transition matrix lazily; heterogenous-zone columns are rare, so
    the matrix builds once per process over the zones seen so far."""
    import jax.numpy as jnp

    mat_t, mat_o = _zone_matrix()
    t = jnp.asarray(mat_t)
    o = jnp.asarray(mat_o)
    z = jnp.clip(jnp.asarray(zids, jnp.int32), 0, t.shape[0] - 1)
    rows_t = jnp.take(t, z, axis=0)
    rows_o = jnp.take(o, z, axis=0)
    inst = jnp.asarray(instant_ms, jnp.int64)[:, None]
    pos = jnp.sum((rows_t <= inst).astype(jnp.int32), axis=1)
    return jnp.take_along_axis(rows_o, pos[:, None], axis=1)[:, 0]


@functools.lru_cache(maxsize=1)
def _zone_matrix():
    """(Z, T) transition/offset matrix over UTC + fixed offsets + named
    zones (padded with +inf transitions so searchsorted stays exact)."""
    n_named = len(_named_zones())
    zids = [UTC_ID] + list(range(1, 1682)) + [
        _NAMED_BASE + i for i in range(n_named)
    ]
    max_id = _NAMED_BASE + n_named
    rules = {z: zone_rules(z) for z in zids}
    width = max(1, max(len(t) for t, _ in rules.values()))
    big = np.iinfo(np.int64).max
    mat_t = np.full((max_id, width), big, dtype=np.int64)
    mat_o = np.zeros((max_id, width + 1), dtype=np.int64)
    for z, (t, o) in rules.items():
        mat_t[z, : len(t)] = t * 1000
        mat_o[z, : len(o)] = o * 1000
        mat_o[z, len(o):] = o[-1] * 1000  # pad with the last offset
    return mat_t, mat_o


def wall_to_instant_millis(wall_ms, zid: int):
    """Device: local wall-clock millis (as if UTC) -> instant millis in
    zone `zid`. Two-step offset resolution: estimate with the offset at
    the wall time read as an instant, then re-read at the corrected
    instant (gap/overlap rows resolve to the LATER offset — Trino picks
    the earlier for overlaps; divergence limited to the 1-2 ambiguous
    hours per year, documented)."""
    off1 = offset_millis_at(wall_ms, zid)
    inst1 = wall_ms - off1
    off2 = offset_millis_at(inst1, zid)
    return wall_ms - off2


# -- host-side scalar helpers (literals / formatting) -----------------------


def offset_millis_py(zid: int, instant_ms: int) -> int:
    trans, offs = zone_rules(zid)
    pos = int(np.searchsorted(trans, instant_ms // 1000, side="right"))
    return int(offs[pos]) * 1000


def format_tstz(packed: int) -> str:
    """Packed value -> 'YYYY-MM-DD HH:MM:SS.mmm Zone' (Trino rendering)."""
    import datetime as _dt

    ms = packed >> MILLIS_SHIFT
    zid = packed & ZONE_MASK
    off = offset_millis_py(zid, ms)
    local = _dt.datetime(1970, 1, 1) + _dt.timedelta(milliseconds=ms + off)
    return (
        f"{local.year:04d}-{local.month:02d}-{local.day:02d} "
        f"{local.hour:02d}:{local.minute:02d}:{local.second:02d}"
        f".{local.microsecond // 1000:03d} {zone_name(zid)}"
    )


def _split_zone(text: str) -> Tuple[str, Optional[int]]:
    """'body [Zone|+HH:MM|Z]' -> (body, zone id or None). The ONE
    trailing-zone scanner shared by literal typing (literal_has_zone)
    and parsing (parse_tstz) so the two can never disagree."""
    s = text.strip()
    if s.endswith(("Z", "z")):
        return s[:-1], UTC_ID
    parts = s.rsplit(" ", 1)
    if len(parts) == 2:
        try:
            return parts[0], zone_id(parts[1])
        except ValueError:
            pass
    # glued ISO offset after the time part (date dashes sit before
    # index 10, which the range guard excludes)
    for i in range(len(s) - 1, max(len(s) - 7, 9), -1):
        if s[i] in "+-" and s[i - 1].isdigit():
            try:
                return s[:i], zone_id(s[i:])
            except ValueError:
                return s, None
    return s, None


def literal_has_zone(text: str) -> bool:
    """True when a timestamp literal carries an explicit zone (name,
    offset, or Z) — the TIMESTAMP vs TIMESTAMP WITH TIME ZONE literal
    distinction (DateTimes.java parse)."""
    return _split_zone(text)[1] is not None


def parse_tstz(text: str, session_zone: str = "UTC") -> Optional[int]:
    """'2020-03-08 01:30:00[.fff] [Zone|+HH:MM]' -> packed int64 (None
    if unparseable). Zone-less strings take the session zone."""
    import datetime as _dt

    s, zone = _split_zone(text)
    if zone is None:
        zone = zone_id(session_zone)
    try:
        dt = _dt.datetime.fromisoformat(s.strip().replace("T", " "))
    except ValueError:
        return None
    wall_ms = (
        (dt - _dt.datetime(1970, 1, 1)) // _dt.timedelta(microseconds=1)
    ) // 1000
    off1 = offset_millis_py(zone, wall_ms)
    off2 = offset_millis_py(zone, wall_ms - off1)
    return pack_py(wall_ms - off2, zone)


def wall_to_instant_rowwise(wall_ms, zids):
    """Device: local wall millis -> instant millis with PER-ROW zones
    (the rowwise form of wall_to_instant_millis)."""
    off1 = offset_millis_rowwise(wall_ms, zids)
    inst1 = wall_ms - off1
    off2 = offset_millis_rowwise(inst1, zids)
    return wall_ms - off2
