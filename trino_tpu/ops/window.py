"""Window function kernels: segmented scans over partition-sorted rows.

Analogue of Trino's WindowOperator + window function implementations
(main/operator/WindowOperator.java:69, operator/window/ — PagesIndex
sorted by partition+order keys, then per-frame accumulation). TPU-first
delta: one multi-key argsort puts rows in (partition, order) order, then
every function is a vectorized segmented scan over the whole column —
no per-row frame loops. Scans use only cumsum/cummax/cummin primitives:
lax.associative_scan (any operand count) HANGS the XLA:TPU compiler at
multi-million-element shapes (see ops/groupby.py's scan NOTE).
Frames supported:

- whole partition      (no ORDER BY, or ROWS/RANGE UNBOUNDED..UNBOUNDED)
- running rows         (ROWS UNBOUNDED PRECEDING..CURRENT ROW)
- running range        (default RANGE frame: current row + peers)

All kernels take `part_start` (True at each partition's first row) and,
where peers matter, `peer_start` (True at each peer group's first row),
both over the sorted row order with dead rows at the tail in their own
"partition"."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from trino_tpu.ops.gather import take_clip


def segment_starts(
    part_cols, part_valids, n: int
) -> jnp.ndarray:
    """True where any partition key differs from the previous row."""
    start = jnp.zeros(n, dtype=jnp.bool_).at[0].set(True)
    for data, valid in zip(part_cols, part_valids):
        prev = jnp.roll(data, 1)
        diff = data != prev
        if valid is not None:
            pv = jnp.roll(valid, 1)
            diff = diff | (valid != pv)
        diff = diff.at[0].set(True)
        start = start | diff
    return start


def _seg_start_index(part_start: jnp.ndarray) -> jnp.ndarray:
    """For each row, the index of its partition's first row."""
    n = part_start.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    return jax.lax.cummax(jnp.where(part_start, idx, 0))


def _seg_end_index(part_start: jnp.ndarray) -> jnp.ndarray:
    """For each row, the index of its partition's last row."""
    n = part_start.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    # next partition start after i (exclusive), scanning from the right
    nxt = jnp.roll(part_start, -1).at[n - 1].set(True)
    ends = jnp.where(nxt, idx, n - 1)
    return jax.lax.cummin(ends[::-1])[::-1]


def row_number(part_start: jnp.ndarray) -> jnp.ndarray:
    idx = jnp.arange(part_start.shape[0], dtype=jnp.int64)
    return idx - _seg_start_index(part_start) + 1


def rank(part_start: jnp.ndarray, peer_start: jnp.ndarray) -> jnp.ndarray:
    idx = jnp.arange(part_start.shape[0], dtype=jnp.int32)
    peer_first = jax.lax.cummax(jnp.where(peer_start, idx, 0))
    return (peer_first - _seg_start_index(part_start) + 1).astype(jnp.int64)


def percent_rank(part_start: jnp.ndarray, peer_start: jnp.ndarray):
    """(rank - 1) / (partition rows - 1); 0 for single-row partitions
    (WindowFunctions: PercentRankFunction semantics)."""
    size = (
        _seg_end_index(part_start) - _seg_start_index(part_start) + 1
    ).astype(jnp.float64)
    r = rank(part_start, peer_start).astype(jnp.float64)
    return jnp.where(size > 1, (r - 1) / jnp.maximum(size - 1, 1), 0.0)


def cume_dist(part_start: jnp.ndarray, peer_start: jnp.ndarray):
    """(rows at or before the current peer group end) / partition rows
    (CumulativeDistributionFunction semantics)."""
    start = _seg_start_index(part_start)
    size = (_seg_end_index(part_start) - start + 1).astype(jnp.float64)
    end = _peer_end_index(part_start, peer_start)
    at_or_before = (end - start + 1).astype(jnp.float64)
    return at_or_before / size


def dense_rank(part_start: jnp.ndarray, peer_start: jnp.ndarray) -> jnp.ndarray:
    groups = jnp.cumsum(peer_start.astype(jnp.int64))
    at_seg_start = take_clip(groups, _seg_start_index(part_start))
    return groups - at_seg_start + 1


def _running_sum(vals: jnp.ndarray, part_start: jnp.ndarray) -> jnp.ndarray:
    """Segmented inclusive cumulative sum."""
    cs = jnp.cumsum(vals)
    seg_start = _seg_start_index(part_start)
    base = take_clip(cs, seg_start) - take_clip(vals, seg_start)
    return cs - base


def _minmax_lanes(vals: jnp.ndarray, kind: str):
    """Order-encode values as uint32 lanes for the cummax chain. For
    "min" the lanes are complemented (reversed lexicographic order =
    complement of each lane). 64-bit float BITCASTS do not compile on
    this TPU backend, so floats go through ops/floatbits.f64_lanes."""
    from trino_tpu.ops.floatbits import f32_bits_ordered, f64_lanes

    if vals.dtype == jnp.float64:
        lanes = list(f64_lanes(vals))
    elif vals.dtype == jnp.float32:
        lanes = [f32_bits_ordered(vals)]
    elif vals.dtype == jnp.bool_:
        lanes = [vals.astype(jnp.uint32)]
    else:
        enc = vals.astype(jnp.int64).astype(jnp.uint64) ^ (
            jnp.uint64(1) << jnp.uint64(63)
        )
        lanes = [
            (enc >> jnp.uint64(32)).astype(jnp.uint32),
            (enc & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32),
        ]
    if kind == "min":
        lanes = [~l for l in lanes]
    return lanes


def _scan_minmax(vals: jnp.ndarray, part_start: jnp.ndarray, kind: str) -> jnp.ndarray:
    """Segmented running min/max WITHOUT lax.associative_scan (whose
    XLA:TPU compile hangs at multi-million-element shapes — see
    ops/groupby.py's scan NOTE; lax.cummax compiles flat).

    One cummax pass per order lane over (segment_id || lane): a later
    segment's id dominates, giving automatic reset; rows not attaining
    the running prefix contribute the neutral 0 to later lanes; run
    boundaries for the next lane are wherever the current run value
    advances. A FINAL pass carries the row index, so the result is
    GATHERED from the actual values — exact for every dtype, no bit
    decode."""
    n = vals.shape[0]
    first = jnp.arange(n) == 0
    g = jnp.maximum(
        jnp.cumsum(part_start.astype(jnp.int64)) - 1, 0
    ).astype(jnp.uint64)
    lanes = _minmax_lanes(vals, kind)
    # final lane: row index — cummax yields the LATEST row attaining the
    # full prefix; all attaining rows hold the identical value (the lane
    # encoding is injective), so any witness gathers correctly
    lanes.append(jnp.arange(n, dtype=jnp.uint32))
    attained = jnp.ones(n, dtype=jnp.bool_)
    g_cur = g
    run_lane = None
    for i, lane in enumerate(lanes):
        contrib = jnp.where(attained, lane, jnp.uint32(0))
        packed = (g_cur << jnp.uint64(32)) | contrib.astype(jnp.uint64)
        run = jax.lax.cummax(packed)
        run_lane = (run & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        if i + 1 < len(lanes):
            change = (run != jnp.roll(run, 1)) | first
            g_cur = jnp.maximum(
                jnp.cumsum(change.astype(jnp.int64)) - 1, 0
            ).astype(jnp.uint64)
            attained = attained & (lane == run_lane)
    pos = run_lane.astype(jnp.int32)
    return take_clip(vals, pos)


def windowed_agg(
    kind: str,  # sum | avg | min | max | count | count_star
    vals: Optional[jnp.ndarray],
    valid: Optional[jnp.ndarray],
    live: jnp.ndarray,
    part_start: jnp.ndarray,
    peer_start: Optional[jnp.ndarray],
    frame: str,  # "partition" | "rows" | "range"
    neutral,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Aggregate over the window frame. Returns (value, count) arrays —
    count also drives NULL-ness (count==0 -> NULL result for sum/min/
    max/avg, like Trino's aggregate window functions)."""
    w = live if valid is None else (live & valid)
    cnt_run = _running_sum(w.astype(jnp.int64), part_start)
    if kind in ("count", "count_star"):
        out_run = cnt_run
    elif kind in ("min", "max"):
        masked = jnp.where(w, vals, jnp.asarray(neutral, vals.dtype))
        out_run = _scan_minmax(masked, part_start, kind)
    else:  # sum / avg accumulate in wide dtype chosen by caller
        masked = jnp.where(w, vals, jnp.zeros((), dtype=vals.dtype))
        out_run = _running_sum(masked, part_start)
    if frame == "rows":
        return out_run, cnt_run
    if frame == "partition":
        end = _seg_end_index(part_start)
        return take_clip(out_run, end), take_clip(cnt_run, end)
    # "range": value at the END of the current peer group
    assert peer_start is not None
    end = _peer_end_index(part_start, peer_start)
    return take_clip(out_run, end), take_clip(cnt_run, end)


def _peer_end_index(part_start: jnp.ndarray, peer_start: jnp.ndarray) -> jnp.ndarray:
    n = part_start.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    nxt = jnp.roll(peer_start | part_start, -1).at[n - 1].set(True)
    ends = jnp.where(nxt, idx, n - 1)
    return jax.lax.cummin(ends[::-1])[::-1]


def shift_in_partition(
    vals: jnp.ndarray,
    valid: Optional[jnp.ndarray],
    part_start: jnp.ndarray,
    offset: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """lead (offset<0) / lag (offset>0): value from `offset` rows back,
    NULL outside the partition."""
    n = vals.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    src = jnp.clip(idx - offset, 0, n - 1)
    seg = jnp.cumsum(part_start.astype(jnp.int32))
    ok = (idx - offset >= 0) & (idx - offset < n)
    ok = ok & (take_clip(seg, src) == seg)
    # axis=0: long-decimal (n, 2) limb pairs gather row-wise
    out = take_clip(vals, src, axis=0)
    out_valid = ok if valid is None else (ok & take_clip(valid, src))
    return out, out_valid


def value_at(
    vals: jnp.ndarray,
    valid: Optional[jnp.ndarray],
    index: jnp.ndarray,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """first_value/last_value: gather at a per-row frame boundary index."""
    out = take_clip(vals, index, axis=0)
    return out, None if valid is None else take_clip(valid, index)


def first_value(vals, valid, part_start):
    return value_at(vals, valid, _seg_start_index(part_start))


def last_value(vals, valid, part_start, peer_start, frame: str):
    if frame == "rows":
        n = vals.shape[0]
        idx = jnp.arange(n, dtype=jnp.int32)
        return value_at(vals, valid, idx)
    if frame == "partition":
        return value_at(vals, valid, _seg_end_index(part_start))
    return value_at(vals, valid, _peer_end_index(part_start, peer_start))


def nth_value(vals, valid, part_start, peer_start, frame: str, n: int):
    """nth_value(x, n): frame-start + (n-1), NULL when the frame holds
    fewer than n rows. Frame-end selection mirrors last_value."""
    n_rows = vals.shape[0]
    start = _seg_start_index(part_start)
    if frame == "rows":
        end = jnp.arange(n_rows, dtype=jnp.int32)
    elif frame == "partition":
        end = _seg_end_index(part_start)
    else:
        end = _peer_end_index(part_start, peer_start)
    idx = start + jnp.int32(n - 1)
    data, v = value_at(vals, valid, jnp.minimum(idx, end))
    in_frame = idx <= end
    vv = in_frame if v is None else (v & in_frame)
    return data, vv


def ntile(n_buckets: int, part_start: jnp.ndarray) -> jnp.ndarray:
    """ntile(n): bucket 1..n by position within the partition."""
    rn = row_number(part_start) - 1
    end = _seg_end_index(part_start)
    start = _seg_start_index(part_start)
    size = (end - start + 1).astype(jnp.int64)
    # Trino semantics: first (size % n) buckets get ceil(size/n) rows
    base = size // n_buckets
    rem = size % n_buckets
    big = rem * (base + 1)
    in_big = rn < big
    bucket = jnp.where(
        in_big,
        rn // jnp.maximum(base + 1, 1),
        rem + (rn - big) // jnp.maximum(base, 1),
    )
    return bucket + 1
