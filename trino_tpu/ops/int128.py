"""Int128 arithmetic over (hi, lo) int64 limb pairs.

Analogue of Trino's Int128 / Int128Math (spi/type/Int128.java:23,
spi/type/Int128Math.java) — the carrier for decimal(19..38). TPU-first
representation: a long-decimal COLUMN is one (n, 2) int64 array
(column 0 = signed high limb, column 1 = low limb holding unsigned
bits), mirroring Int128ArrayBlock's long[2n] layout but vectorized.
All kernels here take/return separate (hi, lo) arrays; the block layer
stacks them.

Two's-complement across the pair: value = hi * 2^64 + (lo as u64).
Carries use the standard unsigned-compare trick; 64x64 -> 128 products
decompose into 32-bit half-limbs so every partial product is exact in
int64 (TPU has no native 128-bit ops; XLA int64 is itself emulated on
32-bit lanes, so staying in small exact pieces is the fast path too).

Division: HALF_UP decimal division. Divisors that fit int64 take the
schoolbook 32-bit-digit path (divmod_u128_u64); full 128-bit divisors
take the bit-serial restoring division (divmod_u128_u128), one
lax.fori_loop of 128 static steps — the complete Int128Math.divide
surface (spi/type/Int128Math.java).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

_MASK32 = jnp.int64(0xFFFFFFFF)
_U64_SIGN = jnp.int64(-0x8000000000000000)  # 1 << 63 as int64


def _u64_lt(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Unsigned < over int64 bit patterns (flip sign bit, signed <)."""
    return (a ^ _U64_SIGN) < (b ^ _U64_SIGN)


def from_i64(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sign-extend an int64 into (hi, lo)."""
    x = x.astype(jnp.int64)
    return x >> jnp.int64(63), x


def add(ah, al, bh, bl):
    lo = al + bl  # wrapping add of the low bits
    carry = _u64_lt(lo, al).astype(jnp.int64)
    return ah + bh + carry, lo


def neg(h, lo):
    nh, nl = ~h, ~lo
    lo2 = nl + jnp.int64(1)
    carry = (lo2 == 0).astype(jnp.int64)  # only wraps when nl was all-1s
    return nh + carry, lo2


def sub(ah, al, bh, bl):
    nh, nl = neg(bh, bl)
    return add(ah, al, nh, nl)


def eq(ah, al, bh, bl):
    return (ah == bh) & (al == bl)


def lt(ah, al, bh, bl):
    """Signed 128-bit less-than."""
    return (ah < bh) | ((ah == bh) & _u64_lt(al, bl))


def sign(h, lo):
    """-1 / 0 / +1 as int64."""
    is_zero = (h == 0) & (lo == 0)
    return jnp.where(is_zero, jnp.int64(0), jnp.where(h < 0, jnp.int64(-1), jnp.int64(1)))


def abs_(h, lo):
    nh, nl = neg(h, lo)
    negv = h < 0
    return jnp.where(negv, nh, h), jnp.where(negv, nl, lo)


def _umul64(a: jnp.ndarray, b: jnp.ndarray):
    """Unsigned 64x64 -> (hi, lo) via 32-bit half-limbs (each partial
    product < 2^64 and exact in int64's bit pattern)."""
    a0 = a & _MASK32
    a1 = (a >> jnp.int64(32)) & _MASK32
    b0 = b & _MASK32
    b1 = (b >> jnp.int64(32)) & _MASK32
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    # partial products can wrap int64's sign bit; every right shift
    # must be LOGICAL, i.e. masked after the arithmetic shift
    mid = (
        ((p00 >> jnp.int64(32)) & _MASK32)
        + (p01 & _MASK32)
        + (p10 & _MASK32)
    )
    lo = (p00 & _MASK32) | (mid << jnp.int64(32))
    hi = (
        p11
        + ((p01 >> jnp.int64(32)) & _MASK32)
        + ((p10 >> jnp.int64(32)) & _MASK32)
        + (mid >> jnp.int64(32))
    )
    return hi, lo


def mul_i64(a: jnp.ndarray, b: jnp.ndarray):
    """Signed full 64x64 -> 128 product."""
    hi, lo = _umul64(a, b)
    # signed correction: for each negative operand subtract the other
    # from the high limb (standard mulhi fixup)
    hi = hi - jnp.where(a < 0, b, jnp.int64(0)) - jnp.where(b < 0, a, jnp.int64(0))
    return hi, lo


def mul_128(ah, al, bh, bl):
    """Full 128x128 product mod 2^128: u128(al,bl) cross terms —
    (ah*2^64 + al)(bh*2^64 + bl) = al*bl + 2^64 (ah*bl + al*bh)."""
    ph, pl = _umul64(al, bl)
    cross = ah * bl + al * bh  # wrapping int64 is exactly mod 2^64
    return ph + cross, pl


def mul_128_64(h, lo, m: jnp.ndarray):
    """(hi, lo) * signed-64 m with |m| <= 2^62, result mod 2^128
    (callers bound magnitudes to 38 digits so the wrap never triggers
    in-range): |value| * |m| = u128(|lo|-part) with sign fixup, plus
    h*m into the high limb."""
    am = jnp.abs(m)
    ph, pl = _umul64(lo, am)  # u64(lo) * |m|
    nh, nl = neg(ph, pl)
    m_neg = m < 0
    ph = jnp.where(m_neg, nh, ph)
    pl = jnp.where(m_neg, nl, pl)
    return ph + h * m, pl


_POW10_63 = [10 ** k for k in range(19)]  # fits int64 through 10^18


def pow10_128(k: int) -> Tuple[int, int]:
    """10^k as (hi, lo) python ints, k <= 38."""
    v = 10 ** k
    return (v >> 64) & ((1 << 64) - 1), v & ((1 << 64) - 1)


def _const64(v: int) -> jnp.ndarray:
    """int64 scalar from a python int given as a 64-bit pattern."""
    if v >= 1 << 63:
        v -= 1 << 64
    return jnp.int64(v)


def rescale_up(h, lo, k: int):
    """(hi, lo) * 10^k for 0 <= k <= 38 (two int64-multiplier steps)."""
    if k == 0:
        return h, lo
    while k > 18:
        h, lo = mul_128_64(h, lo, jnp.int64(10 ** 18))
        k -= 18
    return mul_128_64(h, lo, jnp.int64(10 ** k))


def divmod_u128_u64(h, lo, d: jnp.ndarray):
    """Unsigned 128 / unsigned-63-bit divisor -> (quotient (hi,lo),
    remainder i64). Schoolbook over 32-bit digits: at each step the
    partial remainder < d * 2^32 <= 2^95, kept exact as (r_hi, r_lo)
    with r_hi < 2^31."""
    # digits of the dividend, most-significant first
    digits = [
        (h >> jnp.int64(32)) & _MASK32,
        h & _MASK32,
        (lo >> jnp.int64(32)) & _MASK32,
        lo & _MASK32,
    ]
    q = []
    r = jnp.zeros_like(h)
    for dig in digits:
        # partial = r * 2^32 + dig as a (signed-safe) 128-bit value:
        # r < d <= 2^63 so partial < 2^95, hi limb < 2^31
        p_hi = r >> jnp.int64(32)
        p_lo = (r << jnp.int64(32)) | dig
        # float-seeded quotient-digit estimate; est <= 2^32 so the
        # f64 mantissa bounds the absolute error to a few units
        num = p_hi.astype(jnp.float64) * (2.0 ** 64) + jnp.where(
            p_lo < 0,
            p_lo.astype(jnp.float64) + 2.0 ** 64,
            p_lo.astype(jnp.float64),
        )
        est = jnp.clip(
            jnp.floor(num / d.astype(jnp.float64)), 0.0, 2.0 ** 32
        ).astype(jnp.int64)
        # exact 128-bit remainder rem = partial - est * d, signed
        prod = _umul64(est, d)
        rem_h, rem_l = sub(p_hi, p_lo, prod[0], prod[1])
        # bounded correction (float error is a few ulp of est)
        for _ in range(4):
            over = rem_h < 0
            est = est - over.astype(jnp.int64)
            ah2, al2 = add(rem_h, rem_l, jnp.int64(0), d)
            rem_h = jnp.where(over, ah2, rem_h)
            rem_l = jnp.where(over, al2, rem_l)
        for _ in range(4):
            under = ~lt(rem_h, rem_l, jnp.int64(0), d)
            est = est + under.astype(jnp.int64)
            sh2, sl2 = sub(rem_h, rem_l, jnp.int64(0), d)
            rem_h = jnp.where(under, sh2, rem_h)
            rem_l = jnp.where(under, sl2, rem_l)
        q.append(est & _MASK32)
        r = rem_l
    qh = (q[0] << jnp.int64(32)) | q[1]
    ql = (q[2] << jnp.int64(32)) | q[3]
    return qh, ql, r


def _u128_lt(ah, al, bh, bl):
    """Unsigned 128-bit less-than over limb pairs."""
    return _u64_lt(ah, bh) | ((ah == bh) & _u64_lt(al, bl))


def divmod_u128_u128(uh, ul, dh, dl):
    """Unsigned 128 / unsigned 128 -> (q_hi, q_lo, r_hi, r_lo), d != 0.

    Restoring bit-serial long division as ONE lax.fori_loop of 128
    steps — static control flow, fully vectorized over the batch (the
    divisor-beyond-int64 completion of Int128Math.divide,
    spi/type/Int128Math.java; the 32-bit-digit schoolbook
    divmod_u128_u64 stays the fast path for short divisors)."""
    import jax

    zero = jnp.zeros_like(uh)

    def body(i, st):
        qh, ql, rh, rl = st
        shift = jnp.int64(127) - i.astype(jnp.int64)
        bit = jnp.where(
            shift >= 64,
            (uh >> jnp.clip(shift - 64, 0, 63)) & jnp.int64(1),
            (ul >> jnp.clip(shift, 0, 63)) & jnp.int64(1),
        )
        rh = (rh << jnp.int64(1)) | ((rl >> jnp.int64(63)) & jnp.int64(1))
        rl = (rl << jnp.int64(1)) | bit
        ge = ~_u128_lt(rh, rl, dh, dl)
        sh, sl = sub(rh, rl, dh, dl)
        rh = jnp.where(ge, sh, rh)
        rl = jnp.where(ge, sl, rl)
        qbit = ge.astype(jnp.int64)
        qh = qh | jnp.where(
            shift >= 64, qbit << jnp.clip(shift - 64, 0, 63), zero
        )
        ql = ql | jnp.where(
            shift < 64, qbit << jnp.clip(shift, 0, 63), zero
        )
        return qh, ql, rh, rl

    qh, ql, rh, rl = jax.lax.fori_loop(
        0, 128, body, (zero, zero, zero, zero)
    )
    return qh, ql, rh, rl


def div_round_128(h, lo, dh, dl):
    """Signed 128 / signed nonzero 128, HALF_UP rounding — the full
    Int128Math.divideRoundUp (divisors beyond int64 included)."""
    ah, al = abs_(h, lo)
    bh_a, bl_a = abs_(dh, dl)
    qh, ql, rh, rl = divmod_u128_u128(ah, al, bh_a, bl_a)
    # round up when 2r >= d (r < d < 2^127 so 2r fits unsigned 128)
    r2h = (rh << jnp.int64(1)) | ((rl >> jnp.int64(63)) & jnp.int64(1))
    r2l = rl << jnp.int64(1)
    round_up = ~_u128_lt(r2h, r2l, bh_a, bl_a)
    qh, ql = add(qh, ql, jnp.int64(0), round_up.astype(jnp.int64))
    negv = (sign(h, lo) * sign(dh, dl)) < 0
    nh, nl = neg(qh, ql)
    return jnp.where(negv, nh, qh), jnp.where(negv, nl, ql)


def mod_128(h, lo, dh, dl):
    """Signed 128 %% signed nonzero 128; result takes the DIVIDEND's
    sign (Int128Math.remainder)."""
    ah, al = abs_(h, lo)
    bh_a, bl_a = abs_(dh, dl)
    _, _, rh, rl = divmod_u128_u128(ah, al, bh_a, bl_a)
    negv = h < 0
    nh, nl = neg(rh, rl)
    return jnp.where(negv, nh, rh), jnp.where(negv, nl, rl)


def div_round_i64(h, lo, d: jnp.ndarray):
    """Signed (hi,lo) / signed nonzero int64 d, HALF_UP rounding
    (Trino Int128Math.divideRoundUp semantics for 64-bit divisors)."""
    ah, al = abs_(h, lo)
    ad = jnp.abs(d)
    qh, ql, r = divmod_u128_u64(ah, al, ad)
    round_up = ~_u64_lt(r + r, ad)  # 2r >= d
    qh2, ql2 = add(qh, ql, jnp.int64(0), round_up.astype(jnp.int64))
    negv = (sign(h, lo) * jnp.sign(d)) < 0
    nh, nl = neg(qh2, ql2)
    return jnp.where(negv, nh, qh2), jnp.where(negv, nl, ql2)


def rescale_down_round(h, lo, k: int):
    """(hi, lo) / 10^k with HALF_UP rounding, 0 <= k <= 38."""
    if k == 0:
        return h, lo
    while k > 18:
        h, lo = div_round_i64(h, lo, jnp.int64(10 ** 18))
        k -= 18
    return div_round_i64(h, lo, jnp.int64(10 ** k))


def to_i64(h, lo):
    """(value mod 2^64) as int64 plus an in-range flag (value
    representable in int64)."""
    ok = h == (lo >> jnp.int64(63))
    return lo, ok


# 38-digit overflow bound: |value| < 10^38
_BOUND = 10 ** 38
_BOUND_HI = _const64((_BOUND >> 64) & ((1 << 64) - 1))
_BOUND_LO = _const64(_BOUND & ((1 << 64) - 1))


def overflows_38(h, lo):
    """|value| >= 10^38 (Decimals.overflows analogue)."""
    ah, al = abs_(h, lo)
    # note abs(-2^127) wraps negative; treat hi<0 after abs as overflow
    ge = ~lt(ah, al, _BOUND_HI, _BOUND_LO)
    return ge | (ah < 0)


# -- host conversion ---------------------------------------------------------


def to_python(h: int, lo: int) -> int:
    """(hi, lo) host ints -> python int value."""
    return (int(h) << 64) | (int(lo) & ((1 << 64) - 1))


def from_python(v: int) -> Tuple[int, int]:
    """python int -> (hi, lo) as int64-representable host ints."""
    lo = v & ((1 << 64) - 1)
    h = (v >> 64) & ((1 << 64) - 1)
    if lo >= 1 << 63:
        lo -= 1 << 64
    if h >= 1 << 63:
        h -= 1 << 64
    return h, lo
