"""XLA/Pallas kernels — the engine's "generated code" layer.

Where Trino JIT-compiles JVM bytecode per query (main/sql/gen/,
SURVEY.md §2.9: ExpressionCompiler, JoinCompiler, AccumulatorCompiler),
this package holds jax-traceable kernels that `jax.jit` specializes per
shape/dtype at first call — same role, compiler-native mechanism.
"""
