"""Hash-join build/probe kernels.

Analogue of Trino's PagesIndex + PagesHash + JoinProbe family
(main/operator/PagesIndex.java:80, join/DefaultPagesHash.java:44,
join/LookupJoinOperator.java:36) — re-designed around sorting, which is
what TPUs do well, instead of pointer-chasing:

- Build ("LookupSource"): hash the build keys to 64 bits, sort build
  rows by hash. The sorted-hash array + permutation IS the lookup
  structure — duplicates are adjacent runs, playing the role of Trino's
  PositionLinks chains without linked lists.
- Probe: vectorized binary search (searchsorted) gives each probe row
  its candidate run [lo, hi); run lengths handle duplicate build keys.
- Fan-out (dynamic output size): two-phase — count matches, host picks
  a bucketed output capacity, then a dense expansion pass materializes
  (probe_row, build_row) pairs. Hash collisions are culled by an exact
  key-equality verify on the expanded pairs.
- Outer/semi/anti variants derive from the same expansion plus
  scatter-or'd matched flags (probe side) and a build-side matched
  bitmap (the LookupOuterOperator analogue for RIGHT/FULL joins).

SQL join-key semantics: NULL never matches NULL.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from trino_tpu.ops.hashing import hash64

_NO_MATCH_HASH = jnp.int64(-1)  # probes that must find nothing
_DEAD_BUILD_HASH = jnp.iinfo(jnp.int64).max  # dead build rows sort last


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class LookupSource:
    """Device-resident build side: sorted hashes + row permutation."""

    sorted_hash: jnp.ndarray  # (B,) int64, dead rows = MAX
    perm: jnp.ndarray  # (B,) int32 — build row index at each sorted pos
    key_cols: List[jnp.ndarray]  # original (unsorted) build key columns
    key_valids: List[jnp.ndarray]
    build_live: jnp.ndarray  # (B,) bool

    def tree_flatten(self):
        return (
            (self.sorted_hash, self.perm, self.key_cols, self.key_valids, self.build_live),
            (),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        sh, perm, kc, kv, bl = children
        return cls(sh, perm, list(kc), list(kv), bl)

    @property
    def build_capacity(self) -> int:
        return int(self.perm.shape[0])


@jax.jit
def build_lookup(
    keys: Sequence[jnp.ndarray],
    valids: Sequence[jnp.ndarray],
    live: jnp.ndarray,
) -> LookupSource:
    """Build phase — HashBuilderOperator analogue, one sort instead of
    row-at-a-time inserts (join/HashBuilderOperator.java:58)."""
    any_null = None
    for v in valids:
        any_null = ~v if any_null is None else (any_null | ~v)
    usable = live if any_null is None else (live & ~any_null)
    h = hash64(list(keys), list(valids))
    h = jnp.where(usable, h, _DEAD_BUILD_HASH)
    perm = jnp.argsort(h).astype(jnp.int32)
    return LookupSource(jnp.take(h, perm), perm, list(keys), list(valids), usable)


@jax.jit
def probe_counts(
    ls: LookupSource,
    probe_keys: Sequence[jnp.ndarray],
    probe_valids: Sequence[jnp.ndarray],
    probe_live: jnp.ndarray,
):
    """Phase 1: per-probe-row candidate run [lo, hi). Returns
    (lo, counts, total) — `total` is a device scalar the host reads to
    size the output batch."""
    any_null = None
    for v in probe_valids:
        any_null = ~v if any_null is None else (any_null | ~v)
    usable = probe_live if any_null is None else (probe_live & ~any_null)
    ph = hash64(list(probe_keys), list(probe_valids))
    ph = jnp.where(usable, ph, _NO_MATCH_HASH)
    lo = jnp.searchsorted(ls.sorted_hash, ph, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(ls.sorted_hash, ph, side="right").astype(jnp.int32)
    counts = hi - lo
    return lo, counts, jnp.sum(counts)


@partial(jax.jit, static_argnames=("out_capacity",))
def expand_matches(
    ls: LookupSource,
    probe_keys: Sequence[jnp.ndarray],
    probe_valids: Sequence[jnp.ndarray],
    lo: jnp.ndarray,
    counts: jnp.ndarray,
    out_capacity: int,
):
    """Phase 2: materialize candidate pairs, verify exact key equality.

    Returns (probe_idx, build_idx, pair_live) each (out_capacity,).
    """
    off = jnp.cumsum(counts)  # inclusive
    total = off[-1] if counts.shape[0] else jnp.int32(0)
    j = jnp.arange(out_capacity, dtype=jnp.int32)
    # which probe row produced output j
    pi = jnp.searchsorted(off, j, side="right").astype(jnp.int32)
    pi_c = jnp.clip(pi, 0, counts.shape[0] - 1)
    start = jnp.take(off, pi_c) - jnp.take(counts, pi_c)
    spos = jnp.take(lo, pi_c) + (j - start)
    spos = jnp.clip(spos, 0, ls.perm.shape[0] - 1)
    bi = jnp.take(ls.perm, spos)
    in_range = j < total
    # exact verify (hash collisions): join equality — NULLs never match
    ok = in_range
    for pk, pv, bk, bv in zip(probe_keys, probe_valids, ls.key_cols, ls.key_valids):
        a = jnp.take(pk, pi_c)
        av = jnp.take(pv, pi_c)
        b = jnp.take(bk, jnp.clip(bi, 0, bk.shape[0] - 1))
        bvv = jnp.take(bv, jnp.clip(bi, 0, bv.shape[0] - 1))
        ok = ok & (a == b) & av & bvv
    return pi_c, bi, ok


def probe_matched_flags(probe_capacity, pi, pair_live):
    """Per-probe-row 'has >=1 verified match' — drives semi/anti joins
    (HashSemiJoinOperator analogue) and LEFT-outer row emission."""
    z = jnp.zeros(probe_capacity + 1, dtype=jnp.bool_)
    idx = jnp.where(pair_live, pi, probe_capacity)
    return z.at[idx].max(True, mode="drop")[:probe_capacity]


def build_matched_flags(build_capacity, bi, pair_live, prior=None):
    """Build-side matched bitmap for RIGHT/FULL outer joins
    (join/LookupOuterOperator.java analogue)."""
    z = prior if prior is not None else jnp.zeros(build_capacity, dtype=jnp.bool_)
    idx = jnp.where(pair_live, bi, build_capacity)
    return z.at[idx].max(True, mode="drop")
