"""Hash-join build/probe kernels.

Analogue of Trino's PagesIndex + PagesHash + JoinProbe family
(main/operator/PagesIndex.java:80, join/DefaultPagesHash.java:44,
join/LookupJoinOperator.java:36) — re-designed around sorting, which is
what TPUs do well, instead of pointer-chasing:

- Build ("LookupSource"): hash the build keys to 64 bits, sort build
  rows by hash. The sorted-hash array + permutation IS the lookup
  structure — duplicates are adjacent runs, playing the role of Trino's
  PositionLinks chains without linked lists.
- Probe: vectorized binary search (searchsorted) gives each probe row
  its candidate run [lo, hi); run lengths handle duplicate build keys.
- Fan-out (dynamic output size): two-phase — count matches, host picks
  a bucketed output capacity, then a dense expansion pass materializes
  (probe_row, build_row) pairs. Hash collisions are culled by an exact
  key-equality verify on the expanded pairs.
- Outer/semi/anti variants derive from the same expansion plus
  scatter-or'd matched flags (probe side) and a build-side matched
  bitmap (the LookupOuterOperator analogue for RIGHT/FULL joins).

SQL join-key semantics: NULL never matches NULL.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from trino_tpu.ops.gather import take_clip
from trino_tpu.ops.hashing import hash64

_NO_MATCH_HASH = jnp.int64(1) << jnp.int64(62)  # probes that must find nothing
_DEAD_BUILD_HASH = jnp.iinfo(jnp.int64).max  # dead build rows sort last
# hash64 values are 62-bit, so both sentinels sit above every real hash,
# below 2^63 (no overflow in sorted_run_bounds' (v << 1) | tag key), and
# in two DISTINCT runs — null probes can never count dead build rows


def _keep_rightward(flags: jnp.ndarray, vals: jnp.ndarray):
    """Per element: value of the NEAREST flagged position at or to the
    right. Requires at least one flagged position at-or-right of every
    element (sorted_run_bounds guarantees it: the last run is flagged).

    Formulated as cumsum + scatter + gather instead of a tuple-operand
    associative scan: XLA:TPU compilation of multi-operand
    associative_scan was measured HANGING (>400s, vs 62s for a full
    6.4M-element sort) at multi-million-element shapes — the scan's
    log-depth slice/concat tree explodes; scatter/gather compile flat."""
    n = flags.shape[0]
    # rid[i] = number of flagged positions strictly before i; for a
    # flagged i this is its own ordinal among flagged positions
    cum = jnp.cumsum(flags.astype(jnp.int32))
    rid = cum - flags.astype(jnp.int32)
    # F[k] = vals at the k-th flagged position (drop unflagged writes)
    F = jnp.zeros(n, vals.dtype).at[jnp.where(flags, rid, n)].set(
        vals, mode="drop"
    )
    # element i reads the rid[i]-th flagged value = nearest at-or-right
    return take_clip(F, rid)


def sorted_run_bounds(sorted_arr: jnp.ndarray, q: jnp.ndarray):
    """For each query, the run [lo, hi) of equal values in a sorted
    int64 array — the PagesHash probe (DefaultPagesHash.java:159).

    TPU-native formulation: both per-element binary search (XLA
    searchsorted: measured 343ms for 1M probes into 128k) and a
    take-based bisect loop (~670ms — chained 1M-gathers cost ms each on
    TPU) lose to sorting, which the TPU does at ~25ms/M rows. So: tag
    and sort [queries ++ table] together (queries first within an equal
    run), read lo as the build-prefix count and hi as the count at the
    run's end via prefix sums, and route results back to query order
    with a second multi-operand sort. Two sorts + two scans, no
    serial gathers."""
    B = sorted_arr.shape[0]
    N = q.shape[0]
    if B == 0:
        z = jnp.zeros(N, jnp.int32)
        return z, z
    # key = (value << 1) | is_table : queries sort before equal values
    key = jnp.concatenate(
        [
            (q.astype(jnp.uint64) << jnp.uint64(1)),
            (sorted_arr.astype(jnp.uint64) << jnp.uint64(1))
            | jnp.uint64(1),
        ]
    )
    orig = jnp.concatenate(
        [
            jnp.arange(N, dtype=jnp.int32),
            jnp.full(B, N, dtype=jnp.int32),  # table rows: sentinel
        ]
    )
    key_s, orig_s = jax.lax.sort((key, orig), num_keys=1)
    is_table = (key_s & jnp.uint64(1)).astype(jnp.int32)
    tab_cum = jnp.cumsum(is_table)  # table elems at or before pos
    lo_s = tab_cum - is_table  # strictly before (queries first in run)
    # hi = table count through the end of this value's run
    val_s = key_s >> jnp.uint64(1)
    run_last = jnp.concatenate(
        [val_s[1:] != val_s[:-1], jnp.ones(1, dtype=jnp.bool_)]
    )
    hi_s = _keep_rightward(run_last, tab_cum)
    # route back to query order: queries carry orig < N, table rows N
    _, lo_q, hi_q = jax.lax.sort((orig_s, lo_s, hi_s), num_keys=1)
    return lo_q[:N].astype(jnp.int32), hi_q[:N].astype(jnp.int32)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class LookupSource:
    """Device-resident build side: sorted hashes + row permutation."""

    sorted_hash: jnp.ndarray  # (B,) int64, dead rows = MAX
    perm: jnp.ndarray  # (B,) int32 — build row index at each sorted pos
    key_cols: List[jnp.ndarray]  # original (unsorted) build key columns
    key_valids: List[jnp.ndarray]
    build_live: jnp.ndarray  # (B,) bool

    def tree_flatten(self):
        return (
            (self.sorted_hash, self.perm, self.key_cols, self.key_valids, self.build_live),
            (),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        sh, perm, kc, kv, bl = children
        return cls(sh, perm, list(kc), list(kv), bl)

    @property
    def build_capacity(self) -> int:
        return int(self.perm.shape[0])


@jax.jit
def build_lookup(
    keys: Sequence[jnp.ndarray],
    valids: Sequence[jnp.ndarray],
    live: jnp.ndarray,
) -> LookupSource:
    """Build phase — HashBuilderOperator analogue, one sort instead of
    row-at-a-time inserts (join/HashBuilderOperator.java:58)."""
    any_null = None
    for v in valids:
        any_null = ~v if any_null is None else (any_null | ~v)
    usable = live if any_null is None else (live & ~any_null)
    h = hash64(list(keys), list(valids))
    h = jnp.where(usable, h, _DEAD_BUILD_HASH)
    perm = jnp.argsort(h).astype(jnp.int32)
    return LookupSource(take_clip(h, perm), perm, list(keys), list(valids), usable)


@jax.jit
def probe_counts(
    ls: LookupSource,
    probe_keys: Sequence[jnp.ndarray],
    probe_valids: Sequence[jnp.ndarray],
    probe_live: jnp.ndarray,
):
    """Phase 1: per-probe-row candidate run [lo, hi). Returns
    (lo, counts, total) — `total` is a device scalar the host reads to
    size the output batch."""
    any_null = None
    for v in probe_valids:
        any_null = ~v if any_null is None else (any_null | ~v)
    usable = probe_live if any_null is None else (probe_live & ~any_null)
    ph = hash64(list(probe_keys), list(probe_valids))
    ph = jnp.where(usable, ph, _NO_MATCH_HASH)
    lo, hi = sorted_run_bounds(ls.sorted_hash, ph)
    counts = hi - lo
    return lo, counts, jnp.sum(counts)


@partial(jax.jit, static_argnames=("out_capacity",))
def expand_matches(
    ls: LookupSource,
    probe_keys: Sequence[jnp.ndarray],
    probe_valids: Sequence[jnp.ndarray],
    lo: jnp.ndarray,
    counts: jnp.ndarray,
    out_capacity: int,
):
    """Phase 2: materialize candidate pairs, verify exact key equality.

    Returns (probe_idx, build_idx, pair_live) each (out_capacity,).
    """
    off = jnp.cumsum(counts)  # inclusive
    total = off[-1] if counts.shape[0] else jnp.int32(0)
    j = jnp.arange(out_capacity, dtype=jnp.int32)
    # which probe row produced output j: searchsorted(off, j, 'right')
    # == table-prefix count at j's run end in the tagged merge
    _, pi = sorted_run_bounds(off.astype(jnp.int64), j.astype(jnp.int64))
    pi_c = jnp.clip(pi, 0, counts.shape[0] - 1)
    start = take_clip(off, pi_c) - take_clip(counts, pi_c)
    spos = take_clip(lo, pi_c) + (j - start)
    spos = jnp.clip(spos, 0, ls.perm.shape[0] - 1)
    bi = take_clip(ls.perm, spos)
    in_range = j < total
    # exact verify (hash collisions): join equality — NULLs never match
    ok = in_range
    for pk, pv, bk, bv in zip(probe_keys, probe_valids, ls.key_cols, ls.key_valids):
        a = take_clip(pk, pi_c)
        av = take_clip(pv, pi_c)
        b = take_clip(bk, jnp.clip(bi, 0, bk.shape[0] - 1))
        bvv = take_clip(bv, jnp.clip(bi, 0, bv.shape[0] - 1))
        ok = ok & (a == b) & av & bvv
    return pi_c, bi, ok


def probe_matched_flags(probe_capacity, pi, pair_live):
    """Per-probe-row 'has >=1 verified match' — drives semi/anti joins
    (HashSemiJoinOperator analogue) and LEFT-outer row emission."""
    z = jnp.zeros(probe_capacity + 1, dtype=jnp.bool_)
    idx = jnp.where(pair_live, pi, probe_capacity)
    return z.at[idx].max(True, mode="drop")[:probe_capacity]


def build_matched_flags(build_capacity, bi, pair_live, prior=None):
    """Build-side matched bitmap for RIGHT/FULL outer joins
    (join/LookupOuterOperator.java analogue)."""
    z = prior if prior is not None else jnp.zeros(build_capacity, dtype=jnp.bool_)
    idx = jnp.where(pair_live, bi, build_capacity)
    return z.at[idx].max(True, mode="drop")
