"""Hash-join build/probe kernels.

Analogue of Trino's PagesIndex + PagesHash + JoinProbe family
(main/operator/PagesIndex.java:80, join/DefaultPagesHash.java:44,
join/LookupJoinOperator.java:36) — re-designed around sorting, which is
what TPUs do well, instead of pointer-chasing:

- Build ("LookupSource"): hash the build keys to 32 bits, sort build
  rows by hash. The sorted-hash array + permutation IS the lookup
  structure — duplicates are adjacent runs, playing the role of Trino's
  PositionLinks chains without linked lists.
- Probe: `sorted_run_bounds` positions every probe hash among the
  sorted build hashes with two single-operand packed sorts (r4
  rewrite; see its docstring for why sorts beat every alternative on
  this hardware).
- Fan-out (dynamic output size): two-phase — count matches, host picks
  a bucketed output capacity, then a dense expansion pass materializes
  (probe_row, build_row) pairs. 32-bit hash collisions are culled by an
  exact key-equality verify on the expanded pairs (the same verify
  already required for correctness under any hash width).
- Outer/semi/anti variants derive from the same expansion plus
  scatter-or'd matched flags (probe side) and a build-side matched
  bitmap (the LookupOuterOperator analogue for RIGHT/FULL joins).

SQL join-key semantics: NULL never matches NULL.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from trino_tpu.ops.gather import take_clip
from trino_tpu.ops.hashing import hash32

# u32 hash domain layout: real hashes clamp to <= REAL_MAX so the two
# sentinels own distinct top values. A probe with a NULL key must find
# nothing (NO_MATCH < DEAD: never meets dead build rows either); a dead
# or NULL-keyed build row must never be found (DEAD is the max, and no
# probe can carry it).
_H_REAL_MAX = jnp.uint32(0xFFFFFFFD)
_NO_MATCH_HASH = jnp.uint32(0xFFFFFFFE)  # probes that must find nothing
_DEAD_BUILD_HASH = jnp.uint32(0xFFFFFFFF)  # dead build rows sort last


def sorted_run_bounds(sorted_arr: jnp.ndarray, q: jnp.ndarray):
    """For each query, the run [lo, hi) of equal values in a sorted
    array — the PagesHash probe (DefaultPagesHash.java:159). Values of
    both inputs must fit in uint32 (key hashes and expansion offsets
    do by construction).

    TPU-native formulation (r4): on this hardware gathers run at
    ~16.5ms/M, scatters at ~117ms/M, XLA searchsorted at ~135ms/M, and
    the scan primitives lax.cummax/cummin hang XLA:TPU compiles the way
    associative_scan does — while a single-operand lax.sort is ~2ms/M.
    So the probe is exactly TWO single-operand packed sorts + cumsum:

    1. Each query enters the combined array TWICE — tagged to sort
       before any equal table value (where its table-prefix count = lo)
       and after (= hi). The duplicate entry replaces the rightward
       run-boundary propagation the previous design needed (a
       scatter+gather pair measured at 15.9ms per 1M rows).
    2. value(32b) | tag(2b) | query-id packs into one int64 word, so
       the combined sort carries no payload operands; a second packed
       sort on (query-id | is-hi | count) routes both bounds back to
       query order, where each query's (lo, hi) land adjacent and
       reshape to (N, 2) — no gather, no scatter anywhere.
    """
    B = sorted_arr.shape[0]
    N = q.shape[0]
    if B == 0 or N == 0:
        z = jnp.zeros(N, jnp.int32)
        return z, z
    id_bits = max(int(N - 1).bit_length(), 1)
    if id_bits > 30:  # 32-bit value + 2-bit tag + id must fit 64 bits
        raise ValueError(
            f"sorted_run_bounds: query batch of {N} rows exceeds the "
            "2^30 packed-word id budget; split the batch"
        )
    vshift = jnp.uint64(2 + id_bits)
    tshift = jnp.uint64(id_bits)
    qv = q.astype(jnp.uint64)
    tv = sorted_arr.astype(jnp.uint64)
    iota = jnp.arange(N, dtype=jnp.uint64)
    t0 = jnp.uint64(0) << tshift
    t1 = jnp.uint64(1) << tshift
    t2 = jnp.uint64(2) << tshift
    words = jnp.concatenate(
        [
            (qv << vshift) | t0 | iota,
            (tv << vshift) | t1,
            (qv << vshift) | t2 | iota,
        ]
    )
    ws = jnp.sort(words)
    tag = (ws >> tshift) & jnp.uint64(3)
    is_table = tag == jnp.uint64(1)
    # at a query entry, tables at-or-before == tables strictly before
    bp = jnp.cumsum(is_table.astype(jnp.int32)).astype(jnp.uint64)
    qid = ws & jnp.uint64((1 << id_bits) - 1)
    rid = jnp.where(is_table, jnp.uint64(N), qid)
    is_hi = (tag == jnp.uint64(2)).astype(jnp.uint64)
    res = jnp.sort(
        (rid << jnp.uint64(33)) | (is_hi << jnp.uint64(32)) | bp
    )
    pair = (res[: 2 * N] & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32)
    pair = pair.reshape(N, 2)
    return pair[:, 0], pair[:, 1]


def _key_hash(keys, valids, usable, sentinel):
    """Clamped 32-bit key hash; rows not usable get the sentinel."""
    if keys:
        h = jnp.minimum(hash32(list(keys), list(valids)), _H_REAL_MAX)
    else:
        h = jnp.zeros(usable.shape[0], dtype=jnp.uint32)
    return jnp.where(usable, h, sentinel)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class LookupSource:
    """Device-resident build side: sorted hashes + row permutation."""

    sorted_hash: jnp.ndarray  # (B,) uint32, dead rows = 0xFFFFFFFF
    perm: jnp.ndarray  # (B,) int32 — build row index at each sorted pos
    key_cols: List[jnp.ndarray]  # original (unsorted) build key columns
    key_valids: List[jnp.ndarray]
    build_live: jnp.ndarray  # (B,) bool

    def tree_flatten(self):
        return (
            (self.sorted_hash, self.perm, self.key_cols, self.key_valids, self.build_live),
            (),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        sh, perm, kc, kv, bl = children
        return cls(sh, perm, list(kc), list(kv), bl)

    @property
    def build_capacity(self) -> int:
        return int(self.perm.shape[0])


@jax.jit
def build_lookup(
    keys: Sequence[jnp.ndarray],
    valids: Sequence[jnp.ndarray],
    live: jnp.ndarray,
) -> LookupSource:
    """Build phase — HashBuilderOperator analogue, ONE single-operand
    packed sort instead of row-at-a-time inserts
    (join/HashBuilderOperator.java:58)."""
    any_null = None
    for v in valids:
        any_null = ~v if any_null is None else (any_null | ~v)
    usable = live if any_null is None else (live & ~any_null)
    h = _key_hash(keys, valids, usable, _DEAD_BUILD_HASH)
    B = h.shape[0]
    packed = (h.astype(jnp.uint64) << jnp.uint64(32)) | jnp.arange(
        B, dtype=jnp.uint64
    )
    sp = jnp.sort(packed)
    sorted_hash = (sp >> jnp.uint64(32)).astype(jnp.uint32)
    perm = (sp & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32)
    return LookupSource(sorted_hash, perm, list(keys), list(valids), usable)


@jax.jit
def probe_counts(
    ls: LookupSource,
    probe_keys: Sequence[jnp.ndarray],
    probe_valids: Sequence[jnp.ndarray],
    probe_live: jnp.ndarray,
):
    """Phase 1: per-probe-row candidate run [lo, hi). Returns
    (lo, counts, total) — `total` is a device scalar (callers defer
    reading it; see LookupJoinOperator's speculative expansion)."""
    any_null = None
    for v in probe_valids:
        any_null = ~v if any_null is None else (any_null | ~v)
    usable = probe_live if any_null is None else (probe_live & ~any_null)
    ph = _key_hash(probe_keys, probe_valids, usable, _NO_MATCH_HASH)
    lo, hi = sorted_run_bounds(ls.sorted_hash, ph)
    counts = hi - lo
    return lo, counts, jnp.sum(counts)


@partial(jax.jit, static_argnames=("out_capacity", "verify"))
def expand_matches(
    ls: LookupSource,
    probe_keys: Sequence[jnp.ndarray],
    probe_valids: Sequence[jnp.ndarray],
    lo: jnp.ndarray,
    counts: jnp.ndarray,
    out_capacity: int,
    verify: bool = True,
):
    """Phase 2: materialize candidate pairs; verify exact key equality
    (32-bit hash collisions) unless the CALLER verifies on its gathered
    pair columns instead (verify=False — saves four gathers per key:
    the pair batch carries the key columns anyway).

    Returns (probe_idx, build_idx, pair_live) each (out_capacity,).
    """
    off = jnp.cumsum(counts)  # inclusive
    total = off[-1] if counts.shape[0] else jnp.int32(0)
    j = jnp.arange(out_capacity, dtype=jnp.int32)
    # which probe row produced output j: #offs <= j (hi-rank of j among
    # the sorted offsets)
    _, pi = sorted_run_bounds(off, j)
    pi_c = jnp.clip(pi, 0, counts.shape[0] - 1)
    # lo and start ride one packed int64 gather instead of three
    packed = (
        lo.astype(jnp.int64) << jnp.int64(31)
    ) | (off - counts).astype(jnp.int64)
    g = take_clip(packed, pi_c)
    start = (g & jnp.int64((1 << 31) - 1)).astype(jnp.int32)
    spos = (g >> jnp.int64(31)).astype(jnp.int32) + (j - start)
    spos = jnp.clip(spos, 0, ls.perm.shape[0] - 1)
    bi = take_clip(ls.perm, spos)
    ok = j < total
    if verify:
        # exact verify: join equality — NULLs never match
        for pk, pv, bk, bv in zip(
            probe_keys, probe_valids, ls.key_cols, ls.key_valids
        ):
            a = take_clip(pk, pi_c)
            av = take_clip(pv, pi_c)
            b = take_clip(bk, jnp.clip(bi, 0, bk.shape[0] - 1))
            bvv = take_clip(bv, jnp.clip(bi, 0, bv.shape[0] - 1))
            eqd = a == b
            if getattr(eqd, "ndim", 1) == 2:  # long-decimal limb pairs
                eqd = eqd.all(axis=-1)
            ok = ok & eqd & av & bvv
    return pi_c, bi, ok


def probe_matched_flags(probe_capacity, pi, pair_live):
    """Per-probe-row 'has >=1 verified match' — drives semi/anti joins
    (HashSemiJoinOperator analogue) and LEFT-outer row emission."""
    z = jnp.zeros(probe_capacity + 1, dtype=jnp.bool_)
    idx = jnp.where(pair_live, pi, probe_capacity)
    return z.at[idx].max(True, mode="drop")[:probe_capacity]


def build_matched_flags(build_capacity, bi, pair_live, prior=None):
    """Build-side matched bitmap for RIGHT/FULL outer joins
    (join/LookupOuterOperator.java analogue)."""
    z = prior if prior is not None else jnp.zeros(build_capacity, dtype=jnp.bool_)
    idx = jnp.where(pair_live, bi, build_capacity)
    return z.at[idx].max(True, mode="drop")
