"""Pallas MXU grouped-aggregation kernel.

The GroupByHash + accumulate hot loop (Trino
main/operator/GroupByHash.java:30 probe + Aggregator.processPage,
SURVEY.md §3.3) mapped onto the systolic array: per row tile, the
transposed group-membership one-hot matrix is contracted against the
byte-limb decomposition of the value columns on the MXU —

    acc[L, C] += limbs(values_tile)[L, R] @ one_hot_T(gid_tile)[C, R]^T

Exactness: int64 values are split into eight 8-bit limbs *inside the
kernel* (from two int32 halves — no HBM blowup); a 256-row tile bounds
every per-tile limb sum by 256*255 < 2^16, so the f32 MXU contraction
is exact, and the int32 accumulator holds 2^15 tiles (8.4M rows) per
call. XLA recombines limbs into int64 afterwards; two's-complement
wraparound makes the limb sum equal the true int64 sum mod 2^64 —
exactly SQL BIGINT arithmetic.

Layout notes (the part that makes this TPU-native rather than a CUDA
translation): all row-major (N, k) arrays with tiny k are poison under
TPU (8, 128) tiling (the lane dim pads to 128 — measured 128x HBM
expansion), so every input is transposed to (k, N) with rows as
sublanes, and the group-id vector rides as an extra row of the lo-limb
plane. Index-map constants must be np.int32: under jax x64 they trace
as i64 and Mosaic fails to legalize the index-map signature.

CPU/test path: pallas interpret mode computes the identical program.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.experimental.pallas as pl
import jax.numpy as jnp
import numpy as np

ROW_TILE = 256
MAX_CAPACITY = 2048
# per-tile limb sums are < 2^16, so the int32 accumulator holds 2^15
# tiles before it can wrap — callers must split or fall back past this
MAX_ROWS = ROW_TILE << 15
_I0 = np.int32(0)


def _make_kernel(a8: int):
    def kernel(lo_ref, hi_ref, out_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        C = out_ref.shape[1]
        R = lo_ref.shape[1]
        gid = lo_ref[a8 - 1:a8, :]  # (1, R); dead rows carry >= C
        onehot_t = (
            jax.lax.broadcasted_iota(jnp.int32, (C, R), 0) == gid
        ).astype(jnp.float32)  # (C, R)
        planes = []
        for src in (lo_ref[:], hi_ref[:]):
            for j in range(4):
                planes.append(
                    ((src >> (8 * j)) & 0xFF).astype(jnp.float32)
                )
        limbs = jnp.concatenate(planes, axis=0)  # (8*a8, R)
        contrib = jax.lax.dot_general(
            limbs, onehot_t, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (8*a8, C)
        out_ref[:] += contrib.astype(jnp.int32)

    return kernel


@partial(jax.jit, static_argnames=("capacity", "interpret"))
def grouped_sum_mxu(
    gid: jnp.ndarray,
    values: Sequence[jnp.ndarray],
    live: jnp.ndarray,
    capacity: int,
    interpret: bool = False,
) -> List[jnp.ndarray]:
    """Per-group int64 sums of each value column, with the live-row
    count appended last. gid in [0, capacity) for live rows; dead or
    masked rows are dropped."""
    assert capacity <= MAX_CAPACITY, capacity
    n = gid.shape[0]
    assert n <= MAX_ROWS, (n, "int32 limb accumulator would overflow")
    n_pad = -n % ROW_TILE
    C = max(128, -(-capacity // 128) * 128)

    gid = jnp.where(live, gid, capacity).astype(jnp.int32)
    cols = [v.astype(jnp.int64) for v in values]
    cols.append(jnp.ones(n, dtype=jnp.int64))  # count
    a = len(cols)
    a8 = -(-(a + 1) // 8) * 8  # + the gid row, padded to sublane tile

    lo_rows, hi_rows = [], []
    for v in cols:
        if n_pad:
            v = jnp.concatenate([v, jnp.zeros(n_pad, v.dtype)])
        lo_rows.append(v.astype(jnp.int32))  # truncating wrap: low 32
        hi_rows.append((v >> 32).astype(jnp.int32))
    if n_pad:
        gid = jnp.concatenate([gid, jnp.full(n_pad, capacity, jnp.int32)])
    zero_row = jnp.zeros(n + n_pad, jnp.int32)
    lo_rows.extend([zero_row] * (a8 - a - 1) + [gid])
    hi_rows.extend([zero_row] * (a8 - a))
    lo = jnp.stack(lo_rows, axis=0)  # (a8, N')
    hi = jnp.stack(hi_rows, axis=0)

    num_tiles = (n + n_pad) // ROW_TILE
    out = pl.pallas_call(
        _make_kernel(a8),
        grid=(num_tiles,),
        in_specs=[
            pl.BlockSpec((a8, ROW_TILE), lambda i: (_I0, i)),
            pl.BlockSpec((a8, ROW_TILE), lambda i: (_I0, i)),
        ],
        out_specs=pl.BlockSpec((8 * a8, C), lambda i: (_I0, _I0)),
        out_shape=jax.ShapeDtypeStruct((8 * a8, C), jnp.int32),
        interpret=interpret,
    )(lo, hi)

    # XLA epilogue: recombine limb-plane rows -> int64 per value
    results = []
    for k in range(a):
        acc = jnp.zeros(C, dtype=jnp.int64)
        for j in range(4):
            acc = acc + (out[j * a8 + k].astype(jnp.int64) << (8 * j))
            acc = acc + (
                out[(4 + j) * a8 + k].astype(jnp.int64) << (32 + 8 * j)
            )
        results.append(acc[:capacity])
    return results


def grouped_sum_reference(gid, values, live, capacity):
    """Scatter-based oracle with identical semantics."""
    idx = jnp.where(live, gid, capacity)
    outs = []
    for v in list(values) + [jnp.ones(gid.shape[0], jnp.int64)]:
        z = jnp.zeros(capacity + 1, dtype=jnp.int64)
        outs.append(z.at[idx].add(v.astype(jnp.int64))[:capacity])
    return outs
