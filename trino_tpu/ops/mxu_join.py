"""MXU matmul join-project kernel: aggregate an equi-join without
expanding it.

For the high-fanout shape

    SELECT b.g..., SUM(p.x), COUNT(p.y), COUNT(*)
    FROM probe p JOIN build b ON p.k = b.k
    GROUP BY b.g...

the gather-expansion join (ops/join.py) materializes |pairs| rows only
for the aggregation to immediately reduce them — at fanout F the
expansion writes F·|probe| rows of HBM traffic. Because every aggregate
argument comes off the PROBE side and every group column off the BUILD
side, the pair sum factors through the key:

    result[g] = sum_j [j matched, group(j) = g] · S[kid(j)]
    S[k]      = sum over probe rows i with key(i) = k of f(i)

S is computed per probe page on the systolic array as a one-hot
indicator contraction — the grouped_sum_mxu kernel (ops/mxu_groupby.py):
limbs(values)[L, R] @ one_hot(kid)[C, R]^T with f32 accumulate, exact
int64 via 8-bit limb planes — so the join-project is a matmul and no
pair batch ever exists. The outer sum over build rows is a gather of
S[kid(j)] (at most |build| rows); the ordinary HashAggregationOperator
performs the final grouping, which brings exact group canonicalization,
NULL group keys and dictionary columns for free.

Key-id assignment is exact, not hash-trusting: build keys get dense ids
by value (one two-operand sort). The probe→kid lookup normally rides
the join plane's sorted-hash run machinery (two packed sorts, ~2ms/M)
with a representative-key verify; when the build side contains a 32-bit
hash collision between DISTINCT keys — detected once at the barrier by
comparing distinct-hash and distinct-key counts — runs are no longer
key-pure and the lookup falls back to an exact searchsorted over the
sorted distinct keys. Past the Pallas capacity/row bounds
(MAX_CAPACITY/MAX_ROWS) the contraction itself falls back to the XLA
scatter segment-sum with identical semantics.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from trino_tpu.ops import join as J
from trino_tpu.ops.gather import take_clip
from trino_tpu.ops.mxu_groupby import (
    MAX_CAPACITY,
    MAX_ROWS,
    grouped_sum_mxu,
    grouped_sum_reference,
)

__all__ = [
    "MAX_CAPACITY",
    "MAX_ROWS",
    "build_key_analysis",
    "probe_page_sums",
    "finalize_partials",
]


@jax.jit
def build_key_analysis(key, valid, live, sorted_hash, perm):
    """Dense key ids for the build side, plus the probe-lookup tables.

    Returns (kid, kid_by_pos, distinct_keys, n_distinct, hash_pure):

    - kid[j] in [0, n_distinct) for usable build rows (live, non-NULL
      key); the batch capacity B for the rest (out-of-domain sentinel).
      Ids are assigned in key-sorted order, so distinct_keys is sorted.
    - kid_by_pos[p] = kid of the build row at sorted-hash position p
      (LookupSource.perm order) — the hash-path probe reads its run's
      first position here.
    - distinct_keys[k] = the key value owning id k; tail slots hold the
      dtype max so searchsorted order is preserved.
    - hash_pure: every sorted-hash run contains exactly one distinct
      key (no 32-bit collision between distinct build keys), i.e. the
      hash-path lookup is exact after a representative-key verify.
    """
    B = key.shape[0]
    usable = live & valid
    dead = (~usable).astype(jnp.int32)
    iota = jnp.arange(B, dtype=jnp.int32)
    d_s, k_s, order = jax.lax.sort((dead, key, iota), num_keys=2)
    us = d_s == 0  # usable rows sort first
    same_prev = jnp.concatenate([
        jnp.zeros(1, dtype=jnp.bool_),
        (k_s[1:] == k_s[:-1]) & us[1:] & us[:-1],
    ])
    starts = us & ~same_prev
    kid_s = jnp.cumsum(starts.astype(jnp.int32)) - 1
    kid_s = jnp.where(us, kid_s, B)
    kid = jnp.zeros(B, jnp.int32).at[order].set(kid_s)
    n_distinct = jnp.sum(starts.astype(jnp.int32))
    distinct_keys = jnp.full(B, jnp.iinfo(key.dtype).max, dtype=key.dtype)
    distinct_keys = distinct_keys.at[kid_s].set(k_s, mode="drop")
    kid_by_pos = take_clip(kid, perm)
    # distinct real hashes == distinct keys <=> runs are key-pure
    real = sorted_hash <= jnp.uint32(0xFFFFFFFD)
    h_start = jnp.concatenate([
        jnp.ones(1, dtype=jnp.bool_), sorted_hash[1:] != sorted_hash[:-1]
    ])
    n_hash = jnp.sum((real & h_start).astype(jnp.int32))
    return kid, kid_by_pos, distinct_keys, n_distinct, n_hash == n_distinct


@partial(
    jax.jit,
    static_argnames=("kinds", "capacity", "use_mxu", "interpret", "hash_path"),
)
def probe_page_sums(
    ls,
    kid_by_pos,
    distinct_keys,
    n_distinct,
    probe_key,
    probe_key_valid,
    probe_live,
    arg_data,
    arg_valid,
    kinds,
    capacity: int,
    use_mxu: bool,
    interpret: bool,
    hash_path: bool,
):
    """One probe page's per-key contraction.

    `kinds` is the static aggregate layout; arg_data/arg_valid align
    with it (placeholders for count_star). Per kind the value columns
    are: sum -> (NULL-zeroed values, non-NULL indicator); count ->
    (non-NULL indicator); count_star -> none (the kernel's appended
    live-row count serves it). Returns the per-kid int64 sums in that
    column order with the matched-row count last.
    """
    if hash_path:
        lo, counts, _total = J.probe_counts(
            ls, [probe_key], [probe_key_valid], probe_live
        )
        pos = jnp.clip(lo, 0, ls.perm.shape[0] - 1)
        bi = take_clip(ls.perm, pos)
        rep = take_clip(ls.key_cols[0], bi)
        repv = take_clip(ls.key_valids[0], bi)
        kid = take_clip(kid_by_pos, pos)
        matched = (counts > 0) & (rep == probe_key) & repv
    else:
        pos = jnp.searchsorted(distinct_keys, probe_key).astype(jnp.int32)
        kid = jnp.clip(pos, 0, distinct_keys.shape[0] - 1)
        matched = (pos < n_distinct) & (
            take_clip(distinct_keys, kid) == probe_key
        )
    matched = matched & probe_key_valid & probe_live
    cols = []
    for kind, d, v in zip(kinds, arg_data, arg_valid):
        if kind == "sum":
            cols.append(jnp.where(v, d.astype(jnp.int64), 0))
            cols.append(v.astype(jnp.int64))
        elif kind == "count":
            cols.append(v.astype(jnp.int64))
        # count_star rides the appended live-row count
    gid = jnp.where(matched, kid, capacity)
    if use_mxu:
        return tuple(grouped_sum_mxu(
            gid, tuple(cols), matched, capacity, interpret=interpret
        ))
    return tuple(grouped_sum_reference(gid, tuple(cols), matched, capacity))


@partial(jax.jit, static_argnames=("kinds",))
def finalize_partials(kid, build_live, sums, kinds):
    """Expand the accumulated per-kid sums back onto build rows.

    A build row is live iff it is usable (kid < capacity), its batch
    row is live, and at least one probe row matched its key — an
    unmatched build row contributes no pairs, so its group must not
    exist unless another build row creates it. Returns
    (live, [(data, valid), ...] per aggregate); SUM carries
    valid = any non-NULL contribution (SQL: SUM over only NULLs is
    NULL), COUNT/COUNT(*) are always valid.
    """
    capacity = sums[-1].shape[0]
    kidc = jnp.clip(kid, 0, capacity - 1)
    cnt = take_clip(sums[-1], kidc)
    live = build_live & (kid < capacity) & (cnt > 0)
    always = jnp.ones(kid.shape[0], dtype=jnp.bool_)
    outs = []
    i = 0
    for kind in kinds:
        if kind == "sum":
            s = take_clip(sums[i], kidc)
            nn = take_clip(sums[i + 1], kidc)
            i += 2
            outs.append((s, nn > 0))
        elif kind == "count":
            c = take_clip(sums[i], kidc)
            i += 1
            outs.append((c, always))
        else:  # count_star
            outs.append((cnt, always))
    return live, outs
