"""SQL type system.

Analogue of trino-spi's type layer (spi/type/, ~80 type classes,
SURVEY.md §2.5) re-designed for XLA: every SQL type maps to a fixed-width
physical dtype so batches are static-shape device arrays. Variable-width
VARCHAR is represented as int32 dictionary codes plus a host-side
dictionary (the moral equivalent of Trino's DictionaryBlock,
spi/block/DictionaryBlock.java) — see block.py.

Trino compiles per-type equal/hash/compare operators at runtime via
TypeOperators invokedynamic handles (spi/type/TypeOperators.java:64);
here the analogue is simply that each type exposes its physical dtype and
the generic jnp ops specialize at trace time under jax.jit.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import jax.numpy as jnp
import numpy as np


class TypeKind(enum.Enum):
    BOOLEAN = "boolean"
    TINYINT = "tinyint"
    SMALLINT = "smallint"
    INTEGER = "integer"
    BIGINT = "bigint"
    REAL = "real"
    DOUBLE = "double"
    DECIMAL = "decimal"
    VARCHAR = "varchar"
    CHAR = "char"
    DATE = "date"
    TIMESTAMP = "timestamp"
    # packed (instant_millis << 12 | zone_id) int64 — the reference's
    # short encoding (spi/type/DateTimeEncoding.java); ops/tz.py
    TIMESTAMP_TZ = "timestamp with time zone"
    INTERVAL_DAY = "interval day to second"
    INTERVAL_YEAR = "interval year to month"
    ARRAY = "array"
    MAP = "map"
    ROW = "row"
    UNKNOWN = "unknown"  # type of NULL literal


@dataclasses.dataclass(frozen=True)
class DataType:
    """A SQL data type. Parametric types carry precision/scale/length;
    ARRAY carries its element type (spi/type/ArrayType analogue —
    physical layout is offsets + flattened element column, block.py
    ArrayColumn)."""

    kind: TypeKind
    precision: Optional[int] = None  # decimal precision / varchar length
    scale: Optional[int] = None  # decimal scale
    element: Optional["DataType"] = None  # ARRAY element / MAP value type
    key: Optional["DataType"] = None  # MAP key type
    # ROW fields: ((name, type), ...); names may be None (anonymous)
    row_fields: Optional[Tuple[Tuple[Optional[str], "DataType"], ...]] = None

    # ---- classification -------------------------------------------------
    @property
    def is_string(self) -> bool:
        return self.kind in (TypeKind.VARCHAR, TypeKind.CHAR)

    @property
    def is_integerlike(self) -> bool:
        return self.kind in (
            TypeKind.TINYINT,
            TypeKind.SMALLINT,
            TypeKind.INTEGER,
            TypeKind.BIGINT,
            TypeKind.DATE,
            TypeKind.TIMESTAMP,
            TypeKind.INTERVAL_DAY,
            TypeKind.INTERVAL_YEAR,
        )

    @property
    def is_decimal(self) -> bool:
        return self.kind == TypeKind.DECIMAL

    @property
    def is_long_decimal(self) -> bool:
        """decimal(19..38): Int128 carrier — physically an (n, 2) int64
        array of (signed high, unsigned low) limbs, the vectorized
        Int128ArrayBlock (spi/block/Int128ArrayBlock.java)."""
        return self.kind == TypeKind.DECIMAL and (self.precision or 0) > 18

    @property
    def lanes(self) -> int:
        """Trailing physical lanes per value (1 for flat types, 2 for
        long decimals); device arrays are (capacity,) or (capacity, lanes)."""
        return 2 if self.is_long_decimal else 1

    @property
    def is_floating(self) -> bool:
        return self.kind in (TypeKind.REAL, TypeKind.DOUBLE)

    @property
    def is_numeric(self) -> bool:
        return self.is_integerlike or self.is_decimal or self.is_floating

    @property
    def is_orderable(self) -> bool:
        return self.kind != TypeKind.UNKNOWN

    # ---- physical layout ------------------------------------------------
    @property
    def dtype(self) -> np.dtype:
        """Physical on-device dtype for one value of this type."""
        k = self.kind
        if k == TypeKind.BOOLEAN:
            return np.dtype(np.bool_)
        if k == TypeKind.TINYINT:
            return np.dtype(np.int8)
        if k == TypeKind.SMALLINT:
            return np.dtype(np.int16)
        if k in (TypeKind.INTEGER, TypeKind.DATE):
            return np.dtype(np.int32)
        if k in (
            TypeKind.BIGINT,
            TypeKind.TIMESTAMP,
            TypeKind.TIMESTAMP_TZ,
            TypeKind.DECIMAL,
            TypeKind.INTERVAL_DAY,
        ):
            return np.dtype(np.int64)
        if k == TypeKind.INTERVAL_YEAR:
            return np.dtype(np.int32)
        if k == TypeKind.REAL:
            return np.dtype(np.float32)
        if k == TypeKind.DOUBLE:
            return np.dtype(np.float64)
        if k in (TypeKind.VARCHAR, TypeKind.CHAR):
            return np.dtype(np.int32)  # dictionary codes
        if k == TypeKind.UNKNOWN:
            return np.dtype(np.int8)
        if k in (TypeKind.ARRAY, TypeKind.MAP):
            # the per-row physical value is the LENGTH (cardinality);
            # element/entry data lives in flattened child columns
            # (ArrayColumn / MapColumn)
            return np.dtype(np.int32)
        if k == TypeKind.ROW:
            # per-row physical value is a presence byte; fields live in
            # parallel child columns (RowColumn)
            return np.dtype(np.int8)
        raise ValueError(f"no physical dtype for {self}")

    @property
    def is_array(self) -> bool:
        return self.kind == TypeKind.ARRAY

    @property
    def is_map(self) -> bool:
        return self.kind == TypeKind.MAP

    @property
    def is_row(self) -> bool:
        return self.kind == TypeKind.ROW

    @property
    def is_nested(self) -> bool:
        return self.kind in (TypeKind.ARRAY, TypeKind.MAP, TypeKind.ROW)

    def __str__(self) -> str:
        if self.kind == TypeKind.ARRAY:
            return f"array({self.element})"
        if self.kind == TypeKind.MAP:
            return f"map({self.key}, {self.element})"
        if self.kind == TypeKind.ROW:
            parts = [
                (f"{n} {t}" if n else str(t)) for n, t in self.row_fields
            ]
            return f"row({', '.join(parts)})"
        if self.kind == TypeKind.DECIMAL:
            return f"decimal({self.precision},{self.scale})"
        if self.kind == TypeKind.VARCHAR and self.precision is not None:
            return f"varchar({self.precision})"
        if self.kind == TypeKind.CHAR and self.precision is not None:
            return f"char({self.precision})"
        return self.kind.value


# Singletons for the common non-parametric types.
BOOLEAN = DataType(TypeKind.BOOLEAN)
TINYINT = DataType(TypeKind.TINYINT)
SMALLINT = DataType(TypeKind.SMALLINT)
INTEGER = DataType(TypeKind.INTEGER)
BIGINT = DataType(TypeKind.BIGINT)
REAL = DataType(TypeKind.REAL)
DOUBLE = DataType(TypeKind.DOUBLE)
DATE = DataType(TypeKind.DATE)
TIMESTAMP = DataType(TypeKind.TIMESTAMP)
TIMESTAMP_TZ = DataType(TypeKind.TIMESTAMP_TZ)
VARCHAR = DataType(TypeKind.VARCHAR)
INTERVAL_DAY = DataType(TypeKind.INTERVAL_DAY)
INTERVAL_YEAR = DataType(TypeKind.INTERVAL_YEAR)
UNKNOWN = DataType(TypeKind.UNKNOWN)


MAX_DECIMAL_PRECISION = 38  # spi/type/Decimals.java MAX_PRECISION
MAX_SHORT_PRECISION = 18  # fits a scaled int64 lane


def decimal(precision: int, scale: int) -> DataType:
    if precision > MAX_DECIMAL_PRECISION:
        raise ValueError(
            f"decimal precision {precision} exceeds {MAX_DECIMAL_PRECISION}"
        )
    return DataType(TypeKind.DECIMAL, precision, scale)


def varchar(length: Optional[int] = None) -> DataType:
    return DataType(TypeKind.VARCHAR, length)


def array_of(element: DataType) -> DataType:
    return DataType(TypeKind.ARRAY, element=element)


def map_of(key: DataType, value: DataType) -> DataType:
    """MAP(key, value) — spi/type/MapType analogue. Physical layout:
    per-row entry counts + two flattened child columns (block.MapColumn)."""
    return DataType(TypeKind.MAP, key=key, element=value)


def row_of(*fields) -> DataType:
    """ROW(name type, ...) — spi/type/RowType analogue. Accepts
    (name, type) pairs or bare types (anonymous fields)."""
    out = []
    for f in fields:
        if isinstance(f, DataType):
            out.append((None, f))
        else:
            n, t = f
            out.append((n, t))
    return DataType(TypeKind.ROW, row_fields=tuple(out))


def char(length: int) -> DataType:
    return DataType(TypeKind.CHAR, length)


# ---------------------------------------------------------------------------
# Type arithmetic / coercion — the analogue of Trino's TypeCoercion
# (main/type/TypeCoercion.java): implicit-cast lattice used by the analyzer.
# ---------------------------------------------------------------------------

_NUMERIC_LADDER = [
    TypeKind.TINYINT,
    TypeKind.SMALLINT,
    TypeKind.INTEGER,
    TypeKind.BIGINT,
    TypeKind.DECIMAL,
    TypeKind.REAL,
    TypeKind.DOUBLE,
]


_TEMPORAL = {
    TypeKind.DATE,
    TypeKind.TIMESTAMP,
    TypeKind.TIMESTAMP_TZ,
    TypeKind.INTERVAL_DAY,
    TypeKind.INTERVAL_YEAR,
}


def common_super_type(a: DataType, b: DataType) -> Optional[DataType]:
    """Least common type both operands coerce to, or None."""
    if a == b:
        return a
    if a.kind == TypeKind.UNKNOWN:
        return b
    if b.kind == TypeKind.UNKNOWN:
        return a
    if a.is_string and b.is_string:
        return VARCHAR
    # temporal kinds are "integerlike" physically but never join the
    # numeric coercion ladder
    if a.kind in _TEMPORAL or b.kind in _TEMPORAL:
        if {a.kind, b.kind} == {TypeKind.DATE, TypeKind.TIMESTAMP}:
            return TIMESTAMP
        return None
    if a.kind == b.kind == TypeKind.DECIMAL:
        scale = max(a.scale, b.scale)
        intd = max(a.precision - a.scale, b.precision - b.scale)
        return decimal(min(intd + scale, MAX_DECIMAL_PRECISION), scale)
    if a.is_numeric and b.is_numeric:
        ia = _NUMERIC_LADDER.index(a.kind)
        ib = _NUMERIC_LADDER.index(b.kind)
        hi, hik = (a, a.kind) if ia >= ib else (b, b.kind)
        lo = b if ia >= ib else a
        if hik == TypeKind.DECIMAL and lo.is_integerlike:
            # integer widens into decimal at its digit capacity
            # (DecimalCasts: tinyint->3, smallint->5, int->10, bigint->19)
            ip = integer_decimal_precision(lo)
            s = hi.scale or 0
            p = min(max(hi.precision - s, ip) + s, MAX_DECIMAL_PRECISION)
            return decimal(p, s)
        if hik in (TypeKind.REAL, TypeKind.DOUBLE) and (
            lo.is_decimal or lo.is_integerlike or lo.is_floating
        ):
            return DOUBLE if hik == TypeKind.DOUBLE or lo.kind == TypeKind.DOUBLE else hi
        return hi
    return None


def integer_decimal_precision(t: DataType) -> int:
    """Digit capacity of an integer kind when it coerces to decimal
    (DecimalCasts: tinyint 3, smallint 5, integer 10, bigint 19)."""
    return {
        TypeKind.TINYINT: 3,
        TypeKind.SMALLINT: 5,
        TypeKind.INTEGER: 10,
    }.get(t.kind, 19)


def _as_decimal_shape(t: DataType):
    if t.is_decimal:
        return t.precision or 0, t.scale or 0
    return integer_decimal_precision(t), 0


def decimal_arith_type(op: str, a: DataType, b: DataType) -> DataType:
    """Trino's exact decimal operator result types
    (main/type/DecimalOperators.java signature longVariables):
      +/-: p = min(38, max(p1-s1, p2-s2) + max(s1,s2) + 1), s = max(s1,s2)
      *:   p = min(38, p1 + p2),                            s = s1 + s2
      /:   p = min(38, p1 + s2 + max(s2 - s1, 0)),          s = max(s1,s2)
      %:   p = min(p1-s1, p2-s2) + max(s1,s2),              s = max(s1,s2)
    """
    p1, s1 = _as_decimal_shape(a)
    p2, s2 = _as_decimal_shape(b)
    cap = MAX_DECIMAL_PRECISION
    if op in ("add", "sub", "+", "-"):
        return decimal(min(cap, max(p1 - s1, p2 - s2) + max(s1, s2) + 1),
                       max(s1, s2))
    if op in ("mul", "*"):
        if s1 + s2 > cap:
            raise TypeError(
                f"decimal multiply scale {s1 + s2} exceeds {cap}"
            )
        return decimal(min(cap, p1 + p2), s1 + s2)
    if op in ("div", "/"):
        return decimal(min(cap, p1 + s2 + max(s2 - s1, 0)), max(s1, s2))
    if op in ("mod", "%"):
        return decimal(min(p1 - s1, p2 - s2) + max(s1, s2), max(s1, s2))
    raise TypeError(f"unknown decimal op {op}")


def arithmetic_result_type(op: str, a: DataType, b: DataType) -> DataType:
    """Result type of a binary arithmetic expression after coercion."""
    # date/interval arithmetic
    if a.kind == TypeKind.DATE and b.kind in (TypeKind.INTERVAL_DAY, TypeKind.INTERVAL_YEAR):
        return DATE
    if b.kind == TypeKind.DATE and a.kind in (TypeKind.INTERVAL_DAY, TypeKind.INTERVAL_YEAR):
        return DATE
    if a.kind == TypeKind.TIMESTAMP or b.kind == TypeKind.TIMESTAMP:
        if a.kind in (TypeKind.INTERVAL_DAY,) or b.kind in (TypeKind.INTERVAL_DAY,):
            return TIMESTAMP
    if (a.is_decimal or b.is_decimal) and not (
        a.is_floating or b.is_floating
    ):
        opname = {"+": "add", "-": "sub", "*": "mul", "/": "div", "%": "mod"}.get(op, op)
        return decimal_arith_type(opname, a, b)
    common = common_super_type(a, b)
    if common is None:
        raise TypeError(f"cannot apply {op} to {a} and {b}")
    if common.is_integerlike and op == "/":
        return common  # integer division
    return common


def decimal_scale_factor(t: DataType) -> int:
    assert t.is_decimal
    return 10 ** (t.scale or 0)
