"""Interactive SQL console.

Analogue of client/trino-cli (Trino.java:36, Console.java:80 — jline
REPL over the statement protocol; SURVEY.md §2.11). Two modes:

  python -m trino_tpu.cli --server http://host:port     remote protocol
  python -m trino_tpu.cli --catalog tpch --schema tiny  in-process engine

`--execute "sql"` runs one statement and exits (the CLI batch mode).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional, Sequence


def format_table(column_names: Sequence[str], rows: Sequence[Sequence]) -> str:
    """ASCII table like the reference CLI's aligned output."""
    cols = [str(c) for c in column_names]
    rendered = [
        ["NULL" if v is None else str(v) for v in row] for row in rows
    ]
    widths = [len(c) for c in cols]
    for row in rendered:
        for i, v in enumerate(row):
            widths[i] = max(widths[i], len(v))
    sep = "-+-".join("-" * w for w in widths)
    out = [
        " | ".join(c.ljust(w) for c, w in zip(cols, widths)),
        sep,
    ]
    for row in rendered:
        out.append(" | ".join(v.ljust(w) for v, w in zip(row, widths)))
    out.append(f"({len(rows)} row{'s' if len(rows) != 1 else ''})")
    return "\n".join(out)


class _RemoteBackend:
    def __init__(self, uri: str):
        from trino_tpu.client import Client

        self._client = Client(uri)

    def execute(self, sql: str):
        r = self._client.execute(sql)
        return r.column_names, r.rows


class _LocalBackend:
    def __init__(self, catalog: str, schema: str):
        from trino_tpu.connectors.blackhole import create_blackhole_connector
        from trino_tpu.connectors.memory import create_memory_connector
        from trino_tpu.connectors.tpcds import create_tpcds_connector
        from trino_tpu.connectors.tpch import create_tpch_connector
        from trino_tpu.engine import LocalQueryRunner, Session

        self._runner = LocalQueryRunner(Session(catalog=catalog, schema=schema))
        self._runner.register_catalog("tpch", create_tpch_connector())
        self._runner.register_catalog("tpcds", create_tpcds_connector())
        self._runner.register_catalog("memory", create_memory_connector())
        self._runner.register_catalog("blackhole", create_blackhole_connector())

    def execute(self, sql: str):
        r = self._runner.execute(sql)
        return r.column_names, r.rows


def run_statement(backend, sql: str, out) -> bool:
    try:
        names, rows = backend.execute(sql)
        print(format_table(names, rows), file=out)
        return True
    except Exception as e:
        print(f"Query failed: {e}", file=out)
        return False


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="trino-tpu")
    ap.add_argument("--server", help="coordinator URI (remote mode)")
    ap.add_argument("--catalog", default="tpch")
    ap.add_argument("--schema", default="tiny")
    ap.add_argument("--execute", "-e", help="run one statement and exit")
    args = ap.parse_args(argv)

    backend = (
        _RemoteBackend(args.server)
        if args.server
        else _LocalBackend(args.catalog, args.schema)
    )
    if args.execute:
        ok = run_statement(backend, args.execute, sys.stdout)
        return 0 if ok else 1

    # REPL: statements end with ';'
    buffer: List[str] = []
    print("trino-tpu> ", end="", flush=True)
    for line in sys.stdin:
        buffer.append(line)
        text = "".join(buffer).strip()
        if text.lower() in ("quit", "exit", "quit;", "exit;"):
            break
        if text.endswith(";"):
            buffer = []
            if text.strip("; \n"):
                run_statement(backend, text, sys.stdout)
        print("trino-tpu> ", end="", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
