"""Central JAX configuration for trino_tpu.

Imported for side effect before any jax.numpy use. We enable x64 because
SQL semantics need BIGINT (int64) and DECIMAL-as-scaled-int64 exactness
(Trino models decimals as Int128/long — spi/type/DecimalType; we use
int64 which covers TPC-H's decimal(12,2) aggregates). Hot kernels
(hashing, probing) deliberately downcast to int32/uint32 lanes so the
TPU VPU runs native-width ops.
"""

import jax

jax.config.update("jax_enable_x64", True)


def get_shard_map():
    """Version-tolerant shard_map lookup: the top-level `jax.shard_map`
    export (newer jax, `check_vma` kwarg) first, then
    `jax.experimental.shard_map.shard_map` (0.4.x, `check_rep` kwarg)
    behind an adapter that translates the renamed kwarg. Returns None
    when neither exists so callers can degrade (mesh plane falls back to
    the page exchange; mesh tests skip) instead of failing at import."""
    try:
        from jax import shard_map as sm

        return sm
    except ImportError:
        pass
    try:
        from jax.experimental.shard_map import shard_map as _sm
    except ImportError:
        return None
    import functools
    import inspect

    params = inspect.signature(_sm).parameters
    if "check_vma" in params:
        return _sm

    @functools.wraps(_sm)
    def sm(f, **kw):
        if "check_vma" in kw:
            check = kw.pop("check_vma")
            if "check_rep" in params:
                kw["check_rep"] = check
        return _sm(f, **kw)

    return sm

# Persistent compilation cache: the engine compiles one XLA program per
# (operator, shape) and TPU compiles are tens of seconds over a
# tunneled device — caching them on disk makes every process after the
# first (test runs, bench prewarm, the driver's bench) hit warm
# executables. Management (salted directory layout, startup scrub,
# LRU eviction, counters) lives in compile/cache.py; the gating — TPU
# processes only, TRINO_TPU_NO_COMPILE_CACHE=1 opt-out — is applied
# there too.
try:
    from trino_tpu.compile.cache import configure_persistent_cache

    configure_persistent_cache()
except Exception:
    pass  # cache is an optimization; never fail import over it
