"""Central JAX configuration for trino_tpu.

Imported for side effect before any jax.numpy use. We enable x64 because
SQL semantics need BIGINT (int64) and DECIMAL-as-scaled-int64 exactness
(Trino models decimals as Int128/long — spi/type/DecimalType; we use
int64 which covers TPC-H's decimal(12,2) aggregates). Hot kernels
(hashing, probing) deliberately downcast to int32/uint32 lanes so the
TPU VPU runs native-width ops.
"""

import jax

jax.config.update("jax_enable_x64", True)
