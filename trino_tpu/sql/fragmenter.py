"""Distributed planning: AddExchanges + PlanFragmenter.

Analogue of main/sql/planner/optimizations/AddExchanges.java:140 (insert
REMOTE partitioned/broadcast/gathering exchanges by partitioning
properties, :266–276) and main/sql/planner/PlanFragmenter.java (cut the
plan at remote exchanges into a SubPlan tree of PlanFragments with
SystemPartitioningHandle-style handles — SURVEY.md §2.2, §2.7).

Two passes:
1. `add_exchanges(root)` — a properties-driven visitor that tracks each
   subtree's distribution (`single` / `source` / `hash(channels)` /
   `any`) and inserts ExchangeNodes where an operator needs a different
   one: partial->FINAL aggregation around a hash repartition, partitioned
   or broadcast joins, local-sort + merging gather, partial limits.
2. `fragment(root)` — cuts at every ExchangeNode, producing PlanFragments
   whose leaves are ScanNodes or RemoteSourceNodes.

TPU mapping: each "hash" fragment's tasks later become mesh shards; the
exchange rides ICI all_to_all when producer and consumer tasks share a
slice, and the host page wire across hosts (parallel/exchange.py holds
the collective form of the same repartition).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from trino_tpu import types as T
from trino_tpu.exec.operators import agg_state_meta
from trino_tpu.sql import plan as P


def _metrics():
    # deferred: trino_tpu.runtime's package __init__ imports the task
    # module, which imports this module (PlanFragment)
    from trino_tpu.runtime.metrics import METRICS

    return METRICS

# -- distribution properties ------------------------------------------------

SINGLE = ("single",)
SOURCE = ("source",)
ANY = ("any",)  # distributed, partitioning unknown (post-project remap loss)


def hash_dist(channels: Tuple[int, ...]):
    return ("hash", tuple(channels))


def is_distributed(dist) -> bool:
    return dist != SINGLE


# -- fragments ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PlanFragment:
    """One schedulable stage (PlanFragment analogue). `partitioning` is
    how this fragment's tasks are laid out: "single" | "hash" | "source";
    `output_kind` + `output_channels` describe the PartitionedOutput at
    its root ("single" | "hash" | "broadcast" | "arbitrary").
    `suggested_partitions` is the stats-driven task count for hash
    fragments (DeterminePartitionCount.java:90)."""

    id: int
    root: P.PlanNode
    partitioning: str
    output_kind: str
    output_channels: Tuple[int, ...] = ()
    output_merge_keys: Tuple = ()
    suggested_partitions: Optional[int] = None


@dataclasses.dataclass
class SubPlan:
    fragment: PlanFragment
    children: List["SubPlan"]

    def all_fragments(self) -> List[PlanFragment]:
        out = [self.fragment]
        for c in self.children:
            out.extend(c.all_fragments())
        return out


# -- pass 1: AddExchanges ----------------------------------------------------


class _AddExchanges:
    def __init__(self, estimate_rows, broadcast_threshold: int,
                 scan_partitioning=None):
        self._estimate = estimate_rows
        self._broadcast_threshold = broadcast_threshold
        # ScanNode -> Optional[hash_dist(...)] from declared connector
        # bucketing (AddExchanges' use of actual table partitioning via
        # NodePartitioningManager.java:96)
        self._scan_partitioning = scan_partitioning

    def visit(self, node: P.PlanNode):
        m = getattr(self, f"_{type(node).__name__}", None)
        if m is None:
            raise NotImplementedError(f"AddExchanges: {type(node).__name__}")
        return m(node)

    # leaves
    def _ScanNode(self, node):
        if self._scan_partitioning is not None:
            dist = self._scan_partitioning(node)
            if dist is not None:
                # the connector's splits ARE hash buckets on these
                # channels: downstream joins/aggs on the same keys skip
                # their repartition exchange (co-bucketed execution)
                return node, dist
        return node, SOURCE

    def _ValuesNode(self, node):
        return node, SINGLE

    # a spooled (adaptively materialized) subtree is a literal leaf
    _SpooledValuesNode = _ValuesNode

    # pass-through (channels unchanged)
    def _FilterNode(self, node):
        child, dist = self.visit(node.child)
        return dataclasses.replace(node, child=child), dist

    def _LimitNode(self, node):
        child, dist = self.visit(node.child)
        if not is_distributed(dist):
            return dataclasses.replace(node, child=child), dist
        # partial limit per task, gather, final limit (LimitNode partial)
        pre = None
        if node.count is not None:
            pre = P.LimitNode(child, node.count + node.offset, 0, node.fields)
        gathered = _gather(pre if pre is not None else child)
        return (
            P.LimitNode(gathered, node.count, node.offset, node.fields),
            SINGLE,
        )

    def _ProjectNode(self, node):
        child, dist = self.visit(node.child)
        out = dataclasses.replace(node, child=child)
        if dist[0] != "hash":
            return out, dist
        # remap hash channels through identity projections; a lost key
        # degrades the property to "any" (still distributed)
        mapping: Dict[int, int] = {}
        from trino_tpu.expr.ir import InputRef

        for i, e in enumerate(node.exprs):
            if isinstance(e, InputRef) and e.index not in mapping:
                mapping[e.index] = i
        new_channels = []
        for c in dist[1]:
            if c not in mapping:
                return out, ANY
            new_channels.append(mapping[c])
        return out, hash_dist(tuple(new_channels))

    def _EnforceSingleRowNode(self, node):
        child, dist = self.visit(node.child)
        if is_distributed(dist):
            child = _gather(child)
        return dataclasses.replace(node, child=child), SINGLE

    def _SortNode(self, node):
        child, dist = self.visit(node.child)
        if not is_distributed(dist):
            return dataclasses.replace(node, child=child), dist
        # local sort per task + merging gather (distributed sort,
        # MergeOperator.java:46 / dist-sort.rst)
        local = P.SortNode(child, node.keys, node.fields)
        ex = P.ExchangeNode(
            local, "gather", (), node.fields, merge_keys=tuple(node.keys)
        )
        return ex, SINGLE

    def _TopNNode(self, node):
        child, dist = self.visit(node.child)
        if not is_distributed(dist):
            return dataclasses.replace(node, child=child), dist
        partial = P.TopNNode(child, node.keys, node.count, node.fields)
        gathered = _gather(partial)
        return P.TopNNode(gathered, node.keys, node.count, node.fields), SINGLE

    def _UnionAllNode(self, node):
        new_inputs = []
        for child in node.inputs:
            c, dist = self.visit(child)
            if is_distributed(dist):
                c = _gather(c)
            new_inputs.append(c)
        return dataclasses.replace(node, inputs=tuple(new_inputs)), SINGLE

    def _OutputNode(self, node):
        child, dist = self.visit(node.child)
        if is_distributed(dist):
            child = _gather(child)
        return dataclasses.replace(node, child=child), SINGLE

    # aggregation: naive single-step placement over a repartition or
    # gather; push_partial_aggregation_through_exchange later splits it
    # into partial -> exchange -> final (the Trino split between
    # AddExchanges and PushPartialAggregationThroughExchange)
    def _AggregateNode(self, node):
        child, dist = self.visit(node.child)
        from trino_tpu.exec.operators import HOLISTIC_KINDS

        holistic = any(a.kind in HOLISTIC_KINDS for a in node.aggs)
        if not is_distributed(dist) or holistic or any(
            a.distinct for a in node.aggs
        ):
            # distinct and holistic aggregation run single-step after a
            # gather (the MarkDistinct distributed form and mergeable
            # holistic sketches are future work)
            if is_distributed(dist):
                child = _gather(child)
            return dataclasses.replace(node, child=child), SINGLE
        groups = tuple(node.group_channels)
        if groups and dist == hash_dist(groups):
            # child already partitioned on the exact grouping keys: the
            # repartition exchange is provably redundant (co-bucketed
            # scans, or an upstream join/agg on the same keys)
            _metrics().increment("exchanges_elided")
            out = dataclasses.replace(node, child=child)
            return out, hash_dist(tuple(range(len(groups))))
        if not groups:
            return dataclasses.replace(node, child=_gather(child)), SINGLE
        ex = P.ExchangeNode(
            child, "repartition", groups, tuple(child.fields)
        )
        out = dataclasses.replace(node, child=ex)
        return out, hash_dist(tuple(range(len(groups))))

    def _WindowNode(self, node):
        child, dist = self.visit(node.child)
        if not is_distributed(dist):
            return dataclasses.replace(node, child=child), dist
        keys = tuple(node.partition_channels)
        if not keys:
            # no PARTITION BY: the whole input is one window partition
            child = _gather(child)
            return dataclasses.replace(node, child=child), SINGLE
        if dist != hash_dist(keys):
            child = P.ExchangeNode(
                child, "repartition", keys, tuple(node.child.fields)
            )
        else:
            _metrics().increment("exchanges_elided")
        out = dataclasses.replace(node, child=child)
        # window appends columns; partition channel positions survive
        return out, hash_dist(keys)

    # joins: partitioned or broadcast
    def _JoinNode(self, node):
        left, ldist = self.visit(node.left)
        right, rdist = self.visit(node.right)
        if not is_distributed(ldist) and not is_distributed(rdist):
            return dataclasses.replace(node, left=left, right=right), SINGLE

        build_rows = self._estimate(node.right)
        # FULL outer can never broadcast: a replicated build would emit
        # its unmatched rows once PER TASK (AddExchanges enforces the
        # same partitioned-only rule for full joins)
        broadcast = node.kind != "full" and (
            node.kind == "cross"
            or not node.right_keys
            or build_rows <= self._broadcast_threshold
        )
        if broadcast:
            # Replicate the build side whenever EITHER side is
            # distributed. A single-distribution build must still cross a
            # fragment boundary when the probe is multi-task: its internal
            # gather exchanges deliver to one consumer partition only, so
            # leaving it inline would starve every probe task but one.
            if is_distributed(rdist) or is_distributed(ldist):
                right = P.ExchangeNode(
                    right, "broadcast", (), _fields_of(node.right)
                )
            out_dist = ldist if is_distributed(ldist) else SINGLE
            return (
                dataclasses.replace(node, left=left, right=right),
                out_dist,
            )
        # partitioned join: both sides hash-distributed on the join keys
        lkeys, rkeys = tuple(node.left_keys), tuple(node.right_keys)
        if ldist != hash_dist(lkeys):
            left = P.ExchangeNode(left, "repartition", lkeys, _fields_of(node.left))
        else:
            _metrics().increment("exchanges_elided")
        if rdist != hash_dist(rkeys):
            right = P.ExchangeNode(right, "repartition", rkeys, _fields_of(node.right))
        else:
            _metrics().increment("exchanges_elided")
        out = dataclasses.replace(node, left=left, right=right)
        # semi/anti keep only left columns; inner/left keep left prefix —
        # either way the left keys' positions survive unchanged
        return out, hash_dist(lkeys)


def _fields_of(node: P.PlanNode) -> Tuple[P.Field, ...]:
    return tuple(node.fields)


def _gather(node: P.PlanNode) -> P.ExchangeNode:
    return P.ExchangeNode(node, "gather", (), tuple(node.fields))


def _partial_fields(node: P.AggregateNode, child: P.PlanNode) -> List[P.Field]:
    """Fields of the partial step's output (partial_output_schema shape)."""
    child_types = [(f.type, None) for f in child.fields]
    fields = [child.fields[c] for c in node.group_channels]
    for a in node.aggs:
        spec = _spec_of(a)
        (vt, _), _ = agg_state_meta(spec, child_types)
        name = a.kind if a.arg_channel is None else f"{a.kind}_{a.arg_channel}"
        fields.append(P.Field(f"{name}_val", vt))
        fields.append(P.Field(f"{name}_cnt", T.BIGINT))
    return fields


def _spec_of(a: P.AggCall):
    from trino_tpu.exec.operators import AggSpec

    return AggSpec(a.kind, a.arg_channel, a.out_type, a.distinct,
                   a.arg2_channel, a.percentile, a.separator,
                   a.arg3_channel, a.param, a.post)


# -- exchange-tree rewrite passes --------------------------------------------


def eliminate_redundant_exchanges(root: P.PlanNode) -> P.PlanNode:
    """Drop a repartition feeding another repartition on the same keys:
    the inner shuffle lays rows out exactly as the outer one will again,
    so it only costs wire time. Arises when property tracking degrades
    to ANY (e.g. through a projection that drops a key) and a
    conservative repartition gets stacked on an existing one. Counted
    in the `exchanges_elided` metric alongside the property-driven
    skips in _AddExchanges."""

    def walk(n: P.PlanNode) -> P.PlanNode:
        kids = [walk(c) for c in n.children()]
        if kids:
            n = _replace_children(n, kids)
        if (
            isinstance(n, P.ExchangeNode)
            and n.kind == "repartition"
            and isinstance(n.child, P.ExchangeNode)
            and n.child.kind == "repartition"
            and n.child.hash_channels == n.hash_channels
            and not n.child.merge_keys
        ):
            _metrics().increment("exchanges_elided")
            n = dataclasses.replace(n, child=n.child.child)
        return n

    return walk(root)


# skip the partial/final split when the estimated aggregation output is
# at least this fraction of its input: the partial step would shrink
# nothing, so it only adds a device pass + a wider wire schema
PARTIAL_AGG_MIN_REDUCTION = 0.9


def push_partial_aggregation_through_exchange(
    root: P.PlanNode, stats=None
) -> P.PlanNode:
    """Split a mergeable single-step aggregation sitting on a
    repartition (or gather) exchange into partial -> exchange -> final,
    so each producer task pre-aggregates before rows cross the wire
    (PushPartialAggregationThroughExchange.java as an explicit pass
    over the naive plan _AddExchanges now emits).

    With a StatsCalculator the split is cost-based: when NDV(group
    keys) ~= input rows (estimated output >= PARTIAL_AGG_MIN_REDUCTION
    of input) the partial step cannot reduce wire volume and is
    skipped — Trino's preferPartialAggregation cost gate. Without
    stats (legacy one-arg callers) the split stays structural."""
    from trino_tpu.exec.operators import HOLISTIC_KINDS

    def walk(n: P.PlanNode) -> P.PlanNode:
        kids = [walk(c) for c in n.children()]
        if kids:
            n = _replace_children(n, kids)
        if not isinstance(n, P.AggregateNode) or n.step != "single":
            return n
        if any(a.kind in HOLISTIC_KINDS or a.distinct for a in n.aggs):
            return n
        ex = n.child
        if not isinstance(ex, P.ExchangeNode) or ex.merge_keys:
            return n
        groups = tuple(n.group_channels)
        if ex.kind == "repartition":
            if not groups or set(ex.hash_channels) != set(groups):
                return n
        elif ex.kind != "gather" or groups:
            return n
        if stats is not None and groups:
            # skip the split ONLY on confident stats: every group key
            # needs a known NDV. Unknown NDV defaults to sqrt(rows) in
            # StatsCalculator, so with >=2 keys the product saturates
            # at row_count and the gate would silently disable partial
            # aggregation everywhere (TPC-DS q72 regressed ~20% wall
            # from exactly that) — unknown stats keep the structural
            # split, which is also runtime-adaptive on the wire.
            try:
                child_stats = stats.stats(ex.child)
                in_rows = child_stats.row_count
                ndvs = [child_stats.col(c).ndv for c in groups]
            except Exception:
                in_rows, ndvs = None, [None]
            if in_rows and all(v is not None for v in ndvs):
                out_rows = 1.0
                for v in ndvs:
                    out_rows *= v
                out_rows = min(out_rows, in_rows)
                if out_rows >= PARTIAL_AGG_MIN_REDUCTION * in_rows:
                    return n
        k = len(groups)
        partial_fields = tuple(_partial_fields(n, ex.child))
        partial = dataclasses.replace(
            n, child=ex.child, step="partial", fields=partial_fields
        )
        final_aggs = tuple(
            dataclasses.replace(a, arg_channel=k + 2 * i)
            for i, a in enumerate(n.aggs)
        )
        if ex.kind == "gather":
            new_ex = P.ExchangeNode(partial, "gather", (), partial_fields)
        else:
            # partial output puts the group keys first
            new_ex = P.ExchangeNode(
                partial, "repartition", tuple(range(k)), partial_fields
            )
        return P.AggregateNode(
            new_ex, tuple(range(k)), final_aggs, n.fields, step="final"
        )

    return walk(root)


# -- row estimation: the cost-based StatsCalculator (sql/stats.py) -----------


def make_row_estimator(catalogs):
    """Cardinality estimates for the broadcast-vs-partitioned decision,
    backed by the stats-propagation framework (main/cost/ analogue)."""
    from trino_tpu.sql.stats import StatsCalculator

    calc = StatsCalculator(catalogs)
    return lambda node: calc.stats(node).row_count


# -- pass 2: fragment cutting ------------------------------------------------


class _Fragmenter:
    def __init__(self):
        self.fragments: Dict[int, PlanFragment] = {}
        self.children: Dict[int, List[int]] = {}
        self._next_id = 0

    def cut(self, root: P.PlanNode) -> SubPlan:
        """Cut the exchange-annotated plan; the root fragment is always
        single-partitioned (the coordinator-consumed stage)."""
        new_root, child_ids = self._rewrite(root)
        fid = self._new_fragment(new_root, "single", (), ())
        self.children[fid] = child_ids
        return self._subplan(fid)

    def _subplan(self, fid: int) -> SubPlan:
        return SubPlan(
            self.fragments[fid],
            [self._subplan(c) for c in self.children.get(fid, [])],
        )

    def _new_fragment(self, root, output_kind, output_channels, merge_keys) -> int:
        fid = self._next_id
        self._next_id += 1
        self.fragments[fid] = PlanFragment(
            id=fid,
            root=root,
            partitioning=_fragment_partitioning(root),
            output_kind=output_kind,
            output_channels=tuple(output_channels),
            output_merge_keys=tuple(merge_keys),
        )
        return fid

    def _rewrite(self, node: P.PlanNode) -> Tuple[P.PlanNode, List[int]]:
        """Replace each ExchangeNode subtree with a RemoteSourceNode and
        register the producer fragment. Returns (node', child fragment
        ids referenced anywhere below node)."""
        if isinstance(node, P.ExchangeNode):
            child, grandchildren = self._rewrite(node.child)
            if node.kind == "gather":
                out_kind, out_channels = "single", ()
            elif node.kind == "repartition":
                out_kind, out_channels = "hash", node.hash_channels
            else:
                out_kind, out_channels = "broadcast", ()
            fid = self._new_fragment(child, out_kind, out_channels, node.merge_keys)
            self.children[fid] = grandchildren
            rs = P.RemoteSourceNode(
                (fid,), tuple(node.fields), tuple(node.merge_keys)
            )
            return rs, [fid]
        kids = list(node.children())
        if not kids:
            return node, []
        new_kids, ids = [], []
        for c in kids:
            nc, cids = self._rewrite(c)
            new_kids.append(nc)
            ids.extend(cids)
        return _replace_children(node, new_kids), ids


def _replace_children(node: P.PlanNode, kids: List[P.PlanNode]) -> P.PlanNode:
    if isinstance(node, P.JoinNode):
        return dataclasses.replace(node, left=kids[0], right=kids[1])
    if isinstance(node, P.UnionAllNode):
        return dataclasses.replace(node, inputs=tuple(kids))
    return dataclasses.replace(node, child=kids[0])


def _make_scan_partitioning(catalogs, target_splits: int):
    """ScanNode -> Optional[hash_dist] from the connector's declared
    bucketing (spi.ConnectorMetadata.table_partitioning). The derived
    property relies on both schedulers' split assignment rule — task p
    of tc scans splits[p::tc] of get_splits(max(target_splits, tc)) — so
    bucket i lands on task i only when the connector returns EXACTLY tc
    splits; with a session target_splits > 1 the request can exceed tc
    and fold several buckets onto one task, where a runtime-repartitioned
    third side would no longer align. Bucketing is therefore only
    claimed at the default split target."""
    if target_splits > 1:
        return None

    def resolve(node):
        try:
            conn = catalogs.get(node.catalog)
            cols = conn.metadata.table_partitioning(node.handle)
        except Exception:
            return None
        if not cols:
            return None
        try:
            chans = tuple(node.columns.index(c) for c in cols)
        except ValueError:
            # a pruned-away bucket column: splits are still buckets, but
            # the property is unverifiable downstream — stay SOURCE
            return None
        return hash_dist(chans)

    return resolve


def _fragment_partitioning(root: P.PlanNode) -> str:
    """Task layout of a fragment, derived from its leaves: connector
    splits ("source"), hash-partitioned remote input ("hash"), else a
    single task. Broadcast-only remote inputs pair with whatever the
    other leaves say (a broadcast build feeding a source-distributed
    probe keeps "source")."""
    def any_node(n, pred) -> bool:
        return pred(n) or any(any_node(c, pred) for c in n.children())

    if any_node(root, lambda n: isinstance(n, P.ScanNode)):
        return "source"
    # consumer of a hash repartition is hash-partitioned; a gather/
    # broadcast-only consumer runs single — plan_distributed refines
    # this once producers are known (consumes_hash_input).
    if any_node(root, lambda n: isinstance(n, P.RemoteSourceNode)):
        return "hash"
    return "single"


def consumes_hash_input(fragment: PlanFragment, producers: Dict[int, PlanFragment]) -> bool:
    """True when any remote source feeding this fragment is
    hash-partitioned output (fixed task count > 1 is meaningful)."""
    found = [False]

    def walk(n):
        if isinstance(n, P.RemoteSourceNode):
            for fid in n.fragment_ids:
                if producers[fid].output_kind == "hash":
                    found[0] = True
        for c in n.children():
            walk(c)

    walk(fragment.root)
    return found[0]


# -- public entry ------------------------------------------------------------


def plan_distributed(
    root: P.OutputNode,
    catalogs,
    broadcast_threshold: int = 1_000_000,
    target_splits: int = 1,
    validation: str = "passes",
) -> SubPlan:
    """Logical plan -> SubPlan tree of PlanFragments (the
    LogicalPlanner->AddExchanges->PlanFragmenter.createSubPlans path).
    `validation` != "off" runs the fragment-level sanity checkers
    (sql/validate.py) over the result before it ships to schedulers."""
    from trino_tpu.sql.stats import StatsCalculator

    calc = StatsCalculator(catalogs)
    estimate = lambda node: calc.stats(node).row_count
    adder = _AddExchanges(
        estimate, broadcast_threshold,
        scan_partitioning=_make_scan_partitioning(catalogs, target_splits),
    )
    annotated, _ = adder.visit(root)
    annotated = eliminate_redundant_exchanges(annotated)
    annotated = push_partial_aggregation_through_exchange(annotated, calc)
    subplan = _Fragmenter().cut(annotated)
    # refine "hash" vs "single" partitioning now that producers are known,
    # and derive stats-driven partition counts per hash stage
    frags = {f.id: f for f in subplan.all_fragments()}
    from trino_tpu.sql.stats import determine_partition_count

    def hash_input_rows(fragment: PlanFragment) -> float:
        total = [0.0]

        def walk(n):
            if isinstance(n, P.RemoteSourceNode):
                for fid in n.fragment_ids:
                    prod = frags[fid]
                    if prod.output_kind == "hash":
                        total[0] += estimate(prod.root)
            for c in n.children():
                walk(c)

        walk(fragment.root)
        return total[0]

    def refine(sp: SubPlan):
        f = sp.fragment
        if f.partitioning == "hash":
            if not consumes_hash_input(f, frags):
                sp.fragment = dataclasses.replace(f, partitioning="single")
            else:
                rows = hash_input_rows(f)
                sp.fragment = dataclasses.replace(
                    f,
                    suggested_partitions=determine_partition_count(rows, 1 << 10),
                )
        for c in sp.children:
            refine(c)

    refine(subplan)
    if validation != "off":
        from trino_tpu.sql.validate import validate_subplan

        validate_subplan(subplan)
    return subplan


def explain_distributed(
    subplan: SubPlan,
    catalogs=None,
    batch_rows: int = 1 << 20,
    dynamic_filtering: bool = True,
    warn_threshold: int = 0,
) -> str:
    """EXPLAIN (TYPE DISTRIBUTED) rendering: one section per fragment.
    With `catalogs` each fragment also carries its compile-churn census
    summary (`expected_xla_lowerings` — sql/validate.py)."""
    lines = []
    for f in sorted(subplan.all_fragments(), key=lambda f: f.id):
        out = f.output_kind
        if f.output_channels:
            out += f" on={list(f.output_channels)}"
        header = f"Fragment {f.id} [{f.partitioning}] output={out}"
        if catalogs is not None:
            from trino_tpu.sql.validate import census_line, shape_census

            classes = shape_census(
                f.root, catalogs, batch_rows=batch_rows,
                dynamic_filtering=dynamic_filtering,
            )
            header += " " + census_line(classes, warn_threshold)
        lines.append(header)
        lines.append(P.explain_text(f.root, indent=1))
    return "\n".join(lines)
