"""Plan optimizer: memo, iterative rule engine, cost-based join reorder.

Analogue of the reference's optimizer stack (SURVEY.md §2.2):

- `Memo` — group-per-subtree plan store whose nodes point at child
  *groups* (main/sql/planner/iterative/Memo.java:37). Rules replace a
  group's representative without rebuilding the whole tree.
- `IterativeOptimizer` — applies a rule set to every group to fixpoint
  (main/sql/planner/iterative/IterativeOptimizer.java:63). Rules get a
  `Context` with a GroupRef resolver and a StatsCalculator, mirroring
  Rule.Context's Lookup + StatsProvider.
- `ReorderJoins` — cost-based join-order search over maximal inner-join
  regions: DPsub over connected sub-graphs with probe/build orientation
  chosen by cost, replacing the analyzer's greedy smaller-side order
  (main/sql/planner/iterative/rule/ReorderJoins.java:84 + main/cost/
  JoinStatsRule / CostCalculatorUsingExchanges). Output schema is
  restored with a permutation Project so enclosing plans are untouched.

The pass pipeline (`optimize`) mirrors PlanOptimizers.java's staged
list: simplification to fixpoint, then join reordering, then a cleanup
fixpoint for the projections reordering introduces.

The rule inventory is deliberately smaller than the reference's ~220:
the analyzer already plans subqueries/pushdowns during translation, so
the rules here are the ones with post-translation leverage. Each rule
cites its reference analogue.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from trino_tpu import types as T
from trino_tpu.expr import ir
from trino_tpu.sql import plan as P
from trino_tpu.sql.cost import CostCalculator
from trino_tpu.sql.stats import StatsCalculator

MAX_DP_LEAVES = 10       # beyond this, keep the analyzer's greedy order
MAX_FIXPOINT_PASSES = 16


# ---------------------------------------------------------------------------
# expression utilities
# ---------------------------------------------------------------------------


def expr_refs(e: ir.Expr) -> set:
    """Channels an expression reads."""
    out: set = set()

    def walk(x: ir.Expr):
        if isinstance(x, ir.InputRef):
            out.add(x.index)
        for c in x.children():
            walk(c)

    walk(e)
    return out


def substitute(e: ir.Expr, mapping: Dict[int, ir.Expr]) -> ir.Expr:
    """Replace InputRefs by expressions (projection inlining)."""
    if isinstance(e, ir.InputRef):
        return mapping[e.index]
    if isinstance(e, ir.Call):
        return ir.Call(e.name, tuple(substitute(a, mapping) for a in e.args), e.type)
    if isinstance(e, ir.Cast):
        return ir.Cast(substitute(e.arg, mapping), e.type)
    if isinstance(e, ir.Case):
        return ir.Case(
            tuple(substitute(c, mapping) for c in e.conds),
            tuple(substitute(r, mapping) for r in e.results),
            substitute(e.default, mapping) if e.default is not None else None,
            e.type,
        )
    if isinstance(e, ir.InList):
        return ir.InList(substitute(e.value, mapping), e.options, e.type)
    return e  # Literal


def shift_refs(e: ir.Expr, delta: int) -> ir.Expr:
    if isinstance(e, ir.InputRef):
        return ir.InputRef(e.index + delta, e.type)
    if isinstance(e, ir.Call):
        return ir.Call(e.name, tuple(shift_refs(a, delta) for a in e.args), e.type)
    if isinstance(e, ir.Cast):
        return ir.Cast(shift_refs(e.arg, delta), e.type)
    if isinstance(e, ir.Case):
        return ir.Case(
            tuple(shift_refs(c, delta) for c in e.conds),
            tuple(shift_refs(r, delta) for r in e.results),
            shift_refs(e.default, delta) if e.default is not None else None,
            e.type,
        )
    if isinstance(e, ir.InList):
        return ir.InList(shift_refs(e.value, delta), e.options, e.type)
    return e


def split_conjuncts(e: ir.Expr) -> List[ir.Expr]:
    if isinstance(e, ir.Call) and e.name == "and":
        out: List[ir.Expr] = []
        for a in e.args:
            out.extend(split_conjuncts(a))
        return out
    return [e]


# ---------------------------------------------------------------------------
# child plumbing for frozen plan nodes
# ---------------------------------------------------------------------------


def with_children(node: P.PlanNode, new_children: Sequence[P.PlanNode]) -> P.PlanNode:
    kids = tuple(node.children())
    if len(kids) != len(new_children):
        raise ValueError("child arity mismatch")
    if all(a is b for a, b in zip(kids, new_children)):
        return node
    if isinstance(node, P.JoinNode):
        left, right = new_children
        return dataclasses.replace(node, left=left, right=right)
    if isinstance(node, P.UnionAllNode):
        return dataclasses.replace(node, inputs=tuple(new_children))
    return dataclasses.replace(node, child=new_children[0])


def _fresh_tree(node: P.PlanNode) -> P.PlanNode:
    """Rebuild every interior node of a subtree as a new object.

    Rewrites that replicate a subtree into several plan positions (the
    multi-sketch UNION ALL expansion) must not alias the same node
    object from two parents: node identity doubles as the plan-node id,
    and id()-keyed consumers (StatsCalculator's memo, the structure
    validator) assume tree shape. Leaves stay shared — they have no
    children for a traversal to double-visit.
    """
    kids = tuple(node.children())
    if not kids:
        return node
    new_kids = [_fresh_tree(k) for k in kids]
    if isinstance(node, P.JoinNode):
        return dataclasses.replace(node, left=new_kids[0], right=new_kids[1])
    if isinstance(node, P.UnionAllNode):
        return dataclasses.replace(node, inputs=tuple(new_kids))
    return dataclasses.replace(node, child=new_kids[0])


# ---------------------------------------------------------------------------
# Memo
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GroupRef(P.PlanNode):
    """Placeholder child pointing at a memo group
    (iterative/GroupReference.java)."""

    group: int
    fields: Tuple[P.Field, ...]

    def children(self):
        return ()


class Memo:
    """Plan store: every subtree lives in a group; nodes reference child
    groups through GroupRef (Memo.java:37 — without multi-expression
    exploration groups; one representative per group, like the
    reference's, which also keeps exactly one node per group and relies
    on rules returning full replacements)."""

    def __init__(self, root: P.PlanNode):
        self._nodes: Dict[int, P.PlanNode] = {}
        self._next = 0
        self.root = self._insert(root)

    def _insert(self, node: P.PlanNode) -> int:
        if isinstance(node, GroupRef):
            return node.group
        kids = [
            GroupRef(self._insert(c), c.fields)
            if not isinstance(c, GroupRef) else c
            for c in node.children()
        ]
        gid = self._next
        self._next += 1
        self._nodes[gid] = with_children(node, kids) if kids else node
        return gid

    def node(self, gid: int) -> P.PlanNode:
        return self._nodes[gid]

    def resolve(self, node: P.PlanNode) -> P.PlanNode:
        """GroupRef -> its group's current representative."""
        if isinstance(node, GroupRef):
            return self._nodes[node.group]
        return node

    def replace(self, gid: int, new_subtree: P.PlanNode) -> None:
        """Install a replacement for a group; fresh (non-GroupRef)
        children get groups of their own."""
        kids = [
            c if isinstance(c, GroupRef)
            else GroupRef(self._insert(c), c.fields)
            for c in new_subtree.children()
        ]
        self._nodes[gid] = (
            with_children(new_subtree, kids) if kids else new_subtree
        )

    def extract(self, gid: Optional[int] = None) -> P.PlanNode:
        gid = self.root if gid is None else gid
        node = self._nodes[gid]
        kids = [
            self.extract(c.group) if isinstance(c, GroupRef) else c
            for c in node.children()
        ]
        return with_children(node, kids) if kids else node

    def groups(self) -> List[int]:
        return list(self._nodes)


@dataclasses.dataclass
class Context:
    """Rule.Context analogue: lookup + stats. `last_rule` records the
    most recently applied rule so a PlanValidationError can name the
    rewrite that broke the invariant."""

    memo: Memo
    stats: Optional[StatsCalculator] = None
    last_rule: Optional[str] = None

    def resolve(self, node: P.PlanNode) -> P.PlanNode:
        return self.memo.resolve(node)


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


class Rule:
    """apply() returns a replacement subtree (children may be the
    matched node's GroupRef children) or None for no match."""

    name = "rule"

    def apply(self, node: P.PlanNode, ctx: Context) -> Optional[P.PlanNode]:
        raise NotImplementedError


class MergeFilters(Rule):
    """Filter(Filter(x)) -> Filter(x, p1 AND p2)
    (rule/MergeFilters.java)."""

    name = "merge_filters"

    def apply(self, node, ctx):
        if not isinstance(node, P.FilterNode):
            return None
        child = ctx.resolve(node.child)
        if not isinstance(child, P.FilterNode):
            return None
        return P.FilterNode(
            child.child,
            ir.and_(child.predicate, node.predicate),
            node.fields,
        )


class RemoveIdentityProject(Rule):
    """Project that reproduces its child verbatim disappears
    (rule/RemoveRedundantIdentityProjections.java)."""

    name = "remove_identity_project"

    def apply(self, node, ctx):
        if not isinstance(node, P.ProjectNode):
            return None
        child = ctx.resolve(node.child)
        if len(node.exprs) != len(child.fields):
            return None
        if node.fields != child.fields:
            return None
        for i, e in enumerate(node.exprs):
            if not (isinstance(e, ir.InputRef) and e.index == i):
                return None
        # splice the child's group in place of this one
        return child if not isinstance(node.child, GroupRef) else ctx.memo.node(
            node.child.group
        )


class InlineProjections(Rule):
    """Project(Project(x)) -> Project(x) when safe: every inner
    expression is trivial or referenced at most once
    (rule/InlineProjections.java's duplication guard)."""

    name = "inline_projections"

    def apply(self, node, ctx):
        if not isinstance(node, P.ProjectNode):
            return None
        child = ctx.resolve(node.child)
        if not isinstance(child, P.ProjectNode):
            return None
        counts: Dict[int, int] = {}
        for e in node.exprs:
            for r in expr_refs(e):
                counts[r] = counts.get(r, 0) + 1
        for idx, inner in enumerate(child.exprs):
            trivial = isinstance(inner, (ir.InputRef, ir.Literal))
            if not trivial and counts.get(idx, 0) > 1:
                return None
        mapping = dict(enumerate(child.exprs))
        return P.ProjectNode(
            child.child,
            tuple(substitute(e, mapping) for e in node.exprs),
            node.fields,
        )


class PushFilterThroughProject(Rule):
    """Filter(Project(x)) -> Project(Filter(x)) by substituting the
    projection into the predicate (rule/PushdownFilterIntoProject
    family); filters run earlier and joins below become visible to
    reordering."""

    name = "push_filter_through_project"

    def apply(self, node, ctx):
        if not isinstance(node, P.FilterNode):
            return None
        child = ctx.resolve(node.child)
        if not isinstance(child, P.ProjectNode):
            return None
        # duplication guard (the reference's isInliningCandidate): only
        # push when every projection the predicate touches is trivial —
        # otherwise the expensive expression runs in the filter AND in
        # the retained Project
        for r in expr_refs(node.predicate):
            if r < len(child.exprs) and not isinstance(
                child.exprs[r], (ir.InputRef, ir.Literal)
            ):
                return None
        mapping = dict(enumerate(child.exprs))
        pred = substitute(node.predicate, mapping)
        grandchild = child.child
        return P.ProjectNode(
            P.FilterNode(
                grandchild,
                pred,
                ctx.resolve(grandchild).fields
                if isinstance(grandchild, GroupRef)
                else grandchild.fields,
            ),
            child.exprs,
            child.fields,
        )


class InferTransitivePredicates(Rule):
    """EqualityInference over a post-join filter (sql/equality.py —
    main/sql/planner/EqualityInference.java:57): equivalence classes
    from inner-join equi-keys and conjunct equalities; every
    single-channel deterministic conjunct is replicated onto each
    equivalent channel, so a filter on one join key reaches the other
    side's scan once PushFilterIntoJoin distributes the conjuncts.
    Fires at most once per filter (derive() returns only conjuncts not
    already present), ordered BEFORE PushFilterIntoJoin so the derived
    copies are still above the join when they appear."""

    name = "infer_transitive_predicates"

    def apply(self, node, ctx):
        from trino_tpu.sql.equality import EqualityInference

        if not isinstance(node, P.FilterNode):
            return None
        join = ctx.resolve(node.child)
        if not isinstance(join, P.JoinNode) or join.kind not in ("inner", "cross"):
            return None
        left = ctx.resolve(join.left)
        width_l = len(left.fields)
        conjuncts = split_conjuncts(node.predicate)
        inf = EqualityInference()
        for lk, rk in zip(join.left_keys, join.right_keys):
            inf.add_equality(lk, width_l + rk)
        inf.add_conjunct_equalities(conjuncts)
        derived = inf.derive(conjuncts, join.fields, _is_deterministic)
        if not derived:
            return None
        return P.FilterNode(
            node.child, ir.and_(*(conjuncts + derived)), node.fields
        )


class PushFilterIntoJoin(Rule):
    """Split a post-join filter's conjuncts to the join sides they
    reference (rule/PushPredicateIntoTableScan's ancestor pass,
    PredicatePushDown.java): inner joins only — under outer joins a
    pushed predicate changes NULL-extension semantics."""

    name = "push_filter_into_join"

    def apply(self, node, ctx):
        if not isinstance(node, P.FilterNode):
            return None
        join = ctx.resolve(node.child)
        if not isinstance(join, P.JoinNode) or join.kind not in ("inner", "cross"):
            return None
        left = ctx.resolve(join.left)
        width_l = len(left.fields)
        width = len(join.fields)
        left_parts: List[ir.Expr] = []
        right_parts: List[ir.Expr] = []
        keep: List[ir.Expr] = []
        for c in split_conjuncts(node.predicate):
            refs = expr_refs(c)
            if refs and max(refs) < width_l:
                left_parts.append(c)
            elif refs and min(refs) >= width_l and max(refs) < width:
                right_parts.append(c)
            else:
                keep.append(c)
        if not left_parts and not right_parts:
            return None
        new_left = join.left
        if left_parts:
            new_left = P.FilterNode(
                join.left, ir.and_(*left_parts), left.fields
            )
        new_right = join.right
        if right_parts:
            rfields = ctx.resolve(join.right).fields
            new_right = P.FilterNode(
                join.right,
                ir.and_(*[shift_refs(c, -width_l) for c in right_parts]),
                rfields,
            )
        out: P.PlanNode = dataclasses.replace(
            join, left=new_left, right=new_right
        )
        if keep:
            out = P.FilterNode(out, ir.and_(*keep), node.fields)
        return out


class PushPredicateIntoTableScan(Rule):
    """Filter(Scan) -> Scan' [+ residual Filter] through the connector's
    apply_filter SPI hook (rule/PushPredicateIntoTableScan.java:141 +
    ConnectorMetadata.applyFilter). Only conjuncts expressible as
    per-column ``ColumnConstraint``s are offered; whatever the
    connector declines — plus everything unclassifiable — stays in a
    FilterNode above the scan (residual-predicate semantics)."""

    name = "push_predicate_into_table_scan"

    def __init__(self, catalogs):
        self._catalogs = catalogs

    def apply(self, node, ctx):
        from trino_tpu.connectors.pushdown import (
            classify_conjunct,
            merge_handle_constraints,
        )

        if not isinstance(node, P.FilterNode):
            return None
        scan = ctx.resolve(node.child)
        if not isinstance(scan, P.ScanNode):
            return None
        handle = scan.handle
        conjuncts = split_conjuncts(node.predicate)
        offered: Dict[int, object] = {}
        for i, c in enumerate(conjuncts):
            if not _is_deterministic(c):
                continue
            cc = classify_conjunct(c, scan.columns, scan.fields)
            if cc is not None and cc not in handle.constraints:
                offered[i] = cc
        if not offered:
            return None
        try:
            conn = self._catalogs.get(scan.catalog)
        except KeyError:
            return None
        result = conn.metadata.apply_filter(handle, tuple(offered.values()))
        if result is None:
            return None
        new_handle, residual = result
        accepted = [cc for cc in offered.values() if cc not in residual]
        if not accepted:
            return None
        if new_handle is handle or new_handle == handle:
            # connector claimed acceptance but returned the same handle;
            # fold the constraints in engine-side so the plan records them
            new_handle = merge_handle_constraints(handle, accepted)
        keep = [
            c
            for i, c in enumerate(conjuncts)
            if i not in offered or offered[i] not in accepted
        ]
        new_scan = dataclasses.replace(scan, handle=new_handle)
        if not keep:
            return new_scan
        return P.FilterNode(new_scan, ir.and_(*keep), node.fields)


class PushProjectionIntoTableScan(Rule):
    """Project(Scan) -> Project(Scan') with the scan narrowed to the
    channels the projection actually reads, when the connector accepts
    via apply_projection (rule/PushProjectionIntoTableScan.java). The
    page source then materializes only surviving columns (the tpch
    generator literally skips generating the rest)."""

    name = "push_projection_into_table_scan"

    def __init__(self, catalogs):
        self._catalogs = catalogs

    def apply(self, node, ctx):
        if not isinstance(node, P.ProjectNode):
            return None
        scan = ctx.resolve(node.child)
        if not isinstance(scan, P.ScanNode):
            return None
        used = sorted(set().union(*map(expr_refs, node.exprs)) if node.exprs else ())
        if not used:
            # count(*)-style: only the row count matters — scan the
            # cheapest single column (fixed-width over dictionary)
            used = [
                min(
                    range(len(scan.fields)),
                    key=lambda i: (scan.fields[i].type.is_string, i),
                )
            ]
        if len(used) >= len(scan.columns):
            return None
        try:
            conn = self._catalogs.get(scan.catalog)
        except KeyError:
            return None
        new_cols = tuple(scan.columns[i] for i in used)
        new_handle = conn.metadata.apply_projection(scan.handle, new_cols)
        if new_handle is None:
            return None
        remap = {
            old: ir.InputRef(new, scan.fields[old].type)
            for new, old in enumerate(used)
        }
        new_scan = P.ScanNode(
            scan.catalog,
            new_handle,
            new_cols,
            tuple(scan.fields[i] for i in used),
        )
        return P.ProjectNode(
            new_scan,
            tuple(substitute(e, remap) for e in node.exprs),
            node.fields,
        )


class LimitOverSortToTopN(Rule):
    """Limit(Sort(x)) -> TopN (rule/MergeLimitWithSort.java)."""

    name = "limit_over_sort_to_topn"

    def apply(self, node, ctx):
        if not isinstance(node, P.LimitNode) or node.count is None:
            return None
        if node.offset:
            return None
        child = ctx.resolve(node.child)
        if not isinstance(child, P.SortNode):
            return None
        return P.TopNNode(child.child, child.keys, node.count, node.fields)


class EvaluateEmptyJoin(Rule):
    """Inner join with a zero-row Values side is empty
    (rule/EvaluateEmptyIntersect / RemoveEmpty* family)."""

    name = "evaluate_empty_join"

    def apply(self, node, ctx):
        if not isinstance(node, P.JoinNode) or node.kind not in ("inner", "cross"):
            return None
        for side in (node.left, node.right):
            s = ctx.resolve(side)
            if isinstance(s, P.ValuesNode) and not s.rows:
                return P.ValuesNode(node.fields, ())
        return None


class MergeLimits(Rule):
    """Limit(Limit(x)) -> one Limit with the tighter count and summed
    offsets (rule/MergeLimits.java)."""

    name = "merge_limits"

    def apply(self, node, ctx):
        if not isinstance(node, P.LimitNode):
            return None
        child = ctx.resolve(node.child)
        if not isinstance(child, P.LimitNode):
            return None
        # outer sees child's post-offset stream: child rows
        # [child.offset, child.offset+child.count); outer then skips
        # node.offset more and takes node.count
        counts = []
        if child.count is not None:
            counts.append(max(child.count - node.offset, 0))
        if node.count is not None:
            counts.append(node.count)
        return P.LimitNode(
            child.child,
            min(counts) if counts else None,
            child.offset + node.offset,
            node.fields,
        )


class PushLimitThroughProject(Rule):
    """Limit(Project(x)) -> Project(Limit(x)) — projections are
    row-wise, so limiting first shrinks the projected batch
    (rule/PushLimitThroughProject.java). Only fires when the projection
    is not itself sitting on another Limit (avoid ping-ponging with
    MergeLimits)."""

    name = "push_limit_through_project"

    def apply(self, node, ctx):
        if not isinstance(node, P.LimitNode):
            return None
        child = ctx.resolve(node.child)
        if not isinstance(child, P.ProjectNode):
            return None
        inner = ctx.resolve(child.child)
        if isinstance(inner, (P.LimitNode, P.TopNNode)):
            return None
        limited = P.LimitNode(
            child.child, node.count, node.offset, tuple(inner.fields)
            if hasattr(inner, "fields") else tuple(child.child.fields),
        )
        return P.ProjectNode(limited, child.exprs, node.fields)


class PushTopNThroughProject(Rule):
    """TopN(Project(x)) -> Project(TopN(x)) when every sort key maps to
    a direct input column of the projection
    (rule/PushTopNThroughProject.java)."""

    name = "push_topn_through_project"

    def apply(self, node, ctx):
        if not isinstance(node, P.TopNNode):
            return None
        child = ctx.resolve(node.child)
        if not isinstance(child, P.ProjectNode):
            return None
        inner = ctx.resolve(child.child)
        if isinstance(inner, (P.TopNNode, P.SortNode, P.LimitNode)):
            return None
        remapped = []
        for k in node.keys:
            ex = child.exprs[k.channel]
            if not isinstance(ex, ir.InputRef):
                return None
            remapped.append(dataclasses.replace(k, channel=ex.index))
        topn = P.TopNNode(
            child.child, tuple(remapped), node.count,
            tuple(child.child.fields)
            if hasattr(child.child, "fields") else tuple(inner.fields),
        )
        return P.ProjectNode(topn, child.exprs, node.fields)


class RemoveTrivialFilters(Rule):
    """Filter(TRUE) disappears; Filter(FALSE/NULL) becomes an empty
    Values (rule/RemoveTrivialFilters.java)."""

    name = "remove_trivial_filters"

    def apply(self, node, ctx):
        if not isinstance(node, P.FilterNode):
            return None
        p = node.predicate
        if not isinstance(p, ir.Literal):
            return None
        if p.value is True:
            child = ctx.resolve(node.child)
            return child
        return P.ValuesNode(node.fields, ())


class PushLimitThroughUnion(Rule):
    """Limit(n, Union(a, b)) -> Limit(n, Union(Limit(n+off, a), ...)):
    each branch needs at most the outer window
    (rule/PushLimitThroughUnion.java). Fires once per union (inner
    limits mark it)."""

    name = "push_limit_through_union"

    def apply(self, node, ctx):
        if not isinstance(node, P.LimitNode) or node.count is None:
            return None
        child = ctx.resolve(node.child)
        if not isinstance(child, P.UnionAllNode):
            return None
        want = node.count + node.offset
        new_inputs = []
        changed = False
        for inp in child.inputs:
            r = ctx.resolve(inp)
            if isinstance(r, P.LimitNode) and r.count is not None \
                    and r.count <= want:
                new_inputs.append(inp)
                continue
            new_inputs.append(P.LimitNode(
                inp, want, 0,
                tuple(r.fields) if hasattr(r, "fields") else node.fields,
            ))
            changed = True
        if not changed:
            return None
        return P.LimitNode(
            dataclasses.replace(child, inputs=tuple(new_inputs)),
            node.count, node.offset, node.fields,
        )


_NONDETERMINISTIC_FNS = {"rand", "random", "uuid", "shuffle", "now"}


def _is_deterministic(e) -> bool:
    """False when the expression calls a volatile function — pushing it
    below an aggregation/window re-evaluates it against a different row
    set (PredicatePushDown pushes deterministic conjuncts only)."""
    if isinstance(e, ir.Call):
        if e.name in _NONDETERMINISTIC_FNS:
            return False
        return all(_is_deterministic(a) for a in e.args)
    for f in dataclasses.fields(e) if dataclasses.is_dataclass(e) else ():
        v = getattr(e, f.name)
        if isinstance(v, ir.Expr) and not _is_deterministic(v):
            return False
        if isinstance(v, tuple) and any(
            isinstance(i, ir.Expr) and not _is_deterministic(i) for i in v
        ):
            return False
    return True


class PushFilterThroughAggregation(Rule):
    """Filter conjuncts touching only GROUP KEY outputs move below the
    aggregation (PredicatePushDown.visitAggregation): the filter then
    shrinks the aggregation's input instead of its output."""

    name = "push_filter_through_aggregation"

    def apply(self, node, ctx):
        if not isinstance(node, P.FilterNode):
            return None
        agg = ctx.resolve(node.child)
        if not isinstance(agg, P.AggregateNode) or agg.step != "single":
            return None
        k = len(agg.group_channels)
        if k == 0:
            return None
        child_fields = ctx.resolve(agg.child).fields
        mapping = {
            i: ir.InputRef(
                agg.group_channels[i],
                child_fields[agg.group_channels[i]].type,
            )
            for i in range(k)
        }
        push, keep = [], []
        for c in split_conjuncts(node.predicate):
            refs = expr_refs(c)
            if refs and max(refs) < k and _is_deterministic(c):
                push.append(substitute(c, mapping))
            else:
                keep.append(c)
        if not push:
            return None
        new_child = P.FilterNode(agg.child, ir.and_(*push), child_fields)
        out: P.PlanNode = dataclasses.replace(agg, child=new_child)
        if keep:
            out = P.FilterNode(out, ir.and_(*keep), node.fields)
        return out


class PushFilterThroughWindow(Rule):
    """Filter conjuncts over PARTITION BY columns move below the window
    (rule/PushdownFilterIntoWindow's safe case): dropping whole
    partitions cannot change any surviving row's window result."""

    name = "push_filter_through_window"

    def apply(self, node, ctx):
        if not isinstance(node, P.FilterNode):
            return None
        win = ctx.resolve(node.child)
        if not isinstance(win, P.WindowNode):
            return None
        part = set(win.partition_channels)
        if not part:
            return None
        child_fields = ctx.resolve(win.child).fields
        push, keep = [], []
        for c in split_conjuncts(node.predicate):
            refs = expr_refs(c)
            if refs and all(r in part for r in refs) \
                    and _is_deterministic(c):
                push.append(c)  # window passes child channels through
            else:
                keep.append(c)
        if not push:
            return None
        new_child = P.FilterNode(win.child, ir.and_(*push), child_fields)
        out: P.PlanNode = dataclasses.replace(win, child=new_child)
        if keep:
            out = P.FilterNode(out, ir.and_(*keep), node.fields)
        return out


class FlattenUnion(Rule):
    """UnionAll(UnionAll(a, b), c) -> UnionAll(a, b, c)
    (rule/MergeUnion.java)."""

    name = "flatten_union"

    def apply(self, node, ctx):
        if not isinstance(node, P.UnionAllNode):
            return None
        flat, changed = [], False
        for inp in node.inputs:
            r = ctx.resolve(inp)
            if isinstance(r, P.UnionAllNode):
                flat.extend(r.inputs)
                changed = True
            else:
                flat.append(inp)
        if not changed:
            return None
        return P.UnionAllNode(tuple(flat), node.fields)


class PushFilterThroughUnion(Rule):
    """Filter(UnionAll(inputs)) -> UnionAll(Filter(input)...) — branch
    channels align 1:1, so the predicate applies verbatim per branch
    (PredicatePushDown.visitUnion)."""

    name = "push_filter_through_union"

    def apply(self, node, ctx):
        if not isinstance(node, P.FilterNode):
            return None
        u = ctx.resolve(node.child)
        if not isinstance(u, P.UnionAllNode):
            return None
        new_inputs = tuple(
            P.FilterNode(inp, node.predicate, ctx.resolve(inp).fields)
            for inp in u.inputs
        )
        return P.UnionAllNode(new_inputs, u.fields)


class RemoveRedundantDistinct(Rule):
    """DISTINCT over an aggregation output keyed on every column is a
    no-op: group keys are already unique
    (rule/RemoveRedundantDistinctLimit's core observation)."""

    name = "remove_redundant_distinct"

    def apply(self, node, ctx):
        if not isinstance(node, P.AggregateNode) or node.aggs:
            return None
        if tuple(node.group_channels) != tuple(range(len(node.fields))):
            return None
        child = ctx.resolve(node.child)
        if not isinstance(child, P.AggregateNode):
            return None
        # the child's whole output is its group-key set (a distinct or
        # a grouped aggregation selecting only its keys)
        if len(child.fields) == len(child.group_channels) + len(child.aggs) \
                and len(node.fields) == len(child.fields) \
                and not child.aggs:
            return child
        return None


class PushAggregationThroughOuterJoin(Rule):
    """Aggregation grouping on ALL left-join probe columns, aggregating
    only build columns, pushes below the join when the probe side is
    provably distinct (rule/PushAggregationThroughOuterJoin.java —
    the correlated-scalar / Q17 shape). count() over NULL-extended
    rows restores its 0 via a coalesce projection."""

    name = "push_aggregation_through_outer_join"

    _PUSHABLE = {"sum", "min", "max", "avg", "any", "count"}

    def apply(self, node, ctx):
        if not isinstance(node, P.AggregateNode) or node.step != "single":
            return None
        join = ctx.resolve(node.child)
        if not isinstance(join, P.JoinNode) or join.kind != "left" \
                or join.residual is not None:
            return None
        left = ctx.resolve(join.left)
        wl = len(left.fields)
        # grouping must cover exactly the probe columns (any order)
        if sorted(node.group_channels) != list(range(wl)):
            return None
        # probe side provably distinct: its own full-width distinct
        if not (
            isinstance(left, P.AggregateNode)
            and not left.aggs
            and tuple(left.group_channels) == tuple(range(len(left.fields)))
        ):
            return None
        right = ctx.resolve(join.right)
        for a in node.aggs:
            if a.kind not in self._PUSHABLE or a.distinct:
                return None
            if a.arg_channel is None or a.arg_channel < wl:
                return None
            if a.arg2_channel is not None or a.arg3_channel is not None:
                return None
        rk = tuple(join.right_keys)
        shifted = tuple(
            dataclasses.replace(a, arg_channel=a.arg_channel - wl)
            for a in node.aggs
        )
        r_fields = tuple(right.fields[c] for c in rk) + tuple(
            P.Field(None, a.out_type) for a in node.aggs
        )
        right_agg = P.AggregateNode(join.right, rk, shifted, r_fields)
        nj_fields = left.fields + r_fields
        new_join = P.JoinNode(
            "left", join.left, right_agg,
            tuple(join.left_keys), tuple(range(len(rk))), None, nj_fields,
        )
        # restore the original output layout [group keys..., aggs...];
        # count over a null-extended row reads 0, not NULL
        exprs: List[ir.Expr] = []
        for g in node.group_channels:
            exprs.append(ir.InputRef(g, left.fields[g].type))
        for i, a in enumerate(node.aggs):
            ref: ir.Expr = ir.InputRef(wl + len(rk) + i, a.out_type)
            if a.kind == "count":
                ref = ir.Call(
                    "coalesce", (ref, ir.Literal(0, a.out_type)),
                    a.out_type,
                )
            exprs.append(ref)
        return P.ProjectNode(new_join, tuple(exprs), node.fields)


SIMPLIFICATION_RULES: Tuple[Rule, ...] = (
    MergeFilters(),
    InlineProjections(),
    RemoveIdentityProject(),
    PushFilterThroughProject(),
    InferTransitivePredicates(),
    PushFilterIntoJoin(),
    LimitOverSortToTopN(),
    EvaluateEmptyJoin(),
    MergeLimits(),
    PushLimitThroughProject(),
    PushTopNThroughProject(),
    RemoveTrivialFilters(),
    PushLimitThroughUnion(),
    PushFilterThroughAggregation(),
    PushFilterThroughWindow(),
    FlattenUnion(),
    PushFilterThroughUnion(),
    RemoveRedundantDistinct(),
    PushAggregationThroughOuterJoin(),
)


class IterativeOptimizer:
    """Fixpoint driver (IterativeOptimizer.java:63): visit every memo
    group, offer each rule the group's representative, install
    replacements, repeat until a full pass fires nothing."""

    def __init__(self, rules: Sequence[Rule] = SIMPLIFICATION_RULES):
        self._rules = tuple(rules)

    def optimize(
        self,
        root: P.PlanNode,
        stats: Optional[StatsCalculator] = None,
        validator=None,
    ) -> P.PlanNode:
        """`validator(plan, rule_name)` — when given (plan_validation=
        rules), the extracted plan is re-validated after EVERY rule
        application, so a violation names the exact rewrite that
        introduced it."""
        memo = Memo(root)
        ctx = Context(memo, stats)
        for _ in range(MAX_FIXPOINT_PASSES):
            fired = False
            for gid in memo.groups():
                if gid not in memo._nodes:
                    continue
                progress = True
                while progress:
                    progress = False
                    node = memo.node(gid)
                    for rule in self._rules:
                        result = rule.apply(node, ctx)
                        if result is not None and result is not node:
                            memo.replace(gid, result)
                            ctx.last_rule = rule.name
                            if validator is not None:
                                validator(memo.extract(), rule.name)
                            progress = True
                            fired = True
                            break
            if not fired:
                break
        return memo.extract()


# ---------------------------------------------------------------------------
# cost-based join reordering
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Region:
    """A maximal tree of clean inner joins. leaves are the non-region
    subtrees in original concat order; edges are equi-join pairs
    ((leaf_i, off_i), (leaf_j, off_j))."""

    leaves: List[P.PlanNode]
    edges: List[Tuple[Tuple[int, int], Tuple[int, int]]]


def _is_region_join(node: P.PlanNode) -> bool:
    return (
        isinstance(node, P.JoinNode)
        and node.kind == "inner"
        and node.residual is None
    )


def _extract_region(root: P.JoinNode) -> _Region:
    leaves: List[P.PlanNode] = []
    edges: List[Tuple[Tuple[int, int], Tuple[int, int]]] = []

    def locate(layout: List[int], ch: int) -> Tuple[int, int]:
        off = ch
        for leaf_idx in layout:
            w = len(leaves[leaf_idx].fields)
            if off < w:
                return (leaf_idx, off)
            off -= w
        raise AssertionError("key channel outside layout")

    def walk(node: P.PlanNode) -> List[int]:
        if _is_region_join(node):
            left_layout = walk(node.left)
            right_layout = walk(node.right)
            for lk, rk in zip(node.left_keys, node.right_keys):
                edges.append((locate(left_layout, lk), locate(right_layout, rk)))
            return left_layout + right_layout
        leaves.append(node)
        return [len(leaves) - 1]

    walk(root)
    return _Region(leaves, edges)


# -- shared "other aggregates" re-aggregation plumbing for the approx
# rewrites: both expand an AggregateNode into two levels, so plain
# aggregates must split into per-level calls (sum->sum/sum,
# count->count/sum, avg->sum+count/sum+sum then a final division).
_REAGG_KINDS = {"sum", "count", "count_star", "min", "max", "any"}
_REAGG_MAP = {"sum": "sum", "count": "sum", "count_star": "sum",
              "min": "min", "max": "max", "any": "any"}


def _reagg_ok(o: P.AggCall) -> bool:
    """Can this plain aggregate re-aggregate through two levels?"""
    if o.distinct:
        return False
    # avg re-aggregates as (sum, count): float avgs in double, decimal
    # avgs EXACTLY via a decimal(38,s) sum + HALF_UP division (the
    # DecimalAverageAggregation contract) — VERDICT r3 item #3
    return o.kind in _REAGG_KINDS or o.kind == "avg"


def _reagg_a1_calls(o: P.AggCall, pos: int, arg_ch, a1_aggs, a1_fields):
    """Append o's LEVEL-1 state aggregates; returns their slot indexes."""
    slots = []
    if o.kind == "avg":
        sum_t = (
            T.decimal(T.MAX_DECIMAL_PRECISION, o.out_type.scale or 0)
            if o.out_type.is_decimal
            else T.DOUBLE
        )
        slots.append(len(a1_aggs))
        a1_aggs.append(P.AggCall("sum", arg_ch, sum_t))
        a1_fields.append(P.Field(f"$s{pos}", sum_t))
        slots.append(len(a1_aggs))
        a1_aggs.append(P.AggCall("count", arg_ch, T.BIGINT))
        a1_fields.append(P.Field(f"$c{pos}", T.BIGINT))
    else:
        slots.append(len(a1_aggs))
        a1_aggs.append(P.AggCall(o.kind, arg_ch, o.out_type))
        a1_fields.append(P.Field(f"$s{pos}", o.out_type))
    return slots


def _reagg_a2_call(o: P.AggCall, si: int):
    """(kind, out_type) of the LEVEL-2 re-aggregate for state slot si."""
    if o.kind == "avg":
        sum_t = (
            T.decimal(T.MAX_DECIMAL_PRECISION, o.out_type.scale or 0)
            if o.out_type.is_decimal
            else T.DOUBLE
        )
        return "sum", (sum_t if si == 0 else T.BIGINT)
    return _REAGG_MAP[o.kind], o.out_type


def _reagg_final_expr(o: P.AggCall, chs, ref):
    """Final output expression from the A2 channels `chs`."""
    if o.kind == "avg":
        return ir.Call("div", (ref(chs[0]), ref(chs[1])), o.out_type)
    return ref(chs[0])


class RewriteMultiSketch:
    """SEVERAL approx sketch aggregates in one node -> tagged UNION ALL
    expansion (VERDICT r3 item #3 — the single-sketch rewrites below
    were gated to exactly one approx aggregate per node; this removes
    the holistic raw-row fallback for every approx_distinct /
    approx_percentile combination).

    Each sketch's register/bucket file becomes a grouping dimension as
    in the single rewrites, but the dimensions cannot share one GROUP
    BY (a (k, b1, b2) grouping would be the register-file PRODUCT). So
    the child replicates once per sketch through UNION ALL with a $tag
    column, every branch computing ONLY its sketch's bucket/payload
    (NULL elsewhere), and plain re-aggregable siblings riding branch 0
    alone (their inputs are NULL on other branches, which every
    mergeable aggregate ignores; count(*) becomes count($one) with
    $one NULL off branch 0). One A1 over (k, tag, bucket), one A2 over
    k with per-tag CASE masks, then the original output layout.

    Trade-off: the child subtree evaluates once per sketch — still
    mergeable end to end (partial/final wire, spill, mesh collectives),
    unlike the holistic path's full raw-row gather to one node.
    approx_percentile payloads travel as DOUBLE here (its bucket
    interpolation is double-precision already)."""

    _SKETCH_KINDS = ("approx_distinct", "approx_percentile")

    def rewrite(self, node: P.PlanNode) -> P.PlanNode:
        kids = [self.rewrite(c) for c in node.children()]
        node = with_children(node, kids)
        if not isinstance(node, P.AggregateNode) or node.step != "single":
            return node
        sketches = [
            (i, a) for i, a in enumerate(node.aggs)
            if a.kind in self._SKETCH_KINDS and not a.distinct
        ]
        if len(sketches) < 2:
            return node  # single sketches keep their leaner rewrites
        sk_pos = {i for i, _ in sketches}
        others = [
            (i, a) for i, a in enumerate(node.aggs) if i not in sk_pos
        ]
        if not all(_reagg_ok(o) for _, o in others):
            return node
        return self._expand(node, sketches, others)

    def _expand(self, node: P.AggregateNode, sketches, others):
        child = node.child
        K = len(node.group_channels)
        ref = lambda ch, nd: ir.InputRef(ch, nd.fields[ch].type)
        null = lambda t: ir.Literal(None, t)

        # -- branches: one projection of the child per sketch ----------
        branches: List[P.PlanNode] = []
        branch_fields: Optional[Tuple[P.Field, ...]] = None
        for t, (pos, a) in enumerate(sketches):
            # each branch gets its own copy of the child subtree —
            # aliasing one object under two UnionAll inputs turns the
            # tree into a DAG (see _fresh_tree)
            src = child if t == 0 else _fresh_tree(child)
            exprs: List[ir.Expr] = [
                ref(c, child) for c in node.group_channels
            ]
            fields: List[P.Field] = [
                child.fields[c] for c in node.group_channels
            ]
            exprs.append(ir.Literal(t, T.BIGINT))
            fields.append(P.Field("$tag", T.BIGINT))
            x = ref(a.arg_channel, child)
            if a.kind == "approx_distinct":
                exprs += [
                    ir.Call("hll_bucket", (x,), T.BIGINT),
                    ir.Call("hll_rho", (x,), T.BIGINT),
                    null(T.DOUBLE),
                ]
            else:
                exprs += [
                    ir.Call("pctl_bucket", (x,), T.BIGINT),
                    null(T.BIGINT),
                    ir.Cast(x, T.DOUBLE),
                ]
            fields += [
                P.Field("$b", T.BIGINT),
                P.Field("$rho", T.BIGINT),
                P.Field("$x", T.DOUBLE),
            ]
            for pos2, o in others:
                if o.arg_channel is None:
                    # count(*) marker: 1 on branch 0, NULL elsewhere
                    exprs.append(
                        ir.Literal(1, T.BIGINT) if t == 0 else null(T.BIGINT)
                    )
                    fields.append(P.Field(f"$one{pos2}", T.BIGINT))
                else:
                    ft = child.fields[o.arg_channel]
                    exprs.append(
                        ref(o.arg_channel, child) if t == 0 else null(ft.type)
                    )
                    fields.append(ft)
            branches.append(P.ProjectNode(src, tuple(exprs), tuple(fields)))
            branch_fields = branches[-1].fields
        u = P.UnionAllNode(tuple(branches), branch_fields)

        # -- A1: group by (k, tag, b) ---------------------------------
        # union layout: [k... | $tag=K | $b=K+1 | $rho=K+2 | $x=K+3 |
        # other args from K+4]
        rho_u, x_u = K + 2, K + 3
        a1_aggs: List[P.AggCall] = [
            P.AggCall("max", rho_u, T.BIGINT),   # $maxrho
            P.AggCall("count", x_u, T.BIGINT),   # $c  (pctl)
            P.AggCall("min", x_u, T.DOUBLE),     # $mn (pctl)
            P.AggCall("max", x_u, T.DOUBLE),     # $mx (pctl)
        ]
        a1_fields = list(u.fields[: K + 2]) + [
            P.Field("$maxrho", T.BIGINT), P.Field("$c", T.BIGINT),
            P.Field("$mn", T.DOUBLE), P.Field("$mx", T.DOUBLE),
        ]
        state_slots: Dict[int, List[int]] = {}
        for j, (pos2, o) in enumerate(others):
            arg = K + 4 + j  # the per-other column in the union layout
            # count(*) must count ONLY branch-0 rows: it aggregates the
            # $one marker (NULL off branch 0) as a plain count
            o_eff = (
                o if o.arg_channel is not None
                else P.AggCall("count", arg, o.out_type)
            )
            state_slots[pos2] = _reagg_a1_calls(
                o_eff, pos2, arg, a1_aggs, a1_fields,
            )
        a1 = P.AggregateNode(
            u, tuple(range(K + 2)), tuple(a1_aggs), tuple(a1_fields),
            "single",
        )
        # A1 layout: [k..., $tag, $b, $maxrho, $c, $mn, $mx, states...]

        # -- L2: weights + per-tag masks ------------------------------
        tag_ch, b_ch = K, K + 1
        mr, c_ch, mn_ch, mx_ch = K + 2, K + 3, K + 4, K + 5
        exprs2: List[ir.Expr] = [ref(c, a1) for c in range(K)]
        fields2: List[P.Field] = list(a1.fields[:K])

        def mask(t, e, out_t):
            return ir.Case(
                (ir.Call(
                    "eq", (ref(tag_ch, a1), ir.Literal(t, T.BIGINT)),
                    T.BOOLEAN,
                ),),
                (e,),
                None,
                out_t,
            )

        sk_ch: Dict[int, List[int]] = {}
        for t, (pos, a) in enumerate(sketches):
            chs = []
            if a.kind == "approx_distinct":
                w = ir.Call(
                    "hll_weight_rho", (ref(mr, a1), ref(b_ch, a1)), T.DOUBLE
                )
                chs.append(len(exprs2))
                exprs2.append(mask(t, w, T.DOUBLE))
                fields2.append(P.Field(f"$w{t}", T.DOUBLE))
                chs.append(len(exprs2))
                exprs2.append(mask(t, ref(b_ch, a1), T.BIGINT))
                fields2.append(P.Field(f"$mb{t}", T.BIGINT))
            else:
                for src, ot in ((mn_ch, T.DOUBLE), (c_ch, T.BIGINT),
                                (mx_ch, T.DOUBLE)):
                    chs.append(len(exprs2))
                    exprs2.append(mask(t, ref(src, a1), ot))
                    fields2.append(P.Field(f"$p{t}_{src}", ot))
            sk_ch[pos] = chs
        state_ch2: Dict[int, List[int]] = {}
        for pos2, o in others:
            state_ch2[pos2] = []
            for slot in state_slots[pos2]:
                state_ch2[pos2].append(len(exprs2))
                exprs2.append(ref(K + 2 + slot, a1))
                fields2.append(a1.fields[K + 2 + slot])
        l2 = P.ProjectNode(a1, tuple(exprs2), tuple(fields2))

        # -- A2: group by k -------------------------------------------
        a2_aggs: List[P.AggCall] = []
        a2_fields = list(l2.fields[:K])
        out_ch: Dict[int, List[int]] = {}
        for t, (pos, a) in enumerate(sketches):
            chs = sk_ch[pos]
            if a.kind == "approx_distinct":
                out_ch[pos] = [K + len(a2_aggs), K + len(a2_aggs) + 1]
                a2_aggs.append(P.AggCall("sum", chs[0], T.DOUBLE))
                a2_fields.append(P.Field(f"$sw{t}", T.DOUBLE))
                a2_aggs.append(P.AggCall("count", chs[1], T.BIGINT))
                a2_fields.append(P.Field(f"$cnt{t}", T.BIGINT))
            else:
                out_ch[pos] = [K + len(a2_aggs)]
                a2_aggs.append(P.AggCall(
                    "pctl_merge", chs[0], a.out_type,
                    arg2_channel=chs[1], arg3_channel=chs[2],
                    percentile=a.percentile,
                ))
                a2_fields.append(P.Field(f"$p{t}", a.out_type))
        final_ch: Dict[int, List[int]] = {}
        for pos2, o in others:
            final_ch[pos2] = []
            for si, ch2 in enumerate(state_ch2[pos2]):
                re_kind, out_t = _reagg_a2_call(o, si)
                final_ch[pos2].append(K + len(a2_aggs))
                a2_aggs.append(P.AggCall(re_kind, ch2, out_t))
                a2_fields.append(P.Field(f"$f{pos2}_{si}", out_t))
        a2 = P.AggregateNode(
            l2, tuple(range(K)), tuple(a2_aggs), tuple(a2_fields), "single"
        )

        # -- restore the original output layout -----------------------
        exprs4: List[ir.Expr] = [ref(c, a2) for c in range(K)]
        smap = dict(sketches)
        for i, a in enumerate(node.aggs):
            if i in smap:
                if a.kind == "approx_distinct":
                    exprs4.append(ir.Call(
                        "hll_estimate",
                        (ref(out_ch[i][0], a2), ref(out_ch[i][1], a2)),
                        T.BIGINT,
                    ))
                else:
                    exprs4.append(ref(out_ch[i][0], a2))
            else:
                exprs4.append(_reagg_final_expr(
                    a, final_ch[i], lambda c: ref(c, a2)
                ))
        return P.ProjectNode(a2, tuple(exprs4), tuple(node.fields))


class RewriteApproxDistinct:
    """approx_distinct -> a two-level MERGEABLE aggregation (plan
    rewrite), replacing the holistic raw-row gather (VERDICT r2
    missing #1; reference:
    operator/aggregation/ApproximateCountDistinctAggregations.java).

    approx_distinct(x) GROUP BY k becomes

        Project  k..., hll_estimate($sw, $cnt), other finals...
          Aggregate k:    sum($w) as $sw, count($b) as $cnt, re-aggs...
            Project k..., $w = hll_weight_rho($maxrho, $b), $b, states...
              Aggregate (k..., $b): max($r) as $maxrho, partial others...
                Project k..., $b = hll_bucket(x), $r = hll_rho(x), args...

    i.e. the HLL register file IS a grouping dimension: register
    updates are a grouped max, register merges across partials are the
    SAME grouped max, and every level is a plain mergeable aggregation
    that rides the existing partial/final wire, spill, and mesh
    collective paths unchanged — nothing gathers raw rows. NULL x rows
    land in the NULL-bucket group (SQL GROUP BY keeps them), carry
    weight 0, and keep all-NULL key groups alive, so no join or
    null-key normalization is needed. m=2048 registers (standard error
    1.04/sqrt(m) = 2.3%, the reference's default).

    Mixed aggregates re-aggregate through both levels (sum->sum,
    count->sum, min->min, ...). Queries mixing approx_distinct with
    non-re-aggregable kinds (avg over decimals, holistic kinds,
    DISTINCT-qualified aggs) or with several approx_distincts keep the
    single-step holistic path."""

    def rewrite(self, node: P.PlanNode) -> P.PlanNode:
        kids = [self.rewrite(c) for c in node.children()]
        node = with_children(node, kids)
        if not isinstance(node, P.AggregateNode) or node.step != "single":
            return node
        hlls = [
            (i, a) for i, a in enumerate(node.aggs)
            if a.kind == "approx_distinct"
        ]
        if len(hlls) != 1:
            return node
        others = [
            (i, a) for i, a in enumerate(node.aggs)
            if a.kind != "approx_distinct"
        ]
        if not all(_reagg_ok(o) for _, o in others):
            return node
        return self._expand(node, hlls[0], others)

    def _expand(self, node: P.AggregateNode, hll, others) -> P.PlanNode:
        child = node.child
        K = len(node.group_channels)
        hll_pos, hll_agg = hll
        ref = lambda ch, nd: ir.InputRef(ch, nd.fields[ch].type)

        # -- L0: project keys + bucket/rho + other args --
        exprs: List[ir.Expr] = [
            ref(c, child) for c in node.group_channels
        ]
        fields: List[P.Field] = [
            child.fields[c] for c in node.group_channels
        ]
        x = ref(hll_agg.arg_channel, child)
        exprs += [
            ir.Call("hll_bucket", (x,), T.BIGINT),
            ir.Call("hll_rho", (x,), T.BIGINT),
        ]
        fields += [P.Field("$hll_b", T.BIGINT), P.Field("$hll_r", T.BIGINT)]
        arg_ch: Dict[int, Optional[int]] = {}
        for pos, o in others:
            if o.arg_channel is None:
                arg_ch[pos] = None
                continue
            arg_ch[pos] = len(exprs)
            exprs.append(ref(o.arg_channel, child))
            fields.append(child.fields[o.arg_channel])
        l0 = P.ProjectNode(child, tuple(exprs), tuple(fields))

        # -- A1: group by (k..., bucket); max(rho) + partial others --
        a1_aggs: List[P.AggCall] = [
            P.AggCall("max", K + 1, T.BIGINT)
        ]
        a1_fields = list(l0.fields[: K + 1]) + [P.Field("$maxrho", T.BIGINT)]
        # per other agg: list of A1 state slots (avg splits in two)
        state_slots: Dict[int, List[int]] = {}
        for pos, o in others:
            state_slots[pos] = _reagg_a1_calls(
                o, pos, arg_ch[pos], a1_aggs, a1_fields
            )
        a1 = P.AggregateNode(
            l0, tuple(range(K + 1)), tuple(a1_aggs), tuple(a1_fields),
            "single",
        )
        # A1 output layout: [k..., $b, $maxrho, states...]

        # -- L2: keys + weight + bucket + states --
        exprs2: List[ir.Expr] = [ref(c, a1) for c in range(K)]
        fields2: List[P.Field] = list(a1.fields[:K])
        exprs2.append(
            ir.Call(
                "hll_weight_rho",
                (ref(K + 1, a1), ref(K, a1)),
                T.DOUBLE,
            )
        )
        fields2.append(P.Field("$w", T.DOUBLE))
        exprs2.append(ref(K, a1))
        fields2.append(P.Field("$hll_b", T.BIGINT))
        state_ch2: Dict[int, List[int]] = {}
        for pos, o in others:
            state_ch2[pos] = []
            for slot in state_slots[pos]:
                state_ch2[pos].append(len(exprs2))
                exprs2.append(ref(K + 2 + slot - 1, a1))
                fields2.append(a1.fields[K + 2 + slot - 1])
        l2 = P.ProjectNode(a1, tuple(exprs2), tuple(fields2))

        # -- A2: group by k; sum(w), count(b), re-agg others --
        a2_aggs: List[P.AggCall] = [
            P.AggCall("sum", K, T.DOUBLE),
            P.AggCall("count", K + 1, T.BIGINT),
        ]
        a2_fields = list(l2.fields[:K]) + [
            P.Field("$sw", T.DOUBLE), P.Field("$cnt", T.BIGINT),
        ]
        final_ch: Dict[int, List[int]] = {}
        for pos, o in others:
            final_ch[pos] = []
            for si, ch2 in enumerate(state_ch2[pos]):
                re_kind, out_t = _reagg_a2_call(o, si)
                final_ch[pos].append(K + len(a2_aggs))
                a2_aggs.append(P.AggCall(re_kind, ch2, out_t))
                a2_fields.append(P.Field(f"$f{pos}_{si}", out_t))
        a2 = P.AggregateNode(
            l2, tuple(range(K)), tuple(a2_aggs), tuple(a2_fields),
            "single",
        )

        # -- L4: restore the original output layout --
        exprs4: List[ir.Expr] = [ref(c, a2) for c in range(K)]
        for i, a in enumerate(node.aggs):
            if i == hll_pos:
                exprs4.append(
                    ir.Call(
                        "hll_estimate",
                        (ref(K, a2), ref(K + 1, a2)),
                        T.BIGINT,
                    )
                )
            else:
                exprs4.append(_reagg_final_expr(
                    node.aggs[i], final_ch[i], lambda c: ref(c, a2)
                ))
        return P.ProjectNode(a2, tuple(exprs4), tuple(node.fields))


class RewriteDistinctAggs:
    """DISTINCT aggregates -> dedup-then-aggregate (two plain
    aggregation levels), the reference's
    SingleDistinctAggregationToGroupBy rule. count(DISTINCT x) GROUP BY
    k becomes

        Aggregate k: count(x), ...
          Aggregate (k..., x): [dedup]

    Both levels are ordinary mergeable aggregations, so DISTINCT aggs
    ride the partial/final wire AND the mesh collective data plane
    (mesh_plan rejects AggCall.distinct — this rewrite removes it).
    Applies when every aggregate is DISTINCT over the SAME argument
    (the common count(DISTINCT x) shape); mixed distinct/plain keeps
    the local MarkDistinct-style path."""

    _KINDS = {"count", "sum", "avg", "min", "max"}

    def rewrite(self, node: P.PlanNode) -> P.PlanNode:
        kids = [self.rewrite(c) for c in node.children()]
        node = with_children(node, kids)
        if not isinstance(node, P.AggregateNode) or node.step != "single":
            return node
        if not node.aggs or not all(a.distinct for a in node.aggs):
            return node
        if any(a.arg_channel is None for a in node.aggs):
            return node
        if not all(a.kind in self._KINDS for a in node.aggs):
            return node
        child = node.child
        # "same argument" up to projection duplication: the analyzer
        # gives each aggregate its own projected channel, so compare the
        # underlying expressions when the child is a Project
        def basis(ch):
            if isinstance(child, P.ProjectNode):
                return child.exprs[ch]
            return ch

        bases = {basis(a.arg_channel) for a in node.aggs}
        if len(bases) != 1:
            return node
        K = len(node.group_channels)
        arg = node.aggs[0].arg_channel
        dedup_fields = tuple(
            [child.fields[c] for c in node.group_channels]
            + [child.fields[arg]]
        )
        dedup = P.AggregateNode(
            child,
            tuple(node.group_channels) + (arg,),
            (),
            dedup_fields,
            "single",
        )
        aggs = tuple(
            P.AggCall(a.kind, K, a.out_type, percentile=a.percentile)
            for a in node.aggs
        )
        return P.AggregateNode(
            dedup, tuple(range(K)), aggs, node.fields, "single"
        )


class RewriteApproxPercentile:
    """approx_percentile -> mergeable bucket summaries + a bounded merge
    (VERDICT r2 missing #1; reference: qdigest-state
    ApproximateDoublePercentileAggregations.java).

    approx_percentile(x, f) GROUP BY k becomes

        Aggregate k: pctl_merge($mn, $c, $mx, f), re-agg others...
          Aggregate (k..., $qb): count(x) $c, min(x) $mn, max(x) $mx
            Project k..., $qb = pctl_bucket(x), x, args...

    The inner level is a plain mergeable aggregation (rides partial/
    final, spill, mesh); pctl_merge buffers only per-bucket summaries —
    bounded by distinct quantile buckets, never raw rows — and
    interpolates within the chosen bucket (error <= the bucket's 1.6%
    relative width; exact for single-valued buckets). Skipped when a
    second approx aggregate or a non-re-aggregable kind shares the
    node (those keep the single-step holistic path)."""

    def rewrite(self, node: P.PlanNode) -> P.PlanNode:
        kids = [self.rewrite(c) for c in node.children()]
        node = with_children(node, kids)
        if not isinstance(node, P.AggregateNode) or node.step != "single":
            return node
        pcts = [
            (i, a) for i, a in enumerate(node.aggs)
            if a.kind == "approx_percentile"
        ]
        if len(pcts) != 1:
            return node
        others = [
            (i, a) for i, a in enumerate(node.aggs)
            if a.kind != "approx_percentile"
        ]
        if not all(_reagg_ok(o) for _, o in others):
            return node
        if pcts[0][1].distinct:
            return node
        return self._expand(node, pcts[0], others)

    def _expand(self, node: P.AggregateNode, pct, others) -> P.PlanNode:
        child = node.child
        K = len(node.group_channels)
        pct_pos, pct_agg = pct
        x_t = child.fields[pct_agg.arg_channel].type
        ref = lambda ch, nd: ir.InputRef(ch, nd.fields[ch].type)

        # -- L0: keys + bucket + x + other args --
        exprs: List[ir.Expr] = [ref(c, child) for c in node.group_channels]
        fields: List[P.Field] = [child.fields[c] for c in node.group_channels]
        x = ref(pct_agg.arg_channel, child)
        exprs.append(ir.Call("pctl_bucket", (x,), T.BIGINT))
        fields.append(P.Field("$qb", T.BIGINT))
        x_ch0 = len(exprs)
        exprs.append(x)
        fields.append(child.fields[pct_agg.arg_channel])
        arg_ch: Dict[int, Optional[int]] = {}
        for pos, o in others:
            if o.arg_channel is None:
                arg_ch[pos] = None
                continue
            arg_ch[pos] = len(exprs)
            exprs.append(ref(o.arg_channel, child))
            fields.append(child.fields[o.arg_channel])
        l0 = P.ProjectNode(child, tuple(exprs), tuple(fields))

        # -- A1: group by (k..., qb): count/min/max of x + partials --
        a1_aggs = [
            P.AggCall("count", x_ch0, T.BIGINT),
            P.AggCall("min", x_ch0, x_t),
            P.AggCall("max", x_ch0, x_t),
        ]
        a1_fields = list(l0.fields[: K + 1]) + [
            P.Field("$c", T.BIGINT), P.Field("$mn", x_t), P.Field("$mx", x_t),
        ]
        state_slots: Dict[int, List[int]] = {}
        for pos, o in others:
            state_slots[pos] = _reagg_a1_calls(
                o, pos, arg_ch[pos], a1_aggs, a1_fields
            )
        a1 = P.AggregateNode(
            l0, tuple(range(K + 1)), tuple(a1_aggs), tuple(a1_fields),
            "single",
        )
        # layout: [k..., $qb, $c, $mn, $mx, states...]

        # -- A2: group by k: pctl_merge + re-aggs --
        a2_aggs = [
            P.AggCall(
                "pctl_merge", K + 2, pct_agg.out_type,
                arg2_channel=K + 1, arg3_channel=K + 3,
                percentile=pct_agg.percentile,
            )
        ]
        a2_fields = list(a1.fields[:K]) + [
            P.Field(f"$p{pct_pos}", pct_agg.out_type)
        ]
        final_ch: Dict[int, List[int]] = {}
        for pos, o in others:
            final_ch[pos] = []
            for si, slot in enumerate(state_slots[pos]):
                re_kind, out_t = _reagg_a2_call(o, si)
                final_ch[pos].append(K + len(a2_aggs))
                a2_aggs.append(P.AggCall(re_kind, K + 1 + slot, out_t))
                a2_fields.append(P.Field(f"$f{pos}_{si}", out_t))
        a2 = P.AggregateNode(
            a1, tuple(range(K)), tuple(a2_aggs), tuple(a2_fields), "single"
        )

        # -- restore original layout --
        exprs4: List[ir.Expr] = [ref(c, a2) for c in range(K)]
        for i, a in enumerate(node.aggs):
            if i == pct_pos:
                exprs4.append(ref(K, a2))
            else:
                exprs4.append(_reagg_final_expr(
                    a, final_ch[i], lambda c: ref(c, a2)
                ))
        return P.ProjectNode(a2, tuple(exprs4), tuple(node.fields))


class ReorderJoins:
    """DPsub join-order search over a region (ReorderJoins.java:84 — the
    reference enumerates partitions per multi-join node with a cost
    comparator and a result limit; this explores all connected subsets,
    feasible at the region sizes analytic queries produce). Cross joins
    are admitted only to connect otherwise-disconnected components and
    only one leaf at a time, mirroring EliminateCrossJoins' bias."""

    def __init__(self, stats: StatsCalculator, cost: CostCalculator):
        self._stats = stats
        self._cost = cost

    def rewrite(self, node: P.PlanNode) -> P.PlanNode:
        if _is_region_join(node):
            return self._reorder(node)
        kids = [self.rewrite(c) for c in node.children()]
        return with_children(node, kids)

    # -- region machinery --
    def _reorder(self, root: P.JoinNode) -> P.PlanNode:
        region = _extract_region(root)
        # recurse into leaves first (nested regions under aggregates etc.)
        region.leaves = [self.rewrite(l) for l in region.leaves]
        n = len(region.leaves)  # a join region always has >= 2 leaves
        if n > MAX_DP_LEAVES:
            # oversized region: keep the analyzer's greedy order
            return self._rebuild_original(root, region)
        plan, layout = self._dp(region)
        if plan is None:
            return self._rebuild_original(root, region)
        if layout == tuple(range(n)):
            return plan
        # permutation project restoring the original output order
        widths = [len(l.fields) for l in region.leaves]
        new_offsets: Dict[int, int] = {}
        pos = 0
        for leaf_idx in layout:
            new_offsets[leaf_idx] = pos
            pos += widths[leaf_idx]
        exprs: List[ir.Expr] = []
        fields: List[P.Field] = []
        for leaf_idx in range(n):
            base = new_offsets[leaf_idx]
            for off, f in enumerate(region.leaves[leaf_idx].fields):
                exprs.append(ir.InputRef(base + off, f.type))
                fields.append(f)
        return P.ProjectNode(plan, tuple(exprs), tuple(fields))

    def _rebuild_original(self, node: P.PlanNode, region: _Region,
                          counter: Optional[List[int]] = None) -> P.PlanNode:
        """Original structure with (recursively-rewritten) leaves."""
        if counter is None:
            counter = [0]
        if _is_region_join(node):
            left = self._rebuild_original(node.left, region, counter)
            right = self._rebuild_original(node.right, region, counter)
            return dataclasses.replace(node, left=left, right=right)
        leaf = region.leaves[counter[0]]
        counter[0] += 1
        return leaf

    def _dp(self, region: _Region):
        n = len(region.leaves)
        full = (1 << n) - 1
        # best[mask] = (total_cost, plan, layout)
        best: Dict[int, Tuple[float, P.PlanNode, Tuple[int, ...]]] = {}
        for i, leaf in enumerate(region.leaves):
            best[1 << i] = (self._cost.cost(leaf).total, leaf, (i,))

        def crossing(s1: int, s2: int):
            out = []
            for (a, b) in region.edges:
                (la, _), (lb, _) = a, b
                if (s1 >> la) & 1 and (s2 >> lb) & 1:
                    out.append((a, b))
                elif (s2 >> la) & 1 and (s1 >> lb) & 1:
                    out.append((b, a))
            return out

        def offsets(layout: Tuple[int, ...]) -> Dict[int, int]:
            out: Dict[int, int] = {}
            pos = 0
            for li in layout:
                out[li] = pos
                pos += len(region.leaves[li].fields)
            return out

        def make_join(probe, build, keys):
            (_, pplan, playout) = probe
            (_, bplan, blayout) = build
            poff = offsets(playout)
            boff = offsets(blayout)
            lkeys = tuple(poff[l] + o for ((l, o), _) in keys)
            rkeys = tuple(boff[l] + o for (_, (l, o)) in keys)
            kind = "inner" if keys else "cross"
            node = P.JoinNode(
                kind, pplan, bplan, lkeys, rkeys, None,
                pplan.fields + bplan.fields,
            )
            return (self._cost.cost(node).total, node, playout + blayout)

        for mask in range(1, full + 1):
            if mask in best or bin(mask).count("1") < 2:
                continue
            lowest = mask & -mask
            entry = None
            s1 = (mask - 1) & mask
            while s1:
                s2 = mask ^ s1
                if (s1 & lowest) and s1 in best and s2 in best:
                    keys = crossing(s1, s2)
                    candidates = []
                    if keys:
                        # orientation: either side may probe
                        candidates.append(make_join(
                            best[s1], best[s2],
                            [(a, b) for (a, b) in keys],
                        ))
                        candidates.append(make_join(
                            best[s2], best[s1],
                            [(b, a) for (a, b) in keys],
                        ))
                    elif bin(s2).count("1") == 1 or bin(s1).count("1") == 1:
                        # cross join admitted one leaf at a time
                        candidates.append(make_join(best[s1], best[s2], []))
                    for cand in candidates:
                        if entry is None or cand[0] < entry[0]:
                            entry = cand
                s1 = (s1 - 1) & mask
            if entry is not None:
                best[mask] = entry
        hit = best.get(full)
        if hit is None:
            return None, None
        return hit[1], hit[2]


# ---------------------------------------------------------------------------
# pipeline
# ---------------------------------------------------------------------------


def optimize(
    root: P.PlanNode,
    catalogs,
    session=None,
) -> P.PlanNode:
    """The PlanOptimizers pipeline: iterative simplification, cost-based
    join reordering, cleanup. `session.enable_optimizer` gates the whole
    pass; `session.join_reordering_strategy` gates the CBO step
    ("automatic" | "none" — SystemSessionProperties
    JOIN_REORDERING_STRATEGY)."""
    if session is not None and not getattr(session, "enable_optimizer", True):
        return root
    strategy = getattr(session, "join_reordering_strategy", "automatic")
    validation = getattr(session, "plan_validation", "passes")
    if validation != "off":
        from trino_tpu.sql.validate import validate_logical
    else:
        validate_logical = None
    per_rule = None
    if validation == "rules":
        per_rule = lambda plan, rule: validate_logical(
            plan, stage="optimizer", rule=rule
        )

    def checkpoint(plan: P.PlanNode, stage: str) -> None:
        # PlanSanityChecker.validateIntermediatePlan: every pass must
        # hand the next one a well-formed plan
        if validate_logical is not None:
            validate_logical(plan, stage=stage)

    stats = StatsCalculator(catalogs)
    rules: Tuple[Rule, ...] = SIMPLIFICATION_RULES
    if getattr(session, "enable_pushdown", True) and catalogs is not None:
        rules = rules + (
            PushPredicateIntoTableScan(catalogs),
            PushProjectionIntoTableScan(catalogs),
        )
    it = IterativeOptimizer(rules)
    checkpoint(root, "analyzer")
    root = it.optimize(root, stats, validator=per_rule)
    checkpoint(root, "iterative")
    root = RewriteMultiSketch().rewrite(root)
    root = RewriteApproxDistinct().rewrite(root)
    root = RewriteApproxPercentile().rewrite(root)
    checkpoint(root, "approx_rewrites")
    root = RewriteDistinctAggs().rewrite(root)
    checkpoint(root, "distinct_aggs")
    if strategy == "automatic":
        cost = CostCalculator(stats)
        root = ReorderJoins(stats, cost).rewrite(root)
        root = it.optimize(root, stats, validator=per_rule)
        checkpoint(root, "join_reordering")
    return root


# -- timestamptz key canonicalization (correctness, not optimization) --------


def _is_tstz(t: T.DataType) -> bool:
    return t.kind == T.TypeKind.TIMESTAMP_TZ


def _masked_tstz(c: int, t: T.DataType) -> ir.Expr:
    # at_timezone_id(x, 0) clears the packed zone bits while keeping the
    # instant and validity — the canonical grouping/join key
    return ir.Call(
        "at_timezone_id",
        (ir.InputRef(c, t), ir.Literal(0, T.INTEGER)),
        t,
    )


def _tstz_side_project(child: P.PlanNode, need: List[int]):
    """Project appending one zone-masked copy per channel in `need`;
    returns (project, {orig channel: masked channel})."""
    cf = child.fields
    base = len(cf)
    pos = {c: base + x for x, c in enumerate(need)}
    exprs = tuple(ir.InputRef(i, f.type) for i, f in enumerate(cf)) + tuple(
        _masked_tstz(c, cf[c].type) for c in need
    )
    flds = cf + tuple(
        P.Field((cf[c].name or "tstz") + "$utc", cf[c].type)
        for c in need
    )
    return P.ProjectNode(child, exprs, flds), pos


def _canonicalize_agg(n: P.AggregateNode) -> P.PlanNode:
    cf = n.child.fields
    k = len(n.group_channels)
    tg = [
        j for j, c in enumerate(n.group_channels) if _is_tstz(cf[c].type)
    ]
    is_td = lambda a: (
        a.distinct
        and a.arg_channel is not None
        and _is_tstz(cf[a.arg_channel].type)
    )
    if not tg and not any(is_td(a) for a in n.aggs):
        return n
    need: List[int] = []
    for c in n.group_channels:
        if _is_tstz(cf[c].type) and c not in need:
            need.append(c)
    for a in n.aggs:
        if is_td(a) and a.arg_channel not in need:
            need.append(a.arg_channel)
    below, pos = _tstz_side_project(n.child, need)
    groups = tuple(pos.get(c, c) for c in n.group_channels)
    aggs = tuple(
        dataclasses.replace(a, arg_channel=pos[a.arg_channel])
        if is_td(a)
        else a
        for a in n.aggs
    )
    if not tg:
        # only a DISTINCT arg was tstz: schema is unchanged
        return dataclasses.replace(n, child=below, aggs=aggs)
    # an any() per tstz key carries one ORIGINAL packed value (with its
    # zone) out of each group, so rendering keeps the source zone
    reps = tuple(
        P.AggCall("any", n.group_channels[j], cf[n.group_channels[j]].type)
        for j in tg
    )
    agg_fields = n.fields + tuple(
        P.Field((n.fields[j].name or "tstz") + "$any", n.fields[j].type)
        for j in tg
    )
    agg = P.AggregateNode(below, groups, aggs + reps, agg_fields, n.step)
    rep_at = {j: k + len(aggs) + x for x, j in enumerate(tg)}
    exprs = tuple(
        ir.InputRef(rep_at.get(i, i), n.fields[i].type)
        for i in range(len(n.fields))
    )
    return P.ProjectNode(agg, exprs, n.fields)


def _canonicalize_join(n: P.JoinNode) -> P.PlanNode:
    if not n.left_keys:
        return n

    def side(child, keys):
        cf = child.fields
        need = []
        for c in keys:
            if _is_tstz(cf[c].type) and c not in need:
                need.append(c)
        if not need:
            return child, tuple(keys), 0
        proj, pos = _tstz_side_project(child, need)
        return proj, tuple(pos.get(c, c) for c in keys), len(need)

    nleft, lk, el = side(n.left, n.left_keys)
    nright, rk, er = side(n.right, n.right_keys)
    if not el and not er:
        return n
    lf, rf = n.left.fields, n.right.fields
    nl, nr = len(lf), len(rf)
    residual = n.residual
    if residual is not None and el:
        # residual is typed over left++right: right-side refs shift past
        # the appended left-side masked copies
        mapping = {
            i: ir.InputRef(
                i if i < nl else i + el,
                lf[i].type if i < nl else rf[i - nl].type,
            )
            for i in range(nl + nr)
        }
        residual = substitute(residual, mapping)
    semi = n.kind in ("semi", "anti")
    jfields = nleft.fields if semi else nleft.fields + nright.fields
    j = dataclasses.replace(
        n,
        left=nleft,
        right=nright,
        left_keys=lk,
        right_keys=rk,
        residual=residual,
        fields=jfields,
    )
    sel = (
        tuple(range(nl))
        if semi
        else tuple(range(nl)) + tuple(nl + el + i for i in range(nr))
    )
    if len(sel) == len(jfields):
        return j
    exprs = tuple(ir.InputRef(i, jfields[i].type) for i in sel)
    return P.ProjectNode(j, exprs, n.fields)


def _canonicalize_window(n: P.WindowNode) -> P.PlanNode:
    cf = n.child.fields
    need: List[int] = []
    for c in n.partition_channels:
        if _is_tstz(cf[c].type) and c not in need:
            need.append(c)
    if not need:
        return n
    # partition on the zone-masked copies appended below; function args
    # and order keys keep their original (unshifted) channels
    below, pos = _tstz_side_project(n.child, need)
    parts = tuple(pos.get(c, c) for c in n.partition_channels)
    n_funcs = len(n.fields) - len(cf)
    wfields = below.fields + n.fields[len(cf):]
    w = dataclasses.replace(
        n, child=below, partition_channels=parts, fields=wfields
    )
    # project above drops the masked copies, restoring the schema
    base = len(below.fields)
    sel = tuple(range(len(cf))) + tuple(base + i for i in range(n_funcs))
    exprs = tuple(ir.InputRef(i, wfields[i].type) for i in sel)
    return P.ProjectNode(w, exprs, n.fields)


def canonicalize_tstz_keys(root: P.PlanNode) -> P.PlanNode:
    """Correctness pass, applied to every plan even when the optimizer
    is off: timestamptz packs millis<<12 | zoneKey, but SQL equality is
    instant-only, so GROUP BY / JOIN / DISTINCT must key on the instant
    and never the zone bits (the reference keys on
    LongTimestampWithTimeZone.getEpochMillis()). Rewrites tstz-keyed
    aggregations, joins, and window PARTITION BY to key on a zone-masked
    copy appended by a Project below; for group keys an any() aggregate
    preserves one original packed value per group as the rendered
    representative, and a Project above restores the original schema."""
    kids = [canonicalize_tstz_keys(c) for c in root.children()]
    if any(a is not b for a, b in zip(kids, root.children())):
        if isinstance(root, P.JoinNode):
            root = dataclasses.replace(root, left=kids[0], right=kids[1])
        elif isinstance(root, P.UnionAllNode):
            root = dataclasses.replace(root, inputs=tuple(kids))
        else:
            root = dataclasses.replace(root, child=kids[0])
    if isinstance(root, P.AggregateNode) and root.step == "single":
        return _canonicalize_agg(root)
    if isinstance(root, P.JoinNode):
        return _canonicalize_join(root)
    if isinstance(root, P.WindowNode):
        return _canonicalize_window(root)
    return root
