"""Plan cost model.

Analogue of main/cost/ (PlanCostEstimate, CostCalculatorUsingExchanges,
TaskCountEstimator — SURVEY.md §2.2): a three-component cost
(cpu, memory, network) derived from the StatsCalculator's row
estimates, consumed by the join-reordering optimizer and by EXPLAIN.

The constants encode the TPU engine's actual cost shape rather than the
reference's JVM one: a hash-join build is a device sort (n log n-ish but
modeled linear with a higher constant), the probe is a sorted-run merge
(linear), and a repartition exchange moves every byte through
host<->device once under the page data plane — so network weight is
high, which biases the reorderer toward smaller intermediate results,
exactly the property the mesh data plane wants too.
"""

from __future__ import annotations

import dataclasses

from trino_tpu.sql import plan as P
from trino_tpu.sql.stats import StatsCalculator

# relative per-row weights
_CPU_SCAN = 1.0
_CPU_FILTER = 0.5
_CPU_PROJECT = 0.5
_CPU_PROBE = 2.0
_CPU_BUILD = 4.0       # sort-based lookup build: costlier than probe
_CPU_AGG = 3.0
_CPU_SORT = 6.0
_NET_PER_ROW = 8.0     # exchange: dominant on the host data plane
_MEM_PER_ROW = 1.0


@dataclasses.dataclass(frozen=True)
class PlanCost:
    """PlanCostEstimate analogue; `total` is the scalar the optimizer
    ranks by (CostComparator with uniform weights)."""

    cpu: float
    memory: float
    network: float

    @property
    def total(self) -> float:
        return self.cpu + self.memory + self.network

    def plus(self, other: "PlanCost") -> "PlanCost":
        return PlanCost(
            self.cpu + other.cpu,
            self.memory + other.memory,
            self.network + other.network,
        )


ZERO_COST = PlanCost(0.0, 0.0, 0.0)


class CostCalculator:
    """Bottom-up cumulative cost (CostCalculatorWithEstimatedExchanges:
    local cost of each node + its children's, with exchange cost imputed
    where the fragmenter will cut)."""

    def __init__(self, stats: StatsCalculator):
        self._stats = stats
        self._memo = {}

    def cost(self, node: P.PlanNode) -> PlanCost:
        key = id(node)
        hit = self._memo.get(key)
        if hit is not None and hit[0] is node:
            return hit[1]
        out = self._local(node)
        for c in node.children():
            out = out.plus(self.cost(c))
        self._memo[key] = (node, out)
        return out

    def _rows(self, node: P.PlanNode) -> float:
        return self._stats.stats(node).row_count

    def _local(self, node: P.PlanNode) -> PlanCost:
        if isinstance(node, P.ScanNode):
            return PlanCost(self._rows(node) * _CPU_SCAN, 0.0, 0.0)
        if isinstance(node, P.FilterNode):
            return PlanCost(self._rows(node.child) * _CPU_FILTER, 0.0, 0.0)
        if isinstance(node, P.ProjectNode):
            return PlanCost(self._rows(node.child) * _CPU_PROJECT, 0.0, 0.0)
        if isinstance(node, P.JoinNode):
            probe = self._rows(node.left)
            build = self._rows(node.right)
            out = self._rows(node)
            # imputed exchange cost: the fragmenter will repartition (or
            # broadcast) both join inputs, so every input row crosses
            # the host data plane once — this is what actually biases
            # the reorderer toward small intermediates
            # (CostCalculatorWithEstimatedExchanges discipline)
            return PlanCost(
                probe * _CPU_PROBE + build * _CPU_BUILD + out,
                build * _MEM_PER_ROW,
                (probe + build) * _NET_PER_ROW,
            )
        if isinstance(node, P.AggregateNode):
            rows = self._rows(node.child)
            groups = self._rows(node)
            return PlanCost(rows * _CPU_AGG, groups * _MEM_PER_ROW, 0.0)
        if isinstance(node, (P.SortNode, P.TopNNode)):
            rows = self._rows(node.child)
            mem = rows if isinstance(node, P.SortNode) else float(
                getattr(node, "count", 0)
            )
            return PlanCost(rows * _CPU_SORT, mem * _MEM_PER_ROW, 0.0)
        if isinstance(node, P.WindowNode):
            rows = self._rows(node.child)
            return PlanCost(rows * _CPU_SORT, rows * _MEM_PER_ROW, 0.0)
        if isinstance(node, P.ExchangeNode):
            rows = self._rows(node.child)
            return PlanCost(0.0, 0.0, rows * _NET_PER_ROW)
        return ZERO_COST
