"""Equality inference over join equivalence classes.

Analogue of main/sql/planner/EqualityInference.java:57 reduced to the
channel-reference form this planner's IR guarantees (join keys and
conjunct equalities are always plain InputRefs — the analyzer
materializes anything more complex through Project nodes first).

Equivalence classes union over (a) inner-join equi-key pairs and
(b) ``eq(InputRef, InputRef)`` conjuncts; ``derive`` then rewrites each
single-channel deterministic conjunct onto every other member of its
channel's class, which is what lets a filter on ``o_orderkey`` also
constrain ``l_orderkey`` across the join and reach the other side's
scan via the existing PushFilterIntoJoin/PushFilterThroughProject
rules.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Set, Tuple

from trino_tpu.expr import ir


def expr_channels(e: ir.Expr) -> Set[int]:
    """All InputRef channels referenced by an expression."""
    out: Set[int] = set()

    def walk(x):
        if isinstance(x, ir.InputRef):
            out.add(x.index)
        for c in x.children():
            walk(c)

    walk(e)
    return out


def substitute_channel(e: ir.Expr, src: int, dst: int, dst_type) -> ir.Expr:
    """Copy of `e` with every InputRef(src) replaced by
    InputRef(dst, dst_type)."""
    import dataclasses

    if isinstance(e, ir.InputRef):
        return ir.InputRef(dst, dst_type) if e.index == src else e
    if isinstance(e, ir.Call):
        return ir.Call(
            e.name,
            tuple(substitute_channel(a, src, dst, dst_type) for a in e.args),
            e.type,
        )
    if isinstance(e, ir.Cast):
        return ir.Cast(substitute_channel(e.arg, src, dst, dst_type), e.type)
    if isinstance(e, ir.InList):
        return dataclasses.replace(
            e, value=substitute_channel(e.value, src, dst, dst_type)
        )
    if isinstance(e, ir.Case):
        return ir.Case(
            tuple(substitute_channel(c, src, dst, dst_type) for c in e.conds),
            tuple(substitute_channel(r, src, dst, dst_type) for r in e.results),
            None
            if e.default is None
            else substitute_channel(e.default, src, dst, dst_type),
            e.type,
        )
    return e  # Literal / LambdaVar: no channels


class EqualityInference:
    """Union-find over output channels of one join (or filter scope)."""

    def __init__(self):
        self._parent: Dict[int, int] = {}

    def _find(self, x: int) -> int:
        p = self._parent.setdefault(x, x)
        while p != self._parent[p]:
            self._parent[p] = self._parent[self._parent[p]]
            p = self._parent[p]
        self._parent[x] = p
        return p

    def add_equality(self, a: int, b: int) -> None:
        ra, rb = self._find(a), self._find(b)
        if ra != rb:
            self._parent[ra] = rb

    def add_conjunct_equalities(self, conjuncts: Iterable[ir.Expr]) -> None:
        """Union channels named by ``eq(InputRef, InputRef)`` conjuncts."""
        for c in conjuncts:
            if (
                isinstance(c, ir.Call)
                and c.name == "eq"
                and len(c.args) == 2
                and all(isinstance(a, ir.InputRef) for a in c.args)
            ):
                self.add_equality(c.args[0].index, c.args[1].index)

    def equivalent(self, channel: int) -> List[int]:
        """All channels in `channel`'s class (including itself)."""
        root = self._find(channel)
        return sorted(
            x for x in self._parent if self._find(x) == root
        )

    def derive(
        self,
        conjuncts: Sequence[ir.Expr],
        fields,
        is_deterministic,
    ) -> List[ir.Expr]:
        """New conjuncts obtained by rewriting each single-channel
        deterministic conjunct onto every equivalent channel. Returns
        only conjuncts not already present (structural equality), so a
        caller that adds the result and re-runs gets [] — the fixpoint
        terminates."""
        existing: List[ir.Expr] = list(conjuncts)
        derived: List[ir.Expr] = []
        for c in conjuncts:
            chans = expr_channels(c)
            if len(chans) != 1 or not is_deterministic(c):
                continue
            (x,) = chans
            # skip the equalities themselves: eq(a, a) after rewrite
            # is vacuous and eq(a, b) rewritten is already implied
            if (
                isinstance(c, ir.Call)
                and c.name == "eq"
                and all(isinstance(a, ir.InputRef) for a in c.args)
            ):
                continue
            for y in self.equivalent(x):
                if y == x:
                    continue
                cand = substitute_channel(c, x, y, fields[y].type)
                if cand not in existing and cand not in derived:
                    derived.append(cand)
        return derived
